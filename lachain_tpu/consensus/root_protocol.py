"""RootProtocol: the era driver.

Behavioral parity with the reference
(/root/reference/src/Lachain.Consensus/RootProtocol/RootProtocol.cs):
  * on request: pull a tx proposal from the producer, feed HoneyBadger, and
    request the era nonce coin (ProcessMessage 154-171; coin at 166-168)
  * block nonce derived from the coin signature (316-322; here: the coin's
    CoinId-era parity folded with the era index)
  * on HB result: parse receipts, build + ECDSA-sign the header, broadcast
    SignedHeaderMessage (TrySignHeader 222-262)
  * collect N-F valid matching signed headers -> produce the block
    (CheckSignatures 264-314)

The producer dependency is a seam (core/block_producer.BlockProducer shape),
so this protocol is testable against a fake producer.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..crypto import ecdsa
from . import messages as M
from .protocol import Broadcaster, Protocol

NONCE_AGREEMENT = -1  # dedicated coin slot for the block nonce


class RootProtocol(Protocol):
    def __init__(
        self,
        pid: M.RootProtocolId,
        broadcaster: Broadcaster,
        producer,  # BlockProducer seam
        ecdsa_priv: bytes,
        ecdsa_pubs: List[bytes],
    ):
        super().__init__(pid, broadcaster)
        self._producer = producer
        self._priv = ecdsa_priv
        self._pubs = ecdsa_pubs
        self._hb_result: Optional[dict] = None
        self._nonce: Optional[int] = None
        self._header = None
        self._txs = None
        self._signatures: Dict[int, bytes] = {}
        self._early_headers: Dict[int, M.SignedHeaderMessage] = {}
        self._produced = False

    # -- era start -------------------------------------------------------------
    def handle_input(self, value) -> None:
        from ..core.block_producer import encode_tx_batch

        proposal = self._producer.get_transactions_to_propose()
        self.request(
            M.HoneyBadgerId(era=self.id.era), encode_tx_batch(proposal)
        )
        self.request(
            M.CoinId(era=self.id.era, agreement=NONCE_AGREEMENT, epoch=0), None
        )

    # -- children ---------------------------------------------------------------
    def handle_child_result(self, child_id, value) -> None:
        if isinstance(child_id, M.HoneyBadgerId):
            if self._hb_result is None:
                self._hb_result = value
        elif isinstance(child_id, M.CoinId):
            if self._nonce is None:
                # fold coin into a u64 nonce (reference XOR-folds the combined
                # signature, RootProtocol.cs:316-322)
                self._nonce = (self.id.era << 1) | (1 if value else 0)
        self._try_sign_header()

    # -- header signing ----------------------------------------------------------
    def _try_sign_header(self) -> None:
        if self._header is not None or self._hb_result is None or self._nonce is None:
            return
        from ..core.block_producer import decode_tx_batch

        seen: Set[bytes] = set()
        txs = []
        for slot in sorted(self._hb_result):
            try:
                batch = decode_tx_batch(self._hb_result[slot])
            except (ValueError, AssertionError):
                continue  # malformed proposal: skip the slot
            for stx in batch:
                h = stx.hash()
                if h not in seen:
                    seen.add(h)
                    txs.append(stx)
        self._txs = txs
        # tx lifecycle: consensus agreed on the era's tx union (the decide
        # point — every honest node derives the same set here)
        from ..utils import txtrace

        txtrace.stamp_many(
            (stx.hash() for stx in txs), "decide", era=self.id.era
        )
        self._header = self._producer.create_header(
            self.id.era, txs, self._nonce
        )
        sig = ecdsa.sign_hash(self._priv, self._header.hash())
        self.broadcaster.broadcast(
            M.SignedHeaderMessage(
                root=self.id,
                header_bytes=self._header.encode(),
                signature=sig,
            )
        )
        self._signatures[self.me] = sig
        # headers that arrived before ours was built
        early, self._early_headers = self._early_headers, {}
        for sender, msg in early.items():
            self._on_signed_header(sender, msg)
        self._try_produce()

    # -- externals ----------------------------------------------------------------
    def handle_external(self, sender: int, payload) -> None:
        if not isinstance(payload, M.SignedHeaderMessage):
            raise TypeError(f"unexpected payload {type(payload)}")
        if self._header is None:
            # one stashed header per sender: a byzantine flooder can only
            # displace its own earlier message, never an honest validator's
            self._early_headers[sender] = payload
            return
        self._on_signed_header(sender, payload)

    def _on_signed_header(self, sender: int, msg: M.SignedHeaderMessage) -> None:
        if sender in self._signatures:
            return
        if msg.header_bytes != self._header.encode():
            return  # disagreeing header (reference logs mismatch, 264-314)
        if not ecdsa.verify_hash(
            self._pubs[sender], self._header.hash(), msg.signature
        ):
            ev = getattr(self.broadcaster, "evidence", None)
            if ev is not None:
                ev.record_invalid_share(self.id.era, sender, "hdr", ())
            return
        self._signatures[sender] = msg.signature
        self._try_produce()

    # -- production -----------------------------------------------------------------
    def _try_produce(self) -> None:
        if self._produced or self._header is None:
            return
        if len(self._signatures) < self.n - self.f:
            return
        from ..core.types import MultiSig

        multisig = MultiSig(
            signatures=tuple(sorted(self._signatures.items()))
        )
        block = self._producer.produce_block(self._header, self._txs, multisig)
        self._produced = True
        self.emit_result(block)
