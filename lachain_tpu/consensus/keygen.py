"""Trustless distributed key generation (on-chain Joint-Feldman-style DKG).

Functional parity with the reference's DKG
(/root/reference/src/Lachain.Consensus/ThresholdKeygen/):
  * TrustlessKeygen       (TrustlessKeygen.cs:36-261) — commit / send-value /
    confirm lifecycle with full-state serialization for crash-resume
  * BiVarSymmetricPolynomial (Data/BiVarSymmetricPolynomial.cs:9-58)
  * Commitment            (Data/Commitment.cs:9-103)
  * State                 (Data/State.cs:10-103)
  * ThresholdKeyring      (Data/ThresholdKeyring.cs)

Protocol (messages ride on-chain as governance transactions, so every node
processes them in the same total order — that block ordering is what makes
`finished` deterministic across nodes):

  1. Each dealer d samples a random symmetric bivariate polynomial
     F_d(x, y) of degree f and broadcasts COMMIT: g1^{coeffs} plus, for each
     player i, ECIES-encrypted row F_d(i+1, ·).
  2. On COMMIT from d, player i decrypts row_i, checks it against the
     commitment, and broadcasts VALUE: for each player j, ECIES-encrypted
     F_d(i+1, j+1).
  3. On VALUE from sender s for dealer d, player i decrypts F_d(s+1, i+1)
     and checks it against d's commitment. Dealer d is `finished` once
     > 2f senders acked. Keygen is finished once > f dealers finished.
  4. x_i = sum over the first f+1 finished dealers of F_d(0, i+1)
     (interpolated from the acked values); the shared TPKE/TS secret is
     P(0) with P(y) = sum_d F_d(0, y). Nodes broadcast CONFIRM with the
     derived public keyring; at N-f matching confirms the keys go live.

The heavy step — commitment row evaluation, O(N * f^2) G1 scalar muls per
keygen — is expressed as per-row G1 MSMs over the shared backend, so a
cycle-boundary keygen burst rides the same batched TPU data plane as the
per-era share verification (SURVEY.md §2a "centerpiece").
"""
from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ..crypto import bls12381 as bls
from ..crypto import ecdsa
from ..crypto import threshold_sig as ts
from ..crypto import tpke
from ..crypto.hashes import keccak256
from ..crypto.provider import get_backend
from ..utils.serialization import Reader, write_bytes, write_u32, write_u64
from .keys import PrivateConsensusKeys, PublicConsensusKeys


def _tri_index(i: int, j: int) -> int:
    """Index into the packed triangular coefficient array (symmetric poly)."""
    if i > j:
        i, j = j, i
    return i * (i + 1) // 2 + j


class BiVarSymmetricPolynomial:
    """Random symmetric bivariate polynomial over Fr, degree f in each var
    (reference: Data/BiVarSymmetricPolynomial.cs:9-58)."""

    def __init__(self, degree: int, coeffs: Sequence[int]):
        if len(coeffs) != (degree + 1) * (degree + 2) // 2:
            raise ValueError("wrong number of coefficients")
        self.degree = degree
        self.coeffs = [c % bls.R for c in coeffs]

    @classmethod
    def random(cls, degree: int, rng=secrets) -> "BiVarSymmetricPolynomial":
        count = (degree + 1) * (degree + 2) // 2
        return cls(degree, [rng.randbelow(bls.R) for _ in range(count)])

    def commit(self) -> "Commitment":
        backend = get_backend()
        return Commitment(
            [backend.g1_mul(bls.G1_GEN, c) for c in self.coeffs]
        )

    def evaluate_row(self, x: int) -> List[int]:
        """Row polynomial F(x, ·) as f+1 Fr coefficients
        (reference: BiVarSymmetricPolynomial.Evaluate)."""
        row = [0] * (self.degree + 1)
        for i in range(self.degree + 1):
            x_pow = 1
            for j in range(self.degree + 1):
                row[i] = (row[i] + self.coeffs[_tri_index(i, j)] * x_pow) % bls.R
                x_pow = x_pow * x % bls.R
        return row


class Commitment:
    """G1 commitment to a symmetric bivariate polynomial
    (reference: Data/Commitment.cs:9-103)."""

    def __init__(self, coeffs: Sequence[tuple]):
        self.coeffs = list(coeffs)
        degree = 0
        while (degree + 1) * (degree + 2) // 2 < len(self.coeffs):
            degree += 1
        if (degree + 1) * (degree + 2) // 2 != len(self.coeffs):
            raise ValueError("invalid commitment coefficient count")
        self.degree = degree

    def evaluate_row(self, x: int) -> List[tuple]:
        """Committed row: [sum_j C[i,j] * x^j for i] — one G1 MSM per row
        coefficient (reference: Commitment.Evaluate(x))."""
        backend = get_backend()
        powers = [pow(x, j, bls.R) for j in range(self.degree + 1)]
        return [
            backend.g1_msm(
                [self.coeffs[_tri_index(i, j)] for j in range(self.degree + 1)],
                powers,
            )
            for i in range(self.degree + 1)
        ]

    def evaluate(self, x: int, y: int) -> tuple:
        """Committed point g1^{F(x,y)} as one (f+1)^2 MSM
        (reference: Commitment.Evaluate(x, y))."""
        backend = get_backend()
        pts = []
        scalars = []
        for i in range(self.degree + 1):
            for j in range(self.degree + 1):
                pts.append(self.coeffs[_tri_index(i, j)])
                scalars.append(
                    pow(x, i, bls.R) * pow(y, j, bls.R) % bls.R
                )
        return backend.g1_msm(pts, scalars)

    def to_bytes(self) -> bytes:
        return b"".join(bls.g1_to_bytes(c) for c in self.coeffs)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Commitment":
        if len(data) % bls.G1_BYTES != 0:
            raise ValueError("commitment length not a multiple of G1 size")
        backend = get_backend()
        return cls(
            [
                backend.g1_deserialize(data[o : o + bls.G1_BYTES])
                for o in range(0, len(data), bls.G1_BYTES)
            ]
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Commitment)
            and len(self.coeffs) == len(other.coeffs)
            and all(
                bls.g1_eq(a, b) for a, b in zip(self.coeffs, other.coeffs)
            )
        )


@dataclass
class CommitMessage:
    """Dealer broadcast: commitment + per-player encrypted rows
    (reference: CommitMessage in TrustlessKeygen.cs:63-76)."""

    commitment: Commitment
    encrypted_rows: List[bytes]

    def to_bytes(self) -> bytes:
        out = write_bytes(self.commitment.to_bytes())
        out += write_u32(len(self.encrypted_rows))
        for row in self.encrypted_rows:
            out += write_bytes(row)
        return out

    @classmethod
    def from_bytes(cls, data: bytes) -> "CommitMessage":
        r = Reader(data)
        commitment = Commitment.from_bytes(r.bytes_())
        rows = [r.bytes_() for _ in range(r.u32())]
        r.assert_eof()
        return cls(commitment, rows)


@dataclass
class ValueMessage:
    """Player's response to a dealer's commit: encrypted row evaluations
    (reference: ValueMessage in TrustlessKeygen.cs:101-109)."""

    proposer: int
    encrypted_values: List[bytes]

    def to_bytes(self) -> bytes:
        out = write_u32(self.proposer)
        out += write_u32(len(self.encrypted_values))
        for v in self.encrypted_values:
            out += write_bytes(v)
        return out

    @classmethod
    def from_bytes(cls, data: bytes) -> "ValueMessage":
        r = Reader(data)
        proposer = r.u32()
        values = [r.bytes_() for _ in range(r.u32())]
        r.assert_eof()
        return cls(proposer, values)


class KeygenState:
    """Per-dealer progress (reference: Data/State.cs:10-103)."""

    def __init__(self, n: int):
        self.commitment: Optional[Commitment] = None
        self.values: List[int] = [0] * n
        # acks follow the shared on-chain message order (deterministic across
        # nodes); valid[] is this node's local check that the decrypted value
        # matched the commitment — only valid values enter interpolation
        self.acks: List[bool] = [False] * n
        self.valid: List[bool] = [False] * n

    def value_count(self) -> int:
        return sum(self.acks)

    def interpolate_values(self) -> int:
        """F_d(0, my_idx+1): Lagrange-interpolate the first degree+1 VALID
        sender values at 0 (reference: State.InterpolateValues). Any
        degree+1 commitment-checked points of the degree-f row polynomial
        interpolate to the same share, so node-local validity cannot skew
        the result; with > 2f acks at least f+1 are from honest senders and
        decrypt validly."""
        if self.commitment is None:
            raise ValueError("cannot interpolate without commitment")
        need = self.commitment.degree + 1
        xs = [i + 1 for i, v in enumerate(self.valid) if v][:need]
        ys = [self.values[x - 1] for x in xs]
        if len(xs) != need:
            raise ValueError("not enough values to interpolate")
        return bls.fr_interpolate(xs, ys, at=0)

    def to_bytes(self) -> bytes:
        commitment = self.commitment.to_bytes() if self.commitment else b""
        out = write_bytes(commitment)
        out += write_u32(len(self.acks))
        out += b"".join(bls.fr_to_bytes(v) for v in self.values)
        out += bytes(1 if a else 0 for a in self.acks)
        out += bytes(1 if v else 0 for v in self.valid)
        return out

    @classmethod
    def from_bytes(cls, data: bytes) -> "KeygenState":
        r = Reader(data)
        commitment_bytes = r.bytes_()
        n = r.u32()
        state = cls(n)
        if commitment_bytes:
            state.commitment = Commitment.from_bytes(commitment_bytes)
        state.values = [
            bls.fr_from_bytes(r.raw(bls.FR_BYTES)) for _ in range(n)
        ]
        state.acks = [b != 0 for b in r.raw(n)]
        state.valid = [b != 0 for b in r.raw(n)]
        r.assert_eof()
        return state

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, KeygenState)
            and self.commitment == other.commitment
            and self.values == other.values
            and self.acks == other.acks
            and self.valid == other.valid
        )


@dataclass
class ThresholdKeyring:
    """Output of a successful keygen (reference: Data/ThresholdKeyring.cs)."""

    tpke_priv: tpke.TpkePrivateKey
    tpke_pub: tpke.TpkePublicKey
    tpke_verification_keys: List[tpke.TpkeVerificationKey]
    ts_share: ts.TsPrivateKeyShare
    ts_key_set: ts.TsPublicKeySet

    @property
    def public_key_hash(self) -> bytes:
        """keccak(tpke_pub || ts_key_set) — the confirmation vote payload
        (reference: TrustlessKeygen.HandleConfirm keyringHash)."""
        return keccak256(self.tpke_pub.to_bytes() + self.ts_key_set.to_bytes())

    def public_keys(self, f: int, ecdsa_pub_keys: List[bytes]) -> PublicConsensusKeys:
        return PublicConsensusKeys(
            n=self.ts_key_set.n,
            f=f,
            tpke_pub=self.tpke_pub,
            tpke_verification_keys=self.tpke_verification_keys,
            ts_keys=self.ts_key_set,
            ecdsa_pub_keys=ecdsa_pub_keys,
        )

    def private_keys(self, ecdsa_priv: Optional[bytes] = None) -> PrivateConsensusKeys:
        return PrivateConsensusKeys(
            tpke_priv=self.tpke_priv,
            ts_share=self.ts_share,
            ecdsa_priv=ecdsa_priv,
        )


class TrustlessKeygen:
    """DKG driver for one node (reference: TrustlessKeygen.cs:36-261).

    Messages are produced/consumed by the caller (KeyGenManager routes them
    through governance transactions); this class is pure protocol state.
    """

    def __init__(
        self,
        ecdsa_priv: bytes,
        ecdsa_pub_keys: Sequence[bytes],
        f: int,
        cycle: int,
        rng=secrets,
    ):
        self._priv = ecdsa_priv
        self.ecdsa_pub_keys = list(ecdsa_pub_keys)
        self.n = len(self.ecdsa_pub_keys)
        self.f = f
        self.cycle = cycle
        self._rng = rng
        my_pub = ecdsa.public_key_bytes(ecdsa_priv)
        self.my_idx = (
            self.ecdsa_pub_keys.index(my_pub)
            if my_pub in self.ecdsa_pub_keys
            else -1
        )
        self.states = [KeygenState(self.n) for _ in range(self.n)]
        self.finished_dealers: List[int] = []
        self.confirmations: Dict[bytes, int] = {}
        self.confirm_sent = False

    # ----- protocol steps -------------------------------------------------

    def start_keygen(self) -> CommitMessage:
        """Dealer step: sample F(x,y), commit, encrypt rows
        (reference: TrustlessKeygen.StartKeygen:63-76)."""
        poly = BiVarSymmetricPolynomial.random(self.f, self._rng)
        commitment = poly.commit()
        rows = []
        for i in range(self.n):
            row = poly.evaluate_row(i + 1)
            serialized = b"".join(bls.fr_to_bytes(c) for c in row)
            rows.append(
                ecdsa.ecies_encrypt(self.ecdsa_pub_keys[i], serialized)
            )
        return CommitMessage(commitment, rows)

    def sender_by_public_key(self, pub: bytes) -> int:
        try:
            return self.ecdsa_pub_keys.index(pub)
        except ValueError:
            return -1

    def handle_commit(self, sender: int, msg: CommitMessage) -> ValueMessage:
        """Check my row against the commitment; respond with per-player row
        evaluations (reference: TrustlessKeygen.HandleCommit:90-109).
        Raises ValueError on any mismatch (caller treats dealer as faulty)."""
        if not 0 <= sender < self.n:
            raise ValueError(f"commit from unknown sender {sender}")
        if self.my_idx < 0:
            raise ValueError("this node is not a keygen participant")
        if len(msg.encrypted_rows) != self.n:
            raise ValueError("bad encrypted row count")
        if msg.commitment.degree != self.f:
            raise ValueError("commitment degree != f")
        if self.states[sender].commitment is not None:
            raise ValueError(f"double commit from sender {sender}")
        self.states[sender].commitment = msg.commitment
        committed_row = msg.commitment.evaluate_row(self.my_idx + 1)
        try:
            raw = ecdsa.ecies_decrypt(
                self._priv, msg.encrypted_rows[self.my_idx]
            )
        except Exception as e:
            raise ValueError(f"undecryptable row: {e}") from e
        if len(raw) != (self.f + 1) * bls.FR_BYTES:
            raise ValueError("bad row length")
        row = [
            bls.fr_from_bytes(raw[o : o + bls.FR_BYTES])
            for o in range(0, len(raw), bls.FR_BYTES)
        ]
        backend = get_backend()
        for coeff, committed in zip(row, committed_row):
            if not bls.g1_eq(backend.g1_mul(bls.G1_GEN, coeff), committed):
                raise ValueError("commitment does not match row")
        return ValueMessage(
            proposer=sender,
            encrypted_values=[
                ecdsa.ecies_encrypt(
                    self.ecdsa_pub_keys[i],
                    bls.fr_to_bytes(bls.fr_eval_poly(row, i + 1)),
                )
                for i in range(self.n)
            ],
        )

    def handle_send_value(self, sender: int, msg: ValueMessage) -> bool:
        """Check F_d(sender+1, me+1) against d's commitment; returns True
        exactly once, when this node first sees the keygen finished and
        should broadcast its confirmation
        (reference: TrustlessKeygen.HandleSendValue:111-135)."""
        if not 0 <= msg.proposer < self.n:
            raise ValueError(f"value for unknown dealer {msg.proposer}")
        if not 0 <= sender < self.n:
            raise ValueError(f"value from unknown sender {sender}")
        if self.my_idx < 0:
            raise ValueError("this node is not a keygen participant")
        state = self.states[msg.proposer]
        if state.acks[sender]:
            raise ValueError("already handled this value")
        if state.commitment is None:
            raise ValueError("value before commitment")
        if len(msg.encrypted_values) != self.n:
            raise ValueError("bad encrypted value count")
        # the ack is recorded on receipt, after the structural checks every
        # node evaluates identically on the shared on-chain order — so the
        # > 2f quorum (and finished_dealers membership) is deterministic
        # across nodes (reference TrustlessKeygen.cs:111-118 acks the same
        # way). Whether MY ciphertext decrypted to a commitment-consistent
        # value is node-local and only gates interpolation (valid[]), so a
        # byzantine sender can neither poison the Lagrange sum nor split the
        # quorum.
        state.acks[sender] = True
        try:
            value = bls.fr_from_bytes(
                ecdsa.ecies_decrypt(
                    self._priv, msg.encrypted_values[self.my_idx]
                )
            )
            expected = state.commitment.evaluate(self.my_idx + 1, sender + 1)
            if bls.g1_eq(get_backend().g1_mul(bls.G1_GEN, value), expected):
                state.valid[sender] = True
                state.values[sender] = value
        except Exception:
            pass  # structurally fine but undecryptable for me: ack w/o valid
        if (
            state.value_count() > 2 * self.f
            and msg.proposer not in self.finished_dealers
        ):
            self.finished_dealers.append(msg.proposer)
        if self.confirm_sent:
            return False
        if not self.finished():
            return False
        self.confirm_sent = True
        return True

    def handle_confirm(self, keyring_hash: bytes) -> bool:
        """Count confirmation votes per keyring hash; True exactly when the
        N-f'th matching vote arrives
        (reference: TrustlessKeygen.HandleConfirm:138-144)."""
        self.confirmations[keyring_hash] = (
            self.confirmations.get(keyring_hash, 0) + 1
        )
        return self.confirmations[keyring_hash] == self.n - self.f

    def finished(self) -> bool:
        """> f dealers have > 2f acks (reference: Finished:146-149)."""
        return (
            sum(1 for s in self.states if s.value_count() > 2 * self.f)
            > self.f
        )

    def try_get_keys(self) -> Optional[ThresholdKeyring]:
        """Derive the keyring from the first f+1 finished dealers
        (reference: TryGetKeys:151-181)."""
        if not self.finished():
            return None
        backend = get_backend()
        # pub-key polynomial = sum of dealers' committed rows at x=0
        pub_key_poly: List[Optional[tuple]] = [None] * (self.f + 1)
        secret = 0
        for dealer in self.finished_dealers[: self.f + 1]:
            state = self.states[dealer]
            if state.value_count() <= 2 * self.f:
                raise RuntimeError("finished dealer without quorum")
            row_zero = state.commitment.evaluate_row(0)
            for i, pt in enumerate(row_zero):
                pub_key_poly[i] = (
                    pt if pub_key_poly[i] is None
                    else bls.g1_add(pub_key_poly[i], pt)
                )
            secret = (secret + state.interpolate_values()) % bls.R
        # evaluate g1^{P(i)} for i in 0..n via Horner in the exponent
        pub_keys = []
        for i in range(self.n + 1):
            powers = [pow(i, j, bls.R) for j in range(self.f + 1)]
            pub_keys.append(backend.g1_msm(pub_key_poly, powers))
        return ThresholdKeyring(
            tpke_priv=tpke.TpkePrivateKey(secret, self.my_idx),
            tpke_pub=tpke.TpkePublicKey(pub_keys[0], t=self.f),
            tpke_verification_keys=[
                tpke.TpkeVerificationKey(y) for y in pub_keys[1:]
            ],
            ts_share=ts.TsPrivateKeyShare(secret, self.my_idx),
            ts_key_set=ts.TsPublicKeySet(
                [ts.TsPublicKey(y) for y in pub_keys[1:]], t=self.f
            ),
        )

    # ----- crash-resume serialization ------------------------------------

    def to_bytes(self) -> bytes:
        """Full-state snapshot, persisted after every step
        (reference: TrustlessKeygen.ToBytes:195-226)."""
        out = write_u32(self.n) + write_u32(self.f) + write_u64(self.cycle)
        for pub in self.ecdsa_pub_keys:
            out += write_bytes(pub)
        for state in self.states:
            out += write_bytes(state.to_bytes())
        out += write_u32(len(self.finished_dealers))
        for d in self.finished_dealers:
            out += write_u32(d)
        out += write_u32(len(self.confirmations))
        for h, count in self.confirmations.items():
            out += write_bytes(h) + write_u32(count)
        out += bytes([1 if self.confirm_sent else 0])
        return out

    @classmethod
    def from_bytes(cls, data: bytes, ecdsa_priv: bytes) -> "TrustlessKeygen":
        r = Reader(data)
        n = r.u32()
        f = r.u32()
        cycle = r.u64()
        pub_keys = [r.bytes_() for _ in range(n)]
        keygen = cls(ecdsa_priv, pub_keys, f, cycle)
        keygen.states = [
            KeygenState.from_bytes(r.bytes_()) for _ in range(n)
        ]
        keygen.finished_dealers = [r.u32() for _ in range(r.u32())]
        keygen.confirmations = {
            r.bytes_(): r.u32() for _ in range(r.u32())
        }
        keygen.confirm_sent = r.raw(1)[0] != 0
        r.assert_eof()
        return keygen

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, TrustlessKeygen)
            and self.ecdsa_pub_keys == other.ecdsa_pub_keys
            and self.my_idx == other.my_idx
            and self.states == other.states
            and self.finished_dealers == other.finished_dealers
            and self.confirmations == other.confirmations
            and self.confirm_sent == other.confirm_sent
        )
