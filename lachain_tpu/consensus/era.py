"""Era router: creates protocol instances on demand and routes envelopes.

Parity with the reference's EraBroadcaster
(/root/reference/src/Lachain.Core/Consensus/EraBroadcaster.cs):
  * one protocol instance per id, created on first reference (344-410)
  * external payload -> protocol id routing (135-194)
  * id validation / spam defense: era must match, indices in range (418-529)
  * terminated protocols drop further traffic
  * Request/Result plumbing between parents and children (229-301)

This object is synchronous and deterministic: the delivery layer (simulator
or network runtime) decides WHEN dispatch() runs; the router only decides
WHERE an envelope goes. Outbound messages are emitted through a transport
callback, so the same router serves the in-process simulator and the real
node.
"""
from __future__ import annotations

import logging
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import messages as M
from .journal import send_slot
from .binary_agreement import BinaryAgreement
from .binary_broadcast import BinaryBroadcast
from .common_coin import CommonCoin
from .common_subset import CommonSubset
from .honey_badger import HoneyBadger
from .keys import PrivateConsensusKeys, PublicConsensusKeys
from .protocol import Broadcaster, Protocol
from .reliable_broadcast import ReliableBroadcast

logger = logging.getLogger("lachain.consensus.era")


class EraRouter(Broadcaster):
    def __init__(
        self,
        era: int,
        my_id: int,
        public_keys: PublicConsensusKeys,
        private_keys: PrivateConsensusKeys,
        send: Callable[[Optional[int], Any], None],
        extra_factories: Optional[Dict[type, Callable]] = None,
        journal=None,
        evidence=None,
    ):
        """`send(target, payload)`: target None = broadcast to all validators
        (including self-delivery handled by the transport). `journal` is an
        optional ConsensusJournal: every outbound payload is durably
        recorded BEFORE transmission, and re-derived values for a slot
        already sent pre-crash are substituted with the recorded bytes
        (crash-recovery no-self-equivocation, journal.py docstring)."""
        self.era = era
        self._my_id = my_id
        self.public_keys = public_keys
        self.private_keys = private_keys
        self._send = send
        # era-scoped RS flush batcher (rbc_batcher.py), wired on by the
        # network when batching is enabled; None = inline codec calls
        self.rbc_batcher = None
        self._protocols: Dict[Any, Protocol] = {}
        self._extra_factories = extra_factories or {}
        self.terminated = False
        # future-era messages buffered until the era advances (reference:
        # postponed-message window, ConsensusManager.cs:132-155); bounded PER
        # SENDER so one byzantine validator cannot starve honest traffic
        self._postponed: list = []
        self._postponed_per_sender: Dict[int, int] = {}
        self._postponed_sender_cap = 256
        # Byzantine evidence store (evidence.py): detected equivocations and
        # invalid shares, deduped + queryable (la_getEvidence). Injectable so
        # the real node can persist it on its KV.
        if evidence is None:
            from .evidence import EvidenceStore

            evidence = EvidenceStore()
        self.evidence = evidence
        # per-(sender, slot) first-seen latch: the receive-side dual of the
        # _sent_slots send latch. The FIRST payload a sender ships for a
        # decision slot is pinned; a LATER DIFFERING payload for the same
        # slot is equivocation — recorded as evidence and dropped, so the
        # first-seen value keeps driving the protocol deterministically.
        # Bounded per sender so a spammer inventing fresh slots degrades
        # itself (shed + counted), not this node. The native engine applies
        # the IDENTICAL rule to engine-delivered share traffic
        # (consensus_rt.cpp opq_latch), reporting conflicts via XO_EVIDENCE.
        self._first_seen: Dict[tuple, Any] = {}
        self._first_seen_per_sender: Dict[int, int] = {}
        self.first_seen_sender_cap = 2048
        # retransmission outbox: every payload this router sent, per era
        # (target None = broadcast), bounded FIFO. Consensus protocols never
        # retransmit on their own, so a message lost in transit is
        # unrecoverable for the slot UNLESS a peer can re-request it — a
        # message_request for an era is answered by replaying from here.
        # Finished eras are pruned with the protocol GC in advance_era.
        self._outbox: Dict[int, deque] = {}
        self.outbox_cap = 4096  # entries per era; oldest evicted first
        # durable-send latches: (era, slot) -> recorded wire bytes. A slot
        # present here was already sent (this run or pre-crash via
        # rearm_sent); any later send for it re-uses the recorded bytes so
        # a restarted node cannot contradict its pre-crash self. Pruned
        # with the protocol GC.
        self._journal = journal
        self._sent_slots: Dict[Tuple[int, tuple], bytes] = {}
        # pipelined-era window: `era` is the FRONT (newest open) era and
        # `window_floor` the oldest era still in flight (uncommitted).
        # Sequential operation keeps the two equal (advance_era moves both);
        # the pipelined scheduler moves them independently via
        # open_era / commit_era_gc. `pipeline_window` is the configured
        # lookahead; it widens the GC retention so an era's journal and
        # outbox survive until every era that overlapped it has committed.
        self.pipeline_window = 0
        self.window_floor = era

    # -- Broadcaster interface ----------------------------------------------
    @property
    def my_id(self) -> int:
        return self._my_id

    @property
    def n_validators(self) -> int:
        return self.public_keys.n

    @property
    def f(self) -> int:
        return self.public_keys.f

    def broadcast(self, payload) -> None:
        payload = self._durable_send(None, payload)
        self._record_outbox(None, payload)
        self._send(None, payload)

    def send_to(self, validator: int, payload) -> None:
        payload = self._durable_send(validator, payload)
        self._record_outbox(validator, payload)
        self._send(validator, payload)

    # -- durable sends (crash-recovery journal) -------------------------------
    def _payload_era(self, payload) -> int:
        try:
            return getattr(M.payload_protocol_id(payload), "era", self.era)
        except TypeError:
            return self.era

    def _durable_send(self, target: Optional[int], payload):
        """Persist-before-transmit. Substitution happens BEFORE the outbox
        record and before the transport's self-delivery, so the node's own
        protocol state is rebuilt from exactly the bytes its peers saw
        pre-crash — not from a freshly re-derived value."""
        if self._journal is None:
            return payload
        from ..network import wire

        slot = send_slot(payload)
        era = self._payload_era(payload)
        if slot is not None:
            recorded = self._sent_slots.get((era, slot))
            if recorded is not None:
                # slot already durably sent: replay the recorded bytes
                # byte-identically, never the re-derived value
                from ..utils import metrics

                metrics.inc("consensus_journal_replayed_sends_total")
                return wire.decode_payload(recorded)
        data = wire.encode_payload(payload)
        self._journal.record(era, target, data)
        if slot is not None:
            self._sent_slots[(era, slot)] = data
        return payload

    def rearm_sent(self, era: int, target: Optional[int], data: bytes) -> None:
        """Recovery path: re-arm the sent-latch and re-seed the outbox from
        one journaled record (already durable — NOT re-journaled, NOT
        re-transmitted here; retransmission is peer-pulled via
        message_request / stall escalation)."""
        from ..network import wire

        try:
            payload = wire.decode_payload(data)
        except Exception:
            logger.warning("undecodable journal entry for era %d", era)
            return
        slot = send_slot(payload)
        if slot is not None and (era, slot) not in self._sent_slots:
            self._sent_slots[(era, slot)] = data
        q = self._outbox.get(era)
        if q is None:
            q = self._outbox[era] = deque()
        if len(q) < self.outbox_cap:
            q.append((target, payload))

    # -- retransmission outbox ------------------------------------------------
    def _record_outbox(self, target: Optional[int], payload) -> None:
        # key by the PAYLOAD's era, not the router's front era: with a
        # pipeline window open, a tail era's header/coin sends happen while
        # self.era already points one or more eras ahead, and a
        # message_request for the tail era must find them
        era = self._payload_era(payload)
        q = self._outbox.get(era)
        if q is None:
            q = self._outbox[era] = deque()
        if len(q) >= self.outbox_cap:
            q.popleft()
            from ..utils import metrics

            metrics.inc("consensus_outbox_evicted_total")
        q.append((target, payload))

    def outbox_payloads(self, era: int, requester: int) -> List[Any]:
        """Everything this router sent in `era` that `requester` should
        have seen: broadcasts plus messages addressed to it directly."""
        return [
            payload
            for target, payload in self._outbox.get(era, ())
            if target is None or target == requester
        ]

    def replay_outbox(
        self, era: int, requester: int, limit: Optional[int] = None
    ) -> int:
        """Re-send `era`'s outbox to `requester` (message_request service).
        Goes straight through the transport — NOT via send_to — so replays
        are never re-recorded (a replay of a replay would grow the outbox
        unboundedly). `limit` caps the batch (in send order, so protocol
        progression replays front-first); the node scales it with observed
        RTT — a distant requester waits longer between requests, so each
        round must carry more."""
        payloads = self.outbox_payloads(era, requester)
        if limit is not None:
            payloads = payloads[:limit]
        for payload in payloads:
            self._send(requester, payload)
        if payloads:
            from ..utils import metrics

            metrics.inc("consensus_outbox_replayed_total", len(payloads))
        return len(payloads)

    def internal_request(self, req: M.Request) -> None:
        proto = self._ensure_protocol(req.to_id)
        if proto is not None:
            proto.receive(req)

    def internal_response(self, res: M.Result) -> None:
        if res.to_id is None:
            return  # top-level protocol: result observed via .result
        proto = self._protocols.get(res.to_id)
        if proto is not None:
            proto.receive(res)

    # -- dispatch ------------------------------------------------------------
    def dispatch_external(self, sender: int, payload) -> None:
        """Route a validator's payload to its protocol (creating it)."""
        if self.terminated:
            return
        try:
            pid = M.payload_protocol_id(payload)
        except TypeError:
            logger.warning("unroutable payload from %d", sender)
            return
        msg_era = getattr(pid, "era", None)
        if msg_era is not None and not (
            self.window_floor <= msg_era <= self.era
        ):
            if msg_era > self.era:
                # a faster validator is already in a future era: buffer until
                # we advance (reference postponed-message window)
                cnt = self._postponed_per_sender.get(sender, 0)
                if cnt < self._postponed_sender_cap:
                    self._postponed_per_sender[sender] = cnt + 1
                    self._postponed.append((sender, payload))
                else:
                    # per-sender buffer full: the spammer's traffic sheds,
                    # honest senders' buffers are unaffected
                    from ..utils import metrics

                    metrics.inc(
                        "consensus_msgs_shed_total",
                        labels={"reason": "postponed_cap"},
                    )
            else:
                logger.debug("stale era message %s from %d", pid, sender)
            return
        if not self._validate_id(pid):
            logger.warning("invalid protocol id %s from %d", pid, sender)
            return
        if not self._latch_first_seen(sender, payload):
            return  # equivocation (recorded) or latch-budget shed
        proto = self._ensure_protocol(pid)
        if proto is not None:
            proto.receive(M.External(sender=sender, payload=payload))

    def _latch_first_seen(self, sender: int, payload) -> bool:
        """Receive-side equivocation latch. Returns False when the payload
        must be dropped: either it CONFLICTS with the sender's first-seen
        payload for the slot (evidence recorded), or the sender exhausted
        its latch budget (shed, counted). Byte-identical duplicates pass
        through — the protocols' own dedup handles them, exactly as the
        native engine passes equal-bytes duplicates."""
        slot = send_slot(payload)
        if slot is None:
            return True
        key = (sender, slot)
        prev = self._first_seen.get(key)
        if prev is None:
            cnt = self._first_seen_per_sender.get(sender, 0)
            if cnt >= self.first_seen_sender_cap:
                from ..utils import metrics

                metrics.inc(
                    "consensus_msgs_shed_total",
                    labels={"reason": "latch_cap"},
                )
                return False
            self._first_seen_per_sender[sender] = cnt + 1
            self._first_seen[key] = payload
            return True
        if prev == payload:
            return True
        from .evidence import describe_slot

        proto, index = describe_slot(slot)
        if self.evidence.record_equivocation(
            self._payload_era(payload), sender, proto, index
        ):
            logger.warning(
                "equivocation from %d in slot %s%s: conflicting payloads",
                sender, proto, index,
            )
        return False

    def advance_era(self, new_era: int) -> None:
        """Move FORWARD to a new era and replay buffered future-era messages
        (reference: ConsensusManager.FinishEra -> Dispatch of postponed).
        Eras never regress: a stale/duplicate call is a no-op."""
        if new_era <= self.era:
            return
        old_era = self.era
        self.era = new_era
        self.window_floor = new_era
        # drop protocol instances from finished eras (reference FinishEra
        # clears its registry): laggard sub-protocols an era's outcome never
        # needed would otherwise accumulate for the node's lifetime — real
        # memory growth at N=64 scale and a stream of spurious watchdog
        # stall reports. The LAST ACTIVE era is kept so late result_of
        # queries (block production racing the advance, multi-era observer
        # jumps included) still resolve.
        cutoff = min(new_era - 1, old_era)
        self._gc_below(cutoff)
        self._replay_postponed()

    def open_era(self, new_era: int) -> None:
        """Pipelined window open: move the FRONT era forward WITHOUT
        garbage-collecting anything. The eras in [window_floor, new_era]
        stay live concurrently — their protocols keep dispatching, their
        journal/outbox entries stay replayable. GC happens only at the
        commit edge (commit_era_gc), so a crash mid-window can replay every
        in-flight era from the journal instead of re-deriving values
        (no-self-equivocation across the whole window)."""
        if new_era <= self.era:
            return
        self.era = new_era
        self._replay_postponed()

    def commit_era_gc(self, committed_era: int) -> None:
        """Commit-edge GC for pipelined windows: era e is pruned only once
        every era that overlapped its window has committed — i.e. at the
        commit of era c, eras below c - pipeline_window + 1 are settled AND
        un-overlapped, so their journal entries, outboxes, sent-latches and
        protocol instances can go. window_floor advances to the oldest era
        still in flight."""
        self.window_floor = max(self.window_floor, committed_era + 1)
        cutoff = committed_era + 1 - max(self.pipeline_window, 1)
        self._gc_below(cutoff)

    def _gc_below(self, cutoff: int) -> None:
        stale = [
            pid
            for pid in self._protocols
            if getattr(pid, "era", cutoff) < cutoff
        ]
        for pid in stale:
            proto = self._protocols.pop(pid, None)
            if proto is not None:
                # laggards the era's outcome never needed: close their
                # lifetime spans so the trace doesn't report them as
                # stuck-open forever
                proto.close_span(outcome="era_gc")
        # outboxes follow the same retention as protocol instances: the last
        # active era stays serviceable for laggard re-requests, older eras
        # are settled on-chain and recoverable by block sync instead
        for e in [e for e in self._outbox if e < cutoff]:
            del self._outbox[e]
        for key in [k for k in self._sent_slots if k[0] < cutoff]:
            del self._sent_slots[key]
        # first-seen latch follows protocol retention (slot[1] is the
        # protocol id; its era keys the entry, like _sent_slots)
        for key in [
            k
            for k in self._first_seen
            if getattr(k[1][1], "era", cutoff) < cutoff
        ]:
            sender = key[0]
            cnt = self._first_seen_per_sender.get(sender, 0)
            if cnt > 1:
                self._first_seen_per_sender[sender] = cnt - 1
            else:
                self._first_seen_per_sender.pop(sender, None)
            del self._first_seen[key]
        if self._journal is not None:
            self._journal.prune_below(cutoff)

    def _replay_postponed(self) -> None:
        pending, self._postponed = self._postponed, []
        self._postponed_per_sender = {}
        for sender, payload in pending:
            self.dispatch_external(sender, payload)

    def result_of(self, pid) -> Any:
        proto = self._protocols.get(pid)
        return proto.result if proto else None

    def protocol(self, pid) -> Optional[Protocol]:
        return self._protocols.get(pid)

    # -- validation (EraBroadcaster.cs:418-529) -------------------------------
    def _validate_id(self, pid) -> bool:
        era = getattr(pid, "era", None)
        if era is None or not (self.window_floor <= era <= self.era):
            return False
        n = self.n_validators
        if isinstance(pid, M.ReliableBroadcastId):
            return 0 <= pid.sender_id < n
        if isinstance(pid, (M.BinaryAgreementId,)):
            return 0 <= pid.agreement < n
        if isinstance(pid, (M.BinaryBroadcastId, M.CoinId)):
            ok = 0 <= pid.agreement < n or pid.agreement == -1
            return ok and pid.epoch >= 0
        return True

    # -- factory (EraBroadcaster.CreateProtocol, 361-410) ---------------------
    def _ensure_protocol(self, pid) -> Optional[Protocol]:
        proto = self._protocols.get(pid)
        if proto is not None:
            return None if proto.terminated else proto
        if getattr(pid, "era", self.era) < self.window_floor:
            # a dead era's instances are garbage-collected on advance, so
            # their terminated tombstones are gone — a stale internal
            # request must not resurrect a fresh never-terminating
            # protocol whose broadcasts every peer discards
            return None
        proto = self._create(pid)
        if proto is None:
            logger.warning("no factory for protocol id %s", pid)
            return None
        self._protocols[pid] = proto
        return proto

    def _create(self, pid) -> Optional[Protocol]:
        if type(pid) in self._extra_factories:
            return self._extra_factories[type(pid)](pid, self)
        if isinstance(pid, M.BinaryBroadcastId):
            return BinaryBroadcast(pid, self)
        if isinstance(pid, M.CoinId):
            return CommonCoin(
                pid,
                self,
                self.private_keys.ts_share,
                self.public_keys.ts_keys,
            )
        if isinstance(pid, M.BinaryAgreementId):
            return BinaryAgreement(pid, self)
        if isinstance(pid, M.ReliableBroadcastId):
            return ReliableBroadcast(pid, self)
        if isinstance(pid, M.CommonSubsetId):
            return CommonSubset(pid, self)
        if isinstance(pid, M.HoneyBadgerId):
            return HoneyBadger(
                pid, self, self.public_keys, self.private_keys
            )
        return None
