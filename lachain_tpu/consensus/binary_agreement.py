"""Binary agreement (Mostéfaoui et al.) with the reference's coin schedule.

Behavioral parity with
/root/reference/src/Lachain.Consensus/BinaryAgreement/BinaryAgreement.cs:
  * even epochs run BinaryBroadcast(est), odd epochs produce a coin
    (TryProgressEpoch, BinaryAgreement.cs:52-143)
  * the coin cycles deterministic False / True / real-threshold-coin every
    three rounds (CoinToss schedule, CommonCoin/CoinToss.cs:3-33) — the
    deterministic prefix guarantees convergence within <=3 rounds once all
    honest estimates agree, which bounds how long a decided node must keep
    participating
  * F == 0 shortcut: the single "honest majority of one" uses a constant
    coin (BinaryAgreement.cs:196-201)
  * decide when bin_values == {b} and b == coin; else est <- coin

After deciding, the instance keeps participating for EXTRA_ROUNDS more rounds
so laggards can finish (cf. the reference keeping terminated-BA validation in
EraBroadcaster.cs:418-529), then terminates quietly.
"""
from __future__ import annotations

from typing import Dict, FrozenSet, Optional

from . import messages as M
from .protocol import Broadcaster, Protocol

EXTRA_ROUNDS = 3  # deterministic coin cycle length


def coin_schedule(epoch: int):
    """For odd epoch, return False/True for deterministic rounds or None when
    a real threshold coin is required (reference CoinToss.cs:3-33)."""
    assert epoch % 2 == 1
    k = (epoch // 2) % 3
    if k == 0:
        return False
    if k == 1:
        return True
    return None


class BinaryAgreement(Protocol):
    def __init__(self, pid: M.BinaryAgreementId, broadcaster: Broadcaster):
        super().__init__(pid, broadcaster)
        self._epoch = 0
        self._est: Optional[bool] = None
        self._started = False
        self._bin_values: Dict[int, FrozenSet[bool]] = {}  # per even epoch
        self._coins: Dict[int, bool] = {}  # per odd epoch
        self._decided: Optional[bool] = None
        self._decide_epoch: Optional[int] = None
        self._requested_bb: set = set()
        self._requested_coin: set = set()

    # -- input ---------------------------------------------------------------
    def handle_input(self, value: bool) -> None:
        if self._started:
            return
        self._started = True
        self._est = bool(value)
        self._advance()

    # -- child results -------------------------------------------------------
    def handle_child_result(self, child_id, value) -> None:
        if isinstance(child_id, M.BinaryBroadcastId):
            if child_id.epoch not in self._bin_values:
                self._bin_values[child_id.epoch] = value
                self._advance()
        elif isinstance(child_id, M.CoinId):
            if child_id.epoch not in self._coins:
                self._coins[child_id.epoch] = bool(value)
                self._advance()

    def handle_external(self, sender: int, payload) -> None:
        # BA itself has no external messages; children receive theirs directly.
        raise TypeError(f"unexpected payload {type(payload)}")

    # -- round machine -------------------------------------------------------
    def _advance(self) -> None:
        while not self.terminated:
            if self._epoch % 2 == 0:
                bb_id = M.BinaryBroadcastId(
                    self.id.era, self.id.agreement, self._epoch
                )
                if self._epoch not in self._requested_bb:
                    self._requested_bb.add(self._epoch)
                    self.request(bb_id, self._est)
                if self._epoch not in self._bin_values:
                    return  # waiting on BB result
                self._epoch += 1
            else:
                sched = coin_schedule(self._epoch)
                if self.f == 0:
                    # single-validator regime: constant coin suffices
                    coin = True if sched is None else sched
                elif sched is not None:
                    coin = sched
                else:
                    coin_id = M.CoinId(
                        self.id.era, self.id.agreement, self._epoch
                    )
                    if self._epoch not in self._requested_coin:
                        self._requested_coin.add(self._epoch)
                        self.request(coin_id, None)
                    if self._epoch not in self._coins:
                        return  # waiting on coin
                    coin = self._coins[self._epoch]
                self._finish_round(coin)

    def _finish_round(self, coin: bool) -> None:
        w = self._bin_values[self._epoch - 1]
        if len(w) == 1:
            (b,) = w
            self._est = b
            if b == coin and self._decided is None:
                self._decided = b
                self._decide_epoch = self._epoch
                self.emit_result(b)
        else:
            self._est = coin
        self._epoch += 1
        if (
            self._decide_epoch is not None
            and self._epoch > self._decide_epoch + 2 * EXTRA_ROUNDS
        ):
            self.terminated = True
