"""HoneyBadger: ACS orchestration + threshold decryption of the agreed set.

Behavioral parity with
/root/reference/src/Lachain.Consensus/HoneyBadger/HoneyBadger.cs:
  * input: TPKE-encrypt my tx batch, feed ACS (HandleInputMessage 110-117,
    CheckEncryption 119-127)
  * on ACS result: decrypt every accepted slot's ciphertext and broadcast the
    partial decryption (HandleCommonSubset 141-175)
  * incoming decryption shares: stash until ACS completes, dedupe per
    (decryptor, slot), then verify (HandleDecryptedMessage 190-228)
  * at F+1 valid shares for a slot: full-decrypt (CheckDecryptedShares
    237-247); result = {slot: plaintext}

TPU-first redesign of the hot path: instead of verifying each share with 2
pairings on arrival, shares accumulate per slot and are verified IN BATCH
(random-linear-combination: 2 pairings + MSM for the whole slot) exactly when
a slot reaches F+1 candidates — the batched kernel shape that bench.py
measures (BASELINE.md).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..crypto import tpke
from ..utils import tracing
from . import messages as M
from .keys import PrivateConsensusKeys, PublicConsensusKeys
from .protocol import Broadcaster, Protocol


class HoneyBadger(Protocol):
    def __init__(
        self,
        pid: M.HoneyBadgerId,
        broadcaster: Broadcaster,
        public_keys: PublicConsensusKeys,
        private_keys: PrivateConsensusKeys,
        skip_share_validation: bool = False,
    ):
        super().__init__(pid, broadcaster)
        self._pub = public_keys
        self._priv = private_keys
        self._skip_validation = skip_share_validation
        self._ciphertexts: Optional[Dict[int, tpke.EncryptedShare]] = None
        # per-slot: decryptor -> RAW share bytes (candidates, unverified).
        # Points are parsed lazily — only the t+1 shares actually chosen for
        # a combination ever pay the G1 parse + subgroup check (the ingest
        # path peeks the ids straight from the wire bytes)
        self._shares: Dict[int, Dict[int, bytes]] = {}
        self._parsed: Dict[Tuple[int, int], tpke.PartiallyDecryptedShare] = {}
        self._rejected: Dict[int, set] = {}
        self._plaintexts: Dict[int, Optional[bytes]] = {}
        # pre-ACS stash, deduped by (sender, slot) and bounded: a byzantine
        # validator may send at most one candidate per (sender, slot) pair
        self._stashed: Dict[Tuple[int, int], M.DecryptedMessage] = {}
        # slots whose jobs sit in a router-level crypto batcher awaiting flush
        self._inflight: set = set()
        self._batcher_queued = False
        self._lag_cache: Dict[Tuple[int, ...], list] = {}
        self._done = False

    # -- input ---------------------------------------------------------------
    def handle_input(self, value: bytes) -> None:
        enc = self._pub.tpke_pub.encrypt(value, share_id=self.me)
        self.request(M.CommonSubsetId(era=self.id.era), enc.to_bytes())

    # -- ACS result ----------------------------------------------------------
    def handle_child_result(self, child_id, value) -> None:
        if not isinstance(child_id, M.CommonSubsetId) or self._ciphertexts is not None:
            return
        self._ciphertexts = {}
        parsed: Dict[int, tpke.EncryptedShare] = {}
        in_slots = sorted(value)
        decoded = tpke.decode_encrypted_shares_batch(
            [value[s] for s in in_slots]
        )
        for slot, share in zip(in_slots, decoded):
            if share is None:
                # proposer shipped garbage through RBC: slot yields nothing
                self._plaintexts[slot] = None
            else:
                parsed[slot] = share
        # ciphertext validity for ALL accepted slots in one RLC multi-pairing
        # (2 pairings per slot in the reference, TPKE/PrivateKey.cs:21-27)
        slots = sorted(parsed)
        if self._skip_validation:
            oks = [True] * len(slots)
        else:
            oks = tpke.batch_verify_ciphertexts([parsed[s] for s in slots])
        for slot, ok in zip(slots, oks):
            if not ok:
                # invalid ciphertext (fails the pairing validity check)
                self._plaintexts[slot] = None
                continue
            share = parsed[slot]
            self._ciphertexts[slot] = share
            dec = self._priv.tpke_priv.decrypt_share(share, check=False)
            self.broadcaster.broadcast(
                M.DecryptedMessage(
                    hb=self.id, share_id=slot, payload=dec.to_bytes()
                )
            )
            self._shares.setdefault(slot, {})[self.me] = dec.to_bytes()
            self._parsed[(slot, self.me)] = dec
        stashed, self._stashed = self._stashed, {}
        for (sender, _slot), msg in stashed.items():
            self._on_decrypted(sender, msg, defer_decrypt=True)
        # era-tick aggregation point: by the time ACS completes, most slots
        # already hold their F+1 shares (they arrived during agreement and
        # were stashed) — decrypt them all in ONE batched call. This is the
        # S x K kernel shape BASELINE.md measures.
        self._try_decrypt_ready()
        self._try_complete()

    # -- externals -----------------------------------------------------------
    def handle_external(self, sender: int, payload) -> None:
        if not isinstance(payload, M.DecryptedMessage):
            raise TypeError(f"unexpected payload {type(payload)}")
        if self._ciphertexts is None:
            key = (sender, payload.share_id)
            if key not in self._stashed and 0 <= payload.share_id < self.n:
                self._stashed[key] = payload
            return
        self._on_decrypted(sender, payload)

    def _on_decrypted(
        self, sender: int, msg: M.DecryptedMessage, defer_decrypt: bool = False
    ) -> None:
        slot = msg.share_id
        if slot not in (self._ciphertexts or {}):
            return  # unknown/rejected slot
        if slot in self._plaintexts:
            return  # already decrypted
        # id checks straight off the wire bytes — the expensive point parse
        # is deferred until this share is chosen for a combination
        # (HoneyBadger.cs:196-217 dedup/decryptor-id checks)
        ids = tpke.peek_decrypted_share_ids(msg.payload)
        if ids is None or ids[0] != sender or ids[1] != slot:
            return
        slot_shares = self._shares.setdefault(slot, {})
        if sender in slot_shares or sender in self._rejected.get(slot, set()):
            return
        slot_shares[sender] = msg.payload
        if defer_decrypt:
            return
        batcher = getattr(self.broadcaster, "crypto_batcher", None)
        if batcher is not None and not self._skip_validation:
            # O(1) hot path: note once that ready work exists; the expensive
            # per-slot preparation happens exactly once, at flush time
            if (
                not self._batcher_queued
                and slot not in self._inflight
                and len(slot_shares) >= self._pub.f + 1
            ):
                self._batcher_queued = True
                batcher.submit_lazy(self._build_era_jobs_lazy)
        else:
            self._try_decrypt_ready()
            self._try_complete()

    # -- batched verify + combine --------------------------------------------
    def _ready_slots(self) -> List[int]:
        need = self._pub.f + 1
        return [
            s
            for s in (self._ciphertexts or {})
            if s not in self._plaintexts
            and s not in self._inflight
            and len(self._shares.get(s, {})) >= need
        ]

    def _try_decrypt_ready(self) -> None:
        """Decrypt every slot holding >= F+1 candidate shares, batching all
        of them through the TPU backend's era kernel when it is active
        (opportunistic micro-batching: whatever is pending runs NOW; with
        the host backends this degrades to the per-slot RLC batch path).
        """
        from ..crypto.provider import get_backend

        backend = get_backend()
        era_fn = getattr(backend, "tpke_era_verify_combine", None)
        if era_fn is None or self._skip_validation:
            for slot in self._ready_slots():
                self._try_decrypt(slot)
            return
        batcher = getattr(self.broadcaster, "crypto_batcher", None)
        if batcher is not None:
            # router-level flush batcher: the delivery loop flushes at
            # quiescence, fusing every validator's pending slots into ONE
            # backend call (one kernel launch on the TPU backend)
            if not self._batcher_queued and self._ready_slots():
                self._batcher_queued = True
                batcher.submit_lazy(self._build_era_jobs_lazy)
                tracing.instant(
                    "hb.queue_decrypt", cat="crypto", era=self.id.era
                )
            return
        built = self._build_era_jobs()
        if built is None:
            return
        jobs, vks, cb = built
        try:
            with tracing.span(
                "hb.era_decrypt",
                cat="crypto",
                era=self.id.era,
                slots=len(jobs),
            ):
                results = era_fn(jobs, vks)
        except Exception:
            # device path unavailable/broken (jax import, compile, OOM):
            # consensus liveness beats acceleration — host per-slot path
            from .protocol import logger as _plog

            _plog.exception("tpu era decrypt failed; host fallback")
            cb(None)
            return
        cb(results)

    def _build_era_jobs_lazy(self):
        """Batcher flush hook: build jobs for everything ready RIGHT NOW."""
        self._batcher_queued = False
        if self.terminated or self._done:
            return None
        return self._build_era_jobs()

    def _build_era_jobs(self):
        """Choose + lazily parse the combination shares for every ready slot
        and return (jobs, verification_keys, callback), or None when nothing
        is ready. A share failing the parse/subgroup check is dropped, its
        sender rejected, and the slot's choice recomputed from the survivors
        (the loop terminates: every retry removes at least one share)."""
        from ..crypto import bls12381 as bls
        from ..crypto.tpu_backend import EraSlotJob

        need = self._pub.f + 1
        while True:
            ready = self._ready_slots()
            if not ready:
                return None
            chosen_by_slot = {
                s: sorted(self._shares[s])[:need] for s in ready
            }
            wanted = [(s, i) for s in ready for i in chosen_by_slot[s]]
            if self._parse_shares(wanted) == 0:
                break
        jobs = []
        for slot in ready:
            ct = self._ciphertexts[slot]
            chosen = chosen_by_slot[slot]
            key = tuple(chosen)
            cs = self._lag_cache.get(key)
            if cs is None:
                # most slots choose the same first-F+1 decryptor set, so the
                # Lagrange coefficients memoize extremely well per era
                cs = bls.fr_lagrange_coeffs([i + 1 for i in chosen], at=0)
                self._lag_cache[key] = cs
            lag_row = [0] * self.n
            u_row = [None] * self.n
            # only the chosen F+1 lanes go live: they are exactly the
            # shares the combine consumes, so a byzantine validator's
            # extra bad share (never combined) cannot fail the grand check
            # and force the host fallback every era
            for i, c in zip(chosen, cs):
                lag_row[i] = c
                u_row[i] = self._parsed[(slot, i)].ui
            jobs.append(
                EraSlotJob(
                    u_by_validator=u_row,
                    lagrange_row=lag_row,
                    h=tpke.ciphertext_h(ct),
                    w=ct.w,
                )
            )
        self._inflight.update(ready)
        return (
            jobs,
            self._pub.tpke_verification_keys,
            lambda results, _ready=tuple(ready): self._era_results_cb(
                _ready, results
            ),
        )

    def _era_results_cb(self, ready, results) -> None:
        """Batcher flush callback: results is None when the batch call
        itself failed (host per-slot fallback), else per-job (ok, combined)."""
        self._inflight.difference_update(ready)
        if self.terminated or self._done:
            return
        if results is None:
            for slot in ready:
                self._try_decrypt(slot)
        else:
            self._apply_era_results(ready, results)
        # slots whose batch failed may have pruned a share but still hold
        # (or later regain) a quorum: re-queue whatever remains ready
        self._try_decrypt_ready()
        self._try_complete()

    def _apply_era_results(self, ready, results) -> None:
        with tracing.span(
            "hb.apply_era_results",
            cat="crypto",
            era=self.id.era,
            slots=len(ready),
        ):
            self._apply_era_results_inner(ready, results)

    def _apply_era_results_inner(self, ready, results) -> None:
        for slot, (ok, combined) in zip(ready, results):
            if ok:
                self._plaintexts[slot] = tpke.decrypt_with_combined(
                    self._ciphertexts[slot], combined
                )
            else:
                # a byzantine share poisoned the slot batch: the host path
                # isolates + prunes it (and may still decrypt from the
                # surviving valid shares)
                self._try_decrypt(slot)

    def _parse_shares(self, wanted) -> int:
        """Parse raw share bytes into `self._parsed` for the given
        (slot, sender) pairs — one batched deserialize+subgroup check for
        everything missing. Failing shares are dropped and their senders
        rejected for that slot. Returns the number of failures."""
        missing = [k for k in wanted if k not in self._parsed]
        if not missing:
            return 0
        from ..crypto import bls12381 as bls
        from ..crypto.provider import deserialize_batch_g1

        datas = [
            self._shares[slot][sender][: bls.G1_BYTES]
            for slot, sender in missing
        ]
        pts = deserialize_batch_g1(datas)
        failures = 0
        for (slot, sender), pt in zip(missing, pts):
            if pt is None:
                failures += 1
                del self._shares[slot][sender]
                self._rejected.setdefault(slot, set()).add(sender)
                self._flag_invalid(sender, slot)
            else:
                self._parsed[(slot, sender)] = tpke.PartiallyDecryptedShare(
                    ui=pt, decryptor_id=sender, share_id=slot
                )
        return failures

    def _try_decrypt(self, slot: int) -> None:
        if slot in self._plaintexts or self._ciphertexts is None:
            return
        need = self._pub.f + 1
        slot_shares = self._shares.get(slot, {})
        if len(slot_shares) < need:
            return
        self._parse_shares([(slot, i) for i in sorted(slot_shares)])
        if len(slot_shares) < need:
            return  # parse failures shrank the candidate set
        ct = self._ciphertexts[slot]
        decryptors = sorted(slot_shares)
        decs = [self._parsed[(slot, i)] for i in decryptors]
        if self._skip_validation:
            valid = decs
        else:
            vks = [self._pub.tpke_verification_keys[i] for i in decryptors]
            oks = self._pub.tpke_pub.batch_verify_shares(vks, decs, ct)
            valid = [d for d, ok in zip(decs, oks) if ok]
            for d, ok in zip(decs, oks):
                if not ok:
                    del slot_shares[d.decryptor_id]
                    self._rejected.setdefault(slot, set()).add(d.decryptor_id)
                    self._flag_invalid(d.decryptor_id, slot)
        if len(valid) < need:
            return  # byzantine shares pruned; wait for more
        self._plaintexts[slot] = self._pub.tpke_pub.full_decrypt(ct, valid)

    def _flag_invalid(self, sender: int, slot: int) -> None:
        """A decryption share failed its parse or pairing check: record
        the offense (evidence.py) on the router's store (when present —
        unit harnesses may construct protocols without one)."""
        ev = getattr(self.broadcaster, "evidence", None)
        if ev is not None:
            ev.record_invalid_share(self.id.era, sender, "dec", (slot,))

    def _try_complete(self) -> None:
        if self._done or self._ciphertexts is None:
            return
        # every ACS slot must be resolved (decrypted or rejected-as-garbage)
        if any(s not in self._plaintexts for s in self._ciphertexts):
            return
        self._done = True
        result = {
            slot: pt
            for slot, pt in sorted(self._plaintexts.items())
            if pt is not None
        }
        self.emit_result(result)
