"""HoneyBadger: ACS orchestration + threshold decryption of the agreed set.

Behavioral parity with
/root/reference/src/Lachain.Consensus/HoneyBadger/HoneyBadger.cs:
  * input: TPKE-encrypt my tx batch, feed ACS (HandleInputMessage 110-117,
    CheckEncryption 119-127)
  * on ACS result: decrypt every accepted slot's ciphertext and broadcast the
    partial decryption (HandleCommonSubset 141-175)
  * incoming decryption shares: stash until ACS completes, dedupe per
    (decryptor, slot), then verify (HandleDecryptedMessage 190-228)
  * at F+1 valid shares for a slot: full-decrypt (CheckDecryptedShares
    237-247); result = {slot: plaintext}

TPU-first redesign of the hot path: instead of verifying each share with 2
pairings on arrival, shares accumulate per slot and are verified IN BATCH
(random-linear-combination: 2 pairings + MSM for the whole slot) exactly when
a slot reaches F+1 candidates — the batched kernel shape that bench.py
measures (BASELINE.md).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..crypto import tpke
from . import messages as M
from .keys import PrivateConsensusKeys, PublicConsensusKeys
from .protocol import Broadcaster, Protocol


class HoneyBadger(Protocol):
    def __init__(
        self,
        pid: M.HoneyBadgerId,
        broadcaster: Broadcaster,
        public_keys: PublicConsensusKeys,
        private_keys: PrivateConsensusKeys,
        skip_share_validation: bool = False,
    ):
        super().__init__(pid, broadcaster)
        self._pub = public_keys
        self._priv = private_keys
        self._skip_validation = skip_share_validation
        self._ciphertexts: Optional[Dict[int, tpke.EncryptedShare]] = None
        # per-slot: decryptor -> share (candidates, unverified)
        self._shares: Dict[int, Dict[int, tpke.PartiallyDecryptedShare]] = {}
        self._rejected: Dict[int, set] = {}
        self._plaintexts: Dict[int, Optional[bytes]] = {}
        # pre-ACS stash, deduped by (sender, slot) and bounded: a byzantine
        # validator may send at most one candidate per (sender, slot) pair
        self._stashed: Dict[Tuple[int, int], M.DecryptedMessage] = {}
        self._done = False

    # -- input ---------------------------------------------------------------
    def handle_input(self, value: bytes) -> None:
        enc = self._pub.tpke_pub.encrypt(value, share_id=self.me)
        self.request(M.CommonSubsetId(era=self.id.era), enc.to_bytes())

    # -- ACS result ----------------------------------------------------------
    def handle_child_result(self, child_id, value) -> None:
        if not isinstance(child_id, M.CommonSubsetId) or self._ciphertexts is not None:
            return
        self._ciphertexts = {}
        for slot, blob in value.items():
            try:
                share = tpke.EncryptedShare.from_bytes(blob)
            except (ValueError, AssertionError):
                # proposer shipped garbage through RBC: slot yields nothing
                self._plaintexts[slot] = None
                continue
            self._ciphertexts[slot] = share
            try:
                dec = self._priv.tpke_priv.decrypt_share(share)
            except ValueError:
                # invalid ciphertext (fails the pairing validity check)
                self._plaintexts[slot] = None
                continue
            self.broadcaster.broadcast(
                M.DecryptedMessage(
                    hb=self.id, share_id=slot, payload=dec.to_bytes()
                )
            )
            self._shares.setdefault(slot, {})[self.me] = dec
        stashed, self._stashed = self._stashed, {}
        for (sender, _slot), msg in stashed.items():
            self._on_decrypted(sender, msg, defer_decrypt=True)
        # era-tick aggregation point: by the time ACS completes, most slots
        # already hold their F+1 shares (they arrived during agreement and
        # were stashed) — decrypt them all in ONE batched call. This is the
        # S x K kernel shape BASELINE.md measures.
        self._try_decrypt_ready()
        self._try_complete()

    # -- externals -----------------------------------------------------------
    def handle_external(self, sender: int, payload) -> None:
        if not isinstance(payload, M.DecryptedMessage):
            raise TypeError(f"unexpected payload {type(payload)}")
        if self._ciphertexts is None:
            key = (sender, payload.share_id)
            if key not in self._stashed and 0 <= payload.share_id < self.n:
                self._stashed[key] = payload
            return
        self._on_decrypted(sender, payload)

    def _on_decrypted(
        self, sender: int, msg: M.DecryptedMessage, defer_decrypt: bool = False
    ) -> None:
        slot = msg.share_id
        if slot not in (self._ciphertexts or {}):
            return  # unknown/rejected slot
        if slot in self._plaintexts:
            return  # already decrypted
        try:
            dec = tpke.PartiallyDecryptedShare.from_bytes(msg.payload)
        except (ValueError, AssertionError):
            return
        # the share must claim the sender as decryptor (HoneyBadger.cs:196-217
        # dedup/decryptor-id checks)
        if dec.decryptor_id != sender or dec.share_id != slot:
            return
        slot_shares = self._shares.setdefault(slot, {})
        if sender in slot_shares or sender in self._rejected.get(slot, set()):
            return
        slot_shares[sender] = dec
        if not defer_decrypt:
            self._try_decrypt_ready()
            self._try_complete()

    # -- batched verify + combine --------------------------------------------
    def _ready_slots(self) -> List[int]:
        need = self._pub.f + 1
        return [
            s
            for s in (self._ciphertexts or {})
            if s not in self._plaintexts
            and len(self._shares.get(s, {})) >= need
        ]

    def _try_decrypt_ready(self) -> None:
        """Decrypt every slot holding >= F+1 candidate shares, batching all
        of them through the TPU backend's era kernel when it is active
        (opportunistic micro-batching: whatever is pending runs NOW; with
        the host backends this degrades to the per-slot RLC batch path).
        """
        ready = self._ready_slots()
        if not ready:
            return
        from ..crypto.provider import get_backend

        backend = get_backend()
        era_fn = getattr(backend, "tpke_era_verify_combine", None)
        if era_fn is None or self._skip_validation:
            for slot in ready:
                self._try_decrypt(slot)
            return
        from ..crypto import bls12381 as bls
        from ..crypto.tpu_backend import EraSlotJob

        need = self._pub.f + 1
        jobs = []
        for slot in ready:
            ct = self._ciphertexts[slot]
            slot_shares = self._shares[slot]
            chosen = sorted(slot_shares)[:need]
            cs = bls.fr_lagrange_coeffs([i + 1 for i in chosen], at=0)
            lag_row = [0] * self.n
            u_row = [None] * self.n
            # only the chosen F+1 lanes go live: they are exactly the
            # shares the combine consumes, so a byzantine validator's
            # extra bad share (never combined) cannot fail the grand check
            # and force the host fallback every era
            for i, c in zip(chosen, cs):
                lag_row[i] = c
                u_row[i] = slot_shares[i].ui
            jobs.append(
                EraSlotJob(
                    u_by_validator=u_row,
                    lagrange_row=lag_row,
                    h=tpke.ciphertext_h(ct),
                    w=ct.w,
                )
            )
        try:
            results = era_fn(jobs, self._pub.tpke_verification_keys)
        except Exception:
            # device path unavailable/broken (jax import, compile, OOM):
            # consensus liveness beats acceleration — host per-slot path
            from .protocol import logger as _plog

            _plog.exception("tpu era decrypt failed; host fallback")
            for slot in ready:
                self._try_decrypt(slot)
            return
        for slot, (ok, combined) in zip(ready, results):
            if ok:
                self._plaintexts[slot] = tpke.decrypt_with_combined(
                    self._ciphertexts[slot], combined
                )
            else:
                # a byzantine share poisoned the slot batch: the host path
                # isolates + prunes it (and may still decrypt from the
                # surviving valid shares)
                self._try_decrypt(slot)

    def _try_decrypt(self, slot: int) -> None:
        if slot in self._plaintexts or self._ciphertexts is None:
            return
        need = self._pub.f + 1
        slot_shares = self._shares.get(slot, {})
        if len(slot_shares) < need:
            return
        ct = self._ciphertexts[slot]
        decryptors = sorted(slot_shares)
        decs = [slot_shares[i] for i in decryptors]
        if self._skip_validation:
            valid = decs
        else:
            vks = [self._pub.tpke_verification_keys[i] for i in decryptors]
            oks = self._pub.tpke_pub.batch_verify_shares(vks, decs, ct)
            valid = [d for d, ok in zip(decs, oks) if ok]
            for d, ok in zip(decs, oks):
                if not ok:
                    del slot_shares[d.decryptor_id]
                    self._rejected.setdefault(slot, set()).add(d.decryptor_id)
        if len(valid) < need:
            return  # byzantine shares pruned; wait for more
        self._plaintexts[slot] = self._pub.tpke_pub.full_decrypt(ct, valid)

    def _try_complete(self) -> None:
        if self._done or self._ciphertexts is None:
            return
        # every ACS slot must be resolved (decrypted or rejected-as-garbage)
        if any(s not in self._plaintexts for s in self._ciphertexts):
            return
        self._done = True
        result = {
            slot: pt
            for slot, pt in sorted(self._plaintexts.items())
            if pt is not None
        }
        self.emit_result(result)
