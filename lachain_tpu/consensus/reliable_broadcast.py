"""Reliable broadcast (Bracha + Reed-Solomon shards + Merkle commitments).

Behavioral parity with
/root/reference/src/Lachain.Consensus/ReliableBroadcast/ReliableBroadcast.cs:
  * sender RS-encodes the payload into N shards over a Merkle root and ships
    VAL_i to validator i (ConstructValMessages, 321-338)
  * VAL accepted only from the slot's sender (125-160)
  * each validator ECHOes its own shard; at N-2F echoes, interpolate the
    payload, re-encode, recheck the root (201-234, 421-444)
  * READY on successful interpolation; READY amplification at F+1 (236-249)
  * deliver at 2F+1 READY + successful reconstruction (251-288)

Shard count: K = N - 2F data shards (tolerates F missing + F wrong).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..crypto import hashes
from ..ops import rs
from . import messages as M
from .protocol import Broadcaster, Protocol


class ReliableBroadcast(Protocol):
    def __init__(self, pid: M.ReliableBroadcastId, broadcaster: Broadcaster):
        super().__init__(pid, broadcaster)
        self._echo: Dict[bytes, Dict[int, Tuple[bytes, Tuple[bytes, ...]]]] = {}
        self._ready: Dict[bytes, Set[int]] = {}
        self._echo_sent = False
        self._ready_sent = False
        # per-root reconstruction (an equivocating sender can make different
        # honest nodes interpolate different roots first; delivery must follow
        # whichever root reaches READY quorum, so track payloads per root)
        self._payloads: Dict[bytes, bytes] = {}
        self._bad_roots: Set[bytes] = set()
        self._delivered = False
        self._val_seen = False
        # roots with an interpolation submitted to the era RBC batcher and
        # not yet resolved — suppresses duplicate submissions while further
        # echoes for the same root keep arriving
        self._interp_inflight: Set[bytes] = set()

    @property
    def _k(self) -> int:
        return max(self.n - 2 * self.f, 1)

    @property
    def _batcher(self):
        """The era RBC flush batcher, when the network wired one onto the
        router (rbc_batcher.py); None means every codec call runs inline."""
        return getattr(self.broadcaster, "rbc_batcher", None)

    # -- sender input --------------------------------------------------------
    def handle_input(self, value: Optional[bytes]) -> None:
        if value is None:
            return  # participant-only instance
        if self.id.sender_id != self.me:
            raise ValueError("only the slot's sender may input a payload")
        batcher = self._batcher
        if batcher is not None:
            # eager-encode: the proposal is queued before the era front so
            # the first flush codes every validator's proposal in one call
            batcher.submit_encode(
                self.id.era, value, self._k, self.n, self._send_vals
            )
            return
        self._send_vals(rs.encode(value, self._k, self.n))

    def _send_vals(self, shards: List[bytes]) -> None:
        leaves = [hashes.keccak256(s) for s in shards]
        root = hashes.merkle_root(leaves)
        for i in range(self.n):
            branch = tuple(hashes.merkle_proof(leaves, i))
            self.broadcaster.send_to(
                i,
                M.ValMessage(
                    rbc=self.id,
                    root=root,
                    branch=branch,
                    shard=shards[i],
                    shard_index=i,
                ),
            )

    # -- externals -----------------------------------------------------------
    def handle_external(self, sender: int, payload) -> None:
        if isinstance(payload, M.ValMessage):
            self._on_val(sender, payload)
        elif isinstance(payload, M.EchoMessage):
            self._on_echo(sender, payload)
        elif isinstance(payload, M.ReadyMessage):
            self._on_ready(sender, payload)
        else:
            raise TypeError(f"unexpected payload {type(payload)}")

    def _on_val(self, sender: int, msg: M.ValMessage) -> None:
        # VAL must come from the slot's sender, once, addressed to me
        if sender != self.id.sender_id or self._val_seen:
            return
        if msg.shard_index != self.me:
            return
        if not self._check_branch(msg.root, msg.branch, msg.shard, msg.shard_index):
            return
        self._val_seen = True
        if not self._echo_sent:
            self._echo_sent = True
            self.broadcaster.broadcast(
                M.EchoMessage(
                    rbc=self.id,
                    root=msg.root,
                    branch=msg.branch,
                    shard=msg.shard,
                    shard_index=msg.shard_index,
                )
            )

    def _on_echo(self, sender: int, msg: M.EchoMessage) -> None:
        # each validator echoes exactly its own shard
        if msg.shard_index != sender:
            return
        # duplicate check BEFORE the branch proof: a re-delivered echo must
        # not pay keccak + Merkle verification again (the .get keeps bogus
        # roots from allocating state pre-verification)
        seen = self._echo.get(msg.root)
        if seen is not None and sender in seen:
            return
        if not self._check_branch(msg.root, msg.branch, msg.shard, msg.shard_index):
            return
        slot = self._echo.setdefault(msg.root, {})
        slot[sender] = (msg.shard, msg.branch)
        self._try_interpolate(msg.root)
        self._try_deliver()

    def _on_ready(self, sender: int, msg: M.ReadyMessage) -> None:
        peers = self._ready.setdefault(msg.root, set())
        if sender in peers:
            return
        peers.add(sender)
        if len(peers) >= self.f + 1 and not self._ready_sent:
            self._ready_sent = True
            self.broadcaster.broadcast(
                M.ReadyMessage(rbc=self.id, root=msg.root)
            )
        self._try_deliver()

    # -- reconstruction ------------------------------------------------------
    def _check_branch(
        self, root: bytes, branch, shard: bytes, index: int
    ) -> bool:
        leaf = hashes.keccak256(shard)
        return hashes.merkle_verify(leaf, index, list(branch), root)

    def _try_interpolate(self, root: bytes) -> None:
        if root in self._payloads or root in self._bad_roots:
            return
        slot = self._echo.get(root, {})
        if len(slot) < self.n - 2 * self.f:
            return
        full: List[Optional[bytes]] = [None] * self.n
        for idx, (shard, _branch) in slot.items():
            full[idx] = shard
        batcher = self._batcher
        if batcher is not None:
            if root in self._interp_inflight:
                return  # already queued; later echoes cannot change the verdict
            self._interp_inflight.add(root)
            batcher.submit_interpolate(
                self.id.era,
                full,
                self._k,
                self.n,
                root,
                lambda payload, _root=root: self._apply_interpolation(
                    _root, payload
                ),
            )
            return
        reencoded = rs.reencode(full, self._k)
        if reencoded is None:
            self._apply_interpolation(root, None)
            return
        # malicious-sender check: recomputed Merkle root must match
        leaves = [hashes.keccak256(s) for s in reencoded]
        if hashes.merkle_root(leaves) != root:
            self._apply_interpolation(root, None)  # equivocated shards
            return
        self._apply_interpolation(root, rs.decode(full, self._k))

    def _apply_interpolation(
        self, root: bytes, payload: Optional[bytes]
    ) -> None:
        """Settle one interpolation verdict (inline or batcher callback):
        None marks the root bad forever; a payload arms READY + delivery."""
        self._interp_inflight.discard(root)
        if root in self._payloads or root in self._bad_roots:
            return
        if payload is None:
            self._bad_roots.add(root)
            return
        self._payloads[root] = payload
        if not self._ready_sent:
            self._ready_sent = True
            self.broadcaster.broadcast(M.ReadyMessage(rbc=self.id, root=root))
        self._try_deliver()

    def _try_deliver(self) -> None:
        if self._delivered:
            return
        for root, payload in self._payloads.items():
            if len(self._ready.get(root, set())) >= 2 * self.f + 1:
                self._delivered = True
                self.emit_result(payload)
                return
