"""Deterministic in-process multi-validator simulator with adversarial
delivery.

Parity with the reference's test harness (SURVEY.md §4.1):
  * DeliveryService w/ TAKE_FIRST / TAKE_LAST / TAKE_RANDOM reordering and
    duplicate injection (test/Lachain.ConsensusTest/DeliverySerivce.cs:10-124)
  * BroadcastSimulator auto-instantiating protocols
    (BroadcastSimulator.cs:16-225)
  * muted ("crashed") players (DeliverySerivce.cs:45-48)

Unlike the reference's thread-based router, delivery here is a single seeded
loop: identical seeds replay identical executions, including adversarial
reorderings — the determinism requirement called out in SURVEY.md §7
("hard parts" #3).

Beyond the legacy ad-hoc knobs (mode / repeat_probability / muted), a
`FaultPlan` (network/faults.py) injects seeded drop/delay/duplicate/reorder
faults plus scheduled crash/restart windows and healing partitions; the
virtual clock is the delivered-message count. Lost messages are repaired the
same way the real node repairs them — replay from each router's per-era
outbox — triggered here on quiescence (the in-process analogue of the
message_request wire exchange).
"""
from __future__ import annotations

import enum
import heapq
import random
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..utils import metrics, tracing
from . import messages as M
from .era import EraRouter
from .keys import PrivateConsensusKeys, PublicConsensusKeys


class DeliveryMode(enum.Enum):
    TAKE_FIRST = "first"
    TAKE_LAST = "last"
    TAKE_RANDOM = "random"


class SimulatedNetwork:
    """N validators, one EraRouter each, a shared adversarial delivery queue."""

    def __init__(
        self,
        public_keys: PublicConsensusKeys,
        private_keys: List[PrivateConsensusKeys],
        era: int = 0,
        seed: int = 0,
        mode: DeliveryMode = DeliveryMode.TAKE_FIRST,
        repeat_probability: float = 0.0,
        muted: Optional[Set[int]] = None,
        extra_factories: Optional[Dict[type, Callable]] = None,
        router_cls=EraRouter,
        use_crypto_batcher: bool = True,
        use_rbc_batcher: bool = False,
        fault_plan=None,
        max_recovery_rounds: int = 16,
    ):
        self.n = public_keys.n
        self.rng = random.Random(seed)
        self.mode = mode
        self.repeat_probability = repeat_probability
        self.muted = muted or set()
        # seeded fault schedule: clocked by delivered-message count so two
        # runs with one seed replay bit-identical fault sequences
        self.fault_plan = fault_plan
        self._vtime = 0.0
        self.faults = (
            fault_plan.session(clock=lambda: self._vtime)
            if fault_plan is not None
            else None
        )
        self.recovery_rounds = 0
        self.max_recovery_rounds = max_recovery_rounds
        # (sender, target, payload). Container picked per mode so every
        # _pop is O(1) at 2M-message eras (N=64): deque for FIFO/LIFO
        # (popleft/pop), plain list for RANDOM (indexed swap-with-last +
        # pop from the end — deque middle indexing is O(n))
        self._queue = (
            [] if mode is DeliveryMode.TAKE_RANDOM else deque()
        )
        # time-armed copies (fault delays + LinkShaper latency): a heap of
        # (ready_at, seq, sender, target, payload) surfaced once the
        # virtual clock reaches ready_at. The seq tiebreak keeps pops
        # deterministic and keeps payloads out of heap comparisons.
        self._delayed: List[Tuple[float, int, int, int, Any]] = []
        self._delay_seq = 0
        self.routers: List[EraRouter] = []
        for i in range(self.n):
            self.routers.append(
                router_cls(
                    era=era,
                    my_id=i,
                    public_keys=public_keys,
                    private_keys=private_keys[i],
                    send=self._make_send(i),
                    extra_factories=extra_factories,
                )
            )
        self.delivered_count = 0
        # router-level TPKE flush batcher (crypto_batcher.py): flushed once
        # every queued DecryptedMessage has been delivered, fusing every
        # validator's pending verify+combine work into one backend call
        self.crypto_batcher = None
        self._decrypted_in_queue = 0
        if use_crypto_batcher:
            from .crypto_batcher import TpkeEraBatcher

            self.crypto_batcher = TpkeEraBatcher()
            for r in self.routers:
                r.crypto_batcher = self.crypto_batcher
        # router-level RBC flush batcher (rbc_batcher.py): every pending
        # Reed-Solomon encode/interpolate flushes as one batched matrix
        # product at quiescence. Opt-in (default off) so seed-pinned
        # message schedules in existing tests stay byte-identical.
        self.rbc_batcher = None
        if use_rbc_batcher:
            from .rbc_batcher import RbcEraBatcher

            self.rbc_batcher = RbcEraBatcher()
            for r in self.routers:
                r.rbc_batcher = self.rbc_batcher

    def _make_send(self, sender: int):
        def send(target: Optional[int], payload) -> None:
            if sender in self.muted:
                return  # crashed player: no outbound traffic
            if self.faults is not None and self.faults.crashed(sender):
                return  # scheduled crash window: no outbound traffic
            if type(payload) is M.DecryptedMessage:
                self._decrypted_in_queue += self.n if target is None else 1
            if target is None:
                for t in range(self.n):
                    self._queue.append((sender, t, payload))
            else:
                self._queue.append((sender, target, payload))

        return send

    def inject(self, sender: int, target: Optional[int], payload) -> None:
        """Adversary-layer injection: enqueue a payload AS IF `sender` sent
        it, bypassing the sender's router (and its no-self-equivocation
        journal latch). target None = broadcast. Keeps the DecryptedMessage
        flush accounting coherent so the crypto batcher still fires."""
        if type(payload) is M.DecryptedMessage:
            self._decrypted_in_queue += self.n if target is None else 1
        if target is None:
            for t in range(self.n):
                self._queue.append((sender, t, payload))
        else:
            self._queue.append((sender, target, payload))

    # -- adversarial queue ----------------------------------------------------
    def _pop(self) -> Tuple[int, int, Any]:
        if self.mode is DeliveryMode.TAKE_FIRST:
            item = self._queue.popleft()
        elif self.mode is DeliveryMode.TAKE_LAST:
            item = self._queue.pop()
        else:
            # uniform random choice via swap-with-last + list pop: O(1);
            # surviving order is irrelevant under random selection
            idx = self.rng.randrange(len(self._queue))
            last = self._queue.pop()
            if idx < len(self._queue):
                item = self._queue[idx]
                self._queue[idx] = last
            else:
                item = last
        if self.repeat_probability > 0 and self.rng.random() < self.repeat_probability:
            if type(item[2]) is M.DecryptedMessage:
                self._decrypted_in_queue += 1
            self._queue.append(item)  # duplicate injection
        if (
            self.faults is not None
            and self._queue
            and self.faults.reorder_hit()
        ):
            # fault-plan reordering: swap the picked message with a random
            # queued one (composes with any DeliveryMode)
            idx = self.faults.rng.randrange(len(self._queue))
            item, self._queue[idx] = self._queue[idx], item
        return item

    # -- execution ------------------------------------------------------------
    def post_request(self, validator: int, pid, value) -> None:
        """Inject a top-level ProtocolRequest into one validator."""
        self.routers[validator].internal_request(
            M.Request(from_id=None, to_id=pid, input=value)
        )

    def run(
        self,
        done: Callable[[], bool],
        max_messages: int = 1_000_000,
    ) -> bool:
        """Deliver until `done()` or quiescence/cap. True iff done() held."""
        while not done():
            if self._delayed and self._delayed[0][0] <= self._vtime:
                # a time-armed copy's moment has come: deliver it directly —
                # its link decision was already made when it was armed, so
                # WAN latency defers a message without re-rolling its fate
                if self.delivered_count >= max_messages:
                    raise RuntimeError(
                        f"message cap {max_messages} exceeded — livelock?"
                    )
                _, _, sender, target, payload = heapq.heappop(self._delayed)
                self.delivered_count += 1
                self._vtime += 1.0
                if type(payload) is M.DecryptedMessage:
                    self._decrypted_in_queue -= 1
                if target not in self.muted and not (
                    self.faults is not None and self.faults.crashed(target)
                ):
                    self.routers[target].dispatch_external(sender, payload)
                self._maybe_flush()
                continue
            if not self._queue:
                if self._delayed:
                    # every undelivered message is still in flight on a
                    # shaped/delayed link: advance the virtual clock to the
                    # earliest arrival (latency passing, not quiescence)
                    self._vtime = max(self._vtime, self._delayed[0][0])
                    continue
                metrics.set_gauge("consensus_dispatch_queue_depth", 0)
                # RBC before TPKE: interpolation verdicts unblock READY /
                # delivery traffic that feeds the ACS, whose completions are
                # what make decrypt-share batches grow — flushing RBC first
                # keeps the later crypto flush as large as possible
                if self.rbc_batcher is not None and self.rbc_batcher.pending:
                    self.rbc_batcher.flush()
                    continue
                if self.crypto_batcher is not None and self.crypto_batcher.pending:
                    self.crypto_batcher.flush()
                    continue
                if self.faults is not None:
                    # outbox replay is the in-process stand-in for the
                    # message_request wire exchange: waiting on it is a
                    # network receive wait
                    with tracing.wait("net", kind="recover"):
                        recovered = self._recover()
                    if recovered:
                        continue
                return done()
            if self.delivered_count >= max_messages:
                raise RuntimeError(
                    f"message cap {max_messages} exceeded — livelock?"
                )
            sender, target, payload = self._pop()
            self.delivered_count += 1
            self._vtime += 1.0
            if type(payload) is M.DecryptedMessage:
                self._decrypted_in_queue -= 1
            deliver = True
            if self.faults is not None and sender != target:
                # self-delivery never traverses the network: only link
                # traffic is subject to loss/dup/delay/partition/shaping
                delays = self.faults.decide(sender, target)
                deliver = bool(delays) and delays[0] <= 0
                for d in delays[1:] if deliver else delays:
                    if type(payload) is M.DecryptedMessage:
                        self._decrypted_in_queue += 1
                    if d <= 0:
                        # duplicate: a second full traversal of the link,
                        # re-rolling the dice like any fresh send
                        self._queue.append((sender, target, payload))
                    else:
                        # delayed/shaped copy: armed to surface once the
                        # clock reaches its delivery time
                        self._delay_seq += 1
                        heapq.heappush(
                            self._delayed,
                            (
                                self._vtime + d,
                                self._delay_seq,
                                sender,
                                target,
                                payload,
                            ),
                        )
            elif self.faults is not None and self.faults.crashed(target):
                deliver = False  # crashed: not even self-delivery
            if deliver and target not in self.muted:
                # crashed player: no inbound processing either
                self.routers[target].dispatch_external(sender, payload)
            self._maybe_flush()
        return True

    def _maybe_flush(self) -> None:
        """Flush the TPKE batcher once every queued DecryptedMessage has
        been delivered: the cross-validator batch is at its largest — flush
        NOW, before BinaryAgreement lag rounds spawn fresh coin work."""
        b = self.crypto_batcher
        if b is not None and b.pending and self._decrypted_in_queue == 0:
            b.flush()

    def _recover(self) -> bool:
        """Quiescent but not done under a fault plan: the wedged-era state
        the recovery protocol exists for. Jump the virtual clock to the next
        schedule boundary (healing partitions / restarting crashed nodes
        needs time to pass, and quiescence means no deliveries advance it),
        then replay every live router's per-era outbox across every
        currently-unblocked link — the in-process model of the
        message_request/outbox-replay wire exchange. Returns True when any
        message was re-enqueued; bounded by max_recovery_rounds so a
        genuinely unrecoverable plan (f+1 permanent crashes) terminates."""
        f = self.faults
        if self.recovery_rounds >= self.max_recovery_rounds:
            return False
        boundary = f.next_boundary(self._vtime)
        if boundary is not None:
            self._vtime = max(self._vtime, boundary)
        self.recovery_rounds += 1
        requeued = 0
        for requester in range(self.n):
            if requester in self.muted or f.crashed(requester):
                continue
            for responder in range(self.n):
                if (
                    responder == requester
                    or responder in self.muted
                    or f.crashed(responder)
                    or f.partitioned(responder, requester)
                ):
                    continue
                router = self.routers[responder]
                requeued += router.replay_outbox(router.era, requester)
        return requeued > 0

    def results(self, pid) -> List[Any]:
        return [r.result_of(pid) for r in self.routers]
