"""Host shims for the natively-hosted crypto protocols.

The C++ engine (native/consensus_rt.cpp) owns the MESSAGE state machines of
CommonCoin, HoneyBadger and RootProtocol — dedupe, thresholds, stashes,
result routing — while these shims own every cryptographic operation: BLS
threshold signing/combining, TPKE encrypt/decrypt-share/verify/combine, and
ECDSA header signatures. The two halves talk through BATCHED crossings (one
generic callback op covers many messages: all pending coin shares, all ready
decrypt-share slots, all unverified header signatures), which is what removes
the per-message Python callback cost from the era hot path.

Each shim mirrors its oracle class (common_coin.py / honey_badger.py /
root_protocol.py) statement-for-statement on the crypto side, reusing the
exact same primitives, so a TAKE_FIRST native run stays bit-identical to the
Python engine — tests/test_native_rt.py pins that equality.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..crypto import ecdsa, tpke
from ..crypto import threshold_sig as ts
from ..utils import tracing
from . import messages as M

# --- shared contract with consensus_rt.cpp (enums CrossOp/PostOp/ReqKind) ---

# engine -> Python crossing ops
XO_COIN_SIGN = 1
XO_COIN_COMBINE = 2
XO_COIN_RESULT = 3
XO_HB_ACS = 4
XO_HB_QUEUE = 5
XO_HB_DONE = 6
XO_ROOT_INPUT = 7
XO_ROOT_SIGN = 8
XO_ROOT_VERIFY = 9
XO_ROOT_PRODUCE = 10
XO_EVIDENCE = 11
XO_RBC_ENCODE = 12
XO_RBC_NEED = 13

XO_NAMES = {
    XO_COIN_SIGN: "coin_sign",
    XO_COIN_COMBINE: "coin_combine",
    XO_COIN_RESULT: "coin_result",
    XO_HB_ACS: "hb_acs",
    XO_HB_QUEUE: "hb_queue",
    XO_HB_DONE: "hb_done",
    XO_ROOT_INPUT: "root_input",
    XO_ROOT_SIGN: "root_sign",
    XO_ROOT_VERIFY: "root_verify",
    XO_ROOT_PRODUCE: "root_produce",
    XO_EVIDENCE: "evidence",
    XO_RBC_ENCODE: "rbc_encode",
    XO_RBC_NEED: "rbc_need",
}

# Python -> engine post ops
PO_COIN_SHARE = 1
PO_COIN_RESULT = 2
PO_HB_ACS_INPUT = 3
PO_HB_DECRYPTED = 4
PO_HB_ACS_DONE = 5
PO_HB_RESOLVED = 6
PO_HB_REJECT = 7
PO_HB_SET_INFLIGHT = 8
PO_HB_CLEAR_INFLIGHT = 9
PO_HB_CLEAR_QUEUED = 10
PO_HB_REQUEUE_CHECK = 11
PO_ROOT_HEADER = 12
PO_ROOT_ACCEPT = 13
PO_ROOT_REJECT = 14
PO_RBC_VALS = 15
PO_RBC_RESULT = 16

# rt_request kinds
RQ_HB = 1
RQ_COIN = 2
RQ_ROOT = 3


def iter_pairs(blob: bytes) -> List[Tuple[int, bytes]]:
    """Decode the engine's (u32 id, u32 len, bytes)* big-endian framing."""
    out = []
    off = 0
    end = len(blob)
    while off + 8 <= end:
        ident = int.from_bytes(blob[off : off + 4], "big")
        ln = int.from_bytes(blob[off + 4 : off + 8], "big")
        off += 8
        out.append((ident, blob[off : off + ln]))
        off += ln
    return out


class CoinHost:
    """Crypto half of a native CommonCoin (common_coin.py oracle): owns the
    ThresholdSigner; share dedupe/threshold/routing live in the engine."""

    def __init__(self, router, cid: M.CoinId):
        self.router = router
        self.cid = cid
        self._signer = ts.ThresholdSigner(
            cid.to_bytes(),
            router.private_keys.ts_share,
            router.public_keys.ts_keys,
        )
        self._flagged: set = set()  # senders already reported as evidence

    def sign(self) -> None:
        # common_coin.py::handle_input — the engine broadcasts + records the
        # share and runs its combine check inside the rt_post call
        my_share = self._signer.sign()
        payload = M.CoinMessage(coin=self.cid, share=my_share.to_bytes())
        wire = self.router._native_send(payload)
        self._signer.add_share(my_share, verify=False)
        self.router._net._rt_post(
            self.router.my_id,
            PO_COIN_SHARE,
            self.cid.agreement,
            self.cid.epoch,
            wire.share,
            era=self.cid.era,
        )

    def combine(self, blob: bytes) -> None:
        # common_coin.py::_try_combine crypto half: one batched G2 parse for
        # every share the engine has not shipped yet, then evaluate the
        # combined signature (deferred verification, prune on failure)
        pending = iter_pairs(blob)
        if pending:
            from ..crypto import bls12381 as bls
            from ..crypto.provider import deserialize_batch_g2

            pts = deserialize_batch_g2(
                [data[: bls.G2_BYTES] for _, data in pending]
            )
            for (sender, _), pt in zip(pending, pts):
                if pt is None:
                    self._flag_invalid(sender)
                    continue  # malformed/bad-subgroup share: drop
                self._signer.add_share(
                    ts.PartialSignature(sigma=pt, signer_id=sender),
                    verify=False,
                )
        sig = self._signer.signature
        # common_coin.py::_try_combine: batch-verifier prunes are evidence
        for sender in self._signer.pruned - self._flagged:
            self._flag_invalid(sender)
        if sig is not None:
            self.router._net._rt_post(
                self.router.my_id,
                PO_COIN_RESULT,
                self.cid.agreement,
                self.cid.epoch,
                bytes([1 if sig.parity else 0]),
                era=self.cid.era,
            )

    def _flag_invalid(self, sender: int) -> None:
        if sender in self._flagged:
            return
        self._flagged.add(sender)
        ev = getattr(self.router, "evidence", None)
        if ev is not None:
            ev.record_invalid_share(
                self.cid.era,
                sender,
                "coin",
                (self.cid.agreement, self.cid.epoch),
            )


class HoneyBadgerHost:
    """Crypto half of a native HoneyBadger (honey_badger.py oracle): TPKE
    encrypt/decode/verify/decrypt + the era-batcher build/apply protocol.
    The engine mirrors share candidates; `_cands` is this side's snapshot,
    refreshed from the engine at every batch build."""

    def __init__(self, router, era: int):
        self.router = router
        self.id = M.HoneyBadgerId(era=era)
        self._pub = router.public_keys
        self._priv = router.private_keys
        self.me = router.my_id
        self.n = self._pub.n
        self._ciphertexts: Dict[int, tpke.EncryptedShare] = {}
        self._plaintexts: Dict[int, Optional[bytes]] = {}
        self._parsed: Dict[Tuple[int, int], tpke.PartiallyDecryptedShare] = {}
        self._cands: Dict[int, Dict[int, bytes]] = {}
        self._lag_cache: Dict[Tuple[int, ...], list] = {}
        self.done = False
        self.result: Optional[dict] = None

    def _post(self, op: int, a: int = 0, b: int = 0, data: bytes = b"") -> None:
        self.router._net._rt_post(
            self.router.my_id, op, a, b, data, era=self.id.era
        )

    # -- input ---------------------------------------------------------------
    def handle_input(self, value: bytes) -> None:
        enc = self._pub.tpke_pub.encrypt(value, share_id=self.me)
        self._post(PO_HB_ACS_INPUT, data=enc.to_bytes())

    # -- ACS result (XO_HB_ACS) ----------------------------------------------
    def on_acs(self, blob: bytes) -> None:
        # honey_badger.py::handle_child_result crypto half. Slot order in the
        # blob is ascending (engine), matching the oracle's sorted(value)
        items = iter_pairs(blob)
        decoded = tpke.decode_encrypted_shares_batch([d for _, d in items])
        parsed: Dict[int, tpke.EncryptedShare] = {}
        for (slot, _), share in zip(items, decoded):
            if share is None:
                # proposer shipped garbage through RBC: slot yields nothing
                self._plaintexts[slot] = None
                self._post(PO_HB_RESOLVED, a=slot)
            else:
                parsed[slot] = share
        slots = sorted(parsed)
        oks = tpke.batch_verify_ciphertexts([parsed[s] for s in slots])
        valid = []
        for slot, ok in zip(slots, oks):
            if not ok:
                self._plaintexts[slot] = None
                self._post(PO_HB_RESOLVED, a=slot)
                continue
            self._ciphertexts[slot] = parsed[slot]
            valid.append(slot)
        # one threaded backend call for all U^{x_i} muls instead of one
        # native crossing per slot (same math, same emission order)
        decs = tpke.decrypt_shares_batch(
            self._priv.tpke_priv, [parsed[s] for s in valid]
        )
        for slot, dec in zip(valid, decs):
            payload = M.DecryptedMessage(
                hb=self.id, share_id=slot, payload=dec.to_bytes()
            )
            wire = self.router._native_send(payload)
            self._parsed[(slot, self.me)] = dec
            self._post(PO_HB_DECRYPTED, a=slot, data=wire.payload)
        self._post(PO_HB_ACS_DONE)

    # -- batcher protocol (XO_HB_QUEUE -> lazy build -> results cb) ----------
    def on_queue(self) -> None:
        self.router.crypto_batcher.submit_lazy(
            self._build_era_jobs_lazy, era=self.id.era
        )
        tracing.instant("hb.queue_decrypt", cat="crypto", era=self.id.era)

    def _refresh_cands(self) -> List[int]:
        """Pull the engine's ready slots + candidate shares; returns the
        ready slot list (ascending, the oracle's _ready_slots order)."""
        blob = self.router._net._rt_hb_export(
            self.router.my_id, era=self.id.era
        )
        ready = []
        off = 0
        end = len(blob)
        while off + 8 <= end:
            slot = int.from_bytes(blob[off : off + 4], "big")
            nsenders = int.from_bytes(blob[off + 4 : off + 8], "big")
            off += 8
            cands: Dict[int, bytes] = {}
            for _ in range(nsenders):
                sender = int.from_bytes(blob[off : off + 4], "big")
                ln = int.from_bytes(blob[off + 4 : off + 8], "big")
                off += 8
                cands[sender] = blob[off : off + ln]
                off += ln
            self._cands[slot] = cands
            ready.append(slot)
        return ready

    def _build_era_jobs_lazy(self):
        self._post(PO_HB_CLEAR_QUEUED)
        if self.done:
            return None
        return self._build_era_jobs()

    def _build_era_jobs(self):
        # honey_badger.py::_build_era_jobs, with the ready/candidate state
        # exported from the engine instead of self._shares
        from ..crypto import bls12381 as bls
        from ..crypto.tpu_backend import EraSlotJob

        need = self._pub.f + 1
        while True:
            ready = self._refresh_cands()
            if not ready:
                return None
            chosen_by_slot = {
                s: sorted(self._cands[s])[:need] for s in ready
            }
            wanted = [(s, i) for s in ready for i in chosen_by_slot[s]]
            if self._parse_shares(wanted) == 0:
                break
        jobs = []
        for slot in ready:
            ct = self._ciphertexts[slot]
            chosen = chosen_by_slot[slot]
            key = tuple(chosen)
            cs = self._lag_cache.get(key)
            if cs is None:
                cs = bls.fr_lagrange_coeffs([i + 1 for i in chosen], at=0)
                self._lag_cache[key] = cs
            lag_row = [0] * self.n
            u_row = [None] * self.n
            for i, c in zip(chosen, cs):
                lag_row[i] = c
                u_row[i] = self._parsed[(slot, i)].ui
            jobs.append(
                EraSlotJob(
                    u_by_validator=u_row,
                    lagrange_row=lag_row,
                    h=tpke.ciphertext_h(ct),
                    w=ct.w,
                )
            )
        for slot in ready:
            self._post(PO_HB_SET_INFLIGHT, a=slot)
        return (
            jobs,
            self._pub.tpke_verification_keys,
            lambda results, _ready=tuple(ready): self._era_results_cb(
                _ready, results
            ),
        )

    def _era_results_cb(self, ready, results) -> None:
        for slot in ready:
            self._post(PO_HB_CLEAR_INFLIGHT, a=slot)
        if self.done:
            return
        if results is None:
            for slot in ready:
                self._try_decrypt(slot)
        else:
            with tracing.span(
                "hb.apply_era_results",
                cat="crypto",
                era=self.id.era,
                slots=len(ready),
            ):
                for slot, (ok, combined) in zip(ready, results):
                    if ok:
                        self._resolve(
                            slot,
                            tpke.decrypt_with_combined(
                                self._ciphertexts[slot], combined
                            ),
                        )
                    else:
                        self._try_decrypt(slot)
        self._post(PO_HB_REQUEUE_CHECK)

    def _resolve(self, slot: int, plaintext: Optional[bytes]) -> None:
        self._plaintexts[slot] = plaintext
        self._post(PO_HB_RESOLVED, a=slot)

    def _parse_shares(self, wanted) -> int:
        # honey_badger.py::_parse_shares over the engine-candidate mirror;
        # failures prune BOTH sides (engine reject + local mirror)
        missing = [k for k in wanted if k not in self._parsed]
        if not missing:
            return 0
        from ..crypto import bls12381 as bls
        from ..crypto.provider import deserialize_batch_g1

        datas = [
            self._cands[slot][sender][: bls.G1_BYTES]
            for slot, sender in missing
        ]
        pts = deserialize_batch_g1(datas)
        failures = 0
        for (slot, sender), pt in zip(missing, pts):
            if pt is None:
                failures += 1
                del self._cands[slot][sender]
                self._post(PO_HB_REJECT, a=slot, b=sender)
                self._flag_invalid(sender, slot)
            else:
                self._parsed[(slot, sender)] = tpke.PartiallyDecryptedShare(
                    ui=pt, decryptor_id=sender, share_id=slot
                )
        return failures

    def _try_decrypt(self, slot: int) -> None:
        # honey_badger.py::_try_decrypt (host per-slot fallback path)
        if slot in self._plaintexts:
            return
        need = self._pub.f + 1
        slot_shares = self._cands.get(slot, {})
        if len(slot_shares) < need:
            return
        self._parse_shares([(slot, i) for i in sorted(slot_shares)])
        if len(slot_shares) < need:
            return  # parse failures shrank the candidate set
        ct = self._ciphertexts[slot]
        decryptors = sorted(slot_shares)
        decs = [self._parsed[(slot, i)] for i in decryptors]
        vks = [self._pub.tpke_verification_keys[i] for i in decryptors]
        oks = self._pub.tpke_pub.batch_verify_shares(vks, decs, ct)
        valid = [d for d, ok in zip(decs, oks) if ok]
        for d, ok in zip(decs, oks):
            if not ok:
                del slot_shares[d.decryptor_id]
                self._post(PO_HB_REJECT, a=slot, b=d.decryptor_id)
                self._flag_invalid(d.decryptor_id, slot)
        if len(valid) < need:
            return  # byzantine shares pruned; wait for more
        self._resolve(slot, self._pub.tpke_pub.full_decrypt(ct, valid))

    def _flag_invalid(self, sender: int, slot: int) -> None:
        # honey_badger.py::_flag_invalid mirror (same record coordinates)
        ev = getattr(self.router, "evidence", None)
        if ev is not None:
            ev.record_invalid_share(self.id.era, sender, "dec", (slot,))

    # -- completion (XO_HB_DONE) ----------------------------------------------
    def finish(self) -> dict:
        self.done = True
        self.result = {
            slot: pt
            for slot, pt in sorted(self._plaintexts.items())
            if pt is not None
        }
        return self.result


class RootHost:
    """Crypto half of a native RootProtocol (root_protocol.py oracle): tx
    batch assembly, header build + ECDSA sign/verify, block production."""

    def __init__(self, router, era: int, producer, ecdsa_priv, ecdsa_pubs):
        self.router = router
        self.id = M.RootProtocolId(era=era)
        self._producer = producer
        self._priv = ecdsa_priv
        self._pubs = ecdsa_pubs
        self._header = None
        self._header_hash = None
        self._txs = None
        self._signatures: Dict[int, bytes] = {}

    # XO_ROOT_INPUT — root_protocol.py::handle_input HB half (the engine
    # requests the nonce coin right after this crossing returns)
    def on_input(self) -> None:
        from ..core.block_producer import encode_tx_batch

        proposal = self._producer.get_transactions_to_propose()
        self.router.hb_host(self.id.era).handle_input(
            encode_tx_batch(proposal)
        )

    # XO_ROOT_SIGN — root_protocol.py::_try_sign_header
    def on_sign(self, parity: int) -> None:
        from ..core.block_producer import decode_tx_batch

        hb_result = self.router.hb_host(self.id.era).result or {}
        nonce = (self.id.era << 1) | (1 if parity else 0)
        seen = set()
        txs = []
        for slot in sorted(hb_result):
            try:
                batch = decode_tx_batch(hb_result[slot])
            except (ValueError, AssertionError):
                continue  # malformed proposal: skip the slot
            for stx in batch:
                h = stx.hash()
                if h not in seen:
                    seen.add(h)
                    txs.append(stx)
        self._txs = txs
        # tx lifecycle decide stamp — same point as the Python oracle's
        # _try_sign_header union (sampled-only, first stamp wins)
        from ..utils import txtrace

        txtrace.stamp_many(
            (stx.hash() for stx in txs), "decide", era=self.id.era
        )
        self._header = self._producer.create_header(self.id.era, txs, nonce)
        self._header_hash = self._header.hash()
        sig = ecdsa.sign_hash(self._priv, self._header_hash)
        payload = M.SignedHeaderMessage(
            root=self.id, header_bytes=self._header.encode(), signature=sig
        )
        wire = self.router._native_send(payload)
        self._signatures[self.router.my_id] = sig
        # two segments: the FRESH bytes drive header matching (the oracle
        # compares against self._header.encode()), the wire bytes — possibly
        # journal-substituted recorded bytes — are what actually broadcasts
        own = (
            len(payload.header_bytes).to_bytes(4, "big")
            + payload.header_bytes
            + payload.signature
        )
        bcast = (
            len(wire.header_bytes).to_bytes(4, "big")
            + wire.header_bytes
            + wire.signature
        )
        self.router._net._rt_post(
            self.router.my_id,
            PO_ROOT_HEADER,
            0,
            0,
            len(own).to_bytes(4, "big") + own + bcast,
            era=self.id.era,
        )

    # XO_ROOT_VERIFY — root_protocol.py::_on_signed_header signature checks
    def on_verify(self, blob: bytes) -> None:
        me = self.router.my_id
        era = self.id.era
        for sender, sig in iter_pairs(blob):
            if ecdsa.verify_hash(self._pubs[sender], self._header_hash, sig):
                self._signatures[sender] = sig
                self.router._net._rt_post(
                    me, PO_ROOT_ACCEPT, sender, 0, b"", era=era
                )
            else:
                self.router._net._rt_post(
                    me, PO_ROOT_REJECT, sender, 0, b"", era=era
                )
                # root_protocol.py::_on_signed_header ECDSA-reject mirror
                ev = getattr(self.router, "evidence", None)
                if ev is not None:
                    ev.record_invalid_share(era, sender, "hdr", ())

    # XO_ROOT_PRODUCE — root_protocol.py::_try_produce
    def on_produce(self):
        from ..core.types import MultiSig

        multisig = MultiSig(
            signatures=tuple(sorted(self._signatures.items()))
        )
        block = self._producer.produce_block(self._header, self._txs, multisig)
        self.router._native_results[self.id] = block
        # top-level completion: break the engine out of its chunk, exactly
        # like internal_response(to_id=None) does for Python protocols
        self.router._net._request_stop(era=self.id.era)
        return block


class RbcHost:
    """RS + Merkle half of the native ReliableBroadcast (version 7 boundary
    op). The engine keeps the full Bracha message state machine (VAL/ECHO/
    READY dedupe, thresholds, delivery) and crosses out only the codec work:
    XO_RBC_ENCODE for the sender-side shard fan-out, XO_RBC_NEED for the
    interpolate + re-encode + root-recheck verdict. Both run through the
    era RBC batcher (rbc_batcher.py) when one is wired on, so every
    validator's pending codec work in an era fuses into one batched matrix
    product — and the per-(root, k, n) verdict memo collapses the N
    in-process validators' identical interpolations into one."""

    def __init__(self, router, era: int):
        self.router = router
        self.era = era
        self.me = router.my_id
        self.n = router.n_validators
        self.f = router.f
        self.k = max(self.n - 2 * self.f, 1)

    @property
    def _batcher(self):
        return self.router.rbc_batcher

    # XO_RBC_ENCODE — reliable_broadcast.py::handle_input codec half
    def on_encode(self, slot: int, value: bytes) -> None:
        batcher = self._batcher
        if batcher is not None:
            batcher.submit_encode(
                self.era,
                value,
                self.k,
                self.n,
                lambda shards, _slot=slot: self._post_vals(_slot, shards),
            )
            return
        from ..ops import rs

        self._post_vals(slot, rs.encode(value, self.k, self.n))

    def _post_vals(self, slot: int, shards) -> None:
        from ..crypto import hashes

        leaves = hashes.keccak256_batch(shards)
        root = hashes.merkle_root(leaves)
        blob = bytearray(self.era.to_bytes(4, "big"))
        blob += root
        blob += self.n.to_bytes(4, "big")
        for i in range(self.n):
            branch = hashes.merkle_proof(leaves, i)
            blob += len(branch).to_bytes(4, "big")
            for h in branch:
                blob += len(h).to_bytes(4, "big")
                blob += h
            blob += len(shards[i]).to_bytes(4, "big")
            blob += shards[i]
        self.router._net._rt_post(
            self.me, PO_RBC_VALS, slot, 0, bytes(blob), era=self.era
        )

    # XO_RBC_NEED — reliable_broadcast.py::_try_interpolate codec half
    def on_need(self, slot: int, blob: bytes) -> None:
        root = blob[:32]
        full = [None] * self.n
        for idx, shard in iter_pairs(blob[32:]):
            if 0 <= idx < self.n:
                full[idx] = shard
        batcher = self._batcher
        if batcher is not None:
            batcher.submit_interpolate(
                self.era,
                full,
                self.k,
                self.n,
                root,
                lambda payload, _slot=slot, _root=root: self._post_result(
                    _slot, _root, payload
                ),
            )
            return
        from .rbc_batcher import scalar_verdict

        self._post_result(slot, root, scalar_verdict(full, self.k, root))

    def _post_result(self, slot: int, root: bytes, payload) -> None:
        ok = 1 if payload is not None else 0
        blob = self.era.to_bytes(4, "big") + root + (payload or b"")
        self.router._net._rt_post(
            self.me, PO_RBC_RESULT, slot, ok, blob, era=self.era
        )
