"""Validator attendance bookkeeping.

Parity with the reference's ValidatorAttendance
(/root/reference/src/Lachain.Consensus/ValidatorAttendance.cs:11-127):
per-cycle counts of blocks each validator co-signed, persisted so the
staking contract's attendance-detection phase can slash absentees. Tracks a
two-cycle window (previous + next) and rotates it on cycle advance.
"""
from __future__ import annotations

from typing import Dict

from ..utils.serialization import Reader, write_bytes, write_u32, write_u64


class ValidatorAttendance:
    def __init__(
        self,
        previous_cycle: int,
        previous: Dict[bytes, int] = None,
        next_: Dict[bytes, int] = None,
    ):
        self.previous_cycle = previous_cycle
        self.next_cycle = previous_cycle + 1
        self._previous: Dict[bytes, int] = dict(previous or {})
        self._next: Dict[bytes, int] = dict(next_ or {})

    def get(self, public_key: bytes, cycle: int) -> int:
        if cycle == self.previous_cycle:
            return self._previous.get(public_key, 0)
        if cycle == self.next_cycle:
            return self._next.get(public_key, 0)
        return 0

    def counts_for(self, cycle: int) -> Dict[bytes, int]:
        """All recorded per-validator counts for `cycle` — keyed by whoever
        actually co-signed, NOT by any particular era's validator set, so a
        rotated-out validator's attendance still reaches the detection
        report."""
        if cycle == self.previous_cycle:
            return dict(self._previous)
        if cycle == self.next_cycle:
            return dict(self._next)
        return {}

    def increment(self, public_key: bytes, cycle: int) -> None:
        if cycle == self.previous_cycle:
            self._previous[public_key] = self._previous.get(public_key, 0) + 1
        if cycle == self.next_cycle:
            self._next[public_key] = self._next.get(public_key, 0) + 1

    def to_bytes(self) -> bytes:
        out = write_u64(self.previous_cycle)
        out += write_u32(len(self._previous))
        for pk, count in self._previous.items():
            out += write_bytes(pk) + write_u64(count)
        out += write_u32(len(self._next))
        for pk, count in self._next.items():
            out += write_bytes(pk) + write_u64(count)
        return out

    @classmethod
    def from_bytes(
        cls, data: bytes, current_cycle: int, current_as_next: bool
    ) -> "ValidatorAttendance":
        """Deserialize, rotating the window to `current_cycle`
        (reference: ValidatorAttendance.FromBytes:82-119)."""
        r = Reader(data)
        previous_cycle = r.u64()
        previous = {r.bytes_(): r.u64() for _ in range(r.u32())}
        next_ = {r.bytes_(): r.u64() for _ in range(r.u32())}
        r.assert_eof()
        if previous_cycle == current_cycle:
            return cls(previous_cycle, previous, next_)
        if previous_cycle == current_cycle - 1 and not current_as_next:
            return cls(previous_cycle, previous, next_)
        if previous_cycle == current_cycle - 1 and current_as_next:
            return cls(current_cycle, next_, {})
        if previous_cycle == current_cycle - 2 and not current_as_next:
            return cls(previous_cycle + 1, next_, {})
        return cls(current_cycle, {}, {})

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, ValidatorAttendance)
            and self.previous_cycle == other.previous_cycle
            and self._previous == other._previous
        )
