"""Consensus message and protocol-identifier model.

Parity with the reference's protobuf `ConsensusMessage` oneof
(/root/reference/src/Lachain.Proto/consensus.proto:77-91) and the
`(Era, Agreement, Epoch)`-keyed protocol ids
(/root/reference/src/Lachain.Consensus/*Id.cs). We use frozen dataclasses +
the framework's fixed-width codec instead of protobuf: the wire format is
defined by this module, and every message is hashable/comparable so the
deterministic simulator can reorder and deduplicate them.

Envelope model (reference: Messages/MessageEnvelope.cs:5-35):
  * External : a validator-signed ConsensusMessage from the network.
  * Request  : parent protocol asks a child to start (ProtocolRequest.cs).
  * Result   : child protocol reports its output (ProtocolResult.cs).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, FrozenSet, Optional, Tuple

# ---------------------------------------------------------------------------
# Protocol identifiers
# ---------------------------------------------------------------------------


@dataclass(frozen=True, order=True)
class RootProtocolId:
    era: int


@dataclass(frozen=True, order=True)
class HoneyBadgerId:
    era: int


@dataclass(frozen=True, order=True)
class CommonSubsetId:
    era: int


@dataclass(frozen=True, order=True)
class ReliableBroadcastId:
    era: int
    sender_id: int  # the validator whose value is being broadcast


@dataclass(frozen=True, order=True)
class BinaryAgreementId:
    era: int
    agreement: int  # which ACS slot


@dataclass(frozen=True, order=True)
class BinaryBroadcastId:
    era: int
    agreement: int
    epoch: int


@dataclass(frozen=True, order=True)
class CoinId:
    era: int
    agreement: int
    epoch: int

    def to_bytes(self) -> bytes:
        from ..utils.serialization import write_i64

        return b"coin" + write_i64(self.era) + write_i64(self.agreement) + write_i64(self.epoch)


ProtocolId = Any  # union of the id dataclasses above


# ---------------------------------------------------------------------------
# External consensus payloads (the ConsensusMessage oneof)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ValMessage:
    """RBC VAL: sender ships shard i + Merkle branch to validator i
    (reference: ReliableBroadcast.ConstructValMessages)."""

    rbc: ReliableBroadcastId
    root: bytes
    branch: Tuple[bytes, ...]
    shard: bytes
    shard_index: int


@dataclass(frozen=True)
class EchoMessage:
    rbc: ReliableBroadcastId
    root: bytes
    branch: Tuple[bytes, ...]
    shard: bytes
    shard_index: int


@dataclass(frozen=True)
class ReadyMessage:
    rbc: ReliableBroadcastId
    root: bytes


@dataclass(frozen=True)
class BValMessage:
    bb: BinaryBroadcastId
    value: bool


@dataclass(frozen=True)
class AuxMessage:
    bb: BinaryBroadcastId
    value: bool


@dataclass(frozen=True)
class ConfMessage:
    bb: BinaryBroadcastId
    values: FrozenSet[bool]


@dataclass(frozen=True)
class CoinMessage:
    """A threshold-signature share of the coin id bytes."""

    coin: CoinId
    share: bytes  # serialized PartialSignature


@dataclass(frozen=True)
class DecryptedMessage:
    """A TPKE partially-decrypted share for one ACS slot
    (reference: HoneyBadger.CreateDecryptedMessage)."""

    hb: HoneyBadgerId
    share_id: int
    payload: bytes  # serialized PartiallyDecryptedShare


@dataclass(frozen=True)
class SignedHeaderMessage:
    root: RootProtocolId
    header_bytes: bytes
    signature: bytes  # ECDSA over header hash


ConsensusPayload = Any  # union of the payload dataclasses above


def payload_protocol_id(payload) -> ProtocolId:
    """Route an external payload to its protocol id
    (role of EraBroadcaster's message->id mapping, EraBroadcaster.cs:135-194)."""
    if isinstance(payload, (ValMessage, EchoMessage, ReadyMessage)):
        return payload.rbc
    if isinstance(payload, (BValMessage, AuxMessage, ConfMessage)):
        return payload.bb
    if isinstance(payload, CoinMessage):
        return payload.coin
    if isinstance(payload, DecryptedMessage):
        return payload.hb
    if isinstance(payload, SignedHeaderMessage):
        return payload.root
    raise TypeError(f"unroutable payload: {type(payload)}")


# ---------------------------------------------------------------------------
# Envelopes
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class External:
    """Validator `sender` (index into the era's validator set) sent `payload`."""

    sender: int
    payload: ConsensusPayload


@dataclass(frozen=True)
class Request:
    """Parent protocol `from_id` requests `to_id` to run with `input`."""

    from_id: Optional[ProtocolId]
    to_id: ProtocolId
    input: Any


@dataclass(frozen=True)
class Result:
    """Protocol `from_id` produced `value` (delivered to `to_id` parent)."""

    from_id: ProtocolId
    to_id: Optional[ProtocolId]
    value: Any


Envelope = Any  # External | Request | Result
