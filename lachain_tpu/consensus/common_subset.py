"""Asynchronous Common Subset (ACS): N reliable broadcasts + N binary
agreements.

Behavioral parity with
/root/reference/src/Lachain.Consensus/CommonSubset/CommonSubset.cs:
  * input fans out to my RBC slot; BAs vote on which RBCs completed (88-104)
  * once N-F BAs output 1, input 0 to all remaining BAs (134-155)
  * complete when ALL N BAs have output and every accepted slot's RBC value
    arrived; result = {slot: payload for slots with BA == 1} (157-188)
"""
from __future__ import annotations

from typing import Dict

from . import messages as M
from .protocol import Broadcaster, Protocol


class CommonSubset(Protocol):
    def __init__(self, pid: M.CommonSubsetId, broadcaster: Broadcaster):
        super().__init__(pid, broadcaster)
        self._rbc_results: Dict[int, bytes] = {}
        self._ba_results: Dict[int, bool] = {}
        self._ba_inputs: set = set()
        self._filled_zeros = False
        self._done = False

    def handle_input(self, value: bytes) -> None:
        # my own slot's RBC gets the payload; the others are participant-only
        for j in range(self.n):
            rbc = M.ReliableBroadcastId(era=self.id.era, sender_id=j)
            self.request(rbc, value if j == self.me else None)

    def handle_external(self, sender: int, payload) -> None:
        raise TypeError(f"unexpected payload {type(payload)}")

    def handle_child_result(self, child_id, value) -> None:
        if isinstance(child_id, M.ReliableBroadcastId):
            j = child_id.sender_id
            if j in self._rbc_results:
                return
            self._rbc_results[j] = value
            # RBC j delivered -> vote yes on slot j (unless already voted)
            self._vote(j, True)
        elif isinstance(child_id, M.BinaryAgreementId):
            j = child_id.agreement
            if j in self._ba_results:
                return
            self._ba_results[j] = bool(value)
            ones = sum(1 for v in self._ba_results.values() if v)
            if ones >= self.n - self.f and not self._filled_zeros:
                # enough slots accepted: refuse the stragglers
                self._filled_zeros = True
                for k in range(self.n):
                    if k not in self._ba_results:
                        self._vote(k, False)
        self._try_complete()

    def _vote(self, j: int, value: bool) -> None:
        if j in self._ba_inputs:
            return
        self._ba_inputs.add(j)
        ba = M.BinaryAgreementId(era=self.id.era, agreement=j)
        self.request(ba, value)

    def _try_complete(self) -> None:
        if self._done or len(self._ba_results) < self.n:
            return
        accepted = [j for j, v in self._ba_results.items() if v]
        if any(j not in self._rbc_results for j in accepted):
            return  # BA said yes but the RBC value hasn't arrived yet
        self._done = True
        self.emit_result({j: self._rbc_results[j] for j in sorted(accepted)})
