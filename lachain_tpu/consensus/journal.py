"""Durable consensus send journal: persist-before-transmit.

Crash-recovery BFT must persist what it sent BEFORE transmitting, or a
restarted validator can equivocate against its pre-crash self (Miller et
al. 2016 §4.2 operates under a crash-fault model for honest nodes; the
discipline is Raft's persist-before-respond rule applied to consensus
sends). The exposure is concrete: BA AUX/CONF values and the signed block
header depend on message ARRIVAL ORDER, so a mid-era restart that re-runs
the era from scratch can legitimately derive a DIFFERENT value for a slot
it already voted on — and two signed values for one slot is Byzantine
behavior that honest peers will use against us.

This journal records every outbound consensus payload (era, target, wire
bytes) under the ``EntryPrefix.CONSENSUS_STATE`` keyspace, written through
the KV's batched fsynced path before the payload reaches the transport.
On restart the node replays it to:

  * re-arm the era router's "already sent" latches — when the re-run era
    reaches the same decision point again, the RECORDED bytes are re-sent,
    byte-identical, never a re-derived value;
  * re-seed the PR-2 retransmission outbox, so peers' ``message_request``s
    are served across the restart;
  * discover which eras were in flight, to rejoin them via
    ``message_request``.

Entries are pruned with the protocol GC (EraRouter.advance_era): an era
settled on-chain no longer needs its sends — recovery for peers is block
sync, not replay.

Key layout: ``CONSENSUS_STATE | era u64 | seq u64`` ->
``i64(target, -1 = broadcast) | bytes(payload wire bytes)``.
"""
from __future__ import annotations

from typing import Dict, Iterator, Optional, Tuple

from ..storage.kv import EntryPrefix, KVStore, prefixed
from ..utils import metrics
from ..utils.serialization import Reader, write_bytes, write_i64, write_u64

from . import messages as M

_PREFIX = prefixed(EntryPrefix.CONSENSUS_STATE)


def send_slot(payload) -> Optional[tuple]:
    """The per-era decision slot a payload occupies — the unit of
    "already sent": one durable value per slot, re-sends must be
    byte-identical. The slot key identifies the decision point, NOT the
    value, except where the protocol legitimately sends both values
    (BVAL: a node may broadcast BVAL(0) and BVAL(1) in one epoch after
    seeing f+1 of the other — that is not equivocation, so the value is
    part of the slot). Returns None for unlatchable payloads (journaled,
    never substituted)."""
    if isinstance(payload, M.ValMessage):
        # one VAL per recipient shard (the sender's proposal commitment)
        return ("val", payload.rbc, payload.shard_index)
    if isinstance(payload, M.EchoMessage):
        return ("echo", payload.rbc)
    if isinstance(payload, M.ReadyMessage):
        return ("ready", payload.rbc)
    if isinstance(payload, M.BValMessage):
        return ("bval", payload.bb, payload.value)
    if isinstance(payload, M.AuxMessage):
        return ("aux", payload.bb)
    if isinstance(payload, M.ConfMessage):
        return ("conf", payload.bb)
    if isinstance(payload, M.CoinMessage):
        return ("coin", payload.coin)
    if isinstance(payload, M.DecryptedMessage):
        return ("dec", payload.hb, payload.share_id)
    if isinstance(payload, M.SignedHeaderMessage):
        # the big one: two signed headers for one era is classic equivocation
        return ("hdr", payload.root)
    return None


class ConsensusJournal:
    """Append-only send journal over the node's KV store.

    Writes ride ``write_batch`` — the KV's fsynced path — so a record is
    durable before the send it covers leaves the node. Sequence numbers
    are per-era and continue across restarts (seeded from a prefix scan at
    construction), so replayed entries keep their original send order.
    """

    def __init__(self, kv: KVStore):
        self._kv = kv
        self._next_seq: Dict[int, int] = {}
        for era, seq, _target, _data in self.entries():
            if seq >= self._next_seq.get(era, 0):
                self._next_seq[era] = seq + 1

    def record(self, era: int, target: Optional[int], payload_bytes: bytes) -> None:
        """Durably append one send BEFORE it is transmitted."""
        seq = self._next_seq.get(era, 0)
        key = _PREFIX + write_u64(era) + write_u64(seq)
        value = write_i64(-1 if target is None else target) + write_bytes(
            payload_bytes
        )
        self._kv.write_batch([(key, value)])
        self._next_seq[era] = seq + 1
        metrics.inc("consensus_journal_records_total")

    def entries(self) -> Iterator[Tuple[int, int, Optional[int], bytes]]:
        """Yield (era, seq, target, payload_bytes) in (era, seq) order.
        Undecodable values are skipped (reported by fsck, repaired there)."""
        for key, value in self._kv.scan_prefix(_PREFIX):
            tail = key[len(_PREFIX):]
            if len(tail) != 16:
                continue
            era = int.from_bytes(tail[:8], "big")
            seq = int.from_bytes(tail[8:], "big")
            try:
                r = Reader(value)
                target = r.i64()
                data = r.bytes_()
            except Exception:
                continue
            yield era, seq, (None if target < 0 else target), data

    def eras(self) -> list:
        """Distinct eras with journaled sends, ascending."""
        out = set()
        for era, _seq, _target, _data in self.entries():
            out.add(era)
        return sorted(out)

    def prune_below(self, era_cutoff: int) -> int:
        """Drop entries for eras < `era_cutoff` (the protocol-GC retention:
        settled eras recover by block sync, not replay). One batched
        delete; returns the number of entries dropped."""
        doomed = [
            key
            for key, _ in self._kv.scan_prefix(_PREFIX)
            if len(key) == len(_PREFIX) + 16
            and int.from_bytes(key[len(_PREFIX):len(_PREFIX) + 8], "big")
            < era_cutoff
        ]
        if doomed:
            self._kv.write_batch([], doomed)
            for era in [
                e for e in self._next_seq if e < era_cutoff
            ]:
                del self._next_seq[era]
            metrics.inc("consensus_journal_pruned_total", len(doomed))
        return len(doomed)
