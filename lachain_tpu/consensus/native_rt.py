"""ctypes binding for the native consensus engine (libconsensus_rt).

`NativeSimulatedNetwork` is a drop-in for `simulator.SimulatedNetwork`: the
delivery queue and ALL seven consensus protocols run inside the C++ engine
(native/consensus_rt.cpp). The flood protocols (BinaryBroadcast,
BinaryAgreement, ReliableBroadcast, CommonSubset) are hosted wholesale; the
crypto-bearing protocols (CommonCoin, HoneyBadger, RootProtocol) are split —
the engine owns their MESSAGE state machines while Python host shims
(native_hosts.py) own every cryptographic operation, reached through BATCHED
boundary crossings instead of one Python round-trip per message. The Python
protocol classes remain the pinned cryptographic oracle: a TAKE_FIRST run is
bit-identical across engines (tests/test_native_rt.py).

A validator whose `_extra_factories` overrides one of the crypto protocols
(the malicious-subclass test pattern, or forcing the Python engines for
debugging) keeps that protocol in Python: its ownership bit stays clear and
its opaque messages keep flowing through the legacy per-message callback.

Reference roles covered: AbstractProtocol's thread+queue runtime
(/root/reference/src/Lachain.Consensus/AbstractProtocol.cs:11-168) and the
test DeliveryService (test/Lachain.ConsensusTest/DeliverySerivce.cs:10-124).
"""
from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import weakref
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Set

from ..utils import metrics, tracing
from . import messages as M
from .era import EraRouter
from .keys import PrivateConsensusKeys, PublicConsensusKeys
from .native_hosts import (
    RQ_COIN,
    RQ_HB,
    RQ_ROOT,
    XO_COIN_COMBINE,
    XO_COIN_RESULT,
    XO_COIN_SIGN,
    XO_EVIDENCE,
    XO_HB_ACS,
    XO_HB_DONE,
    XO_HB_QUEUE,
    XO_NAMES,
    XO_RBC_ENCODE,
    XO_RBC_NEED,
    XO_ROOT_INPUT,
    XO_ROOT_PRODUCE,
    XO_ROOT_SIGN,
    XO_ROOT_VERIFY,
    CoinHost,
    HoneyBadgerHost,
    RbcHost,
    RootHost,
)
from .simulator import DeliveryMode

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libconsensus_rt.so")

# opaque payload kinds (shared contract with consensus_rt.cpp MT_OPAQUE)
KIND_DECRYPTED = 0
KIND_SIGNED_HEADER = 1
KIND_COIN = 2

# per-validator native-ownership mask (consensus_rt.cpp enum OwnMask)
OWN_HB = 1
OWN_COIN = 2
OWN_ROOT = 4

# labeled counter of every engine->Python boundary crossing; op
# "opaque_message" is the legacy per-message callback the batched ops replace
CROSSINGS_METRIC = "consensus_callback_crossings_total"

_OPAQUE_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_int32,  # target
    ctypes.c_int32,  # sender
    ctypes.c_int32,  # era
    ctypes.c_int32,  # kind
    ctypes.c_int32,  # agreement
    ctypes.c_int32,  # epoch
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_size_t,
)
_ACS_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_int32,  # target
    ctypes.c_int32,  # era
    ctypes.c_int32,  # nslots
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
    ctypes.POINTER(ctypes.c_size_t),
)
_COINREQ_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32
)
_CROSS_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_int32,  # target
    ctypes.c_int32,  # era
    ctypes.c_int32,  # op (XO_*)
    ctypes.c_int32,  # a
    ctypes.c_int32,  # b
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_size_t,
)

_lib_cache: List[Any] = [None]


def load_rt():
    if _lib_cache[0] is not None:
        return _lib_cache[0]
    # LACHAIN_CONSENSUS_LIB loads an alternate engine build verbatim (the
    # ASan/TSan gates in tests/native/ point it at instrumented builds) —
    # no mtime-rebuild, same contract as LACHAIN_LSM_LIB in storage/lsm.py
    override = os.environ.get("LACHAIN_CONSENSUS_LIB")
    lib_path = override or _LIB_PATH
    if not override:
        sources = [
            os.path.join(_NATIVE_DIR, "consensus_rt.cpp"),
            os.path.join(_NATIVE_DIR, "Makefile"),
        ]
        if not os.path.exists(_LIB_PATH) or any(
            os.path.getmtime(_LIB_PATH) < os.path.getmtime(s) for s in sources
        ):
            subprocess.run(
                ["make", "-s", "-C", _NATIVE_DIR], check=True,
                capture_output=True,
            )
    lib = ctypes.CDLL(lib_path)
    lib.lt_crt_version.restype = ctypes.c_int
    _crt_ver = lib.lt_crt_version()
    assert _crt_ver in (6, 7), _crt_ver
    lib.rt_new.restype = ctypes.c_void_p
    lib.rt_new.argtypes = [
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_uint32,
        ctypes.c_uint64,
        ctypes.c_int,
    ]
    lib.rt_free.argtypes = [ctypes.c_void_p]
    lib.rt_set_callbacks.argtypes = [
        ctypes.c_void_p,
        _OPAQUE_CB,
        _ACS_CB,
        _COINREQ_CB,
        _CROSS_CB,
    ]
    lib.rt_set_owned.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.rt_set_coin_need.argtypes = [ctypes.c_void_p, ctypes.c_int]
    # version 7 added the batched RBC boundary (XO_RBC_ENCODE/NEED). Probe it
    # so a stale .so built from older sources degrades to the engine's
    # per-message RBC path instead of crashing (keccak_batch-style fallback).
    lib._lt_has_rbc_host = _crt_ver >= 7 and hasattr(lib, "rt_set_rbc_host")
    if lib._lt_has_rbc_host:
        lib.rt_set_rbc_host.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rt_request.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.rt_post.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.rt_hb_ready_export.restype = ctypes.c_size_t
    lib.rt_hb_ready_export.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.rt_native_handled.restype = ctypes.c_uint64
    lib.rt_native_handled.argtypes = [ctypes.c_void_p]
    lib.rt_debug_state.restype = ctypes.c_size_t
    lib.rt_debug_state.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.rt_mute.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rt_advance_era.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.rt_post_acs_input.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.rt_post_coin_result.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.rt_broadcast_opaque.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.rt_send_opaque.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.rt_run.restype = ctypes.c_size_t
    lib.rt_run.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.rt_request_stop.argtypes = [ctypes.c_void_p]
    lib.rt_opaque_pending.restype = ctypes.c_uint64
    lib.rt_opaque_pending.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rt_queue_len.restype = ctypes.c_size_t
    lib.rt_queue_len.argtypes = [ctypes.c_void_p]
    lib.rt_delivered.restype = ctypes.c_uint64
    lib.rt_delivered.argtypes = [ctypes.c_void_p]
    lib.rt_monotonic_ns.restype = ctypes.c_uint64
    lib.rt_monotonic_ns.argtypes = []
    lib.rt_trace_configure.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.rt_trace_dropped.restype = ctypes.c_uint64
    lib.rt_trace_dropped.argtypes = [ctypes.c_void_p]
    lib.rt_trace_drain.restype = ctypes.c_size_t
    lib.rt_trace_drain.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_size_t,
    ]
    _lib_cache[0] = lib
    return lib


# -- flight recorder ---------------------------------------------------------

# consensus_rt.cpp trace record contract: 32-byte big-endian records
_TRACE_RECORD = struct.Struct(">QQIIII")
TK_ERA_ADVANCE, TK_CROSS, TK_POST, TK_STAGE, TK_PHASE, TK_WAIT = 1, 2, 3, 4, 5, 6
# TP_* dispatch-phase buckets -> era-report phase keys (tracing._DISPATCH_PHASE)
TP_NAMES = {1: "rbc", 2: "ba", 3: "coin", 4: "tpke", 5: "commit", 6: "other"}
# WR_* wait resources (TK_WAIT.a) -> era-report wait buckets (tracing.WAIT_RESOURCES)
WR_NAMES = {1: "net", 2: "crypto_flush", 3: "device", 4: "fsync", 5: "sched"}
# the coarse PO_* ops the engine records (native_post keeps per-slot ops out)
_PO_TRACE_NAMES = {2: "coin_result", 3: "hb_acs_input", 5: "hb_acs_done",
                   12: "root_header"}
_TS_NAMES = {1: "acs_result"}
TRACE_PID_CONSENSUS = 2  # Chrome-export process lane (python host is pid 1)


# clock-offset handshake shared with the LSM binding
clock_offset = tracing.clock_offset


def decode_consensus_trace(
    raw: bytes, offset: float, source: str = "consensus"
) -> List[dict]:
    """Raw drain buffer -> merged-tracer event dicts (see
    tracing.register_native_source for the schema)."""
    evs: List[dict] = []
    for i in range(0, len(raw) - (len(raw) % 32), 32):
        ts, dur, kind, tid, a, b = _TRACE_RECORD.unpack_from(raw, i)
        start = ts / 1e9 + offset
        end = (ts + dur) / 1e9 + offset
        common = dict(
            start=start,
            end=end,
            pid=TRACE_PID_CONSENSUS,
            pname="native-consensus",
        )
        if kind == TK_CROSS:
            op = XO_NAMES.get(a, str(a))
            evs.append(
                dict(
                    common,
                    name=f"cross:{op}",
                    cat="native.cross",
                    tid=tid,
                    tname=f"validator-{tid}",
                    args={"op": op, "era": b, "vid": tid},
                )
            )
        elif kind == TK_PHASE:
            phase = TP_NAMES.get(a, str(a))
            evs.append(
                dict(
                    common,
                    name=f"dispatch:{phase}",
                    cat="native.phase",
                    tid=0,
                    tname="dispatch",
                    # cumulative per-(era,phase) totals: latest wins
                    replace_key=(source, b, a),
                    args={"phase": phase, "era": b, "dur_ns": dur},
                )
            )
        elif kind == TK_ERA_ADVANCE:
            evs.append(
                dict(
                    common,
                    name="era_advance",
                    cat="native.consensus",
                    tid=tid,
                    tname=f"validator-{tid}",
                    args={"vid": tid, "new_era": a, "old_era": b},
                )
            )
        elif kind == TK_POST:
            op = _PO_TRACE_NAMES.get(a, str(a))
            evs.append(
                dict(
                    common,
                    name=f"post:{op}",
                    cat="native.consensus",
                    tid=tid,
                    tname=f"validator-{tid}",
                    args={"op": op, "era": b, "vid": tid},
                )
            )
        elif kind == TK_STAGE:
            evs.append(
                dict(
                    common,
                    name=f"stage:{_TS_NAMES.get(a, str(a))}",
                    cat="native.consensus",
                    tid=tid,
                    tname=f"validator-{tid}",
                    args={"stage": a, "era": b, "vid": tid},
                )
            )
        elif kind == TK_WAIT:
            res = WR_NAMES.get(a, str(a))
            evs.append(
                dict(
                    common,
                    name=f"wait:{res}",
                    cat="native.wait",
                    tid=0,
                    tname="dispatch",
                    args={"resource": res, "era": b},
                )
            )
            metrics.observe_hist(
                "wait_seconds", dur / 1e9, labels={"resource": res}
            )
    return evs


@dataclass(frozen=True)
class NativeCoinParent:
    """Result address for a PYTHON CommonCoin requested by a native
    BinaryAgreement (the coin ownership bit is clear — override factory):
    the Python coin's emit_result routes back into the engine."""

    agreement: int
    epoch: int
    era: int = 0  # routes the result to the right per-era engine


class _EraHosts:
    """Per-era container for the native-protocol host shims of one router."""

    __slots__ = ("coins", "hb", "root", "rbc", "py_parents")

    def __init__(self):
        self.coins: Dict[tuple, CoinHost] = {}
        self.hb: Optional[HoneyBadgerHost] = None
        self.root: Optional[RootHost] = None
        self.rbc: Optional[RbcHost] = None
        # parent protocol ids of PYTHON protocols awaiting a native result
        self.py_parents: Dict[Any, Any] = {}


class NativeEraRouter(EraRouter):
    """EraRouter whose protocols live in the native engine.

    Flood protocols are engine-only. Crypto-bearing protocols are
    engine-hosted with Python crypto shims (native_hosts.py) unless an
    `_extra_factories` override forces the Python class — then requests and
    messages route exactly as in EraRouter, crossing the engine as opaque
    payloads via the legacy per-message callbacks.
    """

    def __init__(
        self,
        era: int,
        my_id: int,
        public_keys: PublicConsensusKeys,
        private_keys: PrivateConsensusKeys,
        net: "NativeSimulatedNetwork",
        extra_factories=None,
        journal=None,
        evidence=None,
    ):
        def _no_send(target, payload):  # pragma: no cover
            raise RuntimeError("native router transports via the engine")

        super().__init__(
            era,
            my_id,
            public_keys,
            private_keys,
            send=_no_send,
            extra_factories=extra_factories,
            journal=journal,
            evidence=evidence,
        )
        self._net = net
        self._acs_parent: Any = None
        self.crypto_batcher = None  # set by the network when batching is on
        self.rbc_batcher = None  # set by the network when RBC batching is on
        self._root_ctx = None  # (producer, ecdsa_priv, ecdsa_pubs)
        self._era_hosts: Dict[int, _EraHosts] = {}
        self._native_results: Dict[Any, Any] = {}

    # -- native ownership ------------------------------------------------------
    def _native_mask(self) -> int:
        """Which crypto protocols THIS validator hosts natively. Computed
        lazily (tests install override factories after construction) and
        synced to the engine before any request enters it."""
        mask = 0
        if M.CoinId not in self._extra_factories:
            mask |= OWN_COIN
        if (
            M.HoneyBadgerId not in self._extra_factories
            and self.crypto_batcher is not None
            and self._net._era_fn_available()
        ):
            mask |= OWN_HB
        # native Root drives native HB + the native nonce coin; a validator
        # running either of those in Python must run Root in Python too
        if (
            self._root_ctx is not None
            and M.RootProtocolId not in self._extra_factories
            and (mask & OWN_HB)
            and (mask & OWN_COIN)
        ):
            mask |= OWN_ROOT
        return mask

    # -- host shims ------------------------------------------------------------
    def _hosts(self, era: int) -> _EraHosts:
        hs = self._era_hosts.get(era)
        if hs is None:
            hs = self._era_hosts[era] = _EraHosts()
        return hs

    def hb_host(self, era: int) -> HoneyBadgerHost:
        hs = self._hosts(era)
        if hs.hb is None:
            hs.hb = HoneyBadgerHost(self, era)
        return hs.hb

    def coin_host(self, era: int, agreement: int, epoch: int) -> CoinHost:
        hs = self._hosts(era)
        key = (agreement, epoch)
        host = hs.coins.get(key)
        if host is None:
            cid = M.CoinId(era=era, agreement=agreement, epoch=epoch)
            host = hs.coins[key] = CoinHost(self, cid)
        return host

    def rbc_host(self, era: int) -> RbcHost:
        hs = self._hosts(era)
        if hs.rbc is None:
            hs.rbc = RbcHost(self, era)
        return hs.rbc

    def root_host(self, era: int) -> RootHost:
        hs = self._hosts(era)
        if hs.root is None:
            producer, priv, pubs = self._root_ctx
            hs.root = RootHost(self, era, producer, priv, pubs)
        return hs.root

    def _native_send(self, payload):
        """Journal-aware emission half of EraRouter.broadcast for payloads
        whose message state machine lives in the engine: durable-record
        (possibly substituting previously recorded wire bytes — the
        no-self-equivocation latch) + outbox, WITHOUT the transport send; the
        caller hands the returned wire payload to the engine, which owns
        delivery."""
        payload = self._durable_send(None, payload)
        self._record_outbox(None, payload)
        return payload

    # -- outbound: divert into the engine -------------------------------------
    def internal_request(self, req: M.Request) -> None:
        to = req.to_id
        if isinstance(to, M.CommonSubsetId):
            self._acs_parent = req.from_id
            self._net._post_acs_input(self._my_id, req.input, era=to.era)
            return
        if isinstance(
            to,
            (M.BinaryAgreementId, M.BinaryBroadcastId, M.ReliableBroadcastId),
        ):
            raise RuntimeError(f"natively-owned protocol requested: {to}")
        to_era = getattr(to, "era", None)
        if to_era is not None and self.window_floor <= to_era <= self.era:
            mask = self._native_mask()
            if isinstance(to, M.RootProtocolId) and (mask & OWN_ROOT):
                self._net._sync_owner(self._my_id)
                self._net._rt_request(self._my_id, RQ_ROOT, 0, 0, era=to_era)
                return
            if isinstance(to, M.HoneyBadgerId) and (mask & OWN_HB):
                self._net._sync_owner(self._my_id)
                self._hosts(to.era).py_parents["hb"] = req.from_id
                self._net._rt_request(self._my_id, RQ_HB, 0, 0, era=to_era)
                if to in self._native_results:
                    return  # done-replay: the result was re-routed already
                self.hb_host(to.era).handle_input(req.input)
                return
            if isinstance(to, M.CoinId) and (mask & OWN_COIN):
                self._net._sync_owner(self._my_id)
                self._hosts(to.era).py_parents[
                    ("coin", to.agreement, to.epoch)
                ] = req.from_id
                self._net._rt_request(
                    self._my_id, RQ_COIN, to.agreement, to.epoch, era=to_era
                )
                return
        super().internal_request(req)

    def internal_response(self, res: M.Result) -> None:
        if isinstance(res.to_id, NativeCoinParent):
            self._net._post_coin_result(
                self._my_id,
                res.to_id.agreement,
                res.to_id.epoch,
                res.value,
                era=res.to_id.era,
            )
            return
        if res.to_id is None:
            # top-level protocol completed (e.g. Root produced its block):
            # break the engine out of its chunk so the driver can re-check
            # done() promptly — mirrors the Python simulator's per-message
            # done() check and keeps lag-round coin work off the hot path
            self._net._request_stop(era=getattr(res.from_id, "era", None))
            return
        super().internal_response(res)

    def broadcast(self, payload) -> None:
        # python-side protocol emission: durable-record + outbox exactly as
        # EraRouter.broadcast, then transport through the engine
        payload = self._native_send(payload)
        self._engine_transport(payload)

    def _engine_transport(self, payload) -> None:
        """Hand one host-shim payload to the engine for delivery (the
        transport half of broadcast — no journaling, no outbox record)."""
        if isinstance(payload, M.DecryptedMessage):
            self._net._bcast_opaque(
                self._my_id,
                KIND_DECRYPTED,
                payload.share_id,
                0,
                payload.payload,
                era=payload.hb.era,
            )
        elif isinstance(payload, M.SignedHeaderMessage):
            data = (
                len(payload.header_bytes).to_bytes(4, "big")
                + payload.header_bytes
                + payload.signature
            )
            self._net._bcast_opaque(
                self._my_id, KIND_SIGNED_HEADER, 0, 0, data, era=payload.root.era
            )
        elif isinstance(payload, M.CoinMessage):
            self._net._bcast_opaque(
                self._my_id,
                KIND_COIN,
                payload.coin.agreement,
                payload.coin.epoch,
                payload.share,
                era=payload.coin.era,
            )
        else:
            raise TypeError(f"unexpected python-protocol payload {type(payload)}")

    def replay_outbox(
        self, era: int, requester: int, limit: Optional[int] = None
    ) -> int:
        """Retransmission service over the engine transport. The engine only
        floods (its receive paths are idempotent — repeated shares are
        dropped by the per-sender latches), so a targeted replay request is
        answered with a re-broadcast of the recorded payloads. The engine
        runs the router's current era only; older eras' flood traffic is
        engine-internal and already superseded by the decided block.
        `limit` caps the batch, same contract as EraRouter.replay_outbox."""
        if not (self.window_floor <= era <= self.era):
            return 0
        payloads = self.outbox_payloads(era, requester)
        if limit is not None:
            payloads = payloads[:limit]
        for payload in payloads:
            self._engine_transport(payload)
        if payloads:
            from ..utils import metrics

            metrics.inc("consensus_outbox_replayed_total", len(payloads))
        return len(payloads)

    def send_to(self, validator: int, payload) -> None:
        raise TypeError("python-side protocols only broadcast")

    def _create(self, pid):
        if isinstance(
            pid,
            (
                M.BinaryBroadcastId,
                M.BinaryAgreementId,
                M.ReliableBroadcastId,
                M.CommonSubsetId,
            ),
        ):
            raise RuntimeError(f"natively-owned protocol id {pid}")
        if (
            isinstance(pid, M.RootProtocolId)
            and type(pid) not in self._extra_factories
            and self._root_ctx is not None
        ):
            # Root context was given natively (set_root_context) but this
            # validator cannot own Root (an HB/Coin override forced Python):
            # fall back to the Python RootProtocol built from the same context
            from .root_protocol import RootProtocol

            producer, priv, pubs = self._root_ctx
            return RootProtocol(
                pid, self, producer=producer, ecdsa_priv=priv, ecdsa_pubs=pubs
            )
        return super()._create(pid)

    def result_of(self, pid) -> Any:
        if pid in self._native_results:
            return self._native_results[pid]
        return super().result_of(pid)

    def native_state(self) -> str:
        """Engine-side state of this validator's natively-owned protocols
        (for watchdog stall reports)."""
        return self._net.native_state_of(self._my_id)

    def advance_era(self, new_era: int) -> None:
        if new_era <= self.era:
            return
        old_era = self.era
        super().advance_era(new_era)
        # host shims and native results follow the same retention as
        # protocol instances: keep the last active era, drop older
        cutoff = min(new_era - 1, old_era)
        self._prune_native_state(cutoff)
        self._net._advance_era(self._my_id, new_era)

    def commit_era_gc(self, committed_era: int) -> None:
        super().commit_era_gc(committed_era)
        self._prune_native_state(
            committed_era + 1 - max(self.pipeline_window, 1)
        )

    def _prune_native_state(self, cutoff: int) -> None:
        for e in [e for e in self._era_hosts if e < cutoff]:
            del self._era_hosts[e]
        for pid in [
            p
            for p in self._native_results
            if getattr(p, "era", cutoff) < cutoff
        ]:
            del self._native_results[pid]

    # -- engine callbacks (legacy per-message path) ----------------------------
    def _on_opaque(
        self, sender: int, era: int, kind: int, agreement: int, epoch: int, data: bytes
    ) -> None:
        if kind == KIND_DECRYPTED:
            payload = M.DecryptedMessage(
                hb=M.HoneyBadgerId(era=era), share_id=agreement, payload=data
            )
        elif kind == KIND_SIGNED_HEADER:
            hlen = int.from_bytes(data[:4], "big")
            payload = M.SignedHeaderMessage(
                root=M.RootProtocolId(era=era),
                header_bytes=data[4 : 4 + hlen],
                signature=data[4 + hlen :],
            )
        elif kind == KIND_COIN:
            payload = M.CoinMessage(
                coin=M.CoinId(era=era, agreement=agreement, epoch=epoch),
                share=data,
            )
        else:  # unknown kind: drop (forward-compat)
            return
        self.dispatch_external(sender, payload)

    def _on_acs_result(self, era: int, result: Dict[int, bytes]) -> None:
        self.internal_response(
            M.Result(
                from_id=M.CommonSubsetId(era=era),
                to_id=self._acs_parent,
                value=result,
            )
        )

    def _on_coin_request(self, era: int, agreement: int, epoch: int) -> None:
        cid = M.CoinId(era=era, agreement=agreement, epoch=epoch)
        super().internal_request(
            M.Request(
                from_id=NativeCoinParent(
                    agreement=agreement, epoch=epoch, era=era
                ),
                to_id=cid,
                input=None,
            )
        )

    # -- engine callbacks (batched crossing path) ------------------------------
    def _on_cross(self, era: int, op: int, a: int, b: int, blob: bytes) -> None:
        if op == XO_COIN_SIGN:
            self.coin_host(era, a, b).sign()
        elif op == XO_COIN_COMBINE:
            self.coin_host(era, a, b).combine(blob)
        elif op == XO_COIN_RESULT:
            # native coin completed for a PYTHON parent (or a direct request)
            value = bool(blob[0]) if blob else False
            cid = M.CoinId(era=era, agreement=a, epoch=b)
            self._native_results[cid] = value
            parent = self._hosts(era).py_parents.pop(("coin", a, b), None)
            if parent is None:
                self._net._request_stop()
            else:
                super().internal_response(
                    M.Result(from_id=cid, to_id=parent, value=value)
                )
        elif op == XO_HB_ACS:
            self.hb_host(era).on_acs(blob)
        elif op == XO_HB_QUEUE:
            self.hb_host(era).on_queue()
        elif op == XO_HB_DONE:
            result = self.hb_host(era).finish()
            hbid = M.HoneyBadgerId(era=era)
            self._native_results[hbid] = result
            if a:  # parent is Python-side (or a direct top-level request)
                parent = self._hosts(era).py_parents.pop("hb", None)
                if parent is None:
                    self._net._request_stop()
                else:
                    super().internal_response(
                        M.Result(from_id=hbid, to_id=parent, value=result)
                    )
        elif op == XO_RBC_ENCODE:
            self.rbc_host(era).on_encode(a, blob)
        elif op == XO_RBC_NEED:
            self.rbc_host(era).on_need(a, blob)
        elif op == XO_ROOT_INPUT:
            self.root_host(era).on_input()
        elif op == XO_ROOT_SIGN:
            # pipelined window: the sign point is the front/tail boundary —
            # the scheduler stashes the coin parity here and resumes the
            # sign on the tail lane once the parent block has committed
            if self._net._defer_sign(self._my_id, era, a):
                return
            self.root_host(era).on_sign(a)
        elif op == XO_ROOT_VERIFY:
            self.root_host(era).on_verify(blob)
        elif op == XO_ROOT_PRODUCE:
            self.root_host(era).on_produce()
        elif op == XO_EVIDENCE:
            # engine equivocation latch tripped: a=offender b=opq_kind,
            # blob = be32(agreement) + be32(epoch). Build the exact record
            # era.py::_latch_first_seen would (evidence-set identity between
            # engines is pinned by tests)
            agreement = int.from_bytes(blob[0:4], "big", signed=True)
            epoch = int.from_bytes(blob[4:8], "big", signed=True)
            if b == KIND_DECRYPTED:
                proto, index = "dec", (agreement,)
            elif b == KIND_COIN:
                proto, index = "coin", (agreement, epoch)
            else:
                proto, index = "hdr", ()
            self.evidence.record_equivocation(era, a, proto, index)
        else:  # unknown op: refuse loudly — a silent drop would stall
            raise RuntimeError(f"unknown native crossing op {op}")


class NativeSimulatedNetwork:
    """Drop-in for simulator.SimulatedNetwork backed by the C++ engine."""

    def __init__(
        self,
        public_keys: PublicConsensusKeys,
        private_keys: List[PrivateConsensusKeys],
        era: int = 0,
        seed: int = 0,
        mode: DeliveryMode = DeliveryMode.TAKE_FIRST,
        repeat_probability: float = 0.0,
        muted: Optional[Set[int]] = None,
        extra_factories=None,
        use_crypto_batcher: bool = True,
        use_rbc_batcher: bool = False,
        fault_plan=None,
        journals: Optional[List] = None,
        pipeline_window: int = 0,
    ):
        self.n = public_keys.n
        self.muted = set(muted or set())
        self.fault_plan = fault_plan
        if fault_plan is not None:
            # one FaultPlan, three delivery layers: here the plan maps onto
            # the engine's own fault knobs — duplication -> repeat_ppm,
            # reordering -> TAKE_RANDOM delivery, a crash that never
            # restarts -> a muted player. Features the engine cannot express
            # (probabilistic drop, delay, partitions, mid-era restart) are
            # refused loudly rather than silently weakened: a chaos run that
            # *looks* like it injected loss but didn't would certify a
            # recovery path that was never exercised.
            unsupported = []
            if fault_plan.drop > 0:
                unsupported.append("drop")
            if fault_plan.delay > 0:
                unsupported.append("delay")
            if fault_plan.partitions:
                unsupported.append("partitions")
            if any(c.restart is not None for c in fault_plan.crashes):
                unsupported.append("crash restart")
            if getattr(fault_plan, "shaper", None) is not None:
                unsupported.append("link shaper")
            if unsupported:
                raise ValueError(
                    "native engine cannot express FaultPlan feature(s): "
                    + ", ".join(unsupported)
                    + " — use the python simulator (engine='python') for "
                    "full fault injection"
                )
            if fault_plan.reorder > 0 and mode is DeliveryMode.TAKE_FIRST:
                mode = DeliveryMode.TAKE_RANDOM
            repeat_probability = max(
                repeat_probability, fault_plan.duplicate
            )
            seed = seed ^ (fault_plan.seed << 1)
            self.muted |= {c.node for c in fault_plan.crashes}
        self.mode = mode
        self._lib = load_rt()
        mode_i = {
            DeliveryMode.TAKE_FIRST: 0,
            DeliveryMode.TAKE_LAST: 1,
            DeliveryMode.TAKE_RANDOM: 2,
        }[mode]
        # engine-construction parameters are kept so the pipelined window
        # can instantiate ONE ENGINE PER IN-FLIGHT ERA: an engine has one
        # queue and one dispatch loop, so wall-clock overlap of era e's tail
        # with era e+1's front requires two independently pumpable engines.
        # Per-era engines also keep determinism trivial — each era's engine
        # sees exactly the event sequence a sequential run would feed it.
        self.f = public_keys.f
        self._mode_i = mode_i
        self._repeat_ppm = int(repeat_probability * 1_000_000)
        self._base_seed = seed & 0xFFFFFFFFFFFFFFFF
        self._coin_need = public_keys.ts_keys.t + 1
        self.pipeline_window = max(int(pipeline_window), 0)
        self._pipeline_active = False
        self._deferred: Dict[int, Dict[int, int]] = {}
        self._era_engines: Dict[int, int] = {}
        self._native_handled_closed = 0
        self._trace_dropped_closed = 0
        self._trace_backlog: List[dict] = []
        self._trace_capacity = 0
        self._h = self._lib.rt_new(
            self.n,
            public_keys.f,
            mode_i,
            self._repeat_ppm,
            seed,
            era,
        )
        if not self._h:
            raise ValueError(
                f"native engine rejected N={self.n}: rt_new supports "
                "1 <= N <= 512 (512-bit membership masks)"
            )
        self._era_engines[era] = self._h
        for v in self.muted:
            self._lib.rt_mute(self._h, v)
        # threshold for the native coin's combine trigger (CommonCoin needs
        # t+1 shares before a combine can possibly succeed)
        self._lib.rt_set_coin_need(self._h, self._coin_need)
        self.routers: List[NativeEraRouter] = [
            NativeEraRouter(
                era=era,
                my_id=i,
                public_keys=public_keys,
                private_keys=private_keys[i],
                net=self,
                extra_factories=extra_factories,
                journal=journals[i] if journals is not None else None,
            )
            for i in range(self.n)
        ]
        for r in self.routers:
            r.pipeline_window = self.pipeline_window
        # callback exceptions, stashed per era and re-raised from the pump
        # loop of the thread that owns that era's engine
        self._cb_errors: List[tuple] = []
        # keep CFUNCTYPE objects alive for the engine's lifetime; every
        # per-era engine shares the same set — callbacks carry the era, which
        # routes them to the right per-era host shims
        self._cbs = (
            _OPAQUE_CB(self._cb_opaque),
            _ACS_CB(self._cb_acs),
            _COINREQ_CB(self._cb_coinreq),
            _CROSS_CB(self._cb_cross),
        )
        self._lib.rt_set_callbacks(self._h, *self._cbs)
        self.delivered_count = 0
        # router-level TPKE flush batcher (crypto_batcher.py): flushed by
        # run() once every queued DecryptedMessage has been delivered — the
        # point where the cross-validator batch is largest
        self.crypto_batcher = None
        if use_crypto_batcher:
            from .crypto_batcher import TpkeEraBatcher

            self.crypto_batcher = TpkeEraBatcher()
            for r in self.routers:
                r.crypto_batcher = self.crypto_batcher
        # era-scoped RBC codec batcher (rbc_batcher.py): opt-in, and only
        # when the .so exports the version-7 RBC host boundary — a stale
        # library degrades to the engine's per-message RS path. LACHAIN_RBC_BATCH=0
        # force-disables it even when requested (ops kill switch).
        self.rbc_batcher = None
        self._rbc_host_on = False
        if (
            use_rbc_batcher
            and self._lib._lt_has_rbc_host
            and os.environ.get("LACHAIN_RBC_BATCH", "1") != "0"
        ):
            from .rbc_batcher import RbcEraBatcher

            self.rbc_batcher = RbcEraBatcher()
            self._rbc_host_on = True
            for r in self.routers:
                r.rbc_batcher = self.rbc_batcher
            self._lib.rt_set_rbc_host(self._h, 1)
        self._own_masks = [-1] * self.n  # engine-side mask cache (-1 unset)
        self._sync_ownership()
        # flight recorder: size the engine ring, align its clock with
        # time.monotonic, and register it with the merged tracer. A weakref
        # keeps the registry from pinning a leaked network alive; close()
        # unregisters explicitly.
        self._trace_offset = clock_offset(self._lib.rt_monotonic_ns)
        self._trace_dropped_seen = 0
        self._trace_source = f"consensus-{id(self):x}"
        self.trace_configure(tracing.DEFAULT_CAPACITY)
        ref = weakref.ref(self)
        tracing.register_native_source(
            self._trace_source,
            lambda: (
                [] if ref() is None else ref()._drain_trace()  # noqa: B023
            ),
        )

    # -- per-era engine lifecycle ---------------------------------------------
    def _live_engines(self) -> List[int]:
        hs: List[int] = []
        if self._h is not None:
            hs.append(self._h)
        for h in self._era_engines.values():
            if h not in hs:
                hs.append(h)
        return hs

    def _h_for(self, era: Optional[int]) -> Optional[int]:
        """Engine handle for `era`: the per-era engine when the pipeline
        window is active, the single shared engine otherwise. None means the
        era's engine is already closed — its traffic is settled and posts
        for it are dropped, mirroring the stale-era drop."""
        if self._pipeline_active and era is not None:
            return self._era_engines.get(era)
        return self._h

    def _era_seed(self, era: int) -> int:
        # deterministic per-era engine seed: two runs with the same base
        # seed get byte-identical delivery schedules era by era
        return (self._base_seed ^ (era * 0x9E3779B97F4A7C15)) & (
            (1 << 64) - 1
        )

    def _open_era_engine(self, era: int) -> None:
        if era in self._era_engines:
            return
        # engines are constructed on the scheduler thread only: the GF(256)
        # table bootstrap in consensus_rt.cpp is guarded by a plain static
        # flag, so first-construction must never race across threads
        h = self._lib.rt_new(
            self.n, self.f, self._mode_i, self._repeat_ppm,
            self._era_seed(era), era,
        )
        if not h:
            raise ValueError(
                f"native engine rejected N={self.n}: rt_new supports "
                "1 <= N <= 512 (512-bit membership masks)"
            )
        for v in self.muted:
            self._lib.rt_mute(h, v)
        self._lib.rt_set_coin_need(h, self._coin_need)
        if self._rbc_host_on:
            self._lib.rt_set_rbc_host(h, 1)
        self._lib.rt_set_callbacks(h, *self._cbs)
        for vid in range(self.n):
            if self._own_masks[vid] >= 0:
                self._lib.rt_set_owned(h, vid, self._own_masks[vid])
        self._lib.rt_trace_configure(h, max(int(self._trace_capacity), 0))
        self._era_engines[era] = h

    def _close_era_engine(self, era: int) -> None:
        h = self._era_engines.pop(era, None)
        if h is None or h == self._h:
            # the construction-time engine doubles as the legacy single-era
            # handle; keep it alive (quiescent) for the aggregate accessors
            return
        try:
            self._trace_backlog.extend(self._drain_engine_trace(h))
        except Exception:  # pragma: no cover - tracing must never kill an era
            pass
        self._native_handled_closed += int(self._lib.rt_native_handled(h))
        self._trace_dropped_closed += int(self._lib.rt_trace_dropped(h))
        self._lib.rt_free(h)

    # -- flight recorder -------------------------------------------------------
    def trace_configure(self, capacity: int) -> None:
        """Resize the engine-side trace rings; 0 disables recording (and
        the hot-path clock reads) entirely — the bench overhead check."""
        self._trace_capacity = max(int(capacity), 0)
        for h in self._live_engines():
            self._lib.rt_trace_configure(h, self._trace_capacity)

    def trace_dropped(self) -> int:
        total = self._trace_dropped_closed
        for h in self._live_engines():
            total += int(self._lib.rt_trace_dropped(h))
        return total

    def _drain_engine_trace(self, h: int) -> List[dict]:
        # size query, then copying call; the copy consumes the ring. Slack
        # covers records appended between the two calls; if the ring still
        # outgrew the buffer (got > len(buf) means no copy happened), retry.
        for _ in range(4):
            need = self._lib.rt_trace_drain(h, None, 0)
            if need == 0:
                return []
            buf = (ctypes.c_uint8 * (need + 4096))()
            got = self._lib.rt_trace_drain(h, buf, len(buf))
            if got <= len(buf):
                return decode_consensus_trace(
                    bytes(buf[:got]), self._trace_offset, self._trace_source
                )
        return []

    def _drain_trace(self) -> List[dict]:
        """Consume the engine rings -> merged-tracer event dicts. Publishes
        native drop-counter growth as a counter delta so
        trace_events_dropped_total keeps counter semantics. While the
        pipeline window is live, only the backlog of CLOSED era engines is
        served: draining a ring that another thread is appending to would
        race inside the engine, so live rings wait for pipeline_end."""
        evs, self._trace_backlog = self._trace_backlog, []
        if not self._pipeline_active:
            for h in self._live_engines():
                evs.extend(self._drain_engine_trace(h))
        dropped = self.trace_dropped()
        if dropped > self._trace_dropped_seen:
            metrics.inc(
                "trace_events_dropped_total",
                dropped - self._trace_dropped_seen,
                labels={"source": "consensus"},
            )
            self._trace_dropped_seen = dropped
        return evs

    def close(self) -> None:
        if self._h is not None or self._era_engines:
            # pull any still-buffered engine events into the merged tracer
            # before the rings are freed
            self._pipeline_active = False
            try:
                tracing.drain_native()
            except Exception:
                pass
            tracing.unregister_native_source(self._trace_source)
            for h in self._live_engines():
                self._lib.rt_free(h)
            self._era_engines = {}
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass

    # -- native ownership ------------------------------------------------------
    def _era_fn_available(self) -> bool:
        from ..crypto.provider import get_backend

        return (
            getattr(get_backend(), "tpke_era_verify_combine", None) is not None
        )

    def _sync_owner(self, vid: int) -> None:
        mask = self.routers[vid]._native_mask()
        if mask != self._own_masks[vid]:
            self._own_masks[vid] = mask
            for h in self._live_engines():
                self._lib.rt_set_owned(h, vid, mask)

    def _sync_ownership(self) -> None:
        for vid in range(self.n):
            self._sync_owner(vid)

    def set_root_context(self, vid: int, producer, ecdsa_priv, ecdsa_pubs) -> None:
        """Give validator `vid` its block-production context so RootProtocol
        can be hosted natively (the Python fallback uses the same context)."""
        self.routers[vid]._root_ctx = (producer, ecdsa_priv, ecdsa_pubs)
        self._sync_owner(vid)

    # -- engine entry points ---------------------------------------------------
    # Each takes era=None and routes to that era's engine via _h_for. A None
    # handle means the era's engine already closed (its block committed and
    # settled traffic is still draining through host shims) — the post is
    # dropped, exactly like the router's stale-era drop.
    def _post_acs_input(self, vid: int, data: bytes, era: int = None) -> None:
        h = self._h_for(era)
        if h is not None:
            self._lib.rt_post_acs_input(h, vid, data, len(data))

    def _post_coin_result(
        self, vid: int, agreement: int, epoch: int, value, era: int = None
    ) -> None:
        h = self._h_for(era)
        if h is not None:
            self._lib.rt_post_coin_result(
                h, vid, agreement, epoch, 1 if value else 0
            )

    def _bcast_opaque(
        self,
        vid: int,
        kind: int,
        agreement: int,
        epoch: int,
        data: bytes,
        era: int = None,
    ) -> None:
        h = self._h_for(era)
        if h is not None:
            self._lib.rt_broadcast_opaque(
                h, vid, kind, agreement, epoch, data, len(data)
            )

    def _send_opaque(
        self,
        vid: int,
        target: int,
        kind: int,
        agreement: int,
        epoch: int,
        data: bytes,
        era: int = None,
    ) -> None:
        # unicast opaque injection: the adversary layer's transport (the
        # caller chooses `vid`, so sender spoofing / replay is expressible)
        h = self._h_for(era)
        if h is not None:
            self._lib.rt_send_opaque(
                h, vid, target, kind, agreement, epoch, data, len(data)
            )

    def _rt_request(self, vid: int, kind: int, a: int, b: int, era: int = None) -> None:
        h = self._h_for(era)
        if h is None:
            return
        self._lib.rt_request(h, vid, kind, a, b)
        # a request posted OUTSIDE run() (post_request path) can recurse
        # through the engine into host code; surface its failure now
        self._raise_cb_error(era)

    def _rt_post(
        self, vid: int, op: int, a: int, b: int, data: bytes = b"", era: int = None
    ) -> None:
        h = self._h_for(era)
        if h is not None:
            self._lib.rt_post(h, vid, op, a, b, data, len(data))

    def _rt_hb_export(self, vid: int, era: int = None) -> bytes:
        h = self._h_for(era)
        if h is None:
            return b""
        size = self._lib.rt_hb_ready_export(h, vid, None, 0)
        if not size:
            return b""
        buf = ctypes.create_string_buffer(size)
        self._lib.rt_hb_ready_export(h, vid, buf, size)
        return buf.raw[:size]

    def native_state_of(self, vid: int, era: int = None) -> str:
        def one(h):
            size = self._lib.rt_debug_state(h, vid, None, 0)
            if not size:
                return ""
            buf = ctypes.create_string_buffer(size)
            self._lib.rt_debug_state(h, vid, buf, size)
            return buf.raw[:size].decode("utf-8", "replace")

        if self._pipeline_active and era is None:
            # stall reports want the whole window, labeled per era
            parts = [
                f"era{e}:{one(h)}"
                for e, h in sorted(self._era_engines.items())
            ]
            return " | ".join(parts)
        h = self._h_for(era)
        return one(h) if h is not None else ""

    def native_handled(self) -> int:
        """Messages the engine consumed natively that PREVIOUSLY each cost a
        per-message Python callback — the eliminated crossings."""
        total = self._native_handled_closed
        for h in self._live_engines():
            total += int(self._lib.rt_native_handled(h))
        return total

    def _advance_era(self, vid: int, era: int) -> None:
        self._lib.rt_advance_era(self._h, vid, era)

    def _request_stop(self, era: int = None) -> None:
        if self._pipeline_active and era is None:
            for h in self._live_engines():
                self._lib.rt_request_stop(h)
            return
        h = self._h_for(era)
        if h is not None:
            self._lib.rt_request_stop(h)

    def mute(self, vid: int) -> None:
        self.muted.add(vid)
        for h in self._live_engines():
            self._lib.rt_mute(h, vid)

    # -- callbacks (engine -> Python); exceptions are stashed per era and
    #    re-raised from the pump loop of the thread owning that era's engine,
    #    since they cannot unwind through the C++ frames ----------------------
    def _stash_cb_error(self, era, exc) -> None:
        self._cb_errors.append((era, exc))

    def _pop_cb_error(self, era=None) -> Optional[BaseException]:
        """Take the first stashed error for `era` (None matches any — the
        sequential path, where one thread owns every engine)."""
        for i, (e, exc) in enumerate(self._cb_errors):
            if era is None or e == era or e is None:
                del self._cb_errors[i]
                return exc
        return None

    def _raise_cb_error(self, era=None) -> None:
        err = self._pop_cb_error(era)
        if err is not None:
            raise err

    def _cb_opaque(self, target, sender, era, kind, agreement, epoch, data, length):
        if self._cb_errors:
            return
        try:
            metrics.inc(CROSSINGS_METRIC, labels={"op": "opaque_message"})
            blob = ctypes.string_at(data, length) if length else b""
            self.routers[target]._on_opaque(
                sender, era, kind, agreement, epoch, blob
            )
            if kind == KIND_DECRYPTED and self.crypto_batcher is not None:
                h = self._h_for(era)
                if (
                    h is not None
                    and self.crypto_batcher.pending_for(era)
                    and self._lib.rt_opaque_pending(h, KIND_DECRYPTED) == 0
                ):
                    # all decryption shares delivered: break out so the pump
                    # loop can flush the cross-validator batch before
                    # lag-round traffic
                    self._lib.rt_request_stop(h)
        except BaseException as exc:  # noqa: BLE001
            self._stash_cb_error(era, exc)

    def _cb_acs(self, target, era, nslots, slots, datas, lens):
        if self._cb_errors:
            return
        try:
            metrics.inc(CROSSINGS_METRIC, labels={"op": "acs_result"})
            result = {
                int(slots[i]): (
                    ctypes.string_at(datas[i], lens[i]) if lens[i] else b""
                )
                for i in range(nslots)
            }
            self.routers[target]._on_acs_result(era, result)
        except BaseException as exc:  # noqa: BLE001
            self._stash_cb_error(era, exc)

    def _cb_coinreq(self, target, era, agreement, epoch):
        if self._cb_errors:
            return
        try:
            metrics.inc(CROSSINGS_METRIC, labels={"op": "coin_request"})
            self.routers[target]._on_coin_request(era, agreement, epoch)
        except BaseException as exc:  # noqa: BLE001
            self._stash_cb_error(era, exc)

    def _cb_cross(self, target, era, op, a, b, data, length):
        if self._cb_errors:
            return
        try:
            metrics.inc(
                CROSSINGS_METRIC,
                labels={"op": XO_NAMES.get(op, f"op{op}")},
            )
            blob = ctypes.string_at(data, length) if length else b""
            self.routers[target]._on_cross(era, op, a, b, blob)
        except BaseException as exc:  # noqa: BLE001
            self._stash_cb_error(era, exc)

    # -- execution (simulator.py::run contract) --------------------------------
    def post_request(self, validator: int, pid, value) -> None:
        self._sync_ownership()
        # proposal injection does the RBC encode (erasure coding) before
        # the first dispatch chunk runs — outside the engine's phase
        # accumulators, so tag it as propose-phase work here
        with tracing.span(
            "consensus.propose", era=getattr(pid, "era", None)
        ):
            self.routers[validator].internal_request(
                M.Request(from_id=None, to_id=pid, input=value)
            )

    def run(
        self,
        done: Callable[[], bool],
        max_messages: int = 1_000_000,
        chunk: int = 16384,
    ) -> bool:
        try:
            while not done():
                processed = self._lib.rt_run(self._h, chunk)
                self.delivered_count += processed
                self._raise_cb_error()
                metrics.set_gauge(
                    "consensus_dispatch_queue_depth",
                    self._lib.rt_queue_len(self._h),
                )
                # RBC codec batch flushes first: interpolations unblock
                # READY/deliver and thus ACS, so draining them before the
                # TPKE flush keeps the later crypto batch as large as it
                # can possibly get
                if (
                    self.rbc_batcher is not None
                    and self.rbc_batcher.pending
                    and self._lib.rt_queue_len(self._h) == 0
                ):
                    self.rbc_batcher.flush()
                    self._raise_cb_error()
                    continue
                if (
                    self.crypto_batcher is not None
                    and self.crypto_batcher.pending
                    and (
                        self._lib.rt_queue_len(self._h) == 0
                        or self._lib.rt_opaque_pending(self._h, KIND_DECRYPTED)
                        == 0
                    )
                ):
                    self.crypto_batcher.flush()
                    self._raise_cb_error()
                    continue
                if processed == 0:
                    return done()
                if (
                    self.delivered_count >= max_messages
                    and self._lib.rt_queue_len(self._h) > 0
                    and not done()
                ):
                    raise RuntimeError(
                        f"message cap {max_messages} exceeded — livelock?"
                    )
            return True
        finally:
            metrics.set_gauge(
                "consensus_native_handled_messages", self.native_handled()
            )

    # -- pipelined window (era overlap) ----------------------------------------
    # The windowed scheduler (core/devnet.py) splits every era at the
    # XO_ROOT_SIGN crossing: the FRONT (propose/encrypt/RBC/BA/coin/
    # TPKE-verify-combine) runs on the scheduler thread; the TAIL (header
    # sign + flood + ECDSA verify + produce/commit) runs on a worker thread
    # that commits eras strictly ascending. Each per-era engine is pumped by
    # exactly one thread at a time: the scheduler hands the engine to the
    # tail worker at front-complete and never touches it again.
    def pipeline_begin(self) -> None:
        if self.pipeline_window < 1:
            raise RuntimeError("pipeline_begin requires pipeline_window >= 1")
        self._sync_ownership()
        full = OWN_HB | OWN_COIN | OWN_ROOT
        for r in self.routers:
            if r._native_mask() != full:
                raise RuntimeError(
                    "era pipelining requires full native ownership on every "
                    f"validator (validator {r._my_id} mask "
                    f"{r._native_mask():#x}) — python-protocol overrides must "
                    "run sequentially"
                )
        self._pipeline_active = True
        self._deferred = {}

    def pipeline_end(self) -> None:
        self._pipeline_active = False
        self._deferred = {}

    def open_era(self, era: int) -> None:
        """Admit `era` into the window: give it an engine (scheduler thread
        only — see _open_era_engine) and forward every router."""
        self._open_era_engine(era)
        for r in self.routers:
            r.open_era(era)

    def commit_era(self, era: int) -> None:
        """Called by the tail worker after `era`'s block committed: journal
        GC honoring the overlap window, then retire the era's engine."""
        for r in self.routers:
            r.commit_era_gc(era)
        self._deferred.pop(era, None)
        self._close_era_engine(era)

    def _defer_sign(self, vid: int, era: int, parity: int) -> bool:
        """XO_ROOT_SIGN interception point. Outside the pipelined window:
        decline (the host signs inline). Inside: stash the coin parity —
        era `era`'s front is complete for `vid` — and once all n validators
        reach the sign point, break the engine out of its chunk so run_front
        can return. Muted validators still reach the sign point (they
        receive everything; muting only gags their sends)."""
        if not self._pipeline_active:
            return False
        d = self._deferred.setdefault(era, {})
        d[vid] = parity
        if len(d) >= self.n:
            h = self._era_engines.get(era)
            if h is not None:
                self._lib.rt_request_stop(h)
        return True

    def front_complete(self, era: int) -> bool:
        return len(self._deferred.get(era, ())) >= self.n

    def _pump(
        self, era: int, lane: str, done: Callable[[], bool],
        max_messages: int, chunk: int,
    ) -> None:
        """Shared pump loop for one era's engine on one lane. Flushes ONLY
        this era's crypto batches (pending_for/flush(era)): lazy builders
        rt_post into their era's engine, so only the thread owning that
        engine may flush its submissions."""
        h = self._era_engines.get(era)
        if h is None:
            raise RuntimeError(f"era {era} engine is not open")
        delivered = 0
        while not done():
            processed = self._lib.rt_run(h, chunk)
            delivered += processed
            self.delivered_count += processed
            self._raise_cb_error(era)
            metrics.set_gauge(
                "consensus_dispatch_queue_depth", self._lib.rt_queue_len(h)
            )
            if (
                self.rbc_batcher is not None
                and self.rbc_batcher.pending_for(era)
                and self._lib.rt_queue_len(h) == 0
            ):
                self.rbc_batcher.flush(era)
                self._raise_cb_error(era)
                continue
            if (
                self.crypto_batcher is not None
                and self.crypto_batcher.pending_for(era)
                and (
                    self._lib.rt_queue_len(h) == 0
                    or self._lib.rt_opaque_pending(h, KIND_DECRYPTED) == 0
                )
            ):
                self.crypto_batcher.flush(era)
                self._raise_cb_error(era)
                continue
            if processed == 0:
                # in the simulator there is no external input: an idle
                # engine with nothing to flush and the lane not done is a
                # genuine wedge
                raise RuntimeError(self._stall_report(era, lane))
            if delivered >= max_messages and self._lib.rt_queue_len(h) > 0:
                raise RuntimeError(
                    f"era {era} {lane}: message cap {max_messages} "
                    "exceeded — livelock?"
                )

    def run_front(
        self, era: int, max_messages: int = 2_000_000, chunk: int = 16384
    ) -> None:
        """Pump era `era` until every validator's front is complete (all n
        sign-deferred). Scheduler thread only."""
        self._pump(
            era, "front", lambda: self.front_complete(era),
            max_messages, chunk,
        )

    def run_tail(
        self, era: int, max_messages: int = 2_000_000, chunk: int = 16384
    ) -> List[Any]:
        """Resume the deferred signs and pump era `era` to block production
        on every router. Tail-worker thread only; eras strictly ascending."""
        pid = M.RootProtocolId(era=era)
        deferred = self._deferred.get(era, {})
        for vid in range(self.n):
            self.routers[vid].root_host(era).on_sign(deferred[vid])
            self._raise_cb_error(era)

        def tail_done() -> bool:
            return all(pid in r._native_results for r in self.routers)

        self._pump(era, "tail", tail_done, max_messages, chunk)
        return [r._native_results[pid] for r in self.routers]

    def _stall_report(self, era: int, lane: str) -> str:
        in_flight = sorted(self._era_engines)
        lines = [
            f"consensus pipeline stalled: era {era} ({lane} lane) wedged; "
            f"in-flight eras {in_flight}"
        ]
        for vid in range(self.n):
            lines.append(
                f"  validator {vid}: {self.native_state_of(vid, era=era)}"
            )
        return "\n".join(lines)

    def results(self, pid) -> List[Any]:
        return [r.result_of(pid) for r in self.routers]
