"""ctypes binding for the native consensus engine (libconsensus_rt).

`NativeSimulatedNetwork` is a drop-in for `simulator.SimulatedNetwork`: the
delivery queue and the flood protocols (BinaryBroadcast, BinaryAgreement,
ReliableBroadcast, CommonSubset) run inside the C++ engine
(native/consensus_rt.cpp), while every crypto-bearing protocol — CommonCoin,
HoneyBadger, RootProtocol — remains the existing Python class, its messages
crossing the engine as opaque payloads. The split keeps the Python crypto
stack (and the TPU-batched era kernel it drives) as the single source of
cryptographic truth while removing the Python per-message dispatch cost that
dominated N=64 eras (benchmarks/results_r03.json: 479.5 s, 2.45 M messages).

Reference roles covered: AbstractProtocol's thread+queue runtime
(/root/reference/src/Lachain.Consensus/AbstractProtocol.cs:11-168) and the
test DeliveryService (test/Lachain.ConsensusTest/DeliverySerivce.cs:10-124).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Set

from . import messages as M
from .era import EraRouter
from .keys import PrivateConsensusKeys, PublicConsensusKeys
from .simulator import DeliveryMode

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libconsensus_rt.so")

# opaque payload kinds (shared contract with consensus_rt.cpp MT_OPAQUE)
KIND_DECRYPTED = 0
KIND_SIGNED_HEADER = 1
KIND_COIN = 2

_OPAQUE_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_int32,  # target
    ctypes.c_int32,  # sender
    ctypes.c_int32,  # era
    ctypes.c_int32,  # kind
    ctypes.c_int32,  # agreement
    ctypes.c_int32,  # epoch
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_size_t,
)
_ACS_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_int32,  # target
    ctypes.c_int32,  # era
    ctypes.c_int32,  # nslots
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
    ctypes.POINTER(ctypes.c_size_t),
)
_COINREQ_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32
)

_lib_cache: List[Any] = [None]


def load_rt():
    if _lib_cache[0] is not None:
        return _lib_cache[0]
    sources = [
        os.path.join(_NATIVE_DIR, "consensus_rt.cpp"),
        os.path.join(_NATIVE_DIR, "Makefile"),
    ]
    if not os.path.exists(_LIB_PATH) or any(
        os.path.getmtime(_LIB_PATH) < os.path.getmtime(s) for s in sources
    ):
        subprocess.run(
            ["make", "-s", "-C", _NATIVE_DIR], check=True, capture_output=True
        )
    lib = ctypes.CDLL(_LIB_PATH)
    lib.lt_crt_version.restype = ctypes.c_int
    assert lib.lt_crt_version() == 1
    lib.rt_new.restype = ctypes.c_void_p
    lib.rt_new.argtypes = [
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_uint32,
        ctypes.c_uint64,
        ctypes.c_int,
    ]
    lib.rt_free.argtypes = [ctypes.c_void_p]
    lib.rt_set_callbacks.argtypes = [
        ctypes.c_void_p,
        _OPAQUE_CB,
        _ACS_CB,
        _COINREQ_CB,
    ]
    lib.rt_mute.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rt_advance_era.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
    lib.rt_post_acs_input.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.rt_post_coin_result.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.rt_broadcast_opaque.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.rt_run.restype = ctypes.c_size_t
    lib.rt_run.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.rt_request_stop.argtypes = [ctypes.c_void_p]
    lib.rt_opaque_pending.restype = ctypes.c_uint64
    lib.rt_opaque_pending.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rt_queue_len.restype = ctypes.c_size_t
    lib.rt_queue_len.argtypes = [ctypes.c_void_p]
    lib.rt_delivered.restype = ctypes.c_uint64
    lib.rt_delivered.argtypes = [ctypes.c_void_p]
    _lib_cache[0] = lib
    return lib


@dataclass(frozen=True)
class NativeCoinParent:
    """Result address for a CommonCoin requested by a NATIVE BinaryAgreement:
    the Python coin's emit_result routes back into the engine."""

    agreement: int
    epoch: int


class NativeEraRouter(EraRouter):
    """EraRouter whose flood protocols live in the native engine.

    Python-side protocols (Root/HoneyBadger/CommonCoin) are created and routed
    exactly as in EraRouter; requests addressed to natively-owned protocol ids
    divert into the engine, and engine callbacks re-enter through
    `_on_opaque` / `_on_acs_result` / `_on_coin_request`.
    """

    def __init__(
        self,
        era: int,
        my_id: int,
        public_keys: PublicConsensusKeys,
        private_keys: PrivateConsensusKeys,
        net: "NativeSimulatedNetwork",
        extra_factories=None,
    ):
        def _no_send(target, payload):  # pragma: no cover
            raise RuntimeError("native router transports via the engine")

        super().__init__(
            era,
            my_id,
            public_keys,
            private_keys,
            send=_no_send,
            extra_factories=extra_factories,
        )
        self._net = net
        self._acs_parent: Any = None

    # -- outbound: divert into the engine -------------------------------------
    def internal_request(self, req: M.Request) -> None:
        to = req.to_id
        if isinstance(to, M.CommonSubsetId):
            self._acs_parent = req.from_id
            self._net._post_acs_input(self._my_id, req.input)
            return
        if isinstance(
            to,
            (M.BinaryAgreementId, M.BinaryBroadcastId, M.ReliableBroadcastId),
        ):
            raise RuntimeError(f"natively-owned protocol requested: {to}")
        super().internal_request(req)

    def internal_response(self, res: M.Result) -> None:
        if isinstance(res.to_id, NativeCoinParent):
            self._net._post_coin_result(
                self._my_id, res.to_id.agreement, res.to_id.epoch, res.value
            )
            return
        if res.to_id is None:
            # top-level protocol completed (e.g. Root produced its block):
            # break the engine out of its chunk so the driver can re-check
            # done() promptly — mirrors the Python simulator's per-message
            # done() check and keeps lag-round coin work off the hot path
            self._net._request_stop()
            return
        super().internal_response(res)

    def broadcast(self, payload) -> None:
        if isinstance(payload, M.DecryptedMessage):
            self._net._bcast_opaque(
                self._my_id, KIND_DECRYPTED, payload.share_id, 0, payload.payload
            )
        elif isinstance(payload, M.SignedHeaderMessage):
            data = (
                len(payload.header_bytes).to_bytes(4, "big")
                + payload.header_bytes
                + payload.signature
            )
            self._net._bcast_opaque(self._my_id, KIND_SIGNED_HEADER, 0, 0, data)
        elif isinstance(payload, M.CoinMessage):
            self._net._bcast_opaque(
                self._my_id,
                KIND_COIN,
                payload.coin.agreement,
                payload.coin.epoch,
                payload.share,
            )
        else:
            raise TypeError(f"unexpected python-protocol payload {type(payload)}")

    def send_to(self, validator: int, payload) -> None:
        raise TypeError("python-side protocols only broadcast")

    def _create(self, pid):
        if isinstance(
            pid,
            (
                M.BinaryBroadcastId,
                M.BinaryAgreementId,
                M.ReliableBroadcastId,
                M.CommonSubsetId,
            ),
        ):
            raise RuntimeError(f"natively-owned protocol id {pid}")
        return super()._create(pid)

    def advance_era(self, new_era: int) -> None:
        if new_era <= self.era:
            return
        super().advance_era(new_era)
        self._net._advance_era(self._my_id, new_era)

    # -- engine callbacks ------------------------------------------------------
    def _on_opaque(
        self, sender: int, era: int, kind: int, agreement: int, epoch: int, data: bytes
    ) -> None:
        if kind == KIND_DECRYPTED:
            payload = M.DecryptedMessage(
                hb=M.HoneyBadgerId(era=era), share_id=agreement, payload=data
            )
        elif kind == KIND_SIGNED_HEADER:
            hlen = int.from_bytes(data[:4], "big")
            payload = M.SignedHeaderMessage(
                root=M.RootProtocolId(era=era),
                header_bytes=data[4 : 4 + hlen],
                signature=data[4 + hlen :],
            )
        elif kind == KIND_COIN:
            payload = M.CoinMessage(
                coin=M.CoinId(era=era, agreement=agreement, epoch=epoch),
                share=data,
            )
        else:  # unknown kind: drop (forward-compat)
            return
        self.dispatch_external(sender, payload)

    def _on_acs_result(self, era: int, result: Dict[int, bytes]) -> None:
        self.internal_response(
            M.Result(
                from_id=M.CommonSubsetId(era=era),
                to_id=self._acs_parent,
                value=result,
            )
        )

    def _on_coin_request(self, era: int, agreement: int, epoch: int) -> None:
        cid = M.CoinId(era=era, agreement=agreement, epoch=epoch)
        super().internal_request(
            M.Request(
                from_id=NativeCoinParent(agreement=agreement, epoch=epoch),
                to_id=cid,
                input=None,
            )
        )


class NativeSimulatedNetwork:
    """Drop-in for simulator.SimulatedNetwork backed by the C++ engine."""

    def __init__(
        self,
        public_keys: PublicConsensusKeys,
        private_keys: List[PrivateConsensusKeys],
        era: int = 0,
        seed: int = 0,
        mode: DeliveryMode = DeliveryMode.TAKE_FIRST,
        repeat_probability: float = 0.0,
        muted: Optional[Set[int]] = None,
        extra_factories=None,
        use_crypto_batcher: bool = True,
        fault_plan=None,
    ):
        self.n = public_keys.n
        self.muted = set(muted or set())
        self.fault_plan = fault_plan
        if fault_plan is not None:
            # one FaultPlan, three delivery layers: here the plan maps onto
            # the engine's own fault knobs — duplication -> repeat_ppm,
            # reordering -> TAKE_RANDOM delivery, a crash that never
            # restarts -> a muted player. Features the engine cannot express
            # (probabilistic drop, delay, partitions, mid-era restart) are
            # refused loudly rather than silently weakened: a chaos run that
            # *looks* like it injected loss but didn't would certify a
            # recovery path that was never exercised.
            unsupported = []
            if fault_plan.drop > 0:
                unsupported.append("drop")
            if fault_plan.delay > 0:
                unsupported.append("delay")
            if fault_plan.partitions:
                unsupported.append("partitions")
            if any(c.restart is not None for c in fault_plan.crashes):
                unsupported.append("crash restart")
            if unsupported:
                raise ValueError(
                    "native engine cannot express FaultPlan feature(s): "
                    + ", ".join(unsupported)
                    + " — use the python simulator (engine='python') for "
                    "full fault injection"
                )
            if fault_plan.reorder > 0 and mode is DeliveryMode.TAKE_FIRST:
                mode = DeliveryMode.TAKE_RANDOM
            repeat_probability = max(
                repeat_probability, fault_plan.duplicate
            )
            seed = seed ^ (fault_plan.seed << 1)
            self.muted |= {c.node for c in fault_plan.crashes}
        self.mode = mode
        self._lib = load_rt()
        mode_i = {
            DeliveryMode.TAKE_FIRST: 0,
            DeliveryMode.TAKE_LAST: 1,
            DeliveryMode.TAKE_RANDOM: 2,
        }[mode]
        self._h = self._lib.rt_new(
            self.n,
            public_keys.f,
            mode_i,
            int(repeat_probability * 1_000_000),
            seed,
            era,
        )
        for v in self.muted:
            self._lib.rt_mute(self._h, v)
        self.routers: List[NativeEraRouter] = [
            NativeEraRouter(
                era=era,
                my_id=i,
                public_keys=public_keys,
                private_keys=private_keys[i],
                net=self,
                extra_factories=extra_factories,
            )
            for i in range(self.n)
        ]
        self._cb_error: Optional[BaseException] = None
        # keep CFUNCTYPE objects alive for the engine's lifetime
        self._cbs = (
            _OPAQUE_CB(self._cb_opaque),
            _ACS_CB(self._cb_acs),
            _COINREQ_CB(self._cb_coinreq),
        )
        self._lib.rt_set_callbacks(self._h, *self._cbs)
        self.delivered_count = 0
        # router-level TPKE flush batcher (crypto_batcher.py): flushed by
        # run() once every queued DecryptedMessage has been delivered — the
        # point where the cross-validator batch is largest
        self.crypto_batcher = None
        if use_crypto_batcher:
            from .crypto_batcher import TpkeEraBatcher

            self.crypto_batcher = TpkeEraBatcher()
            for r in self.routers:
                r.crypto_batcher = self.crypto_batcher

    def close(self) -> None:
        if self._h is not None:
            self._lib.rt_free(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass

    # -- engine entry points ---------------------------------------------------
    def _post_acs_input(self, vid: int, data: bytes) -> None:
        self._lib.rt_post_acs_input(self._h, vid, data, len(data))

    def _post_coin_result(self, vid: int, agreement: int, epoch: int, value) -> None:
        self._lib.rt_post_coin_result(
            self._h, vid, agreement, epoch, 1 if value else 0
        )

    def _bcast_opaque(
        self, vid: int, kind: int, agreement: int, epoch: int, data: bytes
    ) -> None:
        self._lib.rt_broadcast_opaque(
            self._h, vid, kind, agreement, epoch, data, len(data)
        )

    def _advance_era(self, vid: int, era: int) -> None:
        self._lib.rt_advance_era(self._h, vid, era)

    def _request_stop(self) -> None:
        self._lib.rt_request_stop(self._h)

    def mute(self, vid: int) -> None:
        self.muted.add(vid)
        self._lib.rt_mute(self._h, vid)

    # -- callbacks (engine -> Python); exceptions are stashed and re-raised
    #    from run(), since they cannot unwind through the C++ frames ----------
    def _cb_opaque(self, target, sender, era, kind, agreement, epoch, data, length):
        if self._cb_error is not None:
            return
        try:
            blob = ctypes.string_at(data, length) if length else b""
            self.routers[target]._on_opaque(
                sender, era, kind, agreement, epoch, blob
            )
            if (
                kind == KIND_DECRYPTED
                and self.crypto_batcher is not None
                and self.crypto_batcher.pending
                and self._lib.rt_opaque_pending(self._h, KIND_DECRYPTED) == 0
            ):
                # all decryption shares delivered: break out so run() can
                # flush the cross-validator batch before lag-round traffic
                self._lib.rt_request_stop(self._h)
        except BaseException as exc:  # noqa: BLE001
            self._cb_error = exc

    def _cb_acs(self, target, era, nslots, slots, datas, lens):
        if self._cb_error is not None:
            return
        try:
            result = {
                int(slots[i]): (
                    ctypes.string_at(datas[i], lens[i]) if lens[i] else b""
                )
                for i in range(nslots)
            }
            self.routers[target]._on_acs_result(era, result)
        except BaseException as exc:  # noqa: BLE001
            self._cb_error = exc

    def _cb_coinreq(self, target, era, agreement, epoch):
        if self._cb_error is not None:
            return
        try:
            self.routers[target]._on_coin_request(era, agreement, epoch)
        except BaseException as exc:  # noqa: BLE001
            self._cb_error = exc

    # -- execution (simulator.py::run contract) --------------------------------
    def post_request(self, validator: int, pid, value) -> None:
        self.routers[validator].internal_request(
            M.Request(from_id=None, to_id=pid, input=value)
        )

    def run(
        self,
        done: Callable[[], bool],
        max_messages: int = 1_000_000,
        chunk: int = 16384,
    ) -> bool:
        while not done():
            processed = self._lib.rt_run(self._h, chunk)
            self.delivered_count += processed
            if self._cb_error is not None:
                err, self._cb_error = self._cb_error, None
                raise err
            if (
                self.crypto_batcher is not None
                and self.crypto_batcher.pending
                and (
                    self._lib.rt_queue_len(self._h) == 0
                    or self._lib.rt_opaque_pending(self._h, KIND_DECRYPTED)
                    == 0
                )
            ):
                self.crypto_batcher.flush()
                continue
            if processed == 0:
                return done()
            if (
                self.delivered_count >= max_messages
                and self._lib.rt_queue_len(self._h) > 0
                and not done()
            ):
                raise RuntimeError(
                    f"message cap {max_messages} exceeded — livelock?"
                )
        return True

    def results(self, pid) -> List[Any]:
        return [r.result_of(pid) for r in self.routers]
