"""Smart-malicious adversary fleet: seeded, deterministic misbehaviour.

Where FaultPlan (faults.py) models an UNRELIABLE network — loss, delay,
reorder, crash windows — this module models MALICIOUS validators: nodes that
hold real key shares and use them to attack the protocol from the inside.
Every strategy is a pure function of (plan.seed, traitor id, payload bytes),
so two runs with the same plan are bit-identical — the same property
FaultPlan pins for fault schedules — and the SAME misbehaviour plays out on
both the Python-protocol engine and the native engine (traitors fall back to
Python protocol overrides on the native engine so the wrappers see typed
payloads; honest validators stay fully native). tests/test_consensus_adversary.py
pins cross-engine identity of committed blocks AND evidence sets.

Strategies:
  equivocate        broadcast the real TPKE decryption share / coin share,
                    then a CONFLICTING well-formed variant for the same slot
                    (coin: a real threshold signature over an altered
                    message; dec: the real U_i point multiplied by a scalar,
                    correct trailing ids). Every honest node's first-seen
                    latch records an equivocation and drops the second
                    payload, so liveness holds and evidence is deterministic.
  withhold          ship coin + decryption shares to only f+1 seeded
                    recipients (always including the traitor itself) — the
                    threshold-boundary starvation attack. Tolerated: honest
                    nodes still hold n-f >= f+1 honest shares; no evidence.
  relay             adversarial relay: replay a seeded ~25% of the signed
                    coin/dec frames the traitor receives, spoofing the
                    original sender, to a seeded target subset. Decisions
                    key on (sender, slot) identity — not bytes, because
                    TPKE ciphertexts are randomized per run. Replayed
                    bytes are identical, so latches pass them through and
                    protocol dedupe absorbs them; no evidence, no forks.
  spam              flood a burst of distinct well-formed coin slots (junk
                    share bytes, valid length + trailing id) once per era:
                    exercises the per-sender first-seen latch budget. Honest
                    nodes shed past the cap (consensus_msgs_shed_total,
                    reason="latch_cap" — and the native engine's identical
                    opq_latch_cap) and keep committing.
  equivocate_votes  AUX/CONF vote equivocation (flip the vote, double-send).
                    Python engine only: BB state machines are engine-typed
                    messages on the native engine and cannot be overridden.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional, Tuple

from . import messages as M

STRATEGIES = (
    "equivocate",
    "withhold",
    "relay",
    "spam",
    "equivocate_votes",
)


@dataclass(frozen=True)
class AdversaryPlan:
    """A deterministic misbehaviour schedule for a set of traitor ids."""

    strategy: str
    traitors: Tuple[int, ...]
    seed: int = 0
    # knobs
    spam_slots: int = 2600  # distinct flooded latch slots (> latch cap 2048)
    relay_fanout: int = 2  # replay targets per captured frame
    relay_rate: int = 4  # replay 1-in-N captured frames

    def __post_init__(self):
        if self.strategy not in STRATEGIES:
            raise ValueError(
                f"unknown adversary strategy {self.strategy!r}; "
                f"expected one of {STRATEGIES}"
            )
        object.__setattr__(self, "traitors", tuple(self.traitors))


def _h(seed: int, *parts) -> int:
    """Stateless seeded decision hash: identical across engines and runs
    because it depends only on the plan seed and payload-derived parts."""
    h = hashlib.blake2b(digest_size=8)
    h.update(str(seed).encode())
    for p in parts:
        h.update(p if isinstance(p, bytes) else str(p).encode())
        h.update(b"|")
    return int.from_bytes(h.digest(), "big")


def _subset(seed: int, tag, me: int, n: int, size: int) -> Tuple[int, ...]:
    """Seeded choice of `size` validators out of range(n) minus `me`."""
    others = [t for t in range(n) if t != me]
    others.sort(key=lambda t: _h(seed, tag, t))
    return tuple(sorted(others[:size]))


def _payload_bytes(payload) -> bytes:
    if isinstance(payload, M.CoinMessage):
        return payload.share
    if isinstance(payload, M.DecryptedMessage):
        return payload.payload
    raise TypeError(f"unexpected payload {type(payload)}")


def _payload_era(payload) -> int:
    if isinstance(payload, M.CoinMessage):
        return payload.coin.era
    return payload.hb.era


def conflicting_variant(router, payload):
    """A well-formed payload for the SAME slot that differs from `payload`:
    the equivocation pair. Built from the traitor's REAL key material."""
    if isinstance(payload, M.CoinMessage):
        from ..crypto import threshold_sig as ts

        signer = ts.ThresholdSigner(
            payload.coin.to_bytes() + b"/equivocate",
            router.private_keys.ts_share,
            router.public_keys.ts_keys,
        )
        return M.CoinMessage(coin=payload.coin, share=signer.sign().to_bytes())
    if isinstance(payload, M.DecryptedMessage):
        from ..crypto import bls12381 as bls
        from ..crypto import tpke

        dec = tpke.PartiallyDecryptedShare.from_bytes(payload.payload)
        alt = tpke.PartiallyDecryptedShare(
            ui=bls.g1_mul(dec.ui, 1337),
            decryptor_id=dec.decryptor_id,
            share_id=dec.share_id,
        )
        return M.DecryptedMessage(
            hb=payload.hb, share_id=payload.share_id, payload=alt.to_bytes()
        )
    raise TypeError(f"unexpected payload {type(payload)}")


def _flip_vote(payload):
    if isinstance(payload, M.AuxMessage):
        return M.AuxMessage(bb=payload.bb, value=not payload.value)
    return M.ConfMessage(
        bb=payload.bb, values=frozenset({True, False}) - payload.values
        or frozenset({True}),
    )


# -- transport shims ---------------------------------------------------------


def _is_native(net) -> bool:
    return hasattr(net, "_send_opaque")


def _make_injector(net):
    """Return inject(sender, target, payload): enqueue a payload AS IF
    `sender` sent it (spoofing allowed), bypassing the sender's router and
    its journal latch. target None = broadcast to all n, in target order —
    identical ordering on both engines, so TAKE_FIRST runs stay aligned."""
    if not _is_native(net):
        return net.inject

    from .native_rt import KIND_COIN, KIND_DECRYPTED

    def inject(sender: int, target: Optional[int], payload) -> None:
        if isinstance(payload, M.CoinMessage):
            kind = KIND_COIN
            agreement, epoch = payload.coin.agreement, payload.coin.epoch
        else:
            kind = KIND_DECRYPTED
            agreement, epoch = payload.share_id, 0
        data = _payload_bytes(payload)
        era = _payload_era(payload)
        targets = range(net.n) if target is None else (target,)
        for t in targets:
            net._send_opaque(sender, t, kind, agreement, epoch, data, era=era)

    return inject


def _force_python_protocols(router) -> None:
    """Native engine traitors run Coin/HB (and thus Root) as Python protocol
    overrides, flowing through the legacy cb_opaque path — the wrappers below
    need typed payload objects, which the engine-hosted path never builds."""
    from .common_coin import CommonCoin
    from .honey_badger import HoneyBadger

    fac = router._extra_factories
    fac.setdefault(
        M.CoinId,
        lambda pid, r: CommonCoin(
            pid, r, r.private_keys.ts_share, r.public_keys.ts_keys
        ),
    )
    fac.setdefault(
        M.HoneyBadgerId,
        lambda pid, r: HoneyBadger(pid, r, r.public_keys, r.private_keys),
    )


# -- installation ------------------------------------------------------------


def install(plan: AdversaryPlan, net) -> None:
    """Mutate `net` in place: each traitor's router gets the plan's
    misbehaviour. Call after network construction, before the first run
    (the native ownership mask is computed lazily, so post-construction
    override installation is supported by contract)."""
    native = _is_native(net)
    if plan.strategy == "equivocate_votes" and native:
        raise ValueError(
            "equivocate_votes needs Python BB protocols; the native engine "
            "types BVAL/AUX/CONF messages internally"
        )
    for v in plan.traitors:
        if not 0 <= v < net.n:
            raise ValueError(f"traitor id {v} out of range for n={net.n}")
        _install_traitor(plan, net, v)


def _install_traitor(plan: AdversaryPlan, net, v: int) -> None:
    router = net.routers[v]
    if _is_native(net):
        _force_python_protocols(router)
    inject = _make_injector(net)
    f = router.public_keys.f
    orig_broadcast = router.broadcast
    spammed_eras = set()

    def broadcast(payload) -> None:
        share_like = isinstance(payload, (M.CoinMessage, M.DecryptedMessage))
        if plan.strategy == "withhold" and share_like:
            # threshold-boundary starvation: f+1 recipients only (self
            # always included so the traitor's own protocols stay live)
            era = _payload_era(payload)
            proto = type(payload).__name__
            for t in _subset(plan.seed, ("withhold", v, era, proto), v, net.n, f):
                inject(v, t, payload)
            inject(v, v, payload)
            return
        orig_broadcast(payload)
        if plan.strategy == "equivocate" and share_like:
            inject(v, None, conflicting_variant(router, payload))
        elif plan.strategy == "equivocate_votes" and isinstance(
            payload, (M.AuxMessage, M.ConfMessage)
        ):
            net.inject(v, None, _flip_vote(payload))
        elif plan.strategy == "spam" and isinstance(payload, M.CoinMessage):
            era = payload.coin.era
            if era not in spammed_eras:
                spammed_eras.add(era)
                _flood(plan, net, v, era, inject)

    router.broadcast = broadcast

    if plan.strategy == "relay":
        orig_dispatch = router.dispatch_external
        replayed: dict = {}  # era -> frame keys already replayed (once each)

        def dispatch_external(sender: int, payload) -> None:
            orig_dispatch(sender, payload)
            if sender == v or not isinstance(
                payload, (M.CoinMessage, M.DecryptedMessage)
            ):
                return
            era = _payload_era(payload)
            seen = replayed.setdefault(era, set())
            for stale in [e for e in replayed if e < era - 1]:
                del replayed[stale]  # bounded memory across campaigns
            # decision key is the SLOT identity, never the payload bytes:
            # TPKE ciphertexts are randomized (crypto/tpke.py encrypt), so
            # dec-share bytes differ run to run while the slot schedule is
            # bit-stable — byte-keyed decisions would break two-run and
            # cross-engine replay identity
            if isinstance(payload, M.CoinMessage):
                slot = ("coin", era, payload.coin.agreement, payload.coin.epoch)
            else:
                slot = ("dec", era, payload.share_id)
            key = _h(plan.seed, "relay", v, sender, slot)
            # replay each captured frame AT MOST ONCE: replays of replays
            # (including our own frames echoed back) must not cascade
            if key % plan.relay_rate == 0 and key not in seen:
                seen.add(key)
                for t in _subset(
                    plan.seed, ("rtgt", v, key), sender, net.n, plan.relay_fanout
                ):
                    inject(sender, t, payload)

        router.dispatch_external = dispatch_external


def _flood(plan: AdversaryPlan, net, v: int, era: int, inject) -> None:
    """Spam burst: distinct well-formed coin slots that each claim a
    first-seen latch entry. Length + trailing-id checks pass, so the only
    backstop is the per-sender latch budget — which is the point."""
    from ..crypto import bls12381 as bls

    for k in range(plan.spam_slots):
        cid = M.CoinId(era=era, agreement=v, epoch=100_000 + k)
        junk = (
            hashlib.blake2b(
                b"%d|spam|%d|%d" % (plan.seed, v, k), digest_size=32
            ).digest()
            * ((bls.G2_BYTES + 31) // 32)
        )[: bls.G2_BYTES] + v.to_bytes(4, "big")
        inject(v, None, M.CoinMessage(coin=cid, share=junk))
