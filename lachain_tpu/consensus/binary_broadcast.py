"""BV-broadcast + AUX + CONF ("BinaryBroadcast").

Behavioral parity with the reference
(/root/reference/src/Lachain.Consensus/BinaryAgreement/BinaryBroadcast.cs):
  * BVAL relay at F+1 distinct senders, accept into bin_values at 2F+1
    (BinaryBroadcast.cs:127-159)
  * AUX broadcast when bin_values first becomes non-empty (162-177)
  * CONF of the current bin_values after N-F AUX arrive (179-195)
  * result = bin_values once N-F CONF subsets observed (216-239)
"""
from __future__ import annotations

from typing import Dict, FrozenSet, Set

from . import messages as M
from .protocol import Broadcaster, Protocol


class BinaryBroadcast(Protocol):
    def __init__(self, pid: M.BinaryBroadcastId, broadcaster: Broadcaster):
        super().__init__(pid, broadcaster)
        self._bval_recv: Dict[bool, Set[int]] = {False: set(), True: set()}
        self._bval_sent: Set[bool] = set()
        self._bin_values: Set[bool] = set()
        self._aux_recv: Dict[int, bool] = {}
        self._conf_recv: Dict[int, FrozenSet[bool]] = {}
        self._aux_broadcast = False
        self._conf_broadcast = False
        self._done = False

    # -- input: my estimate --------------------------------------------------
    def handle_input(self, value: bool) -> None:
        value = bool(value)
        if value not in self._bval_sent:
            self._bval_sent.add(value)
            self.broadcaster.broadcast(M.BValMessage(bb=self.id, value=value))

    # -- externals -----------------------------------------------------------
    def handle_external(self, sender: int, payload) -> None:
        if isinstance(payload, M.BValMessage):
            self._on_bval(sender, bool(payload.value))
        elif isinstance(payload, M.AuxMessage):
            self._on_aux(sender, bool(payload.value))
        elif isinstance(payload, M.ConfMessage):
            self._on_conf(sender, frozenset(payload.values))
        else:
            raise TypeError(f"unexpected payload {type(payload)}")

    def _on_bval(self, sender: int, v: bool) -> None:
        self._bval_recv[v].add(sender)
        cnt = len(self._bval_recv[v])
        if cnt >= self.f + 1 and v not in self._bval_sent:
            # relay: enough honest support to echo the value
            self._bval_sent.add(v)
            self.broadcaster.broadcast(M.BValMessage(bb=self.id, value=v))
        if cnt >= 2 * self.f + 1 and v not in self._bin_values:
            self._bin_values.add(v)
            if not self._aux_broadcast:
                self._aux_broadcast = True
                self.broadcaster.broadcast(M.AuxMessage(bb=self.id, value=v))
            self._progress()

    def _on_aux(self, sender: int, v: bool) -> None:
        if sender not in self._aux_recv:
            self._aux_recv[sender] = v
            self._progress()

    def _on_conf(self, sender: int, values: FrozenSet[bool]) -> None:
        if sender not in self._conf_recv:
            self._conf_recv[sender] = values
            self._progress()

    # -- state machine -------------------------------------------------------
    def _progress(self) -> None:
        if self._done:
            return
        if not self._bin_values:
            return
        if not self._conf_broadcast:
            aux_ok = sum(
                1 for v in self._aux_recv.values() if v in self._bin_values
            )
            if aux_ok >= self.n - self.f:
                self._conf_broadcast = True
                self.broadcaster.broadcast(
                    M.ConfMessage(bb=self.id, values=frozenset(self._bin_values))
                )
        if self._conf_broadcast:
            conf_ok = sum(
                1
                for vals in self._conf_recv.values()
                if vals <= self._bin_values
            )
            if conf_ok >= self.n - self.f:
                self._done = True
                self.emit_result(frozenset(self._bin_values))
