"""Device-mesh parallelism package.

:mod:`.mesh` does ``from jax import shard_map`` at import time, which only
exists on newer jax builds (older ones keep it in ``jax.experimental``,
with a different calling convention the module does not target), and its
pipelines need more than one visible device. Probe with the helpers below
before importing it — tests skip on the probe instead of erroring at
collection, and single-device hosts fall back to the host/Pallas
pipelines (crypto/tpu_backend.py).
"""
from __future__ import annotations

from typing import Optional


def shard_map_available() -> bool:
    """True when this jax build exports the top-level ``jax.shard_map``
    that :mod:`.mesh` is written against."""
    try:
        from jax import shard_map  # noqa: F401
    except ImportError:
        return False
    return True


def mesh_unsupported_reason() -> Optional[str]:
    """None when the mesh pipeline can actually run here; otherwise a
    human-readable skip reason (missing jax.shard_map export, or a
    single-device host)."""
    if not shard_map_available():
        return "this jax build has no top-level jax.shard_map export"
    import jax

    if len(jax.devices()) < 2:
        return "needs a multi-device platform"
    return None


def mesh_supported() -> bool:
    return mesh_unsupported_reason() is None
