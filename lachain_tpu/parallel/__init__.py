"""Device-mesh parallelism package.

:mod:`.mesh` is written against the top-level ``jax.shard_map`` API
(keyword mesh/in_specs/out_specs, ``check_vma``). Older jax builds keep
shard_map in ``jax.experimental.shard_map`` with a ``check_rep`` kwarg
instead; :func:`get_shard_map` papers over the difference so the mesh
pipeline runs on both. Probe with :func:`mesh_unsupported_reason` before
importing :mod:`.mesh` — tests skip on the probe instead of erroring at
collection, and single-device hosts fall back to the host/Pallas
pipelines (crypto/tpu_backend.py).
"""
from __future__ import annotations

from typing import Optional


def get_shard_map():
    """Return a ``shard_map(f, mesh=..., in_specs=..., out_specs=...,
    check_vma=...)`` callable, or None when this jax build has neither the
    top-level export nor the experimental one.

    The wrapper normalizes the two historical calling conventions:
    new-style ``jax.shard_map`` takes ``check_vma``; the experimental
    module spells the same knob ``check_rep``.
    """
    try:
        from jax import shard_map as _sm  # new-style top-level export

        return _sm
    except ImportError:
        pass
    try:
        from jax.experimental.shard_map import shard_map as _xsm
    except ImportError:
        return None

    def _compat(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _xsm(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_rep=bool(check_vma),
        )

    return _compat


def shard_map_available() -> bool:
    """True when some usable shard_map exists on this jax build (top-level
    or experimental — :mod:`.mesh` handles both via get_shard_map)."""
    return get_shard_map() is not None


def mesh_unsupported_reason() -> Optional[str]:
    """None when the mesh pipeline can actually run here; otherwise a
    human-readable skip reason (no shard_map at all, or a single-device
    host)."""
    if not shard_map_available():
        return "this jax build has no shard_map (top-level or experimental)"
    import jax

    if len(jax.devices()) < 2:
        return "needs a multi-device platform"
    return None


def mesh_supported() -> bool:
    return mesh_unsupported_reason() is None
