"""Device-mesh sharding for the batched crypto kernels.

The reference scales consensus crypto by protocol fan-out across OS threads
(SURVEY.md §2c "parallelism inventory"); the TPU-native equivalent is SPMD
over a jax.sharding.Mesh: the share axis (N validators x N slots per era) is
the data axis, sharded across devices with shard_map. Each device computes a
local MSM over its shard; the partial sums are combined with an all_gather
followed by a replicated log-tree of point additions (point addition is not
an elementwise psum-reduction, so the combine rides an explicit collective).

Multi-host scaling: the same mesh spans hosts; XLA routes the all_gather over
ICI within a pod slice and DCN across slices — this is the framework's
distributed communication backend for the crypto data plane (SURVEY.md §5
"Distributed communication backend"). Control-plane consensus messages stay
on the host network (lachain_tpu/network).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ..ops import curve


def make_mesh(n_devices: Optional[int] = None, axis: str = "shares") -> Mesh:
    """1-D mesh over the share/batch axis."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def sharded_g1_msm(mesh: Mesh, axis: str = "shares"):
    """Build a jitted MSM over the mesh: points (n,3,L), bits (n,nbits).

    n must be divisible by mesh size and the per-device shard a power of two.
    Output is replicated on every device.
    """

    def local_msm(points, bits):
        partial_sum = curve.g1_msm(points, bits)  # (3, L) local
        gathered = jax.lax.all_gather(partial_sum, axis)  # (ndev, 3, L)
        return curve.g1_reduce_sum(gathered)

    fn = shard_map(
        local_msm,
        mesh=mesh,
        in_specs=(P(axis, None, None), P(axis, None)),
        out_specs=P(),  # replicated
        check_vma=False,
    )
    return jax.jit(fn)


def sharded_g2_msm(mesh: Mesh, axis: str = "shares"):
    def local_msm(points, bits):
        partial_sum = curve.g2_msm(points, bits)  # (3, 2, L)
        gathered = jax.lax.all_gather(partial_sum, axis)
        return curve.g2_reduce_sum(gathered)

    fn = shard_map(
        local_msm,
        mesh=mesh,
        in_specs=(P(axis, None, None, None), P(axis, None)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)


def make_era_mesh(n_devices: int) -> Mesh:
    """2-D mesh for the era kernel: 'slot' = data-parallel over ACS slots,
    'share' = sequence-parallel over the within-slot share axis (the
    framework's dp x sp analog — SURVEY.md §5 maps the reference's
    protocol-thread fan-out onto exactly these two axes)."""
    devs = jax.devices()[:n_devices]
    if n_devices >= 4 and n_devices % 2 == 0:
        shape = (n_devices // 2, 2)
    else:
        shape = (n_devices, 1)
    return Mesh(np.array(devs).reshape(shape), ("slot", "share"))


def sharded_era_step(mesh: Mesh):
    """shard_map the full era kernel over a ('slot', 'share') mesh.

    Slots shard data-parallel (no cross-device traffic); the share axis
    shards within each slot, so per-device partial point-sums are combined
    with an all_gather over 'share' followed by a replicated point-add — the
    explicit-collective pattern for non-arithmetic reductions (point addition
    is not a psum).
    """
    from ..ops import verify as V
    from ..ops import curve as C

    def local_step(u, y, rlc, lag):
        u_agg, y_agg, comb = V.tpke_era_slots_step(u, y, rlc, lag)
        # (S_local, 3, L) partial sums over the local share shard
        def combine(pts):
            gathered = jax.lax.all_gather(pts, "share")  # (nshare, S_l, 3, L)
            return C.g1_reduce_sum(gathered)

        return combine(u_agg), combine(y_agg), combine(comb)

    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(
            P("slot", "share", None, None),
            P("slot", "share", None, None),
            P("slot", "share", None),
            P("slot", "share", None),
        ),
        out_specs=(
            P("slot", None, None),
            P("slot", None, None),
            P("slot", None, None),
        ),
        # outputs ARE replicated over 'share' (all_gather + identical local
        # reduce on every device) but the static varying-axes checker cannot
        # infer that through the point-add tree
        check_vma=False,
    )
    return jax.jit(fn)


def sharded_glv_era_step(mesh: Mesh):
    """shard_map the round-2 GLV/windowed era kernel (ops/msm.py) over a
    ('slot', 'share') mesh.

    Same layout as sharded_era_step: slots are data-parallel; the share axis
    shards within each slot. Each device runs the full windowed MSM over its
    local share shard (tables, window scan, local flagged tree-reduce), then
    the per-device partial sums are combined with an all_gather over 'share'
    plus a replicated flagged point-add tree — point addition is not a psum,
    so the combine is an explicit collective + local tree.
    """
    from ..ops import msm as M

    def local_step(u, y, rlc, lag1, lag2):
        pts, flags = M.tpke_era_glv_kernel(u, y, rlc, lag1, lag2)
        # (S_local, 4, 3, L) local partials + (S_local, 4) flags
        gp = jax.lax.all_gather(pts, "share")  # (nshare, S_l, 4, 3, L)
        gf = jax.lax.all_gather(flags, "share")
        return M.g1_tree_reduce_flagged(gp, gf, axis=0)

    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(
            P("slot", "share", None, None),
            P("slot", "share", None, None),
            P("slot", "share", None),
            P("slot", "share", None),
            P("slot", "share", None),
        ),
        out_specs=(
            P("slot", None, None, None),
            P("slot", None),
        ),
        check_vma=False,
    )
    return jax.jit(fn)


def pad_pow2(n: int, multiple: int) -> int:
    """Smallest power of two >= n that is divisible by `multiple`."""
    size = max(multiple, 1)
    while size < n or size % multiple:
        size *= 2
    return size


class MeshEraPipeline:
    """Multi-device era pipeline: the GLV/windowed era kernel shard_mapped
    over a ('slot', 'share') device mesh.

    Same `run_era(slots, y_points, rng, masks)` contract as the single-chip
    pipelines (ops/verify.py: GlvEraPipeline / PallasEraPipeline), selected
    by the TPU backend whenever more than one device is visible — this is
    how a pod slice (or the CI's 8 virtual CPU devices) runs the BASELINE
    N=128-class era batches: ACS slots data-parallel across the 'slot' axis,
    the within-slot share axis sequence-parallel across 'share' with an
    explicit all_gather + flagged point-add combine.
    """

    def __init__(self, backend=None, n_devices: Optional[int] = None):
        import jax

        from ..crypto.provider import get_backend

        self._backend = backend or get_backend()
        ndev = n_devices if n_devices is not None else len(jax.devices())
        self.mesh = make_era_mesh(ndev)
        self._step = sharded_glv_era_step(self.mesh)
        # era-invariant verification keys: marshal once per
        # (key set, s_pad, k_pad) — id-keyed with a strong reference, same
        # pattern as ops/verify's _TiledYCache
        self._y_cache: dict = {}
        self.calls = 0

    def _y_marshal(self, y_points, s_pad: int, k_pad: int):
        from ..crypto import bls12381 as bls
        from ..ops import msm

        key = (id(y_points), s_pad, k_pad)
        hit = self._y_cache.get(key)
        if hit is not None and hit[0] is y_points:
            return hit[1]
        k = len(y_points)
        y_np = msm.g1_to_device_loose(
            (list(y_points) + [bls.G1_INF] * (k_pad - k)) * s_pad
        ).reshape(s_pad, k_pad, 3, -1)
        if len(self._y_cache) >= 8:
            self._y_cache.pop(next(iter(self._y_cache)))
        self._y_cache[key] = (y_points, y_np)
        return y_np

    def run_era(self, slots, y_points, rng, masks=None):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..crypto import bls12381 as bls
        from ..ops import msm
        from ..ops.verify import era_rlc

        s = len(slots)
        k = len(y_points)
        rlc = era_rlc(slots, k, rng, masks)
        n_slot = self.mesh.shape["slot"]
        n_share = self.mesh.shape["share"]
        # pad the share axis to a power of two divisible by the 'share' mesh
        # axis (the in-kernel tree reduce needs pow2 groups; the shard_map
        # needs even division) and the slot axis to a multiple of 'slot'.
        # Filler lanes carry zero coefficients -> flagged-out infinity.
        k_pad = pad_pow2(k, n_share)
        s_pad = ((s + n_slot - 1) // n_slot) * n_slot
        inf = bls.G1_INF
        u_flat = []
        for u_list, _ in slots:
            u_flat.extend(list(u_list) + [inf] * (k_pad - k))
        u_flat.extend([inf] * (k_pad * (s_pad - s)))
        u_np = msm.g1_to_device_loose(u_flat).reshape(s_pad, k_pad, 3, -1)
        y_np = self._y_marshal(y_points, s_pad, k_pad)
        rlc_rows = [row + [0] * (k_pad - k) for row in rlc]
        rlc_rows += [[0] * k_pad] * (s_pad - s)
        lag_rows = [
            list(lag_list) + [0] * (k_pad - k) for _, lag_list in slots
        ]
        lag_rows += [[0] * k_pad] * (s_pad - s)
        _rlc64, rlc_d, lag1, lag2 = msm.era_digits(rlc_rows, lag_rows)
        with self.mesh:
            args = []
            for arr, spec in (
                (u_np, P("slot", "share", None, None)),
                (y_np, P("slot", "share", None, None)),
                (rlc_d, P("slot", "share", None)),
                (lag1, P("slot", "share", None)),
                (lag2, P("slot", "share", None)),
            ):
                args.append(
                    jax.device_put(
                        jnp.asarray(arr), NamedSharding(self.mesh, spec)
                    )
                )
            pts, flags = self._step(*args)
            jax.block_until_ready((pts, flags))
        pts = np.asarray(pts)
        flags = np.asarray(flags)
        self.calls += 1
        out = []
        for i in range(s):
            cols = msm.g1_from_device_loose(pts[i], flags[i])
            comb = msm.combine_or_host_msm(
                bls.g1_add(cols[2], cols[3]),
                slots[i][0],
                slots[i][1],
                self._backend,
            )
            out.append((cols[0], cols[1], comb))
        return out, rlc
