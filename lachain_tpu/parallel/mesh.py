"""Device-mesh sharding for the batched crypto kernels.

The reference scales consensus crypto by protocol fan-out across OS threads
(SURVEY.md §2c "parallelism inventory"); the TPU-native equivalent is SPMD
over a jax.sharding.Mesh: the share axis (N validators x N slots per era) is
the data axis, sharded across devices with shard_map. Each device computes a
local MSM over its shard; the partial sums are combined with an all_gather
followed by a replicated log-tree of point additions (point addition is not
an elementwise psum-reduction, so the combine rides an explicit collective).

Multi-host scaling: the same mesh spans hosts; XLA routes the all_gather over
ICI within a pod slice and DCN across slices — this is the framework's
distributed communication backend for the crypto data plane (SURVEY.md §5
"Distributed communication backend"). Control-plane consensus messages stay
on the host network (lachain_tpu/network).

shard_map is resolved through :func:`lachain_tpu.parallel.get_shard_map`,
which papers over the top-level vs jax.experimental calling conventions;
importing this module raises ImportError on jax builds with neither.
"""
from __future__ import annotations

import logging
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from . import get_shard_map
from ..ops import curve
from ..utils import metrics, tracing

shard_map = get_shard_map()
if shard_map is None:  # pragma: no cover - guarded by mesh_unsupported_reason
    raise ImportError("this jax build has no shard_map (top-level or experimental)")

logger = logging.getLogger("lachain.mesh")


def make_mesh(n_devices: Optional[int] = None, axis: str = "shares") -> Mesh:
    """1-D mesh over the share/batch axis."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def sharded_g1_msm(mesh: Mesh, axis: str = "shares"):
    """Build a jitted MSM over the mesh: points (n,3,L), bits (n,nbits).

    n must be divisible by mesh size and the per-device shard a power of two.
    Output is replicated on every device.
    """

    def local_msm(points, bits):
        partial_sum = curve.g1_msm(points, bits)  # (3, L) local
        gathered = jax.lax.all_gather(partial_sum, axis)  # (ndev, 3, L)
        return curve.g1_reduce_sum(gathered)

    fn = shard_map(
        local_msm,
        mesh=mesh,
        in_specs=(P(axis, None, None), P(axis, None)),
        out_specs=P(),  # replicated
        check_vma=False,
    )
    return jax.jit(fn)


def sharded_g2_msm(mesh: Mesh, axis: str = "shares"):
    def local_msm(points, bits):
        partial_sum = curve.g2_msm(points, bits)  # (3, 2, L)
        gathered = jax.lax.all_gather(partial_sum, axis)
        return curve.g2_reduce_sum(gathered)

    fn = shard_map(
        local_msm,
        mesh=mesh,
        in_specs=(P(axis, None, None, None), P(axis, None)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)


def make_era_mesh(n_devices: int) -> Mesh:
    """2-D mesh for the era kernel: 'slot' = data-parallel over ACS slots,
    'share' = sequence-parallel over the within-slot share axis (the
    framework's dp x sp analog — SURVEY.md §5 maps the reference's
    protocol-thread fan-out onto exactly these two axes)."""
    devs = jax.devices()[:n_devices]
    if n_devices >= 4 and n_devices % 2 == 0:
        shape = (n_devices // 2, 2)
    else:
        shape = (n_devices, 1)
    return Mesh(np.array(devs).reshape(shape), ("slot", "share"))


def sharded_era_step(mesh: Mesh):
    """shard_map the full era kernel over a ('slot', 'share') mesh.

    Slots shard data-parallel (no cross-device traffic); the share axis
    shards within each slot, so per-device partial point-sums are combined
    with an all_gather over 'share' followed by a replicated point-add — the
    explicit-collective pattern for non-arithmetic reductions (point addition
    is not a psum).
    """
    from ..ops import verify as V
    from ..ops import curve as C

    def local_step(u, y, rlc, lag):
        u_agg, y_agg, comb = V.tpke_era_slots_step(u, y, rlc, lag)
        # (S_local, 3, L) partial sums over the local share shard
        def combine(pts):
            gathered = jax.lax.all_gather(pts, "share")  # (nshare, S_l, 3, L)
            return C.g1_reduce_sum(gathered)

        return combine(u_agg), combine(y_agg), combine(comb)

    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(
            P("slot", "share", None, None),
            P("slot", "share", None, None),
            P("slot", "share", None),
            P("slot", "share", None),
        ),
        out_specs=(
            P("slot", None, None),
            P("slot", None, None),
            P("slot", None, None),
        ),
        # outputs ARE replicated over 'share' (all_gather + identical local
        # reduce on every device) but the static varying-axes checker cannot
        # infer that through the point-add tree
        check_vma=False,
    )
    return jax.jit(fn)


def sharded_glv_era_step(mesh: Mesh):
    """shard_map the round-2 GLV/windowed era kernel (ops/msm.py) over a
    ('slot', 'share') mesh.

    Same layout as sharded_era_step: slots are data-parallel; the share axis
    shards within each slot. Each device runs the full windowed MSM over its
    local share shard (tables, window scan, local flagged tree-reduce), then
    the per-device partial sums are combined with an all_gather over 'share'
    plus a replicated flagged point-add tree — point addition is not a psum,
    so the combine is an explicit collective + local tree.
    """
    from ..ops import msm as M

    def local_step(u, y, rlc, lag1, lag2):
        pts, flags = M.tpke_era_glv_kernel(u, y, rlc, lag1, lag2)
        # (S_local, 4, 3, L) local partials + (S_local, 4) flags
        gp = jax.lax.all_gather(pts, "share")  # (nshare, S_l, 4, 3, L)
        gf = jax.lax.all_gather(flags, "share")
        return M.g1_tree_reduce_flagged(gp, gf, axis=0)

    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(
            P("slot", "share", None, None),
            P("slot", "share", None, None),
            P("slot", "share", None),
            P("slot", "share", None),
            P("slot", "share", None),
        ),
        out_specs=(
            P("slot", None, None, None),
            P("slot", None),
        ),
        check_vma=False,
    )
    return jax.jit(fn)


def pad_pow2(n: int, multiple: int) -> int:
    """Smallest power of two >= n that is divisible by `multiple`."""
    size = max(multiple, 1)
    while size < n or size % multiple:
        size *= 2
    return size


class _EraStaging:
    """Preallocated host marshal buffers for one padded (s_pad, k_pad) grid.

    Filler lanes carry the device encoding of infinity in `u` and zero
    digits in the coefficient planes; `fill()` writes only the live
    [:s, :k] region and re-cleans whatever a PREVIOUS era with a larger
    live region left behind, so per-era work is proportional to live lanes
    instead of the padded grid."""

    __slots__ = ("u", "rlc", "lag1", "lag2", "_inf_row", "_filled")

    def __init__(self, s_pad: int, k_pad: int, inf_row: np.ndarray, w128: int):
        self._inf_row = inf_row  # (3, L) loose-Montgomery infinity
        self.u = np.broadcast_to(
            inf_row, (s_pad, k_pad) + inf_row.shape
        ).copy()
        self.rlc = np.zeros((s_pad, k_pad, w128), dtype=np.int32)
        self.lag1 = np.zeros((s_pad, k_pad, w128), dtype=np.int32)
        self.lag2 = np.zeros((s_pad, k_pad, w128), dtype=np.int32)
        self._filled = (0, 0)

    def clean(self, s: int, k: int) -> None:
        fs, fk = self._filled
        if fs > s:
            self.u[s:fs, :fk] = self._inf_row
            self.rlc[s:fs, :fk] = 0
            self.lag1[s:fs, :fk] = 0
            self.lag2[s:fs, :fk] = 0
        if fk > k:
            top = min(fs, s)
            self.u[:top, k:fk] = self._inf_row
            self.rlc[:top, k:fk] = 0
            self.lag1[:top, k:fk] = 0
            self.lag2[:top, k:fk] = 0
        self._filled = (s, k)


class _LagDigitCache:
    """Digit planes for Lagrange coefficient rows, keyed by the row values.

    A fixed signer set reuses the same Lagrange row across every slot of
    every era, so the glv_split + digit decomposition (the one remaining
    per-value Python loop in the era marshal) amortizes to a dict lookup."""

    def __init__(self, limit: int = 128):
        self._cache: dict = {}
        self._limit = limit

    def get(self, row) -> tuple:
        from ..ops import msm

        key = tuple(row)
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        halves = [msm.glv_split(v) for v in row]
        planes = (
            msm.scalars_to_digits([h[0] for h in halves], msm.W128),
            msm.scalars_to_digits([h[1] for h in halves], msm.W128),
        )
        if len(self._cache) >= self._limit:
            self._cache.pop(next(iter(self._cache)))
        self._cache[key] = planes
        return planes


class MeshEraPipeline:
    """Multi-device era pipeline: the GLV/windowed era kernel shard_mapped
    over a ('slot', 'share') device mesh.

    Same `run_era(slots, y_points, rng, masks)` contract as the single-chip
    pipelines (ops/verify.py: GlvEraPipeline / PallasEraPipeline), selected
    by the TPU backend whenever more than one device is visible — this is
    how a pod slice (or the CI's 8 virtual CPU devices) runs the BASELINE
    N=128-class era batches: ACS slots data-parallel across the 'slot' axis,
    the within-slot share axis sequence-parallel across 'share' with an
    explicit all_gather + flagged point-add combine.

    `dispatch_era` is the async half of the same contract: it does the host
    marshal + device_put + kernel dispatch and returns a `finish()` closure
    that blocks on the result — callers (crypto_batcher) overlap chunk
    e+1's host marshal with chunk e's sharded kernel. At most TWO dispatches
    may be in flight per pipeline: the host staging is double-buffered, and
    a third dispatch would overwrite the buffer a still-running kernel's
    device_put may alias on single-device meshes.
    """

    MAX_INFLIGHT = 2

    def __init__(self, backend=None, n_devices: Optional[int] = None):
        from ..crypto.provider import get_backend
        from ..crypto import bls12381 as bls
        from ..ops import msm

        self._backend = backend or get_backend()
        ndev = n_devices if n_devices is not None else len(jax.devices())
        self.mesh = make_era_mesh(ndev)
        self.n_devices = int(self.mesh.devices.size)
        self._step = sharded_glv_era_step(self.mesh)
        # era-invariant verification keys: marshal + device_put once per
        # (key set, s_pad, k_pad) — id-keyed with a strong reference, same
        # pattern as ops/verify's _TiledYCache
        self._y_cache: dict = {}
        self._lag_cache = _LagDigitCache()
        # double-buffered staging per padded shape (see class docstring)
        self._staging: dict = {}
        self._inf_row = np.ascontiguousarray(
            msm.g1_to_device_loose([bls.G1_INF])[0]
        )
        self._seen_shapes: set = set()
        self.calls = 0
        # device-busy accounting for utilization reporting: seconds between
        # kernel dispatch and result-ready, summed over calls
        self.device_busy_s = 0.0
        self.allgather_mb = 0.0

    def padded_shape(self, s: int, k: int) -> tuple:
        """(s_pad, k_pad) the mesh will run for a live (s, k) era grid —
        the warmup uses this to dedupe tiers that collapse onto one padded
        kernel shape."""
        n_slot = self.mesh.shape["slot"]
        n_share = self.mesh.shape["share"]
        k_pad = pad_pow2(k, n_share)
        s_pad = ((s + n_slot - 1) // n_slot) * n_slot
        return s_pad, k_pad

    def _get_staging(self, s_pad: int, k_pad: int) -> _EraStaging:
        from ..ops import msm

        bufs = self._staging.get((s_pad, k_pad))
        if bufs is None:
            bufs = [
                [
                    _EraStaging(s_pad, k_pad, self._inf_row, msm.W128)
                    for _ in range(2)
                ],
                0,
            ]
            if len(self._staging) >= 8:
                self._staging.pop(next(iter(self._staging)))
            self._staging[(s_pad, k_pad)] = bufs
        pair, flip = bufs
        bufs[1] = flip + 1
        return pair[flip % 2]

    def _y_device(self, y_points, s_pad: int, k_pad: int):
        """Sharded device array for the verification-key grid: era-invariant
        for a fixed validator set, so both the host marshal AND the
        device_put are cached (the old path re-uploaded every era)."""
        from jax.sharding import NamedSharding

        from ..crypto import bls12381 as bls
        from ..ops import msm

        key = (id(y_points), s_pad, k_pad)
        hit = self._y_cache.get(key)
        if hit is not None and hit[0] is y_points:
            return hit[1]
        k = len(y_points)
        y_np = msm.g1_to_device_loose(
            (list(y_points) + [bls.G1_INF] * (k_pad - k)) * s_pad
        ).reshape(s_pad, k_pad, 3, -1)
        y_dev = jax.device_put(
            jnp.asarray(y_np),
            NamedSharding(self.mesh, P("slot", "share", None, None)),
        )
        if len(self._y_cache) >= 8:
            self._y_cache.pop(next(iter(self._y_cache)))
        self._y_cache[key] = (y_points, y_dev)
        return y_dev

    def _allgather_mb(self, s_pad: int) -> float:
        """Bytes the 'share' all_gather moves across the mesh for one call
        (statically computable from the padded shape): every device receives
        the other share-shards' (S_local, 4, 3, L) partials + flags."""
        from ..ops import fpl

        n_slot = self.mesh.shape["slot"]
        n_share = self.mesh.shape["share"]
        s_local = s_pad // n_slot
        shard_bytes = s_local * 4 * (3 * fpl.NLIMBS * 4 + 4)
        return self.n_devices * (n_share - 1) * shard_bytes / 1e6

    def dispatch_era(self, slots, y_points, rng, masks=None):
        """Async half of run_era: marshal + device_put + kernel dispatch,
        returning a finish() closure that blocks and decodes. See the class
        docstring for the MAX_INFLIGHT=2 double-buffer contract."""
        from jax.sharding import NamedSharding

        from ..crypto import bls12381 as bls
        from ..crypto import kernel_cache
        from ..ops import msm
        from ..ops.verify import era_rlc

        s = len(slots)
        k = len(y_points)
        rlc = era_rlc(slots, k, rng, masks)
        s_pad, k_pad = self.padded_shape(s, k)
        waste = 1.0 - (s * k) / float(s_pad * k_pad)
        metrics.set_gauge("mesh_devices", self.n_devices)
        metrics.set_gauge("mesh_pad_waste_fraction", round(waste, 4))
        if (s_pad, k_pad) not in self._seen_shapes:
            self._seen_shapes.add((s_pad, k_pad))
            logger.info(
                "mesh era shape (s=%d,k=%d) -> padded (%d,%d) on %s: "
                "pad waste %.1f%%",
                s, k, s_pad, k_pad, dict(self.mesh.shape), 100.0 * waste,
            )

        with tracing.span(
            "mesh.marshal", cat="crypto", s=s, k=k, s_pad=s_pad, k_pad=k_pad
        ):
            stage = self._get_staging(s_pad, k_pad)
            stage.clean(s, k)
            # live points in one vectorized batch-inversion conversion;
            # filler lanes keep the prefilled infinity encoding
            u_all = [u for u_list, _ in slots for u in u_list]
            stage.u[:s, :k] = msm.g1_to_device_loose(u_all).reshape(
                s, k, 3, -1
            )
            # RLC digits: one byte-decomposition over all S*K coefficients,
            # embedded in the top W64 of W128 windows (era_digits layout)
            rlc64 = msm.scalars_to_digits(
                [c for row in rlc for c in row], msm.W64
            ).reshape(s, k, msm.W64)
            stage.rlc[:s, :k, : msm.W128 - msm.W64] = 0
            stage.rlc[:s, :k, msm.W128 - msm.W64 :] = rlc64
            # Lagrange digit planes: cached per coefficient row (fixed
            # signer sets repeat the same row across slots and eras)
            for i, (_, lag_list) in enumerate(slots):
                l1, l2 = self._lag_cache.get(lag_list)
                stage.lag1[i, :k] = l1
                stage.lag2[i, :k] = l2
            y_dev = self._y_device(y_points, s_pad, k_pad)

        ag_mb = self._allgather_mb(s_pad)
        with self.mesh:
            spec_pts = P("slot", "share", None, None)
            spec_dig = P("slot", "share", None)
            args = [
                jax.device_put(
                    jnp.asarray(arr), NamedSharding(self.mesh, spec)
                )
                for arr, spec in (
                    (stage.u, spec_pts),
                    (stage.rlc, spec_dig),
                    (stage.lag1, spec_dig),
                    (stage.lag2, spec_dig),
                )
            ]
            sid = tracing.begin(
                "mesh.device",
                cat="crypto",
                devices=self.n_devices,
                s_pad=s_pad,
                k_pad=k_pad,
                allgather_mb=round(ag_mb, 3),
            )
            t_dispatch = metrics.monotonic()
            pts, flags = kernel_cache.call_mesh(
                self._step,
                "mesh_glv_era",
                self.mesh,
                args[0],
                y_dev,
                args[1],
                args[2],
                args[3],
            )
        self.calls += 1

        def finish():
            with tracing.wait("device", devices=self.n_devices):
                jax.block_until_ready((pts, flags))
            busy = metrics.monotonic() - t_dispatch
            tracing.end(sid)
            self.device_busy_s += busy
            self.allgather_mb += ag_mb
            p = np.asarray(pts)
            f = np.asarray(flags)
            out = []
            for i in range(s):
                cols = msm.g1_from_device_loose(p[i], f[i])
                comb = msm.combine_or_host_msm(
                    bls.g1_add(cols[2], cols[3]),
                    slots[i][0],
                    slots[i][1],
                    self._backend,
                )
                out.append((cols[0], cols[1], comb))
            return out, rlc

        return finish

    def run_era(self, slots, y_points, rng, masks=None):
        return self.dispatch_era(slots, y_points, rng, masks=masks)()
