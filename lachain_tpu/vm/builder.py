"""Programmatic WASM module assembler.

The framework ships no external WASM toolchain, so contracts used by tests,
fixtures, and the VM benchmark are assembled with this builder (the reference
instead checks in pre-compiled .wasm fixtures,
/root/reference/test/Lachain.CoreTest/Resources/).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

# F32/F64/I32/I64 are re-exported: tests and contract builders import
# the valtype constants from here alongside ModuleBuilder
from .wasm import F32, F64, I32, I64, WASM_MAGIC, WASM_VERSION  # noqa: F401

Body = Union[bytes, Sequence[Union[int, bytes]]]


def uleb(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def sleb(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if (v == 0 and not b & 0x40) or (v == -1 and b & 0x40):
            out.append(b)
            return bytes(out)
        out.append(b | 0x80)


def _flatten(body: Body) -> bytes:
    if isinstance(body, (bytes, bytearray)):
        return bytes(body)
    out = bytearray()
    for item in body:
        if isinstance(item, int):
            out.append(item)
        else:
            out.extend(item)
    return bytes(out)


class Op:
    """Instruction emitters (immediates LEB-encoded)."""

    unreachable = b"\x00"
    nop = b"\x01"
    else_ = b"\x05"
    end = b"\x0b"
    return_ = b"\x0f"
    drop = b"\x1a"
    select = b"\x1b"
    memory_size = b"\x3f\x00"
    memory_grow = b"\x40\x00"

    @staticmethod
    def block(result_type: Optional[int] = None) -> bytes:
        return bytes([0x02, result_type if result_type else 0x40])

    @staticmethod
    def loop(result_type: Optional[int] = None) -> bytes:
        return bytes([0x03, result_type if result_type else 0x40])

    @staticmethod
    def if_(result_type: Optional[int] = None) -> bytes:
        return bytes([0x04, result_type if result_type else 0x40])

    @staticmethod
    def br(depth: int) -> bytes:
        return b"\x0c" + uleb(depth)

    @staticmethod
    def br_if(depth: int) -> bytes:
        return b"\x0d" + uleb(depth)

    @staticmethod
    def br_table(targets: Sequence[int], default: int) -> bytes:
        out = b"\x0e" + uleb(len(targets))
        for t in targets:
            out += uleb(t)
        return out + uleb(default)

    @staticmethod
    def call(func_idx: int) -> bytes:
        return b"\x10" + uleb(func_idx)

    @staticmethod
    def call_indirect(type_idx: int) -> bytes:
        return b"\x11" + uleb(type_idx) + b"\x00"

    @staticmethod
    def local_get(i: int) -> bytes:
        return b"\x20" + uleb(i)

    @staticmethod
    def local_set(i: int) -> bytes:
        return b"\x21" + uleb(i)

    @staticmethod
    def local_tee(i: int) -> bytes:
        return b"\x22" + uleb(i)

    @staticmethod
    def global_get(i: int) -> bytes:
        return b"\x23" + uleb(i)

    @staticmethod
    def global_set(i: int) -> bytes:
        return b"\x24" + uleb(i)

    @staticmethod
    def i32_load(offset: int = 0, align: int = 2) -> bytes:
        return b"\x28" + uleb(align) + uleb(offset)

    @staticmethod
    def i64_load(offset: int = 0, align: int = 3) -> bytes:
        return b"\x29" + uleb(align) + uleb(offset)

    @staticmethod
    def i32_load8_u(offset: int = 0) -> bytes:
        return b"\x2d\x00" + uleb(offset)

    @staticmethod
    def i32_store(offset: int = 0, align: int = 2) -> bytes:
        return b"\x36" + uleb(align) + uleb(offset)

    @staticmethod
    def i64_store(offset: int = 0, align: int = 3) -> bytes:
        return b"\x37" + uleb(align) + uleb(offset)

    @staticmethod
    def i32_store8(offset: int = 0) -> bytes:
        return b"\x3a\x00" + uleb(offset)

    @staticmethod
    def i32_const(v: int) -> bytes:
        return b"\x41" + sleb(v)

    @staticmethod
    def i64_const(v: int) -> bytes:
        return b"\x42" + sleb(v)

    # common numeric shorthands
    i32_eqz = b"\x45"
    i32_eq = b"\x46"
    i32_ne = b"\x47"
    i32_lt_s = b"\x48"
    i32_lt_u = b"\x49"
    i32_gt_u = b"\x4b"
    i32_ge_u = b"\x4f"
    i32_add = b"\x6a"
    i32_sub = b"\x6b"
    i32_mul = b"\x6c"
    i32_div_u = b"\x6e"
    i32_rem_u = b"\x70"
    i32_and = b"\x71"
    i32_or = b"\x72"
    i32_xor = b"\x73"
    i32_shl = b"\x74"
    i32_shr_u = b"\x76"
    i64_add = b"\x7c"
    i64_sub = b"\x7d"
    i64_mul = b"\x7e"
    i64_eq = b"\x51"
    i64_lt_u = b"\x54"
    i64_ge_u = b"\x5a"
    i32_wrap_i64 = b"\xa7"
    i64_extend_i32_u = b"\xad"


class ModuleBuilder:
    def __init__(self):
        self.types: List[Tuple[Tuple[int, ...], Tuple[int, ...]]] = []
        self.imports: List[Tuple[str, str, int]] = []  # (mod, name, type_idx)
        self.funcs: List[Tuple[int, List[int], bytes]] = []
        self.exports: List[Tuple[str, int, int]] = []
        self.mem: Optional[Tuple[int, Optional[int]]] = None
        self.globals: List[Tuple[int, bool, bytes]] = []
        self.data: List[Tuple[int, bytes]] = []
        self.table_elems: List[int] = []
        self.start: Optional[int] = None

    def type_idx(self, params: Sequence[int], results: Sequence[int]) -> int:
        key = (tuple(params), tuple(results))
        if key in self.types:
            return self.types.index(key)
        self.types.append(key)
        return len(self.types) - 1

    def add_import(
        self, module: str, name: str, params: Sequence[int], results: Sequence[int]
    ) -> int:
        if self.funcs:
            raise ValueError("imports must be added before functions")
        ti = self.type_idx(params, results)
        self.imports.append((module, name, ti))
        return len(self.imports) - 1

    def add_function(
        self,
        params: Sequence[int],
        results: Sequence[int],
        locals_: Sequence[int],
        body: Body,
        export: Optional[str] = None,
    ) -> int:
        """Body must NOT include the trailing `end` — it is appended."""
        ti = self.type_idx(params, results)
        idx = len(self.imports) + len(self.funcs)
        self.funcs.append((ti, list(locals_), _flatten(body) + Op.end))
        if export:
            self.exports.append((export, 0, idx))
        return idx

    def add_memory(self, min_pages: int, max_pages: Optional[int] = None) -> None:
        self.mem = (min_pages, max_pages)

    def add_global(self, valtype: int, mutable: bool, init: Body) -> int:
        self.globals.append((valtype, mutable, _flatten(init) + Op.end))
        return len(self.globals) - 1

    def add_data(self, offset: int, data: bytes) -> None:
        self.data.append((offset, data))

    def add_table_funcs(self, func_indices: Sequence[int]) -> None:
        self.table_elems.extend(func_indices)

    def build(self) -> bytes:
        def section(sid: int, payload: bytes) -> bytes:
            return bytes([sid]) + uleb(len(payload)) + payload

        out = WASM_MAGIC + WASM_VERSION
        # types
        p = uleb(len(self.types))
        for params, results in self.types:
            p += b"\x60" + uleb(len(params)) + bytes(params)
            p += uleb(len(results)) + bytes(results)
        out += section(1, p)
        # imports
        if self.imports:
            p = uleb(len(self.imports))
            for mod, name, ti in self.imports:
                mb, nb = mod.encode(), name.encode()
                p += uleb(len(mb)) + mb + uleb(len(nb)) + nb + b"\x00" + uleb(ti)
            out += section(2, p)
        # functions
        p = uleb(len(self.funcs))
        for ti, _, _ in self.funcs:
            p += uleb(ti)
        out += section(3, p)
        # table
        if self.table_elems:
            out += section(4, uleb(1) + b"\x70\x00" + uleb(len(self.table_elems)))
        # memory
        if self.mem is not None:
            lo, hi = self.mem
            p = uleb(1) + (b"\x01" + uleb(lo) + uleb(hi) if hi is not None else b"\x00" + uleb(lo))
            out += section(5, p)
        # globals
        if self.globals:
            p = uleb(len(self.globals))
            for vt, mut, init in self.globals:
                p += bytes([vt, 1 if mut else 0]) + init
            out += section(6, p)
        # exports
        if self.exports:
            p = uleb(len(self.exports))
            for name, kind, idx in self.exports:
                nb = name.encode()
                p += uleb(len(nb)) + nb + bytes([kind]) + uleb(idx)
            out += section(7, p)
        # start
        if self.start is not None:
            out += section(8, uleb(self.start))
        # elements
        if self.table_elems:
            p = uleb(1) + uleb(0) + Op.i32_const(0) + Op.end
            p += uleb(len(self.table_elems))
            for fi in self.table_elems:
                p += uleb(fi)
            out += section(9, p)
        # code
        p = uleb(len(self.funcs))
        for _, locals_, body in self.funcs:
            # group consecutive equal local types
            groups: List[Tuple[int, int]] = []
            for vt in locals_:
                if groups and groups[-1][1] == vt:
                    groups[-1] = (groups[-1][0] + 1, vt)
                else:
                    groups.append((1, vt))
            lp = uleb(len(groups))
            for cnt, vt in groups:
                lp += uleb(cnt) + bytes([vt])
            fb = lp + body
            p += uleb(len(fb)) + fb
        out += section(10, p)
        # data
        if self.data:
            p = uleb(len(self.data))
            for off, d in self.data:
                p += uleb(0) + Op.i32_const(off) + Op.end + uleb(len(d)) + d
            out += section(11, p)
        return out
