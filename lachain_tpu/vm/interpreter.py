"""WASM MVP interpreter with per-instruction gas metering.

The execution engine behind `VirtualMachine` — the role the
dotnet-webassembly submodule plays for the reference
(/root/reference/src/Lachain.Core/Blockchain/VM/VirtualMachine.cs:33-60).
Gas is charged per executed instruction plus host-call costs
(reference GasMetering.cs charges per host op; per-instruction metering here
replaces the engine's compiled-code injection).

Values: i32/i64 are canonical unsigned Python ints; f32/f64 Python floats
(f32 results rounded through single precision).

Float determinism rule: every NaN entering the value domain (loads,
reinterprets, f32 rounding) is canonicalized to the positive quiet NaN with
zero payload, so NaN bit patterns observable by contracts are identical on
every node regardless of host FP hardware. The reference relies on the .NET
JIT's platform behavior here (VirtualMachine.cs:33-60); we make the rule
explicit.
"""
from __future__ import annotations

import math
import os as _os_module
import struct

_ENV_GET = _os_module.environ.get
from typing import Callable, Dict, List, Optional, Tuple

from .wasm import (
    BLOCK_EMPTY,
    FuncType,
    Function,
    I32,
    I64,
    Module,
    PAGE_SIZE,
    WasmDecodeError,
)

MASK32 = 0xFFFFFFFF
MASK64 = 0xFFFFFFFFFFFFFFFF
MAX_CALL_DEPTH = 512
MAX_MEMORY_PAGES = 1024  # 64 MiB hard cap for contracts
MAX_TABLE_SIZE = 65_536  # funcref table cap at instantiation

# Gas schedule. The reference meters compiled WASM where one instruction is
# ~ns scale. Round 2 set 2_000 gas/op because the interpreter dispatches at
# ~2e6 ops/s; the round-3 translator tier (vm/translate.py) executes at
# >3e7 ops/s, so the schedule drops 10x: 200 gas/op bounds a full block
# (1e11 gas) to ~5e8 translated steps — the same seconds-scale wall-clock
# budget as before, with 10x the contract compute per block. The
# interpreter remains the fallback tier for untranslatable functions and
# the differential-testing oracle.
INSTRUCTION_GAS = 200
# untranslatable functions execute on the interpreter at ~1/16 the speed;
# they are billed at the round-2 rate so deliberately untranslatable
# bytecode cannot stretch a block's wall-clock budget. The rate is a pure
# function of the bytecode (translatability), NOT of the tier a node
# happens to execute — a node forced onto the interpreter by
# LACHAIN_TPU_WASM=interp still bills translatable code at the fast rate.
INTERP_INSTRUCTION_GAS = 2_000
MEMORY_GROW_GAS_PER_PAGE = 1_000_000  # priced near storage, not near free
BULK_MEMORY_GAS_PER_BYTE = 10


class WasmTrap(Exception):
    pass


class OutOfGas(WasmTrap):
    pass


class GasMeter:
    __slots__ = ("limit", "spent")

    def __init__(self, limit: int):
        self.limit = limit
        self.spent = 0

    def charge(self, amount: int) -> None:
        self.spent += amount
        if self.spent > self.limit:
            # clamp so callers can never observe (and bill) more gas than the
            # tx's up-front-verified limit, even when a host import charges a
            # large attacker-controlled amount in one step
            self.spent = self.limit
            raise OutOfGas(f"out of gas (limit {self.limit})")

    @property
    def remaining(self) -> int:
        return max(0, self.limit - self.spent)


def _s32(v: int) -> int:
    return v - (1 << 32) if v & 0x80000000 else v


def _s64(v: int) -> int:
    return v - (1 << 64) if v & 0x8000000000000000 else v


_CANON_NAN = struct.unpack("<d", b"\x00\x00\x00\x00\x00\x00\xf8\x7f")[0]


def _canon(v: float) -> float:
    """Consensus determinism rule: every NaN that enters the value domain is
    replaced by the positive quiet NaN with zero payload. NaN payload
    propagation through host FP hardware is platform-dependent; contracts
    could otherwise observe differing bit patterns via reinterpret/store and
    diverge the state hash across nodes."""
    return _CANON_NAN if v != v else v


def _f32(v: float) -> float:
    """Round through single precision (canonicalizing NaNs)."""
    if v != v:
        return _CANON_NAN
    return struct.unpack("<f", struct.pack("<f", v))[0]


def _clz(v: int, bits: int) -> int:
    if v == 0:
        return bits
    return bits - v.bit_length()


def _ctz(v: int, bits: int) -> int:
    if v == 0:
        return bits
    return (v & -v).bit_length() - 1


def _rotl(v: int, n: int, bits: int) -> int:
    n %= bits
    mask = (1 << bits) - 1
    return ((v << n) | (v >> (bits - n))) & mask


def _trunc(f: float, lo: int, hi: int, signed: bool, bits: int) -> int:
    if math.isnan(f) or math.isinf(f):
        raise WasmTrap("invalid conversion to integer")
    t = math.trunc(f)
    if t < lo or t > hi:
        raise WasmTrap("integer overflow in truncation")
    return t & ((1 << bits) - 1)


def _trunc_sat(f: float, lo: int, hi: int, bits: int) -> int:
    if math.isnan(f):
        return 0
    t = math.trunc(max(lo, min(hi, f))) if not math.isinf(f) else (lo if f < 0 else hi)
    return t & ((1 << bits) - 1)


def _nearest(f: float) -> float:
    """Round-to-nearest, ties to even."""
    if math.isnan(f) or math.isinf(f):
        return f
    fl = math.floor(f)
    diff = f - fl
    if diff < 0.5:
        return float(fl)
    if diff > 0.5:
        return float(fl + 1)
    return float(fl if fl % 2 == 0 else fl + 1)


def _build_sidetable(body: List[tuple]) -> Tuple[Dict[int, int], Dict[int, int]]:
    """Map each block/loop/if pc -> matching end pc (and if pc -> else pc)."""
    end_of: Dict[int, int] = {}
    else_of: Dict[int, int] = {}
    stack: List[int] = []
    for pc, ins in enumerate(body):
        op = ins[0]
        if op in (0x02, 0x03, 0x04):
            stack.append(pc)
        elif op == 0x05:
            if not stack:
                raise WasmDecodeError("else outside if")
            else_of[stack[-1]] = pc
        elif op == 0x0B:
            if stack:
                end_of[stack.pop()] = pc
            # else: the function's closing end
    if stack:
        raise WasmDecodeError("unbalanced blocks")
    return end_of, else_of


HostFunc = Callable[..., object]


class Instance:
    """An instantiated module: memory, globals, tables, host imports."""

    def __init__(
        self,
        module: Module,
        host: Optional[Dict[Tuple[str, str], HostFunc]] = None,
        gas: Optional[GasMeter] = None,
    ):
        self.module = module
        self.gas = gas or GasMeter(1 << 62)
        # per-instruction gas multiplier for TRANSLATABLE code: 1 once the
        # fast_wasm_gas hardfork is active, 10 below its height (the
        # round-2 schedule). Set by the VM from the block height; bulk/
        # memory/input gas is unaffected (those prices never changed).
        self.tgas_scale = 1
        self.host = host or {}
        self._imported_funcs: List[Tuple[FuncType, HostFunc]] = []
        for im in module.imports:
            if im.kind == 0:
                fn = self.host.get((im.module, im.name))
                if fn is None:
                    raise WasmTrap(f"unresolved import {im.module}.{im.name}")
                self._imported_funcs.append((module.types[im.type_idx], fn))
            elif im.kind in (1, 2, 3):
                raise WasmTrap("only function imports are supported")
        # memory
        self.memory = bytearray()
        self.mem_pages = 0
        self.mem_max = MAX_MEMORY_PAGES
        if module.mem_limits is not None:
            lo, hi = module.mem_limits
            if lo > MAX_MEMORY_PAGES:
                raise WasmTrap("initial memory too large")
            self.mem_pages = lo
            self.memory = bytearray(lo * PAGE_SIZE)
            if hi is not None:
                self.mem_max = min(hi, MAX_MEMORY_PAGES)
        # globals
        self.globals: List[object] = [
            self._eval_const(g.init) for g in module.globals
        ]
        # tables
        self.table: List[Optional[int]] = []
        if module.tables:
            lo, hi = module.tables[0]
            if lo > MAX_TABLE_SIZE:
                raise WasmTrap("table too large")
            self.table = [None] * lo
        for seg in module.elements:
            off = self._eval_const(seg.offset_expr)
            if not isinstance(off, int):
                raise WasmTrap("bad element offset")
            if off + len(seg.func_indices) > MAX_TABLE_SIZE:
                raise WasmTrap("element segment exceeds table cap")
            if off + len(seg.func_indices) > len(self.table):
                self.table.extend(
                    [None] * (off + len(seg.func_indices) - len(self.table))
                )
            for i, fi in enumerate(seg.func_indices):
                self.table[off + i] = fi
        # data segments
        for seg in module.data:
            off = self._eval_const(seg.offset_expr)
            if not isinstance(off, int):
                raise WasmTrap("bad data offset")
            if off + len(seg.data) > len(self.memory):
                raise WasmTrap("data segment out of bounds")
            self.memory[off : off + len(seg.data)] = seg.data
        self._depth = 0
        if module.start is not None:
            self.call_index(module.start, [])

    def _eval_const(self, expr: List[tuple]):
        """Init expressions: single const or global.get followed by end."""
        if not expr or expr[-1][0] != 0x0B:
            raise WasmTrap("bad init expression")
        ins = expr[0]
        op = ins[0]
        if op == 0x41:
            return ins[1] & MASK32
        if op == 0x42:
            return ins[1] & MASK64
        if op == 0x43:
            return _canon(struct.unpack("<f", ins[1])[0])
        if op == 0x44:
            return _canon(struct.unpack("<d", ins[1])[0])
        if op == 0x23:
            return self.globals[ins[1]]
        raise WasmTrap("unsupported init expression")

    # -- public API ---------------------------------------------------------

    def invoke(self, export_name: str, args: List[object]) -> Optional[object]:
        exp = self.module.export_map().get(export_name)
        if exp is None or exp.kind != 0:
            raise WasmTrap(f"no exported function {export_name!r}")
        return self.call_index(exp.index, args)

    def call_index(self, func_idx: int, args: List[object]) -> Optional[object]:
        n_imp = self.module.num_imported_funcs
        if func_idx < n_imp:
            ftype, fn = self._imported_funcs[func_idx]
            res = fn(*args)
            if ftype.results and res is None:
                raise WasmTrap("host function returned no value")
            return res if ftype.results else None
        fn_def = self.module.functions[func_idx - n_imp]
        ftype = self.module.types[fn_def.type_idx]
        if len(args) != len(ftype.params):
            raise WasmTrap("argument count mismatch")
        self._depth += 1
        if self._depth > MAX_CALL_DEPTH:
            self._depth -= 1
            raise WasmTrap("call stack exhausted")
        try:
            compiled = self._compiled_for(fn_def, ftype)
            if compiled is not False:
                res = compiled(self, *args)
                return res if ftype.results else None
            return self._exec(fn_def, ftype, list(args))
        finally:
            self._depth -= 1

    def _compiled_for(self, fn_def, ftype):
        """Translated tier for a function, cached on the decoded Function
        (modules are cached per code hash in vm.py, so translation runs
        once per contract per process). False = interpreter tier. Both the
        tier AND the gas rate are pure functions of the bytecode: the
        LACHAIN_TPU_WASM=interp override changes which engine RUNS, never
        what is billed — translation is still attempted to classify."""
        tier = getattr(fn_def, "_tier", None)
        if tier is None:
            from .translate import translate_function

            compiled = translate_function(self.module, fn_def, ftype)
            fn_def._gas_rate = (
                INSTRUCTION_GAS if compiled else INTERP_INSTRUCTION_GAS
            )
            tier = compiled or False
            fn_def._tier = tier
        if _ENV_GET("LACHAIN_TPU_WASM") == "interp":
            return False
        return tier

    def m_grow(self, delta: int) -> int:
        """memory.grow semantics shared by both execution tiers."""
        old = self.mem_pages
        if old + delta > self.mem_max:
            return MASK32  # -1
        self.gas.charge(MEMORY_GROW_GAS_PER_PAGE * delta)
        self.mem_pages = old + delta
        self.memory.extend(bytes(delta * PAGE_SIZE))
        return old

    # -- memory helpers -----------------------------------------------------

    def _mem_read(self, addr: int, n: int) -> bytes:
        if addr < 0 or addr + n > len(self.memory):
            raise WasmTrap("out of bounds memory access")
        return bytes(self.memory[addr : addr + n])

    def _mem_write(self, addr: int, data: bytes) -> None:
        if addr < 0 or addr + len(data) > len(self.memory):
            raise WasmTrap("out of bounds memory access")
        self.memory[addr : addr + len(data)] = data

    def mem_read(self, addr: int, n: int) -> bytes:
        """Host-side accessor (bounds-checked)."""
        return self._mem_read(addr, n)

    def mem_write(self, addr: int, data: bytes) -> None:
        self._mem_write(addr, data)

    # -- the interpreter loop ----------------------------------------------

    def _exec(
        self, fn: Function, ftype: FuncType, args: List[object]
    ) -> Optional[object]:
        body = fn.body
        # sidetable cached on the Function itself: shared across Instances
        # (modules are cached per code hash in vm.py)
        tables = getattr(fn, "_sidetable", None)
        if tables is None:
            tables = _build_sidetable(body)
            fn._sidetable = tables
        end_of, else_of = tables

        locals_: List[object] = args
        for vt in fn.locals:
            locals_.append(0 if vt in (I32, I64) else 0.0)

        stack: List[object] = []
        # control: (branch_target_pc, stack_height, arity, keep_on_branch)
        ctrl: List[Tuple[int, int, int]] = []
        pc = 0
        charge = self.gas.charge
        n_body = len(body)
        rate = getattr(fn, "_gas_rate", INTERP_INSTRUCTION_GAS)
        if rate == INSTRUCTION_GAS and self.tgas_scale != 1:
            rate *= self.tgas_scale  # pre-fast_wasm_gas schedule

        while pc < n_body:
            ins = body[pc]
            op = ins[0]
            charge(rate)

            # ---- control ----
            if op == 0x0B:  # end
                if ctrl:
                    ctrl.pop()
                pc += 1
                continue
            if op <= 0x11 or op == 0x1A or op == 0x1B:
                if op == 0x01:  # nop
                    pc += 1
                elif op == 0x00:  # unreachable
                    raise WasmTrap("unreachable")
                elif op == 0x02:  # block
                    arity = 0 if ins[1] == BLOCK_EMPTY else 1
                    ctrl.append((end_of[pc], len(stack), arity))
                    pc += 1
                elif op == 0x03:  # loop
                    ctrl.append((pc + 1, len(stack), 0))
                    pc += 1
                elif op == 0x04:  # if
                    cond = stack.pop()
                    arity = 0 if ins[1] == BLOCK_EMPTY else 1
                    if cond:
                        ctrl.append((end_of[pc], len(stack), arity))
                        pc += 1
                    else:
                        ep = else_of.get(pc)
                        if ep is not None:
                            ctrl.append((end_of[pc], len(stack), arity))
                            pc = ep + 1
                        else:
                            pc = end_of[pc] + 1
                elif op == 0x05:  # else: end of true arm
                    tgt, _, _ = ctrl[-1]
                    pc = tgt  # jump to the matching end (pops the label)
                elif op == 0x0C:  # br
                    if ins[1] == len(ctrl):
                        break  # function-label branch = return
                    pc = self._branch(ins[1], stack, ctrl)
                elif op == 0x0D:  # br_if
                    if stack.pop():
                        if ins[1] == len(ctrl):
                            break
                        pc = self._branch(ins[1], stack, ctrl)
                    else:
                        pc += 1
                elif op == 0x0E:  # br_table
                    idx = stack.pop()
                    targets, default = ins[1], ins[2]
                    depth = targets[idx] if idx < len(targets) else default
                    if depth == len(ctrl):
                        break
                    pc = self._branch(depth, stack, ctrl)
                elif op == 0x0F:  # return
                    break
                elif op == 0x10:  # call
                    callee = ins[1]
                    ct = self.module.func_type(callee)
                    n = len(ct.params)
                    call_args = stack[len(stack) - n :] if n else []
                    del stack[len(stack) - n :]
                    res = self.call_index(callee, call_args)
                    if ct.results:
                        stack.append(res)
                    pc += 1
                elif op == 0x11:  # call_indirect
                    elem = stack.pop()
                    if elem >= len(self.table) or self.table[elem] is None:
                        raise WasmTrap("undefined table element")
                    callee = self.table[elem]
                    ct = self.module.func_type(callee)
                    want = self.module.types[ins[1]]
                    if ct != want:
                        raise WasmTrap("indirect call type mismatch")
                    n = len(ct.params)
                    call_args = stack[len(stack) - n :] if n else []
                    del stack[len(stack) - n :]
                    res = self.call_index(callee, call_args)
                    if ct.results:
                        stack.append(res)
                    pc += 1
                elif op == 0x1A:  # drop
                    stack.pop()
                    pc += 1
                else:  # 0x1b select
                    c = stack.pop()
                    b = stack.pop()
                    a = stack.pop()
                    stack.append(a if c else b)
                    pc += 1
                continue

            # ---- variables ----
            if 0x20 <= op <= 0x24:
                idx = ins[1]
                if op == 0x20:
                    stack.append(locals_[idx])
                elif op == 0x21:
                    locals_[idx] = stack.pop()
                elif op == 0x22:
                    locals_[idx] = stack[-1]
                elif op == 0x23:
                    stack.append(self.globals[idx])
                else:
                    g = self.module.globals[idx]
                    if not g.mutable:
                        raise WasmTrap("assignment to immutable global")
                    self.globals[idx] = stack.pop()
                pc += 1
                continue

            # ---- memory ----
            if 0x28 <= op <= 0x3E:
                offset = ins[2]
                if op <= 0x35:  # loads
                    addr = stack.pop() + offset
                    if op == 0x28:
                        stack.append(int.from_bytes(self._mem_read(addr, 4), "little"))
                    elif op == 0x29:
                        stack.append(int.from_bytes(self._mem_read(addr, 8), "little"))
                    elif op == 0x2A:
                        stack.append(_canon(struct.unpack("<f", self._mem_read(addr, 4))[0]))
                    elif op == 0x2B:
                        stack.append(_canon(struct.unpack("<d", self._mem_read(addr, 8))[0]))
                    elif op == 0x2C:  # i32.load8_s
                        v = self._mem_read(addr, 1)[0]
                        stack.append((v - 256 if v & 0x80 else v) & MASK32)
                    elif op == 0x2D:
                        stack.append(self._mem_read(addr, 1)[0])
                    elif op == 0x2E:
                        v = int.from_bytes(self._mem_read(addr, 2), "little")
                        stack.append((v - 65536 if v & 0x8000 else v) & MASK32)
                    elif op == 0x2F:
                        stack.append(int.from_bytes(self._mem_read(addr, 2), "little"))
                    elif op == 0x30:
                        v = self._mem_read(addr, 1)[0]
                        stack.append((v - 256 if v & 0x80 else v) & MASK64)
                    elif op == 0x31:
                        stack.append(self._mem_read(addr, 1)[0])
                    elif op == 0x32:
                        v = int.from_bytes(self._mem_read(addr, 2), "little")
                        stack.append((v - 65536 if v & 0x8000 else v) & MASK64)
                    elif op == 0x33:
                        stack.append(int.from_bytes(self._mem_read(addr, 2), "little"))
                    elif op == 0x34:
                        v = int.from_bytes(self._mem_read(addr, 4), "little")
                        stack.append((v - (1 << 32) if v & 0x80000000 else v) & MASK64)
                    else:  # 0x35
                        stack.append(int.from_bytes(self._mem_read(addr, 4), "little"))
                else:  # stores
                    val = stack.pop()
                    addr = stack.pop() + offset
                    if op == 0x36:
                        self._mem_write(addr, (val & MASK32).to_bytes(4, "little"))
                    elif op == 0x37:
                        self._mem_write(addr, (val & MASK64).to_bytes(8, "little"))
                    elif op == 0x38:
                        self._mem_write(addr, struct.pack("<f", val))
                    elif op == 0x39:
                        self._mem_write(addr, struct.pack("<d", val))
                    elif op == 0x3A:
                        self._mem_write(addr, bytes([val & 0xFF]))
                    elif op == 0x3B:
                        self._mem_write(addr, (val & 0xFFFF).to_bytes(2, "little"))
                    elif op == 0x3C:
                        self._mem_write(addr, bytes([val & 0xFF]))
                    elif op == 0x3D:
                        self._mem_write(addr, (val & 0xFFFF).to_bytes(2, "little"))
                    else:  # 0x3e i64.store32
                        self._mem_write(addr, (val & MASK32).to_bytes(4, "little"))
                pc += 1
                continue

            if op == 0x3F:  # memory.size
                stack.append(self.mem_pages)
                pc += 1
                continue
            if op == 0x40:  # memory.grow
                stack.append(self.m_grow(stack.pop()))
                pc += 1
                continue

            # ---- constants ----
            if op == 0x41:
                stack.append(ins[1] & MASK32)
                pc += 1
                continue
            if op == 0x42:
                stack.append(ins[1] & MASK64)
                pc += 1
                continue
            if op == 0x43:
                stack.append(_canon(struct.unpack("<f", ins[1])[0]))
                pc += 1
                continue
            if op == 0x44:
                stack.append(_canon(struct.unpack("<d", ins[1])[0]))
                pc += 1
                continue

            # ---- numeric ----
            self._numeric(op, ins, stack)
            pc += 1

        return stack[-1] if ftype.results else None

    def _branch(
        self,
        depth: int,
        stack: List[object],
        ctrl: List[Tuple[int, int, int]],
    ) -> int:
        """Unwind `depth` labels; return new pc."""
        if depth >= len(ctrl):
            raise WasmTrap("branch depth out of range")
        # the label being branched to stays; everything above it is discarded
        target_idx = len(ctrl) - 1 - depth
        tgt, height, arity = ctrl[target_idx]
        vals = stack[len(stack) - arity :] if arity else []
        del stack[height:]
        stack.extend(vals)
        del ctrl[target_idx + 1 :]
        # for blocks the target is the `end` pc — executing it pops the label;
        # for loops the target is the first instruction and the label persists
        return tgt

    def _numeric(self, op: int, ins: tuple, stack: List[object]) -> None:
        push = stack.append
        pop = stack.pop
        if op == 0x45:
            push(1 if pop() == 0 else 0)
        elif op == 0x46 or op == 0x51:
            push(1 if pop() == pop() else 0)
        elif op == 0x47 or op == 0x52:
            push(1 if pop() != pop() else 0)
        elif op == 0x48:
            b, a = pop(), pop()
            push(1 if _s32(a) < _s32(b) else 0)
        elif op == 0x49 or op == 0x54:
            b, a = pop(), pop()
            push(1 if a < b else 0)
        elif op == 0x4A:
            b, a = pop(), pop()
            push(1 if _s32(a) > _s32(b) else 0)
        elif op == 0x4B or op == 0x56:
            b, a = pop(), pop()
            push(1 if a > b else 0)
        elif op == 0x4C:
            b, a = pop(), pop()
            push(1 if _s32(a) <= _s32(b) else 0)
        elif op == 0x4D or op == 0x58:
            b, a = pop(), pop()
            push(1 if a <= b else 0)
        elif op == 0x4E:
            b, a = pop(), pop()
            push(1 if _s32(a) >= _s32(b) else 0)
        elif op == 0x4F or op == 0x5A:
            b, a = pop(), pop()
            push(1 if a >= b else 0)
        elif op == 0x50:
            push(1 if pop() == 0 else 0)
        elif op == 0x53:
            b, a = pop(), pop()
            push(1 if _s64(a) < _s64(b) else 0)
        elif op == 0x55:
            b, a = pop(), pop()
            push(1 if _s64(a) > _s64(b) else 0)
        elif op == 0x57:
            b, a = pop(), pop()
            push(1 if _s64(a) <= _s64(b) else 0)
        elif op == 0x59:
            b, a = pop(), pop()
            push(1 if _s64(a) >= _s64(b) else 0)
        elif 0x5B <= op <= 0x66:  # float comparisons
            b, a = pop(), pop()
            rel = (op - 0x5B) % 6
            if rel == 0:
                push(1 if a == b else 0)
            elif rel == 1:
                push(1 if a != b else 0)
            elif rel == 2:
                push(1 if a < b else 0)
            elif rel == 3:
                push(1 if a > b else 0)
            elif rel == 4:
                push(1 if a <= b else 0)
            else:
                push(1 if a >= b else 0)
        elif op == 0x67:
            push(_clz(pop(), 32))
        elif op == 0x68:
            push(_ctz(pop(), 32))
        elif op == 0x69:
            push(bin(pop()).count("1"))
        elif op == 0x6A:
            b, a = pop(), pop()
            push((a + b) & MASK32)
        elif op == 0x6B:
            b, a = pop(), pop()
            push((a - b) & MASK32)
        elif op == 0x6C:
            b, a = pop(), pop()
            push((a * b) & MASK32)
        elif op == 0x6D:
            b, a = _s32(pop()), _s32(pop())
            if b == 0:
                raise WasmTrap("integer divide by zero")
            q = abs(a) // abs(b)
            if (a < 0) != (b < 0):
                q = -q
            if q == 1 << 31:
                raise WasmTrap("integer overflow")
            push(q & MASK32)
        elif op == 0x6E:
            b, a = pop(), pop()
            if b == 0:
                raise WasmTrap("integer divide by zero")
            push(a // b)
        elif op == 0x6F:
            b, a = _s32(pop()), _s32(pop())
            if b == 0:
                raise WasmTrap("integer divide by zero")
            r = abs(a) % abs(b)
            push((r if a >= 0 else -r) & MASK32)
        elif op == 0x70:
            b, a = pop(), pop()
            if b == 0:
                raise WasmTrap("integer divide by zero")
            push(a % b)
        elif op == 0x71:
            push(pop() & pop())
        elif op == 0x72:
            push(pop() | pop())
        elif op == 0x73:
            push(pop() ^ pop())
        elif op == 0x74:
            b, a = pop(), pop()
            push((a << (b % 32)) & MASK32)
        elif op == 0x75:
            b, a = pop(), pop()
            push((_s32(a) >> (b % 32)) & MASK32)
        elif op == 0x76:
            b, a = pop(), pop()
            push(a >> (b % 32))
        elif op == 0x77:
            b, a = pop(), pop()
            push(_rotl(a, b, 32))
        elif op == 0x78:
            b, a = pop(), pop()
            push(_rotl(a, 32 - (b % 32), 32))
        elif op == 0x79:
            push(_clz(pop(), 64))
        elif op == 0x7A:
            push(_ctz(pop(), 64))
        elif op == 0x7B:
            push(bin(pop()).count("1"))
        elif op == 0x7C:
            b, a = pop(), pop()
            push((a + b) & MASK64)
        elif op == 0x7D:
            b, a = pop(), pop()
            push((a - b) & MASK64)
        elif op == 0x7E:
            b, a = pop(), pop()
            push((a * b) & MASK64)
        elif op == 0x7F:
            b, a = _s64(pop()), _s64(pop())
            if b == 0:
                raise WasmTrap("integer divide by zero")
            q = abs(a) // abs(b)
            if (a < 0) != (b < 0):
                q = -q
            if q == 1 << 63:
                raise WasmTrap("integer overflow")
            push(q & MASK64)
        elif op == 0x80:
            b, a = pop(), pop()
            if b == 0:
                raise WasmTrap("integer divide by zero")
            push(a // b)
        elif op == 0x81:
            b, a = _s64(pop()), _s64(pop())
            if b == 0:
                raise WasmTrap("integer divide by zero")
            r = abs(a) % abs(b)
            push((r if a >= 0 else -r) & MASK64)
        elif op == 0x82:
            b, a = pop(), pop()
            if b == 0:
                raise WasmTrap("integer divide by zero")
            push(a % b)
        elif op == 0x83:
            push(pop() & pop())
        elif op == 0x84:
            push(pop() | pop())
        elif op == 0x85:
            push(pop() ^ pop())
        elif op == 0x86:
            b, a = pop(), pop()
            push((a << (b % 64)) & MASK64)
        elif op == 0x87:
            b, a = pop(), pop()
            push((_s64(a) >> (b % 64)) & MASK64)
        elif op == 0x88:
            b, a = pop(), pop()
            push(a >> (b % 64))
        elif op == 0x89:
            b, a = pop(), pop()
            push(_rotl(a, b, 64))
        elif op == 0x8A:
            b, a = pop(), pop()
            push(_rotl(a, 64 - (b % 64), 64))
        elif 0x8B <= op <= 0x98:  # f32 unary/binary
            self._float_op(op - 0x8B, stack, True)
        elif 0x99 <= op <= 0xA6:  # f64
            self._float_op(op - 0x99, stack, False)
        elif op == 0xA7:  # i32.wrap_i64
            push(pop() & MASK32)
        elif op == 0xA8:
            push(_trunc(pop(), -(1 << 31), (1 << 31) - 1, True, 32))
        elif op == 0xA9:
            push(_trunc(pop(), 0, MASK32, False, 32))
        elif op == 0xAA:
            push(_trunc(pop(), -(1 << 31), (1 << 31) - 1, True, 32))
        elif op == 0xAB:
            push(_trunc(pop(), 0, MASK32, False, 32))
        elif op == 0xAC:  # i64.extend_i32_s
            push(_s32(pop()) & MASK64)
        elif op == 0xAD:
            push(pop() & MASK32)
        elif op == 0xAE:
            push(_trunc(pop(), -(1 << 63), (1 << 63) - 1, True, 64))
        elif op == 0xAF:
            push(_trunc(pop(), 0, MASK64, False, 64))
        elif op == 0xB0:
            push(_trunc(pop(), -(1 << 63), (1 << 63) - 1, True, 64))
        elif op == 0xB1:
            push(_trunc(pop(), 0, MASK64, False, 64))
        elif op == 0xB2:
            push(_f32(float(_s32(pop()))))
        elif op == 0xB3:
            push(_f32(float(pop())))
        elif op == 0xB4:
            push(_f32(float(_s64(pop()))))
        elif op == 0xB5:
            push(_f32(float(pop())))
        elif op == 0xB6:  # f32.demote_f64
            push(_f32(pop()))
        elif op == 0xB7:
            push(float(_s32(pop())))
        elif op == 0xB8:
            push(float(pop()))
        elif op == 0xB9:
            push(float(_s64(pop())))
        elif op == 0xBA:
            push(float(pop()))
        elif op == 0xBB:  # f64.promote_f32
            push(float(pop()))
        elif op == 0xBC:
            push(int.from_bytes(struct.pack("<f", pop()), "little"))
        elif op == 0xBD:
            push(int.from_bytes(struct.pack("<d", pop()), "little"))
        elif op == 0xBE:
            push(_canon(struct.unpack("<f", (pop() & MASK32).to_bytes(4, "little"))[0]))
        elif op == 0xBF:
            push(_canon(struct.unpack("<d", (pop() & MASK64).to_bytes(8, "little"))[0]))
        elif op == 0xC0:  # i32.extend8_s
            v = pop() & 0xFF
            push((v - 256 if v & 0x80 else v) & MASK32)
        elif op == 0xC1:
            v = pop() & 0xFFFF
            push((v - 65536 if v & 0x8000 else v) & MASK32)
        elif op == 0xC2:
            v = pop() & 0xFF
            push((v - 256 if v & 0x80 else v) & MASK64)
        elif op == 0xC3:
            v = pop() & 0xFFFF
            push((v - 65536 if v & 0x8000 else v) & MASK64)
        elif op == 0xC4:
            v = pop() & MASK32
            push((v - (1 << 32) if v & 0x80000000 else v) & MASK64)
        elif op == 0xFC:
            sub = ins[1]
            if sub == 0:
                push(_trunc_sat(pop(), -(1 << 31), (1 << 31) - 1, 32))
            elif sub == 1:
                push(_trunc_sat(pop(), 0, MASK32, 32))
            elif sub == 2:
                push(_trunc_sat(pop(), -(1 << 31), (1 << 31) - 1, 32))
            elif sub == 3:
                push(_trunc_sat(pop(), 0, MASK32, 32))
            elif sub == 4:
                push(_trunc_sat(pop(), -(1 << 63), (1 << 63) - 1, 64))
            elif sub == 5:
                push(_trunc_sat(pop(), 0, MASK64, 64))
            elif sub == 6:
                push(_trunc_sat(pop(), -(1 << 63), (1 << 63) - 1, 64))
            elif sub == 7:
                push(_trunc_sat(pop(), 0, MASK64, 64))
            elif sub == 10:  # memory.copy
                n, s, d = pop(), pop(), pop()
                self.gas.charge(BULK_MEMORY_GAS_PER_BYTE * n)
                data = self._mem_read(s, n)
                self._mem_write(d, data)
            elif sub == 11:  # memory.fill
                n, v, d = pop(), pop(), pop()
                self.gas.charge(BULK_MEMORY_GAS_PER_BYTE * n)
                self._mem_write(d, bytes([v & 0xFF]) * n)
            else:
                raise WasmTrap(f"unsupported 0xfc:{sub}")
        else:
            raise WasmTrap(f"unsupported opcode 0x{op:02x}")

    def _float_op(self, rel: int, stack: List[object], single: bool) -> None:
        push = stack.append
        pop = stack.pop
        # _canon for f64: arithmetic on doubles must never expose the host
        # FPU's NaN (x86 produces a negative qNaN for inf-inf; ARM a positive
        # one) — all results funnel through the canonical quiet NaN
        rnd = _f32 if single else _canon
        if rel == 0:
            push(rnd(abs(pop())))
        elif rel == 1:
            push(rnd(-pop()))
        elif rel == 2:
            v = pop()
            push(v if math.isnan(v) or math.isinf(v) else rnd(float(math.ceil(v))))
        elif rel == 3:
            v = pop()
            push(v if math.isnan(v) or math.isinf(v) else rnd(float(math.floor(v))))
        elif rel == 4:
            v = pop()
            push(v if math.isnan(v) or math.isinf(v) else rnd(float(math.trunc(v))))
        elif rel == 5:
            push(rnd(_nearest(pop())))
        elif rel == 6:
            v = pop()
            if v < 0:
                push(float("nan"))
            else:
                push(rnd(math.sqrt(v)))
        elif rel == 7:
            b, a = pop(), pop()
            push(rnd(a + b))
        elif rel == 8:
            b, a = pop(), pop()
            push(rnd(a - b))
        elif rel == 9:
            b, a = pop(), pop()
            push(rnd(a * b))
        elif rel == 10:
            b, a = pop(), pop()
            if b == 0:
                # 0/0 and NaN/0 are NaN; finite/0 is signed infinity
                push(
                    float("nan")
                    if a == 0 or a != a
                    else math.copysign(float("inf"), a) * math.copysign(1.0, b)
                )
            else:
                push(rnd(a / b))
        elif rel == 11:
            b, a = pop(), pop()
            push(rnd(min(a, b)) if a == a and b == b else float("nan"))
        elif rel == 12:
            b, a = pop(), pop()
            push(rnd(max(a, b)) if a == a and b == b else float("nan"))
        else:  # 13 copysign
            b, a = pop(), pop()
            push(rnd(math.copysign(a, b)))
