"""The `env` host-import table for contracts.

Parity with the reference's ExternalHandler
(/root/reference/src/Lachain.Core/Blockchain/VM/ExternalHandler.cs): call
data, storage, crypto, transfers, nested invocation, events, halt. Names
are the snake_case forms of the reference's Handler_Env_* entries; gas
costs follow GasMetering.cs (vm/gas.py).

Conventions: addresses are 20 bytes, storage keys/values and u256 scalars
are 32-byte big-endian; block number / gas / sizes are i64/i32 return
values.
"""
from __future__ import annotations

import hashlib
from typing import Dict, Tuple

from ..crypto import ecdsa
from ..crypto.hashes import keccak256
from . import gas as G
from .interpreter import WasmTrap

HostTable = Dict[Tuple[str, str], object]

ADDR = 20
WORD = 32


def build_env(vm, frame) -> HostTable:
    """Host functions close over the VM context and the current frame."""
    from ..core import execution  # late import: core.execution calls back in

    inst = lambda: frame.instance  # bound after Instance construction
    charge = lambda n: vm.gas.charge(n)

    def read(off: int, n: int) -> bytes:
        charge(n * G.COPY_FROM_MEMORY_GAS_PER_BYTE)
        return inst().mem_read(off, n)

    def write(off: int, data: bytes) -> None:
        charge(len(data) * G.COPY_TO_MEMORY_GAS_PER_BYTE)
        inst().mem_write(off, data)

    def require_mutable() -> None:
        if frame.static:
            raise WasmTrap("state mutation in static call")

    # ---- call data -------------------------------------------------------
    def get_call_size() -> int:
        charge(G.GET_CALL_SIZE_GAS)
        return len(frame.input)

    def copy_call_value(frm: int, to: int, offset: int) -> None:
        charge(G.GET_CALL_VALUE_GAS)
        if not (0 <= frm <= to <= len(frame.input)):
            raise WasmTrap("copy_call_value out of range")
        write(offset, frame.input[frm:to])

    def set_return(offset: int, length: int) -> None:
        frame.return_data = read(offset, length)

    def get_return_size() -> int:
        charge(G.GET_RETURN_SIZE_GAS)
        return len(frame.child_return)

    def copy_return_value(result_off: int, data_off: int, length: int) -> None:
        charge(G.GET_RETURN_VALUE_GAS)
        if data_off + length > len(frame.child_return):
            raise WasmTrap("copy_return_value out of range")
        write(result_off, frame.child_return[data_off : data_off + length])

    # ---- identity / environment -----------------------------------------
    def get_sender(off: int) -> None:
        write(off, frame.sender)

    def get_address(off: int) -> None:
        write(off, frame.contract)

    def get_msg_value(off: int) -> None:
        charge(G.GET_CALL_VALUE_GAS)
        write(off, frame.value.to_bytes(WORD, "big"))

    def get_tx_origin(off: int) -> None:
        write(off, vm.origin)

    def get_tx_gas_price(off: int) -> None:
        write(off, vm.gas_price.to_bytes(WORD, "big"))

    def get_block_number() -> int:
        charge(G.BLOCK_NUMBER_GAS)
        return vm.block_index

    def get_block_gas_limit() -> int:
        charge(G.BLOCK_NUMBER_GAS)
        return vm.block_gas_limit

    def get_chain_id() -> int:
        charge(G.BLOCK_NUMBER_GAS)
        return vm.chain_id

    def get_gas_left() -> int:
        return vm.gas.remaining

    def get_block_hash(height: int, off: int) -> None:
        charge(G.LOAD_STORAGE_GAS)
        raw = vm.snap.get("blocks", b"h:" + int(height).to_bytes(8, "big"))
        write(off, raw if raw and len(raw) == WORD else b"\x00" * WORD)

    def get_external_balance(addr_off: int, result_off: int) -> None:
        charge(G.LOAD_STORAGE_GAS)
        addr = read(addr_off, ADDR)
        bal = execution.get_balance(vm.snap, addr)
        write(result_off, bal.to_bytes(WORD, "big"))

    # ---- storage ---------------------------------------------------------
    def skey(key: bytes) -> bytes:
        return frame.storage_owner + key

    def load_storage(key_off: int, value_off: int) -> None:
        charge(G.LOAD_STORAGE_GAS)
        key = read(key_off, WORD)
        raw = vm.snap.get("storage", skey(key))
        write(value_off, raw if raw and len(raw) == WORD else b"\x00" * WORD)

    def save_storage(key_off: int, value_off: int) -> None:
        require_mutable()
        charge(G.SAVE_STORAGE_GAS)
        key = read(key_off, WORD)
        vm.snap.put("storage", skey(key), read(value_off, WORD))

    def kill_storage(key_off: int) -> None:
        require_mutable()
        charge(G.KILL_STORAGE_GAS)
        vm.snap.delete("storage", skey(read(key_off, WORD)))

    # ---- crypto ----------------------------------------------------------
    def crypto_keccak256(off: int, length: int, result_off: int) -> None:
        charge(length * G.KECCAK256_GAS_PER_BYTE)
        write(result_off, keccak256(read(off, length)))

    def crypto_sha256(off: int, length: int, result_off: int) -> None:
        charge(length * G.SHA256_GAS_PER_BYTE)
        write(result_off, hashlib.sha256(read(off, length)).digest())

    def crypto_ripemd160(off: int, length: int, result_off: int) -> None:
        charge(length * G.RIPEMD160_GAS_PER_BYTE)
        try:
            h = hashlib.new("ripemd160", read(off, length)).digest()
        except ValueError:  # OpenSSL without legacy provider
            raise WasmTrap("ripemd160 unavailable")
        write(result_off, h)

    def crypto_recover(hash_off: int, sig_off: int, result_off: int) -> int:
        charge(G.RECOVER_GAS)
        pub = ecdsa.recover_hash(read(hash_off, WORD), read(sig_off, 65))
        if pub is None:
            return 0
        write(result_off, ecdsa.address_from_public_key(pub))
        return 1

    def crypto_verify(
        hash_off: int, sig_off: int, pub_off: int
    ) -> int:
        charge(G.VERIFY_GAS)
        ok = ecdsa.verify_hash(
            read(pub_off, 33), read(hash_off, WORD), read(sig_off, 65)
        )
        return 1 if ok else 0

    # ---- value transfer / nested calls ----------------------------------
    def transfer(to_off: int, value_off: int) -> int:
        require_mutable()
        charge(G.TRANSFER_FUNDS_GAS)
        to = read(to_off, ADDR)
        value = int.from_bytes(read(value_off, WORD), "big")
        bal = execution.get_balance(vm.snap, frame.contract)
        if bal < value:
            return 0
        execution.set_balance(vm.snap, frame.contract, bal - value)
        execution.set_balance(
            vm.snap, to, execution.get_balance(vm.snap, to) + value
        )
        return 1

    def _invoke(addr_off, input_off, input_len, value_off, gas_limit, *, static, delegate) -> int:
        charge(G.INVOKE_CONTRACT_GAS)
        to = read(addr_off, ADDR)
        data = read(input_off, input_len)
        value = int.from_bytes(read(value_off, WORD), "big")
        if value and static:
            raise WasmTrap("value transfer in static call")
        if value:
            require_mutable()
        # the value moves inside the child frame's checkpoint (value_from),
        # so a failed call reverts the transfer with everything else
        res = vm.invoke_contract(
            contract=to,
            sender=frame.contract if not delegate else frame.sender,
            value=value,
            input=data,
            gas_limit=gas_limit if gas_limit else 0,
            static=static,
            storage_owner=frame.storage_owner if delegate else None,
            value_from=frame.contract if value else None,
        )
        frame.child_return = res.return_data
        return res.status

    def invoke_contract(addr_off, input_off, input_len, value_off, gas_limit) -> int:
        require_mutable()
        return _invoke(addr_off, input_off, input_len, value_off, gas_limit,
                       static=False, delegate=False)

    def invoke_static_contract(addr_off, input_off, input_len, value_off, gas_limit) -> int:
        return _invoke(addr_off, input_off, input_len, value_off, gas_limit,
                       static=True, delegate=False)

    def invoke_delegate_contract(addr_off, input_off, input_len, value_off, gas_limit) -> int:
        require_mutable()
        return _invoke(addr_off, input_off, input_len, value_off, gas_limit,
                       static=False, delegate=True)

    def create(value_off: int, code_off: int, code_len: int, result_off: int) -> int:
        require_mutable()
        from .vm import deploy_code  # local import: vm.py imports this module

        charge(G.DEPLOY_GAS + code_len * G.DEPLOY_GAS_PER_BYTE)
        code = read(code_off, code_len)
        # endowment must be payable BEFORE any state is written, so a
        # failed create leaves neither code nor a half-made transfer
        value = int.from_bytes(read(value_off, WORD), "big")
        bal = execution.get_balance(vm.snap, frame.contract)
        if bal < value:
            return 0
        nonce = execution.get_nonce(vm.snap, frame.contract)
        execution.set_nonce(vm.snap, frame.contract, nonce + 1)
        status, addr = deploy_code(vm.snap, frame.contract, nonce, code)
        if status != 1:
            return 0  # nonce is consumed, as in the account-create rules
        if value:
            execution.set_balance(vm.snap, frame.contract, bal - value)
            execution.set_balance(vm.snap, addr, value)
        write(result_off, addr)
        return 1

    def create2(value_off: int, code_off: int, code_len: int, salt_off: int, result_off: int) -> int:
        require_mutable()
        from .vm import create2_address, decode_module, get_code, set_code
        from .wasm import WasmDecodeError

        charge(G.DEPLOY_GAS + code_len * G.DEPLOY_GAS_PER_BYTE)
        code = read(code_off, code_len)
        salt = read(salt_off, WORD)
        value = int.from_bytes(read(value_off, WORD), "big")
        bal = execution.get_balance(vm.snap, frame.contract)
        if bal < value:
            return 0
        try:
            module = decode_module(code)
        except WasmDecodeError:
            return 0
        if module.export_map().get("start") is None:
            return 0
        addr = create2_address(frame.contract, salt, code)
        if get_code(vm.snap, addr) is not None:
            return 0
        set_code(vm.snap, addr, code)
        if value:
            execution.set_balance(vm.snap, frame.contract, bal - value)
            execution.set_balance(vm.snap, addr, value)
        write(result_off, addr)
        return 1

    # ---- code introspection ---------------------------------------------
    def get_code_size() -> int:
        from .vm import get_code

        charge(G.GET_CODE_SIZE_GAS)
        code = get_code(vm.snap, frame.contract)
        return len(code) if code else 0

    def copy_code_value(result_off: int, data_off: int, length: int) -> None:
        from .vm import get_code

        charge(G.COPY_CODE_VALUE_GAS)
        code = get_code(vm.snap, frame.contract) or b""
        if data_off + length > len(code):
            raise WasmTrap("copy_code_value out of range")
        write(result_off, code[data_off : data_off + length])

    # ---- events / halt ---------------------------------------------------
    def write_event(data_off: int, data_len: int) -> None:
        require_mutable()
        charge(data_len * G.WRITE_EVENT_PER_BYTE_GAS)
        vm.events.append((frame.contract, read(data_off, data_len)))

    def system_halt(code: int) -> None:
        from .vm import HaltException

        raise HaltException(code)

    env = {
        "get_call_size": get_call_size,
        "copy_call_value": copy_call_value,
        "set_return": set_return,
        "get_return_size": get_return_size,
        "copy_return_value": copy_return_value,
        "get_sender": get_sender,
        "get_address": get_address,
        "get_msgvalue": get_msg_value,
        "get_tx_origin": get_tx_origin,
        "get_tx_gas_price": get_tx_gas_price,
        "get_block_number": get_block_number,
        "get_block_gas_limit": get_block_gas_limit,
        "get_chain_id": get_chain_id,
        "get_gas_left": get_gas_left,
        "get_block_hash": get_block_hash,
        "get_external_balance": get_external_balance,
        "load_storage": load_storage,
        "save_storage": save_storage,
        "kill_storage": kill_storage,
        "crypto_keccak256": crypto_keccak256,
        "crypto_sha256": crypto_sha256,
        "crypto_ripemd160": crypto_ripemd160,
        "crypto_recover": crypto_recover,
        "crypto_verify": crypto_verify,
        "transfer": transfer,
        "invoke_contract": invoke_contract,
        "invoke_static_contract": invoke_static_contract,
        "invoke_delegate_contract": invoke_delegate_contract,
        "create": create,
        "create2": create2,
        "get_code_size": get_code_size,
        "copy_code_value": copy_code_value,
        "write_event": write_event,
        "system_halt": system_halt,
    }
    return {("env", name): fn for name, fn in env.items()}
