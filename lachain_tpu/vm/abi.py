"""Contract ABI: 4-byte keccak selector + 32-byte words + dynamic tails.

Parity with the reference's ContractEncoder/ContractDecoder
(/root/reference/src/Lachain.Core/Blockchain/VM/ContractEncoder.cs:1-169,
ContractDecoder.cs:1-152): methods are addressed by
keccak256(signature)[:4]; scalar args are fixed 32-byte big-endian words;
`bytes` args are a 32-byte length word followed by the payload padded to a
32-byte boundary (a flat layout — offsets are implicit, arguments are decoded
in order).
"""
from __future__ import annotations

from typing import Sequence, Union

from ..crypto.hashes import keccak256

WORD = 32

AbiValue = Union[int, bytes]


def method_selector(signature: str) -> bytes:
    return keccak256(signature.encode())[:4]


def _pad_right(data: bytes) -> bytes:
    rem = len(data) % WORD
    return data + b"\x00" * (WORD - rem) if rem else data


def encode_args(args: Sequence[AbiValue]) -> bytes:
    out = b""
    for a in args:
        if isinstance(a, bool):
            out += int(a).to_bytes(WORD, "big")
        elif isinstance(a, int):
            out += (a % (1 << 256)).to_bytes(WORD, "big")
        elif isinstance(a, (bytes, bytearray)):
            if len(a) == 20:  # address: left-pad into one word
                out += b"\x00" * 12 + bytes(a)
            elif len(a) == 32:
                out += bytes(a)
            else:
                out += len(a).to_bytes(WORD, "big") + _pad_right(bytes(a))
        else:
            raise TypeError(f"unsupported ABI value {type(a)}")
    return out


def encode_call(signature: str, *args: AbiValue) -> bytes:
    return method_selector(signature) + encode_args(args)


class AbiReader:
    """Sequential decoder over an ABI-encoded argument blob."""

    def __init__(self, data: bytes, skip_selector: bool = False):
        self.data = data[4:] if skip_selector else data
        self.pos = 0

    def _word(self) -> bytes:
        if self.pos + WORD > len(self.data):
            raise ValueError("ABI: out of data")
        w = self.data[self.pos : self.pos + WORD]
        self.pos += WORD
        return w

    def uint(self) -> int:
        return int.from_bytes(self._word(), "big")

    def address(self) -> bytes:
        return self._word()[12:]

    def word(self) -> bytes:
        return self._word()

    def bytes_(self) -> bytes:
        n = self.uint()
        if n > len(self.data) - self.pos:
            raise ValueError("ABI: bytes length out of range")
        out = self.data[self.pos : self.pos + n]
        padded = (n + WORD - 1) // WORD * WORD
        self.pos += padded
        return out

    def done(self) -> bool:
        return self.pos >= len(self.data)


def selector_of(invocation: bytes) -> bytes:
    return invocation[:4]
