"""WASM -> Python source translator: the execution tier above the interpreter.

Role: the throughput answer to the reference compiling contracts to native
code (`Compile.FromBinary`, /root/reference/src/Lachain.Core/Blockchain/VM/
VirtualMachine.cs:33-60). The round-2 interpreter dispatches decoded tuples
in a Python loop at ~1e6 ops/s; this module translates each function ONCE
into straight-line Python source (exec-compiled to CPython bytecode), which
removes the dispatch loop, tuple indexing, per-instruction gas calls and
control-flow re-walking — contract throughput rises an order of magnitude
on the same deterministic gas schedule.

Design:
  * stack slots become named locals: slot i is always variable `s{i}`.
    Wasm validation fixes the stack height at every program point, so
    naming by height makes control-flow joins line up without phi moves;
    branches carrying results emit explicit `s{dst} = s{src}` moves.
  * structured control flow maps to real Python control flow:
      block/if (branch-targeted) -> `while True: ... break`
      loop                       -> `while True:` (fallthrough breaks,
                                    `br` continues)
    Multi-level branches unwind with a `_br` counter that counts WRAPPED
    labels only (untargeted blocks emit no loop, so a single Python
    `break` already skips them). The check after every wrapped label:
        if _br:
            _br -= 1
            if _br == 0 and <enclosing wrapped label is a loop>: continue
            break    # _br==0 block target: exit its while; else unwind on
  * only branch-targeted labels get wrapper loops: CPython rejects >20
    statically nested loops and most blocks are not targets. A function
    that still exceeds the nesting budget (or any SyntaxError) falls back
    to the interpreter — a deterministic property of the bytecode, so
    every node makes the same engine choice for the same code.
  * gas: accumulated in a LOCAL (`_g`) per basic block and settled into
    the meter at control boundaries plus a function-level try/finally.
    Before every trap-capable op (loads/stores, div/conversion shims,
    calls, unreachable) the pending block cost folds into `_g`, so a trap
    bills exactly the instructions the interpreter would have billed —
    the two tiers agree on gas for EVERY execution, including traps.
    (Within a pure-arithmetic run the limit is only checked at the next
    boundary; the extra ops a nearly-exhausted frame executes are
    side-effect-free and the frame fails with gas_used clamped to the
    limit either way.)
  * semantics single-sourced: only the hottest ~40 ops (integer
    arithmetic/compares, locals, constants, loads/stores) are inlined as
    source templates; div/rem/rotl/popcnt/converts and ALL float
    arithmetic call back into the interpreter's own `_numeric` /
    `_float_op` switches through 2-line shims, so NaN canonicalization
    and trap edge cases cannot diverge. tests/test_vm.py runs both
    engines differentially.
"""
from __future__ import annotations

import struct as _struct
from typing import List, Optional

from .interpreter import (
    BLOCK_EMPTY,
    BULK_MEMORY_GAS_PER_BYTE,
    INSTRUCTION_GAS,
    MASK32,
    MASK64,
    Instance,
    WasmTrap,
    _canon,
    _clz,
    _ctz,
    _s32,
    _s64,
)

# generated code keeps <= 17 nested Python loops (CPython caps statically
# nested blocks at 20, and the gas-settlement try/finally takes one);
# deeper functions stay interpreted
MAX_LOOP_NESTING = 17


def _num_shim(op: int, *vals):
    """Non-inlined integer/conversion ops through the interpreter's own
    switch (`self` is unused there for these opcode ranges)."""
    st = list(vals)
    Instance._numeric(None, op, (op,), st)
    return st[-1]


def _num_shim_fc(sub: int, a):
    st = [a]
    Instance._numeric(None, 0xFC, (0xFC, sub), st)
    return st[-1]


def _f1(rel: int, single: bool, a):
    st = [a]
    Instance._float_op(None, rel, st, single)
    return st[-1]


def _f2(rel: int, single: bool, a, b):
    st = [a, b]
    Instance._float_op(None, rel, st, single)
    return st[-1]


_ENV = {
    "M32": MASK32,
    "M64": MASK64,
    "_s32": _s32,
    "_s64": _s64,
    "_clz": _clz,
    "_ctz": _ctz,
    "_canon": _canon,
    "_num": _num_shim,
    "_numfc": _num_shim_fc,
    "_f1": _f1,
    "_f2": _f2,
    "WasmTrap": WasmTrap,
    "struct": _struct,
    "BULK_GAS": BULK_MEMORY_GAS_PER_BYTE,
}

# hot binary ops inlined as source (pops b then a, pushes the expression)
_BIN = {
    0x6A: "({a} + {b}) & M32",
    0x6B: "({a} - {b}) & M32",
    0x6C: "({a} * {b}) & M32",
    0x71: "{a} & {b}",
    0x72: "{a} | {b}",
    0x73: "{a} ^ {b}",
    0x74: "({a} << ({b} % 32)) & M32",
    0x75: "(_s32({a}) >> ({b} % 32)) & M32",
    0x76: "{a} >> ({b} % 32)",
    0x7C: "({a} + {b}) & M64",
    0x7D: "({a} - {b}) & M64",
    0x7E: "({a} * {b}) & M64",
    0x83: "{a} & {b}",
    0x84: "{a} | {b}",
    0x85: "{a} ^ {b}",
    0x86: "({a} << ({b} % 64)) & M64",
    0x87: "(_s64({a}) >> ({b} % 64)) & M64",
    0x88: "{a} >> ({b} % 64)",
    0x46: "1 if {a} == {b} else 0",
    0x47: "1 if {a} != {b} else 0",
    0x48: "1 if _s32({a}) < _s32({b}) else 0",
    0x49: "1 if {a} < {b} else 0",
    0x4A: "1 if _s32({a}) > _s32({b}) else 0",
    0x4B: "1 if {a} > {b} else 0",
    0x4C: "1 if _s32({a}) <= _s32({b}) else 0",
    0x4D: "1 if {a} <= {b} else 0",
    0x4E: "1 if _s32({a}) >= _s32({b}) else 0",
    0x4F: "1 if {a} >= {b} else 0",
    0x51: "1 if {a} == {b} else 0",
    0x52: "1 if {a} != {b} else 0",
    0x53: "1 if _s64({a}) < _s64({b}) else 0",
    0x54: "1 if {a} < {b} else 0",
    0x55: "1 if _s64({a}) > _s64({b}) else 0",
    0x56: "1 if {a} > {b} else 0",
    0x57: "1 if _s64({a}) <= _s64({b}) else 0",
    0x58: "1 if {a} <= {b} else 0",
    0x59: "1 if _s64({a}) >= _s64({b}) else 0",
    0x5A: "1 if {a} >= {b} else 0",
}
for _op, _tpl in {  # float comparisons (plain IEEE semantics on floats)
    0x5B: "1 if {a} == {b} else 0",
    0x5C: "1 if {a} != {b} else 0",
    0x5D: "1 if {a} < {b} else 0",
    0x5E: "1 if {a} > {b} else 0",
    0x5F: "1 if {a} <= {b} else 0",
    0x60: "1 if {a} >= {b} else 0",
    0x61: "1 if {a} == {b} else 0",
    0x62: "1 if {a} != {b} else 0",
    0x63: "1 if {a} < {b} else 0",
    0x64: "1 if {a} > {b} else 0",
    0x65: "1 if {a} <= {b} else 0",
    0x66: "1 if {a} >= {b} else 0",
}.items():
    _BIN[_op] = _tpl

_UN = {
    0x45: "1 if {a} == 0 else 0",
    0x50: "1 if {a} == 0 else 0",
    0x67: "_clz({a}, 32)",
    0x68: "_ctz({a}, 32)",
    0x79: "_clz({a}, 64)",
    0x7A: "_ctz({a}, 64)",
    0xA7: "{a} & M32",
    0xAC: "_s32({a}) & M64",
    0xAD: "{a} & M32",
}

# shimmed ops (single-sourced through the interpreter switch)
_SHIM1 = {0x69, 0x7B, 0xA8, 0xA9, 0xAA, 0xAB, 0xAE, 0xAF, 0xB0, 0xB1,
          0xB2, 0xB3, 0xB4, 0xB5, 0xB6, 0xB7, 0xB8, 0xB9, 0xBA, 0xBB,
          0xBC, 0xBD, 0xBE, 0xBF, 0xC0, 0xC1, 0xC2, 0xC3, 0xC4}
_SHIM2 = {0x6D, 0x6E, 0x6F, 0x70, 0x77, 0x78, 0x7F, 0x80, 0x81, 0x82,
          0x89, 0x8A}

_LOADS = {
    0x28: (4, 'int.from_bytes({r}, "little")'),
    0x29: (8, 'int.from_bytes({r}, "little")'),
    0x2A: (4, '_canon(struct.unpack("<f", {r})[0])'),
    0x2B: (8, '_canon(struct.unpack("<d", {r})[0])'),
    0x2C: (1, "(({r}[0] - 256) & M32) if {r}[0] & 0x80 else {r}[0]"),
    0x2D: (1, "{r}[0]"),
    0x2E: (2, '((int.from_bytes({r}, "little") - 65536) & M32) '
              'if {r}[1] & 0x80 else int.from_bytes({r}, "little")'),
    0x2F: (2, 'int.from_bytes({r}, "little")'),
    0x30: (1, "(({r}[0] - 256) & M64) if {r}[0] & 0x80 else {r}[0]"),
    0x31: (1, "{r}[0]"),
    0x32: (2, '((int.from_bytes({r}, "little") - 65536) & M64) '
              'if {r}[1] & 0x80 else int.from_bytes({r}, "little")'),
    0x33: (2, 'int.from_bytes({r}, "little")'),
    0x34: (4, '((int.from_bytes({r}, "little") - (1 << 32)) & M64) '
              'if {r}[3] & 0x80 else int.from_bytes({r}, "little")'),
    0x35: (4, 'int.from_bytes({r}, "little")'),
}

_STORES = {
    0x36: '({v} & M32).to_bytes(4, "little")',
    0x37: '({v} & M64).to_bytes(8, "little")',
    0x38: 'struct.pack("<f", {v})',
    0x39: 'struct.pack("<d", {v})',
    0x3A: "bytes(({v} & 0xFF,))",
    0x3B: '({v} & 0xFFFF).to_bytes(2, "little")',
    0x3C: "bytes(({v} & 0xFF,))",
    0x3D: '({v} & 0xFFFF).to_bytes(2, "little")',
    0x3E: '({v} & M32).to_bytes(4, "little")',
}


class _Unsupported(Exception):
    """Function shape the translator does not handle -> interpreter."""


class _Label:
    __slots__ = (
        "kind", "height", "arity", "targeted", "wrapped", "dead",
        "has_if", "in_else", "synthetic",
    )

    def __init__(self, kind, height, arity, targeted):
        self.kind = kind  # "block" | "loop" | "if" | "func"
        self.height = height
        self.arity = arity
        self.targeted = targeted
        self.wrapped = False
        self.dead = False
        self.has_if = False
        self.in_else = False
        self.synthetic = False  # opened inside dead code


def _find_targets(body) -> set:
    """pcs of structured ops some br targets (-1 = the function label)."""
    stack: List[int] = []
    targets = set()
    for pc, ins in enumerate(body):
        op = ins[0]
        if op in (0x02, 0x03, 0x04):
            stack.append(pc)
        elif op == 0x0B and stack:
            stack.pop()
        elif op in (0x0C, 0x0D):
            d = ins[1]
            targets.add(stack[-1 - d] if d < len(stack) else -1)
        elif op == 0x0E:
            for d in list(ins[1]) + [ins[2]]:
                targets.add(stack[-1 - d] if d < len(stack) else -1)
    return targets


class _Compiler:
    def __init__(self, module, fn, ftype):
        self.module = module
        self.fn = fn
        self.ftype = ftype
        self.lines: List[str] = []
        self.indent = 1
        self.pending_gas = 0
        self.loop_depth = 0

    # -- low-level emission ------------------------------------------------

    def emit(self, line: str) -> None:
        self.lines.append("    " * self.indent + line)

    def dedent(self) -> None:
        """Close a suite, inserting `pass` if it would be empty."""
        if self.lines and self.lines[-1].endswith(":"):
            self.emit("pass")
        self.indent -= 1

    def flush_gas(self) -> None:
        """Hard settlement: fold pending + _g into the meter (control
        boundaries, calls, host ops — places where side effects or
        control transfers require the limit check to be current)."""
        if self.pending_gas:
            self.emit(f"_g += {self.pending_gas}")
            self.pending_gas = 0
        # inline settle: on an OutOfGas raise _g stays set and the finally
        # re-charges it — harmless, the meter clamps spent to the limit
        self.emit("inst.gas.charge(_g * inst.tgas_scale)")
        self.emit("_g = 0")

    def soft_gas(self) -> None:
        """Fold pending into the local accumulator WITHOUT a meter call —
        emitted before trap-capable ops so a trap's finally-settlement
        bills exactly the instructions executed so far."""
        if self.pending_gas:
            self.emit(f"_g += {self.pending_gas}")
            self.pending_gas = 0

    # -- unwind plumbing ---------------------------------------------------

    def nearest_wrapped(self, labels) -> Optional[_Label]:
        for lb in reversed(labels):
            if lb.wrapped:
                return lb
        return None

    def emit_unwind_check(self, labels) -> None:
        """After an inner wrapped label's while, inside the current label
        chain: propagate an in-flight multi-level branch."""
        parent = self.nearest_wrapped(labels)
        if parent is None:
            return  # no outer while: no deep br can be in flight here
        self.emit("if _br:")
        self.indent += 1
        self.emit("_br -= 1")
        if parent.kind == "loop":
            self.emit("if _br == 0: continue")
        self.emit("break")
        self.indent -= 1

    def emit_br(self, labels, depth: int, height: int) -> None:
        if depth >= len(labels):
            raise _Unsupported("branch depth out of range")
        t = len(labels) - 1 - depth
        target = labels[t]
        self.flush_gas()
        if target.kind == "func":
            self.emit_return(height)
            return
        if target.kind != "loop" and target.arity:
            r = target.arity
            for j in range(r):
                src, dst = height - r + j, target.height + j
                if src != dst:
                    self.emit(f"s{dst} = s{src}")
        if not target.wrapped:
            raise _Unsupported("br to unwrapped label")  # cannot happen
        w = sum(1 for lb in labels[t + 1 :] if lb.wrapped)
        if w == 0:
            self.emit("continue" if target.kind == "loop" else "break")
        else:
            self.emit(f"_br = {w}")
            self.emit("break")

    def emit_return(self, height: int) -> None:
        self.flush_gas()
        if self.ftype.results:
            self.emit(f"return s{height - 1}")
        else:
            self.emit("return None")

    # -- main --------------------------------------------------------------

    def compile(self) -> str:
        fn, ftype, module = self.fn, self.ftype, self.module
        body = fn.body
        targets = _find_targets(body)
        nparams = len(ftype.params)
        args = ", ".join(f"l{i}" for i in range(nparams))
        self.lines.append(
            f"def _wfn(inst{', ' + args if args else ''}):"
        )
        self.emit("_br = 0")
        self.emit("_g = 0")
        self.emit("try:")
        self.indent += 1
        from .wasm import I32, I64

        for i, vt in enumerate(fn.locals):
            init = "0" if vt in (I32, I64) else "0.0"
            self.emit(f"l{nparams + i} = {init}")
        labels = [_Label("func", 0, len(ftype.results), False)]
        h = 0

        for pc, ins in enumerate(body):
            op = ins[0]
            lb = labels[-1]
            if h < 0:
                # invalid-but-decodable bytecode (e.g. drop on an empty
                # stack): the interpreter traps at RUNTIME only if the bad
                # path executes — exact parity means falling back to it
                raise _Unsupported("static stack underflow")

            # ---- dead code: skip, but keep structure ---------------------
            if lb.dead:
                if op in (0x02, 0x03, 0x04):
                    dead_lb = _Label("block", 0, 0, False)
                    dead_lb.dead = True
                    dead_lb.synthetic = True
                    labels.append(dead_lb)
                    continue
                if op == 0x05 and not lb.synthetic:
                    # true arm ended dead: else arm starts live again
                    self.dedent()
                    self.emit("else:")
                    self.indent += 1
                    lb.dead = False
                    lb.in_else = True
                    h = lb.height
                    continue
                if op == 0x0B:
                    labels.pop()
                    if not labels:
                        break
                    if lb.synthetic:
                        continue
                    # live-opened label whose body ended dead: close its
                    # emitted structure; the unwind check must still land
                    # right after its while (breaks with _br in flight exit
                    # through here)
                    if lb.has_if:
                        self.dedent()
                    if lb.wrapped:
                        self.dedent()
                        self.loop_depth -= 1
                        self.emit_unwind_check(labels)
                    live_after = (lb.targeted and lb.kind != "loop") or (
                        # an if whose true arm ended dead but which has NO
                        # else: the false path falls through the end
                        lb.has_if
                        and not lb.in_else
                    )
                    if live_after:
                        labels[-1].dead = False
                        h = lb.height + lb.arity
                        # arrivals here execute the end opcode
                        self.pending_gas += INSTRUCTION_GAS
                    else:
                        labels[-1].dead = True
                    continue
                continue

            if op != 0x0B:
                self.pending_gas += INSTRUCTION_GAS

            # ---- control -------------------------------------------------
            if op in (0x02, 0x03, 0x04):
                kind = {0x02: "block", 0x03: "loop", 0x04: "if"}[op]
                arity = 0 if ins[1] == BLOCK_EMPTY else 1
                if op == 0x04:
                    h -= 1  # condition
                new = _Label(kind, h, arity, pc in targets)
                labels.append(new)
                self.flush_gas()
                if new.targeted or kind == "loop":
                    self.loop_depth += 1
                    if self.loop_depth > MAX_LOOP_NESTING:
                        raise _Unsupported("nesting exceeds CPython limit")
                    self.emit("while True:")
                    self.indent += 1
                    new.wrapped = True
                if op == 0x04:
                    self.emit(f"if s{h}:")
                    self.indent += 1
                    new.has_if = True
                continue
            if op == 0x05:  # else (live true arm)
                self.flush_gas()
                self.dedent()
                self.emit("else:")
                self.indent += 1
                lb.in_else = True
                h = lb.height
                continue
            if op == 0x0B:  # end
                labels.pop()
                self.flush_gas()
                if not labels:
                    if lb.wrapped:
                        self.emit("break")
                        self.dedent()
                        self.loop_depth -= 1
                    self.pending_gas += INSTRUCTION_GAS  # the end itself
                    self.emit_return(h)
                    break
                if lb.has_if:
                    self.dedent()
                if lb.wrapped:
                    self.emit("break")
                    self.dedent()
                    self.loop_depth -= 1
                    self.emit_unwind_check(labels)
                # the end instruction's gas lands in the PARENT segment:
                # every arrival at this point (fallthrough, either if arm,
                # br-to-end) passes it, exactly like the interpreter
                # executing the end opcode
                self.pending_gas += INSTRUCTION_GAS
                h = lb.height + lb.arity
                continue
            if op == 0x0C:
                self.emit_br(labels, ins[1], h)
                lb.dead = True
                continue
            if op == 0x0D:
                h -= 1
                self.flush_gas()
                self.emit(f"if s{h}:")
                self.indent += 1
                self.emit_br(labels, ins[1], h)
                self.dedent()
                continue
            if op == 0x0E:  # br_table
                h -= 1
                self.flush_gas()
                tbl, default = list(ins[1]), ins[2]
                if tbl:
                    self.emit(f"_t = s{h}")
                    for k, d in enumerate(tbl):
                        self.emit(f"{'if' if k == 0 else 'elif'} _t == {k}:")
                        self.indent += 1
                        self.emit_br(labels, d, h)
                        self.dedent()
                    self.emit("else:")
                    self.indent += 1
                    self.emit_br(labels, default, h)
                    self.dedent()
                else:
                    self.emit_br(labels, default, h)
                lb.dead = True
                continue
            if op == 0x0F:
                self.emit_return(h)
                lb.dead = True
                continue
            if op == 0x00:
                self.soft_gas()
                self.emit('raise WasmTrap("unreachable")')
                lb.dead = True
                continue
            if op == 0x01:
                continue
            if op == 0x10:  # call
                callee = ins[1]
                try:
                    ct = module.func_type(callee)
                except Exception:
                    raise _Unsupported("call index out of range")
                n = len(ct.params)
                self.flush_gas()
                argl = ", ".join(f"s{h - n + j}" for j in range(n))
                h -= n
                if ct.results:
                    self.emit(f"s{h} = inst.call_index({callee}, [{argl}])")
                    h += 1
                else:
                    self.emit(f"inst.call_index({callee}, [{argl}])")
                continue
            if op == 0x11:  # call_indirect
                type_idx = ins[1]
                if type_idx >= len(module.types):
                    raise _Unsupported("type index out of range")
                want = module.types[type_idx]
                n = len(want.params)
                self.flush_gas()
                h -= 1
                self.emit(f"_t = s{h}")
                self.emit(
                    "if _t >= len(inst.table) or inst.table[_t] is None: "
                    'raise WasmTrap("undefined table element")'
                )
                self.emit("_c = inst.table[_t]")
                self.emit(
                    f"if inst.module.func_type(_c) != "
                    f"inst.module.types[{type_idx}]: "
                    'raise WasmTrap("indirect call type mismatch")'
                )
                argl = ", ".join(f"s{h - n + j}" for j in range(n))
                h -= n
                if want.results:
                    self.emit(f"s{h} = inst.call_index(_c, [{argl}])")
                    h += 1
                else:
                    self.emit(f"inst.call_index(_c, [{argl}])")
                continue
            if op == 0x1A:
                h -= 1
                continue
            if op == 0x1B:
                h -= 3
                self.emit(f"s{h} = s{h} if s{h + 2} else s{h + 1}")
                h += 1
                continue

            # ---- variables ----------------------------------------------
            if op == 0x20:
                self.emit(f"s{h} = l{ins[1]}")
                h += 1
                continue
            if op == 0x21:
                h -= 1
                self.emit(f"l{ins[1]} = s{h}")
                continue
            if op == 0x22:
                self.emit(f"l{ins[1]} = s{h - 1}")
                continue
            if op == 0x23:
                self.emit(f"s{h} = inst.globals[{ins[1]}]")
                h += 1
                continue
            if op == 0x24:
                if ins[1] >= len(module.globals):
                    raise _Unsupported("global index out of range")
                g = module.globals[ins[1]]
                if not g.mutable:
                    # trap only if EXECUTED: the interpreter tier gives
                    # that runtime behavior exactly
                    raise _Unsupported("assignment to immutable global")
                h -= 1
                self.emit(f"inst.globals[{ins[1]}] = s{h}")
                continue

            # ---- memory -------------------------------------------------
            if 0x28 <= op <= 0x35:
                nb, tpl = _LOADS[op]
                off = ins[2]
                a = f"s{h - 1}"
                addr = f"{a} + {off}" if off else a
                self.soft_gas()  # OOB load traps: bill executed ops first
                self.emit(f"_m = inst._mem_read({addr}, {nb})")
                self.emit(f"s{h - 1} = " + tpl.format(r="_m"))
                continue
            if 0x36 <= op <= 0x3E:
                off = ins[2]
                h -= 2
                addr = f"s{h} + {off}" if off else f"s{h}"
                self.soft_gas()  # OOB store traps
                self.emit(
                    f"inst._mem_write({addr}, "
                    + _STORES[op].format(v=f"s{h + 1}")
                    + ")"
                )
                continue
            if op == 0x3F:
                self.emit(f"s{h} = inst.mem_pages")
                h += 1
                continue
            if op == 0x40:
                self.flush_gas()
                self.emit(f"s{h - 1} = inst.m_grow(s{h - 1})")
                continue

            # ---- constants ----------------------------------------------
            if op == 0x41:
                self.emit(f"s{h} = {ins[1] & MASK32}")
                h += 1
                continue
            if op == 0x42:
                self.emit(f"s{h} = {ins[1] & MASK64}")
                h += 1
                continue
            if op in (0x43, 0x44):
                fmt = "<f" if op == 0x43 else "<d"
                v = _canon(_struct.unpack(fmt, ins[1])[0])
                if v != v:
                    self.emit(f"s{h} = _canon(float('nan'))")
                elif v == float("inf"):
                    self.emit(f"s{h} = float('inf')")
                elif v == float("-inf"):
                    self.emit(f"s{h} = float('-inf')")
                else:
                    self.emit(f"s{h} = {v!r}")
                h += 1
                continue

            # ---- numeric ------------------------------------------------
            if op in _BIN:
                h -= 2
                self.emit(
                    f"s{h} = " + _BIN[op].format(a=f"s{h}", b=f"s{h + 1}")
                )
                h += 1
                continue
            if op in _UN:
                self.emit(
                    f"s{h - 1} = " + _UN[op].format(a=f"s{h - 1}")
                )
                continue
            if 0x8B <= op <= 0xA6:  # float arithmetic via interpreter shim
                single = op <= 0x98
                rel = op - (0x8B if single else 0x99)
                flag = "True" if single else "False"
                if rel >= 7:
                    h -= 2
                    self.emit(
                        f"s{h} = _f2({rel}, {flag}, s{h}, s{h + 1})"
                    )
                    h += 1
                else:
                    self.emit(
                        f"s{h - 1} = _f1({rel}, {flag}, s{h - 1})"
                    )
                continue
            if op == 0xFC:
                sub = ins[1]
                if sub <= 7:
                    self.soft_gas()  # trunc traps on NaN/overflow
                    self.emit(f"s{h - 1} = _numfc({sub}, s{h - 1})")
                    continue
                if sub in (10, 11):
                    self.flush_gas()
                    h -= 3
                    d, x, n = f"s{h}", f"s{h + 1}", f"s{h + 2}"
                    self.emit(f"inst.gas.charge(BULK_GAS * {n})")
                    if sub == 10:
                        self.emit(
                            f"inst._mem_write({d}, inst._mem_read({x}, {n}))"
                        )
                    else:
                        self.emit(
                            f"inst._mem_write({d}, bytes(({x} & 0xFF,)) * {n})"
                        )
                    continue
                raise _Unsupported(f"0xfc:{sub}")
            if op in _SHIM1:
                self.soft_gas()  # conversions can trap
                self.emit(f"s{h - 1} = _num({op}, s{h - 1})")
                continue
            if op in _SHIM2:
                h -= 2
                self.soft_gas()  # div/rem trap on zero/overflow
                self.emit(f"s{h} = _num({op}, s{h}, s{h + 1})")
                h += 1
                continue
            raise _Unsupported(f"opcode 0x{op:02x}")

        # settle whatever the last executed segment accumulated — on
        # normal return AND on traps (exact interpreter gas parity)
        self.indent = 1
        self.emit("finally:")
        self.indent += 1
        self.emit("inst.gas.charge(_g * inst.tgas_scale)")
        return "\n".join(self.lines) + "\n"


def translate_function(module, fn, ftype):
    """Compile one decoded function to a Python callable, or None when the
    shape is unsupported (caller falls back to the interpreter)."""
    try:
        src = _Compiler(module, fn, ftype).compile()
        ns = dict(_ENV)
        exec(compile(src, "<wasm>", "exec"), ns)  # noqa: S102
        out = ns["_wfn"]
        out._src = src  # for tests/debugging
        return out
    except Exception:
        # ANY translation failure (unsupported shapes, malformed-but-
        # decodable indices, future compiler bugs) deterministically lands
        # on the interpreter tier, which is always semantically correct
        return None
