"""WASM binary format decoder (MVP + sign-extension + saturating truncation).

Decodes a `.wasm` module into plain dataclasses the interpreter executes.
Fills the role of the reference's `Compile.FromBinary` entry
(/root/reference/src/Lachain.Core/Blockchain/VM/VirtualMachine.cs:33-35,
backed by the dotnet-webassembly submodule); the binary layout follows the
public WebAssembly 1.0 spec.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

WASM_MAGIC = b"\x00asm"
WASM_VERSION = b"\x01\x00\x00\x00"

# value types
I32, I64, F32, F64 = 0x7F, 0x7E, 0x7D, 0x7C
FUNCREF = 0x70
VALTYPES = {I32, I64, F32, F64}
BLOCK_EMPTY = 0x40

SEC_CUSTOM = 0
SEC_TYPE = 1
SEC_IMPORT = 2
SEC_FUNCTION = 3
SEC_TABLE = 4
SEC_MEMORY = 5
SEC_GLOBAL = 6
SEC_EXPORT = 7
SEC_START = 8
SEC_ELEMENT = 9
SEC_CODE = 10
SEC_DATA = 11

PAGE_SIZE = 65536


class WasmDecodeError(Exception):
    pass


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def eof(self) -> bool:
        return self.pos >= len(self.data)

    def byte(self) -> int:
        if self.pos >= len(self.data):
            raise WasmDecodeError("unexpected end of module")
        b = self.data[self.pos]
        self.pos += 1
        return b

    def raw(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise WasmDecodeError("unexpected end of module")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def u32(self) -> int:
        """Unsigned LEB128, max 5 bytes."""
        result = 0
        shift = 0
        while True:
            b = self.byte()
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 32:
                raise WasmDecodeError("u32 LEB128 overflow")
        return result & 0xFFFFFFFF

    def s_leb(self, bits: int) -> int:
        """Signed LEB128."""
        result = 0
        shift = 0
        while True:
            b = self.byte()
            result |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                if shift < bits and (b & 0x40):
                    result |= -(1 << shift)
                break
            if shift > bits + 7:
                raise WasmDecodeError("signed LEB128 overflow")
        return result

    def i32(self) -> int:
        return self.s_leb(32)

    def i64(self) -> int:
        return self.s_leb(64)

    def f32(self) -> bytes:
        return self.raw(4)

    def f64(self) -> bytes:
        return self.raw(8)

    def name(self) -> str:
        n = self.u32()
        return self.raw(n).decode("utf-8")


@dataclass(frozen=True)
class FuncType:
    params: Tuple[int, ...]
    results: Tuple[int, ...]


@dataclass
class Import:
    module: str
    name: str
    kind: int  # 0 func, 1 table, 2 mem, 3 global
    type_idx: int = 0  # for funcs
    desc: tuple = ()


@dataclass
class Export:
    name: str
    kind: int
    index: int


@dataclass
class Global:
    valtype: int
    mutable: bool
    init: List[tuple]  # decoded init expression


@dataclass
class Function:
    type_idx: int
    locals: List[int] = field(default_factory=list)  # flattened local valtypes
    body: List[tuple] = field(default_factory=list)  # decoded instructions


@dataclass
class DataSegment:
    mem_idx: int
    offset_expr: List[tuple]
    data: bytes


@dataclass
class ElementSegment:
    table_idx: int
    offset_expr: List[tuple]
    func_indices: List[int]


@dataclass
class Module:
    types: List[FuncType] = field(default_factory=list)
    imports: List[Import] = field(default_factory=list)
    functions: List[Function] = field(default_factory=list)  # local funcs only
    func_type_indices: List[int] = field(default_factory=list)
    tables: List[Tuple[int, Optional[int]]] = field(default_factory=list)
    mem_limits: Optional[Tuple[int, Optional[int]]] = None
    globals: List[Global] = field(default_factory=list)
    exports: List[Export] = field(default_factory=list)
    start: Optional[int] = None
    elements: List[ElementSegment] = field(default_factory=list)
    data: List[DataSegment] = field(default_factory=list)

    @property
    def num_imported_funcs(self) -> int:
        return sum(1 for im in self.imports if im.kind == 0)

    def export_map(self) -> Dict[str, Export]:
        return {e.name: e for e in self.exports}

    def func_type(self, func_idx: int) -> FuncType:
        n_imp = self.num_imported_funcs
        if func_idx < n_imp:
            imps = [im for im in self.imports if im.kind == 0]
            return self.types[imps[func_idx].type_idx]
        return self.types[self.functions[func_idx - n_imp].type_idx]


# ---------------------------------------------------------------------------
# instruction decoding
# ---------------------------------------------------------------------------

# opcodes with no immediates — everything in 0x45..0xc4 plus misc
_NO_IMM = set(range(0x45, 0xC5)) | {0x00, 0x01, 0x05, 0x0B, 0x0F, 0x1A, 0x1B}


def _decode_expr(r: _Reader) -> List[tuple]:
    """Decode an instruction sequence up to (and including) the matching
    `end` of the implicit outer block. Control-flow instructions get their
    branch targets resolved in a second pass (interpreter-side sidetable).
    Each instruction is a tuple (opcode, *immediates)."""
    out: List[tuple] = []
    depth = 1
    while depth > 0:
        op = r.byte()
        if op in _NO_IMM:
            if op == 0x0B:
                depth -= 1
            elif op == 0x05:
                pass  # else — handled structurally later
            out.append((op,))
        elif op in (0x02, 0x03, 0x04):  # block / loop / if
            bt = r.byte()
            if bt != BLOCK_EMPTY and bt not in VALTYPES:
                raise WasmDecodeError(f"bad blocktype 0x{bt:02x}")
            depth += 1
            out.append((op, bt))
        elif op in (0x0C, 0x0D):  # br / br_if
            out.append((op, r.u32()))
        elif op == 0x0E:  # br_table
            n = r.u32()
            targets = tuple(r.u32() for _ in range(n))
            default = r.u32()
            out.append((op, targets, default))
        elif op == 0x10:  # call
            out.append((op, r.u32()))
        elif op == 0x11:  # call_indirect
            type_idx = r.u32()
            table_idx = r.u32()
            out.append((op, type_idx, table_idx))
        elif op in (0x20, 0x21, 0x22, 0x23, 0x24):  # local/global
            out.append((op, r.u32()))
        elif 0x28 <= op <= 0x3E:  # loads/stores: align + offset
            align = r.u32()
            offset = r.u32()
            out.append((op, align, offset))
        elif op in (0x3F, 0x40):  # memory.size / memory.grow
            r.byte()  # reserved 0x00
            out.append((op,))
        elif op == 0x41:
            out.append((op, r.i32()))
        elif op == 0x42:
            out.append((op, r.i64()))
        elif op == 0x43:
            out.append((op, r.f32()))
        elif op == 0x44:
            out.append((op, r.f64()))
        elif op == 0xFC:  # saturating truncations / bulk memory subset
            sub = r.u32()
            if sub <= 7:
                out.append((op, sub))
            elif sub == 10:  # memory.copy
                r.byte()
                r.byte()
                out.append((op, sub))
            elif sub == 11:  # memory.fill
                r.byte()
                out.append((op, sub))
            else:
                raise WasmDecodeError(f"unsupported 0xfc subopcode {sub}")
        else:
            raise WasmDecodeError(f"unsupported opcode 0x{op:02x}")
    return out


def _decode_limits(r: _Reader) -> Tuple[int, Optional[int]]:
    flag = r.byte()
    lo = r.u32()
    hi = r.u32() if flag & 1 else None
    return lo, hi


def decode_module(data: bytes) -> Module:
    if data[:4] != WASM_MAGIC:
        raise WasmDecodeError("bad magic")
    if data[4:8] != WASM_VERSION:
        raise WasmDecodeError("unsupported version")
    r = _Reader(data, 8)
    m = Module()
    last_sec = -1
    while not r.eof():
        sec = r.byte()
        size = r.u32()
        body = _Reader(r.raw(size))
        if sec != SEC_CUSTOM:
            if sec <= last_sec:
                raise WasmDecodeError(f"section {sec} out of order")
            last_sec = sec
        if sec == SEC_CUSTOM:
            continue
        elif sec == SEC_TYPE:
            for _ in range(body.u32()):
                if body.byte() != 0x60:
                    raise WasmDecodeError("bad functype tag")
                params = tuple(body.byte() for _ in range(body.u32()))
                results = tuple(body.byte() for _ in range(body.u32()))
                if len(results) > 1:
                    raise WasmDecodeError("multi-value not supported")
                m.types.append(FuncType(params, results))
        elif sec == SEC_IMPORT:
            for _ in range(body.u32()):
                mod = body.name()
                name = body.name()
                kind = body.byte()
                if kind == 0:
                    m.imports.append(Import(mod, name, 0, body.u32()))
                elif kind == 1:
                    if body.byte() != FUNCREF:
                        raise WasmDecodeError("bad table elemtype")
                    m.imports.append(Import(mod, name, 1, desc=_decode_limits(body)))
                elif kind == 2:
                    m.imports.append(Import(mod, name, 2, desc=_decode_limits(body)))
                elif kind == 3:
                    vt = body.byte()
                    mut = body.byte()
                    m.imports.append(Import(mod, name, 3, desc=(vt, mut)))
                else:
                    raise WasmDecodeError("bad import kind")
        elif sec == SEC_FUNCTION:
            m.func_type_indices = [body.u32() for _ in range(body.u32())]
        elif sec == SEC_TABLE:
            for _ in range(body.u32()):
                if body.byte() != FUNCREF:
                    raise WasmDecodeError("bad table elemtype")
                m.tables.append(_decode_limits(body))
        elif sec == SEC_MEMORY:
            n = body.u32()
            if n > 1:
                raise WasmDecodeError("multiple memories")
            if n:
                m.mem_limits = _decode_limits(body)
        elif sec == SEC_GLOBAL:
            for _ in range(body.u32()):
                vt = body.byte()
                mut = body.byte() == 1
                init = _decode_expr(body)
                m.globals.append(Global(vt, mut, init))
        elif sec == SEC_EXPORT:
            for _ in range(body.u32()):
                name = body.name()
                kind = body.byte()
                m.exports.append(Export(name, kind, body.u32()))
        elif sec == SEC_START:
            m.start = body.u32()
        elif sec == SEC_ELEMENT:
            for _ in range(body.u32()):
                tbl = body.u32()
                off = _decode_expr(body)
                funcs = [body.u32() for _ in range(body.u32())]
                m.elements.append(ElementSegment(tbl, off, funcs))
        elif sec == SEC_CODE:
            n = body.u32()
            if n != len(m.func_type_indices):
                raise WasmDecodeError("code/function count mismatch")
            for i in range(n):
                fsize = body.u32()
                fr = _Reader(body.raw(fsize))
                locals_: List[int] = []
                for _ in range(fr.u32()):
                    cnt = fr.u32()
                    vt = fr.byte()
                    if vt not in VALTYPES:
                        raise WasmDecodeError("bad local type")
                    # total cap per function, not per declaration group — a
                    # tiny module can otherwise declare ~10^11 locals via
                    # repeated groups and exhaust memory at decode time
                    if cnt + len(locals_) > 50_000:
                        raise WasmDecodeError("too many locals")
                    locals_.extend([vt] * cnt)
                fn = Function(m.func_type_indices[i], locals_, _decode_expr(fr))
                m.functions.append(fn)
        elif sec == SEC_DATA:
            for _ in range(body.u32()):
                mem = body.u32()
                off = _decode_expr(body)
                seg = body.raw(body.u32())
                m.data.append(DataSegment(mem, off, seg))
        else:
            raise WasmDecodeError(f"unknown section {sec}")
    if m.func_type_indices and len(m.functions) != len(m.func_type_indices):
        raise WasmDecodeError("missing code section")
    return m
