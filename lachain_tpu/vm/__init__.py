"""Gas-metered WASM contract VM.

Parity with the reference's VM layer
(/root/reference/src/Lachain.Core/Blockchain/VM/: VirtualMachine.cs,
ExternalHandler.cs, GasMetering.cs, ContractEncoder.cs, ContractDecoder.cs,
ExecutionFrame/) — but self-contained: the reference embeds the
dotnet-webassembly engine (a git submodule); here the engine is our own
MVP-spec interpreter, so the framework carries no external WASM dependency.
"""
from .wasm import Module, WasmDecodeError, decode_module
from .interpreter import Instance, WasmTrap, OutOfGas, GasMeter
from .vm import VirtualMachine, HaltException, InvocationResult

__all__ = [
    "Module",
    "WasmDecodeError",
    "decode_module",
    "Instance",
    "WasmTrap",
    "OutOfGas",
    "GasMeter",
    "VirtualMachine",
    "HaltException",
    "InvocationResult",
]
