"""VirtualMachine facade: contract invocation with frame stack.

Parity with the reference's VM driver
(/root/reference/src/Lachain.Core/Blockchain/VM/VirtualMachine.cs:17-113:
InvokeWasmContract/ExecuteFrame + frame stack; ExecutionFrame/*.cs). The
contract entrypoint is the exported `start` function
(WasmExecutionFrame.cs:84); calldata and results flow through the `env`
host-import table (external.py).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..crypto.hashes import keccak256
from ..storage.state import Snapshot
from . import gas as G
from .external import build_env
from .interpreter import (
    INSTRUCTION_GAS,
    INTERP_INSTRUCTION_GAS,
    GasMeter,
    Instance,
    OutOfGas,
)
from .wasm import WasmDecodeError, decode_module

MAX_FRAME_DEPTH = 16

CODE_PREFIX = b"c:"  # 'contracts' subtree: code by address

# decoded-module cache: Module objects are immutable after decode, so
# repeated/nested invocations skip the binary re-parse (keyed by code hash).
# Lock-guarded: parallel execution lanes (core/parallel_exec.py) decode
# concurrently, and an unguarded move_to_end can race a sibling's eviction
_MODULE_CACHE: "OrderedDict[bytes, object]" = None  # type: ignore[assignment]
_MODULE_CACHE_MAX = 64
_MODULE_CACHE_LOCK = threading.Lock()


def _decode_cached(code: bytes):
    global _MODULE_CACHE
    key = keccak256(code)
    with _MODULE_CACHE_LOCK:
        if _MODULE_CACHE is None:
            from collections import OrderedDict

            _MODULE_CACHE = OrderedDict()
        mod = _MODULE_CACHE.get(key)
        if mod is not None:
            _MODULE_CACHE.move_to_end(key)
            return mod
    # decode outside the lock (the expensive part); a racing duplicate
    # decode yields an equivalent immutable Module — last store wins
    mod = decode_module(code)
    with _MODULE_CACHE_LOCK:
        _MODULE_CACHE[key] = mod
        if len(_MODULE_CACHE) > _MODULE_CACHE_MAX:
            _MODULE_CACHE.popitem(last=False)
    return mod


class HaltException(Exception):
    def __init__(self, code: int):
        super().__init__(f"halt({code})")
        self.code = code


@dataclass
class InvocationResult:
    status: int  # 1 ok, 0 failed
    gas_used: int
    return_data: bytes = b""
    events: List[Tuple[bytes, bytes]] = field(default_factory=list)


def get_code(snap: Snapshot, address: bytes) -> Optional[bytes]:
    return snap.get("contracts", CODE_PREFIX + address)


def set_code(snap: Snapshot, address: bytes, code: bytes) -> None:
    snap.put("contracts", CODE_PREFIX + address, code)


def contract_address(sender: bytes, nonce: int) -> bytes:
    """Deterministic deploy address (reference DeployContract.cs builds it
    from sender+nonce)."""
    return keccak256(sender + nonce.to_bytes(8, "big"))[12:]


def create2_address(sender: bytes, salt: bytes, code: bytes) -> bytes:
    return keccak256(b"\xff" + sender + salt + keccak256(code))[12:]


class ExecutionFrame:
    """One contract activation (reference ExecutionFrame/WasmExecutionFrame.cs)."""

    def __init__(
        self,
        *,
        contract: bytes,
        storage_owner: bytes,
        sender: bytes,
        value: int,
        input: bytes,
        static: bool,
    ):
        self.contract = contract
        self.storage_owner = storage_owner  # differs under delegatecall
        self.sender = sender
        self.value = value
        self.input = input
        self.static = static
        self.return_data = b""
        self.child_return = b""
        self.halted = False
        self.instance: Optional[Instance] = None


class VirtualMachine:
    """Per-invocation VM context: snapshot, tx metadata, frame stack, meter."""

    def __init__(
        self,
        snap: Snapshot,
        *,
        block_index: int,
        origin: bytes,
        gas_price: int,
        chain_id: int,
        block_gas_limit: int = G.DEFAULT_BLOCK_GAS_LIMIT,
    ):
        self.snap = snap
        self.block_index = block_index
        self.origin = origin
        self.gas_price = gas_price
        self.chain_id = chain_id
        self.block_gas_limit = block_gas_limit
        self.frames: List[ExecutionFrame] = []
        self.events: List[Tuple[bytes, bytes]] = []
        self.gas: Optional[GasMeter] = None

    @property
    def frame(self) -> ExecutionFrame:
        return self.frames[-1]

    def invoke_contract(
        self,
        *,
        contract: bytes,
        sender: bytes,
        value: int,
        input: bytes,
        gas_limit: int,
        static: bool = False,
        code: Optional[bytes] = None,
        storage_owner: Optional[bytes] = None,
        value_from: Optional[bytes] = None,
    ) -> InvocationResult:
        """Run the `start` export of the contract at `contract`.

        `value_from`: debit/credit the call value inside this frame's
        checkpoint, so a failed call reverts the transfer too (the
        reference's per-frame snapshot/rollback gives the same guarantee).
        """
        if len(self.frames) >= MAX_FRAME_DEPTH:
            return InvocationResult(status=0, gas_used=0, return_data=b"")
        code = code if code is not None else get_code(self.snap, contract)
        if code is None:
            return InvocationResult(status=0, gas_used=0)
        top_level = not self.frames
        if top_level:
            self.gas = GasMeter(min(gas_limit, self.block_gas_limit))
            self.events = []
        meter = self.gas
        assert meter is not None
        # a nested call's gas limit bounds the CHILD's spend only: the
        # parent's limit is restored afterwards, so a child OutOfGas does
        # not poison the parent's meter
        outer_limit = meter.limit
        if not top_level and gas_limit:
            meter.limit = min(outer_limit, meter.spent + gas_limit)
        frame = ExecutionFrame(
            contract=contract,
            storage_owner=storage_owner or contract,
            sender=sender,
            value=value,
            input=input,
            static=static or (self.frames[-1].static if self.frames else False),
        )
        self.frames.append(frame)
        cp = self.snap.checkpoint()
        n_events = len(self.events)
        start_gas = meter.spent
        try:
            status = 1
            if value and value_from is not None:
                from ..core import execution

                bal = execution.get_balance(self.snap, value_from)
                if bal < value:
                    status = 0
                else:
                    execution.set_balance(self.snap, value_from, bal - value)
                    execution.set_balance(
                        self.snap,
                        contract,
                        execution.get_balance(self.snap, contract) + value,
                    )
            if status == 1:
                meter.charge(len(input) * G.INPUT_DATA_GAS_PER_BYTE)
                module = _decode_cached(code)
                frame.instance = Instance(
                    module, host=build_env(self, frame), gas=meter
                )
                from ..core import hardforks

                if not hardforks.is_active(
                    "fast_wasm_gas", self.block_index
                ):
                    # pre-fork schedule: translatable code bills the
                    # round-2 interpreter rate (2000/op) too
                    frame.instance.tgas_scale = (
                        INTERP_INSTRUCTION_GAS // INSTRUCTION_GAS
                    )
                frame.instance.invoke("start", [])
        except HaltException as e:
            status = 1 if e.code == 0 else 0
        except OutOfGas:
            status = 0
        except Exception:
            # any interpreter/host fault (including malformed-but-decodable
            # bytecode hitting IndexError/TypeError/struct.error) is a
            # deterministic trap, never a node crash
            status = 0
        finally:
            self.frames.pop()
            meter.limit = outer_limit
        gas_used = meter.spent - start_gas
        if status != 1:
            self.snap.restore(cp)
            del self.events[n_events:]
            return InvocationResult(status=0, gas_used=gas_used)
        result = InvocationResult(
            status=1, gas_used=gas_used, return_data=frame.return_data
        )
        if top_level:
            result.events = list(self.events)
        return result


def deploy_code(
    snap: Snapshot, sender: bytes, nonce: int, code: bytes
) -> Tuple[int, bytes]:
    """Validate + store contract code; returns (status, address).

    Parity: DeployContract.cs:1-213 — the code must be a decodable WASM
    module exporting `start`."""
    try:
        module = decode_module(code)
    except WasmDecodeError:
        return 0, b""
    exp = module.export_map().get("start")
    if exp is None or exp.kind != 0:
        return 0, b""
    addr = contract_address(sender, nonce)
    if get_code(snap, addr) is not None:
        return 0, b""
    set_code(snap, addr, code)
    return 1, addr
