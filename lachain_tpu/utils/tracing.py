"""Era-lifecycle span recorder: where inside an era does the time go?

The metrics registry answers "how much / how often"; this module answers
"WHEN, nested under WHAT": era start -> sub-protocol lifetimes (RBC/BA/CC/
ACS/HB) -> TPKE flush -> block persist. Spans are recorded into a bounded
in-process ring buffer (zero dependencies, thread-safe) and exported as
Chrome `trace_event` JSON — load the output of `lachain-tpu trace` (RPC
`la_getTrace`) straight into chrome://tracing or Perfetto.

Protocol lifetimes are NOT stack-shaped (dozens overlap within one era), so
the primitive is a begin()/end() handle pair rather than only a context
manager; `span()` wraps the common scoped case. The 60 s stall watchdog
attaches `open_stack_str()` to its report so a stall names the exact
protocol (and flush/persist phase) it is stuck inside.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

_lock = threading.Lock()
_ids = itertools.count(1)
# finished spans, oldest evicted first; 8192 spans ≈ a few dozen eras at
# N=16 — enough history to explain a stall without unbounded growth.
# LACHAIN_TRACE_CAPACITY (env, or config observability.traceCapacity via
# set_capacity) resizes both this ring and the native-engine rings.
DEFAULT_CAPACITY = int(os.environ.get("LACHAIN_TRACE_CAPACITY") or 8192)
_done: deque = deque(maxlen=DEFAULT_CAPACITY)
_open: "Dict[int, _Span]" = {}
# monotonic epoch so exported timestamps are small positive microseconds
_epoch = time.monotonic()

# -- native flight-recorder merge state --------------------------------------
# Sources (the native consensus engine, each native LSM store) register a
# drain callback returning ready-made event dicts: {name, cat, start, end,
# args, pid, tid, tname, [replace_key]}. `start`/`end` are time.monotonic()
# seconds (the source applies its clock-offset handshake before handing
# events over). Events carrying `replace_key` are cumulative snapshots
# (per-era dispatch-phase totals): only the latest per key is kept.
_native_sources: "Dict[str, Callable[[], List[dict]]]" = {}
_native_done: deque = deque(maxlen=DEFAULT_CAPACITY)
_native_acc: Dict[tuple, dict] = {}
# ring evictions (silent truncation made visible: satellite of ISSUE 6)
_py_dropped = 0


def _count_drop(n: int = 1) -> None:
    """Caller holds _lock. Mirrors the drop into the metrics registry."""
    global _py_dropped
    _py_dropped += n
    try:
        from . import metrics

        metrics.inc(
            "trace_events_dropped_total", n, labels={"source": "python"}
        )
    except Exception:  # metrics must never break the recorder
        pass


def dropped_total() -> int:
    """Python-ring evictions since start (native rings report their own)."""
    with _lock:
        return _py_dropped


class _Span:
    __slots__ = ("sid", "name", "cat", "start", "end", "args")

    def __init__(self, sid: int, name: str, cat: str, start: float, args):
        self.sid = sid
        self.name = name
        self.cat = cat
        self.start = start
        self.end: Optional[float] = None
        self.args: Dict[str, Any] = args

    def to_dict(self, now: Optional[float] = None) -> dict:
        end = self.end if self.end is not None else now
        return {
            "id": self.sid,
            "name": self.name,
            "cat": self.cat,
            "start": self.start,
            "end": end,
            "open": self.end is None,
            "args": dict(self.args),
        }


def begin(name: str, cat: str = "era", **args) -> int:
    """Open a span; returns its id (pass to end()/annotate())."""
    sid = next(_ids)
    sp = _Span(sid, name, cat, time.monotonic(), args)
    with _lock:
        _open[sid] = sp
    return sp.sid


def annotate(sid: int, **args) -> None:
    """Merge args into a still-open span (no-op once closed)."""
    with _lock:
        sp = _open.get(sid)
        if sp is not None:
            sp.args.update(args)


def end(sid: int, **args) -> None:
    """Close a span; idempotent (a GC sweep and a normal completion may
    both try to close the same protocol span)."""
    with _lock:
        sp = _open.pop(sid, None)
        if sp is None:
            return
        sp.end = time.monotonic()
        if args:
            sp.args.update(args)
        if _done.maxlen is not None and len(_done) == _done.maxlen:
            _count_drop()
        _done.append(sp)


def instant(name: str, cat: str = "era", **args) -> None:
    """Record a zero-duration event (block persisted, watchdog firing)."""
    sp = _Span(next(_ids), name, cat, time.monotonic(), args)
    sp.end = sp.start
    with _lock:
        if _done.maxlen is not None and len(_done) == _done.maxlen:
            _count_drop()
        _done.append(sp)


@contextmanager
def span(name: str, cat: str = "era", **args):
    """Scoped begin/end; yields the span id for annotate()."""
    sid = begin(name, cat, **args)
    try:
        yield sid
    finally:
        end(sid)


# The named wait buckets era_report() decomposes idle into. Every blocking
# point in an era thread tags itself with the resource it waits on; the
# remainder (time nothing claims) is reported as idle_unattributed.
WAIT_RESOURCES = ("net", "crypto_flush", "device", "fsync", "sched")
# Overlap precedence between wait intervals: specific resources outrank
# the broad ones. `net` is the catch-all (the hub read loop waits for
# nearly all wall time) so it only owns segments nothing else claims;
# `sched` (the native dispatch loop's queue-empty gap) brackets whatever
# host-side work starved it, so the specific cause wins when present.
_WAIT_PRIORITY = {
    "device": 0,
    "fsync": 1,
    "crypto_flush": 2,
    "sched": 3,
    "net": 4,
}


@contextmanager
def wait(resource: str, **args):
    """Scoped wait-state span: wraps a blocking call (queue get, fsync,
    device sync, socket read) so era_report() can attribute the idle it
    causes to `resource`. Also feeds the wait_seconds{resource} histogram."""
    sid = begin(f"wait.{resource}", cat="wait", resource=resource, **args)
    t0 = time.monotonic()
    try:
        yield sid
    finally:
        end(sid)
        try:
            from . import metrics

            metrics.observe_hist(
                "wait_seconds",
                time.monotonic() - t0,
                labels={"resource": resource},
            )
        except Exception:  # metrics must never break the waiter
            pass


def open_spans() -> List[dict]:
    """Snapshot of currently-open spans, oldest first (the watchdog's
    view of what the node is stuck inside)."""
    now = time.monotonic()
    with _lock:
        spans = sorted(_open.values(), key=lambda s: (s.start, s.sid))
        return [s.to_dict(now) for s in spans]


def open_stack_str() -> str:
    """Human one-liner of the open-span stack for stall reports:
    'era(era=7) > HoneyBadger > tpke.flush'."""
    parts = []
    for s in open_spans():
        era = s["args"].get("era")
        parts.append(
            f"{s['name']}(era={era})" if era is not None else s["name"]
        )
    return " > ".join(parts) if parts else "<no open spans>"


def snapshot(limit: Optional[int] = None) -> List[dict]:
    """Finished + open spans as plain dicts, oldest first."""
    now = time.monotonic()
    with _lock:
        done = list(_done)
        live = sorted(_open.values(), key=lambda s: (s.start, s.sid))
        out = [s.to_dict(now) for s in done + live]
    out.sort(key=lambda d: (d["start"], d["id"]))
    if limit is not None and limit > 0:
        out = out[-limit:]
    return out


def chrome_now_us() -> float:
    """'Now' on the exported Chrome ts axis (microseconds since this
    tracer's epoch). The anchor `la_time` serves so a fleet merger can
    align this node's trace axis with its own clock by RTT bracketing —
    the cross-node analogue of clock_offset()."""
    return (time.monotonic() - _epoch) * 1e6


# -- native flight-recorder merge --------------------------------------------


def clock_offset(native_now_ns: Callable[[], int], samples: int = 5) -> float:
    """Seconds to ADD to a native engine's monotonic ns/1e9 so its
    timestamps land on this tracer's time.monotonic axis. Both clocks are
    CLOCK_MONOTONIC on Linux, but the handshake keeps the alignment honest
    where the epochs differ: bracket the native read with two monotonic
    reads and keep the tightest bracket's midpoint."""
    best_width, best_off = None, 0.0
    for _ in range(max(samples, 1)):
        t0 = time.monotonic()
        ns = native_now_ns()
        t1 = time.monotonic()
        if best_width is None or (t1 - t0) < best_width:
            best_width = t1 - t0
            best_off = (t0 + t1) / 2 - ns / 1e9
    return best_off


def register_native_source(name: str, fn: Callable[[], List[dict]]) -> None:
    """Register a drain callback for a native engine's trace ring.

    `fn` returns event dicts with monotonic-aligned `start`/`end` seconds
    (the binding applies its clock-offset handshake), plus `pid`, `tid`,
    `pname`, `tname` lane hints for the Chrome export. Re-registering a
    name replaces the previous callback (engine restart)."""
    with _lock:
        _native_sources[name] = fn


def unregister_native_source(name: str) -> None:
    with _lock:
        _native_sources.pop(name, None)


def drain_native() -> None:
    """Pull pending events out of every registered native ring into the
    merged buffer. Cheap when rings are empty; callers sprinkle this at
    quiescent points (era end, snapshot/export time)."""
    with _lock:
        sources = list(_native_sources.items())
    for name, fn in sources:
        try:
            evs = fn()
        except Exception:
            # a closed engine must not poison the recorder; the owner
            # unregisters on close, this covers teardown races
            continue
        if not evs:
            continue
        with _lock:
            for ev in evs:
                key = ev.get("replace_key")
                if key is not None:
                    # cumulative snapshot (dispatch-phase totals):
                    # latest per key wins, no ring growth
                    _native_acc[key] = ev
                    continue
                if (
                    _native_done.maxlen is not None
                    and len(_native_done) == _native_done.maxlen
                ):
                    _count_drop()
                _native_done.append(ev)


def native_snapshot() -> List[dict]:
    """Drained native events (plus latest cumulative accumulators) as
    plain dicts, oldest first. Triggers a drain."""
    drain_native()
    with _lock:
        out = list(_native_done) + list(_native_acc.values())
    out.sort(key=lambda d: (d.get("start", 0.0), d.get("tid", 0)))
    return [dict(d) for d in out]


PY_PID = 1  # Python host process lane group in the Chrome export


def _assign_lanes(spans: List[dict]) -> List[tuple]:
    """Per-category, nesting-preserving lane assignment.

    Within one category, each lane holds a stack of enclosing span end
    times: a span may join a lane only if the lane is idle at its start
    or the span nests fully inside the lane's innermost open span.
    Overlapping-but-not-nested spans (concurrent protocol instances)
    therefore land on separate rows, while parent/child pairs stay
    stacked on one row so Perfetto renders real nesting.

    Returns [(span_dict, category, lane_index)], input order preserved.
    """
    lanes_by_cat: Dict[str, List[List[float]]] = {}
    out = []
    for d in spans:
        cat = d["cat"] or "default"
        lanes = lanes_by_cat.setdefault(cat, [])
        placed = None
        for idx, stack in enumerate(lanes):
            while stack and stack[-1] <= d["start"]:
                stack.pop()
            if not stack or d["end"] <= stack[-1]:
                stack.append(d["end"])
                placed = idx
                break
        if placed is None:
            placed = len(lanes)
            lanes.append([d["end"]])
        out.append((d, cat, placed))
    return out


def to_chrome_trace(limit: Optional[int] = None) -> dict:
    """Chrome trace_event JSON (load in chrome://tracing / Perfetto).

    Python-host spans render under pid=1 with one labeled thread-row
    group per category (nesting preserved; concurrent instances fan out
    to numbered sibling rows). Drained native-engine events render under
    their own pids with the engine's real thread roles (WAL writer,
    flusher, compactor, per-validator dispatch) as named rows, so one
    export shows the whole cross-language timeline."""
    events: List[dict] = []
    # (pid, tid) -> row label; pid -> process label
    thread_names: Dict[tuple, str] = {}
    proc_names: Dict[int, str] = {PY_PID: "python-host"}

    tid_of: Dict[tuple, int] = {}

    def py_tid(cat: str, lane: int) -> int:
        key = (cat, lane)
        if key not in tid_of:
            tid_of[key] = len(tid_of) + 1
            label = cat if lane == 0 else f"{cat}#{lane}"
            thread_names[(PY_PID, tid_of[key])] = label
        return tid_of[key]

    for d, cat, lane in _assign_lanes(snapshot(limit)):
        args = dict(d["args"])
        if d["open"]:
            args["open"] = True
        events.append(
            {
                "name": d["name"],
                "cat": d["cat"],
                "ph": "X",
                "pid": PY_PID,
                "tid": py_tid(cat, lane),
                "ts": round((d["start"] - _epoch) * 1e6, 1),
                "dur": round(max((d["end"] - d["start"]) * 1e6, 0.0), 1),
                "args": args,
            }
        )

    for ev in native_snapshot():
        pid = int(ev.get("pid", 2))
        tid = int(ev.get("tid", 0))
        if ev.get("pname"):
            proc_names[pid] = ev["pname"]
        if ev.get("tname"):
            thread_names[(pid, tid)] = ev["tname"]
        events.append(
            {
                "name": ev["name"],
                "cat": ev.get("cat", "native"),
                "ph": "X",
                "pid": pid,
                "tid": tid,
                "ts": round((ev["start"] - _epoch) * 1e6, 1),
                "dur": round(
                    max((ev["end"] - ev["start"]) * 1e6, 0.0), 1
                ),
                "args": dict(ev.get("args") or {}),
            }
        )

    meta: List[dict] = []
    for pid, label in sorted(proc_names.items()):
        meta.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": label},
            }
        )
    for (pid, tid), label in sorted(thread_names.items()):
        meta.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": label},
            }
        )
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def summary() -> dict:
    """Per-span-name aggregate: {name: {count, total_ms, max_ms, open}}."""
    agg: Dict[str, dict] = {}
    for d in snapshot():
        ent = agg.setdefault(
            d["name"], {"count": 0, "total_ms": 0.0, "max_ms": 0.0, "open": 0}
        )
        ms = (d["end"] - d["start"]) * 1e3
        ent["count"] += 1
        ent["total_ms"] = round(ent["total_ms"] + ms, 3)
        ent["max_ms"] = round(max(ent["max_ms"], ms), 3)
        if d["open"]:
            ent["open"] += 1
    return agg


# -- era phase attribution ---------------------------------------------------

# Report columns, and the precedence used when intervals overlap: a span
# counted as TPKE decrypt (device call) wins over the protocol span it is
# nested inside. Idle is derived (wall − attributed), so the table always
# sums to era wall time up to clamp error.
PHASES = (
    "propose",
    "rbc",
    "rbc_device",
    "ba",
    "coin",
    "tpke_verify",
    "tpke_decrypt",
    "exec",
    "merkle",
    "commit",
)
_PHASE_PRIORITY = {
    "tpke_decrypt": 0,
    "tpke_verify": 1,
    # merkle outranks exec: the merkle.freeze span nests inside exec.block,
    # and commit attribution must separate hashing from tx execution.
    # exec outranks commit: the block-execution span nests inside the
    # root_produce commit crossing, and the refactored executor
    # (core/parallel_exec.py) is what the exec column exists to expose
    "merkle": 2,
    "exec": 3,
    "propose": 4,
    "commit": 5,
    "coin": 6,
    "ba": 7,
    "rbc": 8,
    # rs.device spans nest inside the rbc.flush span: the device column must
    # win that overlap so host-vs-device RS time splits cleanly
    "rbc_device": 1.5,
}

# Python span name -> phase. Parent/orchestrator spans (era, HoneyBadger,
# CommonSubset, RootProtocol) are deliberately absent: their time is the
# sum of their children plus idle, so attributing them would double count.
_SPAN_PHASE = {
    "consensus.propose": "propose",
    "ReliableBroadcast": "rbc",
    "rbc.flush": "rbc",
    "rs.device": "rbc_device",
    "BinaryAgreement": "ba",
    "BinaryBroadcast": "ba",
    "CommonCoin": "coin",
    "hb.era_decrypt": "tpke_decrypt",
    "hb.apply_era_results": "tpke_decrypt",
    "exec.block": "exec",
    "merkle.freeze": "merkle",
}

# Native crossing op name -> phase (see consensus/native_hosts.py XO_NAMES).
_CROSS_PHASE = {
    "coin_sign": "coin",
    "coin_combine": "coin",
    "coin_result": "coin",
    "hb_acs": "tpke_verify",
    "hb_queue": "tpke_decrypt",
    "hb_done": "tpke_decrypt",
    "rbc_encode": "rbc",
    "rbc_need": "rbc",
    "root_input": "propose",
    "root_sign": "commit",
    "root_verify": "commit",
    "root_produce": "commit",
}

# Native dispatch-phase accumulator name -> phase (TK_PHASE records;
# exclusive message-dispatch time measured inside the C++ engine).
_DISPATCH_PHASE = {
    "rbc": "rbc",
    "ba": "ba",
    "coin": "coin",
    "tpke": "tpke_decrypt",
    "commit": "commit",
}


def _sweep(intervals: List[tuple], lo: float, hi: float) -> Dict[str, float]:
    """Exclusive per-phase time from possibly-overlapping phase intervals,
    clipped to [lo, hi]; where intervals overlap the highest-priority
    phase owns the time (so nested spans never double count)."""
    edges = {lo, hi}
    clipped = []
    for phase, s, e in intervals:
        s, e = max(s, lo), min(e, hi)
        if e > s:
            clipped.append((phase, s, e))
            edges.add(s)
            edges.add(e)
    cuts = sorted(edges)
    out = {p: 0.0 for p in PHASES}
    for i in range(len(cuts) - 1):
        s, e = cuts[i], cuts[i + 1]
        best = None
        for phase, ps, pe in clipped:
            if ps <= s and pe >= e:
                if best is None or (
                    _PHASE_PRIORITY[phase] < _PHASE_PRIORITY[best]
                ):
                    best = phase
        if best is not None:
            out[best] += e - s
    return out


def _sweep_waits(
    phase_iv: List[tuple],
    wait_iv: List[tuple],
    lo: float,
    hi: float,
) -> Dict[str, float]:
    """Exclusive per-resource wait time on the stretches of [lo, hi] that
    NO phase interval covers: any attributed phase time outranks every
    wait (a wait span bracketing real work must not double count), and
    overlapping waits resolve by _WAIT_PRIORITY."""
    edges = {lo, hi}
    phases = []
    for _, s, e in phase_iv:
        s, e = max(s, lo), min(e, hi)
        if e > s:
            phases.append((s, e))
            edges.add(s)
            edges.add(e)
    waits = []
    for res, s, e in wait_iv:
        s, e = max(s, lo), min(e, hi)
        if e > s:
            waits.append((res, s, e))
            edges.add(s)
            edges.add(e)
    cuts = sorted(edges)
    out = {r: 0.0 for r in WAIT_RESOURCES}
    for i in range(len(cuts) - 1):
        s, e = cuts[i], cuts[i + 1]
        if any(ps <= s and pe >= e for ps, pe in phases):
            continue
        best = None
        for res, ws, we in waits:
            if ws <= s and we >= e:
                pr = _WAIT_PRIORITY.get(res, len(_WAIT_PRIORITY))
                if best is None or pr < best[0]:
                    best = (pr, res)
        if best is not None:
            out.setdefault(best[1], 0.0)
            out[best[1]] += e - s
    return out


def _critical_path(intervals: List[tuple], lo: float, hi: float) -> dict:
    """Longest blocking chain through one era window.

    `intervals` are (kind, name, start, end) with kind in
    {"phase", "wait"}. Walk BACKWARDS from the era end (the commit): at
    each cursor pick the covering interval that reaches furthest back and
    emit one segment per hop; stretches nothing covers become
    "gap"/"unattributed" segments (native dispatch accumulators have no
    intervals, so engine dispatch time lands here, bounded by crossings
    and wait records on either side). By construction the segments tile
    [lo, hi], so their lengths sum to the era wall."""
    eps = 1e-9
    iv = [
        (kind, name, max(s, lo), min(e, hi))
        for kind, name, s, e in intervals
        if min(e, hi) > max(s, lo)
    ]
    segs: List[dict] = []
    cursor = hi
    while cursor - lo > eps:
        best = None
        for kind, name, s, e in iv:
            if s < cursor - eps and e >= cursor - eps:
                if best is None or s < best[2]:
                    best = (kind, name, s)
        if best is not None:
            start = max(best[2], lo)
            segs.append(
                {"kind": best[0], "name": best[1],
                 "start": start, "end": cursor}
            )
            cursor = start
        else:
            prev = lo
            for _, _, s, e in iv:
                if e < cursor - eps and e > prev:
                    prev = e
            segs.append(
                {"kind": "gap", "name": "unattributed",
                 "start": prev, "end": cursor}
            )
            cursor = prev
    segs.reverse()
    merged: List[dict] = []
    for sg in segs:
        if (
            merged
            and merged[-1]["kind"] == sg["kind"]
            and merged[-1]["name"] == sg["name"]
        ):
            merged[-1]["end"] = sg["end"]
        else:
            merged.append(dict(sg))
    out_segs = [
        {
            "kind": sg["kind"],
            "name": sg["name"],
            "start_s": round(sg["start"] - lo, 6),
            "end_s": round(sg["end"] - lo, 6),
            "dur_s": round(sg["end"] - sg["start"], 6),
        }
        for sg in merged
    ]
    top = sorted(out_segs, key=lambda s: -s["dur_s"])[:5]
    return {
        "total_s": round(sum(s["dur_s"] for s in out_segs), 6),
        "segments": out_segs,
        "top": [
            {"kind": s["kind"], "name": s["name"], "dur_s": s["dur_s"]}
            for s in top
        ],
    }


def era_report(
    spans: Optional[List[dict]] = None,
    native: Optional[List[dict]] = None,
) -> dict:
    """Per-era phase attribution: where does era wall time go?

    Combines three sources: Python protocol/crypto spans (interval sweep
    with nesting priority), native crossing events (batched crypto ops,
    from the drained consensus ring), and the engine's per-era exclusive
    dispatch accumulators. Idle = wall − attributed, clamped at 0, then
    DECOMPOSED into named wait buckets (waits_s, from wait.* spans and
    native wait records) plus an idle_unattributed remainder — the
    invariant is buckets + remainder == the old idle value. Each era also
    carries a critical_path block: the longest blocking chain walked
    backwards from the era's end, whose segments tile the era wall. The
    direct input for deciding what to overlap when pipelining eras
    (ROADMAP item 1)."""
    if spans is None:
        spans = snapshot()
    if native is None:
        native = native_snapshot()

    # era window = union over every node's "era" span for that era number
    windows: Dict[int, List[float]] = {}
    for d in spans:
        if d["name"] == "era" and d["args"].get("era") is not None:
            era = int(d["args"]["era"])
            w = windows.setdefault(era, [d["start"], d["end"]])
            w[0] = min(w[0], d["start"])
            w[1] = max(w[1], d["end"])

    per_era_iv: Dict[int, List[tuple]] = {e: [] for e in windows}
    for d in spans:
        phase = _SPAN_PHASE.get(d["name"])
        era = d["args"].get("era")
        if phase is None or era is None or int(era) not in per_era_iv:
            continue
        per_era_iv[int(era)].append((phase, d["start"], d["end"]))

    # mesh device-busy windows (parallel/mesh.MeshEraPipeline spans the
    # kernel dispatch -> result-ready interval as "mesh.device"): these are
    # era-agnostic — the pipeline serves every validator's chunks — so they
    # attribute to eras by time overlap with each era window
    mesh_spans = [
        d for d in spans
        if d["name"] == "mesh.device" and d["end"] is not None
    ]

    # wait-state intervals (Python wait.* spans + native wait records):
    # attributed to eras by time overlap — a hub read wait or an LSM
    # fsync wait serves the node, not one era, so clipping is the honest
    # split (same rule as mesh.device above)
    wait_iv_all: List[tuple] = []
    for d in spans:
        if d["cat"] == "wait" and d["end"] is not None:
            res = d["args"].get("resource") or "net"
            wait_iv_all.append((res, d["start"], d["end"]))

    dispatch: Dict[int, Dict[str, float]] = {}
    for ev in native:
        if ev.get("cat") == "native.wait":
            res = (ev.get("args") or {}).get("resource") or "sched"
            wait_iv_all.append((res, ev["start"], ev["end"]))
            continue
        era = (ev.get("args") or {}).get("era")
        if era is None or int(era) not in windows:
            continue
        era = int(era)
        if ev.get("cat") == "native.cross":
            phase = _CROSS_PHASE.get((ev.get("args") or {}).get("op"))
            if phase is not None:
                per_era_iv[era].append((phase, ev["start"], ev["end"]))
        elif ev.get("cat") == "native.phase":
            phase = _DISPATCH_PHASE.get((ev.get("args") or {}).get("phase"))
            if phase is not None:
                acc = dispatch.setdefault(era, {})
                acc[phase] = acc.get(phase, 0.0) + float(
                    (ev.get("args") or {}).get("dur_ns", 0)
                ) / 1e9

    eras = []
    for era in sorted(windows):
        lo, hi = windows[era]
        wall = max(hi - lo, 0.0)
        # pipelining overlap: how much of this era's window was shared
        # with ANY other in-flight era (intersection with the union of the
        # other windows). 0 everywhere means the eras ran sequentially.
        other = sorted(
            (max(s, lo), min(e, hi))
            for o, (s, e) in windows.items()
            if o != era and min(e, hi) > max(s, lo)
        )
        overlap = 0.0
        cur_s = cur_e = None
        for s, e in other:
            if cur_e is None or s > cur_e:
                if cur_e is not None:
                    overlap += cur_e - cur_s
                cur_s, cur_e = s, e
            else:
                cur_e = max(cur_e, e)
        if cur_e is not None:
            overlap += cur_e - cur_s
        phases = _sweep(per_era_iv[era], lo, hi)
        # engine dispatch time is measured OUTSIDE the crossing callbacks
        # (cross time subtracted natively), so it is exclusive of every
        # interval above and adds linearly
        for phase, secs in dispatch.get(era, {}).items():
            phases[phase] += secs
        attributed = sum(phases.values())
        idle = max(wall - attributed, 0.0)
        # idle decomposition: exclusive wait coverage on the un-attributed
        # stretches of the window. The dispatch accumulators above occupy
        # unswept wall time, so raw wait coverage can exceed the idle
        # residual; scale the buckets down proportionally so
        # buckets + remainder always equal the old idle value exactly.
        wait_iv = [
            (res, s, e) for res, s, e in wait_iv_all
            if min(e, hi) > max(s, lo)
        ]
        waits = _sweep_waits(per_era_iv[era], wait_iv, lo, hi)
        wsum = sum(waits.values())
        if wsum > idle and wsum > 0:
            scale = idle / wsum
            waits = {r: v * scale for r, v in waits.items()}
            wsum = idle
        unattr = max(idle - wsum, 0.0)
        cpath = _critical_path(
            [("phase", p, s, e) for p, s, e in per_era_iv[era]]
            + [("wait", res, s, e) for res, s, e in wait_iv],
            lo,
            hi,
        )
        # per-device utilization row: union of mesh.device (dispatch ->
        # ready) windows clipped to this era, all_gather bytes pro-rated by
        # the clipped fraction. busy/wall is an upper bound on device
        # utilization (the ready edge is observed when the caller blocks)
        dev_iv = []
        dev_mb = 0.0
        dev_n = 0
        for d in mesh_spans:
            cs, ce = max(d["start"], lo), min(d["end"], hi)
            if ce <= cs:
                continue
            dev_iv.append((cs, ce))
            dur = d["end"] - d["start"]
            if dur > 0:
                dev_mb += float(
                    d["args"].get("allgather_mb", 0.0)
                ) * (ce - cs) / dur
            dev_n = max(dev_n, int(d["args"].get("devices", 0)))
        dev_iv.sort()
        busy = 0.0
        cur_s = cur_e = None
        for cs, ce in dev_iv:
            if cur_e is None or cs > cur_e:
                if cur_e is not None:
                    busy += cur_e - cur_s
                cur_s, cur_e = cs, ce
            else:
                cur_e = max(cur_e, ce)
        if cur_e is not None:
            busy += cur_e - cur_s
        eras.append(
            {
                "era": era,
                "wall_s": round(wall, 6),
                "phases_s": {p: round(phases[p], 6) for p in PHASES},
                "idle_s": round(idle, 6),
                "waits_s": {
                    r: round(waits.get(r, 0.0), 6) for r in WAIT_RESOURCES
                },
                "idle_unattributed_s": round(unattr, 6),
                "idle_unattributed_fraction": round(unattr / idle, 4)
                if idle > 0
                else 0.0,
                "critical_path": cpath,
                "overlap_s": round(overlap, 6),
                "attributed_s": round(attributed, 6),
                "coverage": round(
                    (attributed + idle) / wall, 4
                ) if wall > 0 else 1.0,
                "device": {
                    "busy_s": round(busy, 6),
                    "util": round(busy / wall, 4) if wall > 0 else 0.0,
                    "allgather_mb": round(dev_mb, 3),
                    "mesh_devices": dev_n,
                },
            }
        )
    # Byzantine pressure per era (evidence.py per-process registry): how
    # many NEW equivocation / invalid-share records this process minted
    # while the era ran — `trace --era-report` surfaces attack visibility
    # next to the phase timings it distorts
    try:
        from ..consensus.evidence import era_counts

        by_era = era_counts()
        for ent in eras:
            ent["byzantine"] = dict(
                by_era.get(ent["era"], {"equivocation": 0, "invalid_share": 0})
            )
    except Exception:
        pass  # evidence module must never break the report
    return {"eras": eras, "phases": list(PHASES)}


def era_report_table(report: Optional[dict] = None) -> str:
    """Plain-text per-era phase table (CLI `trace --era-report`)."""
    if report is None:
        report = era_report()
    cols = (
        ["era", "wall_s"] + list(PHASES)
        + ["idle_s"] + [f"w:{r}" for r in WAIT_RESOURCES]
        + ["unattr_s", "overlap_s", "dev_util", "equiv", "badshare"]
    )
    rows = [cols]
    for ent in report["eras"]:
        dev = ent.get("device") or {}
        waits = ent.get("waits_s") or {}
        byz = ent.get("byzantine") or {}
        rows.append(
            [str(ent["era"]), f"{ent['wall_s']:.3f}"]
            + [f"{ent['phases_s'][p]:.3f}" for p in PHASES]
            + [f"{ent['idle_s']:.3f}"]
            + [f"{waits.get(r, 0.0):.3f}" for r in WAIT_RESOURCES]
            + [
                f"{ent.get('idle_unattributed_s', 0.0):.3f}",
                f"{ent.get('overlap_s', 0.0):.3f}",
                f"{dev.get('util', 0.0):.3f}",
                str(byz.get("equivocation", 0)),
                str(byz.get("invalid_share", 0)),
            ]
        )
    if len(rows) == 1:
        return "<no completed eras in trace ring>"
    widths = [max(len(r[i]) for r in rows) for i in range(len(cols))]
    lines = [
        "  ".join(cell.rjust(widths[i]) for i, cell in enumerate(row))
        for row in rows
    ]
    lines.insert(1, "  ".join("-" * w for w in widths))
    return "\n".join(lines)


def critical_path_table(report: Optional[dict] = None) -> str:
    """Plain-text per-era critical-path chains (CLI `trace
    --critical-path`): each era's longest blocking chain from start to
    commit, one row per merged segment, offsets relative to era start."""
    if report is None:
        report = era_report()
    lines: List[str] = []
    for ent in report["eras"]:
        cp = ent.get("critical_path") or {}
        lines.append(
            f"era {ent['era']}: critical path "
            f"{cp.get('total_s', 0.0):.3f}s "
            f"(era wall {ent['wall_s']:.3f}s)"
        )
        for sg in cp.get("segments", ()):
            lines.append(
                f"  {sg['start_s']:>10.3f}s -> {sg['end_s']:>10.3f}s  "
                f"{sg['dur_s']:>9.3f}s  {sg['kind']}:{sg['name']}"
            )
    return "\n".join(lines) if lines else "<no completed eras in trace ring>"


def set_capacity(n: int) -> None:
    """Resize the merged span rings (keeps the newest spans). Native
    in-engine ring capacities are configured via their bindings."""
    global _done, _native_done
    with _lock:
        _done = deque(_done, maxlen=max(int(n), 1))
        _native_done = deque(_native_done, maxlen=max(int(n), 1))


def reset_for_tests() -> None:
    global _done, _native_done, _py_dropped
    with _lock:
        _done = deque(maxlen=DEFAULT_CAPACITY)
        _open.clear()
        _native_done = deque(maxlen=DEFAULT_CAPACITY)
        _native_acc.clear()
        _native_sources.clear()
        _py_dropped = 0
