"""Era-lifecycle span recorder: where inside an era does the time go?

The metrics registry answers "how much / how often"; this module answers
"WHEN, nested under WHAT": era start -> sub-protocol lifetimes (RBC/BA/CC/
ACS/HB) -> TPKE flush -> block persist. Spans are recorded into a bounded
in-process ring buffer (zero dependencies, thread-safe) and exported as
Chrome `trace_event` JSON — load the output of `lachain-tpu trace` (RPC
`la_getTrace`) straight into chrome://tracing or Perfetto.

Protocol lifetimes are NOT stack-shaped (dozens overlap within one era), so
the primitive is a begin()/end() handle pair rather than only a context
manager; `span()` wraps the common scoped case. The 60 s stall watchdog
attaches `open_stack_str()` to its report so a stall names the exact
protocol (and flush/persist phase) it is stuck inside.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_ids = itertools.count(1)
# finished spans, oldest evicted first; 8192 spans ≈ a few dozen eras at
# N=16 — enough history to explain a stall without unbounded growth
DEFAULT_CAPACITY = 8192
_done: deque = deque(maxlen=DEFAULT_CAPACITY)
_open: "Dict[int, _Span]" = {}
# monotonic epoch so exported timestamps are small positive microseconds
_epoch = time.monotonic()


class _Span:
    __slots__ = ("sid", "name", "cat", "start", "end", "args")

    def __init__(self, sid: int, name: str, cat: str, start: float, args):
        self.sid = sid
        self.name = name
        self.cat = cat
        self.start = start
        self.end: Optional[float] = None
        self.args: Dict[str, Any] = args

    def to_dict(self, now: Optional[float] = None) -> dict:
        end = self.end if self.end is not None else now
        return {
            "id": self.sid,
            "name": self.name,
            "cat": self.cat,
            "start": self.start,
            "end": end,
            "open": self.end is None,
            "args": dict(self.args),
        }


def begin(name: str, cat: str = "era", **args) -> int:
    """Open a span; returns its id (pass to end()/annotate())."""
    sid = next(_ids)
    sp = _Span(sid, name, cat, time.monotonic(), args)
    with _lock:
        _open[sid] = sp
    return sp.sid


def annotate(sid: int, **args) -> None:
    """Merge args into a still-open span (no-op once closed)."""
    with _lock:
        sp = _open.get(sid)
        if sp is not None:
            sp.args.update(args)


def end(sid: int, **args) -> None:
    """Close a span; idempotent (a GC sweep and a normal completion may
    both try to close the same protocol span)."""
    with _lock:
        sp = _open.pop(sid, None)
        if sp is None:
            return
        sp.end = time.monotonic()
        if args:
            sp.args.update(args)
        _done.append(sp)


def instant(name: str, cat: str = "era", **args) -> None:
    """Record a zero-duration event (block persisted, watchdog firing)."""
    sp = _Span(next(_ids), name, cat, time.monotonic(), args)
    sp.end = sp.start
    with _lock:
        _done.append(sp)


@contextmanager
def span(name: str, cat: str = "era", **args):
    """Scoped begin/end; yields the span id for annotate()."""
    sid = begin(name, cat, **args)
    try:
        yield sid
    finally:
        end(sid)


def open_spans() -> List[dict]:
    """Snapshot of currently-open spans, oldest first (the watchdog's
    view of what the node is stuck inside)."""
    now = time.monotonic()
    with _lock:
        spans = sorted(_open.values(), key=lambda s: (s.start, s.sid))
        return [s.to_dict(now) for s in spans]


def open_stack_str() -> str:
    """Human one-liner of the open-span stack for stall reports:
    'era(era=7) > HoneyBadger > tpke.flush'."""
    parts = []
    for s in open_spans():
        era = s["args"].get("era")
        parts.append(
            f"{s['name']}(era={era})" if era is not None else s["name"]
        )
    return " > ".join(parts) if parts else "<no open spans>"


def snapshot(limit: Optional[int] = None) -> List[dict]:
    """Finished + open spans as plain dicts, oldest first."""
    now = time.monotonic()
    with _lock:
        done = list(_done)
        live = sorted(_open.values(), key=lambda s: (s.start, s.sid))
        out = [s.to_dict(now) for s in done + live]
    out.sort(key=lambda d: (d["start"], d["id"]))
    if limit is not None and limit > 0:
        out = out[-limit:]
    return out


def to_chrome_trace(limit: Optional[int] = None) -> dict:
    """Chrome trace_event JSON (load in chrome://tracing / Perfetto).

    All events share one pid; tid is a lane assigned greedily so spans
    that overlap in time (concurrent protocol instances) land on separate
    rows instead of rendering as a false stack."""
    events = []
    # lane -> end time of the last span placed there
    lanes: List[float] = []
    for d in snapshot(limit):
        start_us = (d["start"] - _epoch) * 1e6
        dur_us = max((d["end"] - d["start"]) * 1e6, 0.0)
        for tid, busy_until in enumerate(lanes):
            if d["start"] >= busy_until:
                lanes[tid] = d["end"]
                break
        else:
            tid = len(lanes)
            lanes.append(d["end"])
        args = dict(d["args"])
        if d["open"]:
            args["open"] = True
        events.append(
            {
                "name": d["name"],
                "cat": d["cat"],
                "ph": "X",
                "pid": 1,
                "tid": tid,
                "ts": round(start_us, 1),
                "dur": round(dur_us, 1),
                "args": args,
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def summary() -> dict:
    """Per-span-name aggregate: {name: {count, total_ms, max_ms, open}}."""
    agg: Dict[str, dict] = {}
    for d in snapshot():
        ent = agg.setdefault(
            d["name"], {"count": 0, "total_ms": 0.0, "max_ms": 0.0, "open": 0}
        )
        ms = (d["end"] - d["start"]) * 1e3
        ent["count"] += 1
        ent["total_ms"] = round(ent["total_ms"] + ms, 3)
        ent["max_ms"] = round(max(ent["max_ms"], ms), 3)
        if d["open"]:
            ent["open"] += 1
    return agg


def set_capacity(n: int) -> None:
    """Resize the finished-span ring (keeps the newest spans)."""
    global _done
    with _lock:
        _done = deque(_done, maxlen=max(int(n), 1))


def reset_for_tests() -> None:
    global _done
    with _lock:
        _done = deque(maxlen=DEFAULT_CAPACITY)
        _open.clear()
