"""Fixed-width binary serialization helpers.

Parity with the reference's Lachain.Utility serialization layer
(/root/reference/src/Lachain.Utility/Serialization/FixedWithSerializer.cs:1-76):
length-prefixed concatenation of fixed-width fields, plus varint/bytes codecs
used across consensus messages and storage records.
"""
from __future__ import annotations

import struct
from typing import List, Sequence


def write_u16(v: int) -> bytes:
    return struct.pack(">H", v)


def write_u32(v: int) -> bytes:
    return struct.pack(">I", v)


def write_u64(v: int) -> bytes:
    return struct.pack(">Q", v)


def write_i64(v: int) -> bytes:
    return struct.pack(">q", v)


def write_u256(v: int) -> bytes:
    return v.to_bytes(32, "big")


def write_bytes(b: bytes) -> bytes:
    """Length-prefixed byte string (u32 big-endian length)."""
    return write_u32(len(b)) + b


def write_bytes_list(items: Sequence[bytes]) -> bytes:
    return write_u32(len(items)) + b"".join(write_bytes(i) for i in items)


class Reader:
    """Cursor-based reader matching the writers above."""

    def __init__(self, data: bytes):
        self._d = data
        self._o = 0

    def _take(self, n: int) -> bytes:
        if self._o + n > len(self._d):
            raise ValueError("serialization underrun")
        out = self._d[self._o : self._o + n]
        self._o += n
        return out

    def u16(self) -> int:
        return struct.unpack(">H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack(">Q", self._take(8))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def u256(self) -> int:
        return int.from_bytes(self._take(32), "big")

    def bytes_(self) -> bytes:
        return self._take(self.u32())

    def bytes_list(self) -> List[bytes]:
        return [self.bytes_() for _ in range(self.u32())]

    def raw(self, n: int) -> bytes:
        return self._take(n)

    def rest(self) -> bytes:
        """Everything remaining (consumes it)."""
        return self._take(len(self._d) - self._o)

    def eof(self) -> bool:
        return self._o == len(self._d)

    def assert_eof(self) -> None:
        if not self.eof():
            raise ValueError("trailing bytes in serialized record")
