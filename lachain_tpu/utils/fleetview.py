"""Fleet-wide observability merge: ONE Chrome trace for N nodes.

Each node exports its own trace ring over RPC (`la_getTrace`) with
timestamps on its private monotonic axis — useless side by side until
the axes are aligned. This module scrapes every node, aligns clocks by
RTT-bracketed `la_time` pings (keep the tightest bracket, take its
midpoint — the over-the-wire analogue of tracing.clock_offset), and
emits a single Chrome trace_event JSON where every node keeps its own
pid lane block. A sampled transaction's `tx.*` lifecycle instants and
the deterministic per-era wire trace ids (network/wire.era_trace_id)
then line up ACROSS lanes: search the merged trace for the 16-hex-char
trace id and Perfetto highlights the tx's submit→pool→propose→decide→
exec→commit path on whichever nodes touched it.

Also builds the fleet era table: per-node era wall/phase durations from
`la_getEraReport`, with per-phase skew (max−min across validators) and
slowest-validator attribution — the first question of any consensus
latency hunt ("WHO is the straggler, and in which phase?") answered
without eyeballing N separate reports.

Stdlib-only (urllib): the merger must run from an operator laptop or a
CI step with no extra dependencies.
"""
from __future__ import annotations

import json
import time
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

# pid namespacing: node i owns [_PID_STRIDE*(i+1), _PID_STRIDE*(i+2)) in
# the merged trace; within the block, the node's original pids (python
# host = 1, native engines = 2+) keep their relative positions
_PID_STRIDE = 100


def _rpc(
    url: str,
    method: str,
    params: Sequence = (),
    timeout: float = 10.0,
    api_key: Optional[str] = None,
):
    body = json.dumps(
        {"jsonrpc": "2.0", "id": 1, "method": method, "params": list(params)}
    ).encode()
    headers = {"Content-Type": "application/json"}
    if api_key:
        headers["X-Api-Key"] = api_key
    req = urllib.request.Request(url, data=body, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        out = json.loads(resp.read())
    if "error" in out:
        raise RuntimeError(
            f"{method} on {url}: {out['error'].get('message', out['error'])}"
        )
    return out["result"]


def probe_offset(
    url: str,
    samples: int = 5,
    timeout: float = 10.0,
    api_key: Optional[str] = None,
    _call=None,
) -> Dict[str, float]:
    """Microseconds to ADD to the node's Chrome ts axis to land on the
    merger's local monotonic axis, found by RTT bracketing: read the
    local clock, ping `la_time`, read again; the node's answer happened
    somewhere inside the bracket, so the tightest bracket's midpoint is
    the best alignment and half its width bounds the error. `_call`
    is a test seam (same signature as the la_time round trip)."""
    call = _call or (
        lambda: _rpc(url, "la_time", timeout=timeout, api_key=api_key)
    )
    best_width = None
    best = {"offset_us": 0.0, "uncertainty_us": 0.0, "wall_skew_us": 0.0}
    for _ in range(max(samples, 1)):
        m0 = time.monotonic() * 1e6
        w0 = time.time() * 1e6
        res = call()
        m1 = time.monotonic() * 1e6
        w1 = time.time() * 1e6
        width = m1 - m0
        if best_width is None or width < best_width:
            best_width = width
            best = {
                "offset_us": round((m0 + m1) / 2 - float(res["traceUs"]), 1),
                "uncertainty_us": round(width / 2, 1),
                # wall skew is diagnostic only (NTP drift between hosts);
                # the merge itself never trusts wall clocks
                "wall_skew_us": round(
                    (w0 + w1) / 2 - float(res["wallUs"]), 1
                ),
            }
    return best


def scrape_node(
    url: str,
    name: str,
    samples: int = 5,
    timeout: float = 10.0,
    api_key: Optional[str] = None,
) -> Dict[str, object]:
    """One node's full observability snapshot. Offset is probed FIRST
    (before the heavy trace download) so the brackets stay tight. Parts
    degrade independently: a node with tracing disabled still lands in
    the era table, a health endpoint mid-restart still leaves the trace
    usable — each failed part is recorded under "errors"."""
    out: Dict[str, object] = {
        "url": url,
        "name": name,
        "offset": None,
        "trace": None,
        "eraReport": None,
        "health": None,
        "errors": {},
    }
    errors: Dict[str, str] = out["errors"]  # type: ignore[assignment]
    try:
        out["offset"] = probe_offset(
            url, samples=samples, timeout=timeout, api_key=api_key
        )
    except Exception as e:  # noqa: BLE001 — record and degrade
        errors["offset"] = str(e)
    for key, method in (
        ("trace", "la_getTrace"),
        ("eraReport", "la_getEraReport"),
        ("health", "la_getHealth"),
    ):
        try:
            out[key] = _rpc(url, method, timeout=timeout, api_key=api_key)
        except Exception as e:  # noqa: BLE001
            errors[key] = str(e)
    return out


def merge_traces(nodes: List[Dict[str, object]]) -> dict:
    """Fold per-node Chrome traces into one. Every event's pid moves into
    its node's pid block, its ts shifts by the node's probed offset onto
    the merger's axis, and the whole fleet is re-based so the earliest
    event sits at ts=0. Nodes whose offset probe failed keep offset 0 —
    their lane renders, visibly mis-aligned, rather than disappearing.

    The returned dict is valid Chrome trace JSON; the extra top-level
    "fleet" key (per-node pid base, offset, uncertainty, health verdict)
    is ignored by viewers and consumed by the era table / CI tooling."""
    events: List[dict] = []
    meta: List[dict] = []
    fleet: List[dict] = []
    for i, node in enumerate(nodes):
        base = _PID_STRIDE * (i + 1)
        offset = node.get("offset") or {}
        off_us = float(offset.get("offset_us", 0.0))
        health = node.get("health") or {}
        fleet.append(
            {
                "name": node["name"],
                "url": node.get("url"),
                "pidBase": base,
                "offsetUs": off_us,
                "uncertaintyUs": offset.get("uncertainty_us"),
                "wallSkewUs": offset.get("wall_skew_us"),
                "status": health.get("status"),
                # WAN posture (PR 18): worst peer SRTT, the RTT-scaled
                # stall budget actually in force, and the node's wire
                # version — a mixed-version fleet mid-rolling-upgrade is
                # visible here without shelling into nodes
                "rttMaxMs": health.get("rttMaxMs"),
                "stallTimeoutEffective": health.get("stallTimeoutEffective"),
                "wireVersion": health.get("wireVersion"),
                "errors": node.get("errors") or {},
            }
        )
        trace = node.get("trace") or {}
        named_pids = set()
        for ev in trace.get("traceEvents", ()):
            ev = dict(ev)
            ev["pid"] = base + int(ev.get("pid", 0))
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    # lane labels carry the node name: "node2 python-host"
                    args = dict(ev.get("args") or {})
                    args["name"] = f"{node['name']} {args.get('name', '')}"
                    ev["args"] = args
                    named_pids.add(ev["pid"])
                meta.append(ev)
                continue
            ev["ts"] = float(ev.get("ts", 0.0)) + off_us
            events.append(ev)
        # nodes emitting events on a pid with no process_name meta would
        # render as an anonymous lane — synthesize a label
        for pid in sorted(
            {e["pid"] for e in events if base <= e["pid"] < base + _PID_STRIDE}
            - named_pids
        ):
            meta.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": 0,
                    "args": {
                        "name": f"{node['name']} pid{pid - base}"
                    },
                }
            )
    if events:
        t0 = min(e["ts"] for e in events)
        for ev in events:
            ev["ts"] = round(ev["ts"] - t0, 1)
    events.sort(key=lambda e: e["ts"])
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "fleet": {"nodes": fleet},
    }


def fleet_era_report(nodes: List[Dict[str, object]]) -> dict:
    """Cross-validator era comparison from the per-node era reports:
    for every era any node completed, the per-node wall time, the
    slowest validator (the straggler consensus waits on), and per-phase
    skew (max−min across the nodes that saw the era — a phase with high
    skew on low mean is one validator's private problem, not a fleet
    regression)."""
    per_era: Dict[int, Dict[str, dict]] = {}
    phases: List[str] = []
    for node in nodes:
        rep = node.get("eraReport") or {}
        for p in rep.get("phases", ()):
            if p not in phases:
                phases.append(p)
        for ent in rep.get("eras", ()):
            per_era.setdefault(int(ent["era"]), {})[
                str(node["name"])
            ] = ent
    eras = []
    for era in sorted(per_era):
        by_node = per_era[era]
        walls = {n: float(e["wall_s"]) for n, e in by_node.items()}
        slowest = max(walls, key=walls.get)  # type: ignore[arg-type]
        phase_skew = {}
        for p in phases:
            vals = [
                float((e.get("phases_s") or {}).get(p, 0.0))
                for e in by_node.values()
            ]
            phase_skew[p] = round(max(vals) - min(vals), 6) if vals else 0.0
        worst_phase = (
            max(phase_skew, key=phase_skew.get) if phase_skew else None
        )
        eras.append(
            {
                "era": era,
                "wall_s": {n: round(w, 6) for n, w in walls.items()},
                "slowest": slowest,
                "wall_skew_s": round(
                    max(walls.values()) - min(walls.values()), 6
                ),
                "phase_skew_s": phase_skew,
                "worst_phase": worst_phase,
            }
        )
    return {"eras": eras, "phases": phases}


def fleet_era_table(report: dict) -> str:
    """Plain-text rendering of fleet_era_report for the CLI."""
    eras = report.get("eras", [])
    if not eras:
        return "<no completed eras reported by any node>"
    names = sorted({n for ent in eras for n in ent["wall_s"]})
    cols = (
        ["era"]
        + [f"{n}_wall_s" for n in names]
        + ["skew_s", "slowest", "worst_phase", "phase_skew_s"]
    )
    rows = [cols]
    for ent in eras:
        wp = ent.get("worst_phase")
        rows.append(
            [str(ent["era"])]
            + [
                f"{ent['wall_s'][n]:.3f}" if n in ent["wall_s"] else "-"
                for n in names
            ]
            + [
                f"{ent['wall_skew_s']:.3f}",
                str(ent["slowest"]),
                str(wp or "-"),
                f"{ent['phase_skew_s'].get(wp, 0.0):.3f}" if wp else "-",
            ]
        )
    widths = [max(len(r[i]) for r in rows) for i in range(len(cols))]
    lines = [
        "  ".join(cell.rjust(widths[i]) for i, cell in enumerate(row))
        for row in rows
    ]
    lines.insert(1, "  ".join("-" * w for w in widths))
    return "\n".join(lines)


def collect(
    urls: Sequence[str],
    names: Optional[Sequence[str]] = None,
    samples: int = 5,
    timeout: float = 10.0,
    api_key: Optional[str] = None,
) -> Tuple[dict, dict]:
    """Scrape + merge in one call: returns (merged_chrome_trace,
    fleet_era_report). Node names default to node0..nodeN-1 in URL
    order — pass explicit names to match deployment labels."""
    if names is None:
        names = [f"node{i}" for i in range(len(urls))]
    nodes = [
        scrape_node(
            url, name, samples=samples, timeout=timeout, api_key=api_key
        )
        for url, name in zip(urls, names)
    ]
    return merge_traces(nodes), fleet_era_report(nodes)
