"""Per-transaction lifecycle stage clock (the fleet-observability tx leg).

Where does a transaction's latency go, from wallet submit to commit?
`utils/metrics.py` answers "how much in aggregate" and `utils/tracing.py`
answers "when, inside which era" — this module pins the six lifecycle
stages of ONE transaction to monotonic stamps so the fleet view can draw
a submit→pool→propose→decide→exec→commit arrow across node lanes:

    submit   RPC/devnet ingress accepted the tx (core/node.Node.submit_tx)
    pool     pool admission succeeded (core/tx_pool.TransactionPool.add)
    propose  the tx rode a local proposal (core/block_producer)
    decide   consensus agreed on a tx set containing it (RootProtocol era
             tail — the union-dedupe loop over the HoneyBadger result)
    exec     block execution reached the tx's block (core/block_manager)
    commit   the block holding the tx persisted (BlockManager._persist)

Design constraints, in order:
  * Deterministic sampling by tx-hash prefix — every node samples the SAME
    transactions, so the fleet merge can line stamps up across processes
    without any coordination. shift=s keeps 1/2^s of txs (0 = all).
  * Bounded memory — stamps live in a locked LRU of TRACE_LRU_CAPACITY
    entries; a flood of sampled txs evicts the oldest timelines, never
    grows.
  * First stamp wins — gossip re-admission, proposal overlap between
    validators, and replayed eras all re-visit stages; the timeline keeps
    the FIRST observation so stage deltas stay causal.
  * Stage sum == e2e by construction — `tx_stage_seconds{stage=S}`
    observes the delta from the PREVIOUS recorded stamp, so the sum of a
    tx's stage observations is exactly its commit-minus-first span and the
    `tx_e2e_seconds` cross-check holds without slack.

Every stamp also emits a `tracing.instant("tx.<stage>", cat="tx",
trace=<8-byte hash prefix hex>)` so the merged fleet Chrome trace carries
per-tx markers whose `trace` arg is IDENTICAL on every node (the tx hash
is global), linking lanes across pids.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from . import metrics, tracing

# lifecycle order; timeline() reports stages in this order and the stage
# histogram's label set is bounded by it
STAGES = ("submit", "pool", "propose", "decide", "exec", "commit")
_STAGE_INDEX = {s: i for i, s in enumerate(STAGES)}

# sampled timelines kept in memory (LRU, oldest evicted)
TRACE_LRU_CAPACITY = 4096

# default: sample 1/16 of txs (observability.txSampleShift overrides)
DEFAULT_SAMPLE_SHIFT = 4

_lock = threading.Lock()
# tx hash -> {"stages": {stage: monotonic_s}, "era": int|None}
_timelines: "OrderedDict[bytes, dict]" = OrderedDict()
_sample_shift = [DEFAULT_SAMPLE_SHIFT]

# sub-ms pool hops up to multi-minute stalls
_STAGE_BUCKETS = (
    0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0,
)
_E2E_BUCKETS = (
    0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


def set_sample_shift(shift: int) -> None:
    """Keep 1/2^shift of txs (0 = every tx). Deterministic across nodes:
    the decision reads the tx hash, so every validator samples the same
    set regardless of local configuration ORDER — but the SHIFT itself
    must match fleet-wide for cross-node timelines to align (DEPLOY.md
    "Fleet observability")."""
    _sample_shift[0] = max(int(shift), 0)


def sample_shift() -> int:
    return _sample_shift[0]


def sampled(tx_hash: bytes) -> bool:
    """Deterministic hash-prefix sampling: same tx → same verdict on every
    node. keccak output is uniform, so the low bits of the first word are
    an unbiased 1/2^shift coin."""
    shift = _sample_shift[0]
    if shift <= 0:
        return True
    mask = (1 << shift) - 1
    return int.from_bytes(tx_hash[:4], "big") & mask == 0


def trace_id(tx_hash: bytes) -> str:
    """The cross-node correlation key for a tx: its hash prefix. Globally
    identical on every node by construction (the hash is the identity)."""
    return tx_hash[:8].hex()


def stamp(tx_hash: bytes, stage: str, era: Optional[int] = None) -> None:
    """Record stage `stage` for `tx_hash` now (first stamp per stage wins).
    No-op for unsampled txs — callers stamp unconditionally and this guard
    keeps the hot path to one int compare for the 15/16 unsampled."""
    if stage not in _STAGE_INDEX or not sampled(tx_hash):
        return
    now = time.monotonic()
    with _lock:
        ent = _timelines.get(tx_hash)
        if ent is None:
            ent = {"stages": {}, "era": None}
            _timelines[tx_hash] = ent
            while len(_timelines) > TRACE_LRU_CAPACITY:
                _timelines.popitem(last=False)
        else:
            _timelines.move_to_end(tx_hash)
        if stage in ent["stages"]:
            return  # first observation wins (re-gossip / era replay)
        ent["stages"][stage] = now
        if era is not None and ent["era"] is None:
            ent["era"] = int(era)
        # delta from the previous recorded stamp: stage observations for
        # one tx sum EXACTLY to its first→commit span (no overlap, no gap)
        prev = max(
            (t for s, t in ent["stages"].items() if s != stage),
            default=None,
        )
        first = min(ent["stages"].values())
    metrics.observe_hist(
        "tx_stage_seconds",
        now - prev if prev is not None else 0.0,
        buckets=_STAGE_BUCKETS,
        labels={"stage": stage},
    )
    if stage == "commit":
        metrics.observe_hist(
            "tx_e2e_seconds", now - first, buckets=_E2E_BUCKETS
        )
    tracing.instant(
        "tx." + stage,
        cat="tx",
        trace=trace_id(tx_hash),
        era=era,
    )


def stamp_many(
    tx_hashes, stage: str, era: Optional[int] = None
) -> None:
    """Batch stamp for block-granularity stages (propose/decide/exec/
    commit visit whole tx sets)."""
    for h in tx_hashes:
        stamp(h, stage, era=era)


def timeline(tx_hash: bytes) -> Optional[dict]:
    """The stamped timeline for a sampled tx, stages in lifecycle order:
    {"hash", "traceId", "era", "stages": [{"stage", "at_s", "dur_s"}...],
    "e2e_s"}. `at_s` is seconds since the FIRST stamp; `dur_s` is the
    delta from the previous stage (sums to e2e_s). None when the tx was
    never stamped (unsampled, or evicted from the LRU)."""
    with _lock:
        ent = _timelines.get(tx_hash)
        if ent is None:
            return None
        stages = dict(ent["stages"])
        era = ent["era"]
    ordered = sorted(stages.items(), key=lambda kv: (kv[1], _STAGE_INDEX[kv[0]]))
    first = ordered[0][1]
    out = []
    prev = first
    for name, at in ordered:
        out.append(
            {
                "stage": name,
                "at_s": round(at - first, 6),
                "dur_s": round(at - prev, 6),
            }
        )
        prev = at
    return {
        "hash": "0x" + tx_hash.hex(),
        "traceId": trace_id(tx_hash),
        "era": era,
        "stages": out,
        "e2e_s": round(ordered[-1][1] - first, 6),
    }


def tracked() -> List[bytes]:
    """Hashes currently held in the LRU, oldest first (tests/CLI)."""
    with _lock:
        return list(_timelines.keys())


def reset_for_tests() -> None:
    with _lock:
        _timelines.clear()
    _sample_shift[0] = DEFAULT_SAMPLE_SHIFT
