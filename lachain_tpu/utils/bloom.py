"""2048-bit log bloom filter (Ethereum-shaped).

Role of the reference's BloomFilter
(/root/reference/src/Lachain.Crypto/Misc/BloomFilter.cs): a fixed 256-byte
filter per block over the addresses that emitted logs, so `eth_getLogs` and
the log-filter machinery skip blocks that cannot match instead of decoding
every transaction's events (the round-2 linear scan).

Bit selection follows the Ethereum yellow-paper M3:2048 scheme: keccak256
of the item, three 11-bit indices from byte pairs (0,1), (2,3), (4,5),
bits set big-endian within the 256-byte array — so the filter is directly
presentable as a Web3 `logsBloom` field.
"""
from __future__ import annotations

from ..crypto.hashes import keccak256

BLOOM_BYTES = 256
_MASK = 2047


def empty() -> bytearray:
    return bytearray(BLOOM_BYTES)


def _bits(item: bytes):
    h = keccak256(item)
    for i in (0, 2, 4):
        yield ((h[i] << 8) | h[i + 1]) & _MASK


def add(bloom: bytearray, item: bytes) -> None:
    for bit in _bits(item):
        bloom[BLOOM_BYTES - 1 - bit // 8] |= 1 << (bit % 8)


def contains(bloom: bytes, item: bytes) -> bool:
    """False means DEFINITELY absent; True means possibly present."""
    for bit in _bits(item):
        if not bloom[BLOOM_BYTES - 1 - bit // 8] & (1 << (bit % 8)):
            return False
    return True
