"""Metrics + per-era crypto operation counters.

Two reference subsystems collapsed into one module:

  * TimeBenchmark — counters wrapped around every crypto hot op, dumped and
    reset at FinishEra (/root/reference/src/Lachain.Crypto/DefaultCrypto.cs:
    47-69, TPKE/PublicKey.cs:13-14, ThresholdSignature/ThresholdSigner.cs:
    13-15; SURVEY.md §7 names this a parity requirement for honest baseline
    comparison).
  * Prometheus-style counters/gauges (AbstractProtocol.cs:15-22,
    BlockManager.cs:62-127, RPC/HTTP/MetricsService.cs:7-26) — rendered in
    text exposition format via `render_text()` and served by the RPC layer.

Thread-safe; everything lives in one process-global registry so the node,
crypto layer and RPC agree on a single view.
"""
from __future__ import annotations

import bisect
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional, Sequence, Tuple

_lock = threading.Lock()
# name -> (count, total_seconds)
_timers: Dict[str, Tuple[int, float]] = {}
# (name, labels) -> value; labels is a sorted tuple of (key, value) pairs,
# () for unlabeled series (the common case; keeps the old flat registry)
_counters: Dict[Tuple[str, tuple], float] = {}
_gauges: Dict[Tuple[str, tuple], float] = {}
# (name, labels) -> Histogram
_histograms: Dict[Tuple[str, tuple], "Histogram"] = {}

# hot-path cell for the per-consensus-message counter: `inc()` takes the
# registry lock per call, which is real overhead at 2M-message eras (N=64
# sim). A bare list-cell `+= 1` is atomic enough under the GIL; render_text
# folds it into the `consensus_messages_processed` counter on exposition.
MESSAGES_PROCESSED = [0]
monotonic = time.monotonic

# Prometheus-ish default latency buckets (seconds): sub-ms crypto ops up to
# multi-second era walls
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


# Cardinality backstop: distinct label sets admitted per metric name (per
# kind). Label values derived from attacker- or workload-controlled input
# (peer ids, method names, stages) must not grow the registry — and the
# scrape payload — without bound. Past the cap, NEW label sets are dropped
# and counted in the unlabeled metrics_labels_dropped_total; existing
# series keep updating.
MAX_LABEL_SETS = 256
_series_counts: Dict[Tuple[str, str], int] = {}  # (kind, name) -> sets
_DROPPED_KEY = ("metrics_labels_dropped_total", ())


def _admit(kind: str, name: str) -> bool:
    """Called under _lock when a labeled series would be CREATED: admit
    while the (kind, name) family is under MAX_LABEL_SETS, else count the
    drop and refuse."""
    k = (kind, name)
    n = _series_counts.get(k, 0)
    if n >= MAX_LABEL_SETS:
        _counters[_DROPPED_KEY] = _counters.get(_DROPPED_KEY, 0.0) + 1.0
        return False
    _series_counts[k] = n + 1
    return True


def _label_key(labels: Optional[dict]) -> tuple:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _fmt_labels(pairs: Sequence[Tuple[str, str]]) -> str:
    if not pairs:
        return ""
    body = ",".join(
        '%s="%s"' % (k, v.replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in pairs
    )
    return "{" + body + "}"


def _fmt_num(v: float) -> str:
    # "1" not "1.0" for bucket bounds; plain repr for everything else
    return "%g" % v


class Histogram:
    """Prometheus histogram with GIL-atomic hot-path cells.

    `observe()` is the MESSAGES_PROCESSED idiom generalized: bucket counts
    and the sum/count live in bare list cells whose `+=` is atomic enough
    under the GIL, so per-frame / per-message call sites never contend on
    the registry lock. A scrape may read sum and count a hair apart —
    the standard trade for lock-free observation."""

    __slots__ = ("name", "labels", "buckets", "_counts", "_sum", "_count")

    def __init__(
        self,
        name: str,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        labels: tuple = (),
    ):
        self.name = name
        self.labels = labels
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # one cell per finite bucket + the +Inf overflow cell
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = [0.0]
        self._count = [0]

    def observe(self, value: float) -> None:
        # le is "less than or equal": first bucket whose bound >= value
        self._counts[bisect.bisect_left(self.buckets, value)] += 1
        self._sum[0] += value
        self._count[0] += 1

    def snapshot(self) -> dict:
        """{count, sum, buckets: [(le, cumulative), ...]} — cumulative as
        the exposition renders them."""
        cum = 0
        out = []
        for bound, c in zip(self.buckets, self._counts):
            cum += c
            out.append((bound, cum))
        return {
            "count": self._count[0],
            "sum": self._sum[0],
            "buckets": out,
        }


def histogram(
    name: str,
    buckets: Sequence[float] = DEFAULT_BUCKETS,
    labels: Optional[dict] = None,
) -> Histogram:
    """Get-or-create the histogram for (name, labels). Hold the returned
    object on hot paths — `observe()` never takes the registry lock."""
    key = (name, _label_key(labels))
    h = _histograms.get(key)
    if h is None:
        with _lock:
            h = _histograms.get(key)
            if h is None:
                if key[1] and not _admit("histogram", name):
                    # over the cardinality cap: hand back a detached
                    # histogram (observations land nowhere, callers keep
                    # working) instead of registering a new series
                    return Histogram(name, buckets, key[1])
                h = Histogram(name, buckets, key[1])
                _histograms[key] = h
    return h


def observe_hist(
    name: str,
    value: float,
    buckets: Sequence[float] = DEFAULT_BUCKETS,
    labels: Optional[dict] = None,
) -> None:
    """Convenience one-shot observation for warm (non-hot) paths."""
    histogram(name, buckets, labels).observe(value)


def histogram_snapshot(name: str, labels: Optional[dict] = None):
    h = _histograms.get((name, _label_key(labels)))
    return h.snapshot() if h is not None else None


@contextmanager
def measure(name: str):
    """Time one operation under `name` (TimeBenchmark.Measure role)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            cnt, total = _timers.get(name, (0, 0.0))
            _timers[name] = (cnt + 1, total + dt)


def timed(name: str):
    """Decorator form of measure() for instrumenting crypto entry points."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with measure(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def inc(
    name: str, amount: float = 1.0, labels: Optional[dict] = None
) -> None:
    key = (name, _label_key(labels))
    with _lock:
        if (
            key[1]
            and key not in _counters
            and not _admit("counter", name)
        ):
            return
        _counters[key] = _counters.get(key, 0.0) + amount


def set_gauge(
    name: str, value: float, labels: Optional[dict] = None
) -> None:
    key = (name, _label_key(labels))
    with _lock:
        if key[1] and key not in _gauges and not _admit("gauge", name):
            return
        _gauges[key] = value


def counter_value(name: str, labels: Optional[dict] = None) -> float:
    with _lock:
        return _counters.get((name, _label_key(labels)), 0.0)


def gauge_value(name: str, labels: Optional[dict] = None) -> Optional[float]:
    with _lock:
        return _gauges.get((name, _label_key(labels)))


def counters_with_prefix(prefix: str) -> Dict[Tuple[str, tuple], float]:
    """All counter series whose name starts with `prefix`, keyed by
    (name, label_items). The scrape-free way to read a labeled family —
    e.g. the fast-sync peer scoreboard (fastsync_peer_*{peer=...}) from
    tests, the console, or a runbook one-liner."""
    with _lock:
        return {
            key: v for key, v in _counters.items()
            if key[0].startswith(prefix)
        }


# Mesh crypto gauges published by parallel/mesh.py (MeshEraPipeline):
#   mesh_devices             devices in the era mesh ('slot' x 'share')
#   mesh_pad_waste_fraction  fraction of the padded (S_pad x K_pad) kernel
#                            grid burnt on filler lanes for the LAST era
#                            call — pad_pow2 can inflate K well past K_live
#                            for non-power-of-two validator counts; tune
#                            with the DEPLOY.md "Multi-device crypto"
#                            runbook (pad-waste tuning)

# LSM read-path gauges published by storage/lsm.py (LsmKV.publish_metrics):
#   lsm_bloom_hits       lookups a table's bloom filter ruled out (the block
#                        fetch the filter saved)
#   lsm_bloom_misses     lookups the filter passed through to a block read
#   lsm_cache_hit_ratio  block-cache hits / (hits + misses), 0.0 when cold
#   lsm_table_count      live SSTables, lsm_compactions_total merges done

# Wait-state surfaces (ISSUE 16 idle anatomy):
#   wait_seconds{resource}          histogram of blocking waits, one series
#                                   per resource bucket (net / crypto_flush /
#                                   device / fsync / sched) — fed by
#                                   tracing.wait() and the native wait
#                                   records; the scrapeable twin of the era
#                                   report's idle decomposition
#   tpke_batcher_queue_depth        submissions queued in the TPKE crypto
#                                   flush batcher (consensus/crypto_batcher)
#   consensus_dispatch_queue_depth  undelivered messages in the dispatch
#                                   queue (native engine or simulator) at
#                                   the last pump iteration; 0 = starved


def observe(name: str, seconds: float) -> None:
    with _lock:
        cnt, total = _timers.get(name, (0, 0.0))
        _timers[name] = (cnt + 1, total + seconds)


def timer_snapshot(
    reset: bool = False, reset_prefix: str = ""
) -> Dict[str, dict]:
    """{name: {count, total_ms, avg_ms}} — the per-era dump
    (DefaultCrypto.ResetBenchmark shape). With `reset_prefix`, only timers
    whose name starts with it are cleared (the reference resets the CRYPTO
    counters per era; block/RPC summaries must survive for scrapes)."""
    with _lock:
        snap = {
            name: {
                "count": cnt,
                "total_ms": round(total * 1e3, 3),
                "avg_ms": round(total * 1e3 / cnt, 4) if cnt else 0.0,
            }
            for name, (cnt, total) in _timers.items()
        }
        if reset:
            if reset_prefix:
                for name in [n for n in _timers if n.startswith(reset_prefix)]:
                    del _timers[name]
            else:
                _timers.clear()
    return snap


def render_text() -> str:
    """Prometheus text exposition of counters, gauges, timers and
    histograms (labeled series grouped under one # TYPE header)."""
    lines = []
    with _lock:
        if MESSAGES_PROCESSED[0]:
            key = ("consensus_messages_processed", ())
            _counters[key] = _counters.get(key, 0.0) + MESSAGES_PROCESSED[0]
            MESSAGES_PROCESSED[0] = 0
        last = None
        for (name, labels), v in sorted(_counters.items()):
            if name != last:
                lines.append(f"# TYPE {name} counter")
                last = name
            lines.append(f"{name}{_fmt_labels(labels)} {v}")
        last = None
        for (name, labels), v in sorted(_gauges.items()):
            if name != last:
                lines.append(f"# TYPE {name} gauge")
                last = name
            lines.append(f"{name}{_fmt_labels(labels)} {v}")
        for name, (cnt, total) in sorted(_timers.items()):
            lines.append(f"# TYPE {name}_seconds summary")
            lines.append(f"{name}_seconds_count {cnt}")
            lines.append(f"{name}_seconds_sum {total}")
        last = None
        for (name, labels), h in sorted(_histograms.items()):
            if name != last:
                lines.append(f"# TYPE {name} histogram")
                last = name
            snap = h.snapshot()
            for bound, cum in snap["buckets"]:
                le = list(labels) + [("le", _fmt_num(bound))]
                lines.append(f"{name}_bucket{_fmt_labels(le)} {cum}")
            inf = list(labels) + [("le", "+Inf")]
            lines.append(f"{name}_bucket{_fmt_labels(inf)} {snap['count']}")
            lines.append(f"{name}_sum{_fmt_labels(labels)} {snap['sum']}")
            lines.append(
                f"{name}_count{_fmt_labels(labels)} {snap['count']}"
            )
    return "\n".join(lines) + "\n"


def reset_all_for_tests() -> None:
    with _lock:
        _timers.clear()
        _counters.clear()
        _gauges.clear()
        _histograms.clear()
        _series_counts.clear()
        MESSAGES_PROCESSED[0] = 0
