"""Metrics + per-era crypto operation counters.

Two reference subsystems collapsed into one module:

  * TimeBenchmark — counters wrapped around every crypto hot op, dumped and
    reset at FinishEra (/root/reference/src/Lachain.Crypto/DefaultCrypto.cs:
    47-69, TPKE/PublicKey.cs:13-14, ThresholdSignature/ThresholdSigner.cs:
    13-15; SURVEY.md §7 names this a parity requirement for honest baseline
    comparison).
  * Prometheus-style counters/gauges (AbstractProtocol.cs:15-22,
    BlockManager.cs:62-127, RPC/HTTP/MetricsService.cs:7-26) — rendered in
    text exposition format via `render_text()` and served by the RPC layer.

Thread-safe; everything lives in one process-global registry so the node,
crypto layer and RPC agree on a single view.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Tuple

_lock = threading.Lock()
# name -> (count, total_seconds)
_timers: Dict[str, Tuple[int, float]] = {}
# name -> value
_counters: Dict[str, float] = {}
_gauges: Dict[str, float] = {}

# hot-path cell for the per-consensus-message counter: `inc()` takes the
# registry lock per call, which is real overhead at 2M-message eras (N=64
# sim). A bare list-cell `+= 1` is atomic enough under the GIL; render_text
# folds it into the `consensus_messages_processed` counter on exposition.
MESSAGES_PROCESSED = [0]
monotonic = time.monotonic


@contextmanager
def measure(name: str):
    """Time one operation under `name` (TimeBenchmark.Measure role)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            cnt, total = _timers.get(name, (0, 0.0))
            _timers[name] = (cnt + 1, total + dt)


def timed(name: str):
    """Decorator form of measure() for instrumenting crypto entry points."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with measure(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def inc(name: str, amount: float = 1.0) -> None:
    with _lock:
        _counters[name] = _counters.get(name, 0.0) + amount


def set_gauge(name: str, value: float) -> None:
    with _lock:
        _gauges[name] = value


def counter_value(name: str) -> float:
    with _lock:
        return _counters.get(name, 0.0)


def observe(name: str, seconds: float) -> None:
    with _lock:
        cnt, total = _timers.get(name, (0, 0.0))
        _timers[name] = (cnt + 1, total + seconds)


def timer_snapshot(
    reset: bool = False, reset_prefix: str = ""
) -> Dict[str, dict]:
    """{name: {count, total_ms, avg_ms}} — the per-era dump
    (DefaultCrypto.ResetBenchmark shape). With `reset_prefix`, only timers
    whose name starts with it are cleared (the reference resets the CRYPTO
    counters per era; block/RPC summaries must survive for scrapes)."""
    with _lock:
        snap = {
            name: {
                "count": cnt,
                "total_ms": round(total * 1e3, 3),
                "avg_ms": round(total * 1e3 / cnt, 4) if cnt else 0.0,
            }
            for name, (cnt, total) in _timers.items()
        }
        if reset:
            if reset_prefix:
                for name in [n for n in _timers if n.startswith(reset_prefix)]:
                    del _timers[name]
            else:
                _timers.clear()
    return snap


def render_text() -> str:
    """Prometheus text exposition of counters, gauges and timers."""
    lines = []
    with _lock:
        if MESSAGES_PROCESSED[0]:
            base = _counters.get("consensus_messages_processed", 0.0)
            _counters["consensus_messages_processed"] = (
                base + MESSAGES_PROCESSED[0]
            )
            MESSAGES_PROCESSED[0] = 0
        for name, v in sorted(_counters.items()):
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {v}")
        for name, v in sorted(_gauges.items()):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {v}")
        for name, (cnt, total) in sorted(_timers.items()):
            lines.append(f"# TYPE {name}_seconds summary")
            lines.append(f"{name}_seconds_count {cnt}")
            lines.append(f"{name}_seconds_sum {total}")
    return "\n".join(lines) + "\n"


def reset_all_for_tests() -> None:
    with _lock:
        _timers.clear()
        _counters.clear()
        _gauges.clear()
        MESSAGES_PROCESSED[0] = 0
