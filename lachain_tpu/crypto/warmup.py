"""Background kernel warmup: precompile the era-kernel shapes a node will hit.

Round-3 finding (ROUND3_NOTES.md #1 / round-3 review weak #3): Mosaic kernels
are not covered by the XLA persistent compilation cache on this platform, and
the first era at a new (S_pad, K_pad) shape stalls 35-110 s while compiling —
a validator joining a running chain burns its first eras compiling.

The reachable shapes are known a priori: the slot axis pads to a power of two
bounded by N, the share axis is fixed at pow2(N) — log2(N)+1 shapes total
(tpu_backend._run_era_batch). This module compiles them on a background
thread at node start, LARGEST FIRST (a healthy chain's first flush carries
close to N slots), so by the time the node's first era tick reaches the
device the hot shape is already compiled. JAX serializes compilations
internally, so a real call racing the warmup simply waits for the same
compile instead of duplicating it.

Reference contrast: the reference has no analogous cost (MCL is AOT-compiled
C++) — this is TPU-specific operational machinery.
"""
from __future__ import annotations

import logging
import threading
from typing import List, Optional, Sequence

logger = logging.getLogger("lachain.warmup")


def _pow2_at_least(n: int) -> int:
    size = 1
    while size < n:
        size *= 2
    return size


def era_warmup_shapes(n_validators: int) -> List[int]:
    """Slot-axis sizes to precompile, largest first."""
    top = _pow2_at_least(max(n_validators, 1))
    shapes = []
    s = top
    while s >= 1:
        shapes.append(s)
        s //= 2
    return shapes


def warmup_era_kernels(
    n_validators: int,
    backend=None,
    shapes: Optional[Sequence[int]] = None,
    include_ts: bool = True,
) -> Optional[threading.Thread]:
    """Start a daemon thread precompiling the TPKE (and optionally the
    G2/coin) era-kernel shapes for an N-validator chain. Returns the thread,
    or None when the backend has no device pipeline to warm."""
    from .provider import get_backend

    backend = backend or get_backend()
    if not hasattr(backend, "tpke_era_verify_combine") or not hasattr(
        backend, "_get_pipeline"
    ):
        return None  # host backends have no compile cost to hide

    def run() -> None:
        from . import bls12381 as bls
        from .tpu_backend import CoinJob, EraSlotJob

        k = n_validators
        todo = list(shapes) if shapes is not None else era_warmup_shapes(k)
        # mesh pipelines pad the (pow2) slot tiers again to a multiple of
        # the 'slot' mesh axis, collapsing the small tiers onto one padded
        # kernel shape — dedupe so warmup compiles each (mesh shape, s_pad,
        # k_pad) entry exactly once (through kernel_cache.call_mesh, which
        # also persists it to disk for the next process)
        try:
            pipe = backend._get_pipeline()
        except Exception:
            pipe = None
        if pipe is not None and hasattr(pipe, "padded_shape"):
            seen: set = set()
            deduped = []
            for s in todo:
                ps = pipe.padded_shape(s, k)
                if ps in seen:
                    continue
                seen.add(ps)
                deduped.append(s)
            todo = deduped
        for s in todo:
            try:
                jobs = [
                    EraSlotJob(
                        u_by_validator=[None] * k,
                        lagrange_row=[0] * k,
                        h=bls.G2_GEN,
                        w=bls.G2_GEN,
                    )
                    for _ in range(s)
                ]
                vks = _dummy_vks(k)
                backend.tpke_era_verify_combine(jobs, vks)
                logger.info("warmed TPKE era shape S=%d K=%d", s, k)
            except Exception:
                logger.exception("era warmup failed at S=%d", s)
                return
        if include_ts and hasattr(backend, "ts_era_verify_combine"):
            try:
                jobs = [
                    CoinJob(
                        sigma_by_signer=[None] * k,
                        lagrange_row=[0] * k,
                        h=bls.G2_GEN,
                    )
                ]
                backend.ts_era_verify_combine(jobs, _dummy_ts_keys(k))
                logger.info("warmed TS coin-era shape K=%d", k)
            except Exception:
                logger.exception("ts era warmup failed")

    t = threading.Thread(target=run, name="ltpu-kernel-warmup", daemon=True)
    t.start()
    return t


_DUMMY_VKS_CACHE: dict = {}
_DUMMY_TS_CACHE: dict = {}


def _dummy_vks(k: int):
    """Stable per-K dummy TPKE verification keys: the pipelines cache
    device marshals by identity, so warmup must reuse ONE list per K (and
    that list must not alias the real validator set's)."""
    from . import bls12381 as bls
    from .tpke import TpkeVerificationKey

    vks = _DUMMY_VKS_CACHE.get(k)
    if vks is None:
        vks = [TpkeVerificationKey(bls.G1_GEN) for _ in range(k)]
        _DUMMY_VKS_CACHE[k] = vks
    return vks


def _dummy_ts_keys(k: int):
    """Stable per-K dummy threshold-signature public keys (attribute .y —
    the coin pipeline reads TsPublicKey, not TpkeVerificationKey)."""
    from . import bls12381 as bls
    from .threshold_sig import TsPublicKey

    keys = _DUMMY_TS_CACHE.get(k)
    if keys is None:
        keys = [TsPublicKey(bls.G1_GEN) for _ in range(k)]
        _DUMMY_TS_CACHE[k] = keys
    return keys
