"""ECVRF over secp256k1 + the stake-weighted lottery.

Parity with the reference's LibVRF.Native binding
(/root/reference/src/Lachain.Crypto references LibVRF 0.0.9; used from
ValidatorStatus/ValidatorStatusManager.cs:437 `Vrf.Evaluate` and
SystemContracts/StakingContract.cs:520,534 `Vrf.IsWinner` / `ProofToHash`).

Construction: ECVRF-SECP256K1-SHA256-TAI shape (RFC 9381 structure, our own
domain separation — wire compat with LibVRF is not a goal):
  prove : H = try-and-increment hash-to-curve(pk, alpha)
          Gamma = H^sk;  k = RFC6979-style nonce
          c = H2(H, Gamma, g^k, H^k);  s = k + c*sk mod n
  verify: U = g^s - pk^c;  V = H^s - Gamma^c;  recompute c
  beta  = sha256(domain || Gamma)  — the lottery roll.

The lottery (`is_winner`) reproduces the stake-weighted Bernoulli rule the
reference uses for validator elections: a staker with `stake` of
`total_stake` rolling for `seats` seats wins iff
  beta/2^256 < 1 - (1 - seats/total)^stake
evaluated in exact integer arithmetic (no floats -> consensus-safe).
"""
from __future__ import annotations

import hashlib
from typing import Tuple

from . import ecdsa as ec
from .hashes import sha256

_PROVE_DOMAIN = b"LTPU-VRF"


def _point_to_bytes(pt: Tuple[int, int]) -> bytes:
    return bytes([0x02 | (pt[1] & 1)]) + pt[0].to_bytes(32, "big")


def _bytes_to_point(b: bytes) -> Tuple[int, int]:
    return ec.decompress_public_key(b)


def _hash_to_curve(pk: bytes, alpha: bytes) -> Tuple[int, int]:
    """Try-and-increment onto secp256k1."""
    ctr = 0
    while True:
        h = sha256(_PROVE_DOMAIN + b"|h2c|" + pk + alpha + ctr.to_bytes(4, "big"))
        x = int.from_bytes(h, "big")
        if x < ec.P:
            y2 = (pow(x, 3, ec.P) + 7) % ec.P
            y = pow(y2, (ec.P + 1) // 4, ec.P)
            if y * y % ec.P == y2:
                return (x, y if y % 2 == 0 else ec.P - y)
        ctr += 1


def _challenge(*points: Tuple[int, int]) -> int:
    h = hashlib.sha256()
    h.update(_PROVE_DOMAIN + b"|c|")
    for pt in points:
        h.update(_point_to_bytes(pt))
    return int.from_bytes(h.digest()[:16], "big")  # 128-bit challenge


def _nonce(sk: bytes, hbytes: bytes) -> int:
    return (
        int.from_bytes(sha256(_PROVE_DOMAIN + b"|k|" + sk + hbytes), "big")
        % ec.N
    ) or 1


def evaluate(sk: bytes, alpha: bytes) -> Tuple[bytes, bytes]:
    """Returns (proof, beta). Proof = Gamma(33) || c(16) || s(32) = 81 bytes.

    Role of Vrf.Evaluate (ValidatorStatusManager.cs:437)."""
    pk = ec.public_key_bytes(sk)
    h_pt = _hash_to_curve(pk, alpha)
    x = int.from_bytes(sk, "big")
    gamma = ec._mul(h_pt, x)
    k = _nonce(sk, _point_to_bytes(h_pt))
    g_k = ec._mul(ec.G, k)
    h_k = ec._mul(h_pt, k)
    c = _challenge(h_pt, gamma, g_k, h_k)
    s = (k + c * x) % ec.N
    proof = _point_to_bytes(gamma) + c.to_bytes(16, "big") + s.to_bytes(32, "big")
    return proof, proof_to_hash(proof)


def verify(pk: bytes, alpha: bytes, proof: bytes) -> bool:
    """Role of Vrf.Verify."""
    if len(proof) != 81:
        return False
    try:
        gamma = _bytes_to_point(proof[:33])
        q = ec.decompress_public_key(pk)
    except (ValueError, AssertionError):
        return False
    c = int.from_bytes(proof[33:49], "big")
    s = int.from_bytes(proof[49:81], "big")
    if not (0 < s < ec.N):
        return False
    h_pt = _hash_to_curve(pk, alpha)
    # U = g^s - pk^c ; V = H^s - Gamma^c
    neg = lambda pt: (pt[0], ec.P - pt[1])
    u = ec._add(ec._mul(ec.G, s), neg(ec._mul(q, c)))
    v = ec._add(ec._mul(h_pt, s), neg(ec._mul(gamma, c)))
    if u is None or v is None:
        return False
    return _challenge(h_pt, gamma, u, v) == c


def proof_to_hash(proof: bytes) -> bytes:
    """beta — the uniform lottery roll (role of Vrf.ProofToHash,
    StakingContract.cs:534)."""
    return sha256(_PROVE_DOMAIN + b"|beta|" + proof[:33])


def is_winner(
    beta: bytes, stake: int, total_stake: int, seats: int
) -> bool:
    """Stake-weighted election: P(win) = 1 - (1 - seats/total)^stake.

    Exact integer evaluation: beta/2^256 < 1 - ((total-seats)/total)^stake
      <=>  (beta_int) * total^stake < (2^256) * (total^stake - (total-seats)^stake)
    (role of Vrf.IsWinner, StakingContract.cs:520).
    """
    if stake <= 0 or total_stake <= 0:
        return False
    if seats >= total_stake:
        return True
    beta_int = int.from_bytes(beta, "big")
    # (1 - seats/total)^stake in Q.256 fixed point via square-and-multiply
    # with floor rounding — exact integer ops, so every node computes the
    # identical bit pattern (consensus-safe), cost O(256 * log2(stake)).
    SHIFT = 256
    q = ((total_stake - seats) << SHIFT) // total_stake
    result = 1 << SHIFT
    base = q
    e = stake
    while e:
        if e & 1:
            result = (result * base) >> SHIFT
        base = (base * base) >> SHIFT
        e >>= 1
    lose_fp = result  # floor of (1 - seats/total)^stake * 2^256
    return beta_int < (1 << SHIFT) - lose_fp
