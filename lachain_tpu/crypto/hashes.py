"""Hash primitives: Keccak-256, SHA-256, Ripemd160, Merkle tree, XOF.

Parity with the reference's hashing layer
(/root/reference/src/Lachain.Crypto/HashUtils.cs:1-86 and
Misc/MerkleTree.cs:183-198). Keccak-256 (the legacy pre-NIST padding used by
Ethereum and the reference's `KeccakDigest(256)`) is implemented natively here
since hashlib only ships NIST SHA-3.
"""
from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence

_KECCAK_ROUNDS = 24
_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]
_MASK = (1 << 64) - 1


def _rol(v: int, s: int) -> int:
    return ((v << s) | (v >> (64 - s))) & _MASK


def _keccak_f(a: List[List[int]]) -> None:
    for rnd in range(_KECCAK_ROUNDS):
        # theta
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rol(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x][y] ^= d[x]
        # rho + pi
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rol(a[x][y], _ROT[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y])
        # iota
        a[0][0] ^= _RC[rnd]


_native_cache = [False, None]


def _native_lib():
    """The C++ backend's lt_keccak256 (cross-checked against the pure-Python
    implementation below in tests/test_hashes.py). Keccak dominates tx/block
    hashing, so the dispatch matters for pool ingest and block execution."""
    if not _native_cache[0]:
        _native_cache[0] = True
        import os as _os

        if _os.environ.get("LACHAIN_TPU_HASHES") != "python":
            try:
                from .native_backend import load_lib

                _native_cache[1] = load_lib()
            except Exception:
                _native_cache[1] = None
    return _native_cache[1]


def keccak256(data: bytes) -> bytes:
    """Keccak-256 with legacy 0x01 padding (Ethereum-style), not SHA3-256."""
    lib = _native_lib()
    if lib is not None:
        import ctypes as _ct

        out = (_ct.c_ubyte * 32)()
        lib.lt_keccak256(data, len(data), out)
        return bytes(out)
    return _keccak256_py(data)


_batch_cache = [False, None]


def _batch_fn():
    """lt_keccak256_batch from the native backend, or None. Separate probe
    from _native_lib so a stale libbls381.so (built before the batch entry
    point existed) degrades to per-item dispatch instead of failing."""
    if not _batch_cache[0]:
        _batch_cache[0] = True
        lib = _native_lib()
        if lib is not None:
            import ctypes as _ct

            try:
                fn = lib.lt_keccak256_batch
            except AttributeError:
                fn = None
            else:
                fn.argtypes = [
                    _ct.c_char_p,
                    _ct.POINTER(_ct.c_uint64),
                    _ct.c_size_t,
                    _ct.c_int,
                    _ct.POINTER(_ct.c_ubyte),
                ]
                fn.restype = _ct.c_int
            _batch_cache[1] = fn
    return _batch_cache[1]


def keccak256_batch(items: Sequence[bytes], nthreads: int = 0) -> List[bytes]:
    """Keccak-256 over a whole batch in ONE native call (threaded in C++,
    GIL released) — the trie commit path hashes ~100k node encodings per
    10k-tx block, and per-item ctypes dispatch is most of that wall.
    Falls back to per-item keccak256 when the native entry is unavailable."""
    n = len(items)
    if n == 0:
        return []
    fn = _batch_fn()
    if fn is None:
        return [keccak256(d) for d in items]
    import ctypes as _ct
    import os as _os

    if nthreads <= 0:
        nthreads = min(_os.cpu_count() or 1, 16)
    offsets = (_ct.c_uint64 * (n + 1))()
    total = 0
    for i, d in enumerate(items):
        offsets[i] = total
        total += len(d)
    offsets[n] = total
    data = b"".join(items)
    out = (_ct.c_ubyte * (n * 32))()
    rc = fn(data, offsets, n, nthreads, out)
    if rc != 0:
        return [keccak256(d) for d in items]
    raw = bytes(out)
    return [raw[i * 32 : (i + 1) * 32] for i in range(n)]


def _keccak256_py(data: bytes) -> bytes:
    rate = 136
    state = [[0] * 5 for _ in range(5)]
    padded = bytearray(data)
    padded.append(0x01)
    while len(padded) % rate:
        padded.append(0x00)
    padded[-1] |= 0x80
    for off in range(0, len(padded), rate):
        block = padded[off : off + rate]
        for i in range(rate // 8):
            lane = int.from_bytes(block[i * 8 : i * 8 + 8], "little")
            state[i % 5][i // 5] ^= lane
        _keccak_f(state)
    out = bytearray()
    for i in range(4):  # 32 bytes = 4 lanes
        out += state[i % 5][i // 5].to_bytes(8, "little")
    return bytes(out)


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def ripemd160(data: bytes) -> bytes:
    h = hashlib.new("ripemd160")
    h.update(data)
    return h.digest()


def xof(domain: bytes, data: bytes, nbytes: int) -> bytes:
    """SHAKE-256 XOF with domain separation — keystream generator for the TPKE
    XOR pad (role of the reference's SHA3-seeded DigestRandomGenerator,
    /root/reference/src/Lachain.Crypto/TPKE/Utils.cs:13-19; our chain defines
    a cleaner XOF rather than reproducing BouncyCastle bit-exactly)."""
    h = hashlib.shake_256()
    h.update(len(domain).to_bytes(1, "big") + domain + data)
    return h.digest(nbytes)


def merkle_root(leaves: Sequence[bytes]) -> Optional[bytes]:
    """Binary Merkle root over 32-byte leaf hashes.

    Shape parity with MerkleTree.ComputeRoot
    (/root/reference/src/Lachain.Crypto/Misc/MerkleTree.cs:183-198): pairwise
    keccak256(left || right), odd node promoted unchanged.
    """
    if not leaves:
        return None
    level = list(leaves)
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level) - 1, 2):
            nxt.append(keccak256(level[i] + level[i + 1]))
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    return level[0]


def merkle_proof(leaves: Sequence[bytes], index: int) -> List[bytes]:
    """Sibling path for leaves[index]; verify with merkle_verify."""
    proof: List[bytes] = []
    level = list(leaves)
    idx = index
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level) - 1, 2):
            nxt.append(keccak256(level[i] + level[i + 1]))
        if len(level) % 2:
            nxt.append(level[-1])
        sib = idx ^ 1
        if sib < len(level):
            proof.append(level[sib])
        else:
            proof.append(b"")  # odd promotion: no sibling at this level
        idx //= 2
        level = nxt
    return proof


def merkle_verify(
    leaf: bytes, index: int, proof: Sequence[bytes], root: bytes
) -> bool:
    node = leaf
    idx = index
    for sib in proof:
        if sib == b"":
            pass  # promoted unchanged
        elif idx % 2 == 0:
            node = keccak256(node + sib)
        else:
            node = keccak256(sib + node)
        idx //= 2
    return node == root
