"""Persistent compiled-kernel cache (VERDICT r4 #4: "kill the compile tax").

Round-3/4 finding: Mosaic (Pallas) kernels are NOT covered by the XLA
persistent compilation cache on this platform, so every node restart pays
35-110 s of compile per era-kernel shape — hidden by the warmup thread, but
on a one-core box that thread competes with consensus for minutes.

Round-5 probe result (benchmarks/results_r05.json kernel_cache probe):
`jax.experimental.serialize_executable` round-trips compiled Mosaic
executables on this platform — a 43 s compile of the fused era kernel
deserializes in ~0.4 s in a fresh process and runs without recompiling.
This module builds the disk cache on that primitive:

  call(jit_fn, name, *args, **static) -> output
    1. in-process memo by (name, arg shapes/dtypes, statics)
    2. disk hit: deserialize_and_load from the cache dir
    3. miss: lower+compile, serialize, atomic-write, then run

Cache keys include the jax version, the device kind and a content hash of
the ops/ kernel sources, so kernel edits and toolchain upgrades invalidate
stale entries instead of silently running old code.

Layout: $LACHAIN_TPU_KERNEL_CACHE (default ~/.cache/lachain_tpu/kernels)/
<key>.exec + <key>.trees (pickled in/out trees).
"""
from __future__ import annotations

import hashlib
import logging
import os
import pickle
import threading
from typing import Any, Dict

from ..utils import metrics

logger = logging.getLogger("lachain.kernel_cache")

_memo: Dict[str, Any] = {}
_lock = threading.Lock()  # guards the lock registry + memo inserts only
_key_locks: Dict[str, threading.Lock] = {}
_src_hash_cache: list = []


def _lock_for(key: str) -> threading.Lock:
    # per-key locks: a multi-minute Mosaic compile of one kernel must not
    # block another thread's ~0.4 s disk load of a DIFFERENT kernel (the
    # warmup thread vs consensus thread case on the one-core box)
    with _lock:
        lk = _key_locks.get(key)
        if lk is None:
            lk = threading.Lock()
            _key_locks[key] = lk
        return lk


def cache_dir() -> str:
    d = os.environ.get("LACHAIN_TPU_KERNEL_CACHE")
    if not d:
        d = os.path.join(
            os.path.expanduser("~"), ".cache", "lachain_tpu", "kernels"
        )
    os.makedirs(d, exist_ok=True)
    return d


def _sources_hash() -> str:
    """Content hash over the kernel source modules — an edited kernel must
    never serve a stale executable."""
    if _src_hash_cache:
        return _src_hash_cache[0]
    import lachain_tpu.ops as ops_pkg

    h = hashlib.sha256()
    root = os.path.dirname(ops_pkg.__file__)
    for fn in sorted(os.listdir(root)):
        if fn.endswith(".py"):
            with open(os.path.join(root, fn), "rb") as fh:
                h.update(fh.read())
    _src_hash_cache.append(h.hexdigest()[:16])
    return _src_hash_cache[0]


def _key(name: str, args, statics: dict) -> str:
    import jax

    dev = jax.devices()[0]
    sig = [
        name,
        jax.__version__,
        getattr(dev, "device_kind", str(dev)),
        _sources_hash(),
        tuple(sorted(statics.items())),
        tuple((tuple(a.shape), str(a.dtype)) for a in args),
    ]
    return hashlib.sha256(repr(sig).encode()).hexdigest()[:32]


def _disk_load(key: str):
    from jax.experimental import serialize_executable as se

    base = os.path.join(cache_dir(), key)
    try:
        with open(base + ".exec", "rb") as fh:
            blob = fh.read()
        with open(base + ".trees", "rb") as fh:
            in_tree, out_tree = pickle.load(fh)
        return se.deserialize_and_load(blob, in_tree, out_tree)
    except FileNotFoundError:
        return None
    except Exception:
        logger.exception("kernel cache entry %s unreadable; recompiling", key)
        return None


def _disk_store(key: str, compiled) -> None:
    from jax.experimental import serialize_executable as se

    try:
        blob, in_tree, out_tree = se.serialize(compiled)
        base = os.path.join(cache_dir(), key)
        tmp = base + f".tmp{os.getpid()}"
        with open(tmp, "wb") as fh:
            fh.write(blob)
        os.replace(tmp, base + ".exec")
        with open(tmp, "wb") as fh:
            pickle.dump((in_tree, out_tree), fh)
        os.replace(tmp, base + ".trees")
        logger.info(
            "kernel cache store %s (%.1f MB)", key, len(blob) / 1e6
        )
    except Exception:
        # serialization unsupported for this executable/platform: the
        # in-process memo still works, only restarts pay the compile
        logger.exception("kernel cache store failed for %s", key)


def _single_device() -> bool:
    # the disk layer is built for the production shape: ONE real chip.
    # Deserialized executables pin their device assignment; on the virtual
    # multi-device CPU test platform (8 devices) they demand per-device
    # shards and fail, so those platforms bypass straight to the jit.
    import jax

    return len(jax.devices()) == 1


def call(jit_fn, name: str, *args, **statics):
    """Run `jit_fn(*args, **statics)` through the persistent cache.
    `args` must all be arrays (shapes form the cache key); `statics` are
    the jit's static kwargs."""
    if not _single_device():
        metrics.inc("kernel_cache_requests_total", labels={"tier": "bypass"})
        return jit_fn(*args, **statics)
    key = _key(name, args, statics)
    compiled = _memo.get(key)
    if compiled is None:
        with _lock_for(key):
            compiled = _memo.get(key)
            if compiled is None:
                compiled = _disk_load(key)
                if compiled is None:
                    metrics.inc(
                        "kernel_cache_requests_total", labels={"tier": "compile"}
                    )
                    t0 = metrics.monotonic()
                    lowered = jit_fn.lower(*args, **statics)
                    compiled = lowered.compile()
                    metrics.observe_hist(
                        "kernel_cache_compile_seconds",
                        metrics.monotonic() - t0,
                        buckets=(0.1, 1.0, 5.0, 15.0, 30.0, 60.0, 120.0),
                    )
                    _disk_store(key, compiled)
                else:
                    metrics.inc(
                        "kernel_cache_requests_total", labels={"tier": "disk"}
                    )
                with _lock:
                    _memo[key] = compiled
            else:
                metrics.inc(
                    "kernel_cache_requests_total", labels={"tier": "memo"}
                )
    else:
        metrics.inc("kernel_cache_requests_total", labels={"tier": "memo"})
    return compiled(*args)


def _mesh_tag(mesh) -> str:
    shape = "x".join(f"{k}{v}" for k, v in dict(mesh.shape).items())
    return f"@mesh[{shape}]dev{mesh.devices.size}"


def call_mesh(jit_fn, name: str, mesh, *args):
    """Run a shard_mapped `jit_fn(*args)` through the persistent cache.

    The mesh variant of call(): unlike the single-chip path it does NOT
    bypass on multi-device platforms — sharded executables serialize and
    deserialize fine when their args carry NamedShardings (round-6 probe:
    a shard_mapped era kernel round-trips on the 8-virtual-device CPU
    platform). The mesh shape joins the cache key, and a deserialized
    executable that rejects this process's device assignment falls back to
    a recompile instead of failing the era."""
    key = _key(name + _mesh_tag(mesh), args, {})
    compiled = _memo.get(key)
    if compiled is None:
        with _lock_for(key):
            compiled = _memo.get(key)
            if compiled is None:
                compiled = _disk_load(key)
                if compiled is not None:
                    try:
                        out = compiled(*args)
                    except Exception:
                        logger.exception(
                            "mesh cache entry %s incompatible with this "
                            "device assignment; recompiling", key
                        )
                        compiled = None
                    else:
                        metrics.inc(
                            "kernel_cache_requests_total", labels={"tier": "disk"}
                        )
                        with _lock:
                            _memo[key] = compiled
                        return out
                metrics.inc(
                    "kernel_cache_requests_total", labels={"tier": "compile"}
                )
                t0 = metrics.monotonic()
                compiled = jit_fn.lower(*args).compile()
                metrics.observe_hist(
                    "kernel_cache_compile_seconds",
                    metrics.monotonic() - t0,
                    buckets=(0.1, 1.0, 5.0, 15.0, 30.0, 60.0, 120.0),
                )
                _disk_store(key, compiled)
                with _lock:
                    _memo[key] = compiled
                return compiled(*args)
    metrics.inc("kernel_cache_requests_total", labels={"tier": "memo"})
    try:
        return compiled(*args)
    except Exception:
        # a memoized executable can go stale if the device set changed
        # under us (tests resetting platforms); drop it and run the jit
        logger.exception("memoized mesh kernel %s failed; re-jitting", key)
        with _lock:
            _memo.pop(key, None)
        return jit_fn(*args)


def warm(jit_fn, name: str, *args, **statics) -> bool:
    """Ensure the executable for this shape is memoized (disk or compile)
    WITHOUT running it. Returns True if it came from disk."""
    if not _single_device():
        jit_fn.lower(*args, **statics).compile()  # jax's in-process cache
        metrics.inc("kernel_cache_warm_total", labels={"tier": "bypass"})
        return False
    key = _key(name, args, statics)
    if key in _memo:
        metrics.inc("kernel_cache_warm_total", labels={"tier": "memo"})
        return True
    with _lock_for(key):
        if key in _memo:
            metrics.inc("kernel_cache_warm_total", labels={"tier": "memo"})
            return True
        compiled = _disk_load(key)
        from_disk = compiled is not None
        if compiled is None:
            metrics.inc("kernel_cache_warm_total", labels={"tier": "compile"})
            compiled = jit_fn.lower(*args, **statics).compile()
            _disk_store(key, compiled)
        else:
            metrics.inc("kernel_cache_warm_total", labels={"tier": "disk"})
        with _lock:
            _memo[key] = compiled
    return from_disk
