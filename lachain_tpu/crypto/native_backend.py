"""ctypes binding for libbls381 (the native C++ BLS12-381 backend).

Builds on demand (make in lachain_tpu/crypto/native) and exposes the same
backend interface as PythonBackend (lachain_tpu.crypto.provider). Points cross
the boundary in the shared wire format (BE uncompressed; see bls12381.py),
internally converting to/from the oracle's tuple representation so the rest of
the Python stack is backend-agnostic.

Role parity: the MCL native binding in the reference
(/root/reference/src/Lachain.Crypto/MclBls12381.cs).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Sequence, Tuple

from . import bls12381 as bls

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libbls381.so")


def _build_if_needed() -> None:
    # rebuild when ANY source is newer than the .so — a stale library built
    # before a source file was added would load fine (lt_version exists)
    # but lack newer symbols, crashing callers with AttributeError
    import glob

    sources = glob.glob(os.path.join(_NATIVE_DIR, "*.cpp")) + [
        os.path.join(_NATIVE_DIR, "Makefile")
    ]
    if os.path.exists(_LIB_PATH) and all(
        os.path.getmtime(_LIB_PATH) >= os.path.getmtime(s) for s in sources
    ):
        return
    subprocess.run(
        ["make", "-s", "-C", _NATIVE_DIR], check=True, capture_output=True
    )


def load_lib():
    # LACHAIN_BLS_LIB loads an alternate backend build verbatim (the
    # ASan/TSan gates in tests/native/ point it at instrumented builds) —
    # no mtime-rebuild, same contract as LACHAIN_LSM_LIB in storage/lsm.py
    override = os.environ.get("LACHAIN_BLS_LIB")
    if override:
        lib_path = override
    else:
        _build_if_needed()
        lib_path = _LIB_PATH
    lib = ctypes.CDLL(lib_path)
    lib.lt_version.restype = ctypes.c_int
    assert lib.lt_version() == 1
    return lib


def _scalar32(s: int) -> bytes:
    return (s % bls.R).to_bytes(32, "big")


class NativeBackend:
    """Backend implementation delegating hot ops to libbls381."""

    name = "native"

    def __init__(self):
        self._lib = load_lib()

    def tpke_era_verify_combine(self, jobs, verification_keys, rng=None):
        """Whole-tick TPKE verify+combine over the C++ group ops (one grand
        multi-pairing); same contract as the TPU backend's kernel version."""
        import secrets as _secrets

        from . import tpke

        return tpke.era_verify_combine_host(
            jobs, verification_keys, backend=self, rng=rng or _secrets
        )

    # -- group ops -----------------------------------------------------------
    def g1_mul(self, point: tuple, scalar: int) -> tuple:
        out = ctypes.create_string_buffer(96)
        rc = self._lib.lt_g1_mul(
            bls.g1_to_bytes(point), _scalar32(scalar), out
        )
        if rc != 0:
            raise ValueError("native g1_mul failed")
        return bls.g1_from_bytes(out.raw, check_subgroup=False)

    def g1_mul_batch(
        self, points: Sequence[tuple], scalars: Sequence[int]
    ) -> List[tuple]:
        """n independent muls in one threaded native call (NOT an MSM — no
        accumulation). The TPKE decrypt-share shape: 64 slots x one
        U^{x_i} each per era tick."""
        if len(points) != len(scalars):
            raise ValueError("g1_mul_batch: length mismatch")
        if not points:
            return []
        pts = b"".join(bls.g1_to_bytes(p) for p in points)
        ss = b"".join(_scalar32(s) for s in scalars)
        out = ctypes.create_string_buffer(96 * len(points))
        nt = min(os.cpu_count() or 1, 16)
        rc = self._lib.lt_g1_mul_batch(pts, ss, len(points), nt, out)
        if rc != 0:
            raise ValueError("native g1_mul_batch failed")
        return [
            bls.g1_from_bytes(
                out.raw[i * 96 : (i + 1) * 96], check_subgroup=False
            )
            for i in range(len(points))
        ]

    def g2_mul(self, point: tuple, scalar: int) -> tuple:
        out = ctypes.create_string_buffer(192)
        rc = self._lib.lt_g2_mul(
            bls.g2_to_bytes(point), _scalar32(scalar), out
        )
        if rc != 0:
            raise ValueError("native g2_mul failed")
        return bls.g2_from_bytes(out.raw, check_subgroup=False)

    def g1_msm(self, points: Sequence[tuple], scalars: Sequence[int]) -> tuple:
        if len(points) != len(scalars):
            raise ValueError("g1_msm: points/scalars length mismatch")
        if not points:
            return bls.G1_INF
        pts = b"".join(bls.g1_to_bytes(p) for p in points)
        ss = b"".join(_scalar32(s) for s in scalars)
        out = ctypes.create_string_buffer(96)
        rc = self._lib.lt_g1_msm(pts, ss, len(points), out)
        if rc != 0:
            raise ValueError("native g1_msm failed")
        return bls.g1_from_bytes(out.raw, check_subgroup=False)

    def g2_msm(self, points: Sequence[tuple], scalars: Sequence[int]) -> tuple:
        if len(points) != len(scalars):
            raise ValueError("g2_msm: points/scalars length mismatch")
        if not points:
            return bls.G2_INF
        pts = b"".join(bls.g2_to_bytes(p) for p in points)
        ss = b"".join(_scalar32(s) for s in scalars)
        out = ctypes.create_string_buffer(192)
        rc = self._lib.lt_g2_msm(pts, ss, len(points), out)
        if rc != 0:
            raise ValueError("native g2_msm failed")
        return bls.g2_from_bytes(out.raw, check_subgroup=False)

    # -- pairings ------------------------------------------------------------
    def pairing_check(self, pairs: Sequence[Tuple[tuple, tuple]]) -> bool:
        """Prod e(P_i, Q_i) == 1. Large products (the era-sized grand check,
        2S pairs) spread their independent Miller loops across threads with
        one shared final exponentiation; small ones stay serial (thread
        spawn would dominate)."""
        if not pairs:
            return True
        g1s = b"".join(bls.g1_to_bytes(p) for p, _ in pairs)
        g2s = b"".join(bls.g2_to_bytes(q) for _, q in pairs)
        if len(pairs) >= 8:
            nt = min(os.cpu_count() or 1, 16)
            rc = self._lib.lt_pairing_check_mt(g1s, g2s, len(pairs), nt)
        else:
            rc = self._lib.lt_pairing_check(g1s, g2s, len(pairs))
        if rc < 0:
            raise ValueError("native pairing_check: bad encoding")
        return rc == 1

    def pairings_equal(self, p_a, q_a, p_b, q_b) -> bool:
        return self.pairing_check([(p_a, q_a), (bls.g1_neg(p_b), q_b)])

    def multi_pairing_bytes(
        self, pairs: Sequence[Tuple[tuple, tuple]]
    ) -> bytes:
        """GT output serialized — for conformance tests vs the oracle."""
        g1s = b"".join(bls.g1_to_bytes(p) for p, _ in pairs)
        g2s = b"".join(bls.g2_to_bytes(q) for _, q in pairs)
        out = ctypes.create_string_buffer(576)
        rc = self._lib.lt_multi_pairing(g1s, g2s, len(pairs), out)
        if rc != 0:
            raise ValueError("native multi_pairing failed")
        return out.raw

    # -- hashing -------------------------------------------------------------
    def hash_to_g1(self, msg: bytes, domain: bytes = b"LTPU-G1") -> tuple:
        out = ctypes.create_string_buffer(96)
        self._lib.lt_hash_to_g1(msg, len(msg), domain, len(domain), out)
        return bls.g1_from_bytes(out.raw, check_subgroup=False)

    def hash_to_g2(self, msg: bytes, domain: bytes = b"LTPU-G2") -> tuple:
        out = ctypes.create_string_buffer(192)
        self._lib.lt_hash_to_g2(msg, len(msg), domain, len(domain), out)
        return bls.g2_from_bytes(out.raw, check_subgroup=False)

    # -- wire deserialization (native on-curve + subgroup check) -------------
    def g1_deserialize(self, data: bytes) -> tuple:
        if len(data) != bls.G1_BYTES:
            raise ValueError("bad G1 encoding length")
        if self._lib.lt_g1_check(data) != 2:
            raise ValueError("G1 point invalid or not in subgroup")
        return bls.g1_from_bytes(data, check_subgroup=False)

    def g2_deserialize(self, data: bytes) -> tuple:
        if len(data) != bls.G2_BYTES:
            raise ValueError("bad G2 encoding length")
        if self._lib.lt_g2_check(data) != 2:
            raise ValueError("G2 point invalid or not in subgroup")
        return bls.g2_from_bytes(data, check_subgroup=False)

    def keccak256(self, data: bytes) -> bytes:
        out = ctypes.create_string_buffer(32)
        self._lib.lt_keccak256(data, len(data), out)
        return out.raw

    # -- baseline proxy ------------------------------------------------------
    def tpke_verify_shares_serial(
        self,
        uis: Sequence[tuple],
        yis: Sequence[tuple],
        h: tuple,
        w: tuple,
    ) -> List[bool]:
        """Reference-style serial loop: 2 pairings per share (the baseline
        the batched TPU path is measured against — BASELINE.md)."""
        n = len(uis)
        ub = b"".join(bls.g1_to_bytes(u) for u in uis)
        yb = b"".join(bls.g1_to_bytes(y) for y in yis)
        res = ctypes.create_string_buffer(n)
        rc = self._lib.lt_tpke_verify_shares_serial(
            ub, yb, n, bls.g2_to_bytes(h), bls.g2_to_bytes(w), res
        )
        if rc != 0:
            raise ValueError("native serial verify failed")
        return [b == 1 for b in res.raw]
