"""secp256k1 ECDSA: sign / verify / recover, RFC 6979 deterministic nonces.

Parity with the reference's ECDSA surface
(/root/reference/src/Lachain.Crypto/DefaultCrypto.cs:17-337 over
Secp256k1.Net): transaction + consensus-header signatures with public-key
recovery, 65-byte (r || s || v) signatures, Ethereum-style addresses.

Pure Python (curve ops on ints). SIGNING IS NOT CONSTANT-TIME on either
backend: both this oracle and the C++ port use branchy double-and-add over
the secret nonce, so timing/cache side channels can leak nonce bits of a
frequently-signing key (lattice attacks). Both are therefore DEVNET-GRADE
for signing; verification/recovery take only public inputs and are
unaffected. A production deployment must swap sign_hash for a
constant-time implementation (complete formulas + branchless window
selection) before exposing validator keys to co-located adversaries.
"""
from __future__ import annotations

import hashlib
import hmac
from typing import List, Optional, Sequence, Tuple

from .hashes import keccak256

# secp256k1 domain parameters
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
G = (GX, GY)


def _inv(a: int, m: int) -> int:
    return pow(a, -1, m)


def _add(p: Optional[Tuple[int, int]], q: Optional[Tuple[int, int]]):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1) * _inv(2 * y1, P) % P
    else:
        lam = (y2 - y1) * _inv(x2 - x1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def _mul(p: Optional[Tuple[int, int]], k: int):
    k %= N
    result = None
    addend = p
    while k:
        if k & 1:
            result = _add(result, addend)
        addend = _add(addend, addend)
        k >>= 1
    return result


from ..utils import metrics

def generate_private_key(rng=None) -> bytes:
    import secrets as _secrets

    rng = rng or _secrets
    while True:
        k = rng.randbelow(N)
        if 1 <= k < N:
            return k.to_bytes(32, "big")


def public_key_point(priv: bytes) -> Tuple[int, int]:
    return _mul(G, int.from_bytes(priv, "big"))


# keccak(priv) -> compressed pubkey; nodes sign with a handful of
# long-lived keys and the pure-Python ladder costs ~10 ms per derivation.
# Keyed by a HASH of the private key so the cache never pins secret bytes
# in process memory beyond the caller's own copy.
_pub_cache: dict = {}


def public_key_bytes(priv: bytes) -> bytes:
    """Compressed SEC1 encoding (33 bytes)."""
    from .hashes import keccak256

    ck = keccak256(priv)
    cached = _pub_cache.get(ck)
    if cached is not None:
        return cached
    pub = None
    lib = _native_lib()
    if lib is not None:
        import ctypes as _ct

        out = (_ct.c_ubyte * 33)()
        if lib.lt_ec_pubkey(priv, out) == 0:
            pub = bytes(out)
    if pub is None:
        x, y = public_key_point(priv)
        pub = bytes([0x02 | (y & 1)]) + x.to_bytes(32, "big")
    if len(_pub_cache) > 4096:
        _pub_cache.clear()
    _pub_cache[ck] = pub
    return pub


def decompress_public_key(pub: bytes) -> Tuple[int, int]:
    # ValueError (not assert) so malformed keys from untrusted input —
    # contract crypto_verify calls, wire MessageBatch senders — are a
    # clean "invalid" on every backend: the native lt_ec_verify returns
    # false for a non-02/03 prefix, and _verify_hash_py catches ValueError.
    # An AssertionError here would trap python-backend nodes while native
    # nodes return 0, forking state across a mixed deployment.
    if len(pub) != 33 or pub[0] not in (2, 3):
        raise ValueError("pubkey must be 33 bytes with 02/03 prefix")
    x = int.from_bytes(pub[1:], "big")
    if x >= P:
        raise ValueError("pubkey x out of range")
    y2 = (pow(x, 3, P) + 7) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise ValueError("pubkey not on curve")
    if (y & 1) != (pub[0] & 1):
        y = P - y
    return (x, y)


def address_from_public_key(pub: bytes) -> bytes:
    """20-byte Ethereum-style address: keccak256(uncompressed_xy)[12:]."""
    x, y = decompress_public_key(pub) if len(pub) == 33 else (
        int.from_bytes(pub[1:33], "big"),
        int.from_bytes(pub[33:], "big"),
    )
    raw = x.to_bytes(32, "big") + y.to_bytes(32, "big")
    return keccak256(raw)[12:]


def _rfc6979_k(priv: bytes, msg_hash: bytes) -> int:
    """Deterministic nonce per RFC 6979 (HMAC-SHA256)."""
    holder = b"\x01" * 32
    key = b"\x00" * 32
    key = hmac.new(key, holder + b"\x00" + priv + msg_hash, hashlib.sha256).digest()
    holder = hmac.new(key, holder, hashlib.sha256).digest()
    key = hmac.new(key, holder + b"\x01" + priv + msg_hash, hashlib.sha256).digest()
    holder = hmac.new(key, holder, hashlib.sha256).digest()
    while True:
        holder = hmac.new(key, holder, hashlib.sha256).digest()
        k = int.from_bytes(holder, "big")
        if 1 <= k < N:
            return k
        key = hmac.new(key, holder + b"\x00", hashlib.sha256).digest()
        holder = hmac.new(key, holder, hashlib.sha256).digest()


_native_lib_cache = [False, None]  # [attempted, lib]


def _native_lib():
    """The C++ secp256k1 backend (lachain_tpu/crypto/native/secp256k1.cpp,
    cross-checked against this module's pure-Python oracle in
    tests/test_ecdsa.py). LACHAIN_TPU_ECDSA=python forces the oracle."""
    if not _native_lib_cache[0]:
        _native_lib_cache[0] = True
        import os as _os

        if _os.environ.get("LACHAIN_TPU_ECDSA") != "python":
            try:
                from .native_backend import load_lib

                _native_lib_cache[1] = load_lib()
            except Exception:
                _native_lib_cache[1] = None
    return _native_lib_cache[1]


@metrics.timed("crypto_ec_sign")
def sign_hash(priv: bytes, msg_hash: bytes) -> bytes:
    """65-byte recoverable signature r(32) || s(32) || v(1), low-s enforced."""
    assert len(msg_hash) == 32 and len(priv) == 32
    lib = _native_lib()
    if lib is not None:
        import ctypes as _ct

        out = (_ct.c_ubyte * 65)()
        if lib.lt_ec_sign(priv, msg_hash, out) == 0:
            return bytes(out)
    return _sign_hash_py(priv, msg_hash)


def _sign_hash_py(priv: bytes, msg_hash: bytes) -> bytes:
    assert len(msg_hash) == 32
    z = int.from_bytes(msg_hash, "big") % N
    d = int.from_bytes(priv, "big")
    extra = b""
    while True:
        # r == 0 / s == 0 are ~2^-256 events; retry with a tweaked nonce
        # stream while keeping z bound to the ORIGINAL message hash.
        k = _rfc6979_k(priv, hashlib.sha256(msg_hash + extra).digest() if extra else msg_hash)
        pt = _mul(G, k)
        r = pt[0] % N
        if r == 0:
            extra += b"\x00"
            continue
        s = _inv(k, N) * (z + r * d) % N
        if s == 0:
            extra += b"\x00"
            continue
        v = (pt[1] & 1) | (2 if pt[0] >= N else 0)
        if s > N // 2:  # low-s normalization flips the parity bit
            s = N - s
            v ^= 1
        return r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([v])


@metrics.timed("crypto_ec_verify")
def verify_hash(pub: bytes, msg_hash: bytes, sig: bytes) -> bool:
    lib = _native_lib()
    if lib is not None and len(pub) == 33 and len(msg_hash) == 32:
        return bool(lib.lt_ec_verify(pub, msg_hash, sig, len(sig)))
    return _verify_hash_py(pub, msg_hash, sig)


def _verify_hash_py(pub: bytes, msg_hash: bytes, sig: bytes) -> bool:
    if len(sig) != 65:
        return False
    try:
        q = decompress_public_key(pub)
    except ValueError:
        return False
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:64], "big")
    if not (1 <= r < N and 1 <= s < N):
        return False
    z = int.from_bytes(msg_hash, "big") % N
    w = _inv(s, N)
    u1 = z * w % N
    u2 = r * w % N
    pt = _add(_mul(G, u1), _mul(q, u2))
    if pt is None:
        return False
    return pt[0] % N == r


def ecdh_shared_secret(priv: bytes, pub: bytes) -> bytes:
    """32-byte shared secret: sha256 of the compressed shared point
    (role of the reference's EcdhAgreement inside Secp256K1Encrypt,
    DefaultCrypto.cs:301-318)."""
    pt = _mul(decompress_public_key(pub), int.from_bytes(priv, "big"))
    if pt is None:
        raise ValueError("degenerate ECDH result")
    compressed = bytes([0x02 | (pt[1] & 1)]) + pt[0].to_bytes(32, "big")
    return hashlib.sha256(compressed).digest()


def aes_gcm_encrypt(key: bytes, plaintext: bytes) -> bytes:
    """nonce(12) || ciphertext+tag (reference: DefaultCrypto.AesGcmEncrypt,
    DefaultCrypto.cs:267-283). Falls back to the pure-Python GCM when the
    `cryptography` package is absent — same wire format either way."""
    import secrets as _secrets

    nonce = _secrets.token_bytes(12)
    try:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    except ImportError:
        from . import _aes_fallback

        return nonce + _aes_fallback.encrypt(key, nonce, plaintext)
    return nonce + AESGCM(key).encrypt(nonce, plaintext, None)


def aes_gcm_decrypt(key: bytes, data: bytes) -> bytes:
    if len(data) < 12 + 16:
        raise ValueError("AES-GCM payload too short")
    try:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    except ImportError:
        from . import _aes_fallback

        return _aes_fallback.decrypt(key, data[:12], data[12:])
    return AESGCM(key).decrypt(data[:12], data[12:], None)


def ecies_encrypt(pub: bytes, plaintext: bytes, rng=None) -> bytes:
    """ECIES = ephemeral ECDH + AES-GCM
    (reference: DefaultCrypto.Secp256K1Encrypt, DefaultCrypto.cs:301-318).
    Layout: ephemeral compressed pubkey (33) || nonce (12) || ct+tag."""
    eph = generate_private_key(rng)
    key = ecdh_shared_secret(eph, pub)
    return public_key_bytes(eph) + aes_gcm_encrypt(key, plaintext)


def ecies_decrypt(priv: bytes, data: bytes) -> bytes:
    """(reference: DefaultCrypto.Secp256K1Decrypt, DefaultCrypto.cs:320-336)"""
    if len(data) < 33 + 12 + 16:
        raise ValueError("ECIES payload too short")
    key = ecdh_shared_secret(priv, data[:33])
    return aes_gcm_decrypt(key, data[33:])


@metrics.timed("crypto_ec_recover")
def recover_hash(msg_hash: bytes, sig: bytes) -> Optional[bytes]:
    """Recover the compressed public key from a 65-byte signature."""
    lib = _native_lib()
    if lib is not None and len(msg_hash) == 32:
        import ctypes as _ct

        out = (_ct.c_ubyte * 33)()
        if lib.lt_ec_recover(msg_hash, sig, len(sig), out) == 0:
            return bytes(out)
        return None
    return _recover_hash_py(msg_hash, sig)


# batches at least this large route to the TPU recover kernel when a chip
# is present (ops/psecp.py: per-lane windowed scalar muls on the MXU);
# smaller batches stay on the native threaded path
import os as _os_mod

_TPU_RECOVER_MIN = int(_os_mod.environ.get("LTPU_TPU_ECDSA_MIN", "2048"))
_tpu_recover_cache = [False, None]


def _tpu_recover(hashes, sigs):
    """TPU batch recovery, or None to fall through to the native path."""
    if not _tpu_recover_cache[0]:
        _tpu_recover_cache[0] = True
        try:
            import jax

            if jax.default_backend() == "tpu":
                from ..ops.psecp import TpuEcdsaRecover

                _tpu_recover_cache[1] = TpuEcdsaRecover()
        except Exception:
            _tpu_recover_cache[1] = None
    rec = _tpu_recover_cache[1]
    if rec is None:
        return None
    try:
        out = rec.recover_batch(list(hashes), list(sigs))
        metrics.inc("crypto_tpu_ecdsa_recover_batches_total")
        return out
    except Exception:
        metrics.inc("crypto_tpu_ecdsa_recover_fallbacks_total")
        return None


@metrics.timed("crypto_ec_recover_batch")
def recover_hash_batch(
    hashes: Sequence[bytes],
    sigs: Sequence[bytes],
    nthreads: Optional[int] = None,
) -> List[Optional[bytes]]:
    """Recover many signatures at once through the native threaded batch
    entry (lt_ec_recover_batch) — the pool-ingest path (role of the
    reference's background TransactionVerifier,
    Blockchain/Operations/TransactionVerifier.cs:23-72). Threads scale on
    multi-core hosts; on this 1-core CI box the win is the amortized
    fixed-base G table + windowed multiplies (~2x vs round 2). Entries
    with non-standard lengths fall back to the scalar path."""
    import os as _os

    n = len(hashes)
    if n != len(sigs):
        raise ValueError("hashes/sigs length mismatch")
    lib = _native_lib()
    regular = [
        i
        for i in range(n)
        if len(hashes[i]) == 32 and len(sigs[i]) == 65
    ]
    out: List[Optional[bytes]] = [None] * n
    if lib is None or not regular:
        return [recover_hash(h, s) for h, s in zip(hashes, sigs)]
    if len(regular) >= _TPU_RECOVER_MIN:
        tpu_out = _tpu_recover(
            [hashes[i] for i in regular], [sigs[i] for i in regular]
        )
        if tpu_out is not None:
            for pos, i in enumerate(regular):
                out[i] = tpu_out[pos]
            # irregular entries keep the scalar path (same contract as the
            # native route below): identical results with or without a chip
            regular_set = set(regular)
            for i in range(n):
                if i not in regular_set:
                    out[i] = recover_hash(hashes[i], sigs[i])
            return out
    import ctypes as _ct

    hb = b"".join(hashes[i] for i in regular)
    sb = b"".join(sigs[i] for i in regular)
    m = len(regular)
    outs = _ct.create_string_buffer(33 * m)
    oks = _ct.create_string_buffer(m)
    nt = nthreads or min(_os.cpu_count() or 1, 16)
    lib.lt_ec_recover_batch(hb, sb, m, nt, outs, oks)
    for pos, i in enumerate(regular):
        if oks.raw[pos] == 1:
            out[i] = outs.raw[33 * pos : 33 * pos + 33]
    regular_set = set(regular)
    for i in range(n):
        if i not in regular_set:
            out[i] = recover_hash(hashes[i], sigs[i])
    return out


def _recover_hash_py(msg_hash: bytes, sig: bytes) -> Optional[bytes]:
    if len(sig) != 65:
        return None
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:64], "big")
    v = sig[64]
    if not (1 <= r < N and 1 <= s < N) or v > 3:
        return None
    x = r + (N if v & 2 else 0)
    if x >= P:
        return None
    y2 = (pow(x, 3, P) + 7) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        return None
    if (y & 1) != (v & 1):
        y = P - y
    rp = (x, y)
    z = int.from_bytes(msg_hash, "big") % N
    rinv = _inv(r, N)
    q = _mul(_add(_mul(rp, s), _mul(G, N - z)), rinv)
    if q is None:
        return None
    return bytes([0x02 | (q[1] & 1)]) + q[0].to_bytes(32, "big")
