// secp256k1 ECDSA: sign / verify / recover — native backend.
//
// The role of Secp256k1.Native in the reference
// (/root/reference/src/Lachain.Crypto/Lachain.Crypto.csproj:21-22,
// DefaultCrypto.cs:79-195). The pure-Python implementation in
// lachain_tpu/crypto/ecdsa.py is the semantic oracle — this file reproduces
// its exact wire behavior (RFC 6979 nonce chain incl. the retry tweak,
// low-s normalization with parity-bit flip, the v|=2 flag for r >= n,
// recovery semantics) at native speed; conformance is enforced by
// tests/test_ecdsa.py cross-checks.
//
// Compiled into libbls381.so alongside the BLS backend (one shared object,
// one ctypes load path).

#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace secp {

typedef unsigned __int128 u128;
typedef uint64_t u64;
typedef uint32_t u32;
typedef uint8_t u8;

// ---------------------------------------------------------------------------
// generic 4x64 modular arithmetic (Montgomery) parameterized by modulus
// ---------------------------------------------------------------------------

struct Mod {
  u64 m[4];    // modulus, little-endian limbs
  u64 inv;     // -m^-1 mod 2^64
  u64 r2[4];   // (2^256)^2 mod m
};

static inline int cmp4(const u64 *a, const u64 *b) {
  for (int i = 3; i >= 0; i--) {
    if (a[i] < b[i]) return -1;
    if (a[i] > b[i]) return 1;
  }
  return 0;
}

static inline bool is_zero4(const u64 *a) {
  return (a[0] | a[1] | a[2] | a[3]) == 0;
}

static inline u64 sub4(u64 *z, const u64 *a, const u64 *b) {
  u128 borrow = 0;
  for (int i = 0; i < 4; i++) {
    u128 cur = (u128)a[i] - b[i] - (u64)borrow;
    z[i] = (u64)cur;
    borrow = (cur >> 64) ? 1 : 0;
  }
  return (u64)borrow;
}

static inline u64 add4(u64 *z, const u64 *a, const u64 *b) {
  u128 carry = 0;
  for (int i = 0; i < 4; i++) {
    u128 cur = (u128)a[i] + b[i] + (u64)carry;
    z[i] = (u64)cur;
    carry = cur >> 64;
  }
  return (u64)carry;
}

static void mod_add(const Mod &M, u64 *z, const u64 *a, const u64 *b) {
  u64 carry = add4(z, a, b);
  if (carry || cmp4(z, M.m) >= 0) {
    u64 t[4];
    sub4(t, z, M.m);
    memcpy(z, t, 32);
  }
}

static void mod_sub(const Mod &M, u64 *z, const u64 *a, const u64 *b) {
  u64 t[4];
  if (sub4(t, a, b)) add4(t, t, M.m);
  memcpy(z, t, 32);
}

// Montgomery product: z = a * b * 2^-256 mod m (CIOS)
static void mont_mul(const Mod &M, u64 *z, const u64 *a, const u64 *b) {
  u64 t[6];
  memset(t, 0, sizeof(t));
  for (int i = 0; i < 4; i++) {
    u64 carry = 0;
    for (int j = 0; j < 4; j++) {
      u128 cur = (u128)a[i] * b[j] + t[j] + carry;
      t[j] = (u64)cur;
      carry = (u64)(cur >> 64);
    }
    u128 cur = (u128)t[4] + carry;
    t[4] = (u64)cur;
    t[5] = (u64)(cur >> 64);

    u64 mfac = t[0] * M.inv;
    u128 c2 = (u128)mfac * M.m[0] + t[0];
    carry = (u64)(c2 >> 64);
    for (int j = 1; j < 4; j++) {
      u128 c3 = (u128)mfac * M.m[j] + t[j] + carry;
      t[j - 1] = (u64)c3;
      carry = (u64)(c3 >> 64);
    }
    u128 c4 = (u128)t[4] + carry;
    t[3] = (u64)c4;
    t[4] = t[5] + (u64)(c4 >> 64);
    t[5] = 0;
  }
  if (t[4] || cmp4(t, M.m) >= 0) {
    u64 s[4];
    sub4(s, t, M.m);
    memcpy(z, s, 32);
  } else {
    memcpy(z, t, 32);
  }
}

static void to_mont(const Mod &M, u64 *z, const u64 *a) {
  mont_mul(M, z, a, M.r2);
}

static void from_mont(const Mod &M, u64 *z, const u64 *a) {
  u64 one[4] = {1, 0, 0, 0};
  mont_mul(M, z, a, one);
}

// z = a^-1 mod m via Fermat (m prime): a^(m-2); exponent passed plain
static void mod_pow(const Mod &M, u64 *z, const u64 *base_mont,
                    const u64 *exp) {
  u64 acc[4];
  u64 one[4] = {1, 0, 0, 0};
  to_mont(M, acc, one);
  for (int i = 255; i >= 0; i--) {
    mont_mul(M, acc, acc, acc);
    if ((exp[i / 64] >> (i % 64)) & 1) mont_mul(M, acc, acc, base_mont);
  }
  memcpy(z, acc, 32);  // stays in Montgomery form
}

static void mod_inv(const Mod &M, u64 *z, const u64 *a_mont) {
  u64 exp[4];
  u64 two[4] = {2, 0, 0, 0};
  sub4(exp, M.m, two);
  mod_pow(M, z, a_mont, exp);
}

// ---------------------------------------------------------------------------
// curve constants
// ---------------------------------------------------------------------------

static const Mod FP = {
    {0xFFFFFFFEFFFFFC2Full, 0xFFFFFFFFFFFFFFFFull, 0xFFFFFFFFFFFFFFFFull,
     0xFFFFFFFFFFFFFFFFull},
    0xD838091DD2253531ull,
    // 2^512 mod p
    {0x000007A2000E90A1ull, 0x0000000000000001ull, 0, 0},
};

static const Mod FN = {
    {0xBFD25E8CD0364141ull, 0xBAAEDCE6AF48A03Bull, 0xFFFFFFFFFFFFFFFEull,
     0xFFFFFFFFFFFFFFFFull},
    0x4B0DFF665588B13Full,
    // 2^512 mod n
    {0x896CF21467D7D140ull, 0x741496C20E7CF878ull, 0xE697F5E45BCD07C6ull,
     0x9D671CD581C69BC5ull},
};

// generator (plain form)
static const u64 GX[4] = {0x59F2815B16F81798ull, 0x029BFCDB2DCE28D9ull,
                          0x55A06295CE870B07ull, 0x79BE667EF9DCBBACull};
static const u64 GY[4] = {0x9C47D08FFB10D4B8ull, 0xFD17B448A6855419ull,
                          0x5DA4FBFC0E1108A8ull, 0x483ADA7726A3C465ull};

static void load_be(u64 *z, const u8 *in) {
  for (int i = 0; i < 4; i++) {
    u64 v = 0;
    for (int j = 0; j < 8; j++) v = (v << 8) | in[(3 - i) * 8 + j];
    z[i] = v;
  }
}

static void store_be(u8 *out, const u64 *a) {
  for (int i = 0; i < 4; i++) {
    u64 v = a[3 - i];
    for (int j = 0; j < 8; j++) out[i * 8 + j] = (u8)(v >> (56 - 8 * j));
  }
}

// ---------------------------------------------------------------------------
// group (Jacobian, a = 0 curve y^2 = x^3 + 7) — coordinates in Montgomery
// ---------------------------------------------------------------------------

struct Pt {
  u64 x[4], y[4], z[4];
  bool inf;
};

static void pt_dbl(Pt &r, const Pt &p) {
  if (p.inf || is_zero4(p.y)) {
    r.inf = true;
    return;
  }
  u64 A[4], B[4], C[4], D[4], E[4], F[4], t[4];
  mont_mul(FP, A, p.x, p.x);         // X^2
  mont_mul(FP, B, p.y, p.y);         // Y^2
  mont_mul(FP, C, B, B);             // Y^4
  mod_add(FP, t, p.x, B);
  mont_mul(FP, D, t, t);
  mod_sub(FP, D, D, A);
  mod_sub(FP, D, D, C);
  mod_add(FP, D, D, D);              // 2((X+B)^2 - A - C)
  mod_add(FP, E, A, A);
  mod_add(FP, E, E, A);              // 3A
  mont_mul(FP, F, E, E);
  mod_sub(FP, r.x, F, D);
  mod_sub(FP, r.x, r.x, D);          // F - 2D
  mod_add(FP, t, C, C);
  mod_add(FP, t, t, t);
  mod_add(FP, t, t, t);              // 8C
  u64 y3[4];
  mod_sub(FP, y3, D, r.x);
  mont_mul(FP, y3, E, y3);
  mod_sub(FP, r.y, y3, t);
  mont_mul(FP, t, p.y, p.z);
  mod_add(FP, r.z, t, t);
  r.inf = false;
}

static void pt_add(Pt &r, const Pt &p, const Pt &q) {
  if (p.inf) {
    r = q;
    return;
  }
  if (q.inf) {
    r = p;
    return;
  }
  u64 z1z1[4], z2z2[4], u1[4], u2[4], s1[4], s2[4], h[4], rr[4], t[4];
  mont_mul(FP, z1z1, p.z, p.z);
  mont_mul(FP, z2z2, q.z, q.z);
  mont_mul(FP, u1, p.x, z2z2);
  mont_mul(FP, u2, q.x, z1z1);
  mont_mul(FP, t, p.y, q.z);
  mont_mul(FP, s1, t, z2z2);
  mont_mul(FP, t, q.y, p.z);
  mont_mul(FP, s2, t, z1z1);
  mod_sub(FP, h, u2, u1);
  mod_sub(FP, rr, s2, s1);
  if (is_zero4(h)) {
    if (is_zero4(rr)) {
      pt_dbl(r, p);
    } else {
      r.inf = true;
    }
    return;
  }
  u64 i[4], j[4], v[4], r2[4];
  mod_add(FP, t, h, h);
  mont_mul(FP, i, t, t);             // (2H)^2
  mont_mul(FP, j, h, i);
  mod_add(FP, r2, rr, rr);
  mont_mul(FP, v, u1, i);
  mont_mul(FP, t, r2, r2);
  mod_sub(FP, t, t, j);
  mod_sub(FP, t, t, v);
  mod_sub(FP, r.x, t, v);            // r2^2 - J - 2V
  mod_sub(FP, t, v, r.x);
  mont_mul(FP, t, r2, t);
  u64 s1j[4];
  mont_mul(FP, s1j, s1, j);
  mod_sub(FP, t, t, s1j);
  mod_sub(FP, r.y, t, s1j);
  u64 zz[4];
  mont_mul(FP, zz, p.z, q.z);
  mont_mul(FP, zz, zz, h);
  mod_add(FP, r.z, zz, zz);
  r.inf = false;
}

static void pt_mul(Pt &r, const Pt &p, const u64 *k /* plain scalar */) {
  Pt acc;
  acc.inf = true;
  for (int i = 255; i >= 0; i--) {
    Pt d;
    pt_dbl(d, acc);
    acc = d;
    if ((k[i / 64] >> (i % 64)) & 1) {
      Pt s;
      pt_add(s, acc, p);
      acc = s;
    }
  }
  r = acc;
}

// ---------------------------------------------------------------------------
// throughput multipliers for the VERIFY/RECOVER ingest path. The reference
// verifies receipt signatures on a background pool ahead of execution
// (Blockchain/Operations/TransactionVerifier.cs:23-72); these give the pool
// the same headroom: a fixed-base comb for G, a 4-bit windowed multiply for
// variable points, and threaded batch entry points. Signing is untouched —
// the RFC 6979 nonce path keeps its simple ladder (timing profile of the
// signing path is a separate concern; see round-2 advisor note).
// ---------------------------------------------------------------------------

static void gen_pt(Pt &g);

// 4-bit windowed multiply: 16-entry table (15 adds + 1 dbl), then 64
// windows of 4 dbls + 1 table add, skipping zero digits — ~25% fewer point
// ops than double-and-add and far fewer branches.
static void pt_mul_win(Pt &r, const Pt &p, const u64 *k /* plain scalar */) {
  Pt tab[16];
  tab[1] = p;
  pt_dbl(tab[2], p);
  for (int j = 3; j < 16; j++) pt_add(tab[j], tab[j - 1], p);
  Pt acc;
  acc.inf = true;
  for (int w = 63; w >= 0; w--) {
    if (!acc.inf) {
      Pt d;
      pt_dbl(d, acc);
      pt_dbl(acc, d);
      pt_dbl(d, acc);
      pt_dbl(acc, d);
    }
    unsigned bit = 4 * (unsigned)w;
    unsigned dig = (unsigned)(k[bit / 64] >> (bit % 64)) & 0xF;
    if (dig) {
      if (acc.inf) {
        acc = tab[dig];
      } else {
        Pt s;
        pt_add(s, acc, tab[dig]);
        acc = s;
      }
    }
  }
  r = acc;
}

// fixed-base comb for G: GTAB[w][j] = j * 2^(8w) * G. 850 KB, built once
// (~10 ms); a G-multiple then costs <= 31 Jacobian adds and no doublings.
static Pt (*GTAB)[256] = nullptr;
static std::once_flag gtab_once;

static void build_gtab() {
  GTAB = new Pt[32][256];
  Pt base;
  gen_pt(base);
  for (int w = 0; w < 32; w++) {
    GTAB[w][0].inf = true;
    GTAB[w][1] = base;
    for (int j = 2; j < 256; j++) pt_add(GTAB[w][j], GTAB[w][j - 1], base);
    for (int d = 0; d < 8; d++) {
      Pt t;
      pt_dbl(t, base);
      base = t;
    }
  }
}

static void pt_mul_g(Pt &r, const u64 *k /* plain scalar */) {
  std::call_once(gtab_once, build_gtab);
  Pt acc;
  acc.inf = true;
  for (int w = 0; w < 32; w++) {
    unsigned byte = (unsigned)(k[w / 8] >> ((w % 8) * 8)) & 0xFF;
    if (!byte) continue;
    if (acc.inf) {
      acc = GTAB[w][byte];
    } else {
      Pt s;
      pt_add(s, acc, GTAB[w][byte]);
      acc = s;
    }
  }
  r = acc;
}

// affine x, y (plain form); returns false for infinity
static bool pt_affine(u64 *ax, u64 *ay, const Pt &p) {
  if (p.inf) return false;
  u64 zi[4], zi2[4], zi3[4], xm[4], ym[4];
  mod_inv(FP, zi, p.z);
  mont_mul(FP, zi2, zi, zi);
  mont_mul(FP, zi3, zi2, zi);
  mont_mul(FP, xm, p.x, zi2);
  mont_mul(FP, ym, p.y, zi3);
  from_mont(FP, ax, xm);
  from_mont(FP, ay, ym);
  return true;
}

static void gen_pt(Pt &g) {
  to_mont(FP, g.x, GX);
  to_mont(FP, g.y, GY);
  u64 one[4] = {1, 0, 0, 0};
  to_mont(FP, g.z, one);
  g.inf = false;
}

// decompress a 33-byte pubkey; false if invalid
static bool pt_decompress(Pt &p, const u8 *pub) {
  if (pub[0] != 2 && pub[0] != 3) return false;
  u64 x[4];
  load_be(x, pub + 1);
  if (cmp4(x, FP.m) >= 0) return false;
  u64 xm[4], y2[4], seven[4] = {7, 0, 0, 0}, sm[4];
  to_mont(FP, xm, x);
  mont_mul(FP, y2, xm, xm);
  mont_mul(FP, y2, y2, xm);
  to_mont(FP, sm, seven);
  mod_add(FP, y2, y2, sm);
  // sqrt: y = y2^((p+1)/4)
  u64 exp[4];
  u64 one4[4] = {1, 0, 0, 0};
  add4(exp, FP.m, one4);
  // (p+1)/4: shift right by 2
  for (int i = 0; i < 4; i++) {
    exp[i] >>= 2;
    if (i < 3) exp[i] |= exp[i + 1] << 62;
  }
  // note: p+1 overflows 4 limbs? p+1 < 2^256, p odd -> no overflow carry
  u64 ym[4];
  mod_pow(FP, ym, y2, exp);
  u64 chk[4];
  mont_mul(FP, chk, ym, ym);
  if (cmp4(chk, y2) != 0) return false;
  u64 y[4];
  from_mont(FP, y, ym);
  if ((y[0] & 1) != (u64)(pub[0] & 1)) {
    u64 t[4];
    sub4(t, FP.m, y);
    to_mont(FP, ym, t);
  }
  p.x[0] = 0;  // fill below
  memcpy(p.x, xm, 32);
  memcpy(p.y, ym, 32);
  u64 one[4] = {1, 0, 0, 0};
  to_mont(FP, p.z, one);
  p.inf = false;
  return true;
}

// ---------------------------------------------------------------------------
// SHA-256 + HMAC (for the RFC 6979 nonce chain)
// ---------------------------------------------------------------------------

static const u32 K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

struct Sha256 {
  u32 h[8];
  u8 buf[64];
  u64 total;
  size_t fill;
};

static inline u32 rotr(u32 v, int s) { return (v >> s) | (v << (32 - s)); }

static void sha_init(Sha256 &s) {
  static const u32 H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                            0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  memcpy(s.h, H0, sizeof(H0));
  s.total = 0;
  s.fill = 0;
}

static void sha_block(Sha256 &s, const u8 *p) {
  u32 w[64];
  for (int i = 0; i < 16; i++)
    w[i] = ((u32)p[4 * i] << 24) | ((u32)p[4 * i + 1] << 16) |
           ((u32)p[4 * i + 2] << 8) | p[4 * i + 3];
  for (int i = 16; i < 64; i++) {
    u32 s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    u32 s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  u32 a = s.h[0], b = s.h[1], c = s.h[2], d = s.h[3], e = s.h[4], f = s.h[5],
      g = s.h[6], hh = s.h[7];
  for (int i = 0; i < 64; i++) {
    u32 S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    u32 ch = (e & f) ^ (~e & g);
    u32 t1 = hh + S1 + ch + K256[i] + w[i];
    u32 S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    u32 maj = (a & b) ^ (a & c) ^ (b & c);
    u32 t2 = S0 + maj;
    hh = g;
    g = f;
    f = e;
    e = d + t1;
    d = c;
    c = b;
    b = a;
    a = t1 + t2;
  }
  s.h[0] += a;
  s.h[1] += b;
  s.h[2] += c;
  s.h[3] += d;
  s.h[4] += e;
  s.h[5] += f;
  s.h[6] += g;
  s.h[7] += hh;
}

static void sha_update(Sha256 &s, const u8 *data, size_t len) {
  s.total += len;
  while (len) {
    size_t take = 64 - s.fill;
    if (take > len) take = len;
    memcpy(s.buf + s.fill, data, take);
    s.fill += take;
    data += take;
    len -= take;
    if (s.fill == 64) {
      sha_block(s, s.buf);
      s.fill = 0;
    }
  }
}

static void sha_final(Sha256 &s, u8 out[32]) {
  u64 bits = s.total * 8;
  u8 pad = 0x80;
  sha_update(s, &pad, 1);
  u8 zero = 0;
  while (s.fill != 56) sha_update(s, &zero, 1);
  u8 lenb[8];
  for (int i = 0; i < 8; i++) lenb[i] = (u8)(bits >> (56 - 8 * i));
  sha_update(s, lenb, 8);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = (u8)(s.h[i] >> 24);
    out[4 * i + 1] = (u8)(s.h[i] >> 16);
    out[4 * i + 2] = (u8)(s.h[i] >> 8);
    out[4 * i + 3] = (u8)s.h[i];
  }
}

static void sha256(const u8 *data, size_t len, u8 out[32]) {
  Sha256 s;
  sha_init(s);
  sha_update(s, data, len);
  sha_final(s, out);
}

static void hmac_sha256(const u8 *key, size_t keylen, const u8 *m1,
                        size_t l1, const u8 *m2, size_t l2, const u8 *m3,
                        size_t l3, u8 out[32]) {
  u8 k[64];
  memset(k, 0, 64);
  if (keylen > 64) {
    sha256(key, keylen, k);
  } else {
    memcpy(k, key, keylen);
  }
  u8 ipad[64], opad[64];
  for (int i = 0; i < 64; i++) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  Sha256 s;
  sha_init(s);
  sha_update(s, ipad, 64);
  if (l1) sha_update(s, m1, l1);
  if (l2) sha_update(s, m2, l2);
  if (l3) sha_update(s, m3, l3);
  u8 inner[32];
  sha_final(s, inner);
  sha_init(s);
  sha_update(s, opad, 64);
  sha_update(s, inner, 32);
  sha_final(s, out);
}

// RFC 6979 nonce (mirrors ecdsa.py:_rfc6979_k exactly)
static void rfc6979_k(u64 *k_out, const u8 priv[32], const u8 hash[32]) {
  u8 holder[32], key[32];
  memset(holder, 0x01, 32);
  memset(key, 0x00, 32);
  u8 sep0 = 0x00, sep1 = 0x01;
  u8 msg[65];
  msg[0] = 0;  // placeholder
  // key = HMAC(key, holder || 0x00 || priv || hash)
  {
    u8 cat[32 + 1 + 32 + 32];
    memcpy(cat, holder, 32);
    cat[32] = sep0;
    memcpy(cat + 33, priv, 32);
    memcpy(cat + 65, hash, 32);
    hmac_sha256(key, 32, cat, sizeof(cat), nullptr, 0, nullptr, 0, key);
  }
  hmac_sha256(key, 32, holder, 32, nullptr, 0, nullptr, 0, holder);
  {
    u8 cat[32 + 1 + 32 + 32];
    memcpy(cat, holder, 32);
    cat[32] = sep1;
    memcpy(cat + 33, priv, 32);
    memcpy(cat + 65, hash, 32);
    hmac_sha256(key, 32, cat, sizeof(cat), nullptr, 0, nullptr, 0, key);
  }
  hmac_sha256(key, 32, holder, 32, nullptr, 0, nullptr, 0, holder);
  (void)msg;
  while (true) {
    hmac_sha256(key, 32, holder, 32, nullptr, 0, nullptr, 0, holder);
    u64 k[4];
    load_be(k, holder);
    if (!is_zero4(k) && cmp4(k, FN.m) < 0) {
      memcpy(k_out, k, 32);
      return;
    }
    u8 cat[33];
    memcpy(cat, holder, 32);
    cat[32] = 0x00;
    hmac_sha256(key, 32, cat, 33, nullptr, 0, nullptr, 0, key);
    hmac_sha256(key, 32, holder, 32, nullptr, 0, nullptr, 0, holder);
  }
}

}  // namespace secp

// ---------------------------------------------------------------------------
// exported API
// ---------------------------------------------------------------------------

using namespace secp;

extern "C" {

// returns 0 ok
int lt_ec_pubkey(const u8 priv[32], u8 out[33]) {
  u64 d[4];
  load_be(d, priv);
  if (is_zero4(d) || cmp4(d, FN.m) >= 0) return 1;
  Pt g, q;
  gen_pt(g);
  pt_mul(q, g, d);
  u64 ax[4], ay[4];
  if (!pt_affine(ax, ay, q)) return 1;
  out[0] = 0x02 | (u8)(ay[0] & 1);
  store_be(out + 1, ax);
  return 0;
}

// returns 0 ok; sig = r(32) || s(32) || v(1), low-s, recoverable
int lt_ec_sign(const u8 priv[32], const u8 hash[32], u8 sig[65]) {
  u64 d[4], z[4];
  load_be(d, priv);
  if (is_zero4(d) || cmp4(d, FN.m) >= 0) return 1;
  load_be(z, hash);
  if (cmp4(z, FN.m) >= 0) {
    u64 t[4];
    sub4(t, z, FN.m);
    memcpy(z, t, 32);
  }
  u8 cur_hash[32];
  memcpy(cur_hash, hash, 32);
  int extra = 0;
  while (true) {
    u64 k[4];
    rfc6979_k(k, priv, cur_hash);
    Pt g, R;
    gen_pt(g);
    pt_mul(R, g, k);
    u64 rx[4], ry[4];
    if (!pt_affine(rx, ry, R)) return 1;
    u64 r[4];
    memcpy(r, rx, 32);
    bool high_x = cmp4(r, FN.m) >= 0;
    if (high_x) {
      u64 t[4];
      sub4(t, r, FN.m);
      memcpy(r, t, 32);
    }
    if (is_zero4(r)) goto retry;
    {
      // s = k^-1 (z + r d) mod n
      u64 km[4], kinv[4], rm[4], dm[4], zm[4], t[4], sm[4], s[4];
      to_mont(FN, km, k);
      mod_inv(FN, kinv, km);
      to_mont(FN, rm, r);
      to_mont(FN, dm, d);
      to_mont(FN, zm, z);
      mont_mul(FN, t, rm, dm);
      mod_add(FN, t, t, zm);
      mont_mul(FN, sm, kinv, t);
      from_mont(FN, s, sm);
      if (is_zero4(s)) goto retry;
      u8 v = (u8)((ry[0] & 1) | (high_x ? 2 : 0));
      // low-s normalization (flips the parity bit)
      u64 half[4];
      memcpy(half, FN.m, 32);
      // n/2 (n odd -> floor)
      for (int i = 0; i < 4; i++) {
        half[i] >>= 1;
        if (i < 3) half[i] |= FN.m[i + 1] << 63;
      }
      if (cmp4(s, half) > 0) {
        u64 t2[4];
        sub4(t2, FN.m, s);
        memcpy(s, t2, 32);
        v ^= 1;
      }
      store_be(sig, r);
      store_be(sig + 32, s);
      sig[64] = v;
      return 0;
    }
  retry:
    // mirror python: new nonce stream from sha256(orig_hash + extras)
    extra += 1;
    {
      u8 buf[32 + 16];
      memcpy(buf, hash, 32);
      for (int i = 0; i < extra && i < 16; i++) buf[32 + i] = 0;
      sha256(buf, 32 + (size_t)(extra < 16 ? extra : 16), cur_hash);
    }
  }
}

// returns 1 valid, 0 invalid
int lt_ec_verify(const u8 pub[33], const u8 hash[32], const u8 *sig,
                 size_t siglen) {
  if (siglen != 65) return 0;
  Pt q;
  if (!pt_decompress(q, pub)) return 0;
  u64 r[4], s[4], z[4];
  load_be(r, sig);
  load_be(s, sig + 32);
  if (is_zero4(r) || is_zero4(s)) return 0;
  if (cmp4(r, FN.m) >= 0 || cmp4(s, FN.m) >= 0) return 0;
  load_be(z, hash);
  if (cmp4(z, FN.m) >= 0) {
    u64 t[4];
    sub4(t, z, FN.m);
    memcpy(z, t, 32);
  }
  u64 sm[4], sinv[4], zm[4], rm[4], u1m[4], u2m[4], u1[4], u2[4];
  to_mont(FN, sm, s);
  mod_inv(FN, sinv, sm);
  to_mont(FN, zm, z);
  to_mont(FN, rm, r);
  mont_mul(FN, u1m, zm, sinv);
  mont_mul(FN, u2m, rm, sinv);
  from_mont(FN, u1, u1m);
  from_mont(FN, u2, u2m);
  Pt p1, p2, sum;
  pt_mul_g(p1, u1);
  pt_mul_win(p2, q, u2);
  pt_add(sum, p1, p2);
  u64 ax[4], ay[4];
  if (!pt_affine(ax, ay, sum)) return 0;
  if (cmp4(ax, FN.m) >= 0) {
    u64 t[4];
    sub4(t, ax, FN.m);
    memcpy(ax, t, 32);
  }
  return cmp4(ax, r) == 0 ? 1 : 0;
}

// returns 0 ok; out = compressed recovered pubkey
int lt_ec_recover(const u8 hash[32], const u8 *sig, size_t siglen,
                  u8 out[33]) {
  if (siglen != 65) return 1;
  u64 r[4], s[4];
  load_be(r, sig);
  load_be(s, sig + 32);
  u8 v = sig[64];
  if (v > 3) return 1;
  if (is_zero4(r) || is_zero4(s)) return 1;
  if (cmp4(r, FN.m) >= 0 || cmp4(s, FN.m) >= 0) return 1;
  // x = r + (v & 2 ? n : 0)
  u64 x[4];
  memcpy(x, r, 32);
  if (v & 2) {
    if (add4(x, x, FN.m)) return 1;  // overflow past 2^256
  }
  if (cmp4(x, FP.m) >= 0) return 1;
  // build compressed candidate point with parity v&1
  u8 comp[33];
  comp[0] = 0x02 | (v & 1);
  store_be(comp + 1, x);
  Pt rp;
  if (!pt_decompress(rp, comp)) return 1;
  u64 z[4];
  load_be(z, hash);
  if (cmp4(z, FN.m) >= 0) {
    u64 t[4];
    sub4(t, z, FN.m);
    memcpy(z, t, 32);
  }
  // q = r^-1 (s R - z G) = (s/r) R + (-z/r) G: two scalar muls, one of
  // them fixed-base — instead of the former three full ladders
  u64 rm[4], rinv[4], sm2[4], zm[4], u1m[4], u2m[4], u1[4], u2[4];
  to_mont(FN, rm, r);
  mod_inv(FN, rinv, rm);
  to_mont(FN, sm2, s);
  // n - z (plain)
  u64 nz[4];
  sub4(nz, FN.m, z);
  if (is_zero4(z)) memset(nz, 0, 32);
  to_mont(FN, zm, nz);
  mont_mul(FN, u1m, sm2, rinv);
  mont_mul(FN, u2m, zm, rinv);
  from_mont(FN, u1, u1m);
  from_mont(FN, u2, u2m);
  Pt p1, p2, q;
  pt_mul_win(p1, rp, u1);
  pt_mul_g(p2, u2);
  pt_add(q, p1, p2);
  u64 ax[4], ay[4];
  if (!pt_affine(ax, ay, q)) return 1;
  out[0] = 0x02 | (u8)(ay[0] & 1);
  store_be(out + 1, ax);
  return 0;
}

// ---------------------------------------------------------------------------
// threaded batch ingest (role of the reference's background
// TransactionVerifier pool, Blockchain/Operations/TransactionVerifier.cs)
// ---------------------------------------------------------------------------

// shared thread-pool driver for the batch entries: warm the G table once
// (call_once inside, but warming before spawn avoids serializing the
// workers), clamp nthreads to [1, min(n, hw)], chunk, run, join
static void run_threaded(size_t n, int nthreads,
                         const std::function<void(size_t, size_t)> &work) {
  { Pt warm; u64 one[4] = {1, 0, 0, 0}; pt_mul_g(warm, one); }
  if (nthreads < 1) nthreads = 1;
  if ((size_t)nthreads > n) nthreads = (int)n;
  unsigned hw = std::thread::hardware_concurrency();
  if (hw && (unsigned)nthreads > hw) nthreads = (int)hw;
  if (nthreads == 1) {
    work((size_t)0, n);
    return;
  }
  std::vector<std::thread> ts;
  size_t per = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; t++) {
    size_t lo = per * (size_t)t;
    size_t hi = lo + per < n ? lo + per : n;
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto &th : ts) th.join();
}

// hashes: n x 32; sigs: n x 65; outs: n x 33; oks: n x 1 (1 = recovered)
int lt_ec_recover_batch(const u8 *hashes, const u8 *sigs, size_t n,
                        int nthreads, u8 *outs, u8 *oks) {
  if (!n) return 0;
  run_threaded(n, nthreads, [&](size_t lo, size_t hi) {
    for (size_t i = lo; i < hi; i++) {
      oks[i] = lt_ec_recover(hashes + 32 * i, sigs + 65 * i, 65,
                             outs + 33 * i) == 0
                   ? 1
                   : 0;
    }
  });
  return 0;
}

// pubs: n x 33; hashes: n x 32; sigs: n x 65; oks: n x 1 (1 = valid)
int lt_ec_verify_batch(const u8 *pubs, const u8 *hashes, const u8 *sigs,
                       size_t n, int nthreads, u8 *oks) {
  if (!n) return 0;
  run_threaded(n, nthreads, [&](size_t lo, size_t hi) {
    for (size_t i = lo; i < hi; i++) {
      oks[i] = (u8)lt_ec_verify(pubs + 33 * i, hashes + 32 * i,
                                sigs + 65 * i, 65);
    }
  });
  return 0;
}

}  // extern "C"
