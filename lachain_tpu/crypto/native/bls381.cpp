// libbls381 — native BLS12-381 backend for lachain-tpu.
//
// Role parity with the reference's MCL native library
// (/root/reference/src/Lachain.Crypto/MclBls12381.cs binding to
// MCL.BLS12_381.Native): pairings, G1/G2 arithmetic, hash-to-curve, plus
// batch-first MSM entry points that the TPU-side kernels mirror.
//
// Conformance: every exported op is cross-tested against the pure-Python
// oracle (lachain_tpu/crypto/bls12381.py) in tests/test_native_backend.py.
// The algorithms intentionally mirror the oracle's structure (affine Miller
// loop on the untwisted curve, base-p final-exp decomposition) so the two
// implementations stay auditable against each other.
//
// Build: see Makefile (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

typedef uint64_t u64;
typedef unsigned __int128 u128;

// ===========================================================================
// Fp — 6x64 Montgomery arithmetic
// ===========================================================================

static const u64 P_LIMBS[6] = {
    0xb9feffffffffaaabull, 0x1eabfffeb153ffffull, 0x6730d2a0f6b0f624ull,
    0x64774b84f38512bfull, 0x4b1ba7b6434bacd7ull, 0x1a0111ea397fe69aull};

// Scalar field order r (for subgroup checks), big-endian bytes on the wire.
static const u64 R_LIMBS[4] = {
    0xffffffff00000001ull, 0x53bda402fffe5bfeull, 0x3339d80809a1d805ull,
    0x73eda753299d7d48ull};

struct Fp {
  u64 v[6];
};

static u64 PINV;     // -p^{-1} mod 2^64
static Fp MONT_ONE;  // R mod p
static Fp MONT_R2;   // R^2 mod p
static Fp MONT_R3;   // R^3 mod p
static Fp FP_ZERO;

static inline bool fp_is_zero(const Fp &a) {
  u64 acc = 0;
  for (int i = 0; i < 6; i++) acc |= a.v[i];
  return acc == 0;
}

static inline bool fp_eq(const Fp &a, const Fp &b) {
  u64 acc = 0;
  for (int i = 0; i < 6; i++) acc |= a.v[i] ^ b.v[i];
  return acc == 0;
}

static inline int cmp_limbs(const u64 *a, const u64 *b, int n) {
  for (int i = n - 1; i >= 0; i--) {
    if (a[i] < b[i]) return -1;
    if (a[i] > b[i]) return 1;
  }
  return 0;
}

static inline void sub_p_if_ge(u64 *t) {  // t has 6 limbs, t < 2p
  // BRANCHLESS: the compare-then-subtract was a data-dependent branch on
  // the hottest helper in the library (~50% mispredict on random values);
  // compute t - p unconditionally and mask-select on the borrow.
  u64 s[6];
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 cur = (u128)t[i] - P_LIMBS[i] - (u64)borrow;
    s[i] = (u64)cur;
    borrow = (cur >> 64) ? 1 : 0;
  }
  u64 keep = (u64)0 - (u64)borrow;  // all-ones if t < p (keep t)
  for (int i = 0; i < 6; i++) t[i] = (t[i] & keep) | (s[i] & ~keep);
}

static inline void fp_add(Fp &z, const Fp &a, const Fp &b) {
  u128 carry = 0;
  u64 t[6];
  for (int i = 0; i < 6; i++) {
    u128 cur = (u128)a.v[i] + b.v[i] + (u64)carry;
    t[i] = (u64)cur;
    carry = cur >> 64;
  }
  // a+b < 2p fits in 384 bits (p has 381 bits) — no 7th limb needed.
  sub_p_if_ge(t);
  memcpy(z.v, t, sizeof(t));
}

static inline void fp_sub(Fp &z, const Fp &a, const Fp &b) {
  u128 borrow = 0;
  u64 t[6];
  for (int i = 0; i < 6; i++) {
    u128 cur = (u128)a.v[i] - b.v[i] - (u64)borrow;
    t[i] = (u64)cur;
    borrow = (cur >> 64) ? 1 : 0;
  }
  // branchless: add p back masked by the borrow (data-dependent branch
  // mispredicts ~50% on random inputs)
  u64 mask = (u64)0 - (u64)borrow;
  u128 carry = 0;
  for (int i = 0; i < 6; i++) {
    u128 cur = (u128)t[i] + (P_LIMBS[i] & mask) + (u64)carry;
    t[i] = (u64)cur;
    carry = cur >> 64;
  }
  memcpy(z.v, t, sizeof(t));
}

static inline void fp_neg(Fp &z, const Fp &a) {
  if (fp_is_zero(a)) {
    z = a;
    return;
  }
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 cur = (u128)P_LIMBS[i] - a.v[i] - (u64)borrow;
    z.v[i] = (u64)cur;
    borrow = (cur >> 64) ? 1 : 0;
  }
}

// ---------------------------------------------------------------------------
// ADX/BMI2 Montgomery multiplication (the MCL/blst-class hot path).
//
// Interleaved operand-scanning CIOS with DUAL carry chains: mulx keeps CF/OF
// untouched, so the lo-limb additions ride the OF chain (adox) while the
// hi-limb additions ride the CF chain (adcx) — the two chains retire in
// parallel and the round is mulx-throughput-bound (~12 mulx/round, 6 rounds).
// Register scheme: the 7-limb accumulator lives in r8..r14 and ROTATES one
// position per round (phase B's shift-by-one-limb is free renaming; the
// freshly-zeroed low limb becomes the next round's top limb).
//
// Guarded by a start-up differential self-check against the portable CIOS
// below (fp_mul_c); any mismatch keeps the portable path (HAVE_ADX=false).
#if defined(__x86_64__) && defined(__ADX__) && defined(__BMI2__)
#define LT_HAVE_ADX_BUILD 1

// round phase A: t(T0..T5) += a_i * b;  7th limb into T6 (must enter 0)
#define LT_MUL_ROUND_A(i, T0, T1, T2, T3, T4, T5, T6)                       \
  "movq " #i "*8(%rsi), %rdx\n\t"                                           \
  "xorl %eax, %eax\n\t" /* clear CF+OF */                                   \
  "mulxq 0(%rcx), %rax, %rbp\n\t"                                           \
  "adoxq %rax, " T0 "\n\t"                                                  \
  "mulxq 8(%rcx), %rax, %r15\n\t"                                           \
  "adcxq %rbp, " T1 "\n\t"                                                  \
  "adoxq %rax, " T1 "\n\t"                                                  \
  "mulxq 16(%rcx), %rax, %rbp\n\t"                                          \
  "adcxq %r15, " T2 "\n\t"                                                  \
  "adoxq %rax, " T2 "\n\t"                                                  \
  "mulxq 24(%rcx), %rax, %r15\n\t"                                          \
  "adcxq %rbp, " T3 "\n\t"                                                  \
  "adoxq %rax, " T3 "\n\t"                                                  \
  "mulxq 32(%rcx), %rax, %rbp\n\t"                                          \
  "adcxq %r15, " T4 "\n\t"                                                  \
  "adoxq %rax, " T4 "\n\t"                                                  \
  "mulxq 40(%rcx), %rax, %r15\n\t"                                          \
  "adcxq %rbp, " T5 "\n\t"                                                  \
  "adoxq %rax, " T5 "\n\t"                                                  \
  "movl $0, %eax\n\t"                                                       \
  "adcxq %r15, " T6 "\n\t"                                                  \
  "adoxq %rax, " T6 "\n\t"

// round phase B: m = T0*PINV; t += m*p; logical >>64 (T0 becomes 0 and is
// the caller's next-round T6)
#define LT_MUL_ROUND_B(T0, T1, T2, T3, T4, T5, T6)                          \
  "movq " T0 ", %rdx\n\t"                                                   \
  "imulq lt_adx_pinv(%rip), %rdx\n\t"                                       \
  "xorl %eax, %eax\n\t"                                                     \
  "mulxq lt_adx_p(%rip), %rax, %rbp\n\t"                                    \
  "adcxq %rax, " T0 "\n\t" /* T0 -> 0 */                                    \
  "mulxq lt_adx_p+8(%rip), %rax, %r15\n\t"                                  \
  "adcxq %rbp, " T1 "\n\t"                                                  \
  "adoxq %rax, " T1 "\n\t"                                                  \
  "mulxq lt_adx_p+16(%rip), %rax, %rbp\n\t"                                 \
  "adcxq %r15, " T2 "\n\t"                                                  \
  "adoxq %rax, " T2 "\n\t"                                                  \
  "mulxq lt_adx_p+24(%rip), %rax, %r15\n\t"                                 \
  "adcxq %rbp, " T3 "\n\t"                                                  \
  "adoxq %rax, " T3 "\n\t"                                                  \
  "mulxq lt_adx_p+32(%rip), %rax, %rbp\n\t"                                 \
  "adcxq %r15, " T4 "\n\t"                                                  \
  "adoxq %rax, " T4 "\n\t"                                                  \
  "mulxq lt_adx_p+40(%rip), %rax, %r15\n\t"                                 \
  "adcxq %rbp, " T5 "\n\t"                                                  \
  "adoxq %rax, " T5 "\n\t"                                                  \
  "movl $0, %eax\n\t"                                                       \
  "adcxq %r15, " T6 "\n\t"                                                  \
  "adoxq %rax, " T6 "\n\t"

#define LT_MUL_ROUND(i, T0, T1, T2, T3, T4, T5, T6)                         \
  LT_MUL_ROUND_A(i, T0, T1, T2, T3, T4, T5, T6)                             \
  LT_MUL_ROUND_B(T0, T1, T2, T3, T4, T5, T6)

__asm__(
    ".section .rodata\n\t"
    ".balign 64\n"
    "lt_adx_p:\n\t"
    ".quad 0xb9feffffffffaaab, 0x1eabfffeb153ffff, 0x6730d2a0f6b0f624\n\t"
    ".quad 0x64774b84f38512bf, 0x4b1ba7b6434bacd7, 0x1a0111ea397fe69a\n"
    "lt_adx_pinv:\n\t"
    ".quad 0x89f3fffcfffcfffd\n\t"
    ".text\n\t"
    ".globl lt_fp_mul_adx\n\t"
    ".hidden lt_fp_mul_adx\n\t"
    ".type lt_fp_mul_adx,@function\n\t"
    ".balign 32\n"
    "lt_fp_mul_adx:\n\t"
    // rdi = z, rsi = a, rdx = b
    "pushq %rbp\n\t"
    "pushq %r12\n\t"
    "pushq %r13\n\t"
    "pushq %r14\n\t"
    "pushq %r15\n\t"
    "movq %rdx, %rcx\n\t"
    "xorl %r8d, %r8d\n\t"
    "xorl %r9d, %r9d\n\t"
    "xorl %r10d, %r10d\n\t"
    "xorl %r11d, %r11d\n\t"
    "xorl %r12d, %r12d\n\t"
    "xorl %r13d, %r13d\n\t"
    "xorl %r14d, %r14d\n\t"
    // clang-format off
    LT_MUL_ROUND(0, "%r8",  "%r9",  "%r10", "%r11", "%r12", "%r13", "%r14")
    LT_MUL_ROUND(1, "%r9",  "%r10", "%r11", "%r12", "%r13", "%r14", "%r8")
    LT_MUL_ROUND(2, "%r10", "%r11", "%r12", "%r13", "%r14", "%r8",  "%r9")
    LT_MUL_ROUND(3, "%r11", "%r12", "%r13", "%r14", "%r8",  "%r9",  "%r10")
    LT_MUL_ROUND(4, "%r12", "%r13", "%r14", "%r8",  "%r9",  "%r10", "%r11")
    LT_MUL_ROUND(5, "%r13", "%r14", "%r8",  "%r9",  "%r10", "%r11", "%r12")
    // clang-format on
    // result t0..t5 = r14, r8, r9, r10, r11, r12 (< 2p); subtract p if >= p
    "movq %r14, %rax\n\t"
    "movq %r8,  %rcx\n\t"
    "movq %r9,  %rdx\n\t"
    "movq %r10, %rsi\n\t"
    "movq %r11, %r15\n\t"
    "movq %r12, %r13\n\t"
    "subq lt_adx_p+0(%rip),  %rax\n\t"
    "sbbq lt_adx_p+8(%rip),  %rcx\n\t"
    "sbbq lt_adx_p+16(%rip), %rdx\n\t"
    "sbbq lt_adx_p+24(%rip), %rsi\n\t"
    "sbbq lt_adx_p+32(%rip), %r15\n\t"
    "sbbq lt_adx_p+40(%rip), %r13\n\t"
    "cmovcq %r14, %rax\n\t"
    "cmovcq %r8,  %rcx\n\t"
    "cmovcq %r9,  %rdx\n\t"
    "cmovcq %r10, %rsi\n\t"
    "cmovcq %r11, %r15\n\t"
    "cmovcq %r12, %r13\n\t"
    "movq %rax, 0(%rdi)\n\t"
    "movq %rcx, 8(%rdi)\n\t"
    "movq %rdx, 16(%rdi)\n\t"
    "movq %rsi, 24(%rdi)\n\t"
    "movq %r15, 32(%rdi)\n\t"
    "movq %r13, 40(%rdi)\n\t"
    "popq %r15\n\t"
    "popq %r14\n\t"
    "popq %r13\n\t"
    "popq %r12\n\t"
    "popq %rbp\n\t"
    "ret\n\t"
    ".size lt_fp_mul_adx, .-lt_fp_mul_adx\n\t");

extern "C" void lt_fp_mul_adx(u64 *z, const u64 *a, const u64 *b);
#endif  // __x86_64__ && __ADX__ && __BMI2__

static bool HAVE_ADX = false;  // set by the init self-check

// Portable CIOS Montgomery multiplication (also the self-check oracle).
static void fp_mul_c(Fp &z, const Fp &a, const Fp &b) {
  u64 t[8];
  memset(t, 0, sizeof(t));
  for (int i = 0; i < 6; i++) {
    u64 carry = 0;
    u64 ai = a.v[i];
    for (int j = 0; j < 6; j++) {
      u128 cur = (u128)ai * b.v[j] + t[j] + carry;
      t[j] = (u64)cur;
      carry = (u64)(cur >> 64);
    }
    u128 cur = (u128)t[6] + carry;
    t[6] = (u64)cur;
    t[7] = (u64)(cur >> 64);

    u64 m = t[0] * PINV;
    u128 cur2 = (u128)m * P_LIMBS[0] + t[0];
    carry = (u64)(cur2 >> 64);
    for (int j = 1; j < 6; j++) {
      u128 c3 = (u128)m * P_LIMBS[j] + t[j] + carry;
      t[j - 1] = (u64)c3;
      carry = (u64)(c3 >> 64);
    }
    u128 c4 = (u128)t[6] + carry;
    t[5] = (u64)c4;
    t[6] = t[7] + (u64)(c4 >> 64);
    t[7] = 0;
  }
  // t[0..5] < 2p (t[6] == 0 for BLS12-381's 381-bit p).
  sub_p_if_ge(t);
  memcpy(z.v, t, 48);
}

static inline void fp_mul(Fp &z, const Fp &a, const Fp &b) {
#ifdef LT_HAVE_ADX_BUILD
  if (HAVE_ADX) {
    lt_fp_mul_adx(z.v, a.v, b.v);
    return;
  }
#endif
  fp_mul_c(z, a, b);
}

static inline void fp_sqr(Fp &z, const Fp &a) { fp_mul(z, a, a); }

static inline void fp_dbl(Fp &z, const Fp &a) { fp_add(z, a, a); }

// Binary extended GCD inversion on the plain (non-Montgomery) value.
static void limbs_rshift1(u64 *a, int n) {
  for (int i = 0; i < n - 1; i++) a[i] = (a[i] >> 1) | (a[i + 1] << 63);
  a[n - 1] >>= 1;
}

static void limbs_add(u64 *a, const u64 *b, int n) {
  u128 carry = 0;
  for (int i = 0; i < n; i++) {
    u128 cur = (u128)a[i] + b[i] + (u64)carry;
    a[i] = (u64)cur;
    carry = cur >> 64;
  }
}

static bool limbs_sub(u64 *a, const u64 *b, int n) {  // a -= b, ret borrow
  u128 borrow = 0;
  for (int i = 0; i < n; i++) {
    u128 cur = (u128)a[i] - b[i] - (u64)borrow;
    a[i] = (u64)cur;
    borrow = (cur >> 64) ? 1 : 0;
  }
  return borrow != 0;
}

static bool limbs_is_zero(const u64 *a, int n) {
  u64 acc = 0;
  for (int i = 0; i < n; i++) acc |= a[i];
  return acc == 0;
}

// a^{-1} mod p for plain a (not Montgomery); result plain.
static void fp_inv_plain(u64 *out, const u64 *a_in) {
  u64 u[6], v[6], b[6], c[6];
  memcpy(u, a_in, 48);
  memcpy(v, P_LIMBS, 48);
  memset(b, 0, 48);
  b[0] = 1;
  memset(c, 0, 48);
  while (!limbs_is_zero(u, 6) && !limbs_is_zero(v, 6)) {
    while (!(u[0] & 1)) {
      limbs_rshift1(u, 6);
      if (b[0] & 1) limbs_add(b, P_LIMBS, 6);
      limbs_rshift1(b, 6);
    }
    while (!(v[0] & 1)) {
      limbs_rshift1(v, 6);
      if (c[0] & 1) limbs_add(c, P_LIMBS, 6);
      limbs_rshift1(c, 6);
    }
    if (cmp_limbs(u, v, 6) >= 0) {
      limbs_sub(u, v, 6);
      if (limbs_sub(b, c, 6)) limbs_add(b, P_LIMBS, 6);
    } else {
      limbs_sub(v, u, 6);
      if (limbs_sub(c, b, 6)) limbs_add(c, P_LIMBS, 6);
    }
  }
  if (limbs_is_zero(u, 6))
    memcpy(out, c, 48);
  else
    memcpy(out, b, 48);
}

// Montgomery-form inversion: inv(aR) = a^{-1} R.
static void fp_inv(Fp &z, const Fp &a) {
  Fp plain_inv;
  // a.v is aR (plain number). egcd gives (aR)^{-1} = a^{-1} R^{-1}.
  fp_inv_plain(plain_inv.v, a.v);
  fp_mul(z, plain_inv, MONT_R3);  // * R^3 * R^{-1} => a^{-1} R
}

static void fp_from_bytes_be(Fp &z, const uint8_t *in) {  // 48 bytes
  Fp plain;
  for (int i = 0; i < 6; i++) {
    u64 limb = 0;
    for (int j = 0; j < 8; j++) limb = (limb << 8) | in[(5 - i) * 8 + j];
    plain.v[i] = limb;
  }
  fp_mul(z, plain, MONT_R2);  // to Montgomery
}

static void fp_to_bytes_be(uint8_t *out, const Fp &a) {
  Fp one;
  memset(one.v, 0, 48);
  one.v[0] = 1;
  Fp plain;
  fp_mul(plain, a, one);  // from Montgomery
  for (int i = 0; i < 6; i++) {
    u64 limb = plain.v[5 - i];
    for (int j = 0; j < 8; j++) out[i * 8 + j] = (uint8_t)(limb >> (56 - 8 * j));
  }
}

static void fp_set_u64(Fp &z, u64 x) {
  Fp plain;
  memset(plain.v, 0, 48);
  plain.v[0] = x;
  fp_mul(z, plain, MONT_R2);
}

// z = a^e where e is nbits-wide big-endian limb array (plain integer exponent)
static void fp_pow_limbs(Fp &z, const Fp &a, const u64 *e, int nlimbs) {
  Fp result = MONT_ONE, base = a;
  int top = nlimbs * 64 - 1;
  while (top >= 0 && !((e[top / 64] >> (top % 64)) & 1)) top--;
  for (int i = 0; i <= top; i++) {
    if ((e[i / 64] >> (i % 64)) & 1) fp_mul(result, result, base);
    fp_sqr(base, base);
  }
  z = result;
}

// sqrt via a^((p+1)/4); returns false if not a QR.
static u64 P_PLUS1_DIV4[6];

static bool fp_sqrt(Fp &z, const Fp &a) {
  Fp s;
  fp_pow_limbs(s, a, P_PLUS1_DIV4, 6);
  Fp chk;
  fp_sqr(chk, s);
  if (!fp_eq(chk, a)) return false;
  z = s;
  return true;
}

// ===========================================================================
// Fp2 = Fp[u]/(u^2+1)
// ===========================================================================

struct Fp2 {
  Fp c0, c1;
};

static Fp2 FP2_ZERO_, FP2_ONE_;

static inline void fp2_add(Fp2 &z, const Fp2 &a, const Fp2 &b) {
  fp_add(z.c0, a.c0, b.c0);
  fp_add(z.c1, a.c1, b.c1);
}
static inline void fp2_sub(Fp2 &z, const Fp2 &a, const Fp2 &b) {
  fp_sub(z.c0, a.c0, b.c0);
  fp_sub(z.c1, a.c1, b.c1);
}
static inline void fp2_neg(Fp2 &z, const Fp2 &a) {
  fp_neg(z.c0, a.c0);
  fp_neg(z.c1, a.c1);
}
static inline void fp2_conj(Fp2 &z, const Fp2 &a) {
  z.c0 = a.c0;
  fp_neg(z.c1, a.c1);
}
static void fp2_mul(Fp2 &z, const Fp2 &a, const Fp2 &b) {
  Fp t0, t1, t2, t3, s0, s1;
  fp_mul(t0, a.c0, b.c0);
  fp_mul(t1, a.c1, b.c1);
  fp_add(t2, a.c0, a.c1);
  fp_add(t3, b.c0, b.c1);
  fp_mul(t2, t2, t3);
  fp_sub(s0, t0, t1);
  fp_sub(t2, t2, t0);
  fp_sub(s1, t2, t1);
  z.c0 = s0;
  z.c1 = s1;
}
static void fp2_sqr(Fp2 &z, const Fp2 &a) {
  Fp t0, t1, s0, s1;
  fp_add(t0, a.c0, a.c1);
  fp_sub(t1, a.c0, a.c1);
  fp_mul(s0, t0, t1);
  fp_mul(t0, a.c0, a.c1);
  fp_add(s1, t0, t0);
  z.c0 = s0;
  z.c1 = s1;
}
static void fp2_muls(Fp2 &z, const Fp2 &a, u64 s) {
  Fp fs;
  fp_set_u64(fs, s);
  fp_mul(z.c0, a.c0, fs);
  fp_mul(z.c1, a.c1, fs);
}
static void fp2_inv(Fp2 &z, const Fp2 &a) {
  Fp n, t, i;
  fp_sqr(n, a.c0);
  fp_sqr(t, a.c1);
  fp_add(n, n, t);
  fp_inv(i, n);
  fp_mul(z.c0, a.c0, i);
  Fp negc1;
  fp_neg(negc1, a.c1);
  fp_mul(z.c1, negc1, i);
}
static inline bool fp2_is_zero(const Fp2 &a) {
  return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}
static inline bool fp2_eq(const Fp2 &a, const Fp2 &b) {
  return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}
// multiply by xi = 1 + u
static inline void fp2_mul_xi(Fp2 &z, const Fp2 &a) {
  Fp t0, t1;
  fp_sub(t0, a.c0, a.c1);
  fp_add(t1, a.c0, a.c1);
  z.c0 = t0;
  z.c1 = t1;
}

static void fp2_pow_limbs(Fp2 &z, const Fp2 &a, const u64 *e, int nlimbs) {
  Fp2 result = FP2_ONE_, base = a;
  int top = nlimbs * 64 - 1;
  while (top >= 0 && !((e[top / 64] >> (top % 64)) & 1)) top--;
  for (int i = 0; i <= top; i++) {
    if ((e[i / 64] >> (i % 64)) & 1) fp2_mul(result, result, base);
    fp2_sqr(base, base);
  }
  z = result;
}

// Mirrors the oracle's fp2_sqrt (norm trick) — root choice must match Python.
static bool fp2_sqrt(Fp2 &z, const Fp2 &a) {
  if (fp_is_zero(a.c1)) {
    Fp s;
    if (fp_sqrt(s, a.c0)) {
      z.c0 = s;
      z.c1 = FP_ZERO;
      return true;
    }
    Fp na;
    fp_neg(na, a.c0);
    if (fp_sqrt(s, na)) {
      z.c0 = FP_ZERO;
      z.c1 = s;
      return true;
    }
    return false;
  }
  Fp n, t, s;
  fp_sqr(n, a.c0);
  fp_sqr(t, a.c1);
  fp_add(n, n, t);
  if (!fp_sqrt(s, n)) return false;
  Fp inv2, two;
  fp_set_u64(two, 2);
  fp_inv(inv2, two);
  Fp lam;
  fp_add(t, a.c0, s);
  fp_mul(t, t, inv2);
  if (!fp_sqrt(lam, t)) {
    fp_sub(t, a.c0, s);
    fp_mul(t, t, inv2);
    if (!fp_sqrt(lam, t)) return false;
  }
  Fp two_lam, inv_2lam;
  fp_add(two_lam, lam, lam);
  fp_inv(inv_2lam, two_lam);
  z.c0 = lam;
  fp_mul(z.c1, a.c1, inv_2lam);
  Fp2 chk;
  fp2_sqr(chk, z);
  return fp2_eq(chk, a);
}

// ===========================================================================
// Fp6 = Fp2[v]/(v^3 - xi), Fp12 = Fp6[w]/(w^2 - v)
// ===========================================================================

struct Fp6 {
  Fp2 c0, c1, c2;
};
struct Fp12 {
  Fp6 c0, c1;
};

static Fp6 FP6_ZERO_, FP6_ONE_;
static Fp12 FP12_ONE_, FP12_ZERO_;

static inline void fp6_add(Fp6 &z, const Fp6 &a, const Fp6 &b) {
  fp2_add(z.c0, a.c0, b.c0);
  fp2_add(z.c1, a.c1, b.c1);
  fp2_add(z.c2, a.c2, b.c2);
}
static inline void fp6_sub(Fp6 &z, const Fp6 &a, const Fp6 &b) {
  fp2_sub(z.c0, a.c0, b.c0);
  fp2_sub(z.c1, a.c1, b.c1);
  fp2_sub(z.c2, a.c2, b.c2);
}
static inline void fp6_neg(Fp6 &z, const Fp6 &a) {
  fp2_neg(z.c0, a.c0);
  fp2_neg(z.c1, a.c1);
  fp2_neg(z.c2, a.c2);
}
static void fp6_mul(Fp6 &z, const Fp6 &a, const Fp6 &b) {
  Fp2 t00, t11, t22, x, y, c0, c1, c2;
  fp2_mul(t00, a.c0, b.c0);
  fp2_mul(t11, a.c1, b.c1);
  fp2_mul(t22, a.c2, b.c2);
  fp2_mul(x, a.c1, b.c2);
  fp2_mul(y, a.c2, b.c1);
  fp2_add(x, x, y);
  fp2_mul_xi(x, x);
  fp2_add(c0, t00, x);
  fp2_mul(x, a.c0, b.c1);
  fp2_mul(y, a.c1, b.c0);
  fp2_add(x, x, y);
  fp2_mul_xi(y, t22);
  fp2_add(c1, x, y);
  fp2_mul(x, a.c0, b.c2);
  fp2_mul(y, a.c2, b.c0);
  fp2_add(x, x, y);
  fp2_add(c2, x, t11);
  z.c0 = c0;
  z.c1 = c1;
  z.c2 = c2;
}
static inline void fp6_sqr(Fp6 &z, const Fp6 &a) { fp6_mul(z, a, a); }
static void fp6_mul_by_v(Fp6 &z, const Fp6 &a) {
  Fp2 t;
  fp2_mul_xi(t, a.c2);
  Fp2 old0 = a.c0, old1 = a.c1;
  z.c0 = t;
  z.c1 = old0;
  z.c2 = old1;
}
static void fp6_inv(Fp6 &z, const Fp6 &a) {
  Fp2 t0, t1, t2, x, y, f, finv;
  fp2_sqr(t0, a.c0);
  fp2_mul(x, a.c1, a.c2);
  fp2_mul_xi(x, x);
  fp2_sub(t0, t0, x);
  fp2_sqr(t1, a.c2);
  fp2_mul_xi(t1, t1);
  fp2_mul(x, a.c0, a.c1);
  fp2_sub(t1, t1, x);
  fp2_sqr(t2, a.c1);
  fp2_mul(x, a.c0, a.c2);
  fp2_sub(t2, t2, x);
  fp2_mul(f, a.c0, t0);
  fp2_mul(x, a.c2, t1);
  fp2_mul(y, a.c1, t2);
  fp2_add(x, x, y);
  fp2_mul_xi(x, x);
  fp2_add(f, f, x);
  fp2_inv(finv, f);
  fp2_mul(z.c0, t0, finv);
  fp2_mul(z.c1, t1, finv);
  fp2_mul(z.c2, t2, finv);
}

static void fp12_mul(Fp12 &z, const Fp12 &a, const Fp12 &b) {
  Fp6 t0, t1, x, y;
  fp6_mul(t0, a.c0, b.c0);
  fp6_mul(t1, a.c1, b.c1);
  fp6_add(x, a.c0, a.c1);
  fp6_add(y, b.c0, b.c1);
  fp6_mul(x, x, y);
  fp6_sub(x, x, t0);
  Fp6 c1;
  fp6_sub(c1, x, t1);
  Fp6 vt1;
  fp6_mul_by_v(vt1, t1);
  fp6_add(z.c0, t0, vt1);
  z.c1 = c1;
}
static inline void fp12_sqr(Fp12 &z, const Fp12 &a) { fp12_mul(z, a, a); }

// complex squaring for Fp12 = Fp6[w]/(w^2 - v): 2 fp6_mul instead of 3
static void fp12_sqr_fast(Fp12 &z, const Fp12 &a) {
  Fp6 t, s0, s1, vt;
  fp6_mul(t, a.c0, a.c1);
  fp6_add(s0, a.c0, a.c1);
  fp6_mul_by_v(vt, a.c1);
  fp6_add(s1, a.c0, vt);
  fp6_mul(s1, s0, s1);  // (a0+a1)(a0+v a1) = a0^2 + v a1^2 + (1+v) a0 a1
  fp6_sub(s1, s1, t);
  fp6_mul_by_v(vt, t);
  fp6_sub(z.c0, s1, vt);
  fp6_add(z.c1, t, t);
}
static inline void fp12_conj(Fp12 &z, const Fp12 &a) {
  z.c0 = a.c0;
  fp6_neg(z.c1, a.c1);
}
static void fp12_inv(Fp12 &z, const Fp12 &a) {
  Fp6 t0, t1, f, finv;
  fp6_sqr(t0, a.c0);
  fp6_sqr(t1, a.c1);
  fp6_mul_by_v(t1, t1);
  fp6_sub(f, t0, t1);
  fp6_inv(finv, f);
  fp6_mul(z.c0, a.c0, finv);
  Fp6 n;
  fp6_mul(n, a.c1, finv);
  fp6_neg(z.c1, n);
}
static void fp12_sub(Fp12 &z, const Fp12 &a, const Fp12 &b) {
  fp6_sub(z.c0, a.c0, b.c0);
  fp6_sub(z.c1, a.c1, b.c1);
}
static bool fp12_is_one(const Fp12 &a) {
  return fp2_eq(a.c0.c0, FP2_ONE_) && fp2_is_zero(a.c0.c1) &&
         fp2_is_zero(a.c0.c2) && fp2_is_zero(a.c1.c0) &&
         fp2_is_zero(a.c1.c1) && fp2_is_zero(a.c1.c2);
}
static bool fp12_is_zero(const Fp12 &a) {
  return fp2_is_zero(a.c0.c0) && fp2_is_zero(a.c0.c1) &&
         fp2_is_zero(a.c0.c2) && fp2_is_zero(a.c1.c0) &&
         fp2_is_zero(a.c1.c1) && fp2_is_zero(a.c1.c2);
}
static bool fp12_eq(const Fp12 &a, const Fp12 &b) {
  Fp12 d;
  fp12_sub(d, a, b);
  return fp12_is_zero(d);
}

// Frobenius coefficients gamma_i = xi^((p-1)*i/6), computed at init.
static Fp2 GAMMA[6];

static void fp12_frobenius(Fp12 &z, const Fp12 &a) {
  Fp2 t;
  fp2_conj(z.c0.c0, a.c0.c0);
  fp2_conj(t, a.c0.c1);
  fp2_mul(z.c0.c1, t, GAMMA[2]);
  fp2_conj(t, a.c0.c2);
  fp2_mul(z.c0.c2, t, GAMMA[4]);
  fp2_conj(t, a.c1.c0);
  fp2_mul(z.c1.c0, t, GAMMA[1]);
  fp2_conj(t, a.c1.c1);
  fp2_mul(z.c1.c1, t, GAMMA[3]);
  fp2_conj(t, a.c1.c2);
  fp2_mul(z.c1.c2, t, GAMMA[5]);
}

// ===========================================================================
// G1 (Jacobian over Fp) and G2 (Jacobian over Fp2)
// ===========================================================================

struct G1 {
  Fp x, y, z;
};
struct G2 {
  Fp2 x, y, z;
};

static G1 G1_INF_;
static G2 G2_INF_;

static inline bool g1_is_inf(const G1 &p) { return fp_is_zero(p.z); }
static inline bool g2_is_inf(const G2 &p) { return fp2_is_zero(p.z); }

static void g1_dbl(G1 &r, const G1 &p) {
  if (g1_is_inf(p) || fp_is_zero(p.y)) {
    r = G1_INF_;
    return;
  }
  Fp a, b, c, d, e, f, t;
  fp_sqr(a, p.x);
  fp_sqr(b, p.y);
  fp_sqr(c, b);
  fp_add(d, p.x, b);
  fp_sqr(d, d);
  fp_sub(d, d, a);
  fp_sub(d, d, c);
  fp_dbl(d, d);
  fp_add(e, a, a);
  fp_add(e, e, a);
  fp_sqr(f, e);
  Fp x3, y3, z3;
  fp_sub(x3, f, d);
  fp_sub(x3, x3, d);
  fp_sub(t, d, x3);
  fp_mul(y3, e, t);
  Fp c8;
  fp_dbl(c8, c);
  fp_dbl(c8, c8);
  fp_dbl(c8, c8);
  fp_sub(y3, y3, c8);
  fp_mul(z3, p.y, p.z);
  fp_dbl(z3, z3);
  r.x = x3;
  r.y = y3;
  r.z = z3;
}

static void g1_add(G1 &r, const G1 &p, const G1 &q) {
  if (g1_is_inf(p)) {
    r = q;
    return;
  }
  if (g1_is_inf(q)) {
    r = p;
    return;
  }
  Fp z1z1, z2z2, u1, u2, s1, s2, t;
  fp_sqr(z1z1, p.z);
  fp_sqr(z2z2, q.z);
  fp_mul(u1, p.x, z2z2);
  fp_mul(u2, q.x, z1z1);
  fp_mul(t, p.y, q.z);
  fp_mul(s1, t, z2z2);
  fp_mul(t, q.y, p.z);
  fp_mul(s2, t, z1z1);
  if (fp_eq(u1, u2)) {
    if (fp_eq(s1, s2)) {
      g1_dbl(r, p);
      return;
    }
    r = G1_INF_;
    return;
  }
  Fp h, i, j, rr, v;
  fp_sub(h, u2, u1);
  fp_dbl(i, h);
  fp_sqr(i, i);
  fp_mul(j, h, i);
  fp_sub(rr, s2, s1);
  fp_dbl(rr, rr);
  fp_mul(v, u1, i);
  Fp x3, y3, z3;
  fp_sqr(x3, rr);
  fp_sub(x3, x3, j);
  fp_sub(x3, x3, v);
  fp_sub(x3, x3, v);
  fp_sub(t, v, x3);
  fp_mul(y3, rr, t);
  Fp s1j;
  fp_mul(s1j, s1, j);
  fp_dbl(s1j, s1j);
  fp_sub(y3, y3, s1j);
  fp_mul(z3, p.z, q.z);
  fp_mul(z3, z3, h);
  fp_dbl(z3, z3);
  r.x = x3;
  r.y = y3;
  r.z = z3;
}

static void g1_neg(G1 &r, const G1 &p) {
  r.x = p.x;
  fp_neg(r.y, p.y);
  r.z = p.z;
}

static void g2_dbl(G2 &r, const G2 &p) {
  if (g2_is_inf(p) || fp2_is_zero(p.y)) {
    r = G2_INF_;
    return;
  }
  Fp2 a, b, c, d, e, f, t;
  fp2_sqr(a, p.x);
  fp2_sqr(b, p.y);
  fp2_sqr(c, b);
  fp2_add(d, p.x, b);
  fp2_sqr(d, d);
  fp2_sub(d, d, a);
  fp2_sub(d, d, c);
  fp2_add(d, d, d);
  fp2_add(e, a, a);
  fp2_add(e, e, a);
  fp2_sqr(f, e);
  Fp2 x3, y3, z3;
  fp2_sub(x3, f, d);
  fp2_sub(x3, x3, d);
  fp2_sub(t, d, x3);
  fp2_mul(y3, e, t);
  Fp2 c8;
  fp2_add(c8, c, c);
  fp2_add(c8, c8, c8);
  fp2_add(c8, c8, c8);
  fp2_sub(y3, y3, c8);
  fp2_mul(z3, p.y, p.z);
  fp2_add(z3, z3, z3);
  r.x = x3;
  r.y = y3;
  r.z = z3;
}

static void g2_add(G2 &r, const G2 &p, const G2 &q) {
  if (g2_is_inf(p)) {
    r = q;
    return;
  }
  if (g2_is_inf(q)) {
    r = p;
    return;
  }
  Fp2 z1z1, z2z2, u1, u2, s1, s2, t;
  fp2_sqr(z1z1, p.z);
  fp2_sqr(z2z2, q.z);
  fp2_mul(u1, p.x, z2z2);
  fp2_mul(u2, q.x, z1z1);
  fp2_mul(t, p.y, q.z);
  fp2_mul(s1, t, z2z2);
  fp2_mul(t, q.y, p.z);
  fp2_mul(s2, t, z1z1);
  if (fp2_eq(u1, u2)) {
    if (fp2_eq(s1, s2)) {
      g2_dbl(r, p);
      return;
    }
    r = G2_INF_;
    return;
  }
  Fp2 h, i, j, rr, v;
  fp2_sub(h, u2, u1);
  fp2_add(i, h, h);
  fp2_sqr(i, i);
  fp2_mul(j, h, i);
  fp2_sub(rr, s2, s1);
  fp2_add(rr, rr, rr);
  fp2_mul(v, u1, i);
  Fp2 x3, y3, z3;
  fp2_sqr(x3, rr);
  fp2_sub(x3, x3, j);
  fp2_sub(x3, x3, v);
  fp2_sub(x3, x3, v);
  fp2_sub(t, v, x3);
  fp2_mul(y3, rr, t);
  Fp2 s1j;
  fp2_mul(s1j, s1, j);
  fp2_add(s1j, s1j, s1j);
  fp2_sub(y3, y3, s1j);
  fp2_mul(z3, p.z, q.z);
  fp2_mul(z3, z3, h);
  fp2_add(z3, z3, z3);
  r.x = x3;
  r.y = y3;
  r.z = z3;
}

static void g2_neg(G2 &r, const G2 &p) {
  r.x = p.x;
  fp2_neg(r.y, p.y);
  r.z = p.z;
}

// scalar = big-endian byte string, arbitrary length
static void g1_mul_scalar(G1 &r, const G1 &p, const uint8_t *scalar,
                          size_t len) {
  G1 acc = G1_INF_;
  bool started = false;
  for (size_t i = 0; i < len; i++) {
    for (int b = 7; b >= 0; b--) {
      if (started) g1_dbl(acc, acc);
      if ((scalar[i] >> b) & 1) {
        g1_add(acc, acc, p);
        started = true;
      }
    }
  }
  r = acc;
}

static void g2_mul_scalar(G2 &r, const G2 &p, const uint8_t *scalar,
                          size_t len) {
  G2 acc = G2_INF_;
  bool started = false;
  for (size_t i = 0; i < len; i++) {
    for (int b = 7; b >= 0; b--) {
      if (started) g2_dbl(acc, acc);
      if ((scalar[i] >> b) & 1) {
        g2_add(acc, acc, p);
        started = true;
      }
    }
  }
  r = acc;
}

static void g1_to_affine(Fp &ax, Fp &ay, const G1 &p) {
  Fp zi, zi2;
  fp_inv(zi, p.z);
  fp_sqr(zi2, zi);
  fp_mul(ax, p.x, zi2);
  fp_mul(zi2, zi2, zi);
  fp_mul(ay, p.y, zi2);
}

static void g2_to_affine(Fp2 &ax, Fp2 &ay, const G2 &p) {
  Fp2 zi, zi2;
  fp2_inv(zi, p.z);
  fp2_sqr(zi2, zi);
  fp2_mul(ax, p.x, zi2);
  fp2_mul(zi2, zi2, zi);
  fp2_mul(ay, p.y, zi2);
}

// ===========================================================================
// GLV + Straus small-MSM machinery (the Lagrange-combine hot path)
//
// The binary egcd inversion costs ~16us on this box, so EVERY to-affine
// conversion in batch paths goes through Montgomery's batch-inversion trick
// (one egcd + 3 muls/element) — g1_to_affine above is for singletons only.
// ===========================================================================

// |z| for BLS12-381 (z = -0xd201000000010000), Hamming weight 6: a scalar
// ladder over it costs 64 doublings + 5 additions
static const uint8_t Z_ABS_BE[8] = {0xd2, 0x01, 0x00, 0x00,
                                    0x00, 0x01, 0x00, 0x00};
// beta: the cube root of unity in Fp whose GLV endomorphism
// phi(x, y) = (beta*x, y) acts as multiplication by lambda = z^2 - 1 on
// G1 (beta = (2^((p-1)/3))^2; the OTHER root pairs with the other
// eigenvalue — resolved empirically and pinned by the soundness
// certificate, tests/test_subgroup_fast.py)
static const uint8_t BETA_G1_BE[48] = {
    0x1a, 0x01, 0x11, 0xea, 0x39, 0x7f, 0xe6, 0x99, 0xec, 0x02, 0x40, 0x86,
    0x63, 0xd4, 0xde, 0x85, 0xaa, 0x0d, 0x85, 0x7d, 0x89, 0x75, 0x9a, 0xd4,
    0x89, 0x7d, 0x29, 0x65, 0x0f, 0xb8, 0x5f, 0x9b, 0x40, 0x94, 0x27, 0xeb,
    0x4f, 0x49, 0xff, 0xfd, 0x8b, 0xfd, 0x00, 0x00, 0x00, 0x00, 0xaa, 0xac};

// Montgomery batch inversion: zs[i] <- zs[i]^{-1}; zero entries stay zero
// (callers use Z==0 as the point-at-infinity marker).
static void fp_batch_inv(Fp *zs, size_t n) {
  if (n == 0) return;
  std::vector<Fp> pre(n);
  Fp acc = MONT_ONE;
  for (size_t i = 0; i < n; i++) {
    pre[i] = acc;
    if (!fp_is_zero(zs[i])) fp_mul(acc, acc, zs[i]);
  }
  Fp inv;
  fp_inv(inv, acc);
  for (size_t i = n; i-- > 0;) {
    if (fp_is_zero(zs[i])) continue;
    Fp t;
    fp_mul(t, inv, pre[i]);
    fp_mul(inv, inv, zs[i]);
    zs[i] = t;
  }
}

// Batch Jacobian -> affine for n points with ONE field inversion; on
// return (xs[i], ys[i]) is affine and valid[i]=false marks infinity.
static void g1_batch_to_affine(const G1 *pts, Fp *xs, Fp *ys,
                               uint8_t *valid, size_t n) {
  std::vector<Fp> zs(n);
  for (size_t i = 0; i < n; i++) zs[i] = pts[i].z;
  fp_batch_inv(zs.data(), n);
  for (size_t i = 0; i < n; i++) {
    if (fp_is_zero(zs[i])) {
      xs[i] = FP_ZERO;
      ys[i] = FP_ZERO;
      valid[i] = 0;
      continue;
    }
    Fp zi2, zi3;
    fp_sqr(zi2, zs[i]);
    fp_mul(zi3, zi2, zs[i]);
    fp_mul(xs[i], pts[i].x, zi2);
    fp_mul(ys[i], pts[i].y, zi3);
    valid[i] = 1;
  }
}

// mixed addition r = p + (qx, qy) [affine q, q != inf] — madd-2007-bl
// (7M + 4S vs the 11M + 5S full Jacobian add); handles p == +-q.
static void g1_madd(G1 &r, const G1 &p, const Fp &qx, const Fp &qy) {
  if (g1_is_inf(p)) {
    r.x = qx;
    r.y = qy;
    r.z = MONT_ONE;
    return;
  }
  Fp z1z1, u2, s2, t;
  fp_sqr(z1z1, p.z);
  fp_mul(u2, qx, z1z1);
  fp_mul(t, qy, p.z);
  fp_mul(s2, t, z1z1);
  if (fp_eq(p.x, u2)) {
    if (fp_eq(p.y, s2)) {
      g1_dbl(r, p);
      return;
    }
    r = G1_INF_;
    return;
  }
  Fp h, hh, i, j, rr, v, x3, y3, z3;
  fp_sub(h, u2, p.x);
  fp_sqr(hh, h);
  fp_dbl(i, hh);
  fp_dbl(i, i);
  fp_mul(j, h, i);
  fp_sub(rr, s2, p.y);
  fp_dbl(rr, rr);
  fp_mul(v, p.x, i);
  fp_sqr(x3, rr);
  fp_sub(x3, x3, j);
  fp_sub(x3, x3, v);
  fp_sub(x3, x3, v);
  fp_sub(t, v, x3);
  fp_mul(y3, rr, t);
  fp_mul(t, p.y, j);
  fp_dbl(t, t);
  fp_sub(y3, y3, t);
  fp_add(z3, p.z, h);
  fp_sqr(z3, z3);
  fp_sub(z3, z3, z1z1);
  fp_sub(z3, z3, hh);
  r.x = x3;
  r.y = y3;
  r.z = z3;
}

// LE-limb schoolbook multiply, out must hold na+nb limbs
static void limbs_mul(u64 *out, const u64 *a, int na, const u64 *b, int nb) {
  memset(out, 0, 8 * (size_t)(na + nb));
  for (int i = 0; i < na; i++) {
    u64 carry = 0;
    for (int j = 0; j < nb; j++) {
      u128 cur = (u128)a[i] * b[j] + out[i + j] + carry;
      out[i + j] = (u64)cur;
      carry = (u64)(cur >> 64);
    }
    out[i + nb] = carry;  // untouched by earlier rounds
  }
}

// GLV decomposition constants (filled in by Init)
static u64 MU384[3];      // floor(2^384 / r) — Barrett
static u64 Z2_LIMBS[2];   // z^2      (lambda + 1)
static u64 LAM_LIMBS[2];  // lambda = z^2 - 1 (phi eigenvalue on G1)

// reduce a 32-byte BE scalar mod r into 4 LE limbs (k < 2^256 < 4r)
static void scalar_mod_r(u64 k[4], const uint8_t be[32]) {
  for (int i = 0; i < 4; i++) {
    u64 l = 0;
    for (int j = 0; j < 8; j++) l = (l << 8) | be[(3 - i) * 8 + j];
    k[i] = l;
  }
  for (int rep = 0; rep < 3; rep++) {
    u64 t[4];
    memcpy(t, k, 32);
    if (!limbs_sub(t, R_LIMBS, 4)) memcpy(k, t, 32);  // k >= r: keep k-r
  }
}

// k (mod r) ->  s1*a1 + lambda * s2*a2  with |ai| < 2^131.
// Unconditionally SOUND: the split is re-verified against k mod r and falls
// back to the trivial (k, 0) decomposition on any Barrett corner case, so
// callers never depend on the rounding-error analysis.
static void glv_split_g1(int &s1, u64 a1[4], int &s2, u64 a2[4],
                         const u64 k[4]) {
  // c1 ~= k*z^2/r, c2 ~= k/r (both floor approximations, error <= 2)
  u64 kz2[6], t9[9], t7[7];
  limbs_mul(kz2, k, 4, Z2_LIMBS, 2);
  limbs_mul(t9, kz2, 6, MU384, 3);
  u64 c1[3] = {t9[6], t9[7], t9[8]};
  limbs_mul(t7, k, 4, MU384, 3);
  u64 c2 = t7[6];  // k/r < 4
  // k1 = k - c1*lambda - c2 (5-limb two's complement)
  u64 c1l[5], k1[5] = {k[0], k[1], k[2], k[3], 0};
  limbs_mul(c1l, c1, 3, LAM_LIMBS, 2);
  bool neg1 = limbs_sub(k1, c1l, 5);
  u64 c2w[5] = {c2, 0, 0, 0, 0};
  if (limbs_sub(k1, c2w, 5)) neg1 = true;
  if (neg1) {  // negate two's complement
    for (int i = 0; i < 5; i++) k1[i] = ~k1[i];
    u64 one[5] = {1, 0, 0, 0, 0};
    limbs_add(k1, one, 5);
  }
  // k2 = c1 - c2*z^2 (5-limb two's complement)
  u64 k2[5] = {c1[0], c1[1], c1[2], 0, 0}, c2z[5];
  u64 c2l[1] = {c2};
  limbs_mul(c2z, c2l, 1, Z2_LIMBS, 2);
  c2z[3] = c2z[4] = 0;
  bool neg2 = limbs_sub(k2, c2z, 5);
  if (neg2) {
    for (int i = 0; i < 5; i++) k2[i] = ~k2[i];
    u64 one[5] = {1, 0, 0, 0, 0};
    limbs_add(k2, one, 5);
  }
  s1 = neg1 ? -1 : 1;
  s2 = neg2 ? -1 : 1;
  memcpy(a1, k1, 32);
  memcpy(a2, k2, 32);
  // soundness re-check: s1*a1 + lambda*s2*a2 == k (mod r)?
  // rhs = a1*?; work mod r via repeated conditional subtraction after
  // reducing the 6-limb lambda*a2 product with the generic path.
  bool ok = k1[4] == 0 && k2[4] == 0 && (a1[3] >> 8) == 0 && (a2[3] >> 8) == 0;
  if (ok) {
    // r1 = a1 mod r, r2 = (lambda * a2) mod r  (product < 2^128 * 2^131)
    u64 la2[6];
    limbs_mul(la2, a2, 4, LAM_LIMBS, 2);
    // reduce la2 (6 limbs) mod r by Barrett with MU384: q = (la2*MU)>>384
    u64 q9[9];
    limbs_mul(q9, la2, 6, MU384, 3);
    u64 q[3] = {q9[6], q9[7], q9[8]};
    u64 qr[7];
    limbs_mul(qr, q, 3, R_LIMBS, 4);
    u64 la2w[7] = {la2[0], la2[1], la2[2], la2[3], la2[4], la2[5], 0};
    limbs_sub(la2w, qr, 7);
    for (int rep = 0; rep < 4; rep++) {
      u64 t[7];
      memcpy(t, la2w, 56);
      u64 rw[7] = {R_LIMBS[0], R_LIMBS[1], R_LIMBS[2], R_LIMBS[3], 0, 0, 0};
      if (!limbs_sub(t, rw, 7)) memcpy(la2w, t, 56);
    }
    // acc = s1*a1 + s2*la2w mod r, then compare against k
    u64 acc[5] = {0, 0, 0, 0, 0};
    u64 a1w[5] = {a1[0], a1[1], a1[2], a1[3], 0};
    u64 l2w[5] = {la2w[0], la2w[1], la2w[2], la2w[3], 0};
    u64 rw[5] = {R_LIMBS[0], R_LIMBS[1], R_LIMBS[2], R_LIMBS[3], 0};
    if (s1 > 0) limbs_add(acc, a1w, 5);
    else if (limbs_sub(acc, a1w, 5)) limbs_add(acc, rw, 5), limbs_add(acc, rw, 5);
    if (s2 > 0) limbs_add(acc, l2w, 5);
    else if (limbs_sub(acc, l2w, 5)) limbs_add(acc, rw, 5), limbs_add(acc, rw, 5);
    for (int rep = 0; rep < 4; rep++) {
      u64 t[5];
      memcpy(t, acc, 40);
      if (!limbs_sub(t, rw, 5)) memcpy(acc, t, 40);
    }
    ok = acc[4] == 0 && acc[0] == k[0] && acc[1] == k[1] && acc[2] == k[2] &&
         acc[3] == k[3];
  }
  if (!ok) {  // fall back to the trivial decomposition (always correct)
    s1 = 1;
    s2 = 1;
    memcpy(a1, k, 32);
    memset(a2, 0, 32);
  }
}

// width-4 NAF of a (LE limbs, destructive); digits odd in {+-1,+-3,+-5,+-7};
// returns digit count (<= 64*nlimbs + 1)
static int wnaf4(int8_t *digits, u64 *a, int nlimbs) {
  int len = 0;
  while (!limbs_is_zero(a, nlimbs)) {
    int d = 0;
    if (a[0] & 1) {
      d = (int)(a[0] & 15);
      if (d > 8) d -= 16;
      if (d > 0) {
        u64 borrow = (u64)d;
        for (int i = 0; i < nlimbs && borrow; i++) {
          u64 prev = a[i];
          a[i] -= borrow;
          borrow = a[i] > prev ? 1 : 0;
        }
      } else {
        u64 carry = (u64)(-d);
        for (int i = 0; i < nlimbs && carry; i++) {
          u64 prev = a[i];
          a[i] += carry;
          carry = a[i] < prev ? 1 : 0;
        }
      }
    }
    digits[len++] = (int8_t)d;
    limbs_rshift1(a, nlimbs);
  }
  return len;
}

// Straus/GLV MSM over G1 for SMALL n (the Lagrange-combine shape: t+1
// points). Each 255-bit scalar splits into two ~129-bit GLV halves (the
// phi half's affine table is the base table with x scaled by beta — phi is
// a homomorphism, so phi(mP) = m*phi(P)); both halves run width-4 NAF over
// a batch-normalized affine table with mixed additions. ~4x over the
// bucket method at n=22 (which cannot amortize buckets at this size).
static void g1_msm_straus(G1 &out, const G1 *points, const uint8_t *scalars,
                          size_t n) {
  const int TBL = 4;  // odd multiples 1,3,5,7
  struct Half {
    int tbl;      // index into the affine tables (j*TBL)
    bool phi;     // use the beta-scaled x
    int8_t digits[260];  // split halves are ~132; the sound fallback
    int len;             // decomposition runs the full 256-bit scalar
  };
  std::vector<Fp> tx(n * TBL), ty(n * TBL), phix(n * TBL);
  std::vector<uint8_t> tvalid(n * TBL);
  std::vector<Half> halves(2 * n);
  // Jacobian odd-multiple tables
  std::vector<G1> jt(n * TBL);
  for (size_t j = 0; j < n; j++) {
    const G1 &p = points[j];
    jt[j * TBL] = p;
    G1 twop;
    g1_dbl(twop, p);
    g1_add(jt[j * TBL + 1], twop, p);
    g1_add(jt[j * TBL + 2], jt[j * TBL + 1], twop);
    g1_add(jt[j * TBL + 3], jt[j * TBL + 2], twop);
  }
  g1_batch_to_affine(jt.data(), tx.data(), ty.data(), tvalid.data(),
                     n * TBL);
  Fp beta;
  fp_from_bytes_be(beta, BETA_G1_BE);
  for (size_t i = 0; i < n * TBL; i++)
    if (tvalid[i]) fp_mul(phix[i], tx[i], beta);
  // scalar split + wNAF
  int maxlen = 0;
  for (size_t j = 0; j < n; j++) {
    u64 k[4];
    scalar_mod_r(k, scalars + j * 32);
    int s1, s2;
    u64 a1[4], a2[4];
    glv_split_g1(s1, a1, s2, a2, k);
    Half &h1 = halves[2 * j], &h2 = halves[2 * j + 1];
    h1.tbl = (int)(j * TBL);
    h1.phi = false;
    h1.len = wnaf4(h1.digits, a1, 4);
    if (s1 < 0)
      for (int i = 0; i < h1.len; i++) h1.digits[i] = -h1.digits[i];
    h2.tbl = (int)(j * TBL);
    h2.phi = true;
    h2.len = wnaf4(h2.digits, a2, 4);
    if (s2 < 0)
      for (int i = 0; i < h2.len; i++) h2.digits[i] = -h2.digits[i];
    if (h1.len > maxlen) maxlen = h1.len;
    if (h2.len > maxlen) maxlen = h2.len;
  }
  G1 acc = G1_INF_;
  for (int pos = maxlen - 1; pos >= 0; pos--) {
    g1_dbl(acc, acc);
    for (size_t h = 0; h < 2 * n; h++) {
      const Half &hf = halves[h];
      if (pos >= hf.len) continue;
      int d = hf.digits[pos];
      if (!d) continue;
      int idx = hf.tbl + (d > 0 ? d - 1 : -d - 1) / 2;
      if (!tvalid[idx]) continue;  // infinity entry
      const Fp &qx = hf.phi ? phix[idx] : tx[idx];
      if (d > 0) {
        g1_madd(acc, acc, qx, ty[idx]);
      } else {
        Fp ny;
        fp_neg(ny, ty[idx]);
        g1_madd(acc, acc, qx, ny);
      }
    }
  }
  out = acc;
}

// --- wire format (matches the Python oracle: BE uncompressed, zero == inf) --

static bool g1_from_bytes(G1 &p, const uint8_t *in) {  // 96 bytes
  bool allz = true;
  for (int i = 0; i < 96; i++)
    if (in[i]) {
      allz = false;
      break;
    }
  if (allz) {
    p = G1_INF_;
    return true;
  }
  fp_from_bytes_be(p.x, in);
  fp_from_bytes_be(p.y, in + 48);
  p.z = MONT_ONE;
  // on-curve: y^2 == x^3 + 4
  Fp y2, x3, four;
  fp_sqr(y2, p.y);
  fp_sqr(x3, p.x);
  fp_mul(x3, x3, p.x);
  fp_set_u64(four, 4);
  fp_add(x3, x3, four);
  return fp_eq(y2, x3);
}

static void g1_to_bytes(uint8_t *out, const G1 &p) {
  if (g1_is_inf(p)) {
    memset(out, 0, 96);
    return;
  }
  Fp ax, ay;
  g1_to_affine(ax, ay, p);
  fp_to_bytes_be(out, ax);
  fp_to_bytes_be(out + 48, ay);
}

static bool g2_from_bytes(G2 &p, const uint8_t *in) {  // 192 bytes
  bool allz = true;
  for (int i = 0; i < 192; i++)
    if (in[i]) {
      allz = false;
      break;
    }
  if (allz) {
    p = G2_INF_;
    return true;
  }
  fp_from_bytes_be(p.x.c0, in);
  fp_from_bytes_be(p.x.c1, in + 48);
  fp_from_bytes_be(p.y.c0, in + 96);
  fp_from_bytes_be(p.y.c1, in + 144);
  p.z = FP2_ONE_;
  Fp2 y2, x3, b2;
  fp2_sqr(y2, p.y);
  fp2_sqr(x3, p.x);
  fp2_mul(x3, x3, p.x);
  Fp four;
  fp_set_u64(four, 4);
  b2.c0 = four;
  b2.c1 = four;  // 4*(1+u)
  fp2_add(x3, x3, b2);
  return fp2_eq(y2, x3);
}

static void g2_to_bytes(uint8_t *out, const G2 &p) {
  if (g2_is_inf(p)) {
    memset(out, 0, 192);
    return;
  }
  Fp2 ax, ay;
  g2_to_affine(ax, ay, p);
  fp_to_bytes_be(out, ax.c0);
  fp_to_bytes_be(out + 48, ax.c1);
  fp_to_bytes_be(out + 96, ay.c0);
  fp_to_bytes_be(out + 144, ay.c1);
}

static const uint8_t R_BYTES_BE[32] = {
    0x73, 0xed, 0xa7, 0x53, 0x29, 0x9d, 0x7d, 0x48, 0x33, 0x39, 0xd8,
    0x08, 0x09, 0xa1, 0xd8, 0x05, 0x53, 0xbd, 0xa4, 0x02, 0xff, 0xfe,
    0x5b, 0xfe, 0xff, 0xff, 0xff, 0xff, 0x00, 0x00, 0x00, 0x01};

// projective equality: X1*Z2^2 == X2*Z1^2 and Y1*Z2^3 == Y2*Z1^3
static bool g1_eq_proj(const G1 &p, const G1 &q) {
  bool pi = g1_is_inf(p), qi = g1_is_inf(q);
  if (pi || qi) return pi == qi;
  Fp z1z1, z2z2, a, b;
  fp_sqr(z1z1, p.z);
  fp_sqr(z2z2, q.z);
  fp_mul(a, p.x, z2z2);
  fp_mul(b, q.x, z1z1);
  if (!fp_eq(a, b)) return false;
  Fp z1c, z2c;
  fp_mul(z1c, z1z1, p.z);
  fp_mul(z2c, z2z2, q.z);
  fp_mul(a, p.y, z2c);
  fp_mul(b, q.y, z1c);
  return fp_eq(a, b);
}

static bool g1_in_subgroup(const G1 &p) {
  // Certified fast membership test: P is in the prime-order subgroup iff
  // phi(P) == [z^2 - 1]P. Soundness: phi - [lambda] is an endomorphism
  // whose kernel intersects every prime-power torsion component of the
  // cofactor trivially — machine-checked over h1 = 3*11^2*10177^2*
  // 859267^2*52437899^2 by tests/test_subgroup_fast.py, which also
  // differentially pins this routine against the full-order [r]P check.
  // Cost: two 64-bit ladders (~130 dbl + 12 add) vs [r]P's 255 dbl +
  // ~127 add — ~2.4x faster, on the wire-deserialization hot path.
  if (g1_is_inf(p)) return true;
  // no lazy caching: decoding beta is one fp_mul, negligible next to the
  // ~130 point doublings below, and a guarded static would race when two
  // GIL-released ctypes calls deserialize concurrently
  Fp beta;
  fp_from_bytes_be(beta, BETA_G1_BE);
  G1 t, t2, pneg, lam, ph;
  g1_mul_scalar(t, p, Z_ABS_BE, 8);   // [|z|]P
  g1_mul_scalar(t2, t, Z_ABS_BE, 8);  // [z^2]P (signs cancel)
  g1_neg(pneg, p);
  g1_add(lam, t2, pneg);  // [z^2 - 1]P
  ph = p;                 // phi: Jacobian (beta*X, Y, Z)
  fp_mul(ph.x, p.x, beta);
  return g1_eq_proj(ph, lam);
}
static bool g2_eq_proj(const G2 &p, const G2 &q) {
  bool pi = g2_is_inf(p), qi = g2_is_inf(q);
  if (pi || qi) return pi == qi;
  Fp2 z1z1, z2z2, a, b;
  fp2_sqr(z1z1, p.z);
  fp2_sqr(z2z2, q.z);
  fp2_mul(a, p.x, z2z2);
  fp2_mul(b, q.x, z1z1);
  if (!fp2_eq(a, b)) return false;
  Fp2 z1c, z2c;
  fp2_mul(z1c, z1z1, p.z);
  fp2_mul(z2c, z2z2, q.z);
  fp2_mul(a, p.y, z2c);
  fp2_mul(b, q.y, z1c);
  return fp2_eq(a, b);
}

// untwist-Frobenius-twist constants: A = 1/xi^((p-1)/3),
// B = 1/xi^((p-1)/2) with xi = 1 + i (derived numerically and pinned
// structurally by tests/test_subgroup_fast_g2.py)
static const uint8_t PSI_AX_C1[48] = {
    0x1a, 0x01, 0x11, 0xea, 0x39, 0x7f, 0xe6, 0x99, 0xec, 0x02, 0x40, 0x86,
    0x63, 0xd4, 0xde, 0x85, 0xaa, 0x0d, 0x85, 0x7d, 0x89, 0x75, 0x9a, 0xd4,
    0x89, 0x7d, 0x29, 0x65, 0x0f, 0xb8, 0x5f, 0x9b, 0x40, 0x94, 0x27, 0xeb,
    0x4f, 0x49, 0xff, 0xfd, 0x8b, 0xfd, 0x00, 0x00, 0x00, 0x00, 0xaa, 0xad,
};
static const uint8_t PSI_BY_C0[48] = {
    0x13, 0x52, 0x03, 0xe6, 0x01, 0x80, 0xa6, 0x8e, 0xe2, 0xe9, 0xc4, 0x48,
    0xd7, 0x7a, 0x2c, 0xd9, 0x1c, 0x3d, 0xed, 0xd9, 0x30, 0xb1, 0xcf, 0x60,
    0xef, 0x39, 0x64, 0x89, 0xf6, 0x1e, 0xb4, 0x5e, 0x30, 0x44, 0x66, 0xcf,
    0x3e, 0x67, 0xfa, 0x0a, 0xf1, 0xee, 0x7b, 0x04, 0x12, 0x1b, 0xde, 0xa2,
};
static const uint8_t PSI_BY_C1[48] = {
    0x06, 0xaf, 0x0e, 0x04, 0x37, 0xff, 0x40, 0x0b, 0x68, 0x31, 0xe3, 0x6d,
    0x6b, 0xd1, 0x7f, 0xfe, 0x48, 0x39, 0x5d, 0xab, 0xc2, 0xd3, 0x43, 0x5e,
    0x77, 0xf7, 0x6e, 0x17, 0x00, 0x92, 0x41, 0xc5, 0xee, 0x67, 0x99, 0x2f,
    0x72, 0xec, 0x05, 0xf4, 0xc8, 0x10, 0x84, 0xfb, 0xed, 0xe3, 0xcc, 0x09,
};

static bool g2_in_subgroup(const G2 &p) {
  // Certified fast membership test: Q in G2 iff psi(Q) == [z]Q, psi the
  // untwist-Frobenius-twist endomorphism psi(x, y) =
  // (A * conj(x), B * conj(y)). Soundness (deterministic, machine-checked
  // by tests/test_subgroup_fast_g2.py): psi satisfies
  // psi^2 - [t]psi + [p] = 0, so a torsion kernel element of order m | h2
  // would force m | z^2 - t*z + p == p - z — and gcd(p - z, h2) == 1.
  // On Jacobian coords conj is a field automorphism: psi(X, Y, Z) =
  // (A*conj(X), B*conj(Y), conj(Z)). Cost: one 64-bit ladder (~64 G2
  // doublings) vs [r]Q's 255 — ~3.5x faster.
  if (g2_is_inf(p)) return true;
  Fp2 ax, by;
  ax.c0 = FP_ZERO;
  fp_from_bytes_be(ax.c1, PSI_AX_C1);
  fp_from_bytes_be(by.c0, PSI_BY_C0);
  fp_from_bytes_be(by.c1, PSI_BY_C1);
  G2 ph, conj;
  conj = p;
  fp_neg(conj.x.c1, p.x.c1);
  fp_neg(conj.y.c1, p.y.c1);
  fp_neg(conj.z.c1, p.z.c1);
  ph = conj;
  fp2_mul(ph.x, conj.x, ax);
  fp2_mul(ph.y, conj.y, by);
  // [z]Q = -[|z|]Q (z is negative)
  G2 t, lam;
  g2_mul_scalar(t, p, Z_ABS_BE, 8);
  g2_neg(lam, t);
  return g2_eq_proj(ph, lam);
}

// ===========================================================================
// Pairing — same structure as the oracle: affine Miller loop on E(Fp12).
// ===========================================================================

static const u64 ATE_LOOP = 0xd201000000010000ull;  // |X_PARAM|

// --- fast Miller loop: affine coordinates ON THE TWIST (Fp2 slopes, one
// cheap Fp2 inversion per step) with sparse line multiplication. Each line
// is scaled by v*w, which is killed by the final exponentiation
// ((vw)^2 = xi in Fp2, so (vw)^(p^6-1) has order <= 2 and dies under
// (p^2+1)*hard). Replaces the reference-shaped affine-E(Fp12) loop whose
// per-step Fp12 inversions made a pairing ~15 ms.

// f *= (A + B*v) + (C*v)*w   [slots c0.c0 = A, c0.c1 = B, c1.c1 = C]
static void fp12_mul_sparse(Fp12 &f, const Fp2 &A, const Fp2 &B,
                            const Fp2 &C) {
  const Fp6 &a = f.c0, &b = f.c1;
  Fp6 r0, r1;
  Fp2 t;
  // a * (A + Bv): (a0*A + xi*a2*B, a1*A + a0*B, a2*A + a1*B)
  Fp2 a0A, a1A, a2A, a0B, a1B, a2B;
  fp2_mul(a0A, a.c0, A);
  fp2_mul(a1A, a.c1, A);
  fp2_mul(a2A, a.c2, A);
  fp2_mul(a0B, a.c0, B);
  fp2_mul(a1B, a.c1, B);
  fp2_mul(a2B, a.c2, B);
  fp2_mul_xi(t, a2B);
  fp2_add(r0.c0, a0A, t);
  fp2_add(r0.c1, a1A, a0B);
  fp2_add(r0.c2, a2A, a1B);
  // + v * (b * Cv) = b*C*v^2 = (xi*b1C, xi*b2C, b0C)
  Fp2 b0C, b1C, b2C;
  fp2_mul(b0C, b.c0, C);
  fp2_mul(b1C, b.c1, C);
  fp2_mul(b2C, b.c2, C);
  fp2_mul_xi(t, b1C);
  fp2_add(r0.c0, r0.c0, t);
  fp2_mul_xi(t, b2C);
  fp2_add(r0.c1, r0.c1, t);
  fp2_add(r0.c2, r0.c2, b0C);
  // c1' = a*(Cv) + b*(A + Bv)
  // a*Cv = (xi*a2C, a0C, a1C)
  Fp2 a0C, a1C, a2C;
  fp2_mul(a0C, a.c0, C);
  fp2_mul(a1C, a.c1, C);
  fp2_mul(a2C, a.c2, C);
  fp2_mul_xi(t, a2C);
  r1.c0 = t;
  r1.c1 = a0C;
  r1.c2 = a1C;
  Fp2 b0A, b1A, b2A, b0B, b1B, b2B;
  fp2_mul(b0A, b.c0, A);
  fp2_mul(b1A, b.c1, A);
  fp2_mul(b2A, b.c2, A);
  fp2_mul(b0B, b.c0, B);
  fp2_mul(b1B, b.c1, B);
  fp2_mul(b2B, b.c2, B);
  fp2_mul_xi(t, b2B);
  fp2_add(r1.c0, r1.c0, b0A);
  fp2_add(r1.c0, r1.c0, t);
  fp2_add(r1.c1, r1.c1, b1A);
  fp2_add(r1.c1, r1.c1, b0B);
  fp2_add(r1.c2, r1.c2, b2A);
  fp2_add(r1.c2, r1.c2, b1B);
  f.c0 = r0;
  f.c1 = r1;
}

struct MLState {
  Fp px, py;
  Fp2 xQ, yQ, X, Y, Z;
  bool inf;
};

static void ml_init(MLState &s, const G1 &p, const G2 &q) {
  s.inf = g1_is_inf(p) || g2_is_inf(q);
  if (s.inf) return;
  g1_to_affine(s.px, s.py, p);
  g2_to_affine(s.xQ, s.yQ, q);
  s.X = s.xQ;
  s.Y = s.yQ;
  s.Z = FP2_ONE_;
}

// Batch variant for the era-sized grand products: to-affine needs a field
// inversion per point (~16us egcd each on this box — 4ms of pure inversion
// at 128 pairs); Montgomery's trick folds ALL of them (G1 z's and the Fp
// norms of G2 z's alike) into ONE egcd + 3 muls per element.
static void ml_init_batch(MLState *states, const G1 *ps, const G2 *qs,
                          size_t n) {
  std::vector<Fp> invs(2 * n);
  for (size_t i = 0; i < n; i++) {
    states[i].inf = g1_is_inf(ps[i]) || g2_is_inf(qs[i]);
    if (states[i].inf) {
      invs[2 * i] = FP_ZERO;
      invs[2 * i + 1] = FP_ZERO;
      continue;
    }
    invs[2 * i] = ps[i].z;
    // norm(z2) = c0^2 + c1^2; its inverse gives fp2 inverse via conjugate
    Fp n0, n1;
    fp_sqr(n0, qs[i].z.c0);
    fp_sqr(n1, qs[i].z.c1);
    fp_add(invs[2 * i + 1], n0, n1);
  }
  fp_batch_inv(invs.data(), 2 * n);
  for (size_t i = 0; i < n; i++) {
    MLState &s = states[i];
    if (s.inf) continue;
    Fp zi2;
    fp_sqr(zi2, invs[2 * i]);
    fp_mul(s.px, ps[i].x, zi2);
    fp_mul(zi2, zi2, invs[2 * i]);
    fp_mul(s.py, ps[i].y, zi2);
    Fp2 z2i;  // (conj z) * norm^{-1}
    fp_mul(z2i.c0, qs[i].z.c0, invs[2 * i + 1]);
    fp_mul(z2i.c1, qs[i].z.c1, invs[2 * i + 1]);
    fp_neg(z2i.c1, z2i.c1);
    Fp2 zi2q;
    fp2_sqr(zi2q, z2i);
    fp2_mul(s.xQ, qs[i].x, zi2q);
    fp2_mul(zi2q, zi2q, z2i);
    fp2_mul(s.yQ, qs[i].y, zi2q);
    s.X = s.xQ;
    s.Y = s.yQ;
    s.Z = FP2_ONE_;
  }
}

// one doubling step of the shared-squaring Miller loop: accumulate this
// pair's line into f (caller has already squared f ONCE for all pairs)
static void ml_dbl_step(MLState &s, Fp12 &f) {
  if (s.inf) return;
  const Fp &px = s.px, &py = s.py;
  Fp2 &X = s.X, &Y = s.Y, &Z = s.Z;
  Fp2 A, B, C, t, t2;
  // --- doubling step: line scaled by 2YZ^2 ---
  Fp2 XX, YY, X3c, YZ, YYZ;
  fp2_sqr(XX, X);
  fp2_sqr(YY, Y);
  fp2_mul(X3c, X, XX);  // X^3
  fp2_mul(YZ, Y, Z);
  fp2_mul(YYZ, YY, Z);
  // A = 3X^3 - 2Y^2Z
  fp2_add(t, X3c, X3c);
  fp2_add(A, t, X3c);
  fp2_add(t, YYZ, YYZ);
  fp2_sub(A, A, t);
  // B = -3*X^2*Z*px
  Fp2 XXZ;
  fp2_mul(XXZ, XX, Z);
  fp2_add(t, XXZ, XXZ);
  fp2_add(t, t, XXZ);
  fp_mul(B.c0, t.c0, px);
  fp_mul(B.c1, t.c1, px);
  fp2_neg(B, B);
  // C = 2*Y*Z^2*py
  Fp2 YZZ;
  fp2_mul(YZZ, YZ, Z);
  fp2_add(t, YZZ, YZZ);
  fp_mul(C.c0, t.c0, py);
  fp_mul(C.c1, t.c1, py);
  fp12_mul_sparse(f, A, B, C);
  // T = 2T:  X3 = 2XYZ(9X^3 - 8Y^2Z); Y3 = 36X^3*YYZ - 27X^6 - 8(YYZ)^2;
  //          Z3 = 8(YZ)^3
  Fp2 XYZ, nine_x3, eight_yyz, X3n, Y3n, Z3n, x3sq, yyzsq, yz2;
  fp2_mul(XYZ, X, YZ);
  fp2_add(t, X3c, X3c);          // 2X^3
  fp2_add(t2, t, t);             // 4X^3
  fp2_add(t2, t2, t2);           // 8X^3
  fp2_add(nine_x3, t2, X3c);     // 9X^3
  fp2_add(t, YYZ, YYZ);          // 2YYZ
  fp2_add(t2, t, t);             // 4YYZ
  fp2_add(eight_yyz, t2, t2);    // 8YYZ
  fp2_sub(t, nine_x3, eight_yyz);
  fp2_mul(X3n, XYZ, t);
  fp2_add(X3n, X3n, X3n);
  fp2_sqr(x3sq, X3c);            // X^6
  fp2_sqr(yyzsq, YYZ);
  fp2_mul(t, X3c, YYZ);          // X^3*Y^2*Z
  Fp2 acc;
  fp2_add(acc, t, t);            // 2
  fp2_add(acc, acc, acc);        // 4
  fp2_add(acc, acc, acc);        // 8
  fp2_add(acc, acc, t);          // 9
  fp2_add(t2, acc, acc);         // 18
  fp2_add(Y3n, t2, t2);          // 36*X^3*YYZ
  {
    // 27*X^6 = 16 + 8 + 2 + 1
    Fp2 two, four, eight, sixteen;
    fp2_add(two, x3sq, x3sq);
    fp2_add(four, two, two);
    fp2_add(eight, four, four);
    fp2_add(sixteen, eight, eight);
    fp2_add(t, sixteen, eight);
    fp2_add(t, t, two);
    fp2_add(t, t, x3sq);
  }
  fp2_sub(Y3n, Y3n, t);
  fp2_add(t, yyzsq, yyzsq);
  fp2_add(t2, t, t);
  fp2_add(t, t2, t2);  // 8 (YYZ)^2
  fp2_sub(Y3n, Y3n, t);
  fp2_sqr(yz2, YZ);
  fp2_mul(Z3n, yz2, YZ);  // (YZ)^3
  fp2_add(Z3n, Z3n, Z3n);
  fp2_add(t, Z3n, Z3n);
  fp2_add(Z3n, t, t);  // 8 (YZ)^3
  X = X3n;
  Y = Y3n;
  Z = Z3n;
}

static void ml_add_step(MLState &s, Fp12 &f) {
  if (s.inf) return;
  const Fp &px = s.px, &py = s.py;
  const Fp2 &xQ = s.xQ, &yQ = s.yQ;
  Fp2 &X = s.X, &Y = s.Y, &Z = s.Z;
  Fp2 A, B, C, t, t2, X3n, Y3n;
  // --- mixed addition step (Q affine): line through Q, scaled by D ---
  Fp2 N, D, NN, DD, DDZ, xqz, yqz;
  fp2_mul(xqz, xQ, Z);
  fp2_mul(yqz, yQ, Z);
  fp2_sub(N, Y, yqz);
  fp2_sub(D, X, xqz);
  // A = N*xQ - yQ*D ; B = -N*px ; C = D*py
  fp2_mul(A, N, xQ);
  fp2_mul(t, yQ, D);
  fp2_sub(A, A, t);
  fp_mul(B.c0, N.c0, px);
  fp_mul(B.c1, N.c1, px);
  fp2_neg(B, B);
  fp_mul(C.c0, D.c0, py);
  fp_mul(C.c1, D.c1, py);
  fp12_mul_sparse(f, A, B, C);
  // T = T + Q: t = N^2*Z - D^2*(X + xQ*Z);
  //            X3 = D*t; Z3 = D^3*Z; Y3 = N*(xQ*D^2*Z - t) - yQ*D^3*Z
  fp2_sqr(NN, N);
  fp2_sqr(DD, D);
  fp2_mul(DDZ, DD, Z);
  Fp2 u_;
  fp2_mul(u_, NN, Z);
  fp2_mul(t2, DD, X);
  fp2_sub(u_, u_, t2);
  fp2_mul(t2, xQ, DDZ);
  fp2_sub(u_, u_, t2);  // u_ = t
  fp2_mul(X3n, D, u_);
  Fp2 D3Z;
  fp2_mul(D3Z, DD, D);
  fp2_mul(D3Z, D3Z, Z);
  fp2_mul(t, xQ, DDZ);
  fp2_sub(t, t, u_);
  fp2_mul(Y3n, N, t);
  fp2_mul(t, yQ, D3Z);
  fp2_sub(Y3n, Y3n, t);
  X = X3n;
  Y = Y3n;
  Z = D3Z;
}

static void miller_loop(Fp12 &f, const G1 &p, const G2 &q) {
  // Homogeneous-projective twist coordinates: ZERO field inversions in the
  // loop (the affine variant spent ~10us/step in fp_inv). Lines are scaled
  // by per-step Fp2 factors, which the final exponentiation kills.
  MLState s;
  ml_init(s, p, q);
  f = FP12_ONE_;
  if (s.inf) return;
  int top = 63;
  while (!((ATE_LOOP >> top) & 1)) top--;
  for (int i = top - 1; i >= 0; i--) {
    fp12_sqr_fast(f, f);
    ml_dbl_step(s, f);
    if ((ATE_LOOP >> i) & 1) ml_add_step(s, f);
  }
  Fp12 fc;
  fp12_conj(fc, f);  // X_PARAM < 0
  f = fc;
}

// Shared-squaring multi-Miller loop: ONE f^2 per iteration for the whole
// product (the per-pair Miller loops each spent ~30% of their time in
// fp12_sqr_fast; a 2S-pair era product shares all of them). Equal to
// Prod_i miller_loop(p_i, q_i) because fp12_conj is a ring homomorphism.
static void miller_loop_multi(Fp12 &f, MLState *states, size_t n) {
  f = FP12_ONE_;
  int top = 63;
  while (!((ATE_LOOP >> top) & 1)) top--;
  for (int i = top - 1; i >= 0; i--) {
    fp12_sqr_fast(f, f);
    bool add = (ATE_LOOP >> i) & 1;
    for (size_t j = 0; j < n; j++) {
      ml_dbl_step(states[j], f);
      if (add) ml_add_step(states[j], f);
    }
  }
  Fp12 fc;
  fp12_conj(fc, f);  // X_PARAM < 0
  f = fc;
}

// --- cyclotomic arithmetic for the final exponentiation -------------------

// Fp4 = Fp2[sigma]/(sigma^2 - xi) squaring: (a + b sigma)^2
static inline void fp4_sqr(Fp2 &ra, Fp2 &rb, const Fp2 &a, const Fp2 &b) {
  Fp2 t0, t1, t2;
  fp2_sqr(t0, a);
  fp2_sqr(t1, b);
  fp2_add(t2, a, b);
  fp2_sqr(t2, t2);
  fp2_mul_xi(ra, t1);
  fp2_add(ra, ra, t0);  // a^2 + xi b^2
  fp2_sub(rb, t2, t0);
  fp2_sub(rb, rb, t1);  // 2ab
}

static bool CYC_OK = false;  // init self-check gates the fast path

// Granger-Scott squaring for unitary elements. Fp4 pairs in this tower:
// A = (c0.c0, c1.c1), B = (c1.c0, c0.c2), C = (c0.c1, c1.c2).
//   A' = 3*A^2 - 2*conj(A); B' = 3*sigma*C^2 + 2*conj(B);
//   C' = 3*B^2 - 2*conj(C);   sigma*(x + y*sigma) = xi*y + x*sigma.
static void fp12_sqr_cyc(Fp12 &z, const Fp12 &a) {
  if (!CYC_OK) {
    fp12_sqr_fast(z, a);
    return;
  }
  Fp2 sa_a, sa_b, sb_a, sb_b, sc_a, sc_b, t;
  fp4_sqr(sa_a, sa_b, a.c0.c0, a.c1.c1);
  fp4_sqr(sb_a, sb_b, a.c1.c0, a.c0.c2);
  fp4_sqr(sc_a, sc_b, a.c0.c1, a.c1.c2);
  // A' -> (c0.c0, c1.c1): re = 3*sa_a - 2*re; im = 3*sa_b + 2*im
  Fp2 r;
  fp2_sub(r, sa_a, a.c0.c0);
  fp2_add(r, r, r);
  fp2_add(z.c0.c0, r, sa_a);
  fp2_add(r, sa_b, a.c1.c1);
  fp2_add(r, r, r);
  fp2_add(z.c1.c1, r, sa_b);
  // B' -> (c1.c0, c0.c2): sigma*C^2 = (xi*sc_b, sc_a)
  fp2_mul_xi(t, sc_b);
  fp2_add(r, t, a.c1.c0);
  fp2_add(r, r, r);
  fp2_add(z.c1.c0, r, t);
  fp2_sub(r, sc_a, a.c0.c2);
  fp2_add(r, r, r);
  fp2_add(z.c0.c2, r, sc_a);
  // C' -> (c0.c1, c1.c2): re = 3*sb_a - 2*re; im = 3*sb_b + 2*im
  fp2_sub(r, sb_a, a.c0.c1);
  fp2_add(r, r, r);
  fp2_add(z.c0.c1, r, sb_a);
  fp2_add(r, sb_b, a.c1.c2);
  fp2_add(r, r, r);
  fp2_add(z.c1.c2, r, sb_b);
}

// g^|x| for cyclotomic g (|x| = ATE_LOOP), then conjugate for g^x (x < 0)
static void cyc_exp_x(Fp12 &out, const Fp12 &g) {
  Fp12 acc = g;
  for (int i = 62; i >= 0; i--) {
    fp12_sqr_cyc(acc, acc);
    if ((ATE_LOOP >> i) & 1) fp12_mul(acc, acc, g);
  }
  fp12_conj(out, acc);  // x negative
}

static void final_exponentiation(Fp12 &out, const Fp12 &f) {
  // easy part
  Fp12 t, finv, g;
  fp12_conj(t, f);
  fp12_inv(finv, f);
  fp12_mul(t, t, finv);  // f^(p^6-1)
  fp12_frobenius(g, t);
  fp12_frobenius(g, g);
  fp12_mul(t, g, t);  // ^(p^2+1) — now in the cyclotomic subgroup
  // hard part: exponent 3h, h = (p^4-p^2+1)/r, via the
  // Hayashida-Hayasaka-Teruya lambda chain (verified symbolically:
  // lambda0 + lambda1*p + lambda2*p^2 + lambda3*p^3 == 3h with
  // l3=(x-1)^2, l2=x*l3, l1=x^4-2x^3+2x-1, l0=x^5-2x^4+2x^2-x+3).
  // The framework's GT convention is this CUBED ate pairing — matching
  // crypto/bls12381.py final_exponentiation; gcd(3, r) = 1 so every
  // pairing equality check is unaffected.
  Fp12 t0, t1, t3, t4, t5, t6, t6b, tmp, accA, accB, accC, accD;
  cyc_exp_x(t3, t);  // t^x
  fp12_sqr_cyc(t1, t);
  fp12_conj(t1, t1);     // t^-2
  fp12_mul(t5, t3, t1);  // t^(x-2)
  cyc_exp_x(t1, t5);     // t^(x^2-2x)
  cyc_exp_x(t0, t1);     // t^(x^3-2x^2)
  cyc_exp_x(t6, t0);     // t^(x^4-2x^3)
  fp12_sqr_cyc(t4, t3);  // t^(2x)
  fp12_mul(t6, t6, t4);  // t^(x^4-2x^3+2x)
  fp12_conj(tmp, t);
  fp12_mul(t6b, t6, tmp);  // ^lambda1
  cyc_exp_x(t4, t6);       // t^(x^5-2x^4+2x^2)
  fp12_conj(tmp, t5);
  fp12_mul(accA, t4, tmp);
  fp12_mul(accA, accA, t);  // ^lambda0
  fp12_mul(accC, t0, t3);   // ^lambda2
  fp12_mul(accD, t1, t);    // ^lambda3
  fp12_frobenius(accB, t6b);
  fp12_frobenius(accC, accC);
  fp12_frobenius(accC, accC);
  fp12_frobenius(accD, accD);
  fp12_frobenius(accD, accD);
  fp12_frobenius(accD, accD);
  fp12_mul(out, accA, accB);
  fp12_mul(out, out, accC);
  fp12_mul(out, out, accD);
}

// init-time self-check for the Granger-Scott squaring sign conventions:
// build a cyclotomic element, compare fp12_sqr_cyc against the always-
// correct fp12_sqr_fast; on mismatch the slow-but-correct path stays.
// Called from the _init constructor AFTER field constants exist.
static void cyc_selfcheck() {
  Fp12 e = FP12_ONE_;
  e.c0.c1.c0 = MONT_ONE;
  e.c1.c0.c1 = MONT_ONE;
  e.c1.c2.c0 = MONT_ONE;
  Fp12 c, inv, u, fr;
  fp12_conj(c, e);
  fp12_inv(inv, e);
  fp12_mul(u, c, inv);
  fp12_frobenius(fr, u);
  fp12_frobenius(fr, fr);
  fp12_mul(u, fr, u);  // cyclotomic
  Fp12 a, b;
  CYC_OK = true;
  fp12_sqr_cyc(a, u);
  fp12_sqr_fast(b, u);
  CYC_OK = fp12_eq(a, b);
}

// ===========================================================================
// Keccak / SHAKE-256 (for the XOF-based hash-to-curve, oracle-compatible)
// ===========================================================================

static const u64 KECCAK_RC[24] = {
    0x0000000000000001ull, 0x0000000000008082ull, 0x800000000000808aull,
    0x8000000080008000ull, 0x000000000000808bull, 0x0000000080000001ull,
    0x8000000080008081ull, 0x8000000000008009ull, 0x000000000000008aull,
    0x0000000000000088ull, 0x0000000080008009ull, 0x000000008000000aull,
    0x000000008000808bull, 0x800000000000008bull, 0x8000000000008089ull,
    0x8000000000008003ull, 0x8000000000008002ull, 0x8000000000000080ull,
    0x000000000000800aull, 0x800000008000000aull, 0x8000000080008081ull,
    0x8000000000008080ull, 0x0000000080000001ull, 0x8000000080008008ull};

static const int KECCAK_ROT[5][5] = {{0, 36, 3, 41, 18},
                                     {1, 44, 10, 45, 2},
                                     {62, 6, 43, 15, 61},
                                     {28, 55, 25, 21, 56},
                                     {27, 20, 39, 8, 14}};

static inline u64 rol64(u64 v, int s) {
  return s == 0 ? v : (v << s) | (v >> (64 - s));
}

static void keccak_f(u64 a[5][5]) {
  for (int rnd = 0; rnd < 24; rnd++) {
    u64 c[5], d[5];
    for (int x = 0; x < 5; x++)
      c[x] = a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4];
    for (int x = 0; x < 5; x++)
      d[x] = c[(x + 4) % 5] ^ rol64(c[(x + 1) % 5], 1);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++) a[x][y] ^= d[x];
    u64 b[5][5];
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        b[y][(2 * x + 3 * y) % 5] = rol64(a[x][y], KECCAK_ROT[x][y]);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y]);
    a[0][0] ^= KECCAK_RC[rnd];
  }
}

// sponge with given rate and domain-pad byte
static void keccak_sponge(uint8_t *out, size_t outlen, const uint8_t *in,
                          size_t inlen, size_t rate, uint8_t pad) {
  u64 st[5][5];
  memset(st, 0, sizeof(st));
  std::vector<uint8_t> buf(in, in + inlen);
  buf.push_back(pad);
  while (buf.size() % rate) buf.push_back(0);
  buf[buf.size() - 1] |= 0x80;
  for (size_t off = 0; off < buf.size(); off += rate) {
    for (size_t i = 0; i < rate / 8; i++) {
      u64 lane = 0;
      for (int j = 7; j >= 0; j--) lane = (lane << 8) | buf[off + i * 8 + j];
      st[i % 5][i / 5] ^= lane;
    }
    keccak_f(st);
  }
  size_t produced = 0;
  while (produced < outlen) {
    for (size_t i = 0; i < rate / 8 && produced < outlen; i++) {
      u64 lane = st[i % 5][i / 5];
      for (int j = 0; j < 8 && produced < outlen; j++) {
        out[produced++] = (uint8_t)(lane >> (8 * j));
      }
    }
    if (produced < outlen) keccak_f(st);
  }
}

static void shake256(uint8_t *out, size_t outlen, const uint8_t *in,
                     size_t inlen) {
  keccak_sponge(out, outlen, in, inlen, 136, 0x1f);
}

extern "C" void lt_keccak256(const uint8_t *in, size_t inlen,
                             uint8_t out[32]) {
  keccak_sponge(out, 32, in, inlen, 136, 0x01);
}

// n keccak256 digests in one crossing: item i is data[offsets[i],
// offsets[i+1]) (offsets has n+1 entries), out is n*32 bytes. The trie
// commit hashes ~100k node encodings per 10k-tx block and per-call ctypes
// dispatch dominates; same partitioning discipline as lt_g1_mul_batch,
// GIL released by ctypes so worker threads overlap. returns 0 ok.
extern "C" int lt_keccak256_batch(const uint8_t *data, const uint64_t *offsets,
                                  size_t n, int nthreads, uint8_t *out) {
  if (!data && n > 0 && offsets[n] > 0) return 1;
  if (nthreads <= 1 || n < 64) {
    for (size_t i = 0; i < n; i++)
      keccak_sponge(out + i * 32, 32, data + offsets[i],
                    (size_t)(offsets[i + 1] - offsets[i]), 136, 0x01);
    return 0;
  }
  if ((size_t)nthreads > n / 2) nthreads = (int)(n / 2);
  std::vector<std::thread> ts;
  ts.reserve(nthreads);
  for (int t = 0; t < nthreads; t++) {
    size_t lo = n * t / nthreads, hi = n * (t + 1) / nthreads;
    ts.emplace_back([&, lo, hi]() {
      for (size_t i = lo; i < hi; i++)
        keccak_sponge(out + i * 32, 32, data + offsets[i],
                      (size_t)(offsets[i + 1] - offsets[i]), 136, 0x01);
    });
  }
  for (auto &th : ts) th.join();
  return 0;
}

// xof(domain, data, n) — must match the oracle: shake256(len(dom)||dom||data)
static void xof(uint8_t *out, size_t outlen, const uint8_t *dom, size_t domlen,
                const uint8_t *data, size_t datalen) {
  std::vector<uint8_t> buf;
  buf.push_back((uint8_t)domlen);
  buf.insert(buf.end(), dom, dom + domlen);
  buf.insert(buf.end(), data, data + datalen);
  shake256(out, outlen, buf.data(), buf.size());
}

// ===========================================================================
// Hash-to-curve (try-and-increment, identical control flow to the oracle)
// ===========================================================================

// big-endian bytes -> Fp via mod p (generic width)
static Fp make_mont_u64(u64 x) {
  Fp z;
  fp_set_u64(z, x);
  return z;
}

static void fp_from_wide_be(Fp &z, const uint8_t *in, size_t len) {
  // Horner in base 2^8 over Montgomery field elements: digit-by-digit.
  // mont(256) precomputed once — as a magic static (guarded init): the
  // hand-rolled `bool init256` latch here was a data race when two
  // threads hash-to-curve concurrently (lt_g2_hash from the verify pool)
  static const Fp mont256 = make_mont_u64(256);
  Fp acc;
  memset(acc.v, 0, 48);
  for (size_t i = 0; i < len; i++) {
    fp_mul(acc, acc, mont256);
    Fp d;
    fp_set_u64(d, in[i]);
    fp_add(acc, acc, d);
  }
  z = acc;
}

static const char H_G1_HEX[] = "396c8c005555e1568c00aaab0000aaab";
static const char H_G2_HEX[] =
    "5d543a95414e7f1091d50792876a202cd91de4547085abaa68a205b2e5a7ddfa628f1cb4"
    "d9e82ef21537e293a6691ae1616ec6e786f0c70cf1c38e31c7238e5";

static std::vector<uint8_t> hex_to_bytes(const char *hex) {
  size_t n = strlen(hex);
  std::vector<uint8_t> out;
  size_t i = 0;
  if (n % 2) {  // odd-length: first nibble alone
    char c = hex[0];
    out.push_back((uint8_t)(c <= '9' ? c - '0' : c - 'a' + 10));
    i = 1;
  }
  for (; i < n; i += 2) {
    auto nib = [](char c) -> uint8_t {
      return c <= '9' ? c - '0' : c - 'a' + 10;
    };
    out.push_back((uint8_t)((nib(hex[i]) << 4) | nib(hex[i + 1])));
  }
  return out;
}

static std::vector<uint8_t> H_G1_BYTES, H_G2_BYTES;

// compare y > p - y  (plain form comparison on byte serialization)
static bool fp_gt_neg(const Fp &y) {
  Fp ny;
  fp_neg(ny, y);
  uint8_t yb[48], nyb[48];
  fp_to_bytes_be(yb, y);
  fp_to_bytes_be(nyb, ny);
  return memcmp(yb, nyb, 48) > 0;
}

extern "C" int lt_hash_to_g1(const uint8_t *msg, size_t msglen,
                             const uint8_t *dom, size_t domlen,
                             uint8_t out[96]) {
  for (uint32_t ctr = 0;; ctr++) {
    std::vector<uint8_t> d(dom, dom + domlen);
    d.push_back('|');
    for (int i = 3; i >= 0; i--) d.push_back((uint8_t)(ctr >> (8 * i)));
    uint8_t xb[64];
    xof(xb, 64, d.data(), d.size(), msg, msglen);
    Fp x;
    fp_from_wide_be(x, xb, 64);
    Fp rhs, four;
    fp_sqr(rhs, x);
    fp_mul(rhs, rhs, x);
    fp_set_u64(four, 4);
    fp_add(rhs, rhs, four);
    Fp y;
    if (fp_sqrt(y, rhs)) {
      if (fp_gt_neg(y)) fp_neg(y, y);
      G1 p;
      p.x = x;
      p.y = y;
      p.z = MONT_ONE;
      G1 cleared;
      g1_mul_scalar(cleared, p, H_G1_BYTES.data(), H_G1_BYTES.size());
      g1_to_bytes(out, cleared);
      return 0;
    }
  }
}

// lexicographic comparison matching the oracle: (y1, y0) > (p-y1, p-y0)
static bool fp2_gt_neg(const Fp2 &y) {
  Fp ny0, ny1;
  fp_neg(ny0, y.c0);
  fp_neg(ny1, y.c1);
  uint8_t a1[48], b1[48];
  fp_to_bytes_be(a1, y.c1);
  fp_to_bytes_be(b1, ny1);
  int c = memcmp(a1, b1, 48);
  if (c != 0) return c > 0;
  uint8_t a0[48], b0[48];
  fp_to_bytes_be(a0, y.c0);
  fp_to_bytes_be(b0, ny0);
  return memcmp(a0, b0, 48) > 0;
}

extern "C" int lt_hash_to_g2(const uint8_t *msg, size_t msglen,
                             const uint8_t *dom, size_t domlen,
                             uint8_t out[192]) {
  Fp four;
  fp_set_u64(four, 4);
  Fp2 b2;
  b2.c0 = four;
  b2.c1 = four;
  for (uint32_t ctr = 0;; ctr++) {
    std::vector<uint8_t> d(dom, dom + domlen);
    d.push_back('|');
    for (int i = 3; i >= 0; i--) d.push_back((uint8_t)(ctr >> (8 * i)));
    uint8_t xb[128];
    xof(xb, 128, d.data(), d.size(), msg, msglen);
    Fp2 x;
    fp_from_wide_be(x.c0, xb, 64);
    fp_from_wide_be(x.c1, xb + 64, 64);
    Fp2 rhs;
    fp2_sqr(rhs, x);
    fp2_mul(rhs, rhs, x);
    fp2_add(rhs, rhs, b2);
    Fp2 y;
    if (fp2_sqrt(y, rhs)) {
      if (fp2_gt_neg(y)) fp2_neg(y, y);
      G2 p;
      p.x = x;
      p.y = y;
      p.z = FP2_ONE_;
      G2 cleared;
      g2_mul_scalar(cleared, p, H_G2_BYTES.data(), H_G2_BYTES.size());
      g2_to_bytes(out, cleared);
      return 0;
    }
  }
}

// ===========================================================================
// Initialization
// ===========================================================================

static void compute_pinv() {
  u64 x = 1;
  for (int i = 0; i < 6; i++) x *= 2 - P_LIMBS[0] * x;  // Newton, 2^64
  PINV = (u64)(0 - x);
}

// Differential self-check for the ADX multiplication: drive both paths over
// a pseudorandom walk plus the edge values (0, 1, R, p-1 in Montgomery
// form); ANY mismatch keeps the portable path. Also pins the asm's baked-in
// pinv constant against the computed one.
static void adx_selfcheck() {
#ifdef LT_HAVE_ADX_BUILD
  if (PINV != 0x89f3fffcfffcfffdull) return;  // asm constant would be wrong
  Fp pm1;  // p - 1 (a valid residue; Montgomery form irrelevant for check)
  for (int i = 0; i < 6; i++) pm1.v[i] = P_LIMBS[i];
  pm1.v[0] -= 1;
  Fp cases[4] = {FP_ZERO, MONT_ONE, MONT_R2, pm1};
  u64 seed = 0x9e3779b97f4a7c15ull;
  Fp a = MONT_R2, b = MONT_ONE;
  for (int iter = 0; iter < 64; iter++) {
    if (iter < 16) {
      a = cases[iter % 4];
      b = cases[(iter / 4) % 4];
    } else {  // xorshift walk keeps values "random" but reproducible
      for (int i = 0; i < 6; i++) {
        seed ^= seed << 13;
        seed ^= seed >> 7;
        seed ^= seed << 17;
        a.v[i] ^= seed & 0x7fffffffffffffffull;
      }
      // reduce below p by clearing the top limb's high bits
      a.v[5] &= 0x0fffffffffffffffull;
    }
    Fp zc, za;
    fp_mul_c(zc, a, b);
    lt_fp_mul_adx(za.v, a.v, b.v);
    if (!fp_eq(zc, za)) return;
    b = zc;  // feed results forward
  }
  HAVE_ADX = true;
#endif
}

static struct Init {
  Init() {
    compute_pinv();
    memset(FP_ZERO.v, 0, 48);
    // MONT_ONE = 2^384 mod p by repeated doubling of 1 (plain)
    u64 one[6] = {1, 0, 0, 0, 0, 0};
    u64 acc[6];
    memcpy(acc, one, 48);
    for (int i = 0; i < 384; i++) {
      u64 t[6];
      memcpy(t, acc, 48);
      u128 carry = 0;
      for (int j = 0; j < 6; j++) {
        u128 cur = ((u128)t[j] << 1) | (u64)carry;
        t[j] = (u64)cur;
        carry = cur >> 64;
      }
      // t might exceed p: subtract until < p (carry can be 1: value < 2^385,
      // p > 2^380 so at most 16 subtractions; loop for safety)
      while (carry || cmp_limbs(t, P_LIMBS, 6) >= 0) {
        u128 borrow = 0;
        for (int j = 0; j < 6; j++) {
          u128 cur = (u128)t[j] - P_LIMBS[j] - (u64)borrow;
          t[j] = (u64)cur;
          borrow = (cur >> 64) ? 1 : 0;
        }
        if (carry && !borrow) {
        }
        if (borrow && carry) carry = 0;  // consumed the overflow bit
        else if (borrow && !carry) {     // went negative — undo (can't happen)
          u128 c2 = 0;
          for (int j = 0; j < 6; j++) {
            u128 cur = (u128)t[j] + P_LIMBS[j] + (u64)c2;
            t[j] = (u64)cur;
            c2 = cur >> 64;
          }
          break;
        }
      }
      memcpy(acc, t, 48);
    }
    memcpy(MONT_ONE.v, acc, 48);
    // MONT_R2 = mont_one "squared" as plain mult needs montmul(R,R)=R^2*R^-1=R
    // Instead: compute R2 = 2^768 mod p by doubling MONT_ONE 384 more times.
    for (int i = 0; i < 384; i++) {
      u64 t[6];
      memcpy(t, acc, 48);
      u128 carry = 0;
      for (int j = 0; j < 6; j++) {
        u128 cur = ((u128)t[j] << 1) | (u64)carry;
        t[j] = (u64)cur;
        carry = cur >> 64;
      }
      while (carry || cmp_limbs(t, P_LIMBS, 6) >= 0) {
        u128 borrow = 0;
        for (int j = 0; j < 6; j++) {
          u128 cur = (u128)t[j] - P_LIMBS[j] - (u64)borrow;
          t[j] = (u64)cur;
          borrow = (cur >> 64) ? 1 : 0;
        }
        if (borrow && carry)
          carry = 0;
        else if (borrow && !carry) {
          u128 c2 = 0;
          for (int j = 0; j < 6; j++) {
            u128 cur = (u128)t[j] + P_LIMBS[j] + (u64)c2;
            t[j] = (u64)cur;
            c2 = cur >> 64;
          }
          break;
        }
      }
      memcpy(acc, t, 48);
    }
    memcpy(MONT_R2.v, acc, 48);
    fp_mul(MONT_R3, MONT_R2, MONT_R2);  // R2*R2*R^-1 = R^3

    // (p+1)/4
    u64 pp1[6];
    memcpy(pp1, P_LIMBS, 48);
    u128 carry = (u128)pp1[0] + 1;
    pp1[0] = (u64)carry;
    for (int j = 1; carry >> 64 && j < 6; j++) {
      carry = (u128)pp1[j] + 1;
      pp1[j] = (u64)carry;
    }
    limbs_rshift1(pp1, 6);
    limbs_rshift1(pp1, 6);
    memcpy(P_PLUS1_DIV4, pp1, 48);

    FP2_ZERO_.c0 = FP_ZERO;
    FP2_ZERO_.c1 = FP_ZERO;
    FP2_ONE_.c0 = MONT_ONE;
    FP2_ONE_.c1 = FP_ZERO;
    FP6_ZERO_.c0 = FP2_ZERO_;
    FP6_ZERO_.c1 = FP2_ZERO_;
    FP6_ZERO_.c2 = FP2_ZERO_;
    FP6_ONE_ = FP6_ZERO_;
    FP6_ONE_.c0 = FP2_ONE_;
    FP12_ZERO_.c0 = FP6_ZERO_;
    FP12_ZERO_.c1 = FP6_ZERO_;
    FP12_ONE_ = FP12_ZERO_;
    FP12_ONE_.c0 = FP6_ONE_;

    G1_INF_.x = FP_ZERO;
    G1_INF_.y = MONT_ONE;
    G1_INF_.z = FP_ZERO;
    G2_INF_.x = FP2_ZERO_;
    G2_INF_.y = FP2_ONE_;
    G2_INF_.z = FP2_ZERO_;

    // gammas: xi^((p-1)/6 * i).  (p-1)/6 via limb division by 6.
    u64 pm1[6];
    memcpy(pm1, P_LIMBS, 48);
    pm1[0] -= 1;  // p is odd, no borrow
    // divide by 6
    u64 quot[6];
    u128 rem = 0;
    for (int i = 5; i >= 0; i--) {
      u128 cur = (rem << 64) | pm1[i];
      quot[i] = (u64)(cur / 6);
      rem = cur % 6;
    }
    Fp2 xi;
    xi.c0 = MONT_ONE;
    xi.c1 = MONT_ONE;
    GAMMA[0] = FP2_ONE_;
    Fp2 g1x;
    fp2_pow_limbs(g1x, xi, quot, 6);
    GAMMA[1] = g1x;
    for (int i = 2; i < 6; i++) fp2_mul(GAMMA[i], GAMMA[i - 1], GAMMA[1]);

    H_G1_BYTES = hex_to_bytes(H_G1_HEX);
    H_G2_BYTES = hex_to_bytes(H_G2_HEX);

    // GLV constants: z^2, lambda = z^2 - 1, and Barrett MU = floor(2^384/r)
    {
      const u64 zabs = 0xd201000000010000ull;
      u128 z2 = (u128)zabs * zabs;
      Z2_LIMBS[0] = (u64)z2;
      Z2_LIMBS[1] = (u64)(z2 >> 64);
      u128 lam = z2 - 1;
      LAM_LIMBS[0] = (u64)lam;
      LAM_LIMBS[1] = (u64)(lam >> 64);
      // binary long division of 2^384 by r: 385 shift-subtract steps
      u64 rem[5] = {0, 0, 0, 0, 0}, q[7] = {0, 0, 0, 0, 0, 0, 0};
      u64 rw[5] = {R_LIMBS[0], R_LIMBS[1], R_LIMBS[2], R_LIMBS[3], 0};
      for (int bit = 384; bit >= 0; bit--) {
        // rem = rem*2 + numerator_bit (numerator = 2^384)
        u64 carry = bit == 384 ? 1 : 0;
        for (int i = 0; i < 5; i++) {
          u64 hi = rem[i] >> 63;
          rem[i] = (rem[i] << 1) | carry;
          carry = hi;
        }
        u64 t[5];
        memcpy(t, rem, 40);
        if (!limbs_sub(t, rw, 5)) {
          memcpy(rem, t, 40);
          q[bit / 64] |= 1ull << (bit % 64);
        }
      }
      MU384[0] = q[0];
      MU384[1] = q[1];
      MU384[2] = q[2];  // MU < 2^130: limbs 3+ are zero
    }

    adx_selfcheck();
    cyc_selfcheck();
  }
} _init;

// ===========================================================================
// Exported API (ctypes-friendly, byte-buffer based)
// ===========================================================================

extern "C" {

// returns 0 ok; 1 bad point encoding
int lt_g1_mul(const uint8_t in[96], const uint8_t scalar[32],
              uint8_t out[96]) {
  G1 p;
  if (!g1_from_bytes(p, in)) return 1;
  G1 r;
  g1_mul_scalar(r, p, scalar, 32);
  g1_to_bytes(out, r);
  return 0;
}

int lt_g2_mul(const uint8_t in[192], const uint8_t scalar[32],
              uint8_t out[192]) {
  G2 p;
  if (!g2_from_bytes(p, in)) return 1;
  G2 r;
  g2_mul_scalar(r, p, scalar, 32);
  g2_to_bytes(out, r);
  return 0;
}

// n independent G1 scalar muls (out[i] = pts[i] * scalars[i]) partitioned
// across threads — the TPKE decrypt-share shape: one node emits U^{x_i} for
// every ready ACS slot in one era tick, and per-call ctypes+spawn overhead
// would eat the win mul-by-mul. nthreads <= 1 or tiny n stays serial.
// returns 0 ok; 1 bad point encoding.
int lt_g1_mul_batch(const uint8_t *pts, const uint8_t *scalars, size_t n,
                    int nthreads, uint8_t *out) {
  if (nthreads <= 1 || n < 8) {
    for (size_t i = 0; i < n; i++) {
      G1 p;
      if (!g1_from_bytes(p, pts + i * 96)) return 1;
      G1 r;
      g1_mul_scalar(r, p, scalars + i * 32, 32);
      g1_to_bytes(out + i * 96, r);
    }
    return 0;
  }
  if ((size_t)nthreads > n / 2) nthreads = (int)(n / 2);
  std::vector<int> bad(nthreads, 0);
  std::vector<std::thread> ts;
  ts.reserve(nthreads);
  for (int t = 0; t < nthreads; t++) {
    size_t lo = n * t / nthreads, hi = n * (t + 1) / nthreads;
    ts.emplace_back([&, t, lo, hi]() {
      for (size_t i = lo; i < hi; i++) {
        G1 p;
        if (!g1_from_bytes(p, pts + i * 96)) {
          bad[t] = 1;
          return;
        }
        G1 r;
        g1_mul_scalar(r, p, scalars + i * 32, 32);
        g1_to_bytes(out + i * 96, r);
      }
    });
  }
  for (auto &th : ts) th.join();
  for (int t = 0; t < nthreads; t++)
    if (bad[t]) return 1;
  return 0;
}

int lt_g1_add(const uint8_t a[96], const uint8_t b[96], uint8_t out[96]) {
  G1 pa, pb;
  if (!g1_from_bytes(pa, a) || !g1_from_bytes(pb, b)) return 1;
  G1 r;
  g1_add(r, pa, pb);
  g1_to_bytes(out, r);
  return 0;
}

int lt_g2_add(const uint8_t a[192], const uint8_t b[192], uint8_t out[192]) {
  G2 pa, pb;
  if (!g2_from_bytes(pa, a) || !g2_from_bytes(pb, b)) return 1;
  G2 r;
  g2_add(r, pa, pb);
  g2_to_bytes(out, r);
  return 0;
}

// MSM over G1. pts: n*96 bytes, scalars: n*32 bytes BE.
// Small/medium n (every consensus shape: Lagrange combines at t+1, era
// aggregates at N) takes the Straus/GLV path; huge n falls back to
// Pippenger, whose shared buckets only win once n outgrows the GLV
// window tables.
//
// CONTRACT: points must be members of the prime-order subgroup. The GLV
// path reduces scalars mod r and uses the phi endomorphism, both of which
// are only multiplication-compatible on the subgroup — an on-curve point
// outside it gets an n-DEPENDENT answer (Straus vs Pippenger disagree).
// Every production caller enforces this at wire-parse time
// (native_backend.py routes deserialization through lt_g1_check == 2).
int lt_g1_msm(const uint8_t *pts, const uint8_t *scalars, size_t n,
              uint8_t out[96]) {
  std::vector<G1> points(n);
  for (size_t i = 0; i < n; i++)
    if (!g1_from_bytes(points[i], pts + i * 96)) return 1;
  if (n >= 1 && n <= 256) {
    G1 total;
    g1_msm_straus(total, points.data(), scalars, n);
    g1_to_bytes(out, total);
    return 0;
  }
  const int c = n < 32 ? 4 : (n < 512 ? 8 : 12);
  const int nbuckets = (1 << c) - 1;
  const int nwindows = (256 + c - 1) / c;
  G1 total = G1_INF_;
  std::vector<G1> buckets(nbuckets);
  for (int w = nwindows - 1; w >= 0; w--) {
    for (int i = 0; i < c; i++) g1_dbl(total, total);
    for (int b = 0; b < nbuckets; b++) buckets[b] = G1_INF_;
    for (size_t i = 0; i < n; i++) {
      int bitpos = w * c;
      // extract c bits starting at bitpos (LSB order) from BE scalar
      u64 frag = 0;
      for (int b = 0; b < c; b++) {
        int bit = bitpos + b;
        if (bit >= 256) break;
        int byte_idx = 31 - bit / 8;
        if ((scalars[i * 32 + byte_idx] >> (bit % 8)) & 1) frag |= 1ull << b;
      }
      if (frag) g1_add(buckets[frag - 1], buckets[frag - 1], points[i]);
    }
    G1 run = G1_INF_, sum = G1_INF_;
    for (int b = nbuckets - 1; b >= 0; b--) {
      g1_add(run, run, buckets[b]);
      g1_add(sum, sum, run);
    }
    g1_add(total, total, sum);
  }
  g1_to_bytes(out, total);
  return 0;
}

int lt_g2_msm(const uint8_t *pts, const uint8_t *scalars, size_t n,
              uint8_t out[192]) {
  std::vector<G2> points(n);
  for (size_t i = 0; i < n; i++)
    if (!g2_from_bytes(points[i], pts + i * 192)) return 1;
  const int c = n < 32 ? 4 : 8;
  const int nbuckets = (1 << c) - 1;
  const int nwindows = (256 + c - 1) / c;
  G2 total = G2_INF_;
  std::vector<G2> buckets(nbuckets);
  for (int w = nwindows - 1; w >= 0; w--) {
    for (int i = 0; i < c; i++) g2_dbl(total, total);
    for (int b = 0; b < nbuckets; b++) buckets[b] = G2_INF_;
    for (size_t i = 0; i < n; i++) {
      int bitpos = w * c;
      u64 frag = 0;
      for (int b = 0; b < c; b++) {
        int bit = bitpos + b;
        if (bit >= 256) break;
        int byte_idx = 31 - bit / 8;
        if ((scalars[i * 32 + byte_idx] >> (bit % 8)) & 1) frag |= 1ull << b;
      }
      if (frag) g2_add(buckets[frag - 1], buckets[frag - 1], points[i]);
    }
    G2 run = G2_INF_, sum = G2_INF_;
    for (int b = nbuckets - 1; b >= 0; b--) {
      g2_add(run, run, buckets[b]);
      g2_add(sum, sum, run);
    }
    g2_add(total, total, sum);
  }
  g2_to_bytes(out, total);
  return 0;
}

// Prod e(Pi, Qi) == 1?  returns 1 yes, 0 no, -1 bad encoding.
int lt_pairing_check(const uint8_t *g1s, const uint8_t *g2s, size_t n) {
  std::vector<MLState> states(n);
  std::vector<G1> ps(n);
  std::vector<G2> qs(n);
  for (size_t i = 0; i < n; i++) {
    if (!g1_from_bytes(ps[i], g1s + i * 96)) return -1;
    if (!g2_from_bytes(qs[i], g2s + i * 192)) return -1;
  }
  ml_init_batch(states.data(), ps.data(), qs.data(), n);
  Fp12 f;
  miller_loop_multi(f, states.data(), n);
  Fp12 e;
  final_exponentiation(e, f);
  return fp12_is_one(e) ? 1 : 0;
}

// Threaded variant for the era-sized grand product (2S pairs at N=64):
// Miller loops are independent, so partition them across threads, multiply
// the partial Fp12 products, and run ONE shared final exponentiation.
// nthreads <= 1 falls back to the serial loop above.
int lt_pairing_check_mt(const uint8_t *g1s, const uint8_t *g2s, size_t n,
                        int nthreads) {
  if (nthreads <= 1 || n < 8) return lt_pairing_check(g1s, g2s, n);
  if ((size_t)nthreads > n / 2) nthreads = (int)(n / 2);
  std::vector<Fp12> partial(nthreads, FP12_ONE_);
  std::vector<int> bad(nthreads, 0);
  std::vector<std::thread> ts;
  ts.reserve(nthreads);
  for (int t = 0; t < nthreads; t++) {
    size_t lo = n * t / nthreads, hi = n * (t + 1) / nthreads;
    ts.emplace_back([&, t, lo, hi]() {
      std::vector<MLState> states(hi - lo);
      std::vector<G1> ps(hi - lo);
      std::vector<G2> qs(hi - lo);
      for (size_t i = lo; i < hi; i++) {
        if (!g1_from_bytes(ps[i - lo], g1s + i * 96) ||
            !g2_from_bytes(qs[i - lo], g2s + i * 192)) {
          bad[t] = 1;
          return;
        }
      }
      ml_init_batch(states.data(), ps.data(), qs.data(), hi - lo);
      Fp12 f;
      miller_loop_multi(f, states.data(), hi - lo);
      partial[t] = f;
    });
  }
  for (auto &th : ts) th.join();
  for (int t = 0; t < nthreads; t++)
    if (bad[t]) return -1;
  Fp12 f = FP12_ONE_;
  for (int t = 0; t < nthreads; t++) {
    Fp12 tmp;
    fp12_mul(tmp, f, partial[t]);
    f = tmp;
  }
  Fp12 e;
  final_exponentiation(e, f);
  return fp12_is_one(e) ? 1 : 0;
}

// GT output for conformance tests: 576 bytes (12 x 48, oracle order)
int lt_multi_pairing(const uint8_t *g1s, const uint8_t *g2s, size_t n,
                     uint8_t out[576]) {
  Fp12 f = FP12_ONE_;
  for (size_t i = 0; i < n; i++) {
    G1 p;
    G2 q;
    if (!g1_from_bytes(p, g1s + i * 96)) return -1;
    if (!g2_from_bytes(q, g2s + i * 192)) return -1;
    Fp12 m;
    miller_loop(m, p, q);
    Fp12 t;
    fp12_mul(t, f, m);
    f = t;
  }
  Fp12 e;
  final_exponentiation(e, f);
  const Fp2 *cs[6] = {&e.c0.c0, &e.c0.c1, &e.c0.c2,
                      &e.c1.c0, &e.c1.c1, &e.c1.c2};
  for (int i = 0; i < 6; i++) {
    fp_to_bytes_be(out + i * 96, cs[i]->c0);
    fp_to_bytes_be(out + i * 96 + 48, cs[i]->c1);
  }
  return 0;
}

// point validation: 1 valid-on-curve, 2 also-in-subgroup, 0 invalid
int lt_g1_check(const uint8_t in[96]) {
  G1 p;
  if (!g1_from_bytes(p, in)) return 0;
  return g1_in_subgroup(p) ? 2 : 1;
}
int lt_g2_check(const uint8_t in[192]) {
  G2 p;
  if (!g2_from_bytes(p, in)) return 0;
  return g2_in_subgroup(p) ? 2 : 1;
}

// Reference-style SERIAL per-share verification loop (the baseline we beat):
// for each i: e(U_i, H) == e(Y_i, W). Writes 0/1 into results[i].
// Mirrors the per-message verify in the reference's HoneyBadger
// (HoneyBadger.cs:205-217) — 2 pairings per share, no batching.
int lt_tpke_verify_shares_serial(const uint8_t *uis, const uint8_t *yis,
                                 size_t n, const uint8_t h[192],
                                 const uint8_t w[192], uint8_t *results) {
  G2 H, W;
  if (!g2_from_bytes(H, h) || !g2_from_bytes(W, w)) return -1;
  for (size_t i = 0; i < n; i++) {
    G1 u, y;
    if (!g1_from_bytes(u, uis + i * 96)) return -1;
    if (!g1_from_bytes(y, yis + i * 96)) return -1;
    G1 yneg;
    g1_neg(yneg, y);
    Fp12 m1, m2, f, e;
    miller_loop(m1, u, H);
    miller_loop(m2, yneg, W);
    fp12_mul(f, m1, m2);
    final_exponentiation(e, f);
    results[i] = fp12_is_one(e) ? 1 : 0;
  }
  return 0;
}

int lt_version() { return 1; }
}
