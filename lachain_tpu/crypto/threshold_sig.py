"""BLS threshold signatures (signatures in G2, public keys in G1).

Functional parity with the reference's threshold-signature layer
(/root/reference/src/Lachain.Crypto/ThresholdSignature/):
  * PrivateKeyShare.HashAndSign   (PrivateKeyShare.cs:20-27) -> sign()
  * PublicKey.ValidateSignature   (PublicKey.cs:15-20)       -> verify()
  * PublicKeySet.AssembleSignature(PublicKeySet.cs:35-44)    -> combine()
  * ThresholdSigner.AddShare      (ThresholdSigner.cs:45-90) -> ThresholdSigner
  * Signature.Parity              (Signature.cs:20-24)       -> Signature.parity
  * TrustedKeyGen                 (TrustedKeyGen.cs:8-35)    -> TsTrustedKeyGen

Scheme:
  keys    : x = f(0), degree-t polynomial; validator i holds x_i = f(i+1);
            shared pk Y = g1^x, per-validator pk Y_i = g1^{x_i}.
  sign    : sigma_i = H_G2(msg)^{x_i}.
  verify  : e(g1, sigma_i) == e(Y_i, H_G2(msg)).
  combine : sigma = Lagrange_0({(i+1, sigma_i)}) in G2; verify against Y.

TPU-first batch verification (`batch_verify_shares`): random linear
combination collapses M share checks into 2 pairings + one G1 MSM + one G2
MSM — the per-coin hot path in CommonCoin (reference CommonCoin.cs:75-96
verifies every share with 2 pairings, serially).
"""
from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from . import bls12381 as bls
from ..utils import metrics
from .hashes import keccak256
from .provider import batch_bisect_verify, get_backend, select_distinct

_SIG_DOMAIN = b"LTPU-TSIG"


import functools


@functools.lru_cache(maxsize=4096)
def _hash_to_sig_point(msg: bytes) -> tuple:
    """Memoized: every sign/verify/combine of one coin re-hashes the same
    coin id (N+1 times per coin per validator at N=64)."""
    return get_backend().hash_to_g2(msg, _SIG_DOMAIN)


@dataclass(frozen=True)
class Signature:
    """Combined or partial signature (a G2 point)."""

    sigma: tuple

    def to_bytes(self) -> bytes:
        return bls.g2_to_bytes(self.sigma)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Signature":
        return cls(get_backend().g2_deserialize(data))

    @property
    def parity(self) -> bool:
        """Deterministic coin bit (role of Signature.Parity in the reference,
        Signature.cs:20-24; we take the low bit of keccak256 of the
        serialized point — any fixed extractor works, all correct nodes
        compute the same combined sigma)."""
        return bool(keccak256(self.to_bytes())[0] & 1)


@dataclass(frozen=True)
class PartialSignature:
    sigma: tuple  # G2
    signer_id: int

    def to_bytes(self) -> bytes:
        from ..utils.serialization import write_u32

        return bls.g2_to_bytes(self.sigma) + write_u32(self.signer_id)

    @classmethod
    def from_bytes(cls, data: bytes) -> "PartialSignature":
        from ..utils.serialization import Reader

        sigma = get_backend().g2_deserialize(data[: bls.G2_BYTES])
        r = Reader(data[bls.G2_BYTES :])
        signer = r.u32()
        r.assert_eof()
        return cls(sigma, signer)


class TsPublicKey:
    """Single public key (shared or per-validator), in G1."""

    def __init__(self, y: tuple):
        self.y = y

    def to_bytes(self) -> bytes:
        return bls.g1_to_bytes(self.y)

    @classmethod
    def from_bytes(cls, data: bytes) -> "TsPublicKey":
        return cls(get_backend().g1_deserialize(data))

    @metrics.timed("crypto_ts_verify")
    def verify(self, msg: bytes, sig: Signature) -> bool:
        """e(g1, sigma) == e(Y, H_G2(msg))
        (reference: ThresholdSignature/PublicKey.cs:15-20)."""
        h = _hash_to_sig_point(msg)
        return get_backend().pairing_check(
            [(bls.G1_GEN, sig.sigma), (bls.g1_neg(self.y), h)]
        )


class TsPublicKeySet:
    """All validators' public keys + threshold
    (reference: ThresholdSignature/PublicKeySet.cs)."""

    def __init__(self, keys: Sequence[TsPublicKey], t: int):
        self.keys = list(keys)
        self.t = t  # t+1 shares assemble a signature
        # shared key = interpolation of the per-validator keys at 0
        xs = list(range(1, len(self.keys) + 1))
        self.shared = TsPublicKey(
            bls.g1_interpolate(xs[: t + 1], [k.y for k in self.keys[: t + 1]])
        )

    def to_bytes(self) -> bytes:
        from ..utils.serialization import write_bytes_list, write_u32

        return write_u32(self.t) + write_bytes_list(
            [k.to_bytes() for k in self.keys]
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "TsPublicKeySet":
        from ..utils.serialization import Reader

        r = Reader(data)
        t = r.u32()
        keys = [TsPublicKey.from_bytes(b) for b in r.bytes_list()]
        r.assert_eof()
        return cls(keys, t)

    @property
    def n(self) -> int:
        return len(self.keys)

    @metrics.timed("crypto_ts_verify_share")
    def verify_share(self, msg: bytes, ps: PartialSignature) -> bool:
        """e(g1, sigma_i) == e(Y_i, H(msg)) — per-share hot op
        (reference: ThresholdSigner.cs:92-95)."""
        if not (0 <= ps.signer_id < len(self.keys)):
            return False
        h = _hash_to_sig_point(msg)
        yk = self.keys[ps.signer_id].y
        return get_backend().pairing_check(
            [(bls.G1_GEN, ps.sigma), (bls.g1_neg(yk), h)]
        )

    def batch_verify_shares(
        self,
        msg: bytes,
        shares: Sequence[PartialSignature],
        rng=secrets,
    ) -> List[bool]:
        """Random-linear-combination batch check (TPU-first redesign):
          e(g1, sum c_i sigma_i) == e(sum c_i Y_i, H(msg))
        2 pairings + 1 G2 MSM + 1 G1 MSM for the whole batch; bisect on
        failure to isolate bad shares."""
        if not shares:
            return []
        in_range = [0 <= s.signer_id < len(self.keys) for s in shares]
        live = [i for i, ok in enumerate(in_range) if ok]
        if not live:
            return [False] * len(shares)
        h = _hash_to_sig_point(msg)
        backend = get_backend()

        def group_ok(idx: List[int]) -> bool:
            # < 2^128 so the TPU path's 128-bit encoding is exact
            cs = [rng.randbelow((1 << 128) - 1) + 1 for _ in idx]
            sig_agg = backend.g2_msm(
                [shares[live[i]].sigma for i in idx], cs
            )
            y_agg = backend.g1_msm(
                [self.keys[shares[live[i]].signer_id].y for i in idx], cs
            )
            return backend.pairing_check(
                [(bls.G1_GEN, sig_agg), (bls.g1_neg(y_agg), h)]
            )

        live_results = batch_bisect_verify(group_ok, len(live))
        results = [False] * len(shares)
        for pos, i in enumerate(live):
            results[i] = live_results[pos]
        return results

    @metrics.timed("crypto_ts_combine")
    def combine(self, shares: Sequence[PartialSignature]) -> Signature:
        """Lagrange-assemble t+1 partial signatures in G2
        (reference: PublicKeySet.cs:35-44)."""
        chosen = select_distinct(
            shares, key=lambda s: s.signer_id, count=self.t + 1
        )
        if chosen is None:
            raise ValueError(
                f"need {self.t + 1} distinct signer ids, got "
                f"{len(set(s.signer_id for s in shares))}"
            )
        shares = chosen
        xs = [s.signer_id + 1 for s in shares]
        cs = bls.fr_lagrange_coeffs(xs, at=0)
        sigma = get_backend().g2_msm([s.sigma for s in shares], cs)
        return Signature(sigma)


def era_verify_combine(
    key_set: TsPublicKeySet,
    coins,
    rng=secrets,
):
    """Era-tick batch: verify + combine MANY coins' shares at once.

    coins: list of (msg: bytes, shares: Dict[int, PartialSignature]) — one
    entry per pending coin, shares keyed by signer id (>= t+1 each).
    Returns a list of Optional[Signature] (None where a coin's batch
    contained an invalid share — callers fall back to the per-share path
    to prune it, mirroring ThresholdSigner.add_share).

    With the `tpu` backend this rides the Pallas G2 era kernel
    (ops/pg2.py) behind `ts_era_verify_combine` — S x K lanes, one grand
    multi-pairing; elsewhere it degrades to the same per-coin host ops
    TsPublicKeySet.batch_verify_shares/combine use. Reference semantics:
    ThresholdSigner.cs:45-95 + PublicKeySet.cs:35-44, serial there.
    """
    # both paths verify exactly the chosen (lowest-signer-id) t+1 shares —
    # the ones the combine consumes — so the device and host backends agree
    # on every input (an unchosen invalid share can never flip the result);
    # coins without t+1 in-range signers resolve to None without any work
    out: List[Optional[Signature]] = [None] * len(coins)
    live: List[int] = []
    chosen_per_coin: List[list] = []
    for idx, (_msg, shares) in enumerate(coins):
        valid_ids = sorted(i for i in shares if 0 <= i < key_set.n)
        if len(valid_ids) > key_set.t:
            live.append(idx)
            chosen_per_coin.append(valid_ids[: key_set.t + 1])

    def host_path():
        for idx, signers in zip(live, chosen_per_coin):
            msg, shares = coins[idx]
            chosen = [shares[i] for i in signers]
            oks = key_set.batch_verify_shares(msg, chosen, rng=rng)
            out[idx] = key_set.combine(chosen) if all(oks) else None
        return out

    backend = get_backend()
    era_fn = getattr(backend, "ts_era_verify_combine", None)
    if era_fn is None or not live:
        return host_path()
    from .tpu_backend import CoinJob

    jobs = []
    for idx, signers in zip(live, chosen_per_coin):
        msg, shares = coins[idx]
        cs = bls.fr_lagrange_coeffs([i + 1 for i in signers], at=0)
        lag_row = [0] * key_set.n
        sigma_row = [None] * key_set.n
        for i, c in zip(signers, cs):
            lag_row[i] = c
            sigma_row[i] = shares[i].sigma
        jobs.append(
            CoinJob(
                sigma_by_signer=sigma_row,
                lagrange_row=lag_row,
                h=_hash_to_sig_point(msg),
            )
        )
    try:
        results = era_fn(jobs, key_set.keys, rng=rng)
    except Exception:
        # device path unavailable/broken: liveness beats acceleration —
        # same degradation rule as HoneyBadger._try_decrypt_ready
        import logging

        logging.getLogger("lachain.crypto").exception(
            "tpu coin era path failed; host fallback"
        )
        return host_path()
    for idx, (ok, comb) in zip(live, results):
        out[idx] = Signature(comb) if ok else None
    return out


class TsPrivateKeyShare:
    """Validator signing share x_i
    (reference: ThresholdSignature/PrivateKeyShare.cs)."""

    def __init__(self, x_i: int, my_id: int):
        self.x_i = x_i % bls.R
        self.my_id = my_id

    def to_bytes(self) -> bytes:
        from ..utils.serialization import write_u32

        return bls.fr_to_bytes(self.x_i) + write_u32(self.my_id)

    @classmethod
    def from_bytes(cls, data: bytes) -> "TsPrivateKeyShare":
        from ..utils.serialization import Reader

        x = bls.fr_from_bytes(data[: bls.FR_BYTES])
        r = Reader(data[bls.FR_BYTES :])
        my_id = r.u32()
        r.assert_eof()
        return cls(x, my_id)

    def public_key(self) -> TsPublicKey:
        return TsPublicKey(bls.g1_mul(bls.G1_GEN, self.x_i))

    @metrics.timed("crypto_ts_sign")
    def sign(self, msg: bytes) -> PartialSignature:
        """sigma_i = H_G2(msg)^{x_i}
        (reference: PrivateKeyShare.cs:20-27 HashAndSign)."""
        h = _hash_to_sig_point(msg)
        return PartialSignature(
            sigma=get_backend().g2_mul(h, self.x_i), signer_id=self.my_id
        )


class ThresholdSigner:
    """Stateful per-message share collector
    (reference: ThresholdSignature/ThresholdSigner.cs:45-90 and the
    IThresholdSigner seam named in SURVEY.md §1).

    Collects shares, verifies each (single or deferred-batch), and produces
    the combined signature once t+1 valid shares are present.
    """

    def __init__(
        self,
        msg: bytes,
        key_share: TsPrivateKeyShare,
        pub_key_set: TsPublicKeySet,
    ):
        self.msg = msg
        self.key_share = key_share
        self.pub_key_set = pub_key_set
        self._shares: Dict[int, PartialSignature] = {}
        self._signature: Optional[Signature] = None
        # signer ids whose shares failed the deferred batch verification —
        # Byzantine evidence the owning protocol surfaces (evidence.py)
        self.pruned: set = set()

    def sign(self) -> PartialSignature:
        return self.key_share.sign(self.msg)

    def add_share(self, ps: PartialSignature, verify: bool = True) -> bool:
        """Returns True if the share was accepted. Combined signature becomes
        available once t+1 distinct valid shares are collected."""
        if self._signature is not None:
            return True  # already done
        if ps.signer_id in self._shares:
            return self._shares[ps.signer_id].sigma == ps.sigma
        if not (0 <= ps.signer_id < self.pub_key_set.n):
            return False
        if verify and not self.pub_key_set.verify_share(self.msg, ps):
            return False
        self._shares[ps.signer_id] = ps
        if len(self._shares) >= self.pub_key_set.t + 1:
            sig = self.pub_key_set.combine(list(self._shares.values()))
            if self.pub_key_set.shared.verify(self.msg, sig):
                self._signature = sig
            else:
                # A bad share slipped in (deferred-verification mode): prune
                # invalid shares so they cannot poison every later combine.
                held = list(self._shares.values())
                oks = self.pub_key_set.batch_verify_shares(self.msg, held)
                self.pruned.update(
                    s.signer_id for s, ok in zip(held, oks) if not ok
                )
                self._shares = {
                    s.signer_id: s for s, ok in zip(held, oks) if ok
                }
                if len(self._shares) >= self.pub_key_set.t + 1:
                    sig = self.pub_key_set.combine(list(self._shares.values()))
                    if self.pub_key_set.shared.verify(self.msg, sig):
                        self._signature = sig
        return True

    @property
    def signature(self) -> Optional[Signature]:
        return self._signature


class TsTrustedKeyGen:
    """Trusted dealer for tests/devnets
    (reference: ThresholdSignature/TrustedKeyGen.cs:8-35)."""

    def __init__(self, n: int, f: int, rng=secrets):
        if n <= 3 * f and not (f == 0 and n >= 1):
            raise ValueError("dealer requires n > 3f")
        coeffs = [rng.randbelow(bls.R) for _ in range(f + 1)]
        self._shares = [bls.fr_eval_poly(coeffs, i + 1) for i in range(n)]
        self.pub_key_set = TsPublicKeySet(
            [
                TsPublicKey(bls.g1_mul(bls.G1_GEN, s))
                for s in self._shares
            ],
            t=f,
        )
        # dealer sanity: interpolated shared key matches g1^f(0)
        assert bls.g1_eq(
            self.pub_key_set.shared.y, bls.g1_mul(bls.G1_GEN, coeffs[0])
        )

    def private_key_share(self, i: int) -> TsPrivateKeyShare:
        return TsPrivateKeyShare(self._shares[i], i)
