"""Threshold public-key encryption (Baek–Zheng style) over BLS12-381.

Functional parity with the reference's TPKE layer
(/root/reference/src/Lachain.Crypto/TPKE/):
  * PublicKey.Encrypt          (TPKE/PublicKey.cs:25-37)   -> encrypt()
  * PrivateKey.Decrypt         (TPKE/PrivateKey.cs:21-31)  -> decrypt_share()
  * PublicKey.VerifyShare      (TPKE/PublicKey.cs:88-92)   -> verify_share()
  * PublicKey.FullDecrypt      (TPKE/PublicKey.cs:55-86)   -> full_decrypt()
  * TrustedKeyGen              (TPKE/TrustedKeyGen.cs:7-41) -> TpkeTrustedKeyGen
  * EncryptedShare / PartiallyDecryptedShare records.

Scheme (same algebra as the reference, our own wire format):
  keys    : master secret x = f(0) for a degree-F polynomial f over Fr;
            validator i holds x_i = f(i+1); Y = g1^x, Y_i = g1^{x_i}.
  encrypt : r <- Fr;  U = g1^r;  V = msg XOR XOF(Y^r);  W = H_G2(U, V)^r.
  validity: e(g1, W) == e(U, H_G2(U, V)).
  decrypt : U_i = U^{x_i}  (a "partially decrypted share").
  verify  : e(U_i, H) == e(Y_i, W)  with H = H_G2(U, V).
  combine : U^x = Lagrange_0({(i+1, U_i)});  msg = V XOR XOF(U^x).

TPU-first redesign (NOT in the reference, see SURVEY.md §5 "long-context"):
the reference verifies shares one at a time, 2 pairings each. Here
`batch_verify_shares` reduces M shares to ONE pairing equality via a random
linear combination:  with random c_j,
    e(sum_j c_j U_j, H) == e(sum_j c_j Y_j, W)
which holds iff every share is valid except w/ probability 2^-128. The hot op
becomes a G1 MSM — batchable on TPU — and pairings drop from 2M to 2.
"""
from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import List, Sequence

from . import bls12381 as bls
from .hashes import xof
from .provider import batch_bisect_verify, get_backend, select_distinct
from ..utils import metrics

_ENC_DOMAIN = b"LTPU-TPKE-PAD"
_HW_DOMAIN = b"LTPU-TPKE-W"


def _pad(y_r_point: tuple, nbytes: int) -> bytes:
    """Keystream derived from the shared G1 point (role of the reference's
    SHA3-seeded DigestRandomGenerator XOR pad, TPKE/Utils.cs:13-19)."""
    return xof(_ENC_DOMAIN, bls.g1_to_bytes(y_r_point), nbytes)


import functools


@functools.lru_cache(maxsize=4096)
def _hash_uv_to_g2(u: tuple, v: bytes) -> tuple:
    """Memoized: one ciphertext's H point is consulted for every decrypt/
    verify/combine touching it — dozens of times per era at N=64. Keyed on
    the raw Jacobian tuple: a different representative of the same point
    just misses and recomputes (hash_to_g2 is deterministic), never
    produces a wrong value."""
    return get_backend().hash_to_g2(
        bls.g1_to_bytes(u) + v, _HW_DOMAIN
    )


def ciphertext_h(share: "EncryptedShare") -> tuple:
    """H_G2(U, V) for a ciphertext — the G2 point every share of this
    ciphertext is verified against (e(U_i, H) == e(Y_i, W))."""
    return _hash_uv_to_g2(share.u, share.v)


def _xor(a: bytes, b: bytes) -> bytes:
    """Single big-int XOR instead of a per-byte Python loop (proposals are
    tens of KB; the loop was ~0.6 ms per call at era scale)."""
    return (int.from_bytes(a, "big") ^ int.from_bytes(b, "big")).to_bytes(
        len(a), "big"
    )


def decrypt_with_combined(share: "EncryptedShare", y_r: tuple) -> bytes:
    """Strip the pad given the combined point U^x (the tail of
    full_decrypt, exposed for callers that obtained `y_r` from the batched
    era kernel instead of a host Lagrange loop)."""
    return _xor(share.v, _pad(y_r, len(share.v)))


@dataclass(frozen=True)
class EncryptedShare:
    """Ciphertext of one validator's tx-batch share
    (reference: TPKE/EncryptedShare.cs:10-55)."""

    u: tuple  # G1
    v: bytes
    w: tuple  # G2
    share_id: int

    def to_bytes(self) -> bytes:
        from ..utils.serialization import write_bytes, write_u32

        return (
            bls.g1_to_bytes(self.u)
            + bls.g2_to_bytes(self.w)
            + write_u32(self.share_id)
            + write_bytes(self.v)
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "EncryptedShare":
        from ..utils.serialization import Reader

        backend = get_backend()
        u = backend.g1_deserialize(data[: bls.G1_BYTES])
        w = backend.g2_deserialize(
            data[bls.G1_BYTES : bls.G1_BYTES + bls.G2_BYTES]
        )
        r = Reader(data[bls.G1_BYTES + bls.G2_BYTES :])
        share_id = r.u32()
        v = r.bytes_()
        r.assert_eof()
        return cls(u=u, v=v, w=w, share_id=share_id)


def decode_encrypted_shares_batch(blobs):
    """Parse many serialized EncryptedShares with batched subgroup checks
    (one aggregate G1 check for the U points, one aggregate G2 check for the
    W points — provider.deserialize_batch_*). Returns a list aligned with
    `blobs`; malformed/invalid entries are None."""
    from ..utils.serialization import Reader
    from .provider import deserialize_batch_g1, deserialize_batch_g2

    metas = []
    for data in blobs:
        try:
            r = Reader(data[bls.G1_BYTES + bls.G2_BYTES :])
            share_id = r.u32()
            v = r.bytes_()
            r.assert_eof()
            metas.append((share_id, v))
        except Exception:
            metas.append(None)
    live = [i for i, m in enumerate(metas) if m is not None]
    us = deserialize_batch_g1([blobs[i][: bls.G1_BYTES] for i in live])
    ws = deserialize_batch_g2(
        [blobs[i][bls.G1_BYTES : bls.G1_BYTES + bls.G2_BYTES] for i in live]
    )
    out = [None] * len(blobs)
    for j, i in enumerate(live):
        if us[j] is None or ws[j] is None:
            continue
        share_id, v = metas[i]
        out[i] = EncryptedShare(u=us[j], v=v, w=ws[j], share_id=share_id)
    return out


@dataclass(frozen=True)
class PartiallyDecryptedShare:
    """One validator's decryption share U_i = U^{x_i}
    (reference: TPKE/PartiallyDecryptedShare.cs:5-19)."""

    ui: tuple  # G1
    decryptor_id: int
    share_id: int

    def to_bytes(self) -> bytes:
        from ..utils.serialization import write_u32

        return (
            bls.g1_to_bytes(self.ui)
            + write_u32(self.decryptor_id)
            + write_u32(self.share_id)
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "PartiallyDecryptedShare":
        from ..utils.serialization import Reader

        ui = get_backend().g1_deserialize(data[: bls.G1_BYTES])
        r = Reader(data[bls.G1_BYTES :])
        dec_id = r.u32()
        share_id = r.u32()
        r.assert_eof()
        return cls(ui=ui, decryptor_id=dec_id, share_id=share_id)


class TpkePublicKey:
    """Master TPKE public key + threshold (reference: TPKE/PublicKey.cs)."""

    def __init__(self, y: tuple, t: int):
        self.y = y  # G1
        self.t = t  # polynomial degree: t+1 shares reconstruct

    def to_bytes(self) -> bytes:
        from ..utils.serialization import write_u32

        return bls.g1_to_bytes(self.y) + write_u32(self.t)

    @classmethod
    def from_bytes(cls, data: bytes) -> "TpkePublicKey":
        from ..utils.serialization import Reader

        y = get_backend().g1_deserialize(data[: bls.G1_BYTES])
        r = Reader(data[bls.G1_BYTES :])
        t = r.u32()
        r.assert_eof()
        return cls(y, t)

    # -- encryption ----------------------------------------------------------
    @metrics.timed("crypto_tpke_encrypt")
    def encrypt(self, msg: bytes, share_id: int, rng=secrets) -> EncryptedShare:
        backend = get_backend()
        r = rng.randbelow(bls.R - 1) + 1
        u = backend.g1_mul(bls.G1_GEN, r)
        y_r = backend.g1_mul(self.y, r)
        v = _xor(msg, _pad(y_r, len(msg)))
        w = get_backend().g2_mul(_hash_uv_to_g2(u, v), r)
        return EncryptedShare(u=u, v=v, w=w, share_id=share_id)

    # -- verification --------------------------------------------------------
    def verify_ciphertext(self, share: EncryptedShare) -> bool:
        """e(g1, W) == e(U, H_G2(U, V)) — ciphertext consistency
        (reference: TPKE/PrivateKey.cs:21-27)."""
        h = _hash_uv_to_g2(share.u, share.v)
        return get_backend().pairing_check(
            [(bls.G1_GEN, share.w), (bls.g1_neg(share.u), h)]
        )

    def verify_share(
        self,
        vk: "TpkeVerificationKey",
        dec: PartiallyDecryptedShare,
        share: EncryptedShare,
    ) -> bool:
        """Single-share check e(U_i, H) == e(Y_i, W)
        (reference: TPKE/PublicKey.cs:88-92) — the op the TPU path batches."""
        h = _hash_uv_to_g2(share.u, share.v)
        return get_backend().pairing_check(
            [(dec.ui, h), (bls.g1_neg(vk.y_i), share.w)]
        )

    @metrics.timed("crypto_tpke_verify_shares")
    def batch_verify_shares(
        self,
        vks: Sequence["TpkeVerificationKey"],
        decs: Sequence[PartiallyDecryptedShare],
        share: EncryptedShare,
        rng=secrets,
    ) -> List[bool]:
        """Batched verification via random linear combination (TPU-first).

        Returns per-share validity. Fast path: one MSM pair + 2 pairings for
        the whole batch; on failure, bisect to isolate the invalid share(s) —
        cost O(2 pairings * log M) in the failure case instead of 2M always.
        """
        assert len(vks) == len(decs)
        if not decs:
            return []
        h = _hash_uv_to_g2(share.u, share.v)
        backend = get_backend()

        def group_ok(idx: List[int]) -> bool:
            # coefficients strictly below 2^128 so the TPU path's 128-bit
            # scalar encoding (ops/verify.py) represents them exactly
            cs = [rng.randbelow((1 << 128) - 1) + 1 for _ in idx]
            u_agg = backend.g1_msm([decs[i].ui for i in idx], cs)
            y_agg = backend.g1_msm([vks[i].y_i for i in idx], cs)
            return backend.pairing_check(
                [(u_agg, h), (bls.g1_neg(y_agg), share.w)]
            )

        return batch_bisect_verify(group_ok, len(decs))

    # -- combination ---------------------------------------------------------
    @metrics.timed("crypto_tpke_full_decrypt")
    def full_decrypt(
        self,
        share: EncryptedShare,
        decs: Sequence[PartiallyDecryptedShare],
    ) -> bytes:
        """Lagrange-combine t+1 decryption shares and strip the pad
        (reference: TPKE/PublicKey.cs:55-86)."""
        chosen = select_distinct(
            decs, key=lambda d: d.decryptor_id, count=self.t + 1
        )
        if chosen is None:
            raise ValueError(
                f"need {self.t + 1} distinct decryptor ids, got "
                f"{len(set(d.decryptor_id for d in decs))}"
            )
        decs = chosen
        xs = [d.decryptor_id + 1 for d in decs]
        cs = bls.fr_lagrange_coeffs(xs, at=0)
        y_r = get_backend().g1_msm([d.ui for d in decs], cs)
        return decrypt_with_combined(share, y_r)


# ciphertext-validity memo: (u, v, w) -> bool. A pairing equation's truth
# is a pure function of the ciphertext, so re-verifications — protocol
# retries on a node, N validators sharing a process in the simulator —
# skip the Millers entirely. Verdicts (both ways) are cached; the RLC
# weights only affect isolation, not the per-ciphertext verdict.
_CT_VALID_MEMO: dict = {}


def batch_verify_ciphertexts(
    shares: Sequence["EncryptedShare"], backend=None, rng=secrets
) -> List[bool]:
    """Validate many ciphertexts with one random-linear-combination
    multi-pairing (single final exponentiation) instead of 2 pairings each
    (reference pays the serial cost per decrypt, TPKE/PrivateKey.cs:21-27).
    Bisects on failure to isolate invalid ciphertexts."""
    from .provider import batch_bisect_verify, get_backend

    if backend is None:
        backend = get_backend()
    if not shares:
        return []
    keys = [(s.u, s.v, s.w) for s in shares]
    out: List = [_CT_VALID_MEMO.get(k) for k in keys]
    todo = [i for i, v in enumerate(out) if v is None]
    if not todo:
        return out
    hs = {i: _hash_uv_to_g2(shares[i].u, shares[i].v) for i in todo}

    def group_ok(idx):
        pairs = []
        for t in idx:
            i = todo[t]
            r_s = rng.randbelow((1 << 128) - 1) + 1
            pairs.append((backend.g1_mul(bls.G1_GEN, r_s), shares[i].w))
            pairs.append(
                (backend.g1_mul(bls.g1_neg(shares[i].u), r_s), hs[i])
            )
        return backend.pairing_check(pairs)

    verdicts = batch_bisect_verify(group_ok, len(todo))
    if len(_CT_VALID_MEMO) > 65536:
        _CT_VALID_MEMO.clear()
    for t, ok in zip(todo, verdicts):
        out[t] = ok
        _CT_VALID_MEMO[keys[t]] = ok
    return out


def peek_decrypted_share_ids(data: bytes):
    """(decryptor_id, share_id) from a serialized PartiallyDecryptedShare
    WITHOUT parsing the point — the ingest-path dedup/equivocation checks
    need only the ids, so the expensive G1 parse is deferred until the share
    is actually chosen for a combination. Returns None when malformed."""
    if len(data) != bls.G1_BYTES + 8:
        return None
    return (
        int.from_bytes(data[bls.G1_BYTES : bls.G1_BYTES + 4], "big"),
        int.from_bytes(data[bls.G1_BYTES + 4 :], "big"),
    )


_Y_AGG_CACHE: dict = {}


def _y_agg_cache_for(verification_keys) -> dict:
    """Per-verification-key-set Y-aggregate cache (keyed by id() holding a
    strong reference, same pattern as ops/verify.GlvEraPipeline.y_device)."""
    key = id(verification_keys)
    hit = _Y_AGG_CACHE.get(key)
    if hit is not None and hit[0] is verification_keys:
        return hit[1]
    if len(_Y_AGG_CACHE) >= 4:
        _Y_AGG_CACHE.pop(next(iter(_Y_AGG_CACHE)))
    cache: dict = {}
    _Y_AGG_CACHE[key] = (verification_keys, cache)
    return cache


def era_verify_combine_host(
    jobs, verification_keys, backend=None, rng=secrets
):
    """Host implementation of the era verify+combine contract
    (crypto/tpu_backend.py::TpuBackend.tpke_era_verify_combine): verify and
    Lagrange-combine a whole era tick's worth of slots with ONE grand
    multi-pairing (a single final exponentiation for every slot) instead of
    2 pairings per slot.

    Per slot: C = sum(lag_i * u_i), Y = sum(lag_i * y_i) over the chosen
    t+1 lanes. Since e(., h) is injective on the prime-order subgroup for
    h != O, `e(C, h) == e(Y, w)` holds for exactly ONE point C — the correct
    combination — so verifying the combined point is equivalent to verifying
    every chosen share (reference semantics TPKE/PublicKey.cs:88-92 + 55-86).
    Slots are weighted by fresh random r_s inside the product so errors in
    different slots cannot cancel; a failing product bisects to isolate the
    bad slot(s), which report (False, None) and fall back to per-share
    pruning in the caller.
    """
    from .provider import batch_bisect_verify, get_backend

    if backend is None:
        backend = get_backend()
    if not jobs:
        return []
    entries = []
    # most slots choose the identical first-t+1 decryptor set, so the
    # Y = sum(lag_i * y_i) aggregate repeats verbatim — cache it per
    # key-set (id-keyed WITH a strong reference so a collected list can
    # never alias a new set's id) and pay ONE MSM per distinct set
    y_cache = _y_agg_cache_for(verification_keys)
    for job in jobs:
        idxs = [
            i
            for i, c in enumerate(job.lagrange_row)
            if c != 0 and job.u_by_validator[i] is not None
        ]
        cs = [job.lagrange_row[i] for i in idxs]
        us = [job.u_by_validator[i] for i in idxs]
        c_pt = backend.g1_msm(us, cs)
        ykey = tuple(zip(idxs, cs))
        y_pt = y_cache.get(ykey)
        if y_pt is None:
            ys = [verification_keys[i].y_i for i in idxs]
            y_pt = backend.g1_msm(ys, cs)
            if len(y_cache) < 4096:
                y_cache[ykey] = y_pt
        entries.append((c_pt, y_pt, job.h, job.w))

    # Cross-validator fold: in an era tick every validator holds a slot for
    # the SAME proposal ciphertext, so slots sharing (h, w) fold into ONE
    # pair of Millers — e(sum_s r_s C_s, h) * e(-sum_s r_s Y_s, w) — via a
    # per-group MSM over the per-slot random weights. At N validators this
    # cuts the grand product from 2*S to 2*(#ciphertexts) Millers. The
    # per-slot weights r_s stay random for EVERY slot (groups inherit their
    # randomness): a fixed error in one group could otherwise cancel a
    # fixed error in another deterministically.
    groups: dict = {}
    for t, e in enumerate(entries):
        groups.setdefault((e[2], e[3]), []).append(t)
    glist = list(groups.values())

    def fold_pairs(idx_list):
        pairs = []
        for t_list in idx_list:
            h, w = entries[t_list[0]][2], entries[t_list[0]][3]
            weights = [rng.randbelow((1 << 128) - 1) + 1 for _ in t_list]
            c_agg = backend.g1_msm([entries[t][0] for t in t_list], weights)
            y_agg = backend.g1_msm([entries[t][1] for t in t_list], weights)
            pairs.append((c_agg, h))
            pairs.append((bls.g1_neg(y_agg), w))
        return pairs

    def group_ok(gidx):
        return backend.pairing_check(fold_pairs([glist[g] for g in gidx]))

    g_oks = batch_bisect_verify(group_ok, len(glist))
    oks = [True] * len(entries)
    for gi, gok in enumerate(g_oks):
        if gok:
            continue
        # a failing ciphertext group bisects again over its own slots
        idxs = glist[gi]

        def slot_ok(sub):
            return backend.pairing_check(
                fold_pairs([[idxs[s]] for s in sub])
            )

        for si, sok in zip(idxs, batch_bisect_verify(slot_ok, len(idxs))):
            oks[si] = sok
    return [(ok, entries[t][0] if ok else None) for t, ok in enumerate(oks)]


@dataclass(frozen=True)
class TpkeVerificationKey:
    """Per-validator verification key Y_i = g1^{x_i}."""

    y_i: tuple

    def to_bytes(self) -> bytes:
        return bls.g1_to_bytes(self.y_i)

    @classmethod
    def from_bytes(cls, data: bytes) -> "TpkeVerificationKey":
        return cls(get_backend().g1_deserialize(data))


class TpkePrivateKey:
    """Validator key share x_i (reference: TPKE/PrivateKey.cs)."""

    def __init__(self, x_i: int, my_id: int):
        self.x_i = x_i % bls.R
        self.my_id = my_id

    def to_bytes(self) -> bytes:
        from ..utils.serialization import write_u32

        return bls.fr_to_bytes(self.x_i) + write_u32(self.my_id)

    @classmethod
    def from_bytes(cls, data: bytes) -> "TpkePrivateKey":
        from ..utils.serialization import Reader

        x = bls.fr_from_bytes(data[: bls.FR_BYTES])
        r = Reader(data[bls.FR_BYTES :])
        my_id = r.u32()
        r.assert_eof()
        return cls(x, my_id)

    @metrics.timed("crypto_tpke_part_decrypt")
    def decrypt_share(
        self, share: EncryptedShare, check: bool = True
    ) -> PartiallyDecryptedShare:
        """Validate ciphertext, then emit U_i = U^{x_i}
        (reference: TPKE/PrivateKey.cs:21-31)."""
        if check:
            h = _hash_uv_to_g2(share.u, share.v)
            ok = get_backend().pairing_check(
                [(bls.G1_GEN, share.w), (bls.g1_neg(share.u), h)]
            )
            if not ok:
                raise ValueError("invalid TPKE ciphertext")
        ui = get_backend().g1_mul(share.u, self.x_i)
        return PartiallyDecryptedShare(
            ui=ui, decryptor_id=self.my_id, share_id=share.share_id
        )


@metrics.timed("crypto_tpke_part_decrypt_batch")
def decrypt_shares_batch(
    priv: TpkePrivateKey, shares: List[EncryptedShare]
) -> List[PartiallyDecryptedShare]:
    """One node's decryption shares U_i = U^{x_i} for many ciphertexts in
    one threaded backend call — the era-tick shape (one share per ready ACS
    slot). Bit-identical to per-share decrypt_share(check=False); backends
    without the batch entry fall back to the scalar loop."""
    backend = get_backend()
    batch = getattr(backend, "g1_mul_batch", None)
    if batch is None or len(shares) < 8:
        return [priv.decrypt_share(s, check=False) for s in shares]
    uis = batch([s.u for s in shares], [priv.x_i] * len(shares))
    return [
        PartiallyDecryptedShare(
            ui=ui, decryptor_id=priv.my_id, share_id=s.share_id
        )
        for ui, s in zip(uis, shares)
    ]


class TpkeTrustedKeyGen:
    """Trusted dealer for devnets/tests (reference: TPKE/TrustedKeyGen.cs:7-41).

    Production key generation is the on-chain DKG
    (lachain_tpu.consensus.keygen), mirroring TrustlessKeygen.
    """

    def __init__(self, n: int, f: int, rng=secrets):
        if n <= 3 * f:
            raise ValueError("TPKE dealer requires n > 3f")
        coeffs = [rng.randbelow(bls.R) for _ in range(f + 1)]
        self.pub = TpkePublicKey(bls.g1_mul(bls.G1_GEN, coeffs[0]), t=f)
        self._shares = [
            bls.fr_eval_poly(coeffs, i + 1) for i in range(n)
        ]
        self.verification_keys = [
            TpkeVerificationKey(bls.g1_mul(bls.G1_GEN, s))
            for s in self._shares
        ]

    def private_key(self, i: int) -> TpkePrivateKey:
        return TpkePrivateKey(self._shares[i], i)
