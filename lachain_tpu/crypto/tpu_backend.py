"""TPU crypto backend: the device data plane behind the provider seam.

This is the third backend promised by `lachain_tpu.crypto.provider`
(role of the MCL-native provider swap in the reference,
/root/reference/src/Lachain.Crypto/CryptoProvider.cs:3-11 + ICrypto.cs:5-117):
consensus code calls the same interface, and the MSM-heavy batch work —
TPKE decryption-share verification + Lagrange combination, the era hot path
(HoneyBadger.cs:205-247 via TPKE/PublicKey.cs:55-92) — runs on the chip
through the Pallas era kernel (ops/pg1.py), while scalar ops, hashing and
pairings delegate to the host backend (native C++ if built, else the
Python oracle).

Design notes (SURVEY.md §7 hard part #4 — host<->TPU latency):
  * Opportunistic micro-batching: `tpke_era_verify_combine` runs whatever
    slots are ready RIGHT NOW (S >= 1); it never waits to fill a batch.
  * The Pallas kernel has static shapes: the slot count pads to the next
    power of two with fully-masked dummy slots, so at most log2(N)+1
    distinct (S_pad, K_pad) shapes ever compile per validator-set size.
  * Soundness: per-lane 64-bit random-linear-combination coefficients make
    every slot's aggregate equality independently random; all live slots
    fold into ONE grand multi-pairing (2 pairs per slot, shared final
    exponentiation). On failure the slot set is bisected — O(log S) pairing
    checks per bad slot, no extra kernel launches — and bad slots are
    reported invalid so callers fall back to the per-share host path.
"""
from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from . import bls12381 as bls
from ..utils import metrics


@dataclass
class CoinJob:
    """One common coin's pending share verification+combination work.

    sigma_by_signer: length-K row of partial-signature points (G2); None
        where validator j's share has not arrived (lane masked out).
    lagrange_row:    length-K row of Lagrange-at-0 coefficients; nonzero
        exactly on the t+1 shares chosen for the combination.
    h:               H_G2(msg) — the hashed coin id being signed.
    """

    sigma_by_signer: List[Optional[tuple]]
    lagrange_row: List[int]
    h: tuple


@dataclass
class EraSlotJob:
    """One ACS slot's pending verification+combination work.

    u_by_validator: length-K row of decryption-share points; None where
        validator j's share has not arrived (that lane is masked out).
    lagrange_row:   length-K row of Lagrange-at-0 coefficients; nonzero
        exactly on the t+1 shares chosen for the combination.
    h:              H_G2(U, V) for the slot's ciphertext.
    w:              the ciphertext's W point (G2).
    """

    u_by_validator: List[Optional[tuple]]
    lagrange_row: List[int]
    h: tuple
    w: tuple


class TpuBackend:
    """Provider backend routing era-shaped batch crypto through the TPU.

    Everything not explicitly overridden delegates to the host backend
    (`native` C++ when available, else the Python oracle) — pairings,
    hash-to-curve, deserialization, and single scalar muls are host ops by
    design (BASELINE.md: the host<->device split is the "sidecar" seam).
    """

    name = "tpu"

    def __init__(
        self,
        host_backend=None,
        pipeline=None,
        ts_pipeline=None,
        min_device_lanes=None,
    ):
        import os

        # below this many kernel lanes (S_pad x K_pad) an era batch runs on
        # the host pipeline even when a chip is present: per-call device
        # overhead (the axon tunnel charges ~0.1 s fixed) plus one-time
        # per-shape Mosaic compiles dwarf the host cost of tiny batches.
        #
        # Round-5 remeasurement (results_r05.json tpu_era_negative): after
        # the host gained the ADX multiplier, Straus/GLV MSM and
        # ciphertext-grouped pairing folds, the host flushes a FULL N=64
        # era batch (4096 lanes) in ~40 ms — under the tunnel's 88 ms
        # round-trip floor alone (kernel exec adds ~190 ms; the marshal,
        # the round-4 suspect, measures 28 ms vectorized). The default
        # therefore routes ALL era shapes to the host; the kernels stay
        # behind this env knob for hardware where the transport is not the
        # bound (co-located chips, multi-chip meshes) and for bench.py.
        if min_device_lanes is None:
            min_device_lanes = int(
                os.environ.get("LTPU_TPU_MIN_LANES", "1000000")
            )
        self.min_device_lanes = min_device_lanes
        if host_backend is None:
            try:
                from .native_backend import NativeBackend

                host_backend = NativeBackend()
            except Exception:
                from .provider import PythonBackend

                host_backend = PythonBackend()
        self._host = host_backend
        self._pipeline = pipeline  # lazy PallasEraPipeline (G1/TPKE)
        self._ts_pipeline = ts_pipeline  # lazy TsPallasPipeline (G2/coins)
        self._host_pipeline = None
        self._ts_host_pipeline = None
        self._y_cache: dict = {}
        # observability: proves the device path executed (asserted by tests
        # and exported through /metrics)
        self.era_calls = 0
        self.era_slots_total = 0
        self.ts_era_calls = 0
        self.ts_era_coins_total = 0
        self.device_msm_calls = 0

    def __getattr__(self, item):
        # only consulted for attributes NOT defined on TpuBackend: pairings,
        # hashing, g1/g2 ops, deserialization all ride the host backend
        return getattr(self._host, item)

    # -- device pipeline -----------------------------------------------------
    def _get_pipeline(self):
        if self._pipeline is None:
            import os

            import jax

            from ..ops.verify import HostEraPipeline, PallasEraPipeline

            # Pipeline selection:
            #   >1 device (pod slice, or CI's virtual 8-CPU mesh) -> the
            #     shard_mapped mesh pipeline (parallel/mesh.MeshEraPipeline):
            #     slots data-parallel, shares sequence-parallel.
            #   one real chip -> the VMEM-resident Pallas kernel.
            #   CPU single-device -> host-MSM emulation of the same contract
            #     (XLA-CPU compilation of the interpret-mode Pallas kernel
            #     costs ~390 s per static shape — unusable for CI).
            # LTPU_FORCE_PALLAS=1 / LTPU_DISABLE_MESH=1 override for debug.
            n_dev = len(jax.devices())
            if os.environ.get("LTPU_FORCE_PALLAS") == "1":
                self._pipeline = PallasEraPipeline(self._host)
            elif n_dev > 1 and os.environ.get("LTPU_DISABLE_MESH") != "1":
                from ..parallel.mesh import MeshEraPipeline

                self._pipeline = MeshEraPipeline(self._host)
            elif jax.default_backend() == "tpu":
                self._pipeline = PallasEraPipeline(self._host)
            else:
                self._pipeline = HostEraPipeline(self._host)
        return self._pipeline

    @property
    def era_dispatch_depth(self) -> int:
        """How many era-batch dispatches may be in flight at once: the mesh
        pipeline's host-staging double buffer admits MAX_INFLIGHT; every
        synchronous pipeline is 1 (dispatch == run)."""
        try:
            return int(getattr(self._get_pipeline(), "MAX_INFLIGHT", 1))
        except Exception:
            return 1

    def _get_ts_pipeline(self):
        if self._ts_pipeline is None:
            import os

            import jax

            from ..ops.verify import TsHostEraPipeline, TsPallasPipeline

            if (
                jax.default_backend() == "tpu"
                or os.environ.get("LTPU_FORCE_PALLAS") == "1"
            ):
                self._ts_pipeline = TsPallasPipeline(self._host)
            else:
                self._ts_pipeline = TsHostEraPipeline(self._host)
        return self._ts_pipeline

    def _device_ok(self, n: int) -> bool:
        if n < self.min_device_lanes:
            return False
        import os

        import jax

        return (
            jax.default_backend() == "tpu"
            or os.environ.get("LTPU_FORCE_PALLAS") == "1"
        )

    def g1_msm(self, points, scalars):
        """Large MSMs ride the Pallas G1 engine; small ones go host. This
        is how TPKE batch_verify_shares/full_decrypt and the TS key
        aggregates hit the chip without their callers changing — the same
        provider-seam trick the reference's MCL swap uses."""
        if not self._device_ok(len(points)):
            return self._host.g1_msm(points, scalars)
        try:
            return self._device_msm(points, scalars, g2=False)
        except Exception:
            metrics.inc("crypto_tpu_msm_fallbacks_total")
            return self._host.g1_msm(points, scalars)

    def g2_msm(self, points, scalars):
        """Large G2 MSMs (ThresholdSigner prune paths, TS combine at big N)
        ride the Pallas G2 engine (ops/pg2.py); small ones go host."""
        if not self._device_ok(len(points)):
            return self._host.g2_msm(points, scalars)
        try:
            return self._device_msm(points, scalars, g2=True)
        except Exception:
            metrics.inc("crypto_tpu_msm_fallbacks_total")
            return self._host.g2_msm(points, scalars)

    def _device_msm(self, points, scalars, g2: bool):
        import jax.numpy as jnp
        import numpy as np

        from ..ops import pg1, pg2
        from ..ops.verify import _pow2_at_least

        t0 = metrics.monotonic()
        n = len(points)
        n_pad = _pow2_at_least(n)
        inf = bls.G2_INF if g2 else bls.G1_INF
        pts = list(points) + [inf] * (n_pad - n)
        ss = [s % bls.R for s in scalars] + [0] * (n_pad - n)
        dig = jnp.asarray(pg1.digits_col(ss, 64))  # 256-bit windows
        if g2:
            fused = np.asarray(
                pg2.msm2_reduce_jit(
                    jnp.asarray(pg2.g2_pack(pts)), dig, n_pad
                )
            )
            pr = pg2.POINT2_ROWS
            out = pg2.g2_unpack(fused[:pr], fused[pr] != 0)
        else:
            fused = np.asarray(
                pg1.msm_reduce_jit(
                    jnp.asarray(pg1.g1_pack(pts)), dig, n_pad
                )
            )
            out = pg1.g1_unpack(fused[:132], fused[132] != 0)
        metrics.inc("crypto_tpu_device_msm_calls_total")
        metrics.observe_hist(
            "crypto_tpu_device_msm_seconds",
            metrics.monotonic() - t0,
            labels={"group": "g2" if g2 else "g1"},
        )
        self.device_msm_calls += 1
        return out[0]

    def _get_host_pipeline(self):
        if self._host_pipeline is None:
            from ..ops.verify import HostEraPipeline

            self._host_pipeline = HostEraPipeline(self._host)
        return self._host_pipeline

    def _get_ts_host_pipeline(self):
        if self._ts_host_pipeline is None:
            from ..ops.verify import TsHostEraPipeline

            self._ts_host_pipeline = TsHostEraPipeline(self._host)
        return self._ts_host_pipeline

    def _stable_y_points(self, vks, attr: str = "y_i") -> list:
        """One stable y-point list per verification-key list so the
        pipeline's device-side key marshal caches across eras (keyed by
        identity with a strong reference, same scheme as the pipeline).
        attr: "y_i" for TPKE verification keys, "y" for TS public keys."""
        key = (id(vks), attr)
        hit = self._y_cache.get(key)
        if hit is not None and hit[0] is vks:
            return hit[1]
        y_points = [getattr(vk, attr) for vk in vks]
        if len(self._y_cache) >= 8:
            self._y_cache.pop(next(iter(self._y_cache)))
        self._y_cache[key] = (vks, y_points)
        return y_points

    # -- the era-tick batch op ----------------------------------------------
    @metrics.timed("crypto_tpu_era_verify_combine")
    def tpke_era_verify_combine(
        self,
        jobs: Sequence[EraSlotJob],
        verification_keys,
        rng=secrets,
    ) -> List[Tuple[bool, Optional[tuple]]]:
        """Verify + combine every pending slot in ONE kernel launch.

        Returns per-job (all_shares_valid, combined_point). When a job's
        shares all verify, `combined` is U^x for the slot (feed the XOF pad
        directly — no separate full_decrypt needed). When the grand pairing
        check fails, bisection isolates the offending slot(s); those report
        (False, None) and the caller falls back to per-share host
        verification to prune the bad share(s).

        Reference semantics being batched: TPKE/PublicKey.cs:88-92 (per-
        share verify) + :55-86 (per-slot Lagrange combine), executed there
        serially per message via HoneyBadger.cs:205-247.
        """
        if not jobs:
            return []
        results = self._run_era_batch(
            jobs=jobs,
            rows=[j.u_by_validator for j in jobs],
            lags=[j.lagrange_row for j in jobs],
            y_points=self._stable_y_points(verification_keys),
            inf_point=bls.G1_INF,
            pipeline_getter=self._get_pipeline,
            host_pipeline_getter=self._get_host_pipeline,
            pairs_for=lambda job, agg: [
                (agg[0], job.h),
                (bls.g1_neg(agg[1]), job.w),
            ],
            rng=rng,
        )
        self.era_calls += 1
        self.era_slots_total += len(jobs)
        metrics.inc("crypto_tpu_era_kernel_calls_total")
        return results

    def tpke_era_verify_combine_async(
        self,
        jobs: Sequence[EraSlotJob],
        verification_keys,
        rng=secrets,
    ):
        """Two-phase tpke_era_verify_combine: does the host marshal +
        kernel dispatch now and returns a `finish()` closure producing the
        same per-job results.

        With the mesh pipeline the kernel runs asynchronously between
        dispatch and finish, so a caller holding several era chunks
        (consensus/crypto_batcher.flush) overlaps chunk e+1's host marshal
        with chunk e's sharded kernel — the double-buffer contract bounds
        in-flight dispatches to MeshEraPipeline.MAX_INFLIGHT. On host/
        Pallas pipelines the work happens at dispatch and finish() just
        returns it."""
        if not jobs:
            return lambda: []
        with metrics.measure("crypto_tpu_era_verify_combine"):
            fin = self._dispatch_era_batch(
                jobs=jobs,
                rows=[j.u_by_validator for j in jobs],
                lags=[j.lagrange_row for j in jobs],
                y_points=self._stable_y_points(verification_keys),
                inf_point=bls.G1_INF,
                pipeline_getter=self._get_pipeline,
                host_pipeline_getter=self._get_host_pipeline,
                pairs_for=lambda job, agg: [
                    (agg[0], job.h),
                    (bls.g1_neg(agg[1]), job.w),
                ],
                rng=rng,
            )

        def finish():
            with metrics.measure("crypto_tpu_era_verify_combine"):
                results = fin()
            self.era_calls += 1
            self.era_slots_total += len(jobs)
            metrics.inc("crypto_tpu_era_kernel_calls_total")
            return results

        return finish

    def _run_era_batch(
        self, jobs, rows, lags, y_points, inf_point, pipeline_getter,
        host_pipeline_getter, pairs_for, rng,
    ) -> List[Tuple[bool, Optional[tuple]]]:
        return self._dispatch_era_batch(
            jobs=jobs, rows=rows, lags=lags, y_points=y_points,
            inf_point=inf_point, pipeline_getter=pipeline_getter,
            host_pipeline_getter=host_pipeline_getter, pairs_for=pairs_for,
            rng=rng,
        )()

    def _dispatch_era_batch(
        self, jobs, rows, lags, y_points, inf_point, pipeline_getter,
        host_pipeline_getter, pairs_for, rng,
    ):
        """Shared engine for both era ops: mask absent lanes, pad the slot
        axis to a power of two with fully-masked dummy slots (bounds the
        static kernel shapes to log2(N)+1 per K), run the pipeline, then
        grand-multi-pair + bisect. `pairs_for(job, agg)` yields the two
        pairing pairs encoding that slot's verification equality; each
        slot's equality is independently randomized by its own RLC
        coefficients, so a pairing product over any subset is a sound
        batch check for that subset.

        Returns a finish() closure: pipelines exposing `dispatch_era`
        (parallel/mesh.MeshEraPipeline) run their kernel asynchronously
        until finish() blocks; synchronous pipelines complete at dispatch
        and finish() just post-processes."""
        from ..ops.verify import _pow2_at_least

        s = len(jobs)
        if s == 0:
            return lambda: []
        k = len(y_points)
        for row, lag in zip(rows, lags):
            if len(row) != k or len(lag) != k:
                raise ValueError(f"era job rows must have length {k}")
        slots = []
        masks = []
        for row, lag in zip(rows, lags):
            masks.append([p is not None for p in row])
            slots.append(
                ([p if p is not None else inf_point for p in row], list(lag))
            )
        s_pad = _pow2_at_least(s)
        for _ in range(s_pad - s):
            slots.append(([inf_point] * k, [0] * k))
            masks.append([False] * k)
        lanes = s_pad * _pow2_at_least(k)
        if lanes >= self.min_device_lanes:
            pipeline = pipeline_getter()
            path = "device"
        else:
            pipeline = host_pipeline_getter()
            path = "host"
        # pad-waste: fraction of the padded slot axis burnt on fully-masked
        # dummy slots — the number that explains bench variance and tunes
        # the batcher's max_slots_per_call
        metrics.inc("crypto_tpu_era_route_total", labels={"path": path})
        metrics.inc("crypto_tpu_era_slots_padded_total", s_pad - s)
        metrics.observe_hist(  # lint-allow: metric-name dimensionless slot-count distribution
            "crypto_tpu_era_batch_slots",
            s,
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
        )
        metrics.observe_hist(  # lint-allow: metric-name dimensionless waste-fraction distribution
            "crypto_tpu_era_pad_waste",
            1.0 - s / s_pad,
            buckets=(0.0, 0.1, 0.2, 0.3, 0.4, 0.5),
        )
        t0 = metrics.monotonic()
        dispatch = getattr(pipeline, "dispatch_era", None)
        if dispatch is not None:
            pipeline_fin = dispatch(slots, y_points, rng, masks=masks)
        else:
            ran = pipeline.run_era(slots, y_points, rng, masks=masks)
            pipeline_fin = lambda: ran  # noqa: E731

        def finish():
            aggs, _rlc = pipeline_fin()
            metrics.observe_hist(
                "crypto_tpu_era_pipeline_seconds",
                metrics.monotonic() - t0,
                labels={"path": path},
            )

            def group_ok(idx: List[int]) -> bool:
                pairs = []
                for i in idx:
                    pairs.extend(pairs_for(jobs[i], aggs[i]))
                return self._host.pairing_check(pairs)

            from .provider import batch_bisect_verify

            ok_flags = batch_bisect_verify(group_ok, s)
            return [
                (ok, aggs[i][2] if ok else None)
                for i, ok in enumerate(ok_flags)
            ]

        return finish

    @metrics.timed("crypto_tpu_ts_era_verify_combine")
    def ts_era_verify_combine(
        self,
        jobs: Sequence[CoinJob],
        ts_public_keys,
        rng=secrets,
    ) -> List[Tuple[bool, Optional[tuple]]]:
        """Verify + combine every pending common coin in ONE kernel launch.

        `ts_public_keys` is the per-validator TS key list (TsPublicKey,
        G1). Returns per-coin (all_shares_valid, combined_sigma). Same
        grand-multi-pairing + slot-bisection structure as
        `tpke_era_verify_combine`; the verify equality per coin is
        e(g1, sum c sigma_j) == e(sum c Y_j, H(coin id)).

        Reference semantics being batched: ThresholdSigner.cs:45-95 (2
        pairings per share) + PublicKeySet.cs:35-44 (serial G2 Lagrange),
        via CommonCoin.cs:75-96.
        """
        if not jobs:
            return []
        results = self._run_era_batch(
            jobs=jobs,
            rows=[j.sigma_by_signer for j in jobs],
            lags=[j.lagrange_row for j in jobs],
            y_points=self._stable_y_points(ts_public_keys, attr="y"),
            inf_point=bls.G2_INF,
            pipeline_getter=self._get_ts_pipeline,
            host_pipeline_getter=self._get_ts_host_pipeline,
            pairs_for=lambda job, agg: [
                (bls.G1_GEN, agg[0]),
                (bls.g1_neg(agg[1]), job.h),
            ],
            rng=rng,
        )
        self.ts_era_calls += 1
        self.ts_era_coins_total += len(jobs)
        metrics.inc("crypto_tpu_ts_era_kernel_calls_total")
        return results
