"""Crypto backend provider seam.

Parity with the reference's provider seam (`ICrypto` / `CryptoProvider`,
/root/reference/src/Lachain.Crypto/CryptoProvider.cs:3-11 and ICrypto.cs:5-117):
all threshold-crypto consumers go through a small backend interface so the
implementation can be swapped without touching consensus code.

Three backends exist:
  * ``python``  — the pure-Python oracle (lachain_tpu.crypto.bls12381).
  * ``native``  — C++ libbls381 via ctypes (fast host path; MCL equivalent).
  * ``tpu``     — Pallas era kernels for the MSM-heavy batch ops
                  (crypto/tpu_backend.py over ops/pg1.py); pairings,
                  hashing and scalar ops delegate to native/python.

The batch operations are the TPU-first redesign: where the reference verifies
each decryption share with 2 pairings (TPKE/PublicKey.cs:88-92, executed
serially per message), we reduce a whole batch to ONE pairing equality via a
random-linear-combination MSM, so the hot op becomes a batched G1/G2 MSM —
exactly the shape TPUs are good at.
"""
from __future__ import annotations

import os
from typing import List, Sequence, Tuple

from . import bls12381 as bls


class PythonBackend:
    """Oracle backend: direct calls into the pure-Python BLS12-381 module."""

    name = "python"

    # -- group ops -----------------------------------------------------------
    def g1_msm(self, points: Sequence[tuple], scalars: Sequence[int]) -> tuple:
        acc = bls.G1_INF
        for pt, s in zip(points, scalars):
            acc = bls.g1_add(acc, bls.g1_mul(pt, s))
        return acc

    def g2_msm(self, points: Sequence[tuple], scalars: Sequence[int]) -> tuple:
        acc = bls.G2_INF
        for pt, s in zip(points, scalars):
            acc = bls.g2_add(acc, bls.g2_mul(pt, s))
        return acc

    def g1_mul(self, point: tuple, scalar: int) -> tuple:
        return bls.g1_mul(point, scalar)

    def g2_mul(self, point: tuple, scalar: int) -> tuple:
        return bls.g2_mul(point, scalar)

    # -- pairings ------------------------------------------------------------
    def pairing_check(
        self, pairs: Sequence[Tuple[tuple, tuple]]
    ) -> bool:
        """Prod e(Pi, Qi) == 1 with one shared final exponentiation."""
        return bls.fp12_eq_one(bls.multi_pairing(pairs))

    def pairings_equal(self, p_a, q_a, p_b, q_b) -> bool:
        return bls.pairings_equal(p_a, q_a, p_b, q_b)

    # -- hashing -------------------------------------------------------------
    def hash_to_g1(self, msg: bytes, domain: bytes = b"LTPU-G1") -> tuple:
        return bls.hash_to_g1(msg, domain)

    def hash_to_g2(self, msg: bytes, domain: bytes = b"LTPU-G2") -> tuple:
        return bls.hash_to_g2(msg, domain)

    # -- wire deserialization (on-curve + subgroup validation) ---------------
    def g1_deserialize(self, data: bytes) -> tuple:
        return bls.g1_from_bytes(data, check_subgroup=True)

    def g2_deserialize(self, data: bytes) -> tuple:
        return bls.g2_from_bytes(data, check_subgroup=True)

    # -- era-shaped batch ops ------------------------------------------------
    def tpke_era_verify_combine(self, jobs, verification_keys, rng=None):
        """Whole-tick TPKE verify+combine (one grand multi-pairing); same
        contract as the TPU backend's kernel-backed version."""
        import secrets as _secrets

        from . import tpke

        return tpke.era_verify_combine_host(
            jobs, verification_keys, backend=self, rng=rng or _secrets
        )


def batch_bisect_verify(group_ok, n: int) -> List[bool]:
    """Shared bisection driver for random-linear-combination batch checks.

    `group_ok(idx_list) -> bool` must be a probabilistic check that a subset of
    items is all-valid (e.g. an RLC pairing equality). Returns per-item
    validity; cost is one group check when everything is valid, and
    O(log n) group checks per invalid item otherwise. Used by both TPKE
    decryption-share verification and threshold-signature share verification
    so the soundness-critical logic lives in exactly one place.
    """
    results = [False] * n

    def solve(idx):
        if group_ok(idx):
            for i in idx:
                results[i] = True
            return
        if len(idx) == 1:
            return
        mid = len(idx) // 2
        solve(idx[:mid])
        solve(idx[mid:])

    if n:
        solve(list(range(n)))
    return results


def deserialize_batch_g1(datas, backend=None, rng=None):
    """Parse many G1 encodings; invalid entries come back as None.

    Every point gets a SOUND per-point subgroup check (the backend's checked
    deserializer). An aggregate random-linear-combination check is NOT sound
    here: E(Fp)'s cofactor has small prime factors (3 and 11 for G1; 13/23
    for G2's twist), so a random weight annihilates an order-3 torsion
    component with probability 1/3 — and a rogue share surviving into a
    combination yields divergent plaintexts across honest validators. The
    batching wins that ARE safe (and used): parse lazily (only the t+1
    CHOSEN shares pay the check, not all N arrivals) and memoize by exact
    wire bytes (identical bytes validate once — in the in-process simulator
    all N validators receive the same broadcast bytes; a real node sees the
    same share via gossip redundancy and replays).
    """
    backend = backend or get_backend()
    return [_memo_parse(d, backend.g1_deserialize, _G1_MEMO) for d in datas]


def deserialize_batch_g2(datas, backend=None, rng=None):
    """G2 analogue of deserialize_batch_g1 (same per-point soundness)."""
    backend = backend or get_backend()
    return [_memo_parse(d, backend.g2_deserialize, _G2_MEMO) for d in datas]


# bytes -> validated point tuple (or None for invalid encodings; points are
# immutable tuples so sharing across callers is safe). Bounded: cleared
# wholesale at the cap — distinct entries per era are few thousand, so the
# cap is hit rarely and a cold restart only re-validates.
_G1_MEMO: dict = {}
_G2_MEMO: dict = {}
_MEMO_CAP = 1 << 18


def _memo_parse(data, parse, memo):
    hit = memo.get(data)
    if hit is not None or data in memo:
        return hit
    try:
        pt = parse(data)
    except (ValueError, AssertionError):
        pt = None
    if len(memo) >= _MEMO_CAP:
        memo.clear()
    memo[bytes(data)] = pt
    return pt


def select_distinct(shares, key, count: int):
    """First `count` shares with distinct `key(share)`, or None if impossible.

    Used before Lagrange combination: duplicates are skipped (not an error)
    so a caller holding [id0, id0, id1, id2] can still combine t+1 = 3
    distinct shares.
    """
    seen = set()
    out = []
    for s in shares:
        k = key(s)
        if k in seen:
            continue
        seen.add(k)
        out.append(s)
        if len(out) == count:
            return out
    return None


_BACKEND = None


def get_backend():
    """Singleton accessor (role of CryptoProvider.GetCrypto in the reference).

    Resolution order: $LACHAIN_TPU_BACKEND if set, else native C++ if the
    shared library built, else the Python oracle.
    """
    global _BACKEND
    if _BACKEND is not None:
        return _BACKEND
    choice = os.environ.get("LACHAIN_TPU_BACKEND", "auto")
    if choice == "tpu":
        from .tpu_backend import TpuBackend

        _BACKEND = TpuBackend()
        return _BACKEND
    if choice in ("native", "auto"):
        try:
            from .native_backend import NativeBackend

            _BACKEND = NativeBackend()
            return _BACKEND
        except Exception:
            if choice == "native":
                raise
    _BACKEND = PythonBackend()
    return _BACKEND


def set_backend(backend) -> None:
    global _BACKEND
    _BACKEND = backend
