"""BLS12-381 pairing-friendly curve — pure-Python reference implementation.

This is the *oracle* backend for lachain-tpu's threshold cryptography. It is
deliberately written for clarity and verifiability, not speed: the fast paths
are (a) the native C++ backend (lachain_tpu/crypto/native) and (b) the batched
JAX kernels (lachain_tpu/ops). Both are conformance-tested against this module.

Role parity with the reference implementation (see /root/reference):
  - MCL.BLS12_381.Net `Fr`, `G1`, `G2`, `GT`, `GT.Pairing`, `G2.SetHashOf`
    used by src/Lachain.Crypto/TPKE/PublicKey.cs and
    src/Lachain.Crypto/ThresholdSignature/PublicKeySet.cs.
  - `MclBls12381.EvaluatePolynomial` / `LagrangeInterpolate`
    (src/Lachain.Crypto/MclBls12381.cs) -> `fr_eval_poly` / `fr_lagrange_at_0`
    plus the group-element interpolation helpers here.

Design notes
------------
* Field elements are plain ints (Fp, Fr) or tuples of ints (Fp2/Fp6/Fp12);
  tuples + module-level functions are the fastest idiomatic pure-Python form.
* All derived constants (cofactors, Frobenius coefficients, final-exponent
  digits) are COMPUTED at import from the curve parameter X_PARAM and asserted,
  so there are no hand-transcribed magic numbers beyond p, r, the generators
  and X_PARAM itself (each validated by on-curve / identity asserts below).
* The pairing is the optimal ate pairing computed on the untwisted curve
  E(Fp12) with textbook affine line functions: slowest possible, easiest to
  audit. `multi_pairing` shares the final exponentiation.
* Subgroup membership: G1/G2 deserialization checks r*P == inf.
"""
from __future__ import annotations

import functools
import hashlib
import math
from typing import List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------

# BLS parameter ("x" / "z" in the literature). Everything else derives from it.
X_PARAM = -0xD201000000010000

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# Sanity: p and r follow the BLS12 family formulas.
assert R == X_PARAM**4 - X_PARAM**2 + 1
assert (X_PARAM - 1) ** 2 % 3 == 0
assert P == (X_PARAM - 1) ** 2 * (X_PARAM**4 - X_PARAM**2 + 1) // 3 + X_PARAM
assert P % 6 == 1

B_G1 = 4  # E : y^2 = x^3 + 4 over Fp
# E': y^2 = x^3 + 4*(1+u) over Fp2 (M-twist), xi = 1 + u
XI = (1, 1)

# Trace of Frobenius over Fp: #E(Fp) = p + 1 - t, t = x + 1 for BLS12.
TRACE = X_PARAM + 1
N_G1 = P + 1 - TRACE
assert N_G1 % R == 0
H_G1 = N_G1 // R  # G1 cofactor

# Curve order over Fp2 and the sextic-twist order (self-derived, see SURVEY.md
# §7 "hard parts": avoids transcribing the 508-bit G2 cofactor by hand).
_T2 = TRACE * TRACE - 2 * P  # trace over Fp2
_FSQ = (4 * P * P - _T2 * _T2) // 3
_F = math.isqrt(_FSQ)
assert _F * _F == _FSQ
# The two sextic twists have orders p^2 + 1 - (+-3f + t2)/2; pick the r-divisible one.
_cand1 = P * P + 1 - (3 * _F + _T2) // 2
_cand2 = P * P + 1 - (-3 * _F + _T2) // 2
if _cand1 % R == 0:
    N_G2 = _cand1
else:
    assert _cand2 % R == 0
    N_G2 = _cand2
H_G2 = N_G2 // R  # G2 cofactor

# ---------------------------------------------------------------------------
# Fp — arithmetic mod p on plain ints
# ---------------------------------------------------------------------------


def fp_inv(a: int) -> int:
    # 3-arg pow with exponent -1 is extended-gcd under the hood: ~40x
    # faster than the Fermat modexp for a 381-bit modulus (9 us vs 340 us
    # measured) — this sits under every point normalization on the host
    return pow(a, -1, P) if a % P else 0


def fp_sqrt(a: int) -> Optional[int]:
    """Square root in Fp (p ≡ 3 mod 4), or None if a is not a QR."""
    s = pow(a, (P + 1) // 4, P)
    return s if s * s % P == a % P else None


# ---------------------------------------------------------------------------
# Fp2 = Fp[u]/(u^2+1) — elements are (a0, a1) meaning a0 + a1*u
# ---------------------------------------------------------------------------

FP2_ZERO = (0, 0)
FP2_ONE = (1, 0)


def fp2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fp2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fp2_neg(a):
    return (-a[0] % P, -a[1] % P)


def fp2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = a0 * b0
    t1 = a1 * b1
    t2 = (a0 + a1) * (b0 + b1)
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def fp2_sqr(a):
    a0, a1 = a
    t = a0 * a1
    return ((a0 + a1) * (a0 - a1) % P, (t + t) % P)


def fp2_muls(a, s: int):
    return (a[0] * s % P, a[1] * s % P)


def fp2_conj(a):
    return (a[0], -a[1] % P)


def fp2_inv(a):
    a0, a1 = a
    norm = (a0 * a0 + a1 * a1) % P
    ninv = fp_inv(norm)
    return (a0 * ninv % P, -a1 * ninv % P)


def fp2_pow(a, e: int):
    result = FP2_ONE
    base = a
    while e:
        if e & 1:
            result = fp2_mul(result, base)
        base = fp2_sqr(base)
        e >>= 1
    return result


def fp2_sqrt(a) -> Optional[Tuple[int, int]]:
    """Square root in Fp2 via the norm trick; None if not a QR."""
    a0, a1 = a[0] % P, a[1] % P
    if a1 == 0:
        s = fp_sqrt(a0)
        if s is not None:
            return (s, 0)
        # a0 = -b^2  =>  sqrt = b*u
        t = fp_sqrt(-a0 % P)
        if t is not None:
            return (0, t)
        return None
    n = (a0 * a0 + a1 * a1) % P
    s = fp_sqrt(n)
    if s is None:
        return None
    inv2 = fp_inv(2)
    t = (a0 + s) * inv2 % P
    lam = fp_sqrt(t)
    if lam is None:
        t = (a0 - s) * inv2 % P
        lam = fp_sqrt(t)
        if lam is None:
            return None
    y0 = lam
    y1 = a1 * fp_inv((2 * lam) % P) % P
    res = (y0, y1)
    return res if fp2_sqr(res) == (a0, a1) else None


# ---------------------------------------------------------------------------
# Fp6 = Fp2[v]/(v^3 - xi) — elements are (c0, c1, c2), each in Fp2
# ---------------------------------------------------------------------------

FP6_ZERO = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE = (FP2_ONE, FP2_ZERO, FP2_ZERO)


def _mul_xi(a):  # a * (1 + u)
    a0, a1 = a
    return ((a0 - a1) % P, (a0 + a1) % P)


def fp6_add(a, b):
    return (fp2_add(a[0], b[0]), fp2_add(a[1], b[1]), fp2_add(a[2], b[2]))


def fp6_sub(a, b):
    return (fp2_sub(a[0], b[0]), fp2_sub(a[1], b[1]), fp2_sub(a[2], b[2]))


def fp6_neg(a):
    return (fp2_neg(a[0]), fp2_neg(a[1]), fp2_neg(a[2]))


def fp6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t00 = fp2_mul(a0, b0)
    t11 = fp2_mul(a1, b1)
    t22 = fp2_mul(a2, b2)
    c0 = fp2_add(t00, _mul_xi(fp2_add(fp2_mul(a1, b2), fp2_mul(a2, b1))))
    c1 = fp2_add(fp2_add(fp2_mul(a0, b1), fp2_mul(a1, b0)), _mul_xi(t22))
    c2 = fp2_add(fp2_add(fp2_mul(a0, b2), fp2_mul(a2, b0)), t11)
    return (c0, c1, c2)


def fp6_sqr(a):
    return fp6_mul(a, a)


def fp6_mul_by_v(a):  # a * v  (shift with v^3 = xi)
    return (_mul_xi(a[2]), a[0], a[1])


def fp6_inv(a):
    a0, a1, a2 = a
    t0 = fp2_sub(fp2_sqr(a0), _mul_xi(fp2_mul(a1, a2)))
    t1 = fp2_sub(_mul_xi(fp2_sqr(a2)), fp2_mul(a0, a1))
    t2 = fp2_sub(fp2_sqr(a1), fp2_mul(a0, a2))
    f = fp2_add(
        fp2_mul(a0, t0),
        _mul_xi(fp2_add(fp2_mul(a2, t1), fp2_mul(a1, t2))),
    )
    finv = fp2_inv(f)
    return (fp2_mul(t0, finv), fp2_mul(t1, finv), fp2_mul(t2, finv))


# ---------------------------------------------------------------------------
# Fp12 = Fp6[w]/(w^2 - v) — elements are (c0, c1), each in Fp6
# ---------------------------------------------------------------------------

FP12_ONE = (FP6_ONE, FP6_ZERO)
FP12_ZERO = (FP6_ZERO, FP6_ZERO)


def fp12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1)), t0), t1)
    return (c0, c1)


def fp12_sqr(a):
    return fp12_mul(a, a)


def fp12_add(a, b):
    return (fp6_add(a[0], b[0]), fp6_add(a[1], b[1]))


def fp12_sub(a, b):
    return (fp6_sub(a[0], b[0]), fp6_sub(a[1], b[1]))


def fp12_neg(a):
    return (fp6_neg(a[0]), fp6_neg(a[1]))


def fp12_conj(a):  # Frobenius^6: w -> -w
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a):
    a0, a1 = a
    f = fp6_sub(fp6_sqr(a0), fp6_mul_by_v(fp6_sqr(a1)))
    finv = fp6_inv(f)
    return (fp6_mul(a0, finv), fp6_neg(fp6_mul(a1, finv)))


def fp12_pow(a, e: int):
    if e < 0:
        return fp12_pow(fp12_inv(a), -e)
    result = FP12_ONE
    base = a
    while e:
        if e & 1:
            result = fp12_mul(result, base)
        base = fp12_sqr(base)
        e >>= 1
    return result


def fp12_eq_one(a) -> bool:
    return a == FP12_ONE


# Frobenius coefficients gamma_i = xi^((p-1)*i/6), i = 1..5 (computed, not
# transcribed — mirrors how MCL bakes them in at build time).
_GAMMA = [FP2_ONE] + [fp2_pow(XI, (P - 1) * i // 6) for i in range(1, 6)]


def fp12_frobenius(a):
    """a^p on Fp12 in the 2-over-3 tower basis {1, v, v^2, w, vw, v^2 w}."""
    (a00, a01, a02), (a10, a11, a12) = a
    c00 = fp2_conj(a00)
    c01 = fp2_mul(fp2_conj(a01), _GAMMA[2])
    c02 = fp2_mul(fp2_conj(a02), _GAMMA[4])
    c10 = fp2_mul(fp2_conj(a10), _GAMMA[1])
    c11 = fp2_mul(fp2_conj(a11), _GAMMA[3])
    c12 = fp2_mul(fp2_conj(a12), _GAMMA[5])
    return ((c00, c01, c02), (c10, c11, c12))


def fp12_frobenius_n(a, n: int):
    for _ in range(n % 12):
        a = fp12_frobenius(a)
    return a


# ---------------------------------------------------------------------------
# Elliptic-curve point ops.
# G1: E(Fp),  Jacobian tuples (X, Y, Z) of ints;  Z == 0 means infinity.
# G2: E'(Fp2), Jacobian tuples (X, Y, Z) of Fp2;   Z == (0,0) means infinity.
# ---------------------------------------------------------------------------

G1_INF = (0, 1, 0)
G2_INF = (FP2_ZERO, FP2_ONE, FP2_ZERO)

G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
    1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
    FP2_ONE,
)


def g1_is_inf(pt) -> bool:
    return pt[2] % P == 0


def g1_dbl(pt):
    X1, Y1, Z1 = pt
    if Z1 % P == 0 or Y1 % P == 0:
        return G1_INF
    A = X1 * X1 % P
    B = Y1 * Y1 % P
    C = B * B % P
    D = 2 * ((X1 + B) * (X1 + B) - A - C) % P
    E = 3 * A % P
    F = E * E % P
    X3 = (F - 2 * D) % P
    Y3 = (E * (D - X3) - 8 * C) % P
    Z3 = 2 * Y1 * Z1 % P
    return (X3, Y3, Z3)


def g1_add(p1, p2):
    if p1[2] % P == 0:
        return p2
    if p2[2] % P == 0:
        return p1
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    Z1Z1 = Z1 * Z1 % P
    Z2Z2 = Z2 * Z2 % P
    U1 = X1 * Z2Z2 % P
    U2 = X2 * Z1Z1 % P
    S1 = Y1 * Z2 * Z2Z2 % P
    S2 = Y2 * Z1 * Z1Z1 % P
    if U1 == U2:
        if S1 == S2:
            return g1_dbl(p1)
        return G1_INF
    H = (U2 - U1) % P
    I = 4 * H * H % P
    J = H * I % P
    rr = 2 * (S2 - S1) % P
    V = U1 * I % P
    X3 = (rr * rr - J - 2 * V) % P
    Y3 = (rr * (V - X3) - 2 * S1 * J) % P
    Z3 = 2 * H * Z1 * Z2 % P
    return (X3, Y3, Z3)


def g1_neg(pt):
    return (pt[0], -pt[1] % P, pt[2])


def g1_mul(pt, k: int):
    k %= N_G1
    result = G1_INF
    addend = pt
    while k:
        if k & 1:
            result = g1_add(result, addend)
        addend = g1_dbl(addend)
        k >>= 1
    return result


def g1_to_affine(pt):
    X, Y, Z = pt
    if Z % P == 0:
        return None  # infinity
    zinv = fp_inv(Z % P)
    z2 = zinv * zinv % P
    return (X * z2 % P, Y * z2 * zinv % P)


def g1_from_affine(aff):
    if aff is None:
        return G1_INF
    return (aff[0] % P, aff[1] % P, 1)


def g1_eq(a, b) -> bool:
    if g1_is_inf(a) or g1_is_inf(b):
        return g1_is_inf(a) and g1_is_inf(b)
    return g1_to_affine(a) == g1_to_affine(b)


def g1_is_on_curve(pt) -> bool:
    if g1_is_inf(pt):
        return True
    aff = g1_to_affine(pt)
    x, y = aff
    return (y * y - (x * x * x + B_G1)) % P == 0


def g2_is_inf(pt) -> bool:
    return pt[2][0] % P == 0 and pt[2][1] % P == 0


def g2_dbl(pt):
    X1, Y1, Z1 = pt
    if g2_is_inf(pt) or Y1 == FP2_ZERO:
        return G2_INF
    A = fp2_sqr(X1)
    B = fp2_sqr(Y1)
    C = fp2_sqr(B)
    D = fp2_muls(fp2_sub(fp2_sub(fp2_sqr(fp2_add(X1, B)), A), C), 2)
    E = fp2_muls(A, 3)
    F = fp2_sqr(E)
    X3 = fp2_sub(F, fp2_muls(D, 2))
    Y3 = fp2_sub(fp2_mul(E, fp2_sub(D, X3)), fp2_muls(C, 8))
    Z3 = fp2_muls(fp2_mul(Y1, Z1), 2)
    return (X3, Y3, Z3)


def g2_add(p1, p2):
    if g2_is_inf(p1):
        return p2
    if g2_is_inf(p2):
        return p1
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    Z1Z1 = fp2_sqr(Z1)
    Z2Z2 = fp2_sqr(Z2)
    U1 = fp2_mul(X1, Z2Z2)
    U2 = fp2_mul(X2, Z1Z1)
    S1 = fp2_mul(fp2_mul(Y1, Z2), Z2Z2)
    S2 = fp2_mul(fp2_mul(Y2, Z1), Z1Z1)
    if U1 == U2:
        if S1 == S2:
            return g2_dbl(p1)
        return G2_INF
    H = fp2_sub(U2, U1)
    I = fp2_muls(fp2_sqr(H), 4)
    J = fp2_mul(H, I)
    rr = fp2_muls(fp2_sub(S2, S1), 2)
    V = fp2_mul(U1, I)
    X3 = fp2_sub(fp2_sub(fp2_sqr(rr), J), fp2_muls(V, 2))
    Y3 = fp2_sub(fp2_mul(rr, fp2_sub(V, X3)), fp2_muls(fp2_mul(S1, J), 2))
    Z3 = fp2_muls(fp2_mul(fp2_mul(H, Z1), Z2), 2)
    return (X3, Y3, Z3)


def g2_neg(pt):
    return (pt[0], fp2_neg(pt[1]), pt[2])


def g2_mul(pt, k: int):
    if k < 0:
        return g2_mul(g2_neg(pt), -k)
    result = G2_INF
    addend = pt
    while k:
        if k & 1:
            result = g2_add(result, addend)
        addend = g2_dbl(addend)
        k >>= 1
    return result


def g2_to_affine(pt):
    X, Y, Z = pt
    if g2_is_inf(pt):
        return None
    zinv = fp2_inv(Z)
    z2 = fp2_sqr(zinv)
    return (fp2_mul(X, z2), fp2_mul(fp2_mul(Y, z2), zinv))


def g2_from_affine(aff):
    if aff is None:
        return G2_INF
    return (aff[0], aff[1], FP2_ONE)


def g2_eq(a, b) -> bool:
    if g2_is_inf(a) or g2_is_inf(b):
        return g2_is_inf(a) and g2_is_inf(b)
    return g2_to_affine(a) == g2_to_affine(b)


def g2_is_on_curve(pt) -> bool:
    if g2_is_inf(pt):
        return True
    x, y = g2_to_affine(pt)
    b = fp2_muls(XI, B_G1)
    return fp2_sub(fp2_sqr(y), fp2_add(fp2_mul(fp2_sqr(x), x), b)) == FP2_ZERO


assert g1_is_on_curve(G1_GEN)
assert g2_is_on_curve(G2_GEN)
assert g1_is_inf(g1_mul(G1_GEN, R))
assert g2_is_inf(g2_mul(G2_GEN, R))


def g1_in_subgroup(pt) -> bool:
    return g1_is_on_curve(pt) and g1_is_inf(g1_mul(pt, R))


def g2_in_subgroup(pt) -> bool:
    return g2_is_on_curve(pt) and g2_is_inf(g2_mul(pt, R))


# ---------------------------------------------------------------------------
# Pairing — optimal ate on the untwisted curve E(Fp12), affine line functions.
# Mirrors the role of GT.Pairing in the reference (MCL binding); the formulas
# are the textbook ones so this module can serve as the conformance oracle.
# ---------------------------------------------------------------------------

# Untwist: psi(x, y) = (x / w^2, y / w^3), w^6 = xi.  Elements of E(Fp12) are
# affine pairs of Fp12 or None for infinity.

# 1/w^2 = w^10 / xi  and 1/w^3 = w^9 / xi in Fp12... computed directly instead:
# w^2 = v (Fp6 element 0 + 1*v + 0*v^2 embedded in c0), w^3 = v*w.
_W2 = ((FP2_ZERO, FP2_ONE, FP2_ZERO), FP6_ZERO)  # w^2 = v
_W3 = (FP6_ZERO, (FP2_ZERO, FP2_ONE, FP2_ZERO))  # w^3 = v*w
_W2_INV = fp12_inv(_W2)
_W3_INV = fp12_inv(_W3)


def _fp2_to_fp12(a):
    return ((a, FP2_ZERO, FP2_ZERO), FP6_ZERO)


def _fp_to_fp12(a: int):
    return (((a % P, 0), FP2_ZERO, FP2_ZERO), FP6_ZERO)


def _untwist(q2_affine):
    """Map an affine G2 (twist) point into E(Fp12) affine coordinates."""
    if q2_affine is None:
        return None
    x, y = q2_affine
    return (
        fp12_mul(_fp2_to_fp12(x), _W2_INV),
        fp12_mul(_fp2_to_fp12(y), _W3_INV),
    )


def _e12_add(p1, p2):
    """Affine addition on E(Fp12): y^2 = x^3 + 4."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == y2:
            # doubling
            if y1 == FP12_ZERO:
                return None
            lam = fp12_mul(
                fp12_mul(fp12_sqr(x1), _fp_to_fp12(3)),
                fp12_inv(fp12_mul(y1, _fp_to_fp12(2))),
            )
        else:
            return None
    else:
        lam = fp12_mul(fp12_sub(y2, y1), fp12_inv(fp12_sub(x2, x1)))
    x3 = fp12_sub(fp12_sub(fp12_sqr(lam), x1), x2)
    y3 = fp12_sub(fp12_mul(lam, fp12_sub(x1, x3)), y1)
    return (x3, y3)


def _line(t, q, pxy):
    """Evaluate the line through t and q (affine E(Fp12)) at P=(px,py) in Fp."""
    px, py = pxy
    x1, y1 = t
    if q is not None and t is not None and x1 == q[0] and y1 != q[1]:
        # vertical line
        return fp12_sub(_fp_to_fp12(px), x1)
    if t == q:
        if y1 == FP12_ZERO:
            return fp12_sub(_fp_to_fp12(px), x1)
        lam = fp12_mul(
            fp12_mul(fp12_sqr(x1), _fp_to_fp12(3)),
            fp12_inv(fp12_mul(y1, _fp_to_fp12(2))),
        )
    else:
        x2, y2 = q
        if x1 == x2:
            return fp12_sub(_fp_to_fp12(px), x1)
        lam = fp12_mul(fp12_sub(y2, y1), fp12_inv(fp12_sub(x2, x1)))
    return fp12_sub(
        fp12_sub(_fp_to_fp12(py), y1),
        fp12_mul(lam, fp12_sub(_fp_to_fp12(px), x1)),
    )


def miller_loop(p1_affine, q2_affine):
    """f_{|x|,Q}(P) with the ate loop count |X_PARAM|; conjugated for x < 0."""
    if p1_affine is None or q2_affine is None:
        return FP12_ONE
    q = _untwist(q2_affine)
    t = q
    f = FP12_ONE
    n = -X_PARAM  # positive loop count
    for i in range(n.bit_length() - 2, -1, -1):
        f = fp12_mul(fp12_sqr(f), _line(t, t, p1_affine))
        t = _e12_add(t, t)
        if (n >> i) & 1:
            f = fp12_mul(f, _line(t, q, p1_affine))
            t = _e12_add(t, q)
    # X_PARAM < 0: f_{-n} ~ conj(f_n) up to final exponentiation.
    return fp12_conj(f)


# Final exponentiation: (p^12-1)/r = (p^6-1)(p^2+1) * h, with the hard part h
# decomposed in base p and evaluated with Frobenius + 4-way Shamir multiexp.
_HARD = (P**4 - P**2 + 1) // R
_HARD_DIGITS = []
_tmp = _HARD
for _ in range(4):
    _HARD_DIGITS.append(_tmp % P)
    _tmp //= P
assert _tmp == 0


def _final_exp_hard(m):
    frobs = [m]
    for _ in range(3):
        frobs.append(fp12_frobenius(frobs[-1]))
    # Shamir: precompute products of subsets of {m, m^p, m^p2, m^p3}.
    table = [FP12_ONE] * 16
    for mask in range(1, 16):
        low = mask & (-mask)
        idx = low.bit_length() - 1
        table[mask] = fp12_mul(table[mask ^ low], frobs[idx])
    nbits = max(d.bit_length() for d in _HARD_DIGITS)
    acc = FP12_ONE
    for i in range(nbits - 1, -1, -1):
        acc = fp12_sqr(acc)
        mask = 0
        for j in range(4):
            if (_HARD_DIGITS[j] >> i) & 1:
                mask |= 1 << j
        if mask:
            acc = fp12_mul(acc, table[mask])
    return acc


def final_exponentiation(f):
    """f^((p^6-1)(p^2+1) * 3h) with h = (p^4-p^2+1)/r — the framework's GT
    convention is the CUBED ate pairing, matching the
    Hayashida-Hayasaka-Teruya addition chain the native backend uses
    (e^3 is bilinear and, since gcd(3, r) = 1, equality checks are
    unchanged; GT values are never serialized on the wire)."""
    # easy part: f^((p^6-1)(p^2+1))
    t = fp12_mul(fp12_conj(f), fp12_inv(f))  # f^(p^6-1)
    t = fp12_mul(fp12_frobenius_n(t, 2), t)  # ^(p^2+1)
    out = _final_exp_hard(t)
    return fp12_mul(fp12_mul(out, out), out)  # ^3


def pairing(p1, q2):
    """e(P, Q) for P in G1 (Jacobian), Q in G2 (Jacobian) -> Fp12.

    Parity: GT.Pairing(G1, G2) in the reference's MCL binding
    (src/Lachain.Crypto/TPKE/PublicKey.cs:88-92 usage).
    """
    return final_exponentiation(
        miller_loop(g1_to_affine(p1), g2_to_affine(q2))
    )


def multi_pairing(pairs: Sequence[Tuple[tuple, tuple]]):
    """Prod e(Pi, Qi) sharing one final exponentiation."""
    f = FP12_ONE
    for p1, q2 in pairs:
        f = fp12_mul(f, miller_loop(g1_to_affine(p1), g2_to_affine(q2)))
    return final_exponentiation(f)


def pairings_equal(p_a, q_a, p_b, q_b) -> bool:
    """e(Pa, Qa) == e(Pb, Qb) via Prod e(Pa,Qa)*e(-Pb,Qb) == 1 (one final exp).

    This is the per-share check shape of TPKE VerifyShare
    (reference: src/Lachain.Crypto/TPKE/PublicKey.cs:88-92) and threshold-sig
    share validation (ThresholdSignature/PublicKey.cs:15-20).
    """
    return fp12_eq_one(multi_pairing([(p_a, q_a), (g1_neg(p_b), q_b)]))


# ---------------------------------------------------------------------------
# Hash-to-curve: XOF-driven try-and-increment + cofactor clearing.
# (Our chain defines its own hash-to-curve; wire compat with MCL's SetHashOf
# is intentionally NOT a goal — see SURVEY.md §7 "hard parts" #2.)
# ---------------------------------------------------------------------------


def _xof(domain: bytes, msg: bytes, nbytes: int) -> bytes:
    h = hashlib.shake_256()
    h.update(len(domain).to_bytes(1, "big") + domain + msg)
    return h.digest(nbytes)


def hash_to_fr(msg: bytes, domain: bytes = b"LTPU-FR") -> int:
    return int.from_bytes(_xof(domain, msg, 48), "big") % R


def hash_to_g1(msg: bytes, domain: bytes = b"LTPU-G1") -> tuple:
    ctr = 0
    while True:
        xb = _xof(domain + b"|" + ctr.to_bytes(4, "big"), msg, 64)
        x = int.from_bytes(xb, "big") % P
        y = fp_sqrt((x * x * x + B_G1) % P)
        if y is not None:
            if y > P - y:
                y = P - y
            pt = (x, y, 1)
            return g1_mul(pt, H_G1)
        ctr += 1


def hash_to_g2(msg: bytes, domain: bytes = b"LTPU-G2") -> tuple:
    """Deterministic hash to the G2 subgroup (role of G2.SetHashOf in MCL)."""
    ctr = 0
    b2 = fp2_muls(XI, B_G1)
    while True:
        xb = _xof(domain + b"|" + ctr.to_bytes(4, "big"), msg, 128)
        x = (
            int.from_bytes(xb[:64], "big") % P,
            int.from_bytes(xb[64:], "big") % P,
        )
        rhs = fp2_add(fp2_mul(fp2_sqr(x), x), b2)
        y = fp2_sqrt(rhs)
        if y is not None:
            if (y[1], y[0]) > (P - y[1], P - y[0]):
                y = fp2_neg(y)
            pt = (x, y, FP2_ONE)
            return g2_mul(pt, H_G2)
        ctr += 1


# ---------------------------------------------------------------------------
# Fr (scalar field) polynomial helpers — parity with MclBls12381.
# ---------------------------------------------------------------------------


def fr_eval_poly(coeffs: Sequence[int], x: int) -> int:
    """Evaluate sum coeffs[i] * x^i mod r (MclBls12381.EvaluatePolynomial)."""
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % R
    return acc


def fr_lagrange_coeffs(xs: Sequence[int], at: int = 0) -> List[int]:
    """Lagrange basis coefficients l_i(at) for interpolation points xs mod r.

    Cached per (xs, at): the per-era combine repeatedly interpolates over
    the SAME share subset (typically the fastest f+1 responders), and the
    O(n^2) modular inversions otherwise sit on the era hot path."""
    return list(_lagrange_cached(tuple(xs), at))


@functools.lru_cache(maxsize=256)
def _lagrange_cached(xs: tuple, at: int) -> tuple:
    n = len(xs)
    assert len(set(x % R for x in xs)) == n, "duplicate interpolation points"
    coeffs = []
    for i in range(n):
        num, den = 1, 1
        for j in range(n):
            if i == j:
                continue
            num = num * ((at - xs[j]) % R) % R
            den = den * ((xs[i] - xs[j]) % R) % R
        coeffs.append(num * pow(den, -1, R) % R)
    return tuple(coeffs)


def fr_interpolate(xs: Sequence[int], ys: Sequence[int], at: int = 0) -> int:
    """Scalar Lagrange interpolation (MclBls12381.LagrangeInterpolate)."""
    cs = fr_lagrange_coeffs(xs, at)
    return sum(c * y for c, y in zip(cs, ys)) % R


def g1_interpolate(xs: Sequence[int], pts: Sequence[tuple], at: int = 0):
    """Interpolate G1 points at `at` (TPKE FullDecrypt combine shape,
    reference: src/Lachain.Crypto/TPKE/PublicKey.cs:55-86)."""
    cs = fr_lagrange_coeffs(xs, at)
    acc = G1_INF
    for c, pt in zip(cs, pts):
        acc = g1_add(acc, g1_mul(pt, c))
    return acc


def g2_interpolate(xs: Sequence[int], pts: Sequence[tuple], at: int = 0):
    """Interpolate G2 points (threshold-signature combine shape,
    reference: src/Lachain.Crypto/ThresholdSignature/PublicKeySet.cs:35-44)."""
    cs = fr_lagrange_coeffs(xs, at)
    acc = G2_INF
    for c, pt in zip(cs, pts):
        acc = g2_add(acc, g2_mul(pt, c))
    return acc


# ---------------------------------------------------------------------------
# Serialization: fixed-width big-endian, uncompressed. All-zero == infinity.
#   Fr: 32 bytes | G1: 96 bytes (x || y) | G2: 192 bytes (x0 x1 y0 y1)
# ---------------------------------------------------------------------------

FR_BYTES = 32
G1_BYTES = 96
G2_BYTES = 192


def fr_to_bytes(a: int) -> bytes:
    return (a % R).to_bytes(FR_BYTES, "big")


def fr_from_bytes(b: bytes) -> int:
    assert len(b) == FR_BYTES
    v = int.from_bytes(b, "big")
    if v >= R:
        raise ValueError("Fr out of range")
    return v


def g1_to_bytes(pt) -> bytes:
    aff = g1_to_affine(pt)
    if aff is None:
        return b"\x00" * G1_BYTES
    return aff[0].to_bytes(48, "big") + aff[1].to_bytes(48, "big")


def g1_from_bytes(b: bytes, check_subgroup: bool = True) -> tuple:
    assert len(b) == G1_BYTES
    if b == b"\x00" * G1_BYTES:
        return G1_INF
    x = int.from_bytes(b[:48], "big")
    y = int.from_bytes(b[48:], "big")
    if x >= P or y >= P:
        raise ValueError("G1 coordinate out of range")
    pt = (x, y, 1)
    if not g1_is_on_curve(pt):
        raise ValueError("G1 point not on curve")
    if check_subgroup and not g1_is_inf(g1_mul(pt, R)):
        raise ValueError("G1 point not in subgroup")
    return pt


def g2_to_bytes(pt) -> bytes:
    aff = g2_to_affine(pt)
    if aff is None:
        return b"\x00" * G2_BYTES
    (x0, x1), (y0, y1) = aff
    return b"".join(v.to_bytes(48, "big") for v in (x0, x1, y0, y1))


def g2_from_bytes(b: bytes, check_subgroup: bool = True) -> tuple:
    assert len(b) == G2_BYTES
    if b == b"\x00" * G2_BYTES:
        return G2_INF
    vals = [int.from_bytes(b[i * 48 : (i + 1) * 48], "big") for i in range(4)]
    if any(v >= P for v in vals):
        raise ValueError("G2 coordinate out of range")
    pt = ((vals[0], vals[1]), (vals[2], vals[3]), FP2_ONE)
    if not g2_is_on_curve(pt):
        raise ValueError("G2 point not on curve")
    if check_subgroup and not g2_is_inf(g2_mul(pt, R)):
        raise ValueError("G2 point not in subgroup")
    return pt


def gt_to_bytes(a) -> bytes:
    out = []
    for c6 in a:
        for c2 in c6:
            for v in c2:
                out.append((v % P).to_bytes(48, "big"))
    return b"".join(out)
