"""Pure-Python AES-GCM, used only when the `cryptography` package is
absent (ecdsa.aes_gcm_encrypt/decrypt fall back here).

Wire-compatible with AESGCM: for a 12-byte nonce the output is
ciphertext||tag(16) over AES-128/192/256 in GCM per NIST SP 800-38D.
Throughput is irrelevant for the call sites (wallet blobs and ECIES
payloads, a few KB) — correctness and zero dependencies are the point.
"""
from __future__ import annotations

# -- AES block cipher -------------------------------------------------------

_SBOX = [
    0x63, 0x7C, 0x77, 0x7B, 0xF2, 0x6B, 0x6F, 0xC5, 0x30, 0x01, 0x67, 0x2B,
    0xFE, 0xD7, 0xAB, 0x76, 0xCA, 0x82, 0xC9, 0x7D, 0xFA, 0x59, 0x47, 0xF0,
    0xAD, 0xD4, 0xA2, 0xAF, 0x9C, 0xA4, 0x72, 0xC0, 0xB7, 0xFD, 0x93, 0x26,
    0x36, 0x3F, 0xF7, 0xCC, 0x34, 0xA5, 0xE5, 0xF1, 0x71, 0xD8, 0x31, 0x15,
    0x04, 0xC7, 0x23, 0xC3, 0x18, 0x96, 0x05, 0x9A, 0x07, 0x12, 0x80, 0xE2,
    0xEB, 0x27, 0xB2, 0x75, 0x09, 0x83, 0x2C, 0x1A, 0x1B, 0x6E, 0x5A, 0xA0,
    0x52, 0x3B, 0xD6, 0xB3, 0x29, 0xE3, 0x2F, 0x84, 0x53, 0xD1, 0x00, 0xED,
    0x20, 0xFC, 0xB1, 0x5B, 0x6A, 0xCB, 0xBE, 0x39, 0x4A, 0x4C, 0x58, 0xCF,
    0xD0, 0xEF, 0xAA, 0xFB, 0x43, 0x4D, 0x33, 0x85, 0x45, 0xF9, 0x02, 0x7F,
    0x50, 0x3C, 0x9F, 0xA8, 0x51, 0xA3, 0x40, 0x8F, 0x92, 0x9D, 0x38, 0xF5,
    0xBC, 0xB6, 0xDA, 0x21, 0x10, 0xFF, 0xF3, 0xD2, 0xCD, 0x0C, 0x13, 0xEC,
    0x5F, 0x97, 0x44, 0x17, 0xC4, 0xA7, 0x7E, 0x3D, 0x64, 0x5D, 0x19, 0x73,
    0x60, 0x81, 0x4F, 0xDC, 0x22, 0x2A, 0x90, 0x88, 0x46, 0xEE, 0xB8, 0x14,
    0xDE, 0x5E, 0x0B, 0xDB, 0xE0, 0x32, 0x3A, 0x0A, 0x49, 0x06, 0x24, 0x5C,
    0xC2, 0xD3, 0xAC, 0x62, 0x91, 0x95, 0xE4, 0x79, 0xE7, 0xC8, 0x37, 0x6D,
    0x8D, 0xD5, 0x4E, 0xA9, 0x6C, 0x56, 0xF4, 0xEA, 0x65, 0x7A, 0xAE, 0x08,
    0xBA, 0x78, 0x25, 0x2E, 0x1C, 0xA6, 0xB4, 0xC6, 0xE8, 0xDD, 0x74, 0x1F,
    0x4B, 0xBD, 0x8B, 0x8A, 0x70, 0x3E, 0xB5, 0x66, 0x48, 0x03, 0xF6, 0x0E,
    0x61, 0x35, 0x57, 0xB9, 0x86, 0xC1, 0x1D, 0x9E, 0xE1, 0xF8, 0x98, 0x11,
    0x69, 0xD9, 0x8E, 0x94, 0x9B, 0x1E, 0x87, 0xE9, 0xCE, 0x55, 0x28, 0xDF,
    0x8C, 0xA1, 0x89, 0x0D, 0xBF, 0xE6, 0x42, 0x68, 0x41, 0x99, 0x2D, 0x0F,
    0xB0, 0x54, 0xBB, 0x16,
]

_RCON = [0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36]


def _xtime(a: int) -> int:
    a <<= 1
    return (a ^ 0x1B) & 0xFF if a & 0x100 else a


def _expand_key(key: bytes) -> list:
    nk = len(key) // 4
    if nk not in (4, 6, 8):
        raise ValueError("AES key must be 16/24/32 bytes")
    nr = nk + 6
    words = [list(key[4 * i : 4 * i + 4]) for i in range(nk)]
    for i in range(nk, 4 * (nr + 1)):
        w = list(words[i - 1])
        if i % nk == 0:
            w = [_SBOX[b] for b in w[1:] + w[:1]]
            w[0] ^= _RCON[i // nk - 1]
        elif nk == 8 and i % nk == 4:
            w = [_SBOX[b] for b in w]
        words.append([a ^ b for a, b in zip(words[i - nk], w)])
    # one flat 16-byte round key per round
    return [
        sum(words[4 * r : 4 * r + 4], []) for r in range(nr + 1)
    ]


def _encrypt_block(round_keys: list, block: bytes) -> bytes:
    nr = len(round_keys) - 1
    s = [b ^ k for b, k in zip(block, round_keys[0])]
    for rnd in range(1, nr):
        s = [_SBOX[b] for b in s]
        # ShiftRows on column-major state: row r rotates left by r
        s = [s[(i + 4 * ((i % 4))) % 16] for i in range(16)]
        t = []
        for c in range(0, 16, 4):
            a0, a1, a2, a3 = s[c : c + 4]
            t += [
                _xtime(a0) ^ _xtime(a1) ^ a1 ^ a2 ^ a3,
                a0 ^ _xtime(a1) ^ _xtime(a2) ^ a2 ^ a3,
                a0 ^ a1 ^ _xtime(a2) ^ _xtime(a3) ^ a3,
                _xtime(a0) ^ a0 ^ a1 ^ a2 ^ _xtime(a3),
            ]
        s = [b ^ k for b, k in zip(t, round_keys[rnd])]
    s = [_SBOX[b] for b in s]
    s = [s[(i + 4 * ((i % 4))) % 16] for i in range(16)]
    return bytes(b ^ k for b, k in zip(s, round_keys[nr]))


# -- GCM --------------------------------------------------------------------

_R = 0xE1 << 120


def _gmul(x: int, y: int) -> int:
    z = 0
    for i in range(127, -1, -1):
        if (y >> i) & 1:
            z ^= x
        x = (x >> 1) ^ _R if x & 1 else x >> 1
    return z


def _ghash(h: int, data: bytes) -> int:
    y = 0
    for i in range(0, len(data), 16):
        blk = data[i : i + 16]
        y = _gmul(int.from_bytes(blk, "big") ^ y, h)
    return y


def _pad16(b: bytes) -> bytes:
    return b + b"\x00" * (-len(b) % 16)


def _gcm_core(key: bytes, nonce: bytes, data: bytes, aad: bytes):
    """Returns (ctr_stream(data), tag_for(aad, processed_output)) pieces:
    the CTR keystream XOR and a closure computing the tag over a given
    ciphertext — encrypt tags its output, decrypt tags its input."""
    if len(nonce) != 12:
        raise ValueError("GCM fallback supports 96-bit nonces only")
    rk = _expand_key(key)
    h = int.from_bytes(_encrypt_block(rk, b"\x00" * 16), "big")
    j0 = nonce + b"\x00\x00\x00\x01"
    out = bytearray()
    ctr = int.from_bytes(j0[12:], "big")
    for i in range(0, len(data), 16):
        ctr = (ctr + 1) & 0xFFFFFFFF
        ks = _encrypt_block(rk, nonce + ctr.to_bytes(4, "big"))
        chunk = data[i : i + 16]
        out += bytes(a ^ b for a, b in zip(chunk, ks))
    ek_j0 = int.from_bytes(_encrypt_block(rk, j0), "big")

    def tag(ciphertext: bytes) -> bytes:
        lengths = (len(aad) * 8).to_bytes(8, "big") + (
            len(ciphertext) * 8
        ).to_bytes(8, "big")
        s = _ghash(h, _pad16(aad) + _pad16(ciphertext) + lengths)
        return (s ^ ek_j0).to_bytes(16, "big")

    return bytes(out), tag


def encrypt(key: bytes, nonce: bytes, plaintext: bytes, aad: bytes = b"") -> bytes:
    ct, tag = _gcm_core(key, nonce, plaintext, aad)
    return ct + tag(ct)


def decrypt(key: bytes, nonce: bytes, data: bytes, aad: bytes = b"") -> bytes:
    if len(data) < 16:
        raise ValueError("ciphertext shorter than GCM tag")
    ct, want = data[:-16], data[-16:]
    pt, tag = _gcm_core(key, nonce, ct, aad)
    got = tag(ct)
    # constant-time-ish compare (hmac.compare_digest without the import
    # ceremony would be fine too; this is not a remote oracle)
    import hmac

    if not hmac.compare_digest(got, want):
        raise ValueError("GCM tag mismatch")
    return pt
