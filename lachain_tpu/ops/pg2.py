"""Pallas G2 engine: VMEM-resident Fp2/G2 kernels for the coin hot path.

Round-3 counterpart of ops/pg1.py for the OTHER half of the era's crypto:
threshold-signature share verification + Lagrange combination, where the
signatures live in G2 (Fp2 coordinates). The reference verifies each coin
share with 2 pairings and combines with a serial G2 Lagrange loop
(/root/reference/src/Lachain.Crypto/ThresholdSignature/ThresholdSigner.cs:
45-95, PublicKeySet.cs:35-44 via CommonCoin.cs:75-96); here S coins x K
shares collapse into three windowed MSM passes in one kernel launch:

  verify : e(g1, sum_j c_j sigma_j) == e(sum_j c_j Y_j, H)   per coin
  combine: sigma = sum_i lambda_i sigma_i                    per coin

sigma-aggregates are G2 MSMs (this module); the key aggregate is a G1 MSM
(reuses pg1's machinery verbatim); the host finishes with one grand
multi-pairing.

Field/kernel design is pg1's, lifted to Fp2 = Fp[i]/(i^2+1):
  * an Fp2 element is a pair of 44x10-bit signed plain-form limb vectors;
    mul is Karatsuba — 3 convs + 3 MXU fold matmuls (folding each conv
    separately keeps every int32 conv accumulator within pg1's proven
    44*2^12.1^2 < 2^29.7 bound; combining convs first would overflow);
    square is (a+b)(a-b) / 2ab — 2 convs + 2 folds.
  * G2 points are Jacobian over Fp2: (288, B) int32 blocks
    (X.c0|X.c1|Y.c0|Y.c1|Z.c0|Z.c1, one 48-row slot per component), same
    incomplete add/dbl formulas as pg1 with Fp ops replaced by Fp2 ops.
  * the MSM is the same one-pallas_call window scan with the accumulator
    and 16-entry table VMEM-resident; LANE_TILE2 = 128 keeps the resident
    table block at 16*288*128*4 B = 2.4 MB.
  * no GLV: the G2 endomorphism (untwist-Frobenius-twist) needs Fp2
    Frobenius + twist constants in-kernel; a 64-window full-scalar pass is
    ~2x the window count for a fraction of the complexity. The RLC verify
    pass stays 16 windows (64-bit coefficients).

Magnitude invariants are pg1's (fuzz-checked in tests/test_pg2.py): every
Fp2 component flows through the same _add/_sub/_fold/_crush compositions
at the same chain depths as pg1's G1 formulas.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import msm, pg1
from ..crypto import bls12381 as bls
from .pg1 import (
    INTERPRET,
    NLIMBS,
    POINT_ROWS,
    TABLE,
    WINDOW,
    _add,
    _const_args,
    _CONST_SPECS,
    _consts,
    _conv,
    _crush,
    _fold,
    _mul_small,
    _pad_lanes,
    _select_entry,
    _sub,
)

COMP_ROWS = 48  # one Fp2 component per 48-row slot (44 limbs + 4 zero
# rows): Mosaic's lane-axis concatenate requires operands at matching
# sublane offsets, and 44-row strides would alternate slices between
# offsets 0 and 4 ("result/input offset mismatch on non-concat dimension")
POINT2_ROWS = 6 * COMP_ROWS  # 288: X.c0|X.c1|Y.c0|Y.c1|Z.c0|Z.c1
W256 = 256 // WINDOW  # 64 windows: full-scalar (Lagrange) pass
LANE_TILE2 = 128  # resident table block 16*288*128*4 = 2.4 MB VMEM


# ---------------------------------------------------------------------------
# Fp2 helpers (pairs of (44, B) limb blocks inside kernel bodies)
# ---------------------------------------------------------------------------


def _fp2_add(x, y, c):
    return (_add(x[0], y[0], c), _add(x[1], y[1], c))


def _fp2_sub(x, y, c):
    return (_sub(x[0], y[0], c), _sub(x[1], y[1], c))


def _fp2_muls(x, k: int, c):
    return (_mul_small(x[0], k, c), _mul_small(x[1], k, c))


def _fp2_mul(x, y, c):
    """Karatsuba: (a+bi)(d+ei) = (ad-be) + ((a+b)(d+e)-ad-be)i.

    The 3 independent Fp products ride ONE conv+fold on a 3x-wide lane
    block (lane-axis packing): Mosaic compile time scales with statement
    count, not tile width, so one (44, 3B) conv costs a third of three
    (44, B) convs to compile — the lever that brought the G2 kernel from
    ~300 s to double-digit compile. Each conv folds before combination so
    conv accumulators keep pg1's proven int32 bound; the 3-term imag
    combination is two crush(1) subs (same chain depth as pg1's X3/Y3)."""
    a, b = x
    d, e = y
    bcols = a.shape[-1]
    xs = jnp.concatenate([a, b, _add(a, b, c)], axis=-1)  # (44, 3B)
    ys = jnp.concatenate([d, e, _add(d, e, c)], axis=-1)
    f = _fold(_conv(xs, ys), c)  # (44, 3B)
    f_ad = f[:, :bcols]
    f_be = f[:, bcols : 2 * bcols]
    f_k = f[:, 2 * bcols :]
    real = _sub(f_ad, f_be, c)
    imag = _sub(_sub(f_k, f_ad, c), f_be, c)
    return (real, imag)


def _fp2_sqr(x, c):
    """(a+bi)^2 = (a+b)(a-b) + 2abi — one conv+fold on a 2x-wide block."""
    a, b = x
    bcols = a.shape[-1]
    xs = jnp.concatenate([_add(a, b, c), a], axis=-1)  # (44, 2B)
    ys = jnp.concatenate([_sub(a, b, c), b], axis=-1)
    f = _fold(_conv(xs, ys), c)
    real = f[:, :bcols]
    ab = f[:, bcols:]
    return (real, _add(ab, ab, c))


def _split(p):
    """(288, B) -> three Fp2 values (X, Y, Z); every slice starts on an
    8-aligned sublane offset (COMP_ROWS = 48)."""
    c = [p[COMP_ROWS * j : COMP_ROWS * j + NLIMBS] for j in range(6)]
    return ((c[0], c[1]), (c[2], c[3]), (c[4], c[5]))


def _join(x, y, z):
    b = x[0].shape[-1]
    z4 = jnp.zeros((COMP_ROWS - NLIMBS, b), jnp.int32)
    return jnp.concatenate(
        [x[0], z4, x[1], z4, y[0], z4, y[1], z4, z[0], z4, z[1], z4],
        axis=0,
    )


# ---------------------------------------------------------------------------
# in-kernel G2 group law (Jacobian over Fp2, incomplete — flags outside)
# ---------------------------------------------------------------------------


def _g2_dbl_val(p, c):
    """(288, B) -> (288, B); same a=0 Jacobian formulas as pg1._g1_dbl_val
    (oracle: crypto/bls12381.py:g2_dbl)."""
    X1, Y1, Z1 = _split(p)
    A = _fp2_sqr(X1, c)
    B = _fp2_sqr(Y1, c)
    C = _fp2_sqr(B, c)
    D = _fp2_sub(_fp2_sub(_fp2_sqr(_fp2_add(X1, B, c), c), A, c), C, c)
    D = _fp2_add(D, D, c)
    E = _fp2_muls(A, 3, c)
    F = _fp2_sqr(E, c)
    X3 = _fp2_sub(F, _fp2_add(D, D, c), c)
    Y3 = _fp2_sub(
        _fp2_mul(E, _fp2_sub(D, X3, c), c), _fp2_muls(C, 8, c), c
    )
    Z3 = _fp2_mul(Y1, Z1, c)
    Z3 = _fp2_add(Z3, Z3, c)
    return _join(X3, Y3, Z3)


def _g2_add_val(p, q, c):
    """(288, B) x (288, B) -> (288, B); requires p != +-q, both finite
    (oracle: crypto/bls12381.py:g2_add)."""
    X1, Y1, Z1 = _split(p)
    X2, Y2, Z2 = _split(q)
    Z1Z1 = _fp2_sqr(Z1, c)
    Z2Z2 = _fp2_sqr(Z2, c)
    U1 = _fp2_mul(X1, Z2Z2, c)
    U2 = _fp2_mul(X2, Z1Z1, c)
    S1 = _fp2_mul(_fp2_mul(Y1, Z2, c), Z2Z2, c)
    S2 = _fp2_mul(_fp2_mul(Y2, Z1, c), Z1Z1, c)
    H = _fp2_sub(U2, U1, c)
    Rr = _fp2_sub(S2, S1, c)
    I = _fp2_sqr(_fp2_add(H, H, c), c)
    J = _fp2_mul(H, I, c)
    Rr2 = _fp2_add(Rr, Rr, c)
    V = _fp2_mul(U1, I, c)
    X3 = _fp2_sub(
        _fp2_sub(_fp2_sqr(Rr2, c), J, c), _fp2_add(V, V, c), c
    )
    S1J = _fp2_mul(S1, J, c)
    Y3 = _fp2_sub(
        _fp2_mul(Rr2, _fp2_sub(V, X3, c), c), _fp2_add(S1J, S1J, c), c
    )
    Z3 = _fp2_mul(_fp2_mul(Z1, Z2, c), H, c)
    Z3 = _fp2_add(Z3, Z3, c)
    return _join(X3, Y3, Z3)


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


def _tile_width2(n: int) -> int:
    floor = 8 if INTERPRET else 128
    return min(LANE_TILE2, max(floor, n))


def _padded2(n: int) -> int:
    t = _tile_width2(n)
    return ((n + t - 1) // t) * t


def _dbl2_kernel(mlo_ref, mhi_ref, wrap_ref, p_ref, o_ref):
    o_ref[:] = _g2_dbl_val(p_ref[:], _consts(mlo_ref, mhi_ref, wrap_ref))


def _add2_kernel(mlo_ref, mhi_ref, wrap_ref, p_ref, q_ref, o_ref):
    o_ref[:] = _g2_add_val(
        p_ref[:], q_ref[:], _consts(mlo_ref, mhi_ref, wrap_ref)
    )


def pl_dbl2(p):
    """(288, n) -> (288, n) Jacobian G2 doubling on-device."""
    if INTERPRET:
        return _g2_dbl_val(p, _const_args())
    n = p.shape[-1]
    w = _padded2(n)
    t = _tile_width2(n)
    out = pl.pallas_call(
        _dbl2_kernel,
        grid=(w // t,),
        in_specs=_CONST_SPECS + [
            pl.BlockSpec((POINT2_ROWS, t), lambda i: (0, i),
                         memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((POINT2_ROWS, t), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((POINT2_ROWS, w), jnp.int32),
        interpret=INTERPRET,
    )(*_const_args(), _pad_lanes(p, w))
    return out[:, :n]


def pl_add2(p, q):
    """(288, n) x (288, n) -> (288, n) incomplete G2 add on-device."""
    if INTERPRET:
        return _g2_add_val(p, q, _const_args())
    n = p.shape[-1]
    w = _padded2(n)
    t = _tile_width2(n)
    out = pl.pallas_call(
        _add2_kernel,
        grid=(w // t,),
        in_specs=_CONST_SPECS + [
            pl.BlockSpec((POINT2_ROWS, t), lambda i: (0, i),
                         memory_space=pltpu.VMEM)
        ] * 2,
        out_specs=pl.BlockSpec((POINT2_ROWS, t), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((POINT2_ROWS, w), jnp.int32),
        interpret=INTERPRET,
    )(*_const_args(), _pad_lanes(p, w), _pad_lanes(q, w))
    return out[:, :n]


def _msm2_kernel(mlo_ref, mhi_ref, wrap_ref, table_ref, dig_ref,
                 acc_ref, flag_ref):
    """Same structure as pg1._msm_kernel: grid (tiles, windows), window
    innermost; accumulator + table blocks VMEM-resident across windows."""
    c = _consts(mlo_ref, mhi_ref, wrap_ref)
    w = pl.program_id(1)
    d = dig_ref[0]
    keep = d == 0
    entry = _select_entry(table_ref[:], d)

    @pl.when(w == 0)
    def _():
        acc_ref[:] = entry
        flag_ref[:] = keep.astype(jnp.int32)

    @pl.when(w > 0)
    def _():
        acc = acc_ref[:]
        flag = flag_ref[:] != 0
        acc = jax.lax.fori_loop(
            0, WINDOW, lambda _, a: _g2_dbl_val(a, c), acc
        )
        added = _g2_add_val(acc, entry, c)
        acc_new = jnp.where(keep, acc, jnp.where(flag, entry, added))
        acc_ref[:] = acc_new
        flag_ref[:] = (flag & keep).astype(jnp.int32)


def _msm2_emulate(table, digits):
    """INTERPRET-mode path: same per-window math as _msm2_kernel as plain
    jnp (see pg1._msm_emulate for why)."""
    c = _const_args()
    acc = None
    flag = None
    for w in range(digits.shape[0]):
        d = digits[w]
        keep = d == 0
        entry = _select_entry(table, d)
        if acc is None:
            acc, flag = entry, keep
            continue
        a4 = jax.lax.fori_loop(
            0, WINDOW, lambda _, a: _g2_dbl_val(a, c), acc
        )
        added = _g2_add_val(a4, entry, c)
        acc = jnp.where(keep, a4, jnp.where(flag, entry, added))
        flag = flag & keep
    return acc, flag[0]


def _msm2_scan(table, digits):
    """table (16, 288, n), digits (W, 1, n) -> ((288, n), (n,) flags)."""
    if INTERPRET:
        return _msm2_emulate(table, digits)
    nw = digits.shape[0]
    n = table.shape[-1]
    w = _padded2(n)
    t = _tile_width2(n)
    table = _pad_lanes(table, w)
    digits = _pad_lanes(digits, w)
    acc, flag = pl.pallas_call(
        _msm2_kernel,
        grid=(w // t, nw),
        in_specs=_CONST_SPECS + [
            pl.BlockSpec((TABLE, POINT2_ROWS, t), lambda i, j: (0, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, t), lambda i, j: (j, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((POINT2_ROWS, t), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((POINT2_ROWS, w), jnp.int32),
            jax.ShapeDtypeStruct((1, w), jnp.int32),
        ],
        interpret=INTERPRET,
    )(*_const_args(), table, digits)
    return acc[:, :n], flag[0, :n] != 0


def build_table2(lanes):
    """(288, n) -> (16, 288, n): entry k = k*P (entry 0 never selected)."""
    two = pl_dbl2(lanes)
    rows = [jnp.zeros_like(lanes), lanes, two]
    cur = two
    for _ in range(TABLE - 3):
        cur = pl_add2(cur, lanes)
        rows.append(cur)
    return jnp.stack(rows, axis=0)


def msm2_windowed(lanes, digits):
    """Windowed G2 MSM: lanes (288, n), digits (W, n) MSB-first 4-bit."""
    table = build_table2(lanes)
    return _msm2_scan(table, digits[:, None, :])


def tree_reduce2_k(acc, flags, k: int):
    """Sum groups of k adjacent G2 lanes (k power of two) with flags."""
    assert k & (k - 1) == 0
    while k > 1:
        a, b = acc[:, 0::2], acc[:, 1::2]
        fa, fb = flags[0::2], flags[1::2]
        r = pl_add2(a, b)
        acc = jnp.where(fb[None, :], a, jnp.where(fa[None, :], b, r))
        flags = fa & fb
        k //= 2
    return acc, flags


# ---------------------------------------------------------------------------
# the coin-era kernel: G2 RLC verify + G2 Lagrange combine + G1 key RLC
# ---------------------------------------------------------------------------


def ts_era_kernel(sig, y, rlc16, lag64, k: int):
    """sig: (288, S*K) signature shares (G2 plain Jacobian limbs);
    y: (132, S*K) per-share verification keys (G1, duplicated per slot);
    rlc16: (16, S*K) 64-bit RLC digits; lag64: (64, S*K) 256-bit Lagrange
    digits. k = K (lanes per slot, power of two).

    Returns one fused (289, 3S) int32 buffer (row 288 = infinity flags):
      cols [0,   S): per-slot sigma RLC aggregates (G2)   — verify
      cols [S,  2S): per-slot sigma Lagrange combines (G2) — the signature
      cols [2S, 3S): per-slot key RLC aggregates (G1, rows 132..287 zero)
    Host finishes: e(g1, sig_agg) == e(y_agg, H) per slot via ONE grand
    multi-pairing (reference runs 2 pairings per SHARE instead:
    ThresholdSigner.cs:92-95)."""
    # one 64-window scan over duplicated lanes serves BOTH sigma passes
    # (RLC digits pad with leading zero windows — flags stay set until the
    # first nonzero digit): one table build + one Mosaic MSM instance
    # instead of two, and Mosaic kernel compiles dominate era setup time
    n = sig.shape[-1]
    rlc64 = jnp.concatenate(
        [
            jnp.zeros(
                (lag64.shape[0] - rlc16.shape[0], n), jnp.int32
            ),
            rlc16,
        ],
        axis=0,
    )
    table = build_table2(sig)
    acc, fl = _msm2_scan(
        jnp.concatenate([table, table], axis=-1),
        jnp.concatenate([rlc64, lag64], axis=1)[:, None, :],
    )
    acc_r, fl_r = acc[:, :n], fl[:n]
    acc_l, fl_l = acc[:, n:], fl[n:]
    acc_y, fl_y = pg1.msm_windowed(y, rlc16)
    out_r, ofl_r = tree_reduce2_k(acc_r, fl_r, k)
    out_l, ofl_l = tree_reduce2_k(acc_l, fl_l, k)
    out_y, ofl_y = pg1.tree_reduce_k(acc_y, fl_y, k)
    s = out_r.shape[-1]
    y_padded = jnp.concatenate(
        [out_y, jnp.zeros((POINT2_ROWS - POINT_ROWS, s), jnp.int32)], axis=0
    )
    pts = jnp.concatenate([out_r, out_l, y_padded], axis=1)  # (288, 3S)
    flags = jnp.concatenate([ofl_r, ofl_l, ofl_y]).astype(jnp.int32)[None, :]
    return jnp.concatenate([pts, flags], axis=0)  # (289, 3S)


ts_era_kernel_jit = jax.jit(ts_era_kernel, static_argnames=("k",))


def msm2_reduce(lanes, digits, k: int):
    """G2 windowed MSM + tree reduce as ONE device program (see
    pg1.msm_reduce for why). Returns (289, n/k): points + flag row."""
    acc, fl = msm2_windowed(lanes, digits)
    out, ofl = tree_reduce2_k(acc, fl, k)
    return jnp.concatenate(
        [out, ofl.astype(jnp.int32)[None, :]], axis=0
    )


msm2_reduce_jit = jax.jit(msm2_reduce, static_argnames=("k",))


# ---------------------------------------------------------------------------
# host marshal
# ---------------------------------------------------------------------------


def g2_pack(points: Sequence[tuple]) -> np.ndarray:
    """Oracle G2 Jacobian tuples -> (288, n) int32 plain limbs (one
    48-row slot per Fp2 component, rows 44..47 of each slot zero).
    Infinity maps to ((0,0),(1,0),(0,0)) — callers flag it separately."""
    comps = []
    for p in points:
        if bls.g2_is_inf(p):
            comps.append((0, 0, 1, 0, 0, 0))
        else:
            (x0, x1), (y0, y1), (z0, z1) = p
            comps.append((x0, x1, y0, y1, z0, z1))
    n = len(points)
    out = np.zeros((POINT2_ROWS, n), dtype=np.int32)
    for j in range(6):
        out[COMP_ROWS * j : COMP_ROWS * j + NLIMBS] = (
            msm._ints_to_limbs_np([c[j] for c in comps]).T
        )
    return out


def g2_unpack(arr, flags=None) -> list:
    """(288, n) limbs (+ optional flags) -> oracle G2 Jacobian tuples."""
    arr = np.asarray(arr)
    out = []
    for i in range(arr.shape[-1]):
        if flags is not None and bool(np.asarray(flags)[i]):
            out.append(bls.G2_INF)
            continue
        v = [
            pg1._limbs_int(arr[COMP_ROWS * j : COMP_ROWS * j + NLIMBS, i])
            for j in range(6)
        ]
        if v[4] == 0 and v[5] == 0:
            out.append(bls.G2_INF)
        else:
            out.append(((v[0], v[1]), (v[2], v[3]), (v[4], v[5])))
    return out
