"""Batched BLS12-381 Fp arithmetic in JAX — multi-limb Montgomery form.

This is the device-side mirror of the native backend's 6x64 Montgomery field
(lachain_tpu/crypto/native/bls381.cpp) re-designed for the TPU's integer VPU:

  * An Fp element is 32 limbs x 12 bits stored as int32, trailing axis of
    shape (..., 32). 12-bit limbs keep every intermediate product sum strictly
    below 2^31: conv products are <= 32 * (2^12-1)^2 < 2^29 and the CIOS
    accumulators stay < 2^30, so no int64 (which TPUs lack natively) is ever
    needed.
  * All functions are shape-polymorphic over leading batch axes and contain
    only static control flow (unrolled Python loops over the 32 limb
    positions), so they trace once under jit/vmap/shard_map.
  * Elements live in Montgomery form (x * 2^384 mod p) on device; conversion
    happens host-side in io.py helpers.

Reference role: the Fr/Fp tower underneath MCL's G1/G2 in the reference
(/root/reference/src/Lachain.Crypto/MclBls12381.cs) — here batch-first because
the consensus hot path verifies N x N shares per era (SURVEY.md §5
"long-context / sequence parallelism" maps to exactly this batch axis).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..crypto import bls12381 as bls

NLIMBS = 32
BASE = 12
MASK = (1 << BASE) - 1
NBITS = NLIMBS * BASE  # 384

P_INT = bls.P
R_MONT = (1 << NBITS) % P_INT
R2_INT = R_MONT * R_MONT % P_INT
PINV12 = (-pow(P_INT, -1, 1 << BASE)) % (1 << BASE)


def int_to_limbs(v: int) -> np.ndarray:
    return np.array(
        [(v >> (BASE * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
    )


def limbs_to_int(a) -> int:
    a = np.asarray(a)
    return sum(int(a[i]) << (BASE * i) for i in range(NLIMBS))


P_LIMBS = jnp.asarray(int_to_limbs(P_INT))
# 2^384 - p, 33 limbs — used for the "add and check carry-out" >= p test.
NEG_P_LIMBS_33 = jnp.asarray(
    np.array(
        [((1 << NBITS) - P_INT >> (BASE * i)) & MASK for i in range(NLIMBS + 1)],
        dtype=np.int32,
    )
)
ONE_MONT = jnp.asarray(int_to_limbs(R_MONT))
ZERO = jnp.asarray(np.zeros(NLIMBS, dtype=np.int32))


def _crush(t, rounds: int = 2):
    """Magnitude reduction: after each round limb magnitudes shrink by ~2^12.

    NOT exact on its own — single +-1 carries can still ripple arbitrarily
    far (e.g. a value of exactly 2^384 is a 33-limb carry chain). Always
    followed by _ripple for exactness; _crush only bounds the inputs so the
    ripple's carries stay in {-1, 0, 1}.
    """
    for _ in range(rounds):
        carry = t >> BASE  # arithmetic shift: handles borrows
        t = (t & MASK) + jnp.pad(
            carry[..., :-1], [(0, 0)] * (t.ndim - 1) + [(1, 0)]
        )
    return t


def _ripple(t):
    """Exact sequential carry propagation (lax.scan over the limb axis).

    Returns (normalized_limbs, carry_out). Carries/borrows of any length are
    handled exactly — this fixes the fixed-round propagation flaw where
    structured values (exactly p, exactly 2^384) produced wrong limbs. A scan
    keeps the compiled graph tiny (one body for all limb positions).
    """
    tt = jnp.moveaxis(t, -1, 0)  # (L, ...batch)

    def step(carry, ti):
        cur = ti + carry
        return cur >> BASE, cur & MASK

    # init carry derived from the input so its varying-axes type matches the
    # scan output under shard_map manual axes
    carry0 = tt[0] & 0
    carry, outs = lax.scan(step, carry0, tt)
    return jnp.moveaxis(outs, 0, -1), carry


def _cond_sub_p(t):
    """t normalized limbs with value in [0, 2p) -> t mod p (exact).

    s = t + (2^384 - p) over 33 limbs; carry-out iff t >= p, in which case
    s mod 2^384 == t - p.
    """
    shape = t.shape[:-1]
    ext = jnp.concatenate(
        [t, jnp.zeros(shape + (1,), dtype=jnp.int32)], axis=-1
    )
    s, _ = _ripple(ext + NEG_P_LIMBS_33)
    ge = s[..., NLIMBS] > 0
    return jnp.where(ge[..., None], s[..., :NLIMBS], t)


def _reduce2p(t, crush_rounds: int = 2):
    """Raw limbs with value in [0, 2p) -> canonical [0, p) representation."""
    t, _ = _ripple(_crush(t, crush_rounds))
    return _cond_sub_p(t)


def normalize(t):
    """Full normalization of raw limbs (value must be in [0, 2p))."""
    return _reduce2p(t, crush_rounds=3)


def add(x, y):
    # x, y canonical -> x + y < 2p
    return _reduce2p(x + y, crush_rounds=1)


def sub(x, y):
    # x - y + p in (0, 2p); arithmetic shifts in crush/ripple absorb borrows
    return _reduce2p(x - y + P_LIMBS, crush_rounds=1)


def neg(x):
    is_zero_x = is_zero(x)
    r = sub(jnp.broadcast_to(ZERO, x.shape), x)
    return jnp.where(is_zero_x[..., None], x, r)


def is_zero(x):
    """x must be normalized (limbs in [0, 2^12), value in [0, p))."""
    return jnp.all(x == 0, axis=-1)


def eq(x, y):
    return jnp.all(x == y, axis=-1)


# one-hot "anti-diagonal sum" matrix: conv(x, y)[k] = sum_{i+j=k} x_i y_j
# expressed as a single (L*L, 2L) int32 matmul — MXU/VPU-friendly and only a
# couple of HLO ops instead of L scatter-adds.
_CONV_ONEHOT = np.zeros((NLIMBS * NLIMBS, 2 * NLIMBS), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _CONV_ONEHOT[_i * NLIMBS + _j, _i + _j] = 1
CONV_ONEHOT = jnp.asarray(_CONV_ONEHOT)


def _conv(x, y):
    """Polynomial product of limb vectors: (..., L) x (..., L) -> (..., 2L).

    Coefficients <= L * (2^12-1)^2 < 2^29 — int32-exact.
    """
    outer = x[..., :, None] * y[..., None, :]  # (..., L, L)
    flat = outer.reshape(outer.shape[:-2] + (NLIMBS * NLIMBS,))
    return flat @ CONV_ONEHOT


def mont_mul(x, y):
    """Montgomery product  x*y*2^-384 mod p  (batched, int32-safe).

    One convolution matmul (<=2^29 per coefficient) followed by L CIOS
    reduction rounds in a lax.scan; every accumulator is provably < 2^31.
    """
    x, y = jnp.broadcast_arrays(x, y)
    t = _conv(x, y)

    def red_step(tt, _):
        m = ((tt[..., 0] & MASK) * PINV12) & MASK
        tt = tt.at[..., :NLIMBS].add(m[..., None] * P_LIMBS)
        carry = tt[..., 0] >> BASE  # low 12 bits are 0 by construction
        tt = jnp.concatenate(
            [tt[..., 1:], jnp.zeros_like(tt[..., :1])], axis=-1
        )
        tt = tt.at[..., 0].add(carry)
        return tt, None

    t, _ = lax.scan(red_step, t, None, length=NLIMBS)
    return _reduce2p(t[..., :NLIMBS], crush_rounds=3)


def mont_sqr(x):
    return mont_mul(x, x)


def to_mont_host(v: int) -> np.ndarray:
    """Host-side: plain int -> Montgomery limb vector."""
    return int_to_limbs(v * R_MONT % P_INT)


def from_mont_host(a) -> int:
    """Host-side: Montgomery limb vector -> plain int."""
    rinv = pow(R_MONT, -1, P_INT)
    return limbs_to_int(np.asarray(a)) * rinv % P_INT
