"""The flagship TPU kernel: batched TPKE share verification + combination.

This is the era hot path of BASELINE.md re-designed batch-first. Per era a
validator receives up to N x N partially-decrypted shares; the reference
verifies each with 2 pairings and combines each slot's F+1 shares with a
Lagrange loop, serially (reference: HoneyBadger.cs:205-217 + TPKE/
PublicKey.cs:55-92). Here the whole batch collapses into:

  verify : e(sum_j c_j U_j, H) == e(sum_j c_j Y_j, W)  (random c_j)
  combine: U^x = sum_i lambda_i U_i                    (per slot)

i.e. three MSMs on device + 2 pairings on host. The MSMs are this module;
pairings ride the native C++ backend (lachain_tpu.crypto.native_backend) —
the host<->TPU split named in SURVEY.md §5 (the "sidecar" boundary).

`tpke_era_step(u, y, rlc_bits, lagrange_bits)` is the jittable "forward step"
exposed through __graft_entry__ and driven by bench.py.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import curve, msm
from ..crypto import bls12381 as bls


def tpke_era_step(u_pts, y_pts, rlc_bits, lagrange_bits):
    """One era's worth of share verification + combination aggregates.

    Args:
      u_pts:        (n, 3, L) decryption shares U_i (Jacobian, Montgomery limbs)
      y_pts:        (n, 3, L) verification keys Y_i for the same shares
      rlc_bits:     (n, nbits) random-linear-combination coefficients
      lagrange_bits:(n, nbits) Lagrange coefficients at 0 (zero rows for
                    shares not selected into the combination subset)

    Returns (u_agg, y_agg, combined): three G1 points (3, L). The host checks
    e(u_agg, H) == e(y_agg, W) and uses `combined` as U^x for the XOR pad.
    """
    u_agg = curve.g1_msm(u_pts, rlc_bits)
    y_agg = curve.g1_msm(y_pts, rlc_bits)
    combined = curve.g1_msm(u_pts, lagrange_bits)
    return u_agg, y_agg, combined


tpke_era_step_jit = jax.jit(tpke_era_step)


def tpke_era_slots_step(u_pts, y_pts, rlc_bits, lagrange_bits):
    """Full-era kernel: S ACS slots x K shares each, all at once.

    Args:
      u_pts:         (S, K, 3, L) decryption shares per slot
      y_pts:         (S, K, 3, L) verification keys per slot
      rlc_bits:      (S, K, nbits) per-slot RLC coefficients
      lagrange_bits: (S, K, nbits) per-slot Lagrange coefficients (zero rows
                     for shares outside the combination subset)

    Returns (u_agg, y_agg, combined), each (S, 3, L): per-slot aggregates.
    The host finishes with one 2-pairing check per slot (shared final exp via
    the native backend's multi-pairing) — versus the reference's 2 pairings
    per SHARE (2*S*K total).

    This is the flagship "forward step" the driver compile-checks via
    __graft_entry__ and bench.py times on real TPU hardware.
    """
    mul_rlc = curve.g1_scalar_mul_bits(u_pts, rlc_bits)      # (S, K, 3, L)
    mul_y = curve.g1_scalar_mul_bits(y_pts, rlc_bits)
    mul_lag = curve.g1_scalar_mul_bits(u_pts, lagrange_bits)

    def reduce_axis1(pts):
        # tree-reduce the share axis; g1_add broadcasts over the slot axis
        return curve.g1_reduce_sum(jnp.moveaxis(pts, 1, 0))  # (K, S, 3, L)

    return reduce_axis1(mul_rlc), reduce_axis1(mul_y), reduce_axis1(mul_lag)


tpke_era_slots_step_jit = jax.jit(tpke_era_slots_step)


def era_rlc(slots, k: int, rng, masks=None):
    """Shared S x K validation + RLC-coefficient generation for every era
    pipeline (device and host): per-lane 64-bit coefficients, zeroed on
    masked (absent-share) lanes. One definition so coefficient width and
    mask semantics cannot diverge between pipelines."""
    s = len(slots)
    for a_list, b_list in slots:
        if len(a_list) != k or len(b_list) != k:
            raise ValueError(
                f"every slot must carry exactly {k} shares/coefficients"
            )
    if masks is not None and (
        len(masks) != s or any(len(m) != k for m in masks)
    ):
        raise ValueError("masks must be S x K")
    rlc = [
        [rng.randbelow((1 << 64) - 1) + 1 for _ in range(k)]
        for _ in range(s)
    ]
    if masks is not None:
        rlc = [
            [c if m else 0 for c, m in zip(row, mrow)]
            for row, mrow in zip(rlc, masks)
        ]
    return rlc


class _TiledYCache:
    """Device-side marshal cache for era-invariant verification keys: one
    (rows, S*K_pad) tiled lane block per (key list, S, K_pad), keyed by
    id() with a strong reference so a collected list can never alias a new
    validator set (shared by the G1 and G2 Pallas pipelines)."""

    def __init__(self, limit: int = 4):
        self._cache = {}
        self._limit = limit

    def get(self, y_points, s: int, k_pad: int):
        import jax.numpy as jnp

        from . import pg1

        key = (id(y_points), s, k_pad)
        hit = self._cache.get(key)
        if hit is not None and hit[0] is y_points:
            return hit[1]
        padded = list(y_points) + [bls.G1_INF] * (k_pad - len(y_points))
        y_dev = jnp.asarray(np.tile(pg1.g1_pack(padded), (1, s)))
        if len(self._cache) >= self._limit:
            self._cache.pop(next(iter(self._cache)))
        self._cache[key] = (y_points, y_dev)
        return y_dev


def _pow2_at_least(k: int) -> int:
    return 1 << max(0, k - 1).bit_length() if k > 1 else 1


class GlvEraPipeline:
    """Round-2 era pipeline on the GLV/windowed kernel (ops/msm.py).

    Host side of the flagship path: vectorized marshal (batch inversion +
    numpy limb packing, no per-bit Python loops), one fused kernel launch
    for the whole era (verify RLC aggregates + GLV Lagrange combine), then
    ONE grand multi-pairing over 2S pairs and plaintext recovery.

    The reference executes the same work as 2*S*K serial pairings plus S
    serial Lagrange loops (TPKE/PublicKey.cs:55-92 via HoneyBadger.cs:
    205-247)."""

    def __init__(self, backend=None):
        import jax

        from ..crypto.provider import get_backend

        self._backend = backend or get_backend()
        self._kernel = jax.jit(msm.tpke_era_glv_kernel3)
        self._y_kernel = jax.jit(msm.y_agg_fixed_base)
        self._y_cache = {}

    def y_device(self, y_points) -> "object":
        """Build the fixed-base tables for the (per-validator-set,
        era-invariant) verification keys once and cache them.

        Keyed by id() BUT holding a strong reference to the key list and
        re-checking identity with `is` — so a garbage-collected list can
        never alias a new validator set's id. Up to 4 sets stay cached."""
        import jax
        import jax.numpy as jnp

        key = id(y_points)
        hit = self._y_cache.get(key)
        if hit is not None and hit[0] is y_points:
            return hit[1]
        y_dev = jnp.asarray(msm.g1_to_device_loose(list(y_points)))
        tables = jax.jit(msm.y_fixed_base_tables)(y_dev)
        if len(self._y_cache) >= 4:
            self._y_cache.pop(next(iter(self._y_cache)))
        self._y_cache[key] = (y_points, tables)
        return tables

    def run_era(self, slots, y_points, rng, masks=None) -> Tuple[list, list]:
        """slots: list of (u_list, lagrange_list) per ACS slot, where u_list
        holds the K decryption-share points and lagrange_list the combine
        coefficients (0 for shares outside the subset). y_points: the K
        verification keys. masks: optional S x K booleans zeroing the RLC
        coefficient of absent-share lanes (era_rlc semantics, shared with
        the host/Pallas/mesh pipelines). Returns (per-slot (u_agg, y_agg,
        combined) oracle points, rlc coefficients used) — the caller
        finishes with the grand pairing check against its H/W points.
        """
        import jax.numpy as jnp

        s = len(slots)
        k = len(y_points)
        u_np = np.stack(
            [msm.g1_to_device_loose(u_list) for u_list, _ in slots]
        )
        y_tables = self.y_device(y_points)
        rlc = era_rlc(slots, k, rng, masks)
        rlc64, rlc_d, lag1, lag2 = msm.era_digits(
            rlc, [lag_list for _, lag_list in slots]
        )
        pts, flags = self._kernel(
            jnp.asarray(u_np),
            jnp.asarray(rlc_d),
            jnp.asarray(lag1),
            jnp.asarray(lag2),
        )
        y_pts, y_flags = self._y_kernel(y_tables, jnp.asarray(rlc64))
        pts = np.asarray(pts)
        flags = np.asarray(flags)
        y_pts = np.asarray(y_pts)
        y_flags = np.asarray(y_flags)
        y_aggs = msm.g1_from_device_loose(y_pts, y_flags)
        out = []
        for i in range(s):
            three = msm.g1_from_device_loose(pts[i], flags[i])
            comb = msm.combine_or_host_msm(
                bls.g1_add(three[1], three[2]),
                slots[i][0],
                slots[i][1],
                self._backend,
            )
            out.append((three[0], y_aggs[i], comb))
        return out, rlc


class PallasEraPipeline:
    """Round-3 era pipeline on the VMEM-resident Pallas kernel (ops/pg1.py).

    Same contract as GlvEraPipeline.run_era, ~12x faster on the chip: the
    windowed MSM runs as one pallas_call per pass with the accumulator and
    the 16-entry tables resident in VMEM, the marshal uploads raw Jacobian
    limbs (no batch inversion, no Montgomery scale), and all per-era device
    outputs come back in a single buffer (the tunnel charges fixed latency
    per distinct buffer).

    Reference semantics unchanged: TPKE/PublicKey.cs:55-92 via
    HoneyBadger.cs:205-247."""

    def __init__(self, backend=None):
        from ..crypto.provider import get_backend

        self._backend = backend or get_backend()
        self._y_cache = _TiledYCache()

    def y_device(self, y_points, s: int):
        """Pack + upload the verification keys once per validator set and
        cache the (132, S*K_pad) duplicated lane block on device
        (_TiledYCache). K pads to the next power of two to match
        run_era's lane layout."""
        return self._y_cache.get(
            y_points, s, _pow2_at_least(len(y_points))
        )

    def run_era(self, slots, y_points, rng, masks=None):
        """slots: list of (u_list, lagrange_list) per ACS slot; y_points:
        the K verification keys. Returns (per-slot (u_agg, y_agg, combined)
        oracle points, rlc coefficients used).

        masks (optional): per-slot list of K bools; False lanes get a ZERO
        RLC coefficient so absent shares (the live-node case, where a slot
        holds only the F+1..K shares that have arrived) contribute to
        neither aggregate — the u_list entry for such a lane is ignored
        (pass G1_INF)."""
        import jax.numpy as jnp

        from . import pg1
        from .msm import glv_split

        s = len(slots)
        k = len(y_points)
        rlc = era_rlc(slots, k, rng, masks)
        # the in-kernel tree reduce sums power-of-two groups of adjacent
        # lanes: pad each slot to the next power of two with flagged-out
        # filler lanes (zero digits -> infinity flags)
        k_pad = _pow2_at_least(k)
        pad = k_pad - k
        u_flat = [u for u_list, _ in slots for u in u_list + [bls.G1_INF] * pad]
        u_np = pg1.g1_pack(u_flat)
        y_dev = self.y_device(y_points, s)
        rlc_flat = [c for row in rlc for c in row + [0] * pad]
        lag_flat = [
            c for _, lag_list in slots for c in lag_list + [0] * pad
        ]
        halves = [glv_split(v) for v in lag_flat]
        rlc16 = pg1.digits_col(rlc_flat, pg1.W64)
        lag1 = pg1.digits_col([h[0] for h in halves], pg1.W128)
        lag2 = pg1.digits_col([h[1] for h in halves], pg1.W128)
        buf = jnp.asarray(pg1.era_pack_inputs(u_np, rlc16, lag1, lag2))
        from ..crypto import kernel_cache

        fused = kernel_cache.call(
            pg1.era_kernel_packed_jit,
            "pg1_era_packed",
            buf,
            y_dev,
            k=k_pad,
            n=s * k_pad,
        )
        fused = np.asarray(fused)  # ONE device->host transfer
        pts, flags = fused[:132], fused[132] != 0
        cols = pg1.g1_unpack(pts, flags)  # 4S points: u_agg|y_agg|c1|c2
        out = []
        for i in range(s):
            u_agg = cols[i]
            y_agg = cols[s + i]
            comb = bls.g1_add(cols[2 * s + i], cols[3 * s + i])
            if comb[2] == 0 and any(c for c in slots[i][1]):
                # incomplete-add collision in the combine tree: no random-
                # coefficient soundness on this lane group, so fall back to
                # the host oracle MSM for the slot (same escape hatch as
                # GlvEraPipeline.run_era)
                u_list, lag_list = slots[i]
                comb = self._backend.g1_msm(
                    [u for u, c in zip(u_list, lag_list) if c],
                    [c for c in lag_list if c],
                )
            out.append((u_agg, y_agg, comb))
        return out, rlc


class TsPallasPipeline:
    """Coin-era pipeline on the Pallas G2 kernel (ops/pg2.py).

    run_era(coins, y_points, rng, masks) where coins = [(sig_list, lag_row)]
    per coin (K G2 signature shares + K Lagrange-at-0 coefficients) and
    y_points = the K per-validator TS public keys (G1). Returns
    (per-coin (sig_rlc_agg G2, y_rlc_agg G1, combined_sig G2), rlc).

    The host finishes with e(g1, sig_agg) == e(y_agg, H(msg)) per coin —
    ONE grand multi-pairing for all coins, versus the reference's 2
    pairings per share (ThresholdSigner.cs:92-95) and serial G2 Lagrange
    combine (PublicKeySet.cs:35-44)."""

    def __init__(self, backend=None):
        from ..crypto.provider import get_backend

        self._backend = backend or get_backend()
        self._y_cache = _TiledYCache()

    def run_era(self, coins, y_points, rng, masks=None):
        import jax.numpy as jnp

        from . import pg1, pg2

        s = len(coins)
        k = len(y_points)
        rlc = era_rlc(coins, k, rng, masks)
        k_pad = _pow2_at_least(k)
        pad = k_pad - k
        sig_flat = [
            p for sig_list, _ in coins for p in sig_list + [bls.G2_INF] * pad
        ]
        rlc_flat = [c for row in rlc for c in row + [0] * pad]
        lag_flat = [c for _, lag in coins for c in lag + [0] * pad]
        from ..crypto import kernel_cache

        fused = kernel_cache.call(
            pg2.ts_era_kernel_jit,
            "pg2_ts_era",
            jnp.asarray(pg2.g2_pack(sig_flat)),
            self._y_cache.get(y_points, s, k_pad),
            jnp.asarray(pg1.digits_col(rlc_flat, pg2.W64)),
            jnp.asarray(pg1.digits_col(lag_flat, pg2.W256)),
            k=k_pad,
        )
        fused = np.asarray(fused)  # ONE device->host transfer
        pr = pg2.POINT2_ROWS
        pts, flags = fused[:pr], fused[pr] != 0
        sig_cols = pg2.g2_unpack(pts[:, : 2 * s], flags[: 2 * s])
        y_cols = pg1.g1_unpack(
            pts[: pg1.POINT_ROWS, 2 * s :], flags[2 * s :]
        )
        out = []
        for i in range(s):
            comb = sig_cols[s + i]
            if bls.g2_is_inf(comb) and any(c for c in coins[i][1]):
                # incomplete-add collision in the combine lanes: no RLC
                # soundness there, host-oracle fallback for this coin (same
                # escape hatch as PallasEraPipeline.run_era)
                sig_list, lag_list = coins[i]
                comb = self._backend.g2_msm(
                    [p for p, c in zip(sig_list, lag_list) if c],
                    [c for c in lag_list if c],
                )
            out.append((sig_cols[i], y_cols[i], comb))
        return out, rlc


class _HostEraPipelineBase:
    """Host-backend emulation of the device era-pipeline contract.

    Same `run_era(slots, y_points, rng, masks)` signature and semantics as
    the Pallas pipelines, computed with the host backend's MSMs; the share
    group differs per subclass (`_share_msm`). Two jobs:
      * CPU CI / non-TPU deployments: XLA-CPU compilation of the
        interpret-mode Pallas kernels costs ~390 s per static shape, so
        everything above the kernel boundary (aggregation, masking,
        soundness decisions) runs — and stays covered — on this path.
      * correctness oracle for the device pipelines.
    Backend selection happens in crypto/tpu_backend.py: Pallas on a real
    chip, this emulation elsewhere."""

    _share_msm = "g1_msm"

    def __init__(self, backend=None):
        from ..crypto.provider import get_backend

        self._backend = backend or get_backend()

    def run_era(self, slots, y_points, rng, masks=None):
        k = len(y_points)
        rlc = era_rlc(slots, k, rng, masks)
        share_msm = getattr(self._backend, self._share_msm)
        out = []
        for i, (pts_list, lag_list) in enumerate(slots):
            live = [j for j, c in enumerate(rlc[i]) if c]
            share_agg = share_msm(
                [pts_list[j] for j in live], [rlc[i][j] for j in live]
            )
            y_agg = self._backend.g1_msm(
                [y_points[j] for j in live], [rlc[i][j] for j in live]
            )
            comb_live = [j for j, c in enumerate(lag_list) if c]
            comb = share_msm(
                [pts_list[j] for j in comb_live],
                [lag_list[j] for j in comb_live],
            )
            out.append((share_agg, y_agg, comb))
        return out, rlc


class HostEraPipeline(_HostEraPipelineBase):
    """TPKE slots: shares are G1 points (see _HostEraPipelineBase)."""

    _share_msm = "g1_msm"


class TsHostEraPipeline(_HostEraPipelineBase):
    """Coin slots: shares are G2 signatures (see _HostEraPipelineBase)."""

    _share_msm = "g2_msm"


class TpuTpkeVerifier:
    """Host-side wrapper: marshals oracle-format shares to the device kernel
    and finishes with 2 native pairings.

    Drop-in accelerated path for TpkePublicKey.batch_verify_shares +
    full_decrypt when the batch is large (the N=64 / 10k-tx regime of
    BASELINE.json config #5).
    """

    def __init__(self, backend=None):
        from ..crypto.provider import get_backend

        self._backend = backend or get_backend()

    def verify_and_combine(
        self,
        u_points: Sequence[tuple],
        y_points: Sequence[tuple],
        h_point: tuple,
        w_point: tuple,
        rlc: Sequence[int],
        lagrange: Sequence[int],
    ) -> Tuple[bool, tuple]:
        """Returns (all_valid, combined_point)."""
        n = len(u_points)
        assert n and n == len(y_points) == len(rlc) == len(lagrange)
        size = 1
        while size < n:
            size *= 2
        u_all = list(u_points) + [bls.G1_INF] * (size - n)
        y_all = list(y_points) + [bls.G1_INF] * (size - n)
        rlc_all = list(rlc) + [0] * (size - n)
        lag_all = list(lagrange) + [0] * (size - n)
        u_dev = jnp.asarray(curve.g1_to_device(u_all))
        y_dev = jnp.asarray(curve.g1_to_device(y_all))
        rlc_bits = jnp.asarray(curve.scalars_to_bits(rlc_all, nbits=128))
        lag_bits = jnp.asarray(curve.scalars_to_bits(lag_all, nbits=256))
        u_agg_d, y_agg_d, comb_d = tpke_era_step_jit(
            u_dev, y_dev, rlc_bits, lag_bits
        )
        u_agg = curve.g1_from_device(np.asarray(u_agg_d)[None])[0]
        y_agg = curve.g1_from_device(np.asarray(y_agg_d)[None])[0]
        combined = curve.g1_from_device(np.asarray(comb_d)[None])[0]
        ok = self._backend.pairing_check(
            [(u_agg, h_point), (bls.g1_neg(y_agg), w_point)]
        )
        return ok, combined
