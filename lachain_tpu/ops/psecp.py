"""Pallas secp256k1 engine: batched ECDSA public-key recovery on the MXU.

The second TPU kernel family (SURVEY.md §2a named batched ECDSA recovery as
the natural second target after the BLS12-381 era kernels). The reference
verifies receipt signatures serially on a CPU thread pool
(/root/reference/src/Lachain.Core/Blockchain/Operations/
TransactionVerifier.cs:23-72); here a whole pool-ingest batch of recoveries
runs as lane-parallel point arithmetic:

  recover_i:  Q_i = u1_i * R_i + u2_i * G
    (u1 = s/r mod n, u2 = -z/r mod n — cheap host bigints; R_i is the
     host-decompressed signature point; the two scalar multiplications are
     ~99.9% of the work and they are exactly the windowed per-lane scalar
     muls the pg1 MSM machinery already implements.)

The host finishes with batch affine conversion (one inversion amortized via
Montgomery's trick).

Field/kernel design is pg1's, re-parameterized for the secp256k1 prime:
  * 26 limbs x 10 bits (260-bit redundant signed representation over the
    256-bit field); conv length 51; fold matrix rows = limbs of
    2^(10(k+j)) mod p, split in 5-bit halves for exact f32 MXU dot
    products (153-term sums < 2^23 — exactly representable).
  * points are Jacobian (96, B) int32 blocks: 32-row component slots
    (26 limbs + 6 zero rows) keep every slice 8-sublane-aligned, the same
    constraint pg2 hit with Mosaic's concatenate.
  * magnitudes: crushed limbs <= 2^12.1, conv accumulators
    26 * 2^24.2 < 2^29 (int32 safe) — strictly smaller than the proven
    BLS bounds, same crush schedule.

Kernel layout per batch of n signatures: 2n lanes [R_0..R_{n-1} | G...G],
per-lane 64x4-bit digits [u1 | u2], one windowed scan (table of 16
per-lane multiples resident in VMEM), then a k=2 tree reduce pairs each
R-lane accumulator with its G-lane partner... lanes are interleaved so the
reduce sums adjacent pairs: lane 2i = u1_i*R_i, lane 2i+1 = u2_i*G.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..crypto import ecdsa
from .pg1 import INTERPRET, TABLE, WINDOW, _select_entry

NLIMBS = 26
BASE = 10
MASK = (1 << BASE) - 1
CONVLEN = 2 * NLIMBS - 1  # 51
COMP_ROWS = 32  # 26 limbs + 6 zero rows: 8-aligned slices
POINT_ROWS = 3 * COMP_ROWS  # 96
P_INT = ecdsa.P
N_INT = ecdsa.N
W256 = 64  # 4-bit windows over 256-bit scalars
LANE_TILE = 256


def _int_to_limbs(v: int) -> np.ndarray:
    return np.array(
        [(v >> (BASE * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
    )


_FOLD_M = np.zeros((NLIMBS, 3 * CONVLEN), dtype=np.int32)
for _j in range(3):
    for _k in range(CONVLEN):
        _FOLD_M[:, _j * CONVLEN + _k] = _int_to_limbs(
            (1 << (BASE * (_k + _j))) % P_INT
        )
_FOLD_LO = jnp.asarray((_FOLD_M & 31).astype(np.float32))
_FOLD_HI = jnp.asarray((_FOLD_M >> 5).astype(np.float32))
_WRAP_COL = jnp.asarray(_int_to_limbs((1 << (BASE * NLIMBS)) % P_INT)[:, None])

_HIGHEST = jax.lax.Precision.HIGHEST


# -- field helpers (pg1's schedule at secp parameters) ----------------------


def _crush(t, wrap, rounds: int = 1):
    b = t.shape[-1]
    for _ in range(rounds):
        carry = t >> BASE
        top = carry[NLIMBS - 1 : NLIMBS, :]
        shifted = jnp.concatenate(
            [jnp.zeros((1, b), jnp.int32), carry[: NLIMBS - 1, :]], axis=0
        )
        t = (t & MASK) + shifted + top * wrap
    return t


def _conv(x, y):
    b = x.shape[-1]
    zpad = jnp.zeros((NLIMBS - 1, b), jnp.int32)
    ypad = jnp.concatenate([zpad, y, zpad], axis=0)  # (3*NLIMBS-2, B)
    t = jnp.zeros((CONVLEN, b), jnp.int32)
    for i in range(NLIMBS):
        t = t + x[i : i + 1, :] * ypad[NLIMBS - 1 - i : 2 * NLIMBS - 1 - i + NLIMBS - 1, :]
    return t


def _fold(t, c):
    mlo, mhi, wrap = c
    a = t & MASK
    bb = (t >> BASE) & MASK
    cc = t >> (2 * BASE)
    planes = jnp.concatenate([a, bb, cc], axis=0).astype(jnp.float32)
    lo = jnp.dot(mlo, planes, preferred_element_type=jnp.float32,
                 precision=_HIGHEST)
    hi = jnp.dot(mhi, planes, preferred_element_type=jnp.float32,
                 precision=_HIGHEST)
    r = lo.astype(jnp.int32) + (hi.astype(jnp.int32) << 5)
    return _crush(r, wrap, 3)


def _mul(x, y, c):
    return _fold(_conv(x, y), c)


def _sqr(x, c):
    return _mul(x, x, c)


def _add(x, y, c):
    return _crush(x + y, c[2], 1)


def _sub(x, y, c):
    return _crush(x - y, c[2], 1)


def _mul_small(x, k: int, c):
    return _crush(x * k, c[2], 2)


def _split(p):
    return (
        p[0:NLIMBS],
        p[COMP_ROWS : COMP_ROWS + NLIMBS],
        p[2 * COMP_ROWS : 2 * COMP_ROWS + NLIMBS],
    )


def _join(x, y, z):
    b = x.shape[-1]
    z6 = jnp.zeros((COMP_ROWS - NLIMBS, b), jnp.int32)
    return jnp.concatenate([x, z6, y, z6, z, z6], axis=0)


# -- group law (Jacobian, a = 0 curve y^2 = x^3 + 7, same shape as BLS) ----


def _pt_dbl_val(p, c):
    X1, Y1, Z1 = _split(p)
    A = _sqr(X1, c)
    B = _sqr(Y1, c)
    C = _sqr(B, c)
    D = _sub(_sub(_sqr(_add(X1, B, c), c), A, c), C, c)
    D = _add(D, D, c)
    E = _mul_small(A, 3, c)
    F = _sqr(E, c)
    X3 = _sub(F, _add(D, D, c), c)
    Y3 = _sub(_mul(E, _sub(D, X3, c), c), _mul_small(C, 8, c), c)
    Z3 = _mul(Y1, Z1, c)
    Z3 = _add(Z3, Z3, c)
    return _join(X3, Y3, Z3)


def _pt_add_val(p, q, c):
    X1, Y1, Z1 = _split(p)
    X2, Y2, Z2 = _split(q)
    Z1Z1 = _sqr(Z1, c)
    Z2Z2 = _sqr(Z2, c)
    U1 = _mul(X1, Z2Z2, c)
    U2 = _mul(X2, Z1Z1, c)
    S1 = _mul(_mul(Y1, Z2, c), Z2Z2, c)
    S2 = _mul(_mul(Y2, Z1, c), Z1Z1, c)
    H = _sub(U2, U1, c)
    Rr = _sub(S2, S1, c)
    I = _sqr(_add(H, H, c), c)
    J = _mul(H, I, c)
    Rr2 = _add(Rr, Rr, c)
    V = _mul(U1, I, c)
    X3 = _sub(_sub(_sqr(Rr2, c), J, c), _add(V, V, c), c)
    S1J = _mul(S1, J, c)
    Y3 = _sub(_mul(Rr2, _sub(V, X3, c), c), _add(S1J, S1J, c), c)
    Z3 = _mul(_mul(Z1, Z2, c), H, c)
    Z3 = _add(Z3, Z3, c)
    return _join(X3, Y3, Z3)


# -- pallas wrappers --------------------------------------------------------

_CONST_SPECS = [
    pl.BlockSpec((NLIMBS, 3 * CONVLEN), lambda *g: (0, 0),
                 memory_space=pltpu.VMEM),
    pl.BlockSpec((NLIMBS, 3 * CONVLEN), lambda *g: (0, 0),
                 memory_space=pltpu.VMEM),
    pl.BlockSpec((NLIMBS, 1), lambda *g: (0, 0), memory_space=pltpu.VMEM),
]


def _const_args():
    return (_FOLD_LO, _FOLD_HI, _WRAP_COL)


def _consts(mlo_ref, mhi_ref, wrap_ref):
    return (mlo_ref[:], mhi_ref[:], wrap_ref[:])


def _tile_width(n: int) -> int:
    floor = 8 if INTERPRET else 128
    return min(LANE_TILE, max(floor, n))


def _padded(n: int) -> int:
    t = _tile_width(n)
    return ((n + t - 1) // t) * t


def _pad_lanes(a, width: int):
    if a.shape[-1] == width:
        return a
    pad = width - a.shape[-1]
    return jnp.concatenate(
        [a, jnp.zeros(a.shape[:-1] + (pad,), a.dtype)], axis=-1
    )


def _dbl_kernel(mlo, mhi, wrap, p_ref, o_ref):
    o_ref[:] = _pt_dbl_val(p_ref[:], _consts(mlo, mhi, wrap))


def _add_kernel(mlo, mhi, wrap, p_ref, q_ref, o_ref):
    o_ref[:] = _pt_add_val(p_ref[:], q_ref[:], _consts(mlo, mhi, wrap))


def pl_dbl(p):
    if INTERPRET:
        return _pt_dbl_val(p, _const_args())
    n = p.shape[-1]
    w = _padded(n)
    t = _tile_width(n)
    out = pl.pallas_call(
        _dbl_kernel,
        grid=(w // t,),
        in_specs=_CONST_SPECS + [
            pl.BlockSpec((POINT_ROWS, t), lambda i: (0, i),
                         memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((POINT_ROWS, t), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((POINT_ROWS, w), jnp.int32),
        interpret=INTERPRET,
    )(*_const_args(), _pad_lanes(p, w))
    return out[:, :n]


def pl_add(p, q):
    if INTERPRET:
        return _pt_add_val(p, q, _const_args())
    n = p.shape[-1]
    w = _padded(n)
    t = _tile_width(n)
    out = pl.pallas_call(
        _add_kernel,
        grid=(w // t,),
        in_specs=_CONST_SPECS + [
            pl.BlockSpec((POINT_ROWS, t), lambda i: (0, i),
                         memory_space=pltpu.VMEM)
        ] * 2,
        out_specs=pl.BlockSpec((POINT_ROWS, t), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((POINT_ROWS, w), jnp.int32),
        interpret=INTERPRET,
    )(*_const_args(), _pad_lanes(p, w), _pad_lanes(q, w))
    return out[:, :n]


def _msm_kernel(mlo, mhi, wrap, table_ref, dig_ref, acc_ref, flag_ref):
    """Same structure as pg1._msm_kernel at secp parameters."""
    c = _consts(mlo, mhi, wrap)
    w = pl.program_id(1)
    d = dig_ref[0]
    keep = d == 0
    entry = _select_entry(table_ref[:], d)

    @pl.when(w == 0)
    def _():
        acc_ref[:] = entry
        flag_ref[:] = keep.astype(jnp.int32)

    @pl.when(w > 0)
    def _():
        acc = acc_ref[:]
        flag = flag_ref[:] != 0
        acc = jax.lax.fori_loop(
            0, WINDOW, lambda _, a: _pt_dbl_val(a, c), acc
        )
        added = _pt_add_val(acc, entry, c)
        acc_new = jnp.where(keep, acc, jnp.where(flag, entry, added))
        acc_ref[:] = acc_new
        flag_ref[:] = (flag & keep).astype(jnp.int32)


def _msm_emulate(table, digits):
    c = _const_args()
    acc = None
    flag = None
    for w in range(digits.shape[0]):
        d = digits[w]
        keep = d == 0
        entry = _select_entry(table, d)
        if acc is None:
            acc, flag = entry, keep
            continue
        a4 = jax.lax.fori_loop(
            0, WINDOW, lambda _, a: _pt_dbl_val(a, c), acc
        )
        added = _pt_add_val(a4, entry, c)
        acc = jnp.where(keep, a4, jnp.where(flag, entry, added))
        flag = flag & keep
    return acc, flag[0]


def _msm_scan(table, digits):
    if INTERPRET:
        return _msm_emulate(table, digits)
    nw = digits.shape[0]
    n = table.shape[-1]
    w = _padded(n)
    t = _tile_width(n)
    table = _pad_lanes(table, w)
    digits = _pad_lanes(digits, w)
    acc, flag = pl.pallas_call(
        _msm_kernel,
        grid=(w // t, nw),
        in_specs=_CONST_SPECS + [
            pl.BlockSpec((TABLE, POINT_ROWS, t), lambda i, j: (0, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, t), lambda i, j: (j, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((POINT_ROWS, t), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((POINT_ROWS, w), jnp.int32),
            jax.ShapeDtypeStruct((1, w), jnp.int32),
        ],
        interpret=INTERPRET,
    )(*_const_args(), table, digits)
    return acc[:, :n], flag[0, :n] != 0


def build_table(lanes):
    two = pl_dbl(lanes)
    rows = [jnp.zeros_like(lanes), lanes, two]
    cur = two
    for _ in range(TABLE - 3):
        cur = pl_add(cur, lanes)
        rows.append(cur)
    return jnp.stack(rows, axis=0)


_SQRT_EXP = (P_INT + 1) // 4
_SQRT_BITS = np.array(
    [(_SQRT_EXP >> i) & 1 for i in range(253, -1, -1)], dtype=np.int32
)[:, None]  # MSB-first column


def sqrt_kernel(x_lanes, bits):
    """Per-lane y = (x^3 + 7)^((p+1)/4): candidate square roots for the
    signature points' x coordinates — the host pow() at ~300 us/lane was
    the recover pipeline's single biggest cost. Square-and-multiply with
    the STATIC exponent bit table rides a fori loop (one sqr+mul+select
    body in the trace). Non-residues produce garbage lanes the host
    rejects with the y^2 == x^3+7 check it already performs."""
    c = _const_args()
    x3 = _mul(_sqr(x_lanes, c), x_lanes, c)
    seven = jnp.zeros_like(x_lanes).at[0].set(7)
    y2 = _add(x3, seven, c)

    def step(i, acc):
        sq = _mul(acc, acc, c)
        withmul = _mul(sq, y2, c)
        return jnp.where(bits[i] != 0, withmul, sq)

    # exponent MSB is 1: start from y2 itself
    y = jax.lax.fori_loop(1, 254, step, y2)
    return y


sqrt_kernel_jit = jax.jit(sqrt_kernel)


def ints_from_limbs(arr) -> list:
    """(26, n) limb planes -> python ints mod p. Device limbs are LOOSE
    (possibly >10-bit or negative), so the shift-accumulate runs in
    python-int space per lane — 26 multiword ops/lane, ~0.15 s per 10k
    lanes, a known slice of the host budget (ROUND3_NOTES gap #2)."""
    arr = np.asarray(arr).astype(np.int64).T  # (n, 26)
    out = []
    for row in arr:
        v = 0
        for i in range(NLIMBS - 1, -1, -1):
            v = (v << 10) + int(row[i])
        out.append(v % P_INT)
    return out


def recover_kernel(lanes, digits):
    """lanes: (96, 2n) interleaved [R_0, G, R_1, G, ...]; digits: (64, 2n)
    interleaved [u1_0, u2_0, u1_1, u2_1, ...]. Returns one fused
    (97, n) buffer: per-signature Q = u1*R + u2*G (row 96 = infinity
    flags)."""
    table = build_table(lanes)
    acc, fl = _msm_scan(table, digits[:, None, :])
    # sum adjacent lane pairs (u1*R_i, u2*G) -> Q_i
    a, b = acc[:, 0::2], acc[:, 1::2]
    fa, fb = fl[0::2], fl[1::2]
    r = pl_add(a, b)
    out = jnp.where(fb[None, :], a, jnp.where(fa[None, :], b, r))
    ofl = fa & fb
    return jnp.concatenate(
        [out, ofl.astype(jnp.int32)[None, :]], axis=0
    )


recover_kernel_jit = jax.jit(recover_kernel)


# -- host marshal -----------------------------------------------------------


_W10 = (1 << np.arange(10)).astype(np.int32)


def limbs_from_ints(vals: Sequence[int]) -> np.ndarray:
    """(n, 26) limb rows, vectorized: bytes -> unpacked bits -> 10-bit
    windows (a Python per-limb loop costs ~1 s at pool-ingest batch
    sizes)."""
    raw = np.frombuffer(
        b"".join(v.to_bytes(32, "big") for v in vals), np.uint8
    ).reshape(-1, 32)
    bits = np.unpackbits(raw[:, ::-1], axis=1, bitorder="little")
    bits = np.concatenate(
        [bits, np.zeros((len(vals), 4), np.uint8)], axis=1
    )  # 260 bits
    return (
        bits.reshape(-1, NLIMBS, 10).astype(np.int32) * _W10
    ).sum(axis=2)


def pt_pack(points: Sequence[Optional[Tuple[int, int]]]) -> np.ndarray:
    """Affine (x, y) tuples (None = infinity) -> (96, n) Jacobian limbs."""
    n = len(points)
    out = np.zeros((POINT_ROWS, n), dtype=np.int32)
    xs = [p[0] if p else 0 for p in points]
    ys = [p[1] if p else 1 for p in points]
    zs = [0 if p is None else 1 for p in points]
    out[0:NLIMBS] = limbs_from_ints(xs).T
    out[COMP_ROWS : COMP_ROWS + NLIMBS] = limbs_from_ints(ys).T
    out[2 * COMP_ROWS, :] = np.asarray(zs, np.int32)
    return out


def _limbs_int(a) -> int:
    return sum(int(a[i]) << (BASE * i) for i in range(NLIMBS)) % P_INT


def pt_unpack(arr, flags=None) -> List[Optional[Tuple[int, int, int]]]:
    """(96, n) limbs -> Jacobian int tuples (None = infinity)."""
    arr = np.asarray(arr)
    xs = ints_from_limbs(arr[0:NLIMBS])
    ys = ints_from_limbs(arr[COMP_ROWS : COMP_ROWS + NLIMBS])
    zs = ints_from_limbs(arr[2 * COMP_ROWS : 2 * COMP_ROWS + NLIMBS])
    fl = (
        np.asarray(flags)
        if flags is not None
        else np.zeros(arr.shape[-1], bool)
    )
    return [
        None if (fl[i] or zs[i] == 0) else (xs[i], ys[i], zs[i])
        for i in range(arr.shape[-1])
    ]


def digits_col(scalars: Sequence[int]) -> np.ndarray:
    """MSB-first 4-bit digit planes (64, n), vectorized via nibble split."""
    raw = np.frombuffer(
        b"".join(s.to_bytes(32, "big") for s in scalars), np.uint8
    ).reshape(-1, 32)
    dig = np.empty((len(scalars), 64), np.int32)
    dig[:, 0::2] = raw >> 4
    dig[:, 1::2] = raw & 0xF
    return dig.T.copy()


class TpuEcdsaRecover:
    """Batched public-key recovery on the chip (pool-ingest scale).

    recover_batch(hashes, sigs) -> list of compressed pubkeys/None with
    semantics identical to ecdsa.recover_hash (differential-tested).
    Host does the cheap bigint work (validation, R decompress, u1/u2,
    batch affine); the chip runs the two 256-bit scalar multiplications
    per signature — ~99.9% of the serial cost."""

    # signatures per kernel launch: 4096 sigs = 8192 lanes bounds both
    # the set of compiled shapes and the power-of-two padding waste
    CHUNK = 4096

    def recover_batch(self, hashes, sigs) -> list:
        n = len(hashes)
        out: list = [None] * n
        vals = []  # (index, x, r, s, z, parity)
        for i in range(n):
            v = self._validate(hashes[i], sigs[i])
            if v is not None:
                vals.append((i, *v))
        if not vals:
            return out
        P, N = ecdsa.P, ecdsa.N
        # square roots for ALL candidate x on the chip, one launch
        m = len(vals)
        m_pad = 1 << max(0, m - 1).bit_length() if m > 1 else 1
        xs = [v[1] for v in vals] + [1] * (m_pad - m)
        y_lanes = np.asarray(
            sqrt_kernel_jit(
                jnp.asarray(limbs_from_ints(xs).T.copy()),
                jnp.asarray(_SQRT_BITS),
            )
        )
        ys = ints_from_limbs(y_lanes)[:m]
        # r^-1 for all signatures: ONE modular inversion via Montgomery's
        # trick (pow(r, -1, N) per signature was ~30% of the pipeline)
        rs = [v[2] for v in vals]
        pref = [1] * (m + 1)
        for i, r in enumerate(rs):
            pref[i + 1] = pref[i] * r % N
        inv_all = pow(pref[m], -1, N)
        rinvs = [0] * m
        for i in range(m - 1, -1, -1):
            rinvs[i] = pref[i] * inv_all % N
            inv_all = inv_all * rs[i] % N
        jobs = []  # (index, hash, sig, R_point, u1, u2)
        for k, (idx, x, r, s_, z, parity) in enumerate(vals):
            y = ys[k]
            if y * y % P != (pow(x, 3, P) + 7) % P:
                continue  # x^3+7 is a non-residue: invalid signature
            if (y & 1) != parity:
                y = P - y
            rinv = rinvs[k]
            u1 = s_ * rinv % N
            u2 = (N - z) * rinv % N if z else 0
            jobs.append((idx, hashes[idx], sigs[idx], (x, y), u1, u2))
        for lo in range(0, len(jobs), self.CHUNK):
            self._run_chunk(jobs[lo : lo + self.CHUNK], out)
        return out

    def _run_chunk(self, jobs, out) -> None:
        if not jobs:
            return
        m = len(jobs)
        m_pad = 1 << max(0, m - 1).bit_length() if m > 1 else 1
        g_aff = (ecdsa.GX, ecdsa.GY)
        pts: list = []
        u_digits: list = []
        for _idx, _h, _sig, r_pt, u1, u2 in jobs:
            pts.extend([r_pt, g_aff])
            u_digits.extend([u1, u2])
        for _ in range(m_pad - m):
            pts.extend([g_aff, g_aff])
            u_digits.extend([0, 0])
        kernel = recover_kernel if INTERPRET else recover_kernel_jit
        fused = np.asarray(
            kernel(
                jnp.asarray(pt_pack(pts)),
                jnp.asarray(digits_col(u_digits)),
            )
        )
        qs = pt_unpack(fused[:POINT_ROWS], fused[POINT_ROWS] != 0)
        # batch affine: one modular inversion via Montgomery's trick
        zs = [q[2] if q else 1 for q in qs[:m]]
        prefix = [1] * (m + 1)
        for i, z in enumerate(zs):
            prefix[i + 1] = prefix[i] * z % P_INT
        inv_all = pow(prefix[m], -1, P_INT)
        zinvs = [0] * m
        for i in range(m - 1, -1, -1):
            zinvs[i] = prefix[i] * inv_all % P_INT
            inv_all = inv_all * zs[i] % P_INT
        for k, (idx, h, sig, _r_pt, _u1, _u2) in enumerate(jobs):
            q = qs[k]
            if q is None:
                # u1*R == +-u2*G degenerates the incomplete pairwise add
                # (Z=0); adversarially constructible, so the oracle scalar
                # path answers for this signature — identical result,
                # attacker gains nothing
                out[idx] = ecdsa.recover_hash(h, sig)
                continue
            zi = zinvs[k]
            zi2 = zi * zi % P_INT
            ax = q[0] * zi2 % P_INT
            ay = q[1] * zi2 % P_INT * zi % P_INT
            out[idx] = bytes([0x02 | (ay & 1)]) + ax.to_bytes(32, "big")

    @staticmethod
    def _validate(h: bytes, sig: bytes):
        """Cheap per-signature validation mirroring ecdsa._recover_hash_py;
        returns (x, r, s, z, parity) or None. The expensive parts — the
        square root (chip) and r^-1 (batched Montgomery inversion) — are
        hoisted out of the per-signature path."""
        if len(sig) != 65 or len(h) != 32:
            return None
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:64], "big")
        v = sig[64]
        N, P = ecdsa.N, ecdsa.P
        if not (1 <= r < N and 1 <= s < N) or v > 3:
            return None
        x = r + (N if v & 2 else 0)
        if x >= P:
            return None
        z = int.from_bytes(h, "big") % N
        return (x, r, s, z, v & 1)
