"""Loose (redundant, signed-limb) BLS12-381 Fp arithmetic — the MSM hot path.

Profiling the exact field module (ops/fp.py) showed ~75% of kernel time in
carry normalization: every add/sub/mul ran one or two 32-step `lax.scan`
ripple-carry chains plus a conditional subtract, each iteration a tiny op
dominated by loop-sync latency on TPU. This module removes ALL of that from
the hot path by making the REPRESENTATION modular instead of the schedule
clever:

  * An element is 44 limbs x 10 bits of signed int32 (trailing axis
    (..., 44), R = 2^440 — wide headroom over the 381-bit modulus).
  * Any limb vector is a legal representative of its residue; limbs may be
    negative. Exact canonicalization happens only on host at the kernel
    boundary.
  * `crush` — the only normalization primitive — is fully modular: each
    round folds per-limb overflow into the next limb, and the TOP limb's
    carry wraps through the identity 2^440 ≡ (2^440 mod p) (mod p) by
    adding carry_top * FOLD_LIMBS. Nothing is ever dropped (a dropped top
    carry would shift the value by k*2^440 != 0 mod p — the bug class that
    sank two earlier designs of this module), so every op preserves the
    residue exactly with NO value-range bookkeeping at all.
  * add/sub/neg are plain limb arithmetic + crush(2): no scans, no
    conditional subtract, negatives included.
  * mont_mul is one convolution matmul + 44 statically unrolled CIOS rounds
    + crush(3). Pure elementwise chains; XLA fuses them.

Magnitude invariants (fuzz-checked in tests/test_msm.py):
  every op's output limbs satisfy |limb| <= 2^10 + 2^8 + 2^10 < 2^11.2
  conv coefficients: 44 * (2^11.2)^2 < 2^28  (signed int32 safe)
  CIOS accumulators: conv + 44 * 2^20 < 2^28.3
  top-limb carries: |carry_top| <= 4 in round 1, <= 1 after, and
  FOLD_LIMBS is zero above limb 38, so folding converges in 2-3 rounds.

Reference role: same as ops/fp.py (the Fp tower under MCL's G1 in
/root/reference/src/Lachain.Crypto/MclBls12381.cs), re-specialized for
latency: this is the module the windowed-MSM kernel (ops/msm.py) runs on.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..crypto import bls12381 as bls

NLIMBS = 44
BASE = 10
MASK = (1 << BASE) - 1
NBITS = NLIMBS * BASE  # 440
CONVLEN = 2 * NLIMBS - 1  # 87

P_INT = bls.P
R_MONT = (1 << NBITS) % P_INT
PINV = (-pow(P_INT, -1, 1 << BASE)) % (1 << BASE)
FOLD_INT = (1 << NBITS) % P_INT  # == R_MONT


def int_to_limbs(v: int) -> np.ndarray:
    assert v >= 0
    return np.array(
        [(v >> (BASE * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
    )


def limbs_to_int(a) -> int:
    """Signed limb vector -> exact integer value (host)."""
    a = np.asarray(a)
    return sum(int(a[i]) << (BASE * i) for i in range(NLIMBS))


P_LIMBS = jnp.asarray(int_to_limbs(P_INT))
ONE_MONT = jnp.asarray(int_to_limbs(R_MONT))
FOLD_LIMBS = jnp.asarray(int_to_limbs(FOLD_INT))
assert int(np.asarray(FOLD_LIMBS)[NLIMBS - 1]) == 0  # top fold limb empty
R2_INT = R_MONT * R_MONT % P_INT


def to_mont_host(v: int) -> np.ndarray:
    return int_to_limbs(v * R_MONT % P_INT)


def from_mont_host(a) -> int:
    rinv = pow(R_MONT, -1, P_INT)
    return limbs_to_int(a) * rinv % P_INT


# one-hot anti-diagonal matrix: conv(x, y)[k] = sum_{i+j=k} x_i y_j as a
# single int32 matmul (measured faster on TPU than a pad/reshape "skew"
# formulation despite the extra MACs — reshapes of unaligned widths relayout
# through HBM)
_CONV_ONEHOT = np.zeros((NLIMBS * NLIMBS, CONVLEN), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _CONV_ONEHOT[_i * NLIMBS + _j, _i + _j] = 1
CONV_ONEHOT = jnp.asarray(_CONV_ONEHOT)


def _conv(x, y):
    outer = x[..., :, None] * y[..., None, :]
    flat = outer.reshape(outer.shape[:-2] + (NLIMBS * NLIMBS,))
    return flat @ CONV_ONEHOT


# Linear Montgomery reduction: REDC(t) = sum_k t_k * (2^10k * 2^-440 mod p)
# — REDC is linear over the conv coefficients, so the whole 44-round CIOS
# loop collapses into ONE matmul against precomputed residues. Coefficients
# (|t_k| < 2^28) are split into three planes (10+10+8 bits, signed top) so
# every product and the 261-term accumulation stay inside int32.
_REDC_ROWS = np.zeros((3 * CONVLEN, NLIMBS), dtype=np.int32)
for _j in range(3):  # plane shift: 2^(10*j)
    for _k in range(CONVLEN):
        _val = (1 << (BASE * (_k + _j))) * pow(1 << NBITS, -1, P_INT) % P_INT
        _REDC_ROWS[_j * CONVLEN + _k] = int_to_limbs(_val)
REDC_M = jnp.asarray(_REDC_ROWS)


def redc(t):
    """(..., CONVLEN) conv coefficients -> (..., NLIMBS) loose limbs of
    t * 2^-440 mod p. Exact for any signed t with |t_k| < 2^28."""
    a = t & MASK
    b = (t >> BASE) & MASK
    c = t >> (2 * BASE)  # signed, |c| <= 2^8
    planes = jnp.concatenate([a, b, c], axis=-1)  # (..., 3*CONVLEN)
    return crush(planes @ REDC_M, 3)


def crush(t, rounds: int = 2):
    """Modular carry fold: per-limb overflow moves one limb up; the top
    limb's carry wraps around through FOLD_LIMBS (2^440 mod p). Exactly
    preserves the value mod p for ANY signed input; arithmetic shifts
    handle borrows."""
    for _ in range(rounds):
        carry = t >> BASE
        top = carry[..., -1:]
        t = (
            (t & MASK)
            + jnp.pad(carry[..., :-1], [(0, 0)] * (t.ndim - 1) + [(1, 0)])
            + top * FOLD_LIMBS
        )
    return t


def add(x, y):
    # crush(1) suffices: inputs have |limb| <= ~2^11.2, so one round leaves
    # |limb| <= 2^10 + 4 + 4*(2^10-1) < 2^12.1 and the conv bound
    # 44*(2^12.1)^2 < 2^30.5 still clears int32
    return crush(x + y, 1)


def sub(x, y):
    return crush(x - y, 1)


def neg(x):
    return crush(-x, 1)


def mont_mul(x, y):
    """x * y * 2^-440 mod p in loose form: one conv + one REDC matmul +
    one crush. No sequential reduction rounds at all."""
    x, y = jnp.broadcast_arrays(x, y)
    return redc(_conv(x, y))


def mont_sqr(x):
    return mont_mul(x, x)


def mul_small(x, k: int):
    """x * k for a small int k (|k| <= ~16): exact, crushed."""
    return crush(x * k)
