"""Pallas G1 engine: VMEM-resident field/point kernels — the round-3 rework.

Round 2's kernel (ops/fpl.py + ops/msm.py) was HBM-bound: XLA materializes
the (lanes, 44, 44) conv outer product of every mont_mul — ~127 MB written
and re-read per multiply (ROUND2_NOTES #1; chunked conv, pad/skew conv and
f32-MXU variants were all probed and did NOT help — it's traffic, not
arithmetic). This module moves the whole windowed-MSM hot path into Pallas
kernels where every intermediate — conv coefficients, reduction planes,
point temporaries, the window accumulator itself — lives in VMEM. Only
32-lane-wide point state crosses HBM, once per window step.

Design (differences from ops/fpl.py, all kernel-boundary-compatible):

  * PLAIN field representation, not Montgomery. Reduction of the 87 conv
    coefficients happens by folding through precomputed residue rows
    M[l, 87j+k] = limbs(2^(10(k+j)) mod p) — structurally the round-2 REDC
    matmul without the R^-1 factor. With no Montgomery scale the host
    marshal needs no R-multiplication and no affine normalization: points
    upload as raw Jacobian limbs, which deletes the per-era batch-inversion
    loop from the host path entirely.
  * The fold matmul runs on the MXU in f32 with the matrix split into two
    5-bit halves and `precision=HIGHEST`: |plane| <= 2^10, half-entries
    < 2^5, products < 2^15, 261-term dot products < 2^23.03 < 2^24 — every
    partial sum is an exactly-representable f32 integer (probed on-device;
    DEFAULT precision is a single bf16 pass and is NOT exact).
  * conv uses only static sublane slices (Mosaic has no dynamic_slice):
    t = sum_i x[i] * ypad[43-i : 130-i] over a zero-padded y — 44 fused
    multiply-adds of (87, B) tiles, no scatter.
  * The MSM is ONE pallas_call with grid (lane_tiles, windows): the window
    axis iterates innermost with the accumulator block held in VMEM across
    iterations (its index map ignores the window index), so the 4-dbl +
    gather-select + add body never round-trips HBM. Table entries are
    gathered per window by XLA outside the kernel (528 B/lane/window).
  * The verifier RLC lanes run a separate 16-window pass (64-bit
    coefficients) instead of riding zero-padded in the 32-window GLV pass —
    the round-2 kernel paid 16 dead windows on those lanes (~15%).

Magnitude invariants (fuzz-checked in tests/test_pg1.py):
  crushed limbs |l| <= 2^11.2 (ops/fpl.py invariant, same crush);
  add/sub outputs after crush(1) <= 2^12.1; conv accumulators
  44 * 2^12.1^2 < 2^29.7 (int32 safe); fold planes in [-2^10, 2^10);
  fold output < 33 * 2^23.03 < 2^28.1, crush(3) closes.

Reference role: batched replacement for the serial per-share MCL pairing
loop (/root/reference/src/Lachain.Crypto/TPKE/PublicKey.cs:55-92 via
HoneyBadger.cs:205-247), same role as ops/msm.py which remains the
non-Pallas fallback (and the multi-chip shard_map path).
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import msm
from ..crypto import bls12381 as bls

NLIMBS = 44
BASE = 10
MASK = (1 << BASE) - 1
CONVLEN = 2 * NLIMBS - 1  # 87
P_INT = bls.P
POINT_ROWS = 3 * NLIMBS  # 132: X | Y | Z stacked on the sublane axis

WINDOW = 4
TABLE = 1 << WINDOW
W64 = 64 // WINDOW  # 16 windows: verifier RLC pass
W128 = 128 // WINDOW  # 32 windows: GLV-half pass

LANE_TILE = 256  # lanes per grid step; all widths pad to a multiple.
# 512 blows the 16 MB scoped-VMEM budget in the msm kernel (the resident
# 16-entry table block is 4.3 MB at 512 plus double-buffering + transients).

# interpret mode on non-TPU backends (CPU tests); compiled on the chip
INTERPRET = jax.default_backend() != "tpu"


def _int_to_limbs(v: int) -> np.ndarray:
    return np.array(
        [(v >> (BASE * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
    )


# fold matrix: column (j, k) row l = limbs(2^(10(k+j)) mod p)[l]; split in
# 5-bit halves so each f32 product is < 2^15 and 261-term sums stay exact
_FOLD_M = np.zeros((NLIMBS, 3 * CONVLEN), dtype=np.int32)
for _j in range(3):
    for _k in range(CONVLEN):
        _FOLD_M[:, _j * CONVLEN + _k] = _int_to_limbs(
            (1 << (BASE * (_k + _j))) % P_INT
        )
_FOLD_LO = jnp.asarray((_FOLD_M & 31).astype(np.float32))
_FOLD_HI = jnp.asarray((_FOLD_M >> 5).astype(np.float32))
# top-carry wrap constant for crush: 2^440 mod p, as a (44, 1) column
_WRAP_COL = jnp.asarray(_int_to_limbs((1 << (BASE * NLIMBS)) % P_INT)[:, None])

_HIGHEST = jax.lax.Precision.HIGHEST


# ---------------------------------------------------------------------------
# in-kernel field helpers (operate on jnp values inside pallas bodies)
# ---------------------------------------------------------------------------


def _crush(t, wrap, rounds: int = 1):
    """Modular carry fold (ops/fpl.py:crush semantics): per-limb overflow
    moves one limb up, the top limb's carry wraps through 2^440 mod p.
    Exact for any signed input. `wrap` is the (44, 1) 2^440-mod-p column
    (pallas kernels cannot capture constant arrays — every kernel threads
    the constants through as inputs)."""
    b = t.shape[-1]
    for _ in range(rounds):
        carry = t >> BASE
        top = carry[NLIMBS - 1 : NLIMBS, :]
        shifted = jnp.concatenate(
            [jnp.zeros((1, b), jnp.int32), carry[: NLIMBS - 1, :]], axis=0
        )
        t = (t & MASK) + shifted + top * wrap
    return t


def _conv(x, y):
    """(44, B) x (44, B) -> (87, B) conv coefficients; static slices only
    (one FMA per x-limb against a shifted window of zero-padded y)."""
    b = x.shape[-1]
    z43 = jnp.zeros((43, b), jnp.int32)
    ypad = jnp.concatenate([z43, y, z43], axis=0)  # (130, B); ypad[43+j]=y[j]
    t = jnp.zeros((CONVLEN, b), jnp.int32)
    for i in range(NLIMBS):
        t = t + x[i : i + 1, :] * ypad[43 - i : 130 - i, :]
    return t


def _fold(t, c):
    """(87, B) conv coefficients -> (44, B) crushed limbs of t mod p.
    Plane split keeps every f32 product/partial-sum exactly representable.
    `c` = (fold_lo, fold_hi, wrap) constant refs' values."""
    mlo, mhi, wrap = c
    a = t & MASK
    bb = (t >> BASE) & MASK
    cc = t >> (2 * BASE)  # signed, |cc| <= 2^10 for |t| < 2^30
    planes = jnp.concatenate([a, bb, cc], axis=0).astype(jnp.float32)
    lo = jnp.dot(mlo, planes, preferred_element_type=jnp.float32,
                 precision=_HIGHEST)
    hi = jnp.dot(mhi, planes, preferred_element_type=jnp.float32,
                 precision=_HIGHEST)
    r = lo.astype(jnp.int32) + (hi.astype(jnp.int32) << 5)
    return _crush(r, wrap, 3)


def _mul(x, y, c):
    return _fold(_conv(x, y), c)


def _sqr(x, c):
    return _mul(x, x, c)


def _add(x, y, c):
    return _crush(x + y, c[2], 1)


def _sub(x, y, c):
    return _crush(x - y, c[2], 1)


def _mul_small(x, k: int, c):
    return _crush(x * k, c[2], 2)


# ---------------------------------------------------------------------------
# in-kernel group law (Jacobian, incomplete — flags carried outside)
# ---------------------------------------------------------------------------


def _g1_dbl_val(p, c):
    """(132, B) -> (132, B); same formulas as ops/msm.py:g1_dbl."""
    X1, Y1, Z1 = p[0:44], p[44:88], p[88:132]
    A = _sqr(X1, c)
    B = _sqr(Y1, c)
    C = _sqr(B, c)
    D = _sub(_sub(_sqr(_add(X1, B, c), c), A, c), C, c)
    D = _add(D, D, c)
    E = _mul_small(A, 3, c)
    F = _sqr(E, c)
    X3 = _sub(F, _add(D, D, c), c)
    Y3 = _sub(_mul(E, _sub(D, X3, c), c), _mul_small(C, 8, c), c)
    Z3 = _mul(Y1, Z1, c)
    Z3 = _add(Z3, Z3, c)
    return jnp.concatenate([X3, Y3, Z3], axis=0)


def _g1_add_val(p, q, c):
    """(132, B) x (132, B) -> (132, B); requires p != +-q, both finite
    (ops/msm.py:g1_add_incomplete formulas)."""
    X1, Y1, Z1 = p[0:44], p[44:88], p[88:132]
    X2, Y2, Z2 = q[0:44], q[44:88], q[88:132]
    Z1Z1 = _sqr(Z1, c)
    Z2Z2 = _sqr(Z2, c)
    U1 = _mul(X1, Z2Z2, c)
    U2 = _mul(X2, Z1Z1, c)
    S1 = _mul(_mul(Y1, Z2, c), Z2Z2, c)
    S2 = _mul(_mul(Y2, Z1, c), Z1Z1, c)
    H = _sub(U2, U1, c)
    Rr = _sub(S2, S1, c)
    I = _sqr(_add(H, H, c), c)
    J = _mul(H, I, c)
    Rr2 = _add(Rr, Rr, c)
    V = _mul(U1, I, c)
    X3 = _sub(_sub(_sqr(Rr2, c), J, c), _add(V, V, c), c)
    S1J = _mul(S1, J, c)
    Y3 = _sub(_mul(Rr2, _sub(V, X3, c), c), _add(S1J, S1J, c), c)
    Z3 = _mul(_mul(Z1, Z2, c), H, c)
    Z3 = _add(Z3, Z3, c)
    return jnp.concatenate([X3, Y3, Z3], axis=0)


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


def _pad_lanes(a, width: int):
    if a.shape[-1] == width:
        return a
    pad = width - a.shape[-1]
    return jnp.concatenate(
        [a, jnp.zeros(a.shape[:-1] + (pad,), a.dtype)], axis=-1
    )


def _tile_width(n: int) -> int:
    # interpret mode (CPU tests) has no 128-lane hardware tiling constraint;
    # a small floor keeps the per-step jnp ops tiny-shaped and the suite fast
    floor = 8 if INTERPRET else 128
    return min(LANE_TILE, max(floor, n))


def _padded(n: int) -> int:
    t = _tile_width(n)
    return ((n + t - 1) // t) * t


def _consts(mlo_ref, mhi_ref, wrap_ref):
    return (mlo_ref[:], mhi_ref[:], wrap_ref[:])


def _dbl_kernel(mlo_ref, mhi_ref, wrap_ref, p_ref, o_ref):
    o_ref[:] = _g1_dbl_val(p_ref[:], _consts(mlo_ref, mhi_ref, wrap_ref))


def _add_kernel(mlo_ref, mhi_ref, wrap_ref, p_ref, q_ref, o_ref):
    o_ref[:] = _g1_add_val(p_ref[:], q_ref[:],
                           _consts(mlo_ref, mhi_ref, wrap_ref))


def _mul_kernel(mlo_ref, mhi_ref, wrap_ref, x_ref, y_ref, o_ref):
    o_ref[:] = _mul(x_ref[:], y_ref[:], _consts(mlo_ref, mhi_ref, wrap_ref))


_CONST_SPECS = [
    pl.BlockSpec((NLIMBS, 3 * CONVLEN), lambda *g: (0, 0),
                 memory_space=pltpu.VMEM),
    pl.BlockSpec((NLIMBS, 3 * CONVLEN), lambda *g: (0, 0),
                 memory_space=pltpu.VMEM),
    pl.BlockSpec((NLIMBS, 1), lambda *g: (0, 0), memory_space=pltpu.VMEM),
]


def _const_args():
    return (_FOLD_LO, _FOLD_HI, _WRAP_COL)


def pl_dbl(p):
    """(132, n) -> (132, n) Jacobian doubling on-device."""
    if INTERPRET:
        return _g1_dbl_val(p, _const_args())
    n = p.shape[-1]
    w = _padded(n)
    t = _tile_width(n)
    out = pl.pallas_call(
        _dbl_kernel,
        grid=(w // t,),
        in_specs=_CONST_SPECS + [
            pl.BlockSpec((POINT_ROWS, t), lambda i: (0, i),
                         memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((POINT_ROWS, t), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((POINT_ROWS, w), jnp.int32),
        interpret=INTERPRET,
    )(*_const_args(), _pad_lanes(p, w))
    return out[:, :n]


def pl_add(p, q):
    """(132, n) x (132, n) -> (132, n) incomplete Jacobian add on-device."""
    if INTERPRET:
        return _g1_add_val(p, q, _const_args())
    n = p.shape[-1]
    w = _padded(n)
    t = _tile_width(n)
    out = pl.pallas_call(
        _add_kernel,
        grid=(w // t,),
        in_specs=_CONST_SPECS + [
            pl.BlockSpec((POINT_ROWS, t), lambda i: (0, i),
                         memory_space=pltpu.VMEM)
        ] * 2,
        out_specs=pl.BlockSpec((POINT_ROWS, t), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((POINT_ROWS, w), jnp.int32),
        interpret=INTERPRET,
    )(*_const_args(), _pad_lanes(p, w), _pad_lanes(q, w))
    return out[:, :n]


def pl_fp_mul(x, y):
    """(44, n) x (44, n) -> (44, n) field multiply on-device."""
    if INTERPRET:
        return _mul(x, y, _const_args())
    n = x.shape[-1]
    w = _padded(n)
    t = _tile_width(n)
    out = pl.pallas_call(
        _mul_kernel,
        grid=(w // t,),
        in_specs=_CONST_SPECS + [
            pl.BlockSpec((NLIMBS, t), lambda i: (0, i),
                         memory_space=pltpu.VMEM)
        ] * 2,
        out_specs=pl.BlockSpec((NLIMBS, t), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((NLIMBS, w), jnp.int32),
        interpret=INTERPRET,
    )(*_const_args(), _pad_lanes(x, w), _pad_lanes(y, w))
    return out[:, :n]


def _select_entry(table, d):
    """(16, 132, B) table, (1, B) digit -> (132, B) entry: 15 masked adds
    in VMEM. Entry 0 never contributes (flag logic handles digit 0), so the
    sum starts from entry 1 and a zero base."""
    e = jnp.zeros_like(table[0])
    for k in range(1, TABLE):
        e = e + jnp.where(d == k, table[k], 0)
    return e


def _msm_kernel(mlo_ref, mhi_ref, wrap_ref, table_ref, dig_ref,
                acc_ref, flag_ref):
    """Grid (tiles, windows), window innermost. The acc/flag blocks' index
    maps ignore the window axis, so Mosaic keeps them resident in VMEM
    across the whole window scan and writes HBM once per lane tile. The
    TABLE block's map also ignores the window axis: the 16-entry table is
    DMA'd once per lane tile and every per-window entry is a VMEM select —
    the round-3-alpha XLA take_along_axis gather cost 500 ms/era in HBM."""
    c = _consts(mlo_ref, mhi_ref, wrap_ref)
    w = pl.program_id(1)
    d = dig_ref[0]  # (1, B)
    keep = d == 0
    entry = _select_entry(table_ref[:], d)

    @pl.when(w == 0)
    def _():
        acc_ref[:] = entry
        flag_ref[:] = keep.astype(jnp.int32)

    @pl.when(w > 0)
    def _():
        acc = acc_ref[:]
        flag = flag_ref[:] != 0
        # fori (not an unrolled loop): one dbl body in the trace keeps the
        # Mosaic compile inside the 60 s budget the driver enforces
        acc = jax.lax.fori_loop(
            0, WINDOW, lambda _, a: _g1_dbl_val(a, c), acc
        )
        added = _g1_add_val(acc, entry, c)
        acc_new = jnp.where(keep, acc, jnp.where(flag, entry, added))
        acc_ref[:] = acc_new
        flag_ref[:] = (flag & keep).astype(jnp.int32)


def _msm_emulate(table, digits):
    """INTERPRET-mode path: run the exact same per-window math as
    _msm_kernel, as plain jitted jnp on full width (pallas interpret mode
    executes op-by-op and is ~100x slower than this on the CPU suite; the
    shared body functions keep the coverage honest, and the pallas plumbing
    itself is exercised by the TPU-gated test + the driver compile check)."""
    c = _const_args()
    acc = None
    flag = None
    for w in range(digits.shape[0]):
        d = digits[w]  # (1, n)
        keep = d == 0
        entry = _select_entry(table, d)
        if acc is None:
            acc, flag = entry, keep
            continue
        a4 = jax.lax.fori_loop(0, WINDOW, lambda _, a: _g1_dbl_val(a, c), acc)
        added = _g1_add_val(a4, entry, c)
        acc = jnp.where(keep, a4, jnp.where(flag, entry, added))
        flag = flag & keep
    return acc, flag[0]


def _msm_scan(table, digits):
    """table (16, 132, n), digits (W, 1, n) -> ((132, n), (n,) inf flags).
    One pallas_call; accumulator and table stay in VMEM across windows."""
    if INTERPRET:
        return _msm_emulate(table, digits)
    nw = digits.shape[0]
    n = table.shape[-1]
    w = _padded(n)
    t = _tile_width(n)
    table = _pad_lanes(table, w)
    digits = _pad_lanes(digits, w)  # pad digits 0 -> pad lanes stay flagged
    acc, flag = pl.pallas_call(
        _msm_kernel,
        grid=(w // t, nw),
        in_specs=_CONST_SPECS + [
            pl.BlockSpec((TABLE, POINT_ROWS, t), lambda i, j: (0, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, t), lambda i, j: (j, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((POINT_ROWS, t), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((POINT_ROWS, w), jnp.int32),
            jax.ShapeDtypeStruct((1, w), jnp.int32),
        ],
        interpret=INTERPRET,
    )(*_const_args(), table, digits)
    return acc[:, :n], flag[0, :n] != 0


def build_table(lanes):
    """(132, n) -> (16, 132, n): entry k = k*P (entry 0 zero, never
    selected thanks to digit flags). 13 chained adds + 1 dbl, each a
    VMEM-resident kernel launch."""
    two = pl_dbl(lanes)
    rows = [jnp.zeros_like(lanes), lanes, two]
    cur = two
    for _ in range(TABLE - 3):
        cur = pl_add(cur, lanes)
        rows.append(cur)
    return jnp.stack(rows, axis=0)


def msm_windowed(lanes, digits):
    """Windowed MSM: lanes (132, n), digits (W, n) MSB-first 4-bit.
    Returns ((132, n) accumulators, (n,) infinity flags)."""
    table = build_table(lanes)
    return _msm_scan(table, digits[:, None, :])


def tree_reduce_k(acc, flags, k: int):
    """Sum groups of k adjacent lanes (k power of two) with explicit
    infinity flags. acc (132, n), flags (n,) -> (132, n/k), (n/k,)."""
    assert k & (k - 1) == 0
    while k > 1:
        a, b = acc[:, 0::2], acc[:, 1::2]
        fa, fb = flags[0::2], flags[1::2]
        r = pl_add(a, b)
        acc = jnp.where(fb[None, :], a, jnp.where(fa[None, :], b, r))
        flags = fa & fb
        k //= 2
    return acc, flags


# ---------------------------------------------------------------------------
# the era kernel: 2 passes (16-window RLC verify, 32-window GLV combine)
# ---------------------------------------------------------------------------

_BETA_COL = jnp.asarray(_int_to_limbs(msm.BETA)[:, None])


def era_kernel(u, y, rlc16, lag1, lag2, k: int):
    """u, y: (132, S*K) share points / verification keys (plain Jacobian
    limbs); rlc16 (16, S*K); lag1, lag2 (32, S*K) GLV halves. k = K.

    Returns (rlc_pts (132, 2S), rlc_flags, lag_pts (132, 2S), lag_flags):
    per-slot u aggregates + y aggregates (verify), then comb1 + comb2
    halves (combine). Host adds comb1+comb2 and runs the grand pairing.
    """
    n = u.shape[-1]
    beta = jnp.broadcast_to(_BETA_COL, (NLIMBS, n))
    phi_x = pl_fp_mul(u[0:44], beta)
    phi_u = jnp.concatenate([phi_x, u[44:132]], axis=0)

    lanes_rlc = jnp.concatenate([u, y], axis=1)
    dig_rlc = jnp.concatenate([rlc16, rlc16], axis=1)
    lanes_lag = jnp.concatenate([u, phi_u], axis=1)
    dig_lag = jnp.concatenate([lag1, lag2], axis=1)

    acc_r, fl_r = msm_windowed(lanes_rlc, dig_rlc)
    acc_l, fl_l = msm_windowed(lanes_lag, dig_lag)
    out_r, ofl_r = tree_reduce_k(acc_r, fl_r, k)
    out_l, ofl_l = tree_reduce_k(acc_l, fl_l, k)
    return out_r, ofl_r, out_l, ofl_l


era_kernel_jit = jax.jit(era_kernel, static_argnames=("k",))


def era_kernel_fused(u, y, rlc16, lag1, lag2, k: int):
    """era_kernel with all outputs fused into ONE (133, 4S) int32 array
    (row 132 carries the infinity flags): the axon tunnel charges ~110 ms
    fixed latency per distinct device->host buffer, so the era downloads
    exactly one."""
    out_r, ofl_r, out_l, ofl_l = era_kernel(u, y, rlc16, lag1, lag2, k)
    pts = jnp.concatenate([out_r, out_l], axis=1)  # (132, 4S)
    flags = jnp.concatenate([ofl_r, ofl_l]).astype(jnp.int32)[None, :]
    return jnp.concatenate([pts, flags], axis=0)  # (133, 4S)


era_kernel_fused_jit = jax.jit(era_kernel_fused, static_argnames=("k",))


def era_pack_inputs(u_np, rlc16, lag1, lag2) -> np.ndarray:
    """Pack all per-era device inputs into ONE uint8 buffer: u limbs as
    uint16 LE (values < 2^10), digit planes as uint8 (values < 16). One
    upload instead of four — the tunnel charges fixed latency per buffer —
    and 2.6x fewer bytes."""
    parts = [
        u_np.astype(np.uint16).tobytes(),
        rlc16.astype(np.uint8).tobytes(),
        lag1.astype(np.uint8).tobytes(),
        lag2.astype(np.uint8).tobytes(),
    ]
    return np.frombuffer(b"".join(parts), np.uint8)


def era_kernel_packed(buf, y, k: int, n: int):
    """Unpack the fused uint8 input buffer on device and run the era."""
    o = POINT_ROWS * n * 2
    u8 = buf[:o].reshape(POINT_ROWS, n, 2).astype(jnp.int32)
    u = u8[..., 0] + (u8[..., 1] << 8)
    r16 = buf[o : o + W64 * n].reshape(W64, n).astype(jnp.int32)
    o += W64 * n
    l1 = buf[o : o + W128 * n].reshape(W128, n).astype(jnp.int32)
    o += W128 * n
    l2 = buf[o : o + W128 * n].reshape(W128, n).astype(jnp.int32)
    return era_kernel_fused(u, y, r16, l1, l2, k)


era_kernel_packed_jit = jax.jit(era_kernel_packed, static_argnames=("k", "n"))


def msm_reduce(lanes, digits, k: int):
    """Windowed MSM + full tree reduce fused into one device program
    (single launch: on the axon tunnel every eager op is a network round
    trip, so the composed-eager version of this costs ~1000x more wall
    clock than the math). Returns (133, n/k): points + flag row."""
    acc, fl = msm_windowed(lanes, digits)
    out, ofl = tree_reduce_k(acc, fl, k)
    return jnp.concatenate(
        [out, ofl.astype(jnp.int32)[None, :]], axis=0
    )


msm_reduce_jit = jax.jit(msm_reduce, static_argnames=("k",))


# ---------------------------------------------------------------------------
# host marshal (plain form: no Montgomery scale, no batch inversion)
# ---------------------------------------------------------------------------


def g1_pack(points: Sequence[tuple]) -> np.ndarray:
    """Oracle Jacobian tuples -> (132, n) int32 plain limbs. Infinity maps
    to (0, 1, 0) — callers flag it separately (same contract as
    ops/msm.py:g1_to_device_loose, minus the affine normalization)."""
    xs = [p[0] if p[2] != 0 else 0 for p in points]
    ys = [p[1] if p[2] != 0 else 1 for p in points]
    zs = [p[2] for p in points]
    return np.concatenate(
        [
            msm._ints_to_limbs_np(xs),
            msm._ints_to_limbs_np(ys),
            msm._ints_to_limbs_np(zs),
        ],
        axis=1,
    ).T.copy()  # (n, 132) -> (132, n)


def g1_unpack(arr, flags=None) -> list:
    """(132, n) limbs (+ optional flags) -> oracle Jacobian tuples."""
    arr = np.asarray(arr)
    out = []
    for i in range(arr.shape[-1]):
        if flags is not None and bool(np.asarray(flags)[i]):
            out.append(bls.G1_INF)
            continue
        x = _limbs_int(arr[0:44, i])
        y = _limbs_int(arr[44:88, i])
        z = _limbs_int(arr[88:132, i])
        out.append(bls.G1_INF if z == 0 else (x, y, z))
    return out


def _limbs_int(a) -> int:
    v = sum(int(a[i]) << (BASE * i) for i in range(NLIMBS))
    return v % P_INT


def digits_col(scalars: Sequence[int], nwindows: int) -> np.ndarray:
    """ints -> (nwindows, n) MSB-first 4-bit digits (lane-last layout)."""
    return msm.scalars_to_digits(scalars, nwindows).T.copy()
