"""GF(2^8) Reed-Solomon erasure coding, vectorized over byte columns.

Parity with the reference's vendored RS codec
(/root/reference/src/Lachain.Consensus/ReliableBroadcast/ReedSolomon/,
GenericGF(285, 256, 0) per ErasureCoding.cs:14-16) used by ReliableBroadcast
to shard payloads (ReliableBroadcast.cs:393-444).

Design: Vandermonde-evaluation Reed-Solomon. A payload is split into K data
shards; each byte column of the K shards is a degree-(K-1) polynomial's
coefficient vector, evaluated at N fixed points to produce N code shards.
Any K received shards reconstruct by interpolation. All per-column work is
table-lookup + XOR over numpy arrays — the byte-parallel structure the
reference loops over serially (ReliableBroadcast.cs:408-416) — and is the
designated second TPU kernel (SURVEY.md §2a): gathers + XOR reductions map
directly onto vectorized device code.

Field: GF(2^8) with the reference's reduction polynomial x^8+x^4+x^3+x^2+1
(0x11D = 285).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

_POLY = 0x11D

# exp/log tables: generator 2 is primitive for 0x11D.
_EXP = np.zeros(512, dtype=np.uint8)
_LOG = np.zeros(256, dtype=np.int32)
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= _POLY
for _i in range(255, 512):
    _EXP[_i] = _EXP[_i - 255]


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(_EXP[_LOG[a] + _LOG[b]])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gf_inv(0)")
    return int(_EXP[255 - _LOG[a]])


def _gf_mul_vec(c: int, v: np.ndarray) -> np.ndarray:
    """c * v for a scalar c and uint8 vector v."""
    if c == 0:
        return np.zeros_like(v)
    if c == 1:
        return v.copy()
    out = np.zeros_like(v)
    nz = v != 0
    out[nz] = _EXP[_LOG[c] + _LOG[v[nz]]]
    return out


def _eval_points(n: int) -> List[int]:
    # x-coordinates 1..n (0 excluded so Vandermonde stays invertible)
    assert n < 256, "GF(2^8) RS supports at most 255 shards"
    return list(range(1, n + 1))


def encode(data: bytes, k: int, n: int) -> List[bytes]:
    """Split `data` into k data shards and RS-extend to n total shards.

    Shard layout: data is left-padded with a 4-byte length prefix then
    zero-padded to k * shard_size; shard j holds coefficient j of each column
    polynomial. Returns n shards of equal size.
    """
    assert 0 < k <= n
    if n > 255:
        # GF(2^8) has only 255 distinct evaluation points; past that the
        # codec switches to GF(2^16) symbols (rs_batch.py) behind the same
        # API — true coding up to 65535 shards, not the whole-payload
        # replication this branch used to fall back to. The native engine's
        # internal rs_encode keeps replication as ITS fallback when no RBC
        # host shim is attached (consensus_rt.cpp).
        from . import rs_batch

        return rs_batch.encode(data, k, n)
    prefixed = len(data).to_bytes(4, "big") + data
    shard_size = (len(prefixed) + k - 1) // k
    padded = prefixed + b"\x00" * (k * shard_size - len(prefixed))
    coeffs = np.frombuffer(padded, dtype=np.uint8).reshape(k, shard_size)
    shards = []
    for x in _eval_points(n):
        # Horner: p(x) = (...((c_{k-1} x) + c_{k-2}) x + ...) + c_0
        acc = np.zeros(shard_size, dtype=np.uint8)
        for j in range(k - 1, -1, -1):
            acc = _gf_mul_vec(x, acc) ^ coeffs[j]
        shards.append(acc.tobytes())
    return shards


def decode(shards: Sequence[Optional[bytes]], k: int) -> Optional[bytes]:
    """Reconstruct the payload from any k non-None shards.

    `shards` is the full n-length list with None for missing entries, in
    eval-point order. Returns None if fewer than k shards are present or the
    length prefix is inconsistent.
    """
    n = len(shards)
    have = [(i, s) for i, s in enumerate(shards) if s is not None]
    if len(have) < k:
        return None
    have = have[:k]
    size = len(have[0][1])
    # adversarial-input guard: a malicious proposer can commit a Merkle
    # root over DIFFERENT-SIZED shards (each with a valid branch); mixed
    # sizes must be a clean decode failure, not a crash (np.stack raises)
    if any(len(s) != size for _, s in have):
        return None
    if n > 255:
        # GF(2^16) symbols (see encode): delegate to the batched codec's
        # single-item path, which applies the same first-k / mixed-size /
        # length-prefix guards plus the even-byte symbol check
        from . import rs_batch

        return rs_batch.decode(shards, k)
    xs = [_eval_points(n)[i] for i, _ in have]
    mat = np.zeros((k, k), dtype=np.uint8)  # Vandermonde rows [x^0 .. x^{k-1}]
    for r, x in enumerate(xs):
        v = 1
        for c in range(k):
            mat[r, c] = v
            v = gf_mul(v, x)
    inv = _gf_mat_inv(mat)
    if inv is None:
        return None
    received = np.stack(
        [np.frombuffer(s, dtype=np.uint8) for _, s in have]
    )  # (k, size)
    coeffs = np.zeros((k, size), dtype=np.uint8)
    for r in range(k):
        acc = np.zeros(size, dtype=np.uint8)
        for c in range(k):
            acc ^= _gf_mul_vec(int(inv[r, c]), received[c])
        coeffs[r] = acc
    flat = coeffs.reshape(-1).tobytes()
    if len(flat) < 4:
        return None
    length = int.from_bytes(flat[:4], "big")
    if length > len(flat) - 4:
        return None
    return flat[4 : 4 + length]


def reencode(shards: Sequence[Optional[bytes]], k: int) -> Optional[List[bytes]]:
    """Reconstruct ALL n shards from any k (for Merkle-root recheck in RBC)."""
    n = len(shards)
    payload = decode(shards, k)
    if payload is None:
        return None
    return encode(payload, k, n)


def _gf_mat_inv(mat: np.ndarray) -> Optional[np.ndarray]:
    """Gauss-Jordan inversion over GF(2^8)."""
    k = mat.shape[0]
    a = mat.astype(np.int32).copy()
    inv = np.eye(k, dtype=np.int32)
    for col in range(k):
        piv = None
        for r in range(col, k):
            if a[r, col] != 0:
                piv = r
                break
        if piv is None:
            return None
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            inv[[col, piv]] = inv[[piv, col]]
        pinv = gf_inv(int(a[col, col]))
        for c in range(k):
            a[col, c] = gf_mul(int(a[col, c]), pinv)
            inv[col, c] = gf_mul(int(inv[col, c]), pinv)
        for r in range(k):
            if r == col or a[r, col] == 0:
                continue
            f = int(a[r, col])
            for c in range(k):
                a[r, c] ^= gf_mul(f, int(a[col, c]))
                inv[r, c] ^= gf_mul(f, int(inv[col, c]))
    return inv.astype(np.uint8)
