"""Batched Reed-Solomon over GF(2^8)/GF(2^16): Vandermonde matrix form.

The scalar codec (ops/rs.py) walks one payload at a time: Horner evaluation
per shard on encode, a per-item Gauss-Jordan + row accumulation on decode.
Algebraically both are matrix products — encode is `V @ C` for the n x k
Vandermonde V (rows [x^0 .. x^{k-1}] at x = 1..n) against the k x L
coefficient matrix C, and decode is `inv(V_sel) @ R` for the received rows.
This module computes them that way, batched: all pending items that share a
(field, k, n) — or for decode a (field, k, erasure-pattern) — are
column-concatenated into ONE matrix product per group, which is the shape
"the designated second TPU kernel" (ops/rs.py docstring, PAPER.md §2a)
wants: a log/exp table gather plus an XOR reduction over the contraction
axis. When a non-CPU jax backend is visible (or LACHAIN_RS_DEVICE=1 forces
it) the product is jitted and dispatched to the device, sharded across the
PR 14 mesh along the column (slot-payload) axis; otherwise the same gather +
XOR runs vectorized in numpy. Both paths use the identical exp/log tables,
so results are bit-identical to ops/rs.py (tests/test_rs_batch.py pins a
200-seed differential).

GF(2^16) (poly x^16+x^12+x^3+x+1 = 0x1100B, generator 2) backs shard counts
past GF(2^8)'s 255 evaluation points: symbols are big-endian uint16 pairs,
shard byte sizes are even, and an odd-sized shard is a clean decode failure.
This removes the n > 255 whole-payload replication fallback that capped
honest coding at N=255 (consensus_rt.cpp keeps replication as its
engine-internal fallback when no host shim is attached).
"""
from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import tracing

logger = logging.getLogger("lachain.rs_batch")

# device dispatch is worth its ferry cost only past a column threshold;
# below it the numpy path wins outright
_DEVICE_MIN_COLS = 4096


class GF:
    """A binary field GF(2^bits) with exp/log tables (generator 2)."""

    def __init__(self, bits: int, poly: int):
        self.bits = bits
        self.order = (1 << bits) - 1
        self.poly = poly
        self.dtype = np.uint8 if bits == 8 else np.uint16
        # big-endian wire dtype: shard bytes <-> symbol arrays
        self.be_dtype = np.uint8 if bits == 8 else np.dtype(">u2")
        self.sym_size = 1 if bits == 8 else 2
        exp = np.zeros(2 * self.order, dtype=self.dtype)
        log = np.zeros(1 << bits, dtype=np.int32)
        x = 1
        for i in range(self.order):
            exp[i] = x
            log[x] = i
            x <<= 1
            if x & (1 << bits):
                x ^= poly
        # generator 2 must cycle through every nonzero element exactly once
        assert x == 1, f"generator 2 is not primitive for poly {poly:#x}"
        exp[self.order :] = exp[: self.order]
        self.exp, self.log = exp, log

    def mul(self, a: int, b: int) -> int:
        if a == 0 or b == 0:
            return 0
        return int(self.exp[self.log[a] + self.log[b]])

    def inv(self, a: int) -> int:
        if a == 0:
            raise ZeroDivisionError("gf_inv(0)")
        return int(self.exp[self.order - self.log[a]])

    def matmul(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        """GF matrix product a (r,k) @ b (k,c): exp[log+log] gather with
        zero masks, XOR-accumulated over the contraction axis. The j-loop
        bounds peak memory at one (r,c) plane per step."""
        a = np.ascontiguousarray(a, dtype=self.dtype)
        b = np.ascontiguousarray(b, dtype=self.dtype)
        r, k = a.shape
        c = b.shape[1]
        out = np.zeros((r, c), dtype=self.dtype)
        log_b = self.log[b]  # (k, c)
        mask_b = b != 0
        log_a = self.log[a]  # (r, k)
        mask_a = a != 0
        for j in range(k):
            if not mask_a[:, j].any() or not mask_b[j].any():
                continue
            prod = self.exp[log_a[:, j, None] + log_b[j][None, :]]
            np.bitwise_xor(
                out,
                np.where(mask_a[:, j, None] & mask_b[j][None, :], prod, 0),
                out=out,
            )
        return out

    def mat_inv(self, mat: np.ndarray) -> Optional[np.ndarray]:
        """Gauss-Jordan inversion (first-nonzero pivot, same scan order as
        ops/rs.py::_gf_mat_inv); None when singular."""
        k = mat.shape[0]
        a = mat.astype(np.int64).copy()
        inv = np.eye(k, dtype=np.int64)
        exp, log, order = self.exp, self.log, self.order
        for col in range(k):
            piv = None
            for r in range(col, k):
                if a[r, col] != 0:
                    piv = r
                    break
            if piv is None:
                return None
            if piv != col:
                a[[col, piv]] = a[[piv, col]]
                inv[[col, piv]] = inv[[piv, col]]
            pinv = self.inv(int(a[col, col]))
            for row_arr in (a, inv):
                row = row_arr[col]
                nz = row != 0
                row[nz] = exp[log[row[nz]] + log[pinv]]
            for r in range(k):
                if r == col or a[r, col] == 0:
                    continue
                fac = int(a[r, col])
                for row_arr in (a, inv):
                    prow = row_arr[col]
                    nz = prow != 0
                    term = np.zeros(k, dtype=np.int64)
                    term[nz] = exp[log[prow[nz]] + log[fac]]
                    row_arr[r] ^= term
        return inv.astype(self.dtype)


GF8 = GF(8, 0x11D)  # matches ops/rs.py tables exactly

_GF16_CACHE: List[Optional[GF]] = [None]


def gf16() -> GF:
    """GF(2^16) built on first use (the 65535-step table bootstrap is not
    free; n <= 255 workloads never pay it)."""
    if _GF16_CACHE[0] is None:
        _GF16_CACHE[0] = GF(16, 0x1100B)
    return _GF16_CACHE[0]


def field_for(n: int) -> GF:
    if n <= 255:
        return GF8
    if n <= 65535:
        return gf16()
    raise ValueError(f"n={n} exceeds GF(2^16) evaluation points")


# -- cached per-(field, k, n) matrices ---------------------------------------

_VCACHE: Dict[Tuple[int, int, int], np.ndarray] = {}
_ICACHE: Dict[Tuple[int, int, Tuple[int, ...]], Optional[np.ndarray]] = {}
_CACHE_CAP = 512


def vandermonde(field: GF, k: int, n: int) -> np.ndarray:
    """n x k evaluation matrix: row i = [x^0 .. x^{k-1}] at x = i+1."""
    key = (field.bits, k, n)
    v = _VCACHE.get(key)
    if v is None:
        if len(_VCACHE) >= _CACHE_CAP:
            _VCACHE.clear()
        v = np.zeros((n, k), dtype=field.dtype)
        for r in range(n):
            acc = 1
            for c in range(k):
                v[r, c] = acc
                acc = field.mul(acc, r + 1)
        _VCACHE[key] = v
    return v


def _inverse_for(
    field: GF, k: int, xs: Tuple[int, ...]
) -> Optional[np.ndarray]:
    key = (field.bits, k, xs)
    if key in _ICACHE:
        return _ICACHE[key]
    if len(_ICACHE) >= _CACHE_CAP:
        _ICACHE.clear()
    mat = np.zeros((k, k), dtype=field.dtype)
    for r, x in enumerate(xs):
        acc = 1
        for c in range(k):
            mat[r, c] = acc
            acc = field.mul(acc, x)
    inv = field.mat_inv(mat)
    _ICACHE[key] = inv
    return inv


# -- device dispatch ---------------------------------------------------------

# {None: unprobed} -> bool; separate broken flag so one device failure
# degrades the process to numpy permanently instead of retrying every call
_DEVICE_ON: List[Optional[bool]] = [None]
_DEVICE_BROKEN: List[bool] = [False]
_JIT_CACHE: Dict[int, object] = {}
_EXP_DEV: Dict[int, object] = {}


def device_enabled() -> bool:
    """True when RS matmuls should dispatch to a jax device. Env knob
    LACHAIN_RS_DEVICE: "1" forces on, "0" forces off; unset auto-enables
    iff the default jax backend is not the CPU interpreter."""
    if _DEVICE_ON[0] is None:
        env = os.environ.get("LACHAIN_RS_DEVICE")
        if env == "0":
            _DEVICE_ON[0] = False
        elif env == "1":
            _DEVICE_ON[0] = True
        else:
            try:
                import jax

                _DEVICE_ON[0] = jax.default_backend() != "cpu"
            except Exception:
                _DEVICE_ON[0] = False
    return bool(_DEVICE_ON[0]) and not _DEVICE_BROKEN[0]


def _device_jit(bits: int):
    fn = _JIT_CACHE.get(bits)
    if fn is None:
        import jax

        def _mm(exp, log_a, mask_a, log_b, mask_b):
            import jax.numpy as jnp

            def body(j, acc):
                la = jax.lax.dynamic_slice_in_dim(log_a, j, 1, 1)  # (r,1)
                ma = jax.lax.dynamic_slice_in_dim(mask_a, j, 1, 1)
                lb = jax.lax.dynamic_slice_in_dim(log_b, j, 1, 0)  # (1,c)
                mb = jax.lax.dynamic_slice_in_dim(mask_b, j, 1, 0)
                prod = jnp.where(ma & mb, exp[la + lb], 0).astype(exp.dtype)
                return acc ^ prod

            import jax.numpy as jnp

            acc0 = jnp.zeros(
                (log_a.shape[0], log_b.shape[1]), dtype=exp.dtype
            )
            return jax.lax.fori_loop(0, log_a.shape[1], body, acc0)

        fn = _JIT_CACHE[bits] = jax.jit(_mm)
    return fn


def _matmul_device(field: GF, a: np.ndarray, b: np.ndarray, era=None):
    """One jitted gather+XOR matmul on the device, columns padded to a
    power of two and (when the mesh has >1 device) sharded along the
    column axis — each device owns a contiguous run of slot payloads."""
    import jax

    a = np.ascontiguousarray(a, dtype=field.dtype)
    b = np.ascontiguousarray(b, dtype=field.dtype)
    c = b.shape[1]
    ndev = jax.device_count()
    c_pad = max(ndev, 1)
    while c_pad < c:
        c_pad *= 2
    b_pad = np.zeros((b.shape[0], c_pad), dtype=field.dtype)
    b_pad[:, :c] = b
    log_a = field.log[a]
    log_b = field.log[b_pad]
    mask_a = a != 0
    mask_b = b_pad != 0
    with tracing.span(
        "rs.device",
        era=era,
        bits=field.bits,
        rows=int(a.shape[0]),
        cols=int(c),
        cols_padded=int(c_pad),
        devices=int(ndev),
    ):
        exp_dev = _EXP_DEV.get(field.bits)
        if exp_dev is None:
            exp_dev = _EXP_DEV[field.bits] = jax.device_put(field.exp)
        args = (log_b, mask_b)
        if ndev > 1 and c_pad % ndev == 0:
            try:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                from ..parallel.mesh import make_mesh

                sharding = NamedSharding(make_mesh(), P(None, "shares"))
                args = tuple(jax.device_put(x, sharding) for x in args)
            except Exception:  # pragma: no cover - mesh-less jax builds
                pass
        out = _device_jit(field.bits)(exp_dev, log_a, mask_a, *args)
        out = np.asarray(jax.device_get(out))
    return out[:, :c]


def _matmul(field: GF, a: np.ndarray, b: np.ndarray, era=None) -> np.ndarray:
    if b.shape[1] >= _DEVICE_MIN_COLS and device_enabled():
        try:
            return _matmul_device(field, a, b, era=era)
        except Exception:
            _DEVICE_BROKEN[0] = True
            logger.exception(
                "RS device matmul failed; numpy fallback for this process"
            )
    return field.matmul(a, b)


# -- batched codec -----------------------------------------------------------


def _coeff_matrix(field: GF, data: bytes, k: int) -> np.ndarray:
    """Length-prefix + zero-pad `data` into the k x L coefficient matrix
    (L in field symbols), mirroring ops/rs.py::encode's layout."""
    prefixed = len(data).to_bytes(4, "big") + data
    unit = k * field.sym_size
    shard_syms = (len(prefixed) + unit - 1) // unit
    shard_syms = max(shard_syms, 1)
    padded = prefixed + b"\x00" * (unit * shard_syms - len(prefixed))
    return (
        np.frombuffer(padded, dtype=field.be_dtype)
        .reshape(k, shard_syms)
        .astype(field.dtype)
    )


def encode_batch(
    items: Sequence[Tuple[bytes, int, int]], era: Optional[int] = None
) -> List[List[bytes]]:
    """Encode many (data, k, n) payloads; one matrix product per (field,
    k, n) group. Returns per-item n-shard lists, ops/rs.py-bit-identical
    for n <= 255 and GF(2^16)-coded past that."""
    results: List[Optional[List[bytes]]] = [None] * len(items)
    groups: Dict[Tuple[int, int, int], List[int]] = {}
    for idx, (data, k, n) in enumerate(items):
        assert 0 < k <= n
        field = field_for(n)
        groups.setdefault((field.bits, k, n), []).append(idx)
    for (bits, k, n), members in groups.items():
        field = GF8 if bits == 8 else gf16()
        v = vandermonde(field, k, n)
        coeffs = [_coeff_matrix(field, items[i][0], k) for i in members]
        widths = [c.shape[1] for c in coeffs]
        out = _matmul(field, v, np.concatenate(coeffs, axis=1), era=era)
        off = 0
        for i, w in zip(members, widths):
            block = out[:, off : off + w]
            off += w
            results[i] = [
                block[r].astype(field.be_dtype).tobytes() for r in range(n)
            ]
    return results  # type: ignore[return-value]


def decode_batch(
    items: Sequence[Tuple[Sequence[Optional[bytes]], int]],
    era: Optional[int] = None,
) -> List[Optional[bytes]]:
    """Decode many (shards, k) items; shards is the full n-length list with
    None for missing entries. One matrix product per (field, k, erasure
    pattern) group; per-item None on any of the scalar path's failure
    conditions (short, mixed-size, odd GF(2^16) size, bad length prefix)."""
    results: List[Optional[bytes]] = [None] * len(items)
    groups: Dict[Tuple[int, int, Tuple[int, ...]], List[int]] = {}
    sel: List[Optional[Tuple[GF, List[Tuple[int, bytes]]]]] = [None] * len(
        items
    )
    for idx, (shards, k) in enumerate(items):
        n = len(shards)
        field = field_for(n)
        have = [(i, s) for i, s in enumerate(shards) if s is not None]
        if len(have) < k:
            continue
        have = have[:k]
        size = len(have[0][1])
        if any(len(s) != size for _, s in have):
            continue  # adversarial mixed-size commitment: clean failure
        if size % field.sym_size:
            continue  # GF(2^16): odd byte length cannot be symbols
        xs = tuple(i + 1 for i, _ in have)
        sel[idx] = (field, have)
        groups.setdefault((field.bits, k, xs), []).append(idx)
    for (bits, k, xs), members in groups.items():
        field = GF8 if bits == 8 else gf16()
        inv = _inverse_for(field, k, xs)
        if inv is None:
            continue  # singular selection: every member fails cleanly
        received = []
        widths = []
        for i in members:
            _field, have = sel[i]
            mat = np.stack(
                [
                    np.frombuffer(s, dtype=field.be_dtype).astype(field.dtype)
                    for _idx, s in have
                ]
            )
            received.append(mat)
            widths.append(mat.shape[1])
        out = _matmul(field, inv, np.concatenate(received, axis=1), era=era)
        off = 0
        for i, w in zip(members, widths):
            coeffs = out[:, off : off + w]
            off += w
            flat = coeffs.astype(field.be_dtype).tobytes()
            if len(flat) < 4:
                continue
            length = int.from_bytes(flat[:4], "big")
            if length > len(flat) - 4:
                continue
            results[i] = flat[4 : 4 + length]
    return results


def encode(data: bytes, k: int, n: int) -> List[bytes]:
    """Single-item convenience (ops/rs.py delegates its n > 255 branch
    here; the differential tests drive it across both fields)."""
    return encode_batch([(data, k, n)])[0]


def decode(shards: Sequence[Optional[bytes]], k: int) -> Optional[bytes]:
    return decode_batch([(shards, k)])[0]
