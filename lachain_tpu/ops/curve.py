"""Batched BLS12-381 G1 point arithmetic + MSM in JAX.

The TPU hot path of the framework: where the reference verifies decryption /
signature shares one at a time with 2 pairings each
(/root/reference/src/Lachain.Crypto/TPKE/PublicKey.cs:88-92,
ThresholdSignature/ThresholdSigner.cs:45-95), lachain-tpu reduces a whole
share batch to multi-scalar multiplications (see crypto/tpke.py
batch_verify_shares) and runs THOSE here, batched over the share axis.

Representation: Jacobian (X, Y, Z) with each coordinate a 32x12-bit Montgomery
limb vector (ops/fp.py); a point is an int32 array (..., 3, NLIMBS). Z == 0
encodes infinity. The group law is branchless: generic-add, doubling and
infinity cases are all computed and merged with jnp.where, so the same traced
program serves every input — the XLA-friendly equivalent of the branchy
Jacobian add in the native backend (bls381.cpp g1_add).

Fp2/G2 batched arithmetic: same design, components stacked on an extra axis
(..., 2, NLIMBS); G2 points are (..., 3, 2, NLIMBS).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from . import fp
from ..crypto import bls12381 as bls

# ---------------------------------------------------------------------------
# host <-> device point conversion
# ---------------------------------------------------------------------------


def g1_to_device(points) -> np.ndarray:
    """List of oracle G1 Jacobian tuples -> (n, 3, NLIMBS) Montgomery array."""
    out = np.zeros((len(points), 3, fp.NLIMBS), dtype=np.int32)
    for i, pt in enumerate(points):
        aff = bls.g1_to_affine(pt)
        if aff is None:
            out[i, 1] = fp.to_mont_host(1)  # (0, 1, 0) = infinity
        else:
            out[i, 0] = fp.to_mont_host(aff[0])
            out[i, 1] = fp.to_mont_host(aff[1])
            out[i, 2] = fp.to_mont_host(1)
    return out


def g1_from_device(arr) -> list:
    """(n, 3, NLIMBS) -> list of oracle G1 tuples."""
    arr = np.asarray(arr)
    out = []
    for i in range(arr.shape[0]):
        x = fp.from_mont_host(arr[i, 0])
        y = fp.from_mont_host(arr[i, 1])
        z = fp.from_mont_host(arr[i, 2])
        out.append((x, y, z))
    return out


def scalars_to_bits(scalars, nbits: int = 256) -> np.ndarray:
    """List of ints -> (n, nbits) int32 bit matrix, MSB first."""
    out = np.zeros((len(scalars), nbits), dtype=np.int32)
    for i, s in enumerate(scalars):
        for b in range(nbits):
            out[i, b] = (s >> (nbits - 1 - b)) & 1
    return out


# ---------------------------------------------------------------------------
# batched group law
# ---------------------------------------------------------------------------


def g1_inf_like(p):
    """Infinity point(s) with the same batch shape as p.

    Derived from p (not fresh constants) so the varying-axes type matches p
    under shard_map — required when used as a lax.scan carry init.
    """
    x = p[..., 0, :] * 0
    y = x + fp.ONE_MONT
    return jnp.stack([x, y, x], axis=-2)


def g1_is_inf(p):
    return fp.is_zero(p[..., 2, :])


def g1_dbl(p):
    X1, Y1, Z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    A = fp.mont_sqr(X1)
    B = fp.mont_sqr(Y1)
    C = fp.mont_sqr(B)
    t = fp.add(X1, B)
    D = fp.sub(fp.sub(fp.mont_sqr(t), A), C)
    D = fp.add(D, D)
    E = fp.add(fp.add(A, A), A)
    F = fp.mont_sqr(E)
    X3 = fp.sub(F, fp.add(D, D))
    C8 = fp.add(C, C)
    C8 = fp.add(C8, C8)
    C8 = fp.add(C8, C8)
    Y3 = fp.sub(fp.mont_mul(E, fp.sub(D, X3)), C8)
    Z3 = fp.mont_mul(Y1, Z1)
    Z3 = fp.add(Z3, Z3)
    res = jnp.stack([X3, Y3, Z3], axis=-2)
    # doubling a point with Y == 0 or infinity -> infinity
    bad = g1_is_inf(p) | fp.is_zero(Y1)
    return jnp.where(bad[..., None, None], g1_inf_like(p), res)


def g1_add(p, q):
    """Branchless complete-ish Jacobian addition (handles inf, equal, neg)."""
    X1, Y1, Z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    X2, Y2, Z2 = q[..., 0, :], q[..., 1, :], q[..., 2, :]
    Z1Z1 = fp.mont_sqr(Z1)
    Z2Z2 = fp.mont_sqr(Z2)
    U1 = fp.mont_mul(X1, Z2Z2)
    U2 = fp.mont_mul(X2, Z1Z1)
    S1 = fp.mont_mul(fp.mont_mul(Y1, Z2), Z2Z2)
    S2 = fp.mont_mul(fp.mont_mul(Y2, Z1), Z1Z1)
    H = fp.sub(U2, U1)
    Rr = fp.sub(S2, S1)
    same_x = fp.is_zero(H)
    same_y = fp.is_zero(Rr)

    I = fp.mont_sqr(fp.add(H, H))
    J = fp.mont_mul(H, I)
    Rr2 = fp.add(Rr, Rr)
    V = fp.mont_mul(U1, I)
    X3 = fp.sub(fp.sub(fp.mont_sqr(Rr2), J), fp.add(V, V))
    S1J = fp.mont_mul(S1, J)
    Y3 = fp.sub(
        fp.mont_mul(Rr2, fp.sub(V, X3)), fp.add(S1J, S1J)
    )
    Z3 = fp.mont_mul(fp.mont_mul(Z1, Z2), H)
    Z3 = fp.add(Z3, Z3)
    generic = jnp.stack([X3, Y3, Z3], axis=-2)

    dbl = g1_dbl(p)
    inf = g1_inf_like(p)
    res = jnp.where(
        same_x[..., None, None],
        jnp.where(same_y[..., None, None], dbl, inf),
        generic,
    )
    res = jnp.where(g1_is_inf(q)[..., None, None], p, res)
    res = jnp.where(g1_is_inf(p)[..., None, None], jnp.broadcast_to(q, res.shape), res)
    return res


def g1_scalar_mul_bits(points, bits):
    """Batched double-and-add: points (..., 3, L), bits (..., nbits) MSB-first.

    lax.scan over the bit axis — static trip count, branchless body.
    """
    nbits = bits.shape[-1]
    acc0 = g1_inf_like(points)

    def step(acc, i):
        acc = g1_dbl(acc)
        with_add = g1_add(acc, points)
        bit = bits[..., i]
        acc = jnp.where(bit[..., None, None] == 1, with_add, acc)
        return acc, None

    acc, _ = lax.scan(step, acc0, jnp.arange(nbits))
    return acc


def g1_reduce_sum(points):
    """Tree-reduce points over axis 0: (n, ..., 3, L) -> (..., 3, L).

    Any n >= 1 and any intermediate batch axes: odd levels are padded with an
    infinity row (statically, at trace time) so no share is ever dropped.
    """
    n = points.shape[0]
    assert n >= 1
    while n > 1:
        if n % 2:
            points = jnp.concatenate(
                [points, g1_inf_like(points[:1])], axis=0
            )
            n += 1
        half = n // 2
        points = g1_add(points[:half], points[half:n])
        n = half
    return points[0]


def g1_msm(points, bits):
    """Full MSM: batched scalar-mul then tree reduction -> single point."""
    return g1_reduce_sum(g1_scalar_mul_bits(points, bits))


# ---------------------------------------------------------------------------
# Fp2 / G2 — component-stacked on axis -2 of the limb pair
# ---------------------------------------------------------------------------


def fp2_add(a, b):
    return jnp.stack(
        [fp.add(a[..., 0, :], b[..., 0, :]), fp.add(a[..., 1, :], b[..., 1, :])],
        axis=-2,
    )


def fp2_sub(a, b):
    return jnp.stack(
        [fp.sub(a[..., 0, :], b[..., 0, :]), fp.sub(a[..., 1, :], b[..., 1, :])],
        axis=-2,
    )


def fp2_mul(a, b):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    b0, b1 = b[..., 0, :], b[..., 1, :]
    t0 = fp.mont_mul(a0, b0)
    t1 = fp.mont_mul(a1, b1)
    t2 = fp.mont_mul(fp.add(a0, a1), fp.add(b0, b1))
    return jnp.stack(
        [fp.sub(t0, t1), fp.sub(fp.sub(t2, t0), t1)], axis=-2
    )


def fp2_sqr(a):
    return fp2_mul(a, a)


def fp2_is_zero(a):
    return fp.is_zero(a[..., 0, :]) & fp.is_zero(a[..., 1, :])


def g2_to_device(points) -> np.ndarray:
    out = np.zeros((len(points), 3, 2, fp.NLIMBS), dtype=np.int32)
    for i, pt in enumerate(points):
        aff = bls.g2_to_affine(pt)
        if aff is None:
            out[i, 1, 0] = fp.to_mont_host(1)
        else:
            (x0, x1), (y0, y1) = aff
            out[i, 0, 0] = fp.to_mont_host(x0)
            out[i, 0, 1] = fp.to_mont_host(x1)
            out[i, 1, 0] = fp.to_mont_host(y0)
            out[i, 1, 1] = fp.to_mont_host(y1)
            out[i, 2, 0] = fp.to_mont_host(1)
    return out


def g2_from_device(arr) -> list:
    arr = np.asarray(arr)
    out = []
    for i in range(arr.shape[0]):
        coords = []
        for c in range(3):
            coords.append(
                (
                    fp.from_mont_host(arr[i, c, 0]),
                    fp.from_mont_host(arr[i, c, 1]),
                )
            )
        out.append(tuple(coords))
    return out


def g2_inf_like(p):
    res = p * 0  # derived from p: keeps shard_map varying-axes type
    return res.at[..., 1, 0, :].add(fp.ONE_MONT)


def g2_is_inf(p):
    return fp2_is_zero(p[..., 2, :, :])


def g2_dbl(p):
    X1, Y1, Z1 = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    A = fp2_sqr(X1)
    B = fp2_sqr(Y1)
    C = fp2_sqr(B)
    D = fp2_sub(fp2_sub(fp2_sqr(fp2_add(X1, B)), A), C)
    D = fp2_add(D, D)
    E = fp2_add(fp2_add(A, A), A)
    F = fp2_sqr(E)
    X3 = fp2_sub(F, fp2_add(D, D))
    C8 = fp2_add(C, C)
    C8 = fp2_add(C8, C8)
    C8 = fp2_add(C8, C8)
    Y3 = fp2_sub(fp2_mul(E, fp2_sub(D, X3)), C8)
    Z3 = fp2_mul(Y1, Z1)
    Z3 = fp2_add(Z3, Z3)
    res = jnp.stack([X3, Y3, Z3], axis=-3)
    bad = g2_is_inf(p) | fp2_is_zero(Y1)
    return jnp.where(bad[..., None, None, None], g2_inf_like(p), res)


def g2_add(p, q):
    X1, Y1, Z1 = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    X2, Y2, Z2 = q[..., 0, :, :], q[..., 1, :, :], q[..., 2, :, :]
    Z1Z1 = fp2_sqr(Z1)
    Z2Z2 = fp2_sqr(Z2)
    U1 = fp2_mul(X1, Z2Z2)
    U2 = fp2_mul(X2, Z1Z1)
    S1 = fp2_mul(fp2_mul(Y1, Z2), Z2Z2)
    S2 = fp2_mul(fp2_mul(Y2, Z1), Z1Z1)
    H = fp2_sub(U2, U1)
    Rr = fp2_sub(S2, S1)
    same_x = fp2_is_zero(H)
    same_y = fp2_is_zero(Rr)
    I = fp2_sqr(fp2_add(H, H))
    J = fp2_mul(H, I)
    Rr2 = fp2_add(Rr, Rr)
    V = fp2_mul(U1, I)
    X3 = fp2_sub(fp2_sub(fp2_sqr(Rr2), J), fp2_add(V, V))
    S1J = fp2_mul(S1, J)
    Y3 = fp2_sub(fp2_mul(Rr2, fp2_sub(V, X3)), fp2_add(S1J, S1J))
    Z3 = fp2_mul(fp2_mul(Z1, Z2), H)
    Z3 = fp2_add(Z3, Z3)
    generic = jnp.stack([X3, Y3, Z3], axis=-3)
    dbl = g2_dbl(p)
    inf = g2_inf_like(p)
    res = jnp.where(
        same_x[..., None, None, None],
        jnp.where(same_y[..., None, None, None], dbl, inf),
        generic,
    )
    res = jnp.where(g2_is_inf(q)[..., None, None, None], p, res)
    res = jnp.where(
        g2_is_inf(p)[..., None, None, None], jnp.broadcast_to(q, res.shape), res
    )
    return res


def g2_scalar_mul_bits(points, bits):
    acc0 = g2_inf_like(points)

    def step(acc, i):
        acc = g2_dbl(acc)
        with_add = g2_add(acc, points)
        bit = bits[..., i]
        acc = jnp.where(bit[..., None, None, None] == 1, with_add, acc)
        return acc, None

    acc, _ = lax.scan(step, acc0, jnp.arange(bits.shape[-1]))
    return acc


def g2_reduce_sum(points):
    """Tree-reduce over axis 0 (any n; odd levels padded with infinity)."""
    n = points.shape[0]
    assert n >= 1
    while n > 1:
        if n % 2:
            points = jnp.concatenate(
                [points, g2_inf_like(points[:1])], axis=0
            )
            n += 1
        half = n // 2
        points = g2_add(points[:half], points[half:n])
        n = half
    return points[0]


def g2_msm(points, bits):
    return g2_reduce_sum(g2_scalar_mul_bits(points, bits))
