"""Windowed multi-scalar multiplication over BLS12-381 G1 — the TPU kernel.

This replaces the round-1 bit-serial double-and-add (ops/curve.py
g1_scalar_mul_bits: 256 doublings + 256 conditional complete-adds per share)
with the design the hardware actually wants:

  * 4-bit windowed scalar-mul with a per-lane table of the 16 small
    multiples: depth 14 table adds + W x (4 dbl + 1 add) instead of
    256 x (dbl + add). Scalars are 64-bit for the verification RLC (the
    verifier picks them; 2^-64 soundness) and 2 x 128-bit via the GLV
    endomorphism for the arbitrary-Fr Lagrange coefficients, so W is 16 or
    32, never 64.
  * GLV: phi(x, y) = (beta x, y) acts as multiplication by lambda on the
    r-torsion, and because lambda ~ 2^127.6 for BLS12-381, plain divmod
    k = k2 * lambda + k1 gives |k1|, |k2| < 2^128 with both parts
    non-negative — no lattice reduction needed. k*P = k1*P + k2*phi(P).
  * INCOMPLETE group ops on the loose field (ops/fpl.py): no per-op
    equality tests, no ripple carries. Infinity is an explicit boolean lane
    flag, never a Z==0 test. Doubling/equal-operand edge cases cannot occur
    for in-range scalars (the accumulator's multiplier always differs from
    the table entry's mod r), and cross-lane collisions in the tree
    reduction have probability ~2^-64 because the verifier's coefficients
    are random — a wrong sum then just fails the batch check and falls back
    to serial verification, which is the existing escape path.

Reference role: the batched replacement for the per-share MCL pairing loop
(/root/reference/src/Lachain.Crypto/TPKE/PublicKey.cs:55-92 via
HoneyBadger.cs:205-247). bench.py drives `tpke_era_glv_kernel` as the
flagship kernel.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

import jax.numpy as jnp
from jax import lax

from . import fpl
from ..crypto import bls12381 as bls

WINDOW = 4
TABLE = 1 << WINDOW  # 16
W128 = 128 // WINDOW  # 32 windows for GLV halves
W64 = 64 // WINDOW  # 16 windows for RLC coefficients

# ---------------------------------------------------------------------------
# GLV constants — derived, then verified against the host oracle at import
# ---------------------------------------------------------------------------

_Z = 0xD201000000010000  # |z| for BLS12-381 (z itself is negative)
LAMBDA = (_Z * _Z - 1) % bls.R  # ~2^127.6, the small cube root of unity
assert (LAMBDA * LAMBDA + LAMBDA + 1) % bls.R == 0
assert LAMBDA.bit_length() <= 128


def _find_beta() -> int:
    """The cube root of unity in Fp matching LAMBDA on G1: lambda*(x,y) =
    (beta*x, y). Two candidates; pick by testing on the generator."""
    # any non-trivial cube root of unity mod p
    exp = (bls.P - 1) // 3
    g = 2
    while True:
        b = pow(g, exp, bls.P)
        if b != 1:
            break
        g += 1
    gen = bls.G1_GEN
    target = bls.g1_to_affine(bls.g1_mul(gen, LAMBDA))
    gx, gy = bls.g1_to_affine(gen)
    for cand in (b, b * b % bls.P):
        if (cand * gx % bls.P, gy) == target:
            return cand
    raise AssertionError("no beta matches lambda on G1")


BETA = _find_beta()
BETA_MONT = jnp.asarray(fpl.to_mont_host(BETA))


def glv_split(k: int) -> Tuple[int, int]:
    """k mod r -> (k1, k2) with k = k1 + k2*lambda, both in [0, 2^128)."""
    k %= bls.R
    k2, k1 = divmod(k, LAMBDA)
    return k1, k2


# ---------------------------------------------------------------------------
# incomplete Jacobian group law on the loose field
# ---------------------------------------------------------------------------


def g1_dbl(p):
    """Jacobian doubling; valid for any non-infinity point (flag-carried
    infinity lanes produce garbage that is never selected)."""
    X1, Y1, Z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    A = fpl.mont_sqr(X1)
    B = fpl.mont_sqr(Y1)
    C = fpl.mont_sqr(B)
    D = fpl.sub(fpl.sub(fpl.mont_sqr(fpl.add(X1, B)), A), C)
    D = fpl.add(D, D)
    E = fpl.mul_small(A, 3)
    F = fpl.mont_sqr(E)
    X3 = fpl.sub(F, fpl.add(D, D))
    Y3 = fpl.sub(
        fpl.mont_mul(E, fpl.sub(D, X3)), fpl.mul_small(C, 8)
    )
    Z3 = fpl.mont_mul(Y1, Z1)
    Z3 = fpl.add(Z3, Z3)
    return jnp.stack([X3, Y3, Z3], axis=-2)


def g1_add_incomplete(p, q):
    """Generic Jacobian add; REQUIRES p != +-q and both non-infinity
    (callers guarantee this by construction / flags)."""
    X1, Y1, Z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    X2, Y2, Z2 = q[..., 0, :], q[..., 1, :], q[..., 2, :]
    Z1Z1 = fpl.mont_sqr(Z1)
    Z2Z2 = fpl.mont_sqr(Z2)
    U1 = fpl.mont_mul(X1, Z2Z2)
    U2 = fpl.mont_mul(X2, Z1Z1)
    S1 = fpl.mont_mul(fpl.mont_mul(Y1, Z2), Z2Z2)
    S2 = fpl.mont_mul(fpl.mont_mul(Y2, Z1), Z1Z1)
    H = fpl.sub(U2, U1)
    Rr = fpl.sub(S2, S1)
    I = fpl.mont_sqr(fpl.add(H, H))
    J = fpl.mont_mul(H, I)
    Rr2 = fpl.add(Rr, Rr)
    V = fpl.mont_mul(U1, I)
    X3 = fpl.sub(fpl.sub(fpl.mont_sqr(Rr2), J), fpl.add(V, V))
    S1J = fpl.mont_mul(S1, J)
    Y3 = fpl.sub(fpl.mont_mul(Rr2, fpl.sub(V, X3)), fpl.add(S1J, S1J))
    Z3 = fpl.mont_mul(fpl.mont_mul(Z1, Z2), H)
    Z3 = fpl.add(Z3, Z3)
    return jnp.stack([X3, Y3, Z3], axis=-2)


def g1_add_flagged(p, fp_, q, fq):
    """Flag-aware add: infinity is an explicit bool lane, never a field
    test. p != +-q required when both flags are False."""
    r = g1_add_incomplete(p, q)
    r = jnp.where(
        fq[..., None, None], p, jnp.where(fp_[..., None, None], q, r)
    )
    return r, fp_ & fq


# ---------------------------------------------------------------------------
# windowed MSM
# ---------------------------------------------------------------------------


def _build_table(points):
    """(..., 3, L) -> (..., TABLE, 3, L): entry k holds k*P (entry 0 is
    garbage; digit==0 lanes are handled by flags).

    lax.scan over the +P chain keeps the compiled graph one-add-sized; the
    fully unrolled version produced a ~30k-op graph per call site."""
    two = g1_dbl(points)

    def step(acc, _):
        nxt = g1_add_incomplete(acc, points)
        return nxt, nxt

    _, chain = lax.scan(step, two, None, length=TABLE - 3)
    # chain: (TABLE-3, ..., 3, L) = [3P .. 15P]
    rows = jnp.concatenate(
        [
            (points * 0)[None],  # entry 0: filler, never selected
            points[None],
            two[None],
            chain,
        ],
        axis=0,
    )
    return jnp.moveaxis(rows, 0, -3)


def g1_msm_windowed(points, digits):
    """Batched windowed scalar-mul: points (..., 3, L), digits (..., W)
    int32 in [0, 16), MSB-first. Returns (result, inf_flag) with the same
    batch shape.

    Depth: 13 table adds + W * (4 dbl + 1 add) — vs 256 * (dbl + add) for
    the bit-serial scan this replaces. The window loop is a lax.scan whose
    body (4 dbl + gather + add) is large enough to amortize device-loop
    overhead — the opposite regime from the per-limb scans this design
    removed.
    """
    table = _build_table(points)  # (..., 16, 3, L)
    nw = digits.shape[-1]
    dseq = jnp.moveaxis(digits, -1, 0)  # (W, ...)

    def take(d):
        idx = d[..., None, None, None]
        entry = jnp.take_along_axis(table, idx, axis=-3)
        return entry[..., 0, :, :]

    acc0 = take(dseq[0])
    flag0 = dseq[0] == 0

    def step(carry, d):
        acc, flag = carry
        for _ in range(WINDOW):
            acc = g1_dbl(acc)
        entry = take(d)
        added = g1_add_incomplete(acc, entry)
        keep = d == 0
        acc = jnp.where(
            keep[..., None, None],
            acc,
            jnp.where(flag[..., None, None], entry, added),
        )
        return (acc, flag & keep), None

    (acc, flag), _ = lax.scan(step, (acc0, flag0), dseq[1:])
    return acc, flag


def g1_tree_reduce_flagged(points, flags, axis: int):
    """Tree-sum along `axis` with explicit infinity flags; log-depth."""
    points = jnp.moveaxis(points, axis, 0)
    flags = jnp.moveaxis(flags, axis, 0)
    n = points.shape[0]
    while n > 1:
        if n % 2:
            points = jnp.concatenate([points, points[:1] * 0], axis=0)
            flags = jnp.concatenate(
                [flags, jnp.ones_like(flags[:1])], axis=0
            )
            n += 1
        half = n // 2
        points, flags = g1_add_flagged(
            points[:half], flags[:half], points[half:n], flags[half:n]
        )
        n = half
    return points[0], flags[0]


# ---------------------------------------------------------------------------
# fixed-base path for the era-invariant verification keys
# ---------------------------------------------------------------------------


def y_fixed_base_tables(y_dev):
    """(K, 3, L) verification keys -> (K, W64, TABLE, 3, L) tables with
    T[i, w, d] = d * 16^w * Y_i.

    The Y_i are fixed for a validator set, so this runs ONCE (off the era
    hot path); per era the y-aggregates then cost only gathers plus one
    flagged tree-sum — no doublings, no scalar-mul scan at all.
    """
    rows = []
    base = y_dev
    for w in range(W64):
        rows.append(_build_table(base))  # (K, TABLE, 3, L)
        if w + 1 < W64:
            for _ in range(WINDOW):
                base = g1_dbl(base)
    # rows[w] built for 16^w; digits are MSB-first so window w weights
    # 16^(W64-1-w): reverse to index by the digit position directly
    return jnp.stack(rows[::-1], axis=1)  # (K, W64, TABLE, 3, L)


def y_agg_fixed_base(tables, rlc_digits):
    """tables (K, W64, TABLE, 3, L); rlc_digits (S, K, W64) MSB-first.
    Returns per-slot aggregates sum_i rlc[s,i] * Y_i as ((S, 3, L), (S,))."""
    s = rlc_digits.shape[0]
    idx = rlc_digits[..., None, None, None]  # (S, K, W, 1, 1, 1)
    entries = jnp.take_along_axis(tables[None], idx, axis=3)
    entries = entries[..., 0, :, :]  # (S, K, W, 3, L)
    flags = rlc_digits == 0
    k, w = entries.shape[1], entries.shape[2]
    entries = entries.reshape(s, k * w, 3, fpl.NLIMBS)
    flags = flags.reshape(s, k * w)
    return g1_tree_reduce_flagged(entries, flags, axis=1)


# ---------------------------------------------------------------------------
# the era kernel: verify-RLC aggregates + GLV Lagrange combine in ONE pass
# ---------------------------------------------------------------------------


def tpke_era_glv_kernel3(u_pts, rlc_digits, lag1_digits, lag2_digits):
    """Era kernel without the y lane group (3K lanes/slot): the verify RHS
    aggregates ride the fixed-base tables (y_agg_fixed_base) instead.
    Returns (points (S, 3grp, 3, L), flags (S, 3grp)): u_agg, comb1, comb2.
    """
    phi_u = jnp.concatenate(
        [
            fpl.mont_mul(u_pts[..., 0:1, :], BETA_MONT),
            u_pts[..., 1:3, :],
        ],
        axis=-2,
    )
    lanes = jnp.concatenate([u_pts, u_pts, phi_u], axis=1)
    digits = jnp.concatenate([rlc_digits, lag1_digits, lag2_digits], axis=1)
    acc, flags = g1_msm_windowed(lanes, digits)
    s, k3 = acc.shape[0], acc.shape[1]
    k = k3 // 3
    acc = acc.reshape(s, 3, k, 3, fpl.NLIMBS)
    flags = flags.reshape(s, 3, k)
    return g1_tree_reduce_flagged(acc, flags, axis=2)


def tpke_era_glv_kernel(u_pts, y_pts, rlc_digits, lag1_digits, lag2_digits):
    """Full-era TPKE kernel (S slots x K shares):

      u_pts, y_pts:   (S, K, 3, L) loose-Montgomery Jacobian points
      rlc_digits:     (S, K, W128) 64-bit verifier RLC coefficients,
                      zero-padded in the top W128-W64 windows
      lag1/lag2:      (S, K, W128) GLV halves of the Lagrange coefficients
                      (zero rows for shares outside the combine subset)

    One fused windowed pass over 4K lanes per slot:
      lane group 0: u * rlc     -> u_agg    (verify LHS)
      lane group 1: y * rlc     -> y_agg    (verify RHS)
      lane group 2: u * lag1    -> comb half 1
      lane group 3: phi(u)*lag2 -> comb half 2
    Host finishes with e(u_agg, H) == e(y_agg, W) per slot and XOR-pads with
    the combined point (reference PublicKey.cs:55-92 semantics).

    Returns (points (S, 4, 3, L), flags (S, 4)): u_agg, y_agg, comb1, comb2
    (comb = comb1 + comb2, added on host after canonicalization — keeping
    the kernel's output regular).
    """
    phi_u = jnp.concatenate(
        [
            fpl.mont_mul(u_pts[..., 0:1, :], BETA_MONT),
            u_pts[..., 1:3, :],
        ],
        axis=-2,
    )
    lanes = jnp.concatenate([u_pts, y_pts, u_pts, phi_u], axis=1)
    digits = jnp.concatenate(
        [rlc_digits, rlc_digits, lag1_digits, lag2_digits], axis=1
    )
    acc, flags = g1_msm_windowed(lanes, digits)  # (S, 4K, 3, L), (S, 4K)
    s, k4 = acc.shape[0], acc.shape[1]
    k = k4 // 4
    acc = acc.reshape(s, 4, k, 3, fpl.NLIMBS)
    flags = flags.reshape(s, 4, k)
    out, out_flags = g1_tree_reduce_flagged(acc, flags, axis=2)
    return out, out_flags


# ---------------------------------------------------------------------------
# host marshal: vectorized conversions (numpy, no per-bit Python loops)
# ---------------------------------------------------------------------------


def scalars_to_digits(scalars: Sequence[int], nwindows: int) -> np.ndarray:
    """List of ints -> (n, nwindows) int32 4-bit digits, MSB-first.
    Vectorized via byte decomposition."""
    nbytes = nwindows * WINDOW // 8
    buf = b"".join(int(s).to_bytes(nbytes, "big") for s in scalars)
    a = np.frombuffer(buf, dtype=np.uint8).reshape(len(scalars), nbytes)
    hi = a >> 4
    lo = a & 0xF
    out = np.empty((len(scalars), nbytes * 2), dtype=np.int32)
    out[:, 0::2] = hi
    out[:, 1::2] = lo
    return out


def era_digits(rlc_rows, lag_rows):
    """Shared era-coefficient marshal for the GLV-kernel pipelines
    (ops/verify.GlvEraPipeline and parallel/mesh.MeshEraPipeline): (S, K)
    integer coefficient rows -> (rlc64, rlc_d, lag1, lag2) digit arrays,
    with the 64-bit RLC coefficients embedded in the top W64 of W128
    windows and the Lagrange coefficients GLV-split into halves. One
    definition so window-width and split conventions cannot diverge between
    the single-device and mesh topologies."""
    s = len(rlc_rows)
    k = len(rlc_rows[0]) if s else 0
    rlc64 = np.stack([scalars_to_digits(row, W64) for row in rlc_rows])
    rlc_d = np.zeros((s, k, W128), dtype=np.int32)
    rlc_d[:, :, W128 - W64 :] = rlc64
    lag1 = np.zeros((s, k, W128), dtype=np.int32)
    lag2 = np.zeros((s, k, W128), dtype=np.int32)
    for i, row in enumerate(lag_rows):
        halves = [glv_split(v) for v in row]
        lag1[i] = scalars_to_digits([h[0] for h in halves], W128)
        lag2[i] = scalars_to_digits([h[1] for h in halves], W128)
    return rlc64, rlc_d, lag1, lag2


def combine_or_host_msm(comb, u_list, lag_list, backend):
    """Shared incomplete-add escape hatch for the era pipelines: a combine
    lane group degenerating to infinity (two equal partial sums collide in
    the incomplete add tree) has no random-coefficient soundness, so the
    ~2^-255 / adversarially-forced case falls back to the host oracle MSM."""
    if comb[2] == 0 and any(c for c in lag_list):
        return backend.g1_msm(
            [u for u, c in zip(u_list, lag_list) if c],
            [c for c in lag_list if c],
        )
    return comb


def _batch_inverse(vals: List[int], p: int) -> List[int]:
    """Montgomery's trick: n field inversions for the price of one."""
    n = len(vals)
    prefix = [1] * (n + 1)
    for i, v in enumerate(vals):
        prefix[i + 1] = prefix[i] * v % p
    inv_all = pow(prefix[n], -1, p)
    out = [0] * n
    for i in range(n - 1, -1, -1):
        out[i] = prefix[i] * inv_all % p
        inv_all = inv_all * vals[i] % p
    return out


def _ints_to_limbs_np(ints: List[int]) -> np.ndarray:
    """List of field ints -> (n, NLIMBS) int32, vectorized limb split."""
    nbytes = 48  # 384 bits covers any canonical field element
    buf = b"".join(v.to_bytes(nbytes, "little") for v in ints)
    a = np.frombuffer(buf, dtype=np.uint8).reshape(len(ints), nbytes)
    bits = np.unpackbits(a, axis=1, bitorder="little")  # (n, 384)
    nfull = 384 // fpl.BASE  # limbs fully covered by 384 bits
    limbs = bits[:, : nfull * fpl.BASE].reshape(len(ints), nfull, fpl.BASE)
    weights = (1 << np.arange(fpl.BASE, dtype=np.int64)).astype(np.int32)
    out = np.zeros((len(ints), fpl.NLIMBS), dtype=np.int32)
    out[:, :nfull] = (limbs * weights).sum(axis=2, dtype=np.int32)
    if nfull < fpl.NLIMBS and nfull * fpl.BASE < 384:
        rest = bits[:, nfull * fpl.BASE : 384]
        w = (1 << np.arange(rest.shape[1], dtype=np.int64)).astype(np.int32)
        out[:, nfull] = (rest * w).sum(axis=1, dtype=np.int32)
    return out


def g1_to_device_loose(points) -> np.ndarray:
    """Oracle Jacobian G1 tuples -> (n, 3, NLIMBS) loose Montgomery affine
    (Z=1). Batch inversion + vectorized limb packing; infinity entries get
    (0, 1, 0) — callers must flag them separately if semantically needed."""
    n = len(points)
    zs = []
    idx = []
    for i, pt in enumerate(points):
        if pt[2] != 0:
            zs.append(pt[2])
            idx.append(i)
    zinvs = _batch_inverse(zs, bls.P) if zs else []
    xs = [0] * n
    ys = [0] * n
    zcol = [0] * n
    one_m = fpl.R_MONT % bls.P  # Mont(1)
    j = 0
    for i, pt in enumerate(points):
        if pt[2] == 0:
            xs[i] = 0
            ys[i] = one_m
            zcol[i] = 0
        else:
            zi = zinvs[j]
            j += 1
            zi2 = zi * zi % bls.P
            ax = pt[0] * zi2 % bls.P
            ay = pt[1] * zi2 % bls.P * zi % bls.P
            xs[i] = ax * fpl.R_MONT % bls.P
            ys[i] = ay * fpl.R_MONT % bls.P
            zcol[i] = one_m
    out = np.stack(
        [
            _ints_to_limbs_np(xs),
            _ints_to_limbs_np(ys),
            _ints_to_limbs_np(zcol),
        ],
        axis=1,
    )
    return out


def g1_from_device_loose(arr, flags=None) -> list:
    """(n, 3, NLIMBS) loose limbs (+ optional inf flags) -> oracle tuples.
    Exact canonicalization happens here, on host ints."""
    arr = np.asarray(arr)
    rinv = pow(fpl.R_MONT, -1, bls.P)
    out = []
    for i in range(arr.shape[0]):
        if flags is not None and bool(np.asarray(flags)[i]):
            out.append(bls.G1_INF)
            continue
        x = fpl.limbs_to_int(arr[i, 0]) * rinv % bls.P
        y = fpl.limbs_to_int(arr[i, 1]) * rinv % bls.P
        z = fpl.limbs_to_int(arr[i, 2]) * rinv % bls.P
        if z == 0:
            out.append(bls.G1_INF)
        else:
            out.append((x, y, z))
    return out
