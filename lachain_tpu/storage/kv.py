"""Key-value storage backends.

Parity with the reference's RocksDB context
(/root/reference/src/Lachain.Storage/RocksDbContext.cs:23-60 — single KV
store, WAL-synced writes, atomic batches) and the 2-byte keyspace prefixes
(EntryPrefix.cs:13-79).

Backends:
  * MemoryKV  — dict-backed, for tests and the in-process devnet.
  * SqliteKV  — durable single-file store with atomic batch commit (WAL mode);
    fills RocksDB's role until the native C++ LSM backend lands (the storage
    engine is deliberately behind this seam so swapping it touches nothing
    above).
"""
from __future__ import annotations

import enum
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Tuple

from ..utils import tracing


class EntryPrefix(enum.IntEnum):
    """2-byte keyspace partition (reference EntryPrefix.cs)."""

    BLOCK_BY_HASH = 0x0101
    BLOCK_HASH_BY_HEIGHT = 0x0102
    BLOCK_HEIGHT = 0x0103
    BLOCK_BLOOM = 0x0104
    TRANSACTION_BY_HASH = 0x0201
    ADDRESS_TX = 0x0202
    TRIE_NODE = 0x0301
    SNAPSHOT_INDEX = 0x0401
    POOL_TX = 0x0501
    KEYGEN_STATE = 0x0601
    VALIDATOR_ATTENDANCE = 0x0701
    LOCAL_TRANSACTION = 0x0801
    CONSENSUS_STATE = 0x0901
    SHRINK_STATE = 0x0A01
    SHRINK_MARK = 0x0A02
    # fast-sync frontier spill: discovered-but-not-yet-fetched trie-node
    # hashes parked in the KV so the in-memory BFS frontier stays bounded
    # on 100k+-node tries. Transient: deleted on sync completion; leftover
    # rows after a mid-sync crash are repairable garbage (fsck prunes them)
    FASTSYNC_FRONTIER = 0x0B01
    # Byzantine evidence records (consensus/evidence.py): durable, deduped
    # accusations (equivocation / invalid shares) that must survive restart —
    # an offense detected pre-crash stays queryable via la_getEvidence
    EVIDENCE = 0x0C01


def prefixed(prefix: EntryPrefix, key: bytes = b"") -> bytes:
    return int(prefix).to_bytes(2, "big") + key


class KVStore:
    """Interface (reference IRocksDbContext shape)."""

    # True when write_batch_async genuinely overlaps WAL encode/fsync with
    # the caller's continued work (the LSM engine); the default emulation
    # below just runs the batch synchronously, so callers gate streamed
    # commits on this flag instead of paying batch-splitting overhead for
    # nothing.
    supports_async_batches = False

    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def put(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def write_batch(self, puts: List[Tuple[bytes, bytes]], deletes: List[bytes] = ()) -> None:
        """Atomic multi-write (reference RocksDBAtomicWrite.cs:1-39)."""
        raise NotImplementedError

    def write_batch_async(
        self, puts: List[Tuple[bytes, bytes]], deletes: List[bytes] = ()
    ):
        """Submit an atomic batch WITHOUT waiting for durability; returns a
        ticket for write_barrier. Engines whose WAL runs on its own writer
        thread (LSM) overlap the batch's encode+fsync with the caller's
        next work — the fsync-overlap seam of the streamed trie commit.
        Default: synchronous write_batch (ticket None)."""
        self.write_batch(puts, deletes)
        return None

    def write_barrier(self, ticket) -> None:
        """Block until the write_batch_async ticket's batch is durable.
        Engines with an append-ordered WAL may treat any LATER durable
        write as an implicit barrier for earlier tickets; callers must
        still issue the barrier before acking state that references the
        async batches. Default: no-op (batches were synchronous)."""

    def scan_prefix(self, prefix: bytes) -> Iterator[Tuple[bytes, bytes]]:
        raise NotImplementedError

    def scan_from(
        self, prefix: bytes, after: bytes, limit: int
    ) -> List[Tuple[bytes, bytes]]:
        """First `limit` rows under `prefix` whose key suffix is strictly
        greater than `after` — the cursor primitive for paged pulls
        (fast-sync snapshot shipping). `after=b""` starts at the front."""
        out: List[Tuple[bytes, bytes]] = []
        floor = prefix + after
        for k, v in self.scan_prefix(prefix):
            if after and k <= floor:
                continue
            out.append((k, v))
            if len(out) >= limit:
                break
        return out

    def ingest(
        self, puts: List[Tuple[bytes, bytes]], chunk: int = 2000
    ) -> None:
        """Bulk-load helper for import paths (snapshot shipping, db
        import): atomic batches of `chunk`, engine hooks may follow up
        (the LSM engine flushes its memtable after a large ingest)."""
        for i in range(0, len(puts), chunk):
            self.write_batch(puts[i : i + chunk])

    def close(self) -> None:
        pass


class MemoryKV(KVStore):
    def __init__(self):
        self._d: Dict[bytes, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: bytes) -> Optional[bytes]:
        return self._d.get(key)

    def put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._d[key] = value

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._d.pop(key, None)

    def write_batch(self, puts, deletes=()) -> None:
        with self._lock:
            for k, v in puts:
                self._d[k] = v
            for k in deletes:
                self._d.pop(k, None)

    def scan_prefix(self, prefix: bytes):
        for k in sorted(self._d):
            if k.startswith(prefix):
                yield k, self._d[k]


class SqliteKV(KVStore):
    """Durable KV on sqlite WAL.

    Durability contract (matching the reference's WAL-synced RocksDB writes,
    RocksDbContext.cs:23-31): `write_batch` — the path every block commit,
    DKG step and snapshot-index update rides — commits with
    `synchronous=FULL`, i.e. the WAL is fsynced before the call returns, so
    a power failure can never lose a committed block. Singleton put/delete
    (per-tx pool persistence, best-effort by design) stay at
    `synchronous=NORMAL`: under WAL that can lose the LAST few pool writes
    on power loss but never corrupts, and the pool re-syncs from gossip.
    """

    def __init__(self, path: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB)"
        )
        self._conn.commit()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE k = ?", (key,)
            ).fetchone()
        return row[0] if row else None

    def put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)", (key, value)
            )
            self._conn.commit()

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (key,))
            self._conn.commit()

    def write_batch(self, puts, deletes=()) -> None:
        from .crashpoints import crash_point

        crash_point("kv.write_batch.pre")
        with self._lock:
            # FULL for the batch commit: block persistence is exactly the
            # write that must survive power failure; the fsync cost is paid
            # once per block, not per key
            self._conn.execute("PRAGMA synchronous=FULL")
            try:
                cur = self._conn.cursor()
                cur.executemany(
                    "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)",
                    list(puts),
                )
                if deletes:
                    cur.executemany(
                        "DELETE FROM kv WHERE k = ?", [(k,) for k in deletes]
                    )
                # mid = after the writes, before the fsynced commit: the
                # window a kill -9 must roll back entirely
                crash_point("kv.write_batch.mid")
                with tracing.wait("fsync"):
                    self._conn.commit()
            except BaseException:
                # a half-written batch must NOT linger in the open implicit
                # transaction, or the next unrelated put() would commit it
                # and break the all-or-nothing contract
                self._conn.rollback()
                raise
            finally:
                self._conn.execute("PRAGMA synchronous=NORMAL")
        crash_point("kv.write_batch.post")

    def scan_prefix(self, prefix: bytes):
        hi = prefix + b"\xff" * 8
        with self._lock:
            rows = self._conn.execute(
                "SELECT k, v FROM kv WHERE k >= ? AND k <= ? ORDER BY k",
                (prefix, hi),
            ).fetchall()
        for k, v in rows:
            if bytes(k).startswith(prefix):
                yield bytes(k), bytes(v)

    def scan_from(self, prefix: bytes, after: bytes, limit: int):
        # indexed range scan: a snapshot page costs O(page), not O(keyspace)
        hi = prefix + b"\xff" * 8
        with self._lock:
            rows = self._conn.execute(
                "SELECT k, v FROM kv WHERE k > ? AND k <= ? ORDER BY k "
                "LIMIT ?",
                (prefix + after, hi, limit),
            ).fetchall()
        return [
            (bytes(k), bytes(v))
            for k, v in rows
            if bytes(k).startswith(prefix)
        ]

    def close(self) -> None:
        with self._lock:
            self._conn.close()
