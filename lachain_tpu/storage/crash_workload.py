"""Deterministic storage workload for the crash-point harnesses.

Drives every instrumented commit pipeline (crashpoints.py point list) over
a REAL on-disk store: pool admission (`pool.save.mid`), block persistence
(`block.persist.*` riding `kv.write_batch.*`), and a shrink pass
(`shrink.*`). The workload is deterministic (fixed key seeds, fixed tx
schedule) and resume-friendly — re-running against a database a previous
run died in continues from the committed tip — so a crash-plan repeat
produces the identical store, which is what the two-run determinism
acceptance test asserts.

Used two ways:

  * in-process: tests arm a CrashPlan (mode "raise") around run_workload()
    and catch InjectedCrash where a real process would have died;
  * subprocess: ``python -m lachain_tpu.storage.crash_workload DB ENGINE``
    with ``LACHAIN_CRASH_POINTS`` set (mode "sigkill") — the process
    genuinely dies at the point, leaving the torn state on disk for fsck
    (the `lachain-tpu chaos --crash-point` scenario and the SIGKILL
    matrix tests).
"""
from __future__ import annotations

import json
import random
import sys

DEFAULT_CHAIN_ID = 225
DEFAULT_BLOCKS = 6
SHRINK_RETAIN = 2


class _Rng:
    def __init__(self, seed: int):
        self._r = random.Random(seed)

    def randbelow(self, n: int) -> int:
        return self._r.randrange(n)


def open_kv(db_path: str, engine: str = "sqlite"):
    if engine == "lsm":
        from .lsm import LsmKV

        return LsmKV(db_path)
    from .kv import SqliteKV

    return SqliteKV(db_path)


def run_workload(
    kv,
    blocks: int = DEFAULT_BLOCKS,
    chain_id: int = DEFAULT_CHAIN_ID,
    shrink: bool = True,
) -> dict:
    """Build (or extend) a chain of `blocks` blocks with one transfer each,
    then run a shrink pass. Returns {height, pooled, shrink} stats."""
    from ..core import execution
    from ..core.block_manager import BlockManager
    from ..core.tx_pool import TransactionPool
    from ..core.types import (
        BlockHeader,
        MultiSig,
        Transaction,
        sign_transaction,
        tx_merkle_root,
    )
    from ..crypto import ecdsa
    from .shrink import DbShrink
    from .state import StateManager

    priv = ecdsa.generate_private_key(_Rng(7))
    sender = ecdsa.address_from_public_key(ecdsa.public_key_bytes(priv))
    recipient = b"\x42" * 20

    state = StateManager(kv)
    bm = BlockManager(kv, state, execution.TransactionExecuter(chain_id))
    bm.build_genesis({sender: 10**18}, chain_id)
    pool = TransactionPool(
        kv,
        chain_id,
        account_nonce=lambda a: execution.get_nonce(state.new_snapshot(), a),
    )
    pool.restore()

    start = bm.current_height() + 1
    for height in range(start, blocks + 1):
        stx = sign_transaction(
            Transaction(
                to=recipient,
                value=height,
                nonce=height - 1,
                gas_price=1,
                gas_limit=100_000,
            ),
            priv,
            chain_id,
        )
        pool.add(stx)
        txs = [stx]
        em = bm.emulate(txs, height)
        prev = bm.block_by_height(height - 1)
        header = BlockHeader(
            index=height,
            prev_block_hash=prev.hash(),
            merkle_root=tx_merkle_root([t.hash() for t in txs]),
            state_hash=em.state_hash,
            nonce=0,
        )
        bm.execute_block(header, txs, MultiSig(()))

    shrink_stats = None
    if shrink:
        shrink_stats = DbShrink(state, kv).shrink(SHRINK_RETAIN)
    return {
        "height": bm.current_height(),
        "pooled": len(pool),
        "shrink": shrink_stats,
    }


STREAM_BLOCKS = 2
STREAM_TXS = 120


def run_stream_workload(
    kv, blocks: int = STREAM_BLOCKS, chain_id: int = DEFAULT_CHAIN_ID
) -> dict:
    """Streamed-commit variant for the trie.merkle.subtree_streamed crash
    window: many-tx blocks over a LOWERED stream threshold, so every block
    commit ships its trie nodes as multiple async WAL batches before the
    root record (the PR 11 fsync-overlap path). Deterministic and
    resume-friendly like run_workload; kept separate so its extra batch
    traffic never shifts the classic matrix's traversal counts."""
    from ..core import execution
    from ..core.block_manager import BlockManager
    from ..core.types import (
        BlockHeader,
        MultiSig,
        Transaction,
        sign_transaction,
        tx_merkle_root,
    )
    from ..crypto import ecdsa
    from .state import StateManager

    priv = ecdsa.generate_private_key(_Rng(7))
    sender = ecdsa.address_from_public_key(ecdsa.public_key_bytes(priv))

    state = StateManager(kv)
    state.stream_threshold = 64
    state._STREAM_BATCH = 100
    state.trie.merkle_workers = 4
    bm = BlockManager(kv, state, execution.TransactionExecuter(chain_id))
    bm.build_genesis({sender: 10**18}, chain_id)

    start = bm.current_height() + 1
    for height in range(start, blocks + 1):
        txs = [
            sign_transaction(
                Transaction(
                    to=b"\x37" * 12 + i.to_bytes(8, "big"),
                    value=height,
                    nonce=(height - 1) * STREAM_TXS + i,
                    gas_price=1,
                    gas_limit=100_000,
                ),
                priv,
                chain_id,
            )
            for i in range(STREAM_TXS)
        ]
        em = bm.emulate(txs, height)
        prev = bm.block_by_height(height - 1)
        header = BlockHeader(
            index=height,
            prev_block_hash=prev.hash(),
            merkle_root=tx_merkle_root([t.hash() for t in txs]),
            state_hash=em.state_hash,
            nonce=0,
        )
        bm.execute_block(header, txs, MultiSig(()))
    return {
        "height": bm.current_height(),
        "root": state.committed.state_hash().hex(),
        "streamed": state.commit_stats.get("streamed_batches", 0),
    }


def main(argv) -> int:
    """Subprocess entry: arm from LACHAIN_CRASH_POINTS, run, print stats.
    A sigkill plan never reaches the print — the parent observes -SIGKILL
    and inspects the torn database. `DB ENGINE stream` runs the streamed-
    commit workload instead of the classic matrix one."""
    from . import crashpoints

    db_path = argv[0]
    engine = argv[1] if len(argv) > 1 else "sqlite"
    stream = len(argv) > 2 and argv[2] == "stream"
    blocks = (
        int(argv[2]) if len(argv) > 2 and not stream else DEFAULT_BLOCKS
    )
    crashpoints.arm_from_env()
    kv = open_kv(db_path, engine)
    try:
        if stream:
            stats = run_stream_workload(kv)
        else:
            stats = run_workload(kv, blocks=blocks)
    finally:
        kv.close()
    print(json.dumps(stats))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
