"""Content-addressed 16-ary Merkle trie — the authenticated state store.

Parity with the reference's versioned trie
(/root/reference/src/Lachain.Storage/Trie/TrieHashMap.cs:17-180,
InternalNode.cs:1-135, NodeSerializer.cs): 16-ary branching over the nibbles
of keccak256(key) (keys hashed before insert, TrieHashMap.cs:90-98), root
hash == state hash per repository.

Redesign vs the reference: nodes are CONTENT-ADDRESSED (stored by the hash of
their canonical encoding) instead of carrying monotone version ids
(VersionFactory.cs). Structural sharing makes every root a free, immutable
snapshot: "versions" are simply root hashes, which collapses the reference's
Committed/Approved/Pending tier machinery into plain values (state.py) and
makes rollback O(1). An LRU node cache fills the role of TrieHashMap's cache.
"""
from __future__ import annotations

import os
import time
from collections import OrderedDict
from concurrent.futures import FIRST_EXCEPTION, ThreadPoolExecutor, wait
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..crypto.hashes import keccak256, keccak256_batch
from ..utils.serialization import Reader, write_bytes, write_u16
from .kv import EntryPrefix, KVStore, prefixed

EMPTY_ROOT = b"\x00" * 32
_NIBBLES = 64  # keccak256 -> 64 nibbles

# batch-size floors for the two merkleization fast paths: below them the
# bookkeeping costs more than the per-node keccak dispatch it saves
MIN_DEFER_OPS = 32    # deferred level-batched hashing
MIN_SHARD_OPS = 512   # subtrie-sharded workers

_KECCAK_BATCH_BUCKETS = (16, 64, 256, 1024, 4096, 16384, 65536)


def resolve_merkle_workers(n: int) -> int:
    """Merkle worker knob -> effective count: 0 = auto (host cores, capped
    at the 16-way subtrie fanout), N pins it. 1 disables sharding but
    keeps deferred batch hashing (the single-core win)."""
    n = int(n)
    if n > 0:
        return min(n, 16)
    return min(os.cpu_count() or 1, 16)


def _nibble(h: bytes, depth: int) -> int:
    byte = h[depth // 2]
    return (byte >> 4) if depth % 2 == 0 else (byte & 0x0F)


def _group_by_nibble(pairs, depth: int) -> Dict[int, list]:
    """Partition (kh, ...) pairs by their nibble at `depth` — the one
    grouping rule both bulk paths share (canonical structure depends on
    the two staying identical)."""
    groups: Dict[int, list] = {}
    for kh, v in pairs:
        groups.setdefault(_nibble(kh, depth), []).append((kh, v))
    return groups


@dataclass(frozen=True)
class LeafNode:
    key_hash: bytes  # full 32-byte hashed key
    value: bytes

    def encode(self) -> bytes:
        return b"L" + self.key_hash + write_bytes(self.value)


@dataclass(frozen=True)
class InternalNode:
    # 16 child hashes (EMPTY_ROOT = no child) — mask+list on the wire like the
    # reference's children-mask encoding (InternalNode.cs)
    children: Tuple[bytes, ...]

    def encode(self) -> bytes:
        mask = 0
        present = []
        for i, c in enumerate(self.children):
            if c != EMPTY_ROOT:
                mask |= 1 << i
                present.append(c)
        return b"I" + write_u16(mask) + b"".join(present)


def _decode(data: bytes):
    if data[0:1] == b"L":
        r = Reader(data[33:])
        return LeafNode(key_hash=data[1:33], value=r.bytes_())
    if data[0:1] == b"I":
        mask = int.from_bytes(data[1:3], "big")
        children = []
        off = 3
        for i in range(16):
            if mask & (1 << i):
                children.append(data[off : off + 32])
                off += 32
            else:
                children.append(EMPTY_ROOT)
        return InternalNode(tuple(children))
    raise ValueError("bad trie node encoding")


class _DeferredHasher:
    """Deferred-hash node sink for bulk merkleization: while armed on a
    Trie, `_store` hands out a 9-byte placeholder token instead of hashing
    the node. `Trie._resolve_deferred` then encodes the accumulated nodes
    level-by-level bottom-up, hashes each level's encodings in ONE native
    batch call (crypto.hashes.keccak256_batch) and patches child
    references — collapsing ~one Python→C keccak crossing per node into
    one per tree level (~6 for a 100k-node block).

    Token contract (what keeps `_bulk`'s no-op short-circuits and the
    collapse rules bit-identical to the immediate-hash path): a token is
    never equal to a real 32-byte hash, to EMPTY_ROOT, or to a different
    token, and tokens are handed out only for genuinely stored nodes — so
    `children == list(node.children)` still means exactly "nothing changed
    under this branch"."""

    __slots__ = ("nodes", "levels", "buckets", "count")
    PREFIX = 0xFE

    def __init__(self):
        self.nodes: Dict[bytes, object] = {}  # token -> node (for _load)
        self.levels: Dict[bytes, int] = {}  # token -> bottom-up level
        # per-level (tokens, nodes) parallel lists — the batch-hash units
        self.buckets: List[Tuple[List[bytes], List[object]]] = []
        self.count = 0

    def store(self, node) -> bytes:
        # HOT: once per stored node. The level is known right here —
        # children are always stored before their parent — so computing
        # it now saves _resolve_deferred a whole extra pass. b"\xfe" ==
        # PREFIX inlined; token child refs are the only 9-byte refs.
        token = b"\xfe" + self.count.to_bytes(8, "big")
        self.count += 1
        lvl = 0
        if type(node) is InternalNode:
            levels = self.levels
            for c in node.children:
                if len(c) == 9:
                    cl = levels[c]
                    if cl >= lvl:
                        lvl = cl + 1
        self.levels[token] = lvl
        self.nodes[token] = node
        buckets = self.buckets
        if lvl >= len(buckets):  # parents are at most one level above
            buckets.append(([], []))
        bt, bn = buckets[lvl]
        bt.append(token)
        bn.append(node)
        return token

    @staticmethod
    def is_token(h: bytes) -> bool:
        # real node hashes are 32 bytes; tokens are 9
        return len(h) == 9 and h[0] == _DeferredHasher.PREFIX


class Trie:
    """Handle over a KV store; every mutation returns a NEW root hash.

    Node writes are WRITE-BACK buffered: _store fills `_pending` instead of
    issuing a kv.put (which on SqliteKV is an fsynced autocommit — ~40us
    PER NODE, 100k nodes per 10k-tx block). StateManager.commit drains the
    buffer into the same atomic write_batch that persists the roots, so
    nodes are never durable later than a root referencing them — strictly
    better crash ordering than the old eager puts (which leaked orphan
    nodes from uncommitted emulations onto disk)."""

    def __init__(self, kv: KVStore, cache_size: int = 65536):
        self._kv = kv
        self._cache: OrderedDict[bytes, object] = OrderedDict()
        self._cache_size = cache_size
        self._pending: Dict[bytes, bytes] = {}  # prefixed key -> encoding
        # read-only view of a parent trie's node cache (see fork()); never
        # mutated through this handle
        self._read_cache: Optional[OrderedDict] = None
        # read-only view of a parent trie's PENDING buffer (_shard_fork):
        # a shard worker starts with an empty buffer of its own, so its
        # new nodes are exactly `_pending` after the run — no diffing
        self._read_pending: Optional[Dict[bytes, bytes]] = None
        # armed deferred-hash sink (apply_many bulk paths only)
        self._defer: Optional[_DeferredHasher] = None
        # merkle worker knob (config execution.merkleWorkers): 0 = auto
        self.merkle_workers: int = 0
        # accumulated apply_many profile (reset_merkle_stats() to zero),
        # for the commit-phase bench breakdown
        self.merkle_stats: Dict[str, float] = {}

    # -- node io -------------------------------------------------------------
    def _store(self, node) -> bytes:
        if self._defer is not None:
            return self._defer.store(node)
        enc = node.encode()
        h = keccak256(enc)
        self._pending[prefixed(EntryPrefix.TRIE_NODE, h)] = enc
        self._cache_put(h, node)
        return h

    def _load(self, h: bytes):
        if self._defer is not None and _DeferredHasher.is_token(h):
            return self._defer.nodes[h]
        node = self._cache.get(h)
        if node is not None:
            self._cache.move_to_end(h)
            return node
        if self._read_cache is not None:
            # forked handle: peek the parent's cache WITHOUT touching its
            # LRU order (move_to_end is what makes the parent cache unsafe
            # to share between threads; a bare get is a single C-level dict
            # read, and the parent thread is quiescent while forks run)
            node = self._read_cache.get(h)
            if node is not None:
                self._cache_put(h, node)
                return node
        key = prefixed(EntryPrefix.TRIE_NODE, h)
        enc = self._pending.get(key)
        if enc is None and self._read_pending is not None:
            enc = self._read_pending.get(key)
        if enc is None:
            enc = self._kv.get(key)
        if enc is None:
            raise KeyError(f"missing trie node {h.hex()}")
        node = _decode(enc)
        self._cache_put(h, node)
        return node

    def peek_pending(self) -> List[Tuple[bytes, bytes]]:
        """The buffered node writes, for the caller's write_batch. Includes
        nodes from discarded emulations (the eager-write design persisted
        those too; shrink reclaims them). The buffer is NOT cleared here —
        call confirm_pending with these items only after the batch is
        durable, so a failed commit keeps the sole copy of the nodes."""
        return list(self._pending.items())

    def confirm_pending(self, items: List[Tuple[bytes, bytes]]) -> None:
        """Drop buffered writes that a successful write_batch persisted."""
        for k, _ in items:
            self._pending.pop(k, None)

    def export_pending(self) -> Dict[bytes, bytes]:
        """Snapshot of the buffered node writes, for replaying into another
        trie over the SAME chain (cross-validator emulation sharing): nodes
        are content-addressed, so absorbing a snapshot taken after an
        identical state transition hands the consumer exactly the nodes its
        own freeze would have buffered."""
        return dict(self._pending)

    def absorb_pending(self, nodes: Dict[bytes, bytes]) -> None:
        """Adopt another trie's exported node buffer (see export_pending).
        Re-absorbing an already-persisted node is harmless — same key, same
        encoding — it just rides the next commit batch again."""
        self._pending.update(nodes)

    def fork(self) -> "Trie":
        """A private handle over the SAME kv for a concurrent reader
        (parallel execution lanes): its own LRU cache and pending buffer
        (seeded with ours — forked roots may reference not-yet-committed
        nodes), plus a read-only peek into our cache so a fork does not
        start cold. The fork is disposable: nodes it stores stay in its
        own pending buffer and are simply dropped with it (lane-local
        speculative state never rides a commit batch)."""
        t = Trie(self._kv, self._cache_size)
        t._pending = dict(self._pending)
        t._read_cache = self._cache
        return t

    def _shard_fork(self) -> "Trie":
        """A worker handle for subtrie-sharded merkleization: like fork(),
        but the pending buffer starts EMPTY and chains read-only over ours
        (copying 100k inherited entries per worker would eat the win). The
        worker's newly stored nodes are exactly its `_pending`, which the
        caller absorbs — unlike lane forks, shard results are canonical."""
        t = Trie(self._kv, self._cache_size)
        t._read_cache = self._cache
        t._read_pending = self._pending
        return t

    def clear_cache(self) -> None:
        self._cache.clear()

    def _cache_put(self, h: bytes, node) -> None:
        self._cache[h] = node
        if len(self._cache) > self._cache_size:
            self._cache.popitem(last=False)

    # -- public api ----------------------------------------------------------
    def get(self, root: bytes, key: bytes) -> Optional[bytes]:
        if root == EMPTY_ROOT:
            return None
        kh = keccak256(key)
        node_hash = root
        depth = 0
        while True:
            node = self._load(node_hash)
            if isinstance(node, LeafNode):
                return node.value if node.key_hash == kh else None
            nxt = node.children[_nibble(kh, depth)]
            if nxt == EMPTY_ROOT:
                return None
            node_hash = nxt
            depth += 1

    def put(self, root: bytes, key: bytes, value: bytes) -> bytes:
        kh = keccak256(key)
        return self._put_hashed(root, kh, value, 0)

    def _put_hashed(self, node_hash: bytes, kh: bytes, value: bytes, depth: int) -> bytes:
        if node_hash == EMPTY_ROOT:
            return self._store(LeafNode(kh, value))
        node = self._load(node_hash)
        if isinstance(node, LeafNode):
            if node.key_hash == kh:
                return self._store(LeafNode(kh, value))
            # split: push the existing leaf down until paths diverge
            children = [EMPTY_ROOT] * 16
            old_nib = _nibble(node.key_hash, depth)
            new_nib = _nibble(kh, depth)
            if old_nib == new_nib:
                sub = self._put_hashed(
                    self._store(node), kh, value, depth + 1
                )
                children[old_nib] = sub
            else:
                children[old_nib] = self._store(node)
                children[new_nib] = self._store(LeafNode(kh, value))
            return self._store(InternalNode(tuple(children)))
        nib = _nibble(kh, depth)
        new_child = self._put_hashed(node.children[nib], kh, value, depth + 1)
        children = list(node.children)
        children[nib] = new_child
        return self._store(InternalNode(tuple(children)))

    def delete(self, root: bytes, key: bytes) -> bytes:
        kh = keccak256(key)
        new_root = self._del_hashed(root, kh, 0)
        return new_root if new_root is not None else root

    def _collapse_or_store(self, children) -> bytes:
        """Store an internal node, applying THE canonical collapse rule
        (single shared copy: the bulk and sequential paths must collapse
        identically or their roots diverge): an empty child set dissolves,
        a single live LEAF child replaces the branch."""
        live = [c for c in children if c != EMPTY_ROOT]
        if not live:
            return EMPTY_ROOT
        if len(live) == 1:
            only = self._load(live[0])
            if isinstance(only, LeafNode):
                return self._store(only)
        return self._store(InternalNode(tuple(children)))

    # -- bulk application ----------------------------------------------------
    # The tree is CANONICAL in its leaf set (inserts create internal chains
    # exactly along shared prefixes; deletes collapse single-leaf branches
    # all the way back up), so applying a batch bottom-up produces the same
    # root as replaying the keys one at a time — while rebuilding each
    # shared internal node ONCE per block instead of once per key. This is
    # the block-commit hot path: at N=64 the per-key replay was ~18% of the
    # whole simulated era.

    def apply_many(
        self,
        root: bytes,
        writes: Dict[bytes, Optional[bytes]],
        workers: Optional[int] = None,
        stream: Optional[Callable[[List[Tuple[bytes, bytes]]], None]] = None,
    ) -> bytes:
        """Apply a {key: value-or-None(delete)} batch; returns the new root
        (bit-identical to sequential put/delete in any order, for any
        worker count).

        Large batches take one of two fast paths, both exact:
          * deferred batch hashing (>= MIN_DEFER_OPS): nodes are encoded
            level-by-level bottom-up and each level is hashed in one
            threaded native keccak call;
          * subtrie sharding (>= MIN_SHARD_OPS and workers > 1): the op
            batch splits by top-level nibble, each subtrie runs on a
            worker over a _shard_fork() handle, and the root is assembled
            from the 16 child hashes on the caller thread.

        `workers` overrides the handle's merkle_workers knob (0 = auto).
        `stream`, when given, receives each completed subtrie's NEW
        (key, encoding) node items as workers finish — the fsync-overlap
        seam StateManager.freeze_and_commit plugs the WAL into."""
        if not writes:
            return root
        entries: Dict[bytes, Optional[bytes]] = {
            keccak256(k): v for k, v in writes.items()
        }
        ops = sorted(entries.items())
        nworkers = resolve_merkle_workers(
            self.merkle_workers if workers is None else workers
        )
        t0 = time.perf_counter()
        if nworkers > 1 and len(ops) >= MIN_SHARD_OPS and root != EMPTY_ROOT:
            node = self._load(root)
            if isinstance(node, InternalNode):
                return self._apply_sharded(
                    root, node, ops, nworkers, stream, t0
                )
        return self._apply_serial(root, ops, nworkers, stream, t0)

    def _apply_serial(self, root, ops, nworkers, stream, t0) -> bytes:
        """Single-walker bulk application; defers hashing into per-level
        native batch calls when the batch is big enough to pay for it."""
        if len(ops) < MIN_DEFER_OPS:
            new_root = self._bulk(root, ops, 0)
            self._set_merkle_stats(t0, 0.0, 0, 1)
            return new_root
        self._defer = _DeferredHasher()
        try:
            out = self._bulk(root, ops, 0)
        finally:
            defer, self._defer = self._defer, None
        resolved, hash_s, items = self._resolve_deferred(defer, nworkers)
        if _DeferredHasher.is_token(out):
            out = resolved[out]
        if stream is not None and items:
            stream(items)
        self._set_merkle_stats(t0, hash_s, len(items), 1)
        return out

    def _apply_sharded(
        self, root_hash, node, ops, nworkers, stream, t0
    ) -> bytes:
        """Subtrie-sharded merkleization over the 16-way top-level fanout.
        Each worker owns an independent subtrie (disjoint key ranges), so
        its node set is canonical regardless of scheduling; the caller
        thread replays the serial path's depth-0 step — per-nibble child
        patch, no-op short-circuit, collapse rule — over the 16 child
        hashes, which is what makes the root bit-identical to `_bulk`."""
        groups = _group_by_nibble(ops, 0)
        children = list(node.children)

        def run(nib: int, group) -> tuple:
            fork = self._shard_fork()
            fork._defer = _DeferredHasher()
            try:
                sub = fork._bulk(children[nib], group, 1)
            finally:
                defer, fork._defer = fork._defer, None
            # per-worker native hashing stays single-threaded: the
            # parallelism budget is already spent on the worker pool
            resolved, hash_s, items = fork._resolve_deferred(defer, 1)
            if _DeferredHasher.is_token(sub):
                sub = resolved[sub]
            return nib, sub, items, hash_s

        results: Dict[int, bytes] = {}
        hash_s = 0.0
        hashed = 0
        with ThreadPoolExecutor(
            max_workers=min(nworkers, len(groups)),
            thread_name_prefix="merkle",
        ) as pool:
            futs = [
                pool.submit(run, nib, group)
                for nib, group in sorted(groups.items())
            ]
            # absorb/stream in COMPLETION order: a finished subtrie's node
            # batch can hit the WAL while its siblings are still hashing
            pending_futs = set(futs)
            while pending_futs:
                done, pending_futs = wait(
                    pending_futs, return_when=FIRST_EXCEPTION
                )
                for fut in done:
                    nib, sub, items, worker_hash_s = fut.result()
                    results[nib] = sub
                    self._pending.update(items)
                    hash_s += worker_hash_s
                    hashed += len(items)
                    if stream is not None and items:
                        stream(items)
        for nib in groups:
            children[nib] = results[nib]
        if children == list(node.children):
            out = root_hash
        else:
            out = self._collapse_or_store(children)
        self._set_merkle_stats(t0, hash_s, hashed, min(nworkers, len(groups)))
        return out

    def _resolve_deferred(
        self, defer: _DeferredHasher, nthreads: int
    ) -> Tuple[Dict[bytes, bytes], float, List[Tuple[bytes, bytes]]]:
        """Hash a deferred sink's nodes level-by-level bottom-up through
        the native batch keccak, patching child tokens with the hashes of
        the level below. Returns (token -> hash, seconds spent hashing,
        new (prefixed key, encoding) items stored).

        HOT PATH: ~one iteration per node per 10k-tx block commit. Token
        tests are inlined as `len(c) == 9` (real child refs are always 32
        bytes) and leaves — the bulk of every batch — skip the patch
        machinery entirely; the Python bookkeeping here must stay well
        under the per-node ctypes crossing it saves, or deferral is a
        net loss at merkle_workers=1."""
        resolved: Dict[bytes, bytes] = {}
        items: List[Tuple[bytes, bytes]] = []
        hash_s = 0.0
        trie_node = int(EntryPrefix.TRIE_NODE).to_bytes(2, "big")
        pending = self._pending
        cache = self._cache
        from ..utils import metrics

        for tokens, bnodes in defer.buckets:
            patched: List[object] = []
            for n in bnodes:
                if type(n) is InternalNode:
                    ch = n.children
                    for c in ch:
                        if len(c) == 9:
                            n = InternalNode(
                                tuple(
                                    [
                                        resolved[c] if len(c) == 9 else c
                                        for c in ch
                                    ]
                                )
                            )
                            break
                patched.append(n)
            encs = [n.encode() for n in patched]
            h0 = time.perf_counter()
            hashes = keccak256_batch(encs, nthreads)
            hash_s += time.perf_counter() - h0
            metrics.observe_hist(  # lint-allow: metric-name dimensionless batch-size distribution
                "trie_keccak_batch_size",
                len(encs),
                buckets=_KECCAK_BATCH_BUCKETS,
            )
            # bulk C-level stores instead of a per-node interpreted loop
            keys = [trie_node + h for h in hashes]
            pairs = list(zip(keys, encs))
            pending.update(pairs)
            items.extend(pairs)
            resolved.update(zip(tokens, hashes))
            cache.update(zip(hashes, patched))
        # one bulk trim instead of per-put LRU churn (_cache_put does a
        # move_to_end + popitem dance per node; recency inside one batch
        # is meaningless anyway)
        while len(cache) > self._cache_size:
            cache.popitem(last=False)
        return resolved, hash_s, items

    def reset_merkle_stats(self) -> None:
        """Zero the accumulated apply_many profile (bench phase breakdowns
        call this before a timed section so the totals cover exactly it)."""
        self.merkle_stats = {}

    def _set_merkle_stats(
        self, t0: float, hash_s: float, nodes: int, workers: int
    ) -> None:
        # ACCUMULATES across apply_many calls: a Snapshot.freeze applies
        # one batch per subtree, and the commit-phase breakdown wants the
        # whole-freeze totals, not the last subtree's
        wall = time.perf_counter() - t0
        st = self.merkle_stats
        st["wall_s"] = st.get("wall_s", 0.0) + wall
        st["hash_s"] = st.get("hash_s", 0.0) + hash_s
        st["assemble_s"] = st.get("assemble_s", 0.0) + max(wall - hash_s, 0.0)
        st["nodes"] = int(st.get("nodes", 0)) + nodes
        st["workers"] = max(int(st.get("workers", 0)), workers)
        from ..utils import metrics

        metrics.inc("trie_nodes_hashed_total", nodes)
        metrics.set_gauge("trie_merkle_workers", workers)

    def _bulk(self, node_hash: bytes, ops, depth: int) -> bytes:
        if not ops:
            return node_hash
        if node_hash == EMPTY_ROOT:
            leaves = [(kh, v) for kh, v in ops if v is not None]
            return self._build_subtree(leaves, depth)
        node = self._load(node_hash)
        if isinstance(node, LeafNode):
            merged = dict(ops)
            if node.key_hash not in merged:
                merged[node.key_hash] = node.value
            leaves = sorted(
                (kh, v) for kh, v in merged.items() if v is not None
            )
            if leaves == [(node.key_hash, node.value)]:
                return node_hash  # no-op batch over this leaf
            return self._build_subtree(leaves, depth)
        children = list(node.children)
        groups = _group_by_nibble(ops, depth)
        for nib, group in groups.items():
            children[nib] = self._bulk(children[nib], group, depth + 1)
        if children == list(node.children):
            # nothing changed under us (absent-key deletes / same-value
            # puts): a pure no-op, like sequential delete of a missing key
            return node_hash
        return self._collapse_or_store(children)

    def _build_subtree(self, leaves, depth: int) -> bytes:
        """Canonical subtree for sorted (kh, value) leaves on empty ground."""
        if not leaves:
            return EMPTY_ROOT
        if len(leaves) == 1:
            kh, v = leaves[0]
            return self._store(LeafNode(kh, v))
        children = [EMPTY_ROOT] * 16
        for nib, group in _group_by_nibble(leaves, depth).items():
            children[nib] = self._build_subtree(group, depth + 1)
        return self._store(InternalNode(tuple(children)))

    def _del_hashed(self, node_hash: bytes, kh: bytes, depth: int) -> Optional[bytes]:
        """Returns the new subtree hash, EMPTY_ROOT if emptied, or None if
        the key was absent (no change)."""
        if node_hash == EMPTY_ROOT:
            return None
        node = self._load(node_hash)
        if isinstance(node, LeafNode):
            return EMPTY_ROOT if node.key_hash == kh else None
        nib = _nibble(kh, depth)
        sub = self._del_hashed(node.children[nib], kh, depth + 1)
        if sub is None:
            return None
        children = list(node.children)
        children[nib] = sub
        return self._collapse_or_store(children)

    def iter_items(self, root: bytes) -> Iterator[Tuple[bytes, bytes]]:
        """All (hashed_key, value) pairs under a root (ordered by key hash)."""
        if root == EMPTY_ROOT:
            return
        stack = [root]
        while stack:
            node = self._load(stack.pop())
            if isinstance(node, LeafNode):
                yield node.key_hash, node.value
            else:
                for c in reversed(node.children):
                    if c != EMPTY_ROOT:
                        stack.append(c)

    def node_count(self, root: bytes) -> int:
        if root == EMPTY_ROOT:
            return 0
        seen = set()
        stack = [root]
        while stack:
            h = stack.pop()
            if h in seen:
                continue
            seen.add(h)
            node = self._load(h)
            if isinstance(node, InternalNode):
                stack.extend(c for c in node.children if c != EMPTY_ROOT)
        return len(seen)
