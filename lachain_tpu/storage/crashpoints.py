"""Deterministic crash-point injection for the storage/commit pipelines.

The seeded-fault machinery (network/faults.py) provokes loss on the WIRE;
this module provokes loss of the PROCESS at named points inside multi-write
commit pipelines — mid `write_batch`, between block persist and the
snapshot-index write, mid shrink stage, mid pool save — so crash-recovery
code (journal replay, fsck, resumable shrink) can be tested against every
torn state the pipelines can produce, reproducibly.

A :class:`CrashPlan` is a declarative schedule of :class:`CrashPoint`s:
each names an instrumented site and the 1-based traversal count at which it
fires. Firing is deterministic by construction — the Nth traversal of a
named site is the same event in every run of the same workload — which is
what makes a two-run repeat of a plan bit-identical.

Two harnesses execute a plan:

  * in-process (`mode="raise"`): the point raises :class:`InjectedCrash`
    (a BaseException, like SystemExit: ordinary ``except Exception``
    recovery paths cannot swallow it, because a real SIGKILL cannot be
    caught either);
  * real subprocess (`mode="sigkill"`): the point delivers SIGKILL to the
    current process, so the torn state on disk is produced by an actual
    process death, not a simulated one.

Instrumented sites call :func:`crash_point` — a no-op costing one global
read when no plan is armed. Subprocess harnesses arm via the
``LACHAIN_CRASH_POINTS`` environment variable (comma-separated
``NAME[@HIT][:MODE]`` specs), parsed by the CLI entrypoint at startup.

Instrumented point names:

  kv.write_batch.pre / .mid / .post   SqliteKV + LsmKV atomic batch
  block.persist.pre / .mid / .post    BlockManager._persist (mid = between
                                      the block batch and state.commit —
                                      the torn-block window fsck repairs)
  shrink.mark.height                  per-height mark checkpoint
  shrink.sweep.pre / shrink.clean.pre stage transitions
  pool.save.mid                       between pool admission and persist
  lsm.wal.encoded                     LsmKV only: the batch's WAL record
                                      partially written (torn tail), never
                                      fsynced/applied — replay discards it
  lsm.wal.fsynced                     LsmKV only: record durable but never
                                      acked/applied — replay applies it
  lsm.compact.mid                     LsmKV only: merged SST renamed into
                                      place, manifest swap lost — open()
                                      sweeps the orphan
  trie.merkle.subtree_streamed        streamed trie commit (StateManager):
                                      after an async subtrie node batch is
                                      enqueued on the WAL writer, before
                                      the root record — leaves durable
                                      orphan nodes with no referencing
                                      root; fsck-clean, replay recommits

The lsm.* sites leave REAL torn native state (lsm.py calls the engine's
partial-execution debug APIs before dying), identical bytes on disk in
both harness modes.
"""
from __future__ import annotations

import os
import signal
import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

ENV_VAR = "LACHAIN_CRASH_POINTS"

MODE_RAISE = "raise"
MODE_SIGKILL = "sigkill"


class InjectedCrash(BaseException):
    """In-process stand-in for a process death at a crash point."""

    def __init__(self, point: str, hit: int):
        super().__init__(f"injected crash at {point} (hit {hit})")
        self.point = point
        self.hit = hit


@dataclass(frozen=True)
class CrashPoint:
    """Fire at the `hit`-th traversal of the instrumented site `name`."""

    name: str
    hit: int = 1
    mode: str = MODE_RAISE


@dataclass(frozen=True)
class CrashPlan:
    """Deterministic crash schedule (faults.py FaultPlan idiom: a frozen
    declarative plan, live state lives in the session)."""

    points: Tuple[CrashPoint, ...] = ()

    def session(self) -> "CrashSession":
        return CrashSession(self)

    @staticmethod
    def parse_point(spec: str) -> CrashPoint:
        """"NAME[@HIT][:MODE]" — e.g. "block.persist.mid",
        "kv.write_batch.mid@3:sigkill"."""
        name, _, mode = spec.partition(":")
        mode = mode or MODE_RAISE
        if mode not in (MODE_RAISE, MODE_SIGKILL):
            raise ValueError(
                f"crash point {spec!r}: mode must be "
                f"{MODE_RAISE!r} or {MODE_SIGKILL!r}"
            )
        name, _, hit_s = name.partition("@")
        if not name:
            raise ValueError(f"crash point {spec!r}: empty name")
        return CrashPoint(name=name, hit=int(hit_s) if hit_s else 1, mode=mode)

    @classmethod
    def parse(cls, specs) -> "CrashPlan":
        return cls(points=tuple(cls.parse_point(s) for s in specs if s))

    def encode_env(self) -> str:
        """The ENV_VAR value that re-arms this plan in a subprocess."""
        return ",".join(
            f"{p.name}@{p.hit}:{p.mode}" for p in self.points
        )


class CrashSession:
    """One armed execution of a CrashPlan: traversal counters + fire log."""

    def __init__(self, plan: CrashPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self.hits: Dict[str, int] = {}
        self.fired: List[Tuple[str, int]] = []
        self._by_name: Dict[str, List[CrashPoint]] = {}
        for p in plan.points:
            self._by_name.setdefault(p.name, []).append(p)

    def visit(self, name: str) -> Optional[CrashPoint]:
        """Count one traversal of `name`; return the point due to fire."""
        with self._lock:
            count = self.hits.get(name, 0) + 1
            self.hits[name] = count
        for p in self._by_name.get(name, ()):
            if p.hit == count:
                self.fired.append((name, count))
                return p
        return None

    @property
    def stats(self) -> Dict[str, object]:
        return {"visited": dict(self.hits), "fired": list(self.fired)}


# -- global arming (one plan per process, like a fault filter per hub) -------

_session: Optional[CrashSession] = None


def arm(plan: CrashPlan) -> CrashSession:
    global _session
    _session = plan.session()
    return _session


def disarm() -> Optional[CrashSession]:
    global _session
    s, _session = _session, None
    return s


def active() -> Optional[CrashSession]:
    return _session


@contextmanager
def armed(plan: CrashPlan):
    s = arm(plan)
    try:
        yield s
    finally:
        disarm()


def arm_from_env() -> Optional[CrashSession]:
    """Arm from LACHAIN_CRASH_POINTS (the subprocess harness path); no-op
    when unset. Called by the CLI entrypoint so a child `lachain-tpu run`
    executes the parent's plan."""
    spec = os.environ.get(ENV_VAR, "")
    if not spec:
        return None
    return arm(CrashPlan.parse(spec.split(",")))


def crash_point(name: str) -> None:
    """Instrumented-site hook. No-op unless a plan is armed and due."""
    s = _session
    if s is None:
        return
    point = s.visit(name)
    if point is None:
        return
    if point.mode == MODE_SIGKILL:
        os.kill(os.getpid(), signal.SIGKILL)
    raise InjectedCrash(name, point.hit)
